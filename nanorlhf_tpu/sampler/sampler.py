"""Jitted autoregressive sampler — the TPU-native replacement for vLLM rollouts.

The reference hands weights to vLLM through a disk round-trip every update
(`/root/reference/GRPO/grpo_trainer.py:122-166`): model→CPU, (merge LoRA),
save_pretrained, rebuild an `LLM` engine, generate, delete engine, model→GPU.
On TPU the policy params already live sharded in HBM, so generation is just
another jitted function over the same tree — the entire handoff disappears.

Output contract is identical to `vllm_generate` (`grpo_trainer.py:152-160`):
`[B*N, max_tokens]` int32, N consecutive samples per prompt (prompt-major),
each row = generated tokens including the terminating EOS, right-padded with
`pad_token_id`. Capability parity with `SamplingParams(temperature, top_p=0.95,
n=N, seed=randint)` (`grpo_trainer.py:127`) — the per-call changing seed
becomes a per-call PRNG key. Greedy mode covers the ReMax baseline rollout
(`ReMax/remax_trainer.py:166-185`) and the r1 accuracy eval
(`examples/r1-v0/grpo_r1.py:291-318`).

Decode is a `lax.while_loop` over single-token steps with a shared KV cache;
it exits early once every sequence has emitted EOS (rollouts are offline-batch,
so big batches keep the MXU busy; early exit claws back the static-shape tax).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core.config import ModelConfig
from nanorlhf_tpu.core.model import (
    decode_step, init_kv_cache, init_paged_kv_cache, prefill,
)
from nanorlhf_tpu.ops.masking import guard_temperature
from nanorlhf_tpu.sampler.paged.pages import full_table


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    temperature: float = 1.0
    top_p: float = 0.95
    n: int = 1
    max_tokens: int = 256
    greedy: bool = False
    # top-k pre-trim for nucleus sampling: the per-step full-vocab sort (the
    # round-1 decode hot spot at 152k vocab) becomes one lax.top_k + a
    # k-sized categorical. Exact nucleus sampling whenever the 0.95-nucleus
    # fits in the top-k — true for trained models at production temperatures;
    # NOT true for random-init/high-entropy policies, where this truncates
    # the tail to the k best tokens (the combined top-k/top-p semantics vLLM
    # exposes as `SamplingParams(top_k=...)`). Set top_k=0 to disable the
    # pre-trim and recover the exact full-vocab nucleus at full-sort cost.
    # Ignored when top_p >= 1.0 (that path is always exact full-vocab).
    top_k: int = 64
    # capture the FULL-distribution logprob of each sampled token during
    # decode (one extra logsumexp per step — the logits are already there).
    # `generate` then returns (tokens, logprobs), letting the trainer skip
    # the policy half of the scoring pass (ROADMAP #5b). The captured values
    # equal `logprobs_from_logits(logits, tokens, temperature)` up to
    # decode-vs-scoring numerics; the trainer logs the residual ratio drift.
    capture_logprobs: bool = False
    # use jax.lax.approx_max_k for the top-k pre-trim: XLA lowers exact
    # lax.top_k to a FULL VOCAB SORT on TPU, which at LLM vocabularies can
    # dominate the decode step; ApproxTopK is the hardware-native O(V) path
    # (exact on CPU). The candidate SET becomes approximate (recall 0.99 per
    # candidate, NOT rank-restricted): a missed in-nucleus token cannot be
    # sampled that step, and the exclusive-cumsum keep rule then undercounts,
    # letting the boundary widen slightly past top_p. The sampling
    # distribution therefore deviates from the exact truncated nucleus —
    # acceptable for RL rollouts, where the ratio math scores the SAMPLED
    # token's full-distribution logprob (exact either way; the
    # truncated-vs-full mismatch is inherent to nucleus sampling and present
    # in the reference's vLLM path too). Set False for the exact candidate
    # set (full-sort cost on TPU).
    approx_top_k: bool = True
    # LEGACY (contiguous-layout-only) straggler lever — prefer `page_size`.
    # >0 enables compacting decode (sampler/compaction.py): the loop runs in
    # this many segments, and between segments finished rows are flushed and
    # live rows gathered into a smaller power-of-two batch — a batch-shrink
    # approximation of continuous batching that the paged KV cache
    # supersedes: `page_size` > 0 with `decode_rows` > 0 recycles finished
    # rows' cache pages to QUEUED prompts mid-loop (true continuous
    # batching) and, unlike compaction, composes with spec_k. 0 = monolithic
    # single-jit loop (bit-stable row streams, fully async dispatch).
    # Mutually exclusive with spec_k > 0 AND with page_size > 0: compaction's
    # row gather assumes every live row sits at the same decode step (shared
    # cache-slot layout), which per-row accept lengths / per-row fill breaks
    # — `compose_check` raises on either combination (the one legality
    # matrix every decode entry point routes through).
    compaction_segments: int = 0
    # >0 switches the KV cache to the PAGED layout (sampler/paged/,
    # docs/PAGED_CACHE.md): K/V live in a global pool of page_size-token
    # pages addressed through a per-row block table instead of a per-row
    # [T_max] slab. On its own (decode_rows == 0) this is a pure re-layout —
    # greedy token streams are bit-identical to the contiguous cache on the
    # CPU mesh (test-pinned) — and it composes with spec_k (paged verify
    # writes) and kv_cache_quant="int8" (paged scale pools). Pick
    # page_size >= 128 on real TPUs (lane-tile alignment for the paged
    # kernels' int8 scale blocks); CPU tests run any size via interpret
    # mode. 0 = contiguous slabs, bit-for-bit untouched.
    page_size: int = 0
    # page_size > 0 only: >0 enables CONTINUOUS BATCHING — the decode loop
    # runs `decode_rows` resident rows over a page pool sized for exactly
    # that many rows, and when a row EOSes mid-loop its pages are released
    # and the next queued prompt is prefilled into the freed pool
    # (sampler/paged/scheduler.py). The long-tail win compaction
    # approximated, without its same-step restriction: works with spec_k.
    # Host-driven (one sync per chunk of decode iterations); row streams
    # are NOT bit-identical to the monolithic loop (admission re-keys the
    # PRNG per row). n > 1 fanout falls back to repeated-prompt prefill on
    # this path. 0 (or >= the total row count) = monolithic paged loop.
    decode_rows: int = 0
    # >0 enables draft-free speculative decode (sampler/speculative.py): a
    # jitted n-gram/prompt-lookup drafter proposes spec_k tokens per row
    # from the row's own prompt+output buffer, and ONE `decode_verify`
    # forward scores all k+1 candidates against the cache — amortizing the
    # dominant per-step weight/cache HBM stream over every accepted token
    # (docs/DECODE_ANALYSIS.md). Greedy rows accept the matched prefix
    # bit-exactly vs this monolithic loop; sampled rows use Leviathan/Chen
    # rejection sampling against the SAME filtered distribution
    # `_sample_token` draws from, so the output distribution is provably
    # unchanged (different PRNG stream, though — spec draws accept/residual
    # variates instead of one categorical per step). capture_logprobs
    # reuses the verify logits, so accepted tokens still carry
    # full-distribution logprobs. 0 = this loop, bit-for-bit untouched.
    # Incompatible with compaction_segments > 0 (see above); composes with
    # page_size > 0 (paged verify writes) including the continuous-batching
    # decode_rows path — the modern replacement for that exclusion.
    spec_k: int = 0
    # n-gram context length the drafter matches on (spec_k > 0 only):
    # smaller = more matches (higher draft rate, lower precision), larger =
    # fewer but better drafts. 3 suits R1-style self-repetitive math
    # rollouts (restated problem text, \boxed{} scaffolding).
    spec_ngram: int = 3
    # queued paged path only (page_size > 0 with decode_rows > 0): >0 splits
    # any admission whose real prompt suffix exceeds this many tokens into
    # KV-only chunk forwards interleaved with the resident rows' decode
    # chunks (sampler/paged/session.py) — a long cold prompt no longer
    # stalls every live stream for its full prefill, bounding the p95
    # inter-token gap (bench detail.session gates it). GREEDY streams are
    # bit-identical to prefill_chunk=0 (the final chunk runs the same
    # bucketed suffix forward and samples from the same admission PRNG
    # fold, test-pinned); sampled streams are equal in distribution only
    # — a chunk-delayed row decodes at later global fold_in(key, it)
    # iterations than it would unchunked. 0 = whole-suffix admission.
    prefill_chunk: int = 0
    # n>1: prefill each prompt ONCE and fan the prompt KV out to its N
    # samples inside the jit, instead of repeating the prompt rows before
    # prefill — ÷N prefill FLOPs and prompt activation memory, the
    # TPU-static analogue of vLLM's prefix sharing for `n=4` requests
    # (`/root/reference/GRPO/grpo_trainer.py:127`). Token streams are
    # bit-identical to the repeat path on the CPU test mesh (test-pinned:
    # the fanned-out first logits and caches match the repeated rows', and
    # decode runs on the same [B*N] shapes either way); on real silicon the
    # fan-out can change XLA reduction/layout choices enough to flip
    # near-tie sampling decisions, so streams there are distributionally
    # equivalent rather than bit-identical (ADVICE r5). Quantify on a given
    # chip with `tools/ablate_decode.py` (the n4_shared vs n4_repeat
    # configs measure both the speedup and any stream divergence).
    shared_prompt_prefill: bool = True


def compose_check(sampling: SamplingParams, *,
                  prefix_cache: bool = False) -> None:
    """THE decode-feature composition gate: raises ValueError on every
    remaining-illegal combination, with the reason. Every entry point that
    assembles decode features (generate() below, the trainer's config
    validation) routes through this one function, so the legality matrix
    lives in exactly one place.

    Since the decode-session refactor (sampler/paged/session.py) the
    features compose by default — spec decode under the radix prefix
    cache, chunked prefill under either, serving's per-row sampling on
    the same loop (docs/PAGED_CACHE.md has the full feature×feature
    matrix). What remains illegal, and why:

      * compaction_segments > 0 with page_size > 0 — compaction is the
        legacy contiguous-layout straggler lever; its between-segment row
        gather assumes per-row [T_max] slabs, which the paged layout's
        block-table indirection doesn't have. The paged cache with
        decode_rows > 0 is its replacement, not its peer.
      * compaction_segments > 0 with spec_k > 0 — the gather also assumes
        every live row sits at the same decode step (shared cache-slot
        layout), which per-row accept lengths break.
      * prefix_cache without continuous batching (page_size > 0 AND
        decode_rows > 0) — the radix cache lives at the ADMISSION point;
        the monolithic one-jit paths prefill the whole batch at trace
        time and have no admission to cache across.
      * prefill_chunk > 0 without continuous batching — chunked prefill
        exists to protect RESIDENT rows' inter-token cadence during a
        long admission; the monolithic paths have neither residents nor
        admissions.

    Per-row serving constraints (spec requires static greedy, no logprob
    capture) are enforced by DecodeSession's constructor — they depend on
    the per_row flag the engine sets, not on SamplingParams."""
    if sampling.page_size > 0 and sampling.compaction_segments > 0:
        raise ValueError(
            "page_size > 0 is incompatible with compaction_segments > 0: "
            "compaction is the legacy contiguous-layout straggler lever "
            "(same-step row gathers over per-row slabs), and the paged "
            "cache replaces it outright — set decode_rows > 0 for true "
            "continuous batching over recycled pages instead of batch "
            "shrinking (sampler/paged/scheduler.py)."
        )
    if sampling.spec_k > 0 and sampling.compaction_segments > 0:
        raise ValueError(
            "spec_k > 0 is incompatible with compaction_segments > 0: "
            "compacting decode gathers rows under the assumption that "
            "every live row sits at the same decode step (shared "
            "cache-slot layout, sampler/compaction.py), which "
            "speculative decode's per-row accept lengths break. "
            "Compaction is legacy — the preferred straggler fix is the "
            "paged cache (SamplingParams.page_size > 0 with "
            "decode_rows > 0), whose continuous batching COMPOSES with "
            "spec_k instead of excluding it."
        )
    queued_capable = sampling.page_size > 0 and sampling.decode_rows > 0
    if prefix_cache and not queued_capable:
        raise ValueError(
            "prefix_cache requires continuous batching: set page_size > 0 "
            "and decode_rows > 0 (rollout_page_size / rollout_decode_rows "
            "on the trainer) — the monolithic paths have no admission "
            "point to cache across."
        )
    if sampling.prefill_chunk > 0 and not queued_capable:
        raise ValueError(
            "prefill_chunk > 0 requires continuous batching: set "
            "page_size > 0 and decode_rows > 0 — chunked prefill "
            "interleaves a long admission with RESIDENT rows' decode "
            "chunks, and the monolithic paths have neither residents nor "
            "mid-loop admissions to protect."
        )


def top_p_filter(logits: jnp.ndarray, top_p: float) -> jnp.ndarray:
    """Mask logits outside the top-p nucleus (smallest set with cum prob ≥ p).

    Sort-based exact variant — the reference/oracle for the sort-free
    bisection filter below and for the fused top-k path in the decode loop.
    """
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # keep tokens whose *exclusive* cumulative prob is < top_p (first always kept)
    keep_sorted = (cum - sorted_probs) < top_p
    # threshold = smallest kept logit
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits >= threshold, logits, -jnp.inf)


def top_p_filter_bisect(logits: jnp.ndarray, top_p: float,
                        iters: int = 26) -> jnp.ndarray:
    """Exact nucleus filter WITHOUT the full-vocab sort.

    XLA lowers `jnp.sort` over an LLM vocabulary to a slow multi-pass sort
    on TPU (the r2-measured decode hot spot), but the nucleus mask is a
    pure THRESHOLD set: sorted-descending, keep-while-exclusive-cum < p is
    exactly {i : p_i >= tau} where tau is the smallest probability in the
    minimal prefix reaching mass p (the sort-based filter keeps threshold
    ties the same way, `logits >= threshold`). The keep-set mass is a
    decreasing step function of tau, so tau comes from bisection over
    (0, p_max]: `iters` reduction passes over [B, V] (VPU-friendly
    elementwise+sum, no data movement) instead of a sort. 26 iterations
    leave an ABSOLUTE bracket of ~p_max·2^-26 ≈ 1.5e-8: near the top of
    the distribution that is inside f32 tie noise the sort cannot order
    stably either, but a token whose probability sits within ~1.5e-8
    BELOW the true cutoff can still be kept — for small-threshold tails
    at LLM vocab sizes this admits negligible extra tail mass rather
    than being bit-exact. Used by `_sample_token` for the `top_k=0`
    nucleus path (the r1-zero launcher default).
    """
    probs = jax.nn.softmax(logits, axis=-1)
    p_max = jnp.max(probs, axis=-1, keepdims=True)

    def step(carry, _):
        lo, hi = carry                       # mass(lo) >= top_p > mass(hi)
        mid = 0.5 * (lo + hi)
        mass = jnp.sum(jnp.where(probs >= mid, probs, 0.0), axis=-1,
                       keepdims=True)
        ok = mass >= top_p
        return (jnp.where(ok, mid, lo), jnp.where(ok, hi, mid)), None

    # lo=0 keeps everything (mass 1 >= p); hi just above p_max keeps nothing
    (lo, _), _ = jax.lax.scan(
        step, (jnp.zeros_like(p_max), p_max * (1 + 1e-6)), None, length=iters
    )
    return jnp.where(probs >= lo, logits, -jnp.inf)


def _nucleus_candidates(logits, top_p, top_k, approx_top_k):
    """(top_logits, top_idx, keep): the top-k candidate set plus the
    exclusive-cum nucleus keep rule over TRUE probabilities (full-vocab
    logsumexp normalization, so the keep set matches the exact filter).
    The single copy of the candidate-selection semantics, shared by
    `_sample_token`'s k-space categorical and the speculative verifier's
    full-vocab rejection filter (`filtered_logits_full`) — the two paths
    must agree on the keep set or spec decode would change the sampling
    distribution. `logits` arrive already temperature-scaled."""
    k = min(top_k, logits.shape[-1])
    if approx_top_k and k < logits.shape[-1]:
        # hardware-native approximate top-k (exact lax.top_k is a full-vocab
        # sort on TPU); aggregate_to_topk (default) already returns the
        # candidates exactly sorted descending
        top_logits, top_idx = jax.lax.approx_max_k(
            logits, k, recall_target=0.99
        )
    else:
        top_logits, top_idx = jax.lax.top_k(logits, k)  # descending
    lse = jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    probs = jnp.exp(top_logits - lse)                   # true (unrenormalized) probs
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_p                        # exclusive-cum; first always kept
    return top_logits, top_idx, keep


def filtered_logits_full(logits, temperature, top_p, top_k, approx_top_k):
    """Full-vocab filtered/temperature-scaled logits whose softmax is
    EXACTLY the distribution `_sample_token` draws from (same candidate
    set + keep rule via `_nucleus_candidates`; -inf outside the keep set).
    The speculative verifier's rejection sampler needs the distribution as
    a dense vocab vector (accept prob of an arbitrary drafted token +
    residual sampling with that token removed), which the k-space
    categorical never materializes. Supports any leading batch shape."""
    scaled = logits.astype(jnp.float32) / guard_temperature(temperature)
    if top_p >= 1.0:
        return scaled
    if top_k <= 0:
        return top_p_filter_bisect(scaled, top_p)
    lead = scaled.shape[:-1]
    V = scaled.shape[-1]
    flat = scaled.reshape(-1, V)
    top_logits, top_idx, keep = _nucleus_candidates(
        flat, top_p, top_k, approx_top_k
    )
    kept = jnp.where(keep, top_logits, -jnp.inf)
    rows = jnp.arange(flat.shape[0])[:, None]
    full = jnp.full_like(flat, -jnp.inf).at[rows, top_idx].set(kept)
    return full.reshape(*lead, V)


def _sample_token(key, logits, temperature, top_p, greedy, top_k=64,
                  approx_top_k=True):
    """Sample one token per row.

    `top_p >= 1.0` (no nucleus requested) stays an EXACT full-vocab
    categorical — truncating to top-k there would silently bias the sampling
    distribution away from the full-vocab logprobs the RL ratio math scores
    against. The nucleus path never sorts or draws Gumbel noise over the
    full vocabulary: candidates come from `lax.top_k`, the nucleus rule is
    applied over their TRUE probabilities (normalized by a full-vocab
    logsumexp, so the keep set matches the exact filter), and the
    categorical runs in k-space with indices mapped back through the top-k
    gather.
    """
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / guard_temperature(temperature)
    if top_p >= 1.0 or top_k <= 0:
        if top_p < 1.0:
            # exact full-vocab nucleus, sort-free (bisection threshold)
            logits = top_p_filter_bisect(logits, top_p)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
    top_logits, top_idx, keep = _nucleus_candidates(
        logits, top_p, top_k, approx_top_k
    )
    top_logits = jnp.where(keep, top_logits, -jnp.inf)
    choice = jax.random.categorical(key, top_logits, axis=-1)
    return jnp.take_along_axis(
        top_idx, choice[..., None], axis=-1
    )[..., 0].astype(jnp.int32)


def _token_logprob(logits, tok, temperature):
    """Full-distribution logprob of `tok` at the sampling temperature — the
    same quantity the scoring pass computes (`logprobs_from_logits`), through
    the SAME `guard_temperature` floor, so captured behavior logprobs and
    scoring logprobs agree bit-for-bit at small temperatures."""
    scaled = logits.astype(jnp.float32) / guard_temperature(temperature)
    lse = jax.nn.logsumexp(scaled, axis=-1)
    return jnp.take_along_axis(scaled, tok[..., None], axis=-1)[..., 0] - lse


@partial(
    jax.jit,
    static_argnames=("config", "max_tokens", "eos_token_id", "pad_token_id",
                     "temperature", "top_p", "greedy", "lora_scale", "top_k",
                     "capture_logprobs", "approx_top_k", "prompt_fanout",
                     "page_size"),
)
def generate_tokens(
    params: dict,
    config: ModelConfig,
    prompt_ids: jnp.ndarray,    # [B, Tp] left-padded
    prompt_mask: jnp.ndarray,   # [B, Tp] bool
    key: jax.Array,
    *,
    max_tokens: int,
    eos_token_id: int,
    pad_token_id: int,
    temperature: float = 1.0,
    top_p: float = 0.95,
    greedy: bool = False,
    lora_scale: float = 1.0,
    top_k: int = 64,
    capture_logprobs: bool = False,
    approx_top_k: bool = True,
    prompt_fanout: int = 1,
    page_size: int = 0,
) -> jnp.ndarray:
    """Core jitted loop: one sample per row. Returns [B*fanout, max_tokens]
    int32, or (tokens, logprobs f32) with capture_logprobs. `prompt_fanout`
    N prefills the [B] prompts once and decodes N samples per prompt
    (prompt-major rows), sharing the prompt KV. `page_size` > 0 runs the
    same loop over the paged KV layout (dense identity block table — no
    recycling here; see sampler/paged/scheduler.py for that)."""
    Tp = prompt_ids.shape[1]
    state = _prefill_state(
        params, config, prompt_ids, prompt_mask, key,
        max_tokens=max_tokens, eos_token_id=eos_token_id,
        pad_token_id=pad_token_id, temperature=temperature, top_p=top_p,
        greedy=greedy, lora_scale=lora_scale, top_k=top_k,
        capture_logprobs=capture_logprobs, approx_top_k=approx_top_k,
        prompt_fanout=prompt_fanout, page_size=page_size,
    )

    def cond(state):
        return (state[0] < max_tokens) & ~jnp.all(state[5])

    def body(state):
        return _decode_body(
            params, config, state, Tp=Tp, max_tokens=max_tokens,
            eos_token_id=eos_token_id, pad_token_id=pad_token_id,
            temperature=temperature, top_p=top_p, greedy=greedy,
            lora_scale=lora_scale, top_k=top_k,
            capture_logprobs=capture_logprobs, approx_top_k=approx_top_k,
            page_size=page_size,
        )

    _, out, lp_out, _, _, _, _, _, _ = jax.lax.while_loop(cond, body, state)
    return (out, lp_out) if capture_logprobs else out


def _prefill_state(params, config, prompt_ids, prompt_mask, key, *,
                   max_tokens, eos_token_id, pad_token_id, temperature,
                   top_p, greedy, lora_scale, top_k, capture_logprobs,
                   approx_top_k, prompt_fanout=1, cache_extra=0,
                   page_size=0):
    """Prefill + first sampled token → the decode-loop carry state:
    (step, out, lp_out, caches, key_mask, done, cur_tok, prompt_len, key).
    Per-step sampling keys are fold_in(key, step), so a segment boundary
    (compaction.py) resumes the identical stream.

    `prompt_fanout` N: the prompts arrive UN-repeated; prefill runs on the
    [B] rows once, then the first logits, prompt KV, and per-row metadata
    fan out ×N (prompt-major, matching `jnp.repeat(..., n, axis=0)` row
    order) before the first token is sampled. Everything downstream —
    including the [B*N]-shaped categorical draw — is then identical to
    prefilling N repeated copies, at 1/N the prefill FLOPs. The interleaved
    repeat is collective-free under a data-sharded batch: each device's row
    block fans out to its own contiguous output block.

    `cache_extra` pads the KV cache/key_mask past Tp + max_tokens — the
    speculative path (spec_k slack) needs room for a full k+1 candidate
    write when a row sits one token short of the budget; 0 (every other
    caller) keeps shapes bit-identical to before. GATED TO THE CONTIGUOUS
    LAYOUT: on the paged path (`page_size` > 0) the slack is forced to 0 —
    a row's page budget ceil(T_max/page_size) already rounds up past the
    logical width, and a verify write past the budget drops at the
    table-routed scatter instead of clobbering a neighbor row, so reserved
    slots buy nothing (the dropped candidates are beyond `max_tokens` and
    are truncated before emission either way — docs/PAGED_CACHE.md walks
    the bound).

    `page_size` > 0 allocates the paged layout instead of contiguous slabs:
    a pool of exactly B*ceil(T_max/page_size) pages with the dense identity
    table (`full_table`) — a pure re-layout of the contiguous cache, no
    recycling, so this state is interchangeable with the contiguous one
    token-for-token."""
    B, Tp = prompt_ids.shape
    if page_size > 0:
        cache_extra = 0
    T_max = Tp + max_tokens + cache_extra
    prompt_mask = prompt_mask.astype(bool)
    dtype = params["embed_tokens"].dtype

    if page_size > 0:
        nb = -(-T_max // page_size)
        caches = init_paged_kv_cache(config, B * nb, page_size, dtype)
        first_logits, caches = prefill(
            params, config, prompt_ids, prompt_mask, caches,
            lora_scale=lora_scale, page_table=full_table(B, nb),
            page_size=page_size, logical_len=T_max,
        )
    else:
        caches = init_kv_cache(config, B, T_max, dtype)
        first_logits, caches = prefill(params, config, prompt_ids, prompt_mask,
                                       caches, lora_scale=lora_scale)

    if prompt_fanout > 1:
        first_logits = jnp.repeat(first_logits, prompt_fanout, axis=0)
        if page_size > 0:
            # pools are stacked [L, B*nb, ...]: fan out whole page GROUPS so
            # row r of the fanned table (identity again) lands on a copy of
            # proto row r // N's pages — the same values the contiguous
            # repeat produces, page-major
            nb = -(-T_max // page_size)
            caches = jax.tree.map(
                lambda c: jnp.repeat(
                    c.reshape(c.shape[0], B, nb, *c.shape[2:]),
                    prompt_fanout, axis=1,
                ).reshape(c.shape[0], B * prompt_fanout * nb, *c.shape[2:]),
                caches,
            )
        else:
            # caches are stacked [L, B, KV, T, d] — batch on axis 1
            caches = jax.tree.map(
                lambda c: jnp.repeat(c, prompt_fanout, axis=1), caches
            )
        prompt_mask = jnp.repeat(prompt_mask, prompt_fanout, axis=0)
        B = B * prompt_fanout

    prompt_len = jnp.sum(prompt_mask, axis=1).astype(jnp.int32)  # real prompt length
    key_mask0 = jnp.zeros((B, T_max), bool).at[:, :Tp].set(prompt_mask)

    out0 = jnp.full((B, max_tokens), pad_token_id, jnp.int32)
    lp0 = jnp.zeros((B, max_tokens), jnp.float32)
    tok0 = _sample_token(jax.random.fold_in(key, 0), first_logits, temperature,
                         top_p, greedy, top_k, approx_top_k)
    out0 = out0.at[:, 0].set(tok0)
    if capture_logprobs:
        lp0 = lp0.at[:, 0].set(_token_logprob(first_logits, tok0, temperature))
    done0 = tok0 == eos_token_id
    return (jnp.int32(1), out0, lp0, caches, key_mask0, done0, tok0,
            prompt_len, key)


def _decode_body(params, config, state, *, Tp, max_tokens, eos_token_id,
                 pad_token_id, temperature, top_p, greedy, lora_scale, top_k,
                 capture_logprobs, approx_top_k, page_size=0):
    """One decode step over the carry state (shared by the monolithic
    while_loop above and the segmented/compacting loop). `page_size` > 0:
    the caches in the carry are paged pools; the dense identity table is a
    shape-derived constant (pool pages // batch rows), so the carry layout
    is unchanged."""
    step, out, lp_out, caches, key_mask, done, cur_tok, prompt_len, key = state
    paged_kw = {}
    if page_size > 0:
        B = key_mask.shape[0]
        paged_kw = dict(page_table=full_table(B, caches[0].shape[1] // B),
                        page_size=page_size)
    # token t was sampled from logits at position prompt_len + step - 1;
    # its KV lands in cache slot Tp + step - 1
    cache_slot = Tp + step - 1
    key_mask = key_mask.at[:, cache_slot].set(True)  # current slot becomes visible
    position = prompt_len + step - 1
    logits, caches = decode_step(
        params, config, cur_tok, position, cache_slot, key_mask, caches,
        lora_scale=lora_scale, **paged_kw,
    )
    tok = _sample_token(jax.random.fold_in(key, step), logits, temperature,
                        top_p, greedy, top_k, approx_top_k)
    tok = jnp.where(done, pad_token_id, tok)
    write = (jnp.arange(max_tokens) == step)[None, :] & ~done[:, None]
    out = jnp.where(write, tok[:, None], out)
    if capture_logprobs:
        lp = _token_logprob(logits, tok, temperature)
        lp_out = jnp.where(write, lp[:, None], lp_out)
    done = done | (tok == eos_token_id)
    return (step + 1, out, lp_out, caches, key_mask, done, tok,
            prompt_len, key)


def generate(
    params: dict,
    config: ModelConfig,
    prompt_ids: jnp.ndarray,
    prompt_mask: jnp.ndarray,
    key: jax.Array,
    sampling: SamplingParams,
    eos_token_id: int,
    pad_token_id: int,
    lora_scale: float = 1.0,
    batch_sharding=None,
    spec_stats_out: list | None = None,
    tracer=None,
    paged_stats_out: list | None = None,
    latency=None,
    prefix_cache=None,
    weight_refresh=None,
) -> jnp.ndarray:
    """vllm_generate-contract entry: [B*N, max_tokens], N consecutive per
    prompt; (tokens, logprobs) when `sampling.capture_logprobs`.

    `batch_sharding` (optional NamedSharding over the batch axes) is only
    consumed by the compacting path, which re-lays-out gathered carries.

    `spec_stats_out` (spec_k > 0 only): a caller-provided list the
    speculative path appends its per-call stats dict to (device scalars:
    verify steps, drafted/accepted/emitted token counts) — the trainer's
    rollout/draft_acceptance metrics and bench's detail.spec_decode read
    it without changing the return contract. `tracer` (an enabled
    telemetry.SpanTracer) switches the speculative path to its
    host-driven loop with real per-iteration "rollout.draft"/
    "rollout.verify" spans (one sync per verify step — observability
    mode, not the fully-async default).

    `paged_stats_out` (page_size > 0 only): same pattern for the paged
    cache — a dict with page_utilization / pages_recycled /
    admitted_midloop (+ per-admission records on the continuous-batching
    path) feeding the trainer's rollout/page_* metrics, the /statusz
    `pages` section, and lineage lease events.

    `latency` (an enabled telemetry.LatencyHub): the queued paged path
    records true per-request TTFT and per-sync-chunk inter-token gaps
    into it (hist.py); the monolithic one-jit paths ignore it — their
    dispatch→ready wall is recorded by the orchestrator instead.

    `prefix_cache` (an enabled serving.RadixCache): the queued paged path
    admits rows through the cross-request radix prefix cache — matched
    prompt prefixes install refcount-shared pages with zero prefill FLOPs
    and only the suffix is prefilled (serving/radix.py). The cache resets
    per call (KV is tied to params), so within a rollout the win comes
    from the n>1 fanout and repeated dataset prompts. Ignored by the
    non-queued paths; COMPOSES with spec_k > 0 (the drafter seeds its
    lookup window from the cached continuation — see compose_check for
    the full legality matrix).

    `weight_refresh` (optional `() -> (version, tree|None)`): in-flight
    mid-sequence weight swaps on the QUEUED paged path only — polled at
    every host sync chunk, a newer tree replaces the session params before
    the next decode chunk and the paged-stats entry grows per-request
    `segments` (docs/ORCHESTRATOR.md §in-flight swaps). The monolithic
    one-jit paths have no host sync point to swap at and ignore it (the
    trainer's `rollout_inflight_swaps` validation requires the queued
    path)."""
    compose_check(sampling, prefix_cache=(
        prefix_cache is not None
        and getattr(prefix_cache, "enabled", False)))
    total_rows = prompt_ids.shape[0] * sampling.n
    queued = (sampling.page_size > 0 and sampling.decode_rows > 0
              and sampling.decode_rows < total_rows)
    fanout = 1
    if sampling.n > 1:
        if sampling.shared_prompt_prefill and not queued:
            # prompts stay [B]; prefill-once-fan-out happens inside the jit
            fanout = sampling.n
        else:
            # queued admission prefills one row at a time — no shared-prefill
            # fan-out there, each logical row becomes its own queue entry
            prompt_ids = jnp.repeat(prompt_ids, sampling.n, axis=0)
            prompt_mask = jnp.repeat(prompt_mask, sampling.n, axis=0)
    if queued:
        from nanorlhf_tpu.sampler.paged.scheduler import generate_tokens_queued

        return generate_tokens_queued(
            params, config, prompt_ids, prompt_mask, key,
            max_tokens=sampling.max_tokens, eos_token_id=eos_token_id,
            pad_token_id=pad_token_id, page_size=sampling.page_size,
            decode_rows=sampling.decode_rows, spec_k=sampling.spec_k,
            spec_ngram=sampling.spec_ngram,
            temperature=sampling.temperature, top_p=sampling.top_p,
            greedy=sampling.greedy, lora_scale=lora_scale,
            top_k=sampling.top_k, capture_logprobs=sampling.capture_logprobs,
            approx_top_k=sampling.approx_top_k,
            prefill_chunk=sampling.prefill_chunk,
            spec_stats_out=spec_stats_out, paged_stats_out=paged_stats_out,
            latency=latency, prefix_cache=prefix_cache,
            weight_refresh=weight_refresh,
        )
    if sampling.spec_k > 0:
        from nanorlhf_tpu.sampler.speculative import generate_spec

        result = generate_spec(
            params, config, prompt_ids, prompt_mask, key,
            max_tokens=sampling.max_tokens, eos_token_id=eos_token_id,
            pad_token_id=pad_token_id, spec_k=sampling.spec_k,
            spec_ngram=sampling.spec_ngram,
            temperature=sampling.temperature, top_p=sampling.top_p,
            greedy=sampling.greedy, lora_scale=lora_scale,
            top_k=sampling.top_k, capture_logprobs=sampling.capture_logprobs,
            approx_top_k=sampling.approx_top_k, prompt_fanout=fanout,
            spec_stats_out=spec_stats_out, tracer=tracer,
            page_size=sampling.page_size,
        )
        _monolithic_paged_stats(result, sampling, prompt_mask, fanout,
                                pad_token_id, paged_stats_out)
        return result
    if sampling.compaction_segments > 0:
        from nanorlhf_tpu.sampler.compaction import generate_tokens_compact

        return generate_tokens_compact(
            params, config, prompt_ids, prompt_mask, key,
            max_tokens=sampling.max_tokens, eos_token_id=eos_token_id,
            pad_token_id=pad_token_id, segments=sampling.compaction_segments,
            temperature=sampling.temperature, top_p=sampling.top_p,
            greedy=sampling.greedy, lora_scale=lora_scale,
            top_k=sampling.top_k, capture_logprobs=sampling.capture_logprobs,
            approx_top_k=sampling.approx_top_k,
            batch_sharding=batch_sharding,
            prompt_fanout=fanout,
        )
    result = generate_tokens(
        params,
        config,
        prompt_ids,
        prompt_mask,
        key,
        max_tokens=sampling.max_tokens,
        eos_token_id=eos_token_id,
        pad_token_id=pad_token_id,
        temperature=sampling.temperature,
        top_p=sampling.top_p,
        greedy=sampling.greedy,
        lora_scale=lora_scale,
        top_k=sampling.top_k,
        capture_logprobs=sampling.capture_logprobs,
        approx_top_k=sampling.approx_top_k,
        prompt_fanout=fanout,
        page_size=sampling.page_size,
    )
    _monolithic_paged_stats(result, sampling, prompt_mask, fanout,
                            pad_token_id, paged_stats_out)
    return result


def _monolithic_paged_stats(result, sampling, prompt_mask, fanout,
                            pad_token_id, paged_stats_out):
    """Fill `paged_stats_out` for the monolithic (non-queued) paged paths:
    no recycling, no admissions — utilization is just final cache occupancy
    over the fully-provisioned pool. Device scalars only (no sync; the
    trainer materializes them at metrics time like spec_stats)."""
    if paged_stats_out is None or sampling.page_size <= 0:
        return
    toks = result[0] if sampling.capture_logprobs else result
    rows, Tp = toks.shape[0], prompt_mask.shape[1]
    P = sampling.page_size
    nb = -(-(Tp + sampling.max_tokens) // P)
    used = (jnp.sum(prompt_mask) * fanout
            + jnp.sum(toks != pad_token_id)).astype(jnp.float32)
    paged_stats_out.append({
        "page_utilization": used / jnp.float32(rows * nb * P),
        "pages_recycled": jnp.int32(0),
        "admitted_midloop": jnp.int32(0),
        "decode_iterations": None,
        "rows": rows,
        "num_pages": rows * nb,
        "page_size": P,
        "admissions": [],
    })
