"""Draft-free speculative decode: n-gram drafting + batched k-token verify.

The rollout at the headline operating point is HBM-bandwidth-bound: ≈8.7 of
the ≈9 ms/step roofline is weight + KV-cache streaming, paid once per
SINGLE generated token (docs/DECODE_ANALYSIS.md). Verifying k drafted
tokens in one `decode_verify` forward amortizes that dominant stream over
every accepted token — the decode-side lever TPU-scale RL stacks lean on
to keep generation off the critical path (RLAX, arxiv 2512.06392;
PipelineRL, arxiv 2509.19128). R1-style math rollouts are highly
self-repetitive (restated problem text, `\\boxed{}` scaffolding, step
templates), so a FREE drafter — prompt-lookup n-gram matching against the
row's own prompt+output buffer, no draft model, zero extra weights —
gets useful acceptance with zero extra model memory.

Per iteration (one `lax.while_loop` step, fully jitted, static shapes):

  1. **draft**: match the last `spec_ngram` emitted tokens of each row
     against every earlier window of its prompt+output buffer (pure
     shifted-compare + gather — no sort, no host sync); propose the
     `spec_k` tokens that followed the most recent match. No match →
     propose pads; verification rejects them and the row still advances
     one token (the bounded-overhead case: one verify forward per token,
     ≈ the monolithic step plus the k extra query rows).
  2. **verify**: ONE small-T causal forward over [cur_tok, d_1..d_k]
     against the cache (`core/model.decode_verify`), producing the exact
     next-token distribution after each candidate prefix.
  3. **accept**: greedy rows keep the longest draft prefix that matches
     the argmax chain — bit-exact vs the monolithic loop. Sampled rows run
     Leviathan/Chen rejection sampling with the deterministic drafter as
     the proposal (accept d with prob p̃(d); on reject, sample from p̃ with
     d removed, renormalized) against the SAME filtered distribution
     `_sample_token` draws from (`filtered_logits_full` shares the
     candidate/keep-rule code), so the output distribution is provably
     unchanged — pinned by the enumeration test in
     tests/test_speculative.py. Every iteration emits between 1 and k+1
     tokens per live row.

Bookkeeping is per-row (accepted rows advance at different rates): the
carry holds [B] generated-token counts, cache fill follows
`Tp + n_gen - 1`, accepted candidates' KV (already written by the verify
forward) is made visible by extending `key_mask`, and rejected candidates
leave garbage KV in never-validated slots that the next verify overwrites.
The KV cache carries `spec_k` slack slots past Tp + max_tokens so a row
one token short of the budget can still absorb a full k+1 candidate write
without clamping into valid slots. On the PAGED layout (`page_size` > 0)
that slack is gated to 0: a candidate write past the row's page budget
drops at the table-routed scatter instead of clobbering anything, and the
dropped positions sit beyond `max_tokens - n_gen`, which the emission
clamp truncates anyway — see docs/PAGED_CACHE.md for the bound.

Interaction with compaction (sampler/compaction.py): mutually exclusive —
compaction's row gather assumes all rows share the same step alignment,
which per-row accept lengths break; `generate` raises on the combination.
The paged cache (SamplingParams.page_size) is the replacement straggler
lever and COMPOSES with this path: monolithic paged verify here, and the
continuous-batching scheduler (sampler/paged/scheduler.py) reuses
`_draft_fn`/`_verify_fn` directly with a live block table.

`capture_logprobs` reuses the verify logits: accepted tokens carry the
same full-distribution logprob `_token_logprob` computes in the monolithic
loop (greedy parity is test-pinned).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from nanorlhf_tpu.core.config import ModelConfig
from nanorlhf_tpu.core.model import decode_verify
from nanorlhf_tpu.ops.masking import guard_temperature
from nanorlhf_tpu.sampler.paged.pages import full_table
from nanorlhf_tpu.sampler.sampler import (
    _prefill_state,
    filtered_logits_full,
)

# static-arg sets for the jitted entrypoints (each lists exactly the
# names present in the wrapped signature — jax rejects unknown names)
_GEN_STATIC = (
    "config", "max_tokens", "eos_token_id", "pad_token_id", "spec_k",
    "spec_ngram", "temperature", "top_p", "greedy", "lora_scale", "top_k",
    "capture_logprobs", "approx_top_k", "prompt_fanout", "page_size",
)
_VERIFY_STATIC = (
    "config", "Tp", "max_tokens", "eos_token_id", "pad_token_id", "spec_k",
    "temperature", "top_p", "greedy", "lora_scale", "top_k",
    "capture_logprobs", "approx_top_k", "page_size",
)


def ngram_propose(buf, end, valid_start, *, k, m, pad_token_id):
    """Prompt-lookup drafting, static shapes, pure gather.

    buf: [B, S] per-row token buffer (left-padded prompt at
    [valid_start, Tp), generated tokens at [Tp, end), pads elsewhere).
    end / valid_start: [B] int32. Proposes the k tokens that followed the
    MOST RECENT earlier occurrence of the row's last m tokens; rows with
    no match get `pad_token_id` drafts (verification rejects them).
    Returns (drafts [B, k] int32, has_match [B] bool).
    """
    B, S = buf.shape
    # context: the last m tokens of each row, buf[end-m .. end-1]
    ctx_pos = jnp.clip(end[:, None] - m + jnp.arange(m)[None, :], 0, S - 1)
    ctx = jnp.take_along_axis(buf, ctx_pos, axis=1)          # [B, m]
    # match[b, j]: the window ENDING at j equals ctx. shifted_d[b, j] =
    # buf[b, j-d] (zero-filled below j=d; those j fail the range check)
    match = jnp.ones((B, S), bool)
    for d in range(m):
        shifted = jnp.pad(buf, ((0, 0), (d, 0)))[:, :S] if d else buf
        match = match & (shifted == ctx[:, m - 1 - d][:, None])
    j = jnp.arange(S)[None, :]
    in_range = (j - (m - 1) >= valid_start[:, None]) & (j <= end[:, None] - 2)
    j_star = jnp.max(jnp.where(match & in_range, j, -1), axis=1)  # [B]
    has = j_star >= 0
    d_pos = jnp.clip(j_star[:, None] + 1 + jnp.arange(k)[None, :], 0, S - 1)
    drafts = jnp.take_along_axis(buf, d_pos, axis=1)
    drafts = jnp.where(has[:, None], drafts, pad_token_id)
    return drafts.astype(jnp.int32), has


def accept_candidates(logits, drafts, step_key, *, temperature, top_p, top_k,
                      greedy, approx_top_k):
    """Exact acceptance rule over verify logits.

    logits: [B, k+1, V] — logits[:, i] is the model's next-token
    distribution after consuming candidate i (cur_tok, d_1..d_i).
    drafts: [B, k]. Returns (emitted [B, k+1], acc [B]): emitted[:, :acc]
    are the accepted drafts, emitted[:, acc] is the model's own token at
    the first mismatch (or a bonus token when all k drafts survive) —
    every iteration emits acc+1 tokens.

    Greedy: accept while d_i equals the argmax chain — bit-exact vs the
    monolithic loop. Sampled: deterministic-proposal rejection sampling
    (Leviathan et al. 2023 / Chen et al. 2023): accept d_i with
    probability p̃_i(d_i) under the SAME filtered distribution
    `_sample_token` uses; on rejection, sample from p̃_i with d_i removed,
    renormalized — the marginal at every position is exactly p̃_i
    (P(tok=d) = p̃(d); P(tok=v≠d) = (1-p̃(d))·p̃(v)/(1-p̃(d)) = p̃(v)).
    """
    B, K1, V = logits.shape
    k = K1 - 1
    if greedy:
        t_hat = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # [B, k+1]
        ok = drafts == t_hat[:, :k]
        acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
        final = jnp.take_along_axis(t_hat, acc[:, None], axis=1)[:, 0]
    else:
        filtered = filtered_logits_full(
            logits, temperature, top_p, top_k, approx_top_k
        )                                                       # [B, k+1, V]
        logp = jax.nn.log_softmax(filtered, axis=-1)
        p_draft = jnp.exp(jnp.take_along_axis(
            logp[:, :k], drafts[..., None], axis=-1
        )[..., 0])                                              # [B, k]
        key_u, key_r = jax.random.split(step_key)
        u = jax.random.uniform(key_u, (B, k))
        ok = u < p_draft
        acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
        # residual/bonus draws for EVERY position, selected at `acc`:
        # positions i<k sample p̃ with the draft removed (the rejection
        # residual — the drafter is a point mass, so max(p̃-q, 0) ∝ p̃ minus
        # the drafted token); position k samples p̃ unmasked (bonus token)
        masked = filtered.at[
            jnp.arange(B)[:, None], jnp.arange(k)[None, :], drafts
        ].set(-jnp.inf)
        res = jax.random.categorical(key_r, masked, axis=-1).astype(jnp.int32)
        final = jnp.take_along_axis(res, acc[:, None], axis=1)[:, 0]
    arange = jnp.arange(K1)[None, :]
    drafts_ext = jnp.concatenate(
        [drafts, jnp.zeros((B, 1), jnp.int32)], axis=1
    )
    emitted = jnp.where(
        arange < acc[:, None], drafts_ext,
        jnp.where(arange == acc[:, None], final[:, None], 0),
    )
    return emitted, acc


def _draft_fn(prompt_rep, state, *, Tp, spec_k, spec_ngram, pad_token_id,
              seed_rep=None, seed_len=None):
    """Draft step over the carry: build the prompt+output buffer and
    propose spec_k tokens per row.

    `seed_rep` ([R, W] int32, right-aligned) / `seed_len` ([R] int32),
    when given, prepend a per-row SEED window to the lookup buffer — the
    radix-matched cached continuation the decode session installs at
    admission (sampler/paged/session.py), which fixes the drafter's
    cold-start blind spot: without it the n-gram match only sees the
    row's OWN prompt+output, so prefix-heavy corpora draft nothing until
    the row has repeated itself. Rows with `seed_len == 0` keep exactly
    the unseeded valid range (shifted by the constant W, which the match
    positions are relative to, so proposals are unchanged). The pad gap
    between a row's seed tail and its first real prompt token stays
    INSIDE the valid range — a window straddling it only matches when
    the row's recent output equals pad runs, which live rows never emit,
    and a junk draft merely gets rejected by verification (greedy output
    is draft-independent either way)."""
    out, done, n_gen, prompt_len = state[1], state[5], state[7], state[8]
    if seed_rep is None:
        buf = jnp.concatenate([prompt_rep, out], axis=1)
        drafts, _ = ngram_propose(
            buf, Tp + n_gen, Tp - prompt_len, k=spec_k, m=spec_ngram,
            pad_token_id=pad_token_id,
        )
        return drafts
    W = seed_rep.shape[1]
    buf = jnp.concatenate([seed_rep, prompt_rep, out], axis=1)
    valid_start = jnp.where(seed_len > 0, W - seed_len,
                            W + Tp - prompt_len)
    drafts, _ = ngram_propose(
        buf, W + Tp + n_gen, valid_start, k=spec_k, m=spec_ngram,
        pad_token_id=pad_token_id,
    )
    return drafts


def _verify_fn(params, config, state, drafts, *, Tp, max_tokens,
               eos_token_id, pad_token_id, spec_k, temperature, top_p,
               greedy, lora_scale, top_k, capture_logprobs, approx_top_k,
               page_size=0, page_table=None):
    """Verify + accept + per-row bookkeeping: one forward over the k+1
    candidates, the acceptance rule, then masked multi-token output
    writes, per-row cache-length/key_mask advance, EOS/budget termination,
    and the acceptance counters.

    `page_size` > 0 runs the verify forward against the paged cache; a
    `page_table` of None rebuilds the dense identity table from the pool
    shape (the monolithic paged path), while the continuous-batching
    scheduler passes its live recycled table."""
    (it, out, lp_out, caches, key_mask, done, cur_tok, n_gen, prompt_len,
     key, n_drafted, n_accepted, n_emitted, n_rowsteps, row_acc) = state
    B = cur_tok.shape[0]
    K1 = spec_k + 1
    arange = jnp.arange(K1)[None, :]

    paged_kw = {}
    if page_size > 0:
        if page_table is None:
            page_table = full_table(B, caches[0].shape[1] // B)
        paged_kw = dict(page_table=page_table, page_size=page_size)
    tokens = jnp.concatenate([cur_tok[:, None], drafts], axis=1)
    positions = (prompt_len + n_gen - 1)[:, None] + jnp.arange(K1)[None, :]
    fill = Tp + n_gen - 1                                   # [B] slot of cur_tok
    logits, caches = decode_verify(
        params, config, tokens, positions, fill, key_mask, caches,
        lora_scale=lora_scale, **paged_kw,
    )
    emitted, acc = accept_candidates(
        logits, drafts, jax.random.fold_in(key, it),
        temperature=temperature, top_p=top_p, top_k=top_k, greedy=greedy,
        approx_top_k=approx_top_k,
    )

    # emission length: acc drafts + 1 model token, truncated at the first
    # EOS inside the accepted block and at the response budget; 0 for rows
    # that were already done (their verify output is discarded wholesale)
    n_emit = acc + 1
    is_eos = (emitted == eos_token_id) & (arange < n_emit[:, None])
    any_eos = jnp.any(is_eos, axis=1)
    n_emit = jnp.where(any_eos, jnp.argmax(is_eos, axis=1) + 1, n_emit)
    n_emit = jnp.minimum(n_emit, max_tokens - n_gen)
    n_emit = jnp.where(done, 0, n_emit)

    # masked multi-token output writes: row b writes emitted[b, :n_emit[b]]
    # at out[b, n_gen[b]:]; invalid lanes get an out-of-range index and drop
    wpos = jnp.where(arange < n_emit[:, None], n_gen[:, None] + arange,
                     max_tokens)
    rows = jnp.arange(B)[:, None]
    out = out.at[rows, wpos].set(emitted, mode="drop")
    if capture_logprobs:
        # full-distribution logprobs straight from the verify logits — the
        # same quantity (and guard_temperature floor) _token_logprob gives
        # the monolithic loop
        scaled = logits.astype(jnp.float32) / guard_temperature(temperature)
        lse = jax.nn.logsumexp(scaled, axis=-1)
        lp_mat = jnp.take_along_axis(
            scaled, emitted[..., None], axis=-1
        )[..., 0] - lse
        lp_out = lp_out.at[rows, wpos].set(lp_mat, mode="drop")

    # advance: the last emitted token becomes cur_tok; its KV slot stays
    # outside key_mask (the invariant — it is (re)written next iteration)
    last = jnp.take_along_axis(
        emitted, jnp.maximum(n_emit - 1, 0)[:, None], axis=1
    )[:, 0]
    cur_tok = jnp.where(n_emit > 0, last, cur_tok)
    slot = jnp.arange(key_mask.shape[1])[None, :]
    key_mask = key_mask | (
        (slot >= fill[:, None]) & (slot < (fill + n_emit)[:, None])
    )
    n_gen = n_gen + n_emit
    eos_emitted = jnp.any(
        (emitted == eos_token_id) & (arange < n_emit[:, None]), axis=1
    )
    live = ~done
    done = done | eos_emitted | (n_gen >= max_tokens)

    liv = live.astype(jnp.int32)
    acc_row = liv * jnp.minimum(acc, jnp.maximum(n_emit - 1, 0))  # [B]
    n_drafted = n_drafted + jnp.sum(liv) * spec_k
    n_accepted = n_accepted + jnp.sum(acc_row)
    n_emitted = n_emitted + jnp.sum(n_emit)
    n_rowsteps = n_rowsteps + jnp.sum(liv)     # live (row, verify-step) pairs
    row_acc = row_acc + acc_row  # per-row accepted drafts (lineage ledger)
    return (it + 1, out, lp_out, caches, key_mask, done, cur_tok, n_gen,
            prompt_len, key, n_drafted, n_accepted, n_emitted, n_rowsteps,
            row_acc)


def _spec_state(base_state):
    """Prefill carry → speculative carry: the scalar step counter becomes a
    per-row generated-token count (accepted rows advance at different
    rates) plus the acceptance counters."""
    (_step, out, lp_out, caches, key_mask, done, tok, prompt_len,
     key) = base_state
    B = tok.shape[0]
    zero = jnp.int32(0)
    return (jnp.int32(1), out, lp_out, caches, key_mask, done, tok,
            jnp.ones((B,), jnp.int32), prompt_len, key, zero, zero, zero,
            zero, jnp.zeros((B,), jnp.int32))


@partial(jax.jit, static_argnames=_GEN_STATIC)
def generate_tokens_spec(
    params: dict,
    config: ModelConfig,
    prompt_ids: jnp.ndarray,
    prompt_mask: jnp.ndarray,
    key: jax.Array,
    *,
    max_tokens: int,
    eos_token_id: int,
    pad_token_id: int,
    spec_k: int,
    spec_ngram: int = 3,
    temperature: float = 1.0,
    top_p: float = 0.95,
    greedy: bool = False,
    lora_scale: float = 1.0,
    top_k: int = 64,
    capture_logprobs: bool = False,
    approx_top_k: bool = True,
    prompt_fanout: int = 1,
    page_size: int = 0,
):
    """Jitted speculative decode loop (the async default). Same output
    contract as `generate_tokens` plus a stats tuple:
    (tokens [B*fanout, max_tokens], logprobs f32, (verify_steps, drafted,
    accepted, emitted, row_steps, accepted_rows) — int32 device scalars
    plus a per-row [B*fanout] accepted-draft vector). `verify_steps` is
    the decode dispatch count — the number the monolithic loop pays once
    per token; `row_steps` counts live (row, verify-step) pairs, so
    emitted/row_steps is mean tokens per row per dispatch (monolithic:
    identically 1)."""
    Tp = prompt_ids.shape[1]
    base = _prefill_state(
        params, config, prompt_ids, prompt_mask, key,
        max_tokens=max_tokens, eos_token_id=eos_token_id,
        pad_token_id=pad_token_id, temperature=temperature, top_p=top_p,
        greedy=greedy, lora_scale=lora_scale, top_k=top_k,
        capture_logprobs=capture_logprobs, approx_top_k=approx_top_k,
        prompt_fanout=prompt_fanout, cache_extra=spec_k,
        page_size=page_size,
    )
    prompt_rep = (
        jnp.repeat(prompt_ids, prompt_fanout, axis=0)
        if prompt_fanout > 1 else prompt_ids
    )
    state = _spec_state(base)
    statics = dict(
        Tp=Tp, max_tokens=max_tokens, eos_token_id=eos_token_id,
        pad_token_id=pad_token_id, spec_k=spec_k, temperature=temperature,
        top_p=top_p, greedy=greedy, lora_scale=lora_scale, top_k=top_k,
        capture_logprobs=capture_logprobs, approx_top_k=approx_top_k,
        page_size=page_size,
    )

    def cond(s):
        # every live row emits >= 1 token/iteration, so max_tokens bounds
        # the trip count; the done check is the real exit
        return (s[0] <= max_tokens) & ~jnp.all(s[5])

    def body(s):
        drafts = _draft_fn(prompt_rep, s, Tp=Tp, spec_k=spec_k,
                           spec_ngram=spec_ngram, pad_token_id=pad_token_id)
        return _verify_fn(params, config, s, drafts, **statics)

    state = jax.lax.while_loop(cond, body, state)
    stats = (state[0] - 1, state[10], state[11], state[12], state[13],
             state[14])
    return state[1], state[2], stats


_draft_jit = partial(
    jax.jit, static_argnames=("Tp", "spec_k", "spec_ngram", "pad_token_id")
)(_draft_fn)
_verify_jit = partial(jax.jit, static_argnames=_VERIFY_STATIC)(_verify_fn)
_prefill_jit = partial(
    jax.jit,
    static_argnames=("config", "max_tokens", "eos_token_id", "pad_token_id",
                     "temperature", "top_p", "greedy", "lora_scale", "top_k",
                     "capture_logprobs", "approx_top_k", "prompt_fanout",
                     "cache_extra", "page_size"),
)(_prefill_state)


def _generate_spec_instrumented(params, config, prompt_ids, prompt_mask, key,
                                tracer, **kw):
    """Host-driven variant for telemetry runs: the same jitted draft/verify
    pieces, one iteration per host step, with real per-iteration
    "rollout.draft"/"rollout.verify" spans on the "rollout" track
    (docs/OBSERVABILITY.md). Costs one device sync per verify step — the
    observability trade, mirroring compaction's per-segment sync; the
    default (tracer off) path is the fully-async jitted while_loop."""
    Tp = prompt_ids.shape[1]
    spec_k, spec_ngram = kw["spec_k"], kw["spec_ngram"]
    prompt_fanout = kw["prompt_fanout"]
    pre_kw = {k: v for k, v in kw.items()
              if k not in ("spec_k", "spec_ngram", "prompt_fanout")}
    # page_size rides through pre_kw (prefill allocates the pool and gates
    # the cache_extra slack) and ver_kw (table-routed verify writes)
    base = _prefill_jit(params, config, prompt_ids, prompt_mask, key,
                        prompt_fanout=prompt_fanout, cache_extra=spec_k,
                        **pre_kw)
    prompt_rep = (
        jnp.repeat(prompt_ids, prompt_fanout, axis=0)
        if prompt_fanout > 1 else prompt_ids
    )
    state = _spec_state(base)
    ver_kw = {k: v for k, v in kw.items()
              if k not in ("spec_ngram", "prompt_fanout")}
    max_tokens = kw["max_tokens"]
    for it in range(max_tokens):
        if bool(np.asarray(state[5]).all()):
            break
        with tracer.span("rollout.draft", track="rollout", iteration=it):
            drafts = _draft_jit(prompt_rep, state, Tp=Tp, spec_k=spec_k,
                                spec_ngram=spec_ngram,
                                pad_token_id=kw["pad_token_id"])
            jax.block_until_ready(drafts)
        with tracer.span("rollout.verify", track="rollout", iteration=it):
            state = _verify_jit(params, config, state, drafts, Tp=Tp,
                                **ver_kw)
            jax.block_until_ready(state[5])
    stats = (state[0] - 1, state[10], state[11], state[12], state[13],
             state[14])
    return state[1], state[2], stats


def generate_spec(
    params: dict,
    config: ModelConfig,
    prompt_ids: jnp.ndarray,
    prompt_mask: jnp.ndarray,
    key: jax.Array,
    *,
    max_tokens: int,
    eos_token_id: int,
    pad_token_id: int,
    spec_k: int,
    spec_ngram: int = 3,
    temperature: float = 1.0,
    top_p: float = 0.95,
    greedy: bool = False,
    lora_scale: float = 1.0,
    top_k: int = 64,
    capture_logprobs: bool = False,
    approx_top_k: bool = True,
    prompt_fanout: int = 1,
    spec_stats_out: list | None = None,
    tracer=None,
    page_size: int = 0,
):
    """`generate`-contract entry for the speculative path: returns tokens
    (or (tokens, logprobs) with capture), appending the stats dict to
    `spec_stats_out` when provided. Stats stay device scalars until the
    caller fetches them — reading after the tokens are ready costs no
    extra sync."""
    kw = dict(
        max_tokens=max_tokens, eos_token_id=eos_token_id,
        pad_token_id=pad_token_id, spec_k=spec_k, spec_ngram=spec_ngram,
        temperature=temperature, top_p=top_p, greedy=greedy,
        lora_scale=lora_scale, top_k=top_k,
        capture_logprobs=capture_logprobs, approx_top_k=approx_top_k,
        prompt_fanout=prompt_fanout, page_size=page_size,
    )
    if tracer is not None and getattr(tracer, "enabled", False):
        out, lp, stats = _generate_spec_instrumented(
            params, config, prompt_ids, prompt_mask, key, tracer, **kw
        )
    else:
        out, lp, stats = generate_tokens_spec(
            params, config, prompt_ids, prompt_mask, key, **kw
        )
    if spec_stats_out is not None:
        steps, drafted, accepted, emitted, row_steps, accepted_rows = stats
        spec_stats_out.append({
            "verify_steps": steps, "drafted": drafted,
            "accepted": accepted, "emitted": emitted,
            "row_steps": row_steps,
            # per-row accepted-draft counts [B]: the lineage ledger's
            # generation events attribute draft acceptance per sample
            "accepted_rows": accepted_rows,
        })
    return (out, lp) if capture_logprobs else out
