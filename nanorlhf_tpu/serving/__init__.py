"""Inference serving plane: cross-request radix prefix cache + gateway.

`radix.py` is the host-side radix tree over ref-counted KV pages (the
paged pool from sampler/paged/ with alloc/release generalized to
refcount inc/dec), `engine.py` the continuous-batching serving engine
over the same jitted decode machinery the rollout scheduler uses, and
`gateway.py` the stdlib-HTTP streaming token API in front of it.
docs/SERVING.md is the narrative."""

from nanorlhf_tpu.serving.radix import RadixCache, RefPagePool

__all__ = ["RadixCache", "RefPagePool", "ServingEngine", "ServingGateway"]


def __getattr__(name):  # engine/gateway pull in jax+model code — lazy
    if name == "ServingEngine":
        from nanorlhf_tpu.serving.engine import ServingEngine
        return ServingEngine
    if name == "ServingGateway":
        from nanorlhf_tpu.serving.gateway import ServingGateway
        return ServingGateway
    raise AttributeError(name)
