"""Serving engine: continuous-batching decode over the radix prefix cache.

The rollout scheduler (`sampler/paged/scheduler.py`) serves a CLOSED
queue — every prompt is known up front and the call returns when the
queue drains. This module reshapes the same machinery into an OPEN
server loop for interactive traffic:

  * A fixed-shape jitted decode chunk over `rows` resident rows, like
    the scheduler's `_decode_chunk`, but with PER-REQUEST sampling
    params carried as traced `[R]` arrays (`temperature`, `top_p`,
    `greedy`, token `budget`) instead of static scalars — one compiled
    program serves any mix of greedy and sampled requests.
  * Admission through one `RadixCache` kept alive for the engine's
    whole lifetime (params are fixed, so cached KV never goes stale):
    a request's matched prefix installs refcount-shared pages with zero
    prefill FLOPs and only the suffix runs through `suffix_logits`.
    Cold admissions take the same path with an empty match — the
    suffix forward starts at the first real token (`fill = pad_count`),
    so pad KV is never written (and never read: `key_mask` excludes
    pad slots).
  * SLO-aware shed-vs-admit: `submit()` rejects when the pending queue
    is full or when the LatencyHub's p95 TTFT is over the
    `slo_ttft_p95` rule's warn threshold (telemetry/health.py) — the
    same rule the health monitor pages on, so the gateway starts
    shedding exactly when the alert would fire.
  * Per-request TTFT (submit → first token ready, blocking on the
    admission forward) and per-chunk mean inter-token gaps stream into
    the attached LatencyHub under the PR 13 metric names.

Threading: one background loop thread owns the carry, the block table,
and all device dispatch. `submit()` only appends to the pending deque
under `make_condition("serving.engine")`; the one extracted lock edge is
serving.engine -> telemetry.hist (the shed check reads hub quantiles
under the condition). Radix plan/insert run OUTSIDE the condition, but
"serving.engine" is still ranked above "serving.radix" in LOCK_ORDER so
a future admission that does hold both stays deadlock-free by
construction.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from nanorlhf_tpu.analysis.lockorder import make_condition
from nanorlhf_tpu.core.model import decode_step, init_paged_kv_cache
from nanorlhf_tpu.ops.masking import guard_temperature
from nanorlhf_tpu.sampler.paged.pages import blocks_per_row
from nanorlhf_tpu.sampler.sampler import _nucleus_candidates
from nanorlhf_tpu.serving.radix import (
    RadixCache, bucket_len, copy_page, prompt_key, suffix_logits,
)
from nanorlhf_tpu.telemetry.health import SLO_RULES

# admission PRNG folds live far from the per-iteration decode stream,
# mirroring the scheduler's convention
_ADMIT_BASE = 10_000_000


def _serving_sample(key, logits, temperature, top_p, greedy, *, top_k,
                    approx_top_k):
    """Per-ROW sampling: `sampler._sample_token` with `temperature` /
    `top_p` / `greedy` as traced `[R]` arrays so one compiled decode
    step serves heterogeneous requests. Both branches are computed and
    selected with `jnp.where(greedy, ...)`; the nucleus keep rule
    broadcasts `top_p[:, None]` against the `[R, K]` candidate set.
    Unlike the rollout sampler there is no exact full-vocab escape for
    `top_p >= 1` — serving always samples in top-k candidate space
    (`top_p = 1` keeps every candidate), which is the usual serving
    trade and keeps the row-mixed program shape fixed."""
    scaled = (logits.astype(jnp.float32)
              / guard_temperature(temperature)[:, None])
    top_logits, top_idx, keep = _nucleus_candidates(
        scaled, top_p[:, None], top_k, approx_top_k)
    kept = jnp.where(keep, top_logits, -jnp.inf)
    choice = jax.random.categorical(key, kept, axis=-1)
    sampled = jnp.take_along_axis(
        top_idx, choice[..., None], axis=-1)[..., 0]
    return jnp.where(greedy, jnp.argmax(logits, axis=-1),
                     sampled).astype(jnp.int32)


@partial(jax.jit, static_argnames=("top_k", "approx_top_k"))
def _first_token(logits, key, temperature, top_p, greedy, *, top_k,
                 approx_top_k):
    """Sample one admission's first token from its suffix logits [V]."""
    return _serving_sample(key, logits[None, :], temperature[None],
                           top_p[None], greedy[None], top_k=top_k,
                           approx_top_k=approx_top_k)[0]


# carry slots: 0 it · 1 out · 2 caches · 3 key_mask · 4 done · 5 cur_tok
# · 6 n_gen · 7 prompt_len · 8 temperature · 9 top_p · 10 greedy ·
# 11 budget · 12 key
def _engine_decode_body(params, config, s, table, *, Tp, max_new,
                        page_size, eos_token_id, pad_token_id, lora_scale,
                        top_k, approx_top_k):
    (it, out, caches, key_mask, done, cur_tok, n_gen, plen, temp, topp,
     greedy, budget, key) = s
    R = cur_tok.shape[0]
    rows = jnp.arange(R)
    slot = Tp + n_gen - 1
    key_mask = key_mask.at[rows, slot].set(True)
    position = plen + n_gen - 1
    logits, caches = decode_step(
        params, config, cur_tok, position, slot, key_mask, caches,
        lora_scale=lora_scale, page_table=table, page_size=page_size,
    )
    tok = _serving_sample(jax.random.fold_in(key, it), logits, temp, topp,
                          greedy, top_k=top_k, approx_top_k=approx_top_k)
    tok = jnp.where(done, pad_token_id, tok)
    live = ~done
    wpos = jnp.where(live, n_gen, max_new)     # done rows drop their write
    out = out.at[rows, wpos].set(tok, mode="drop")
    cur_tok = jnp.where(live, tok, cur_tok)
    n_gen = n_gen + live.astype(jnp.int32)
    done = done | (tok == eos_token_id) | (n_gen >= budget)
    return (it + 1, out, caches, key_mask, done, cur_tok, n_gen, plen,
            temp, topp, greedy, budget, key)


_ENGINE_STATIC = ("config", "Tp", "max_new", "page_size", "sync_every",
                  "eos_token_id", "pad_token_id", "lora_scale", "top_k",
                  "approx_top_k")


@partial(jax.jit, static_argnames=_ENGINE_STATIC)
def _engine_chunk(params, config, state, table, **statics):
    """Up to `sync_every` decode iterations; exits once every row is
    done, so the iteration counter counts true decode dispatches."""
    sync_every = statics.pop("sync_every")

    def cond(cs):
        c, s = cs
        return (c < sync_every) & ~jnp.all(s[4])

    def body(cs):
        c, s = cs
        return c + 1, _engine_decode_body(params, config, s, table,
                                          **statics)

    _, state = jax.lax.while_loop(cond, body, (jnp.int32(0), state))
    return state


@partial(jax.jit, static_argnames=("Tp", "max_new", "eos_token_id",
                                   "pad_token_id"))
def _engine_install(state, caches, r, tok0, pmask_row, plen, temp, topp,
                    greedy, budget, *, Tp, max_new, eos_token_id,
                    pad_token_id):
    """Reset carry row `r` for a freshly admitted request (post-suffix-
    prefill values, per-request sampling params into the [R] arrays)."""
    s = list(state)
    T_mask = s[3].shape[1]
    s[2] = caches
    s[1] = s[1].at[r].set(
        jnp.full((max_new,), pad_token_id, jnp.int32).at[0].set(tok0))
    s[3] = s[3].at[r].set(
        jnp.zeros((T_mask,), bool).at[:Tp].set(pmask_row))
    s[4] = s[4].at[r].set((tok0 == eos_token_id) | (budget <= 1))
    s[5] = s[5].at[r].set(tok0)
    s[6] = s[6].at[r].set(jnp.int32(1))
    s[7] = s[7].at[r].set(plen)
    s[8] = s[8].at[r].set(temp)
    s[9] = s[9].at[r].set(topp)
    s[10] = s[10].at[r].set(greedy)
    s[11] = s[11].at[r].set(budget)
    return tuple(s)


@dataclass
class ServingRequest:
    """One in-flight request: the stream side reads `out_q` until the
    `None` sentinel (the emitted stream INCLUDES the EOS token when one
    fired)."""
    request_id: int
    tokens: np.ndarray            # real token ids, un-padded
    temperature: float
    top_p: float
    greedy: bool
    max_tokens: int
    t_submit: float
    out_q: "queue.Queue" = field(default_factory=queue.Queue)
    n_emitted: int = 0
    cancelled: bool = False       # set by cancel(); loop reaps the row


class ServingEngine:
    """Open-loop continuous batching over the radix prefix cache.

    `prompt_len` / `max_new_tokens` fix the compiled shapes (prompts are
    left-padded to `prompt_len`; longer prompts are rejected at submit).
    `slo_warn_ttft_s=None` reads the warn threshold, quantile, and
    warmup from the `slo_ttft_p95` rule in telemetry.health.SLO_RULES."""

    def __init__(self, params, config, *, eos_token_id, pad_token_id,
                 page_size=16, prompt_len=32, max_new_tokens=32, rows=2,
                 headroom=1.0, sync_every=4, max_queue=64, latency=None,
                 lora_scale=1.0, top_k=64, approx_top_k=True, seed=0,
                 slo_warn_ttft_s: Optional[float] = None):
        self.params = params
        self.config = config
        self.eos_token_id = int(eos_token_id)
        self.pad_token_id = int(pad_token_id)
        self.page_size = int(page_size)
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.rows = int(rows)
        self.sync_every = int(sync_every)
        self.max_queue = int(max_queue)
        self.lora_scale = float(lora_scale)
        self.top_k = int(top_k)
        self.approx_top_k = bool(approx_top_k)

        rule = next(r for r in SLO_RULES if r.name == "slo_ttft_p95")
        self._slo_metric = rule.metric
        self._slo_q = rule.quantile
        self._slo_warmup = rule.warmup
        self._slo_warn = (rule.warn if slo_warn_ttft_s is None
                          else float(slo_warn_ttft_s))

        self._hub = latency if (latency is not None
                                and latency.enabled) else None

        self.T_max = self.prompt_len + self.max_new_tokens
        self.nb = blocks_per_row(self.T_max, self.page_size)
        self._radix = RadixCache(headroom=headroom)
        self.num_pages = (self.rows * self.nb
                          + self._radix.extra_pages(self.rows, self.nb))
        self._radix.reset(num_pages=self.num_pages,
                          page_size=self.page_size)

        R, Tp, mx = self.rows, self.prompt_len, self.max_new_tokens
        caches0 = init_paged_kv_cache(
            config, self.num_pages, self.page_size,
            params["embed_tokens"].dtype)
        self._state = (jnp.int32(1),
                       jnp.full((R, mx), self.pad_token_id, jnp.int32),
                       caches0,
                       jnp.zeros((R, self.T_max), bool),
                       jnp.ones((R,), bool),
                       jnp.zeros((R,), jnp.int32),
                       jnp.ones((R,), jnp.int32),
                       jnp.zeros((R,), jnp.int32),
                       jnp.ones((R,), jnp.float32),
                       jnp.ones((R,), jnp.float32),
                       jnp.zeros((R,), bool),
                       jnp.ones((R,), jnp.int32),
                       jax.random.PRNGKey(seed))
        self._key = jax.random.PRNGKey(seed + 1)
        self._table = np.full((R, self.nb), self.num_pages, np.int32)
        self._owner: list = [None] * R           # row -> ServingRequest
        self._statics = dict(
            Tp=Tp, max_new=mx, page_size=self.page_size,
            sync_every=self.sync_every, eos_token_id=self.eos_token_id,
            pad_token_id=self.pad_token_id, lora_scale=self.lora_scale,
            top_k=self.top_k, approx_top_k=self.approx_top_k,
        )

        self._cond = make_condition("serving.engine")
        self._pending: deque = deque()
        self._n_active = 0
        self._running = True
        self._ids = itertools.count()
        self._counters = {"requests": 0, "admitted": 0, "shed": 0,
                          "completed": 0, "cancelled": 0}
        # per-cause shed counters (serving/shed_total{reason=...}):
        # pre-seeded so every reason exports a 0 row from the first
        # scrape — dashboards can alert on rate() without init gaps
        self._shed_reasons = {"queue_full": 0, "slo_ttft_p95": 0,
                              "closed": 0, "pool": 0, "disconnect": 0}
        self._dispatch_tokens = 0
        self._it_prev = 0
        self._thread = threading.Thread(target=self._loop,
                                        name="serving-engine", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- #
    # client side
    # ------------------------------------------------------------- #

    def submit(self, tokens, *, temperature=1.0, top_p=1.0, greedy=False,
               max_tokens=None):
        """Admission-controlled enqueue. Returns `(request, None)` or
        `(None, shed_reason)` — `"queue_full"` when the pending bound is
        hit, `"slo_ttft_p95"` when the hub's p95 TTFT is over the SLO
        warn threshold (past its warmup count)."""
        toks = np.asarray(tokens, np.int32).ravel()
        if toks.size < 1 or toks.size > self.prompt_len:
            raise ValueError(
                f"prompt length {toks.size} outside [1, {self.prompt_len}]"
                " — the engine's compiled prompt shape is fixed")
        mx = self.max_new_tokens if max_tokens is None else int(max_tokens)
        mx = max(1, min(mx, self.max_new_tokens))
        with self._cond:
            self._counters["requests"] += 1
            reason = self._shed_reason_locked()
            if reason is not None:
                self._counters["shed"] += 1
                self._shed_reasons[reason] = (
                    self._shed_reasons.get(reason, 0) + 1)
                return None, reason
            req = ServingRequest(
                request_id=next(self._ids), tokens=toks,
                temperature=float(temperature), top_p=float(top_p),
                greedy=bool(greedy), max_tokens=mx,
                t_submit=time.perf_counter())
            self._pending.append(req)
            self._cond.notify_all()
        return req, None

    def _shed_reason_locked(self) -> Optional[str]:
        if not self._running:
            return "closed"
        if len(self._pending) >= self.max_queue:
            return "queue_full"
        if (self._hub is not None
                and self._hub.count(self._slo_metric) >= self._slo_warmup
                and self._hub.quantile(self._slo_metric,
                                       self._slo_q) > self._slo_warn):
            return "slo_ttft_p95"
        return None

    def cancel(self, req: ServingRequest) -> None:
        """The client vanished mid-stream (`gw.disconnect`): stop decoding
        for this request and free its resources — a dead socket must not
        keep a row decoding or pin its KV pages. Still-pending requests
        are shed immediately (reason "disconnect"); an admitted row is
        reaped by the loop thread — which owns the block table and radix
        refcounts — on its next iteration, counting into `cancelled`
        (admitted == completed + cancelled at quiescence). Idempotent."""
        was_pending = False
        with self._cond:
            if req.cancelled:
                return
            req.cancelled = True
            try:
                self._pending.remove(req)
                was_pending = True
            except ValueError:
                pass
            if was_pending:
                self._counters["shed"] += 1
                self._shed_reasons["disconnect"] = (
                    self._shed_reasons.get("disconnect", 0) + 1)
            self._cond.notify_all()
        if was_pending:
            req.out_q.put(None)

    def stream(self, req: ServingRequest, timeout: float = 120.0):
        """Yield the request's tokens as they land; ends at the `None`
        sentinel (or on `timeout` seconds of silence)."""
        while True:
            try:
                tok = req.out_q.get(timeout=timeout)
            except queue.Empty:
                return
            if tok is None:
                return
            yield tok

    # ------------------------------------------------------------- #
    # engine loop (single background thread owns all device state)
    # ------------------------------------------------------------- #

    def _loop(self):
        while True:
            with self._cond:
                while (self._running and not self._pending
                       and self._n_active == 0):
                    self._cond.wait(0.05)
                if (not self._running and self._n_active == 0
                        and not self._pending):
                    break
                admits = []
                free_rows = [r for r in range(self.rows)
                             if self._owner[r] is None]
                while free_rows and self._pending:
                    admits.append((free_rows.pop(0),
                                   self._pending.popleft()))
                self._n_active += len(admits)
            for r, req in admits:
                self._admit(r, req)
            self._reap_cancelled()
            if all(o is None for o in self._owner):
                continue
            t0 = time.perf_counter()
            self._state = _engine_chunk(
                self.params, self.config, self._state,
                jnp.asarray(self._table), **self._statics)
            self._deliver(t0)

    def _admit(self, r: int, req: ServingRequest):
        Tp, P = self.prompt_len, self.page_size
        n = int(req.tokens.size)
        pad_count = Tp - n
        toks_p = np.full(Tp, self.pad_token_id, np.int32)
        toks_p[pad_count:] = req.tokens
        mask = np.zeros(Tp, bool)
        mask[pad_count:] = True
        kelems = prompt_key(toks_p, mask)
        try:
            plan = self._radix.plan(kelems, pad_count=pad_count,
                                    n_blocks=self.nb, prompt_len=Tp)
        except RuntimeError:
            # pool sizing makes this unreachable (rows*nb live refs max,
            # the rest evictable) — shed rather than crash if it fires
            with self._cond:
                self._counters["shed"] += 1
                self._shed_reasons["pool"] = (
                    self._shed_reasons.get("pool", 0) + 1)
                self._n_active -= 1
            req.out_q.put(None)
            return
        self._table[r] = plan.row_pages
        caches = self._state[2]
        if plan.cow_src is not None:
            caches = copy_page(caches, plan.cow_src, plan.cow_dst)
        # unified suffix forward: a cold admission is just an empty match
        # — fill starts at the first REAL token, so pad KV never exists
        start = plan.m if plan.m > 0 else pad_count
        s_real = Tp - start
        Sb = bucket_len(s_real, self.T_max - start)
        suffix = np.zeros((1, Sb), np.int32)
        suffix[0, :s_real] = toks_p[start:]
        pos = (start - pad_count) + np.arange(Sb, dtype=np.int32)[None]
        km = np.zeros((1, self.T_max), bool)
        km[0, pad_count:start] = True
        logits, caches = suffix_logits(
            self.params, self.config, jnp.asarray(suffix),
            jnp.asarray(pos), jnp.asarray([start], jnp.int32),
            jnp.int32(s_real - 1), jnp.asarray(km), caches,
            jnp.asarray(plan.row_pages), page_size=P,
            lora_scale=self.lora_scale)
        self._dispatch_tokens += Sb
        tok0 = _first_token(
            logits,
            jax.random.fold_in(self._key, _ADMIT_BASE + req.request_id),
            jnp.float32(req.temperature), jnp.float32(req.top_p),
            jnp.asarray(req.greedy), top_k=self.top_k,
            approx_top_k=self.approx_top_k)
        self._state = _engine_install(
            self._state, caches, r, tok0, jnp.asarray(mask),
            jnp.int32(n), jnp.float32(req.temperature),
            jnp.float32(req.top_p), jnp.asarray(req.greedy),
            jnp.int32(req.max_tokens), Tp=Tp, max_new=self.max_new_tokens,
            eos_token_id=self.eos_token_id,
            pad_token_id=self.pad_token_id)
        self._radix.insert(kelems, plan.row_pages, Tp)
        self._owner[r] = req
        jax.block_until_ready(tok0)
        if self._hub is not None:
            self._hub.record("latency/ttft_s",
                             time.perf_counter() - req.t_submit)
        with self._cond:
            self._counters["admitted"] += 1
        req.out_q.put(int(tok0))
        req.n_emitted = 1

    def _reap_cancelled(self):
        """Loop-thread only: free rows whose owner was cancelled. Forcing
        the done flag makes the jitted chunk skip the row; the page
        release mirrors _deliver's completion path exactly, so a
        disconnect can never leak what a completion would have freed."""
        for r in range(self.rows):
            req = self._owner[r]
            if req is None or not req.cancelled:
                continue
            self._radix.release(self._table[r])
            self._table[r] = self.num_pages
            self._owner[r] = None
            s = list(self._state)
            s[4] = s[4].at[r].set(True)
            self._state = tuple(s)
            req.out_q.put(None)
            with self._cond:
                self._counters["cancelled"] += 1
                self._n_active -= 1
                self._cond.notify_all()

    def _deliver(self, t_chunk0: float):
        state = self._state
        done_h = np.asarray(state[4])
        out_h = np.asarray(state[1])
        n_gen_h = np.asarray(state[6])
        it_now = int(state[0]) - 1
        if self._hub is not None and it_now > self._it_prev:
            self._hub.record("latency/intertoken_s",
                             (time.perf_counter() - t_chunk0)
                             / (it_now - self._it_prev))
        self._it_prev = it_now
        for r in range(self.rows):
            req = self._owner[r]
            if req is None:
                continue
            n = int(n_gen_h[r])
            for tok in out_h[r, req.n_emitted:n]:
                req.out_q.put(int(tok))
            req.n_emitted = n
            if done_h[r]:
                req.out_q.put(None)
                self._radix.release(self._table[r])
                self._table[r] = self.num_pages
                self._owner[r] = None
                with self._cond:
                    self._counters["completed"] += 1
                    self._n_active -= 1
                    self._cond.notify_all()

    # ------------------------------------------------------------- #
    # observability
    # ------------------------------------------------------------- #

    def queue_depth(self) -> int:
        """Live pending-queue length — the autoscaler's leading-indicator
        input (loadgen/autoscaler.py `queue_high`): the queue fills
        before p95 TTFT degrades enough to flip an SLO rule."""
        with self._cond:
            return len(self._pending)

    def metrics(self) -> dict:
        """Flat scalar row for /metrics — the serving/* registry keys
        (METRICS.md) plus the pool's live shared-page gauge."""
        with self._cond:
            c = dict(self._counters)
            reasons = dict(self._shed_reasons)
            pending = len(self._pending)
            active = self._n_active
        snap = self._radix.snapshot()
        rows = {
            "serving/requests": c["requests"],
            "serving/admitted": c["admitted"],
            "serving/shed": c["shed"],
            "serving/completed": c["completed"],
            "serving/cancelled": c["cancelled"],
            "serving/pending": pending,
            "serving/active": active,
            "serving/prefix_hit_tokens": snap["hit_tokens"],
            "serving/prefix_hit_frac": snap["hit_frac"],
            "serving/cow_splits": snap["cow_splits"],
            "serving/evicted_pages": snap["evicted_pages"],
            "serving/prefill_token_dispatch": self._dispatch_tokens,
            "pages/shared": snap["shared_pages"],
        }
        for reason, n in sorted(reasons.items()):
            rows[f'serving/shed_total{{reason="{reason}"}}'] = n
        return rows

    def snapshot(self) -> dict:
        """JSON-able /statusz section: engine shape + live occupancy +
        the radix tree's own snapshot under `prefix_cache`."""
        with self._cond:
            c = dict(self._counters)
            reasons = dict(self._shed_reasons)
            pending = len(self._pending)
            active = self._n_active
        return {
            "rows": self.rows,
            "active": active,
            "pending": pending,
            "prompt_len": self.prompt_len,
            "max_new_tokens": self.max_new_tokens,
            "page_size": self.page_size,
            "num_pages": self.num_pages,
            "counters": c,
            "shed_reasons": reasons,
            "prefill_token_dispatch": self._dispatch_tokens,
            "slo": {"rule": "slo_ttft_p95", "warn_s": self._slo_warn,
                    "quantile": self._slo_q, "warmup": self._slo_warmup},
            "prefix_cache": self._radix.snapshot(),
        }

    @property
    def radix(self) -> RadixCache:
        return self._radix

    def close(self, timeout: float = 30.0) -> None:
        """Stop admitting, drain active rows, shed the pending queue
        (each pending request's stream ends at the sentinel), join the
        loop thread. Idempotent."""
        with self._cond:
            if not self._running and self._thread is None:
                return
            self._running = False
            pending = list(self._pending)
            self._pending.clear()
            self._counters["shed"] += len(pending)
            self._shed_reasons["closed"] = (
                self._shed_reasons.get("closed", 0) + len(pending))
            self._cond.notify_all()
        for req in pending:
            req.out_q.put(None)
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
