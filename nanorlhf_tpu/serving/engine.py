"""Serving engine: continuous-batching decode over the radix prefix cache.

The rollout scheduler (`sampler/paged/scheduler.py`) serves a CLOSED
queue — every prompt is known up front and the call returns when the
queue drains. This module reshapes the same machinery into an OPEN
server loop for interactive traffic. Since the decode-session refactor
the engine owns NO decode loop of its own: it constructs a
`sampler.paged.session.DecodeSession` in per-row mode and every request
flows through the same jitted chunk body, admission path, and release
path the rollout scheduler drives — one scheduler code path for gateway
streams and rollout (test-pinned bit-identical to the pre-session
engine). What remains here is open-loop POLICY:

  * SLO-aware shed-vs-admit: `submit()` rejects when the pending queue
    is full or when the LatencyHub's p95 TTFT is over the
    `slo_ttft_p95` rule's warn threshold (telemetry/health.py) — the
    same rule the health monitor pages on, so the gateway starts
    shedding exactly when the alert would fire.
  * Request lifecycle: per-request sampling params ride the session's
    traced [R] arrays (one compiled decode program serves any mix of
    greedy and sampled requests), tokens stream out through per-request
    queues, cancelled rows are reaped with their pages freed.
  * Composition inherited from the session: `prefill_chunk > 0` chunks
    long cold admissions so resident streams keep their inter-token
    cadence while a long prompt prefills; `spec_k > 0` runs draft+verify
    chunks (greedy requests with the full token budget only — the
    accept rule compiles against static sampling params; see
    `sampler.compose_check`).

Admission through one `RadixCache` kept alive for the engine's whole
lifetime (params are fixed, so cached KV never goes stale): a request's
matched prefix installs refcount-shared pages with zero prefill FLOPs
and only the suffix runs through `suffix_logits`. Cold admissions take
the same path with an empty match — the suffix forward starts at the
first real token, so pad KV is never written (and never read).

Threading: one background loop thread owns the session (carry, block
table, all device dispatch). `submit()` only appends to the pending
deque under `make_condition("serving.engine")`; the one extracted lock
edge is serving.engine -> telemetry.hist (the shed check reads hub
quantiles under the condition). Radix plan/insert run OUTSIDE the
condition, but "serving.engine" is still ranked above "serving.radix"
in LOCK_ORDER so a future admission that does hold both stays
deadlock-free by construction.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import jax
import numpy as np

from nanorlhf_tpu.analysis.lockorder import make_condition
from nanorlhf_tpu.sampler.paged.session import DecodeSession
from nanorlhf_tpu.serving.radix import RadixCache, prompt_key
from nanorlhf_tpu.telemetry.health import SLO_RULES


@dataclass
class ServingRequest:
    """One in-flight request: the stream side reads `out_q` until the
    `None` sentinel (the emitted stream INCLUDES the EOS token when one
    fired)."""
    request_id: int
    tokens: np.ndarray            # real token ids, un-padded
    temperature: float
    top_p: float
    greedy: bool
    max_tokens: int
    t_submit: float
    out_q: "queue.Queue" = field(default_factory=queue.Queue)
    n_emitted: int = 0
    cancelled: bool = False       # set by cancel(); loop reaps the row
    kelems: Optional[tuple] = None


class ServingEngine:
    """Open-loop continuous batching over the radix prefix cache.

    `prompt_len` / `max_new_tokens` fix the compiled shapes (prompts are
    left-padded to `prompt_len`; longer prompts are rejected at submit).
    `slo_warn_ttft_s=None` reads the warn threshold, quantile, and
    warmup from the `slo_ttft_p95` rule in telemetry.health.SLO_RULES.
    `prefill_chunk > 0` splits long cold admissions into that many
    prompt tokens per decode chunk; `spec_k > 0` turns on n-gram
    speculative decode (greedy, full-budget requests only)."""

    def __init__(self, params, config, *, eos_token_id, pad_token_id,
                 page_size=16, prompt_len=32, max_new_tokens=32, rows=2,
                 headroom=1.0, sync_every=4, max_queue=64, latency=None,
                 lora_scale=1.0, top_k=64, approx_top_k=True, seed=0,
                 slo_warn_ttft_s: Optional[float] = None,
                 prefill_chunk=0, spec_k=0, spec_ngram=3):
        self.params = params
        self.config = config
        self.eos_token_id = int(eos_token_id)
        self.pad_token_id = int(pad_token_id)
        self.page_size = int(page_size)
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.rows = int(rows)
        self.sync_every = int(sync_every)
        self.max_queue = int(max_queue)
        self.prefill_chunk = int(prefill_chunk)
        self.spec_k = int(spec_k)

        rule = next(r for r in SLO_RULES if r.name == "slo_ttft_p95")
        self._slo_metric = rule.metric
        self._slo_q = rule.quantile
        self._slo_warmup = rule.warmup
        self._slo_warn = (rule.warn if slo_warn_ttft_s is None
                          else float(slo_warn_ttft_s))

        self._hub = latency if (latency is not None
                                and latency.enabled) else None

        self._radix = RadixCache(headroom=headroom)
        # the session sizes the pool (rows * nb + radix headroom), resets
        # the tree ONCE here, and keeps it warm for the engine's lifetime
        self._sess = DecodeSession(
            params, config, rows=self.rows, prompt_len=self.prompt_len,
            max_tokens=self.max_new_tokens, page_size=self.page_size,
            eos_token_id=self.eos_token_id, pad_token_id=self.pad_token_id,
            key=jax.random.PRNGKey(seed),
            admit_key=jax.random.PRNGKey(seed + 1),
            greedy=(self.spec_k > 0), top_k=int(top_k),
            approx_top_k=bool(approx_top_k), lora_scale=float(lora_scale),
            per_row=True, spec_k=self.spec_k, spec_ngram=int(spec_ngram),
            prefix_cache=self._radix, prefill_chunk=self.prefill_chunk,
            sync_every=self.sync_every, latency=self._hub)
        self.T_max = self._sess.T_max
        self.nb = self._sess.nb
        self.num_pages = self._sess.num_pages

        self._owner: list = [None] * self.rows   # row -> ServingRequest

        self._cond = make_condition("serving.engine")
        self._pending: deque = deque()
        self._n_active = 0
        self._running = True
        self._ids = itertools.count()
        self._counters = {"requests": 0, "admitted": 0, "shed": 0,
                          "completed": 0, "cancelled": 0}
        # per-cause shed counters (serving/shed_total{reason=...}):
        # pre-seeded so every reason exports a 0 row from the first
        # scrape — dashboards can alert on rate() without init gaps
        self._shed_reasons = {"queue_full": 0, "slo_ttft_p95": 0,
                              "closed": 0, "pool": 0, "disconnect": 0}
        self._thread = threading.Thread(target=self._loop,
                                        name="serving-engine", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- #
    # client side
    # ------------------------------------------------------------- #

    def submit(self, tokens, *, temperature=1.0, top_p=1.0, greedy=False,
               max_tokens=None):
        """Admission-controlled enqueue. Returns `(request, None)` or
        `(None, shed_reason)` — `"queue_full"` when the pending bound is
        hit, `"slo_ttft_p95"` when the hub's p95 TTFT is over the SLO
        warn threshold (past its warmup count)."""
        toks = np.asarray(tokens, np.int32).ravel()
        if toks.size < 1 or toks.size > self.prompt_len:
            raise ValueError(
                f"prompt length {toks.size} outside [1, {self.prompt_len}]"
                " — the engine's compiled prompt shape is fixed")
        mx = self.max_new_tokens if max_tokens is None else int(max_tokens)
        mx = max(1, min(mx, self.max_new_tokens))
        if self.spec_k > 0 and (not greedy or mx != self.max_new_tokens):
            raise ValueError(
                "a spec-decode engine (spec_k > 0) serves greedy requests "
                "with the full token budget only: the verify/accept rule "
                "compiles against static sampling params — see "
                "sampler.compose_check")
        with self._cond:
            self._counters["requests"] += 1
            reason = self._shed_reason_locked()
            if reason is not None:
                self._counters["shed"] += 1
                self._shed_reasons[reason] = (
                    self._shed_reasons.get(reason, 0) + 1)
                return None, reason
            req = ServingRequest(
                request_id=next(self._ids), tokens=toks,
                temperature=float(temperature), top_p=float(top_p),
                greedy=bool(greedy), max_tokens=mx,
                t_submit=time.perf_counter())
            self._pending.append(req)
            self._cond.notify_all()
        return req, None

    def _shed_reason_locked(self) -> Optional[str]:
        if not self._running:
            return "closed"
        if len(self._pending) >= self.max_queue:
            return "queue_full"
        if (self._hub is not None
                and self._hub.count(self._slo_metric) >= self._slo_warmup
                and self._hub.quantile(self._slo_metric,
                                       self._slo_q) > self._slo_warn):
            return "slo_ttft_p95"
        return None

    def cancel(self, req: ServingRequest) -> None:
        """The client vanished mid-stream (`gw.disconnect`): stop decoding
        for this request and free its resources — a dead socket must not
        keep a row decoding or pin its KV pages. Still-pending requests
        are shed immediately (reason "disconnect"); an admitted row is
        reaped by the loop thread — which owns the session — on its next
        iteration, counting into `cancelled` (admitted == completed +
        cancelled at quiescence). Idempotent."""
        was_pending = False
        with self._cond:
            if req.cancelled:
                return
            req.cancelled = True
            try:
                self._pending.remove(req)
                was_pending = True
            except ValueError:
                pass
            if was_pending:
                self._counters["shed"] += 1
                self._shed_reasons["disconnect"] = (
                    self._shed_reasons.get("disconnect", 0) + 1)
            self._cond.notify_all()
        if was_pending:
            req.out_q.put(None)

    def stream(self, req: ServingRequest, timeout: float = 120.0):
        """Yield the request's tokens as they land; ends at the `None`
        sentinel (or on `timeout` seconds of silence)."""
        while True:
            try:
                tok = req.out_q.get(timeout=timeout)
            except queue.Empty:
                return
            if tok is None:
                return
            yield tok

    # ------------------------------------------------------------- #
    # engine loop (single background thread owns the session)
    # ------------------------------------------------------------- #

    def _loop(self):
        while True:
            with self._cond:
                while (self._running and not self._pending
                       and self._n_active == 0):
                    self._cond.wait(0.05)
                if (not self._running and self._n_active == 0
                        and not self._pending):
                    break
                admits = []
                free_rows = [r for r in range(self.rows)
                             if self._owner[r] is None]
                while free_rows and self._pending:
                    admits.append((free_rows.pop(0),
                                   self._pending.popleft()))
                self._n_active += len(admits)
            for r, req in admits:
                self._admit(r, req)
            self._reap_cancelled()
            if all(o is None for o in self._owner):
                continue
            self._sess.step()
            self._deliver()

    def _admit(self, r: int, req: ServingRequest):
        Tp = self.prompt_len
        n = int(req.tokens.size)
        pad_count = Tp - n
        toks_p = np.full(Tp, self.pad_token_id, np.int32)
        toks_p[pad_count:] = req.tokens
        mask = np.zeros(Tp, bool)
        mask[pad_count:] = True
        req.kelems = prompt_key(toks_p, mask)
        try:
            tok0 = self._sess.admit(
                r, toks_p, mask, req.request_id, budget=req.max_tokens,
                temperature=req.temperature, top_p=req.top_p,
                greedy=req.greedy, t_start=req.t_submit)
        except RuntimeError:
            # pool sizing makes this unreachable (rows*nb live refs max,
            # the rest evictable) — shed rather than crash if it fires
            with self._cond:
                self._counters["shed"] += 1
                self._shed_reasons["pool"] = (
                    self._shed_reasons.get("pool", 0) + 1)
                self._n_active -= 1
            req.out_q.put(None)
            return
        self._owner[r] = req
        with self._cond:
            self._counters["admitted"] += 1
        if tok0 is None:
            # chunked admission: the first token lands when the final
            # chunk installs the row; _deliver streams it from the carry
            return
        req.out_q.put(int(tok0))
        req.n_emitted = 1

    def _reap_cancelled(self):
        """Loop-thread only: free rows whose owner was cancelled. The
        session forces the done flag (the jitted chunk then skips the
        row) and releases pages exactly as a completion would, so a
        disconnect can never leak what a completion would have freed."""
        for r in range(self.rows):
            req = self._owner[r]
            if req is None or not req.cancelled:
                continue
            self._sess.cancel_row(r)
            self._owner[r] = None
            req.out_q.put(None)
            with self._cond:
                self._counters["cancelled"] += 1
                self._n_active -= 1
                self._cond.notify_all()

    def _deliver(self):
        state = self._sess.state
        done_h = np.asarray(state[5])
        out_h = np.asarray(state[1])
        n_gen_h = np.asarray(state[7])
        pending = self._sess.pending_rows()
        for r in range(self.rows):
            req = self._owner[r]
            if req is None or r in pending:
                continue
            n = int(n_gen_h[r])
            for tok in out_h[r, req.n_emitted:n]:
                req.out_q.put(int(tok))
            req.n_emitted = n
            if done_h[r]:
                req.out_q.put(None)
                self._sess.release(
                    r, gen_tokens=(out_h[r, :n] if self.spec_k > 0
                                   else None))
                self._owner[r] = None
                with self._cond:
                    self._counters["completed"] += 1
                    self._n_active -= 1
                    self._cond.notify_all()

    # ------------------------------------------------------------- #
    # observability
    # ------------------------------------------------------------- #

    def queue_depth(self) -> int:
        """Live pending-queue length — the autoscaler's leading-indicator
        input (loadgen/autoscaler.py `queue_high`): the queue fills
        before p95 TTFT degrades enough to flip an SLO rule."""
        with self._cond:
            return len(self._pending)

    def metrics(self) -> dict:
        """Flat scalar row for /metrics — the serving/* registry keys
        (METRICS.md) plus the pool's live shared-page gauge."""
        with self._cond:
            c = dict(self._counters)
            reasons = dict(self._shed_reasons)
            pending = len(self._pending)
            active = self._n_active
        snap = self._radix.snapshot()
        rows = {
            "serving/requests": c["requests"],
            "serving/admitted": c["admitted"],
            "serving/shed": c["shed"],
            "serving/completed": c["completed"],
            "serving/cancelled": c["cancelled"],
            "serving/pending": pending,
            "serving/active": active,
            "serving/prefix_hit_tokens": snap["hit_tokens"],
            "serving/prefix_hit_frac": snap["hit_frac"],
            "serving/cow_splits": snap["cow_splits"],
            "serving/evicted_pages": snap["evicted_pages"],
            "serving/prefill_token_dispatch": self._sess.dispatch_tokens,
            "pages/shared": snap["shared_pages"],
        }
        for reason, n in sorted(reasons.items()):
            rows[f'serving/shed_total{{reason="{reason}"}}'] = n
        return rows

    def snapshot(self) -> dict:
        """JSON-able /statusz section: engine shape + live occupancy +
        the radix tree's own snapshot under `prefix_cache` + the decode
        session's row/backlog/feature view under `session`."""
        with self._cond:
            c = dict(self._counters)
            reasons = dict(self._shed_reasons)
            pending = len(self._pending)
            active = self._n_active
        return {
            "rows": self.rows,
            "active": active,
            "pending": pending,
            "prompt_len": self.prompt_len,
            "max_new_tokens": self.max_new_tokens,
            "page_size": self.page_size,
            "num_pages": self.num_pages,
            "counters": c,
            "shed_reasons": reasons,
            "prefill_token_dispatch": self._sess.dispatch_tokens,
            "slo": {"rule": "slo_ttft_p95", "warn_s": self._slo_warn,
                    "quantile": self._slo_q, "warmup": self._slo_warmup},
            "prefix_cache": self._radix.snapshot(),
            "session": self._sess.status(),
        }

    @property
    def radix(self) -> RadixCache:
        return self._radix

    @property
    def session(self) -> DecodeSession:
        return self._sess

    def close(self, timeout: float = 30.0) -> None:
        """Stop admitting, drain active rows, shed the pending queue
        (each pending request's stream ends at the sentinel), join the
        loop thread. Idempotent."""
        with self._cond:
            if not self._running and self._thread is None:
                return
            self._running = False
            pending = list(self._pending)
            self._pending.clear()
            self._counters["shed"] += len(pending)
            self._shed_reasons["closed"] = (
                self._shed_reasons.get("closed", 0) + len(pending))
            self._cond.notify_all()
        for req in pending:
            req.out_q.put(None)
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
