"""Serving gateway: streaming token HTTP API over the ServingEngine.

Same stdlib-HTTP discipline as `telemetry/exporter.py` — a
`ThreadingHTTPServer` on a daemon thread, non-streaming responses built
fully then written once with a Content-Length, per-request stderr
silenced — plus one streaming endpoint:

- `POST /generate`  body `{"tokens": [int, ...]}` with optional
  `temperature` / `top_p` / `greedy` / `max_tokens` / `stream`.
  Non-streaming: one JSON object `{"request_id", "tokens"}` once the
  request finishes. `"stream": true`: chunked `application/x-ndjson`,
  one `{"token": t}` line as each token lands, then a final
  `{"done": true, "n": count}` line. Admission control answers 429
  with the shed reason (`queue_full` / `slo_ttft_p95`) and a
  `Retry-After` hint instead of queueing unboundedly.
- `GET /metrics`    Prometheus text: the engine's serving/* gauges
  plus the LatencyHub histogram families when the engine has one.
- `GET /healthz`    200 `ok` while the engine loop runs, 503 after
  close — the k8s-style liveness shape.
- `GET /statusz`    one JSON blob: engine occupancy, counters, SLO
  config, and the radix prefix cache's snapshot.

The gateway binds LOOPBACK ONLY (`127.0.0.1`): the fleet transport's
listener auth (ROADMAP item 2) has not landed, so exposing the port
beyond the host would ship an unauthenticated text API — docs/FLEET.md
records the same rule for the RPC listener. Port semantics follow the
exporter: 0 → disabled no-op, -1 → ephemeral (tests), >0 → that port.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from nanorlhf_tpu.resilience.faults import InjectedFault
from nanorlhf_tpu.telemetry.exporter import (
    render_prometheus, render_prometheus_histograms,
)

_LOOPBACK = ("127.0.0.1", "localhost", "::1")

# Retry-After seconds by shed cause: queue_full clears as soon as a row
# frees (~one decode round), an SLO breach needs the p95 window to move,
# and a closed engine is not coming back on this port soon. Advisory for
# well-behaved closed-loop clients — the open-loop loadgen driver
# records the header but never obeys it.
_RETRY_AFTER = {"queue_full": 1, "slo_ttft_p95": 5, "closed": 30}


class ServingGateway:
    """HTTP front for one ServingEngine. `close()` stops the listener
    only — the engine has its own lifecycle (the caller that built it
    closes it).

    `faults` arms the `gw.disconnect` site (docs/RESILIENCE.md): a fire
    mid-stream simulates the client's socket vanishing, driving the same
    `engine.cancel()` path a real write failure takes — the row's KV
    pages are released and in-flight counters decremented either way."""

    def __init__(self, engine, port: int = -1, host: str = "127.0.0.1",
                 faults=None):
        if host not in _LOOPBACK:
            raise ValueError(
                f"gateway binds loopback only until listener auth lands "
                f"(ROADMAP item 2, docs/FLEET.md); got host {host!r}")
        self.engine = engine
        self._faults = faults
        self.enabled = bool(port)
        self.host = host
        self.port = 0
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        if not self.enabled:
            return
        gw = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            # ---- reads: exporter-style full-body single writes ------ #

            def do_GET(self):  # noqa: N802 (stdlib handler API)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        status, ctype, body = gw._metrics()
                    elif path == "/healthz":
                        status, ctype, body = gw._healthz()
                    elif path in ("/statusz", "/"):
                        status, ctype, body = gw._statusz()
                    else:
                        status, ctype, body = 404, "text/plain", b"not found\n"
                except Exception as e:  # a scrape must never kill itself
                    status, ctype = 500, "text/plain"
                    body = f"{type(e).__name__}: {e}\n".encode()
                self._write(status, ctype, body)

            def do_POST(self):  # noqa: N802
                path = self.path.split("?", 1)[0]
                if path != "/generate":
                    self._write(404, "text/plain", b"not found\n")
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    spec = json.loads(self.rfile.read(n) or b"{}")
                    self._generate(spec)
                except (ValueError, KeyError, TypeError) as e:
                    self._write(400, "application/json",
                                json.dumps({"error": str(e)}).encode())

            def _generate(self, spec: dict):
                tokens = spec.get("tokens")
                if (not isinstance(tokens, list) or not tokens
                        or not all(isinstance(t, int) for t in tokens)):
                    raise ValueError("'tokens' must be a non-empty "
                                     "list of ints")
                req, reason = gw.engine.submit(
                    tokens,
                    temperature=float(spec.get("temperature", 1.0)),
                    top_p=float(spec.get("top_p", 1.0)),
                    greedy=bool(spec.get("greedy", False)),
                    max_tokens=spec.get("max_tokens"),
                )
                if req is None:
                    self._write(
                        429, "application/json",
                        json.dumps({"error": "shed",
                                    "reason": reason}).encode(),
                        headers={"Retry-After":
                                 str(_RETRY_AFTER.get(reason, 5))})
                    return
                if spec.get("stream"):
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson; charset=utf-8")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    count = 0
                    try:
                        for tok in gw.engine.stream(req):
                            if gw._disconnect_fires():
                                raise ConnectionResetError(
                                    "injected client disconnect")
                            self._chunk(json.dumps({"token": tok}) + "\n")
                            count += 1
                        self._chunk(json.dumps({"done": True, "n": count})
                                    + "\n")
                        self.wfile.write(b"0\r\n\r\n")
                    except OSError:
                        # the client vanished mid-stream (gw.disconnect, or
                        # a real broken pipe): stop decoding and free the
                        # row — a dead socket must not pin KV pages or
                        # in-flight counters
                        gw.engine.cancel(req)
                        self.close_connection = True
                    return
                toks = list(gw.engine.stream(req))
                self._write(200, "application/json", json.dumps(
                    {"request_id": req.request_id, "tokens": toks}).encode())

            # ---- plumbing ------------------------------------------ #

            def _write(self, status, ctype, body: bytes, headers=None):
                self.send_response(status)
                self.send_header("Content-Type", f"{ctype}; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                for name, value in (headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def _chunk(self, text: str):
                data = text.encode()
                self.wfile.write(f"{len(data):x}\r\n".encode())
                self.wfile.write(data + b"\r\n")
                self.wfile.flush()

            def log_message(self, fmt, *args):  # silence per-request stderr
                pass

        bind_port = port if port > 0 else 0  # -1 → ephemeral
        self._server = ThreadingHTTPServer((host, bind_port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="serving-gateway",
            daemon=True,
        )
        self._thread.start()

    # ----------------------------------------------------------------- #
    # endpoint bodies (HTTP threads; engine accessors are thread-safe)
    # ----------------------------------------------------------------- #

    def _disconnect_fires(self) -> bool:
        """True when the gw.disconnect site fires (any action — a raising
        schedule is the same vanished client as a returning one here)."""
        if self._faults is None:
            return False
        try:
            return self._faults.fire("gw.disconnect") is not None
        except InjectedFault:
            return True

    def _metrics(self) -> tuple:
        text = render_prometheus(self.engine.metrics())
        hub = getattr(self.engine, "_hub", None)
        if hub is not None and hub.enabled:
            text += render_prometheus_histograms(hub.states())
        return 200, "text/plain", text.encode()

    def _healthz(self) -> tuple:
        running = getattr(self.engine, "_running", False)
        return (200 if running else 503, "text/plain",
                b"ok\n" if running else b"closed\n")

    def _statusz(self) -> tuple:
        body = json.dumps(self.engine.snapshot(), default=str).encode()
        return 200, "application/json", body

    # ----------------------------------------------------------------- #

    def close(self) -> None:
        """Stop the listener and release the port. Idempotent; safe on
        the disabled no-op. Does NOT close the engine."""
        if self._closed or self._server is None:
            self._closed = True
            return
        self._closed = True
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
