"""Cross-request radix prefix cache over the ref-counted paged KV pool.

`rollout_shared_prefill` (sampler.py) shares prompt KV only when N
samples fan out of ONE prompt inside one jit. This module generalizes
that to arbitrary cross-request overlap, the way SGLang-style radix
caches do, on top of the paged layout from sampler/paged/:

  * `RefPagePool` extends the page allocator to REFCOUNTS: a physical
    page may back the block tables of several requests plus the cache
    tree at once; alloc/release become ref/unref, and a page returns to
    the free stack only at refcount zero. Unlike `pages.PageState` (a
    jitted device free-stack), the pool is host-side — admission is
    host-driven in both consumers (the continuous-batching scheduler
    and the serving engine), so the allocator never needs to trace.
  * `RadixCache` maps token-prefix keys to page ids. Keys are the
    LEFT-PADDED prompt rows with the mask bit folded into each element
    (`k_i = tok_i * 2 + mask_i`): two rows match only when their pad
    layout matches, which is exactly the condition under which their
    cache-slot layouts (and hence their per-slot KV values) coincide.
    A node's edge is a token-key span; a node owns the pages whose
    coverage ENDS inside its span, so an edge split at a non-page-
    aligned boundary re-partitions page ownership without copying.
  * A matched prefix of `m` tokens installs `m // P` full shared pages
    into the new request's block table with zero prefill FLOPs
    (refcount inc only). A match ending MID-PAGE is a copy-on-write
    split: the straddling donor page — valid for slots
    `[m_full, m)`, garbage beyond (the donor branch's divergent
    tokens) — is device-copied into a fresh page the request owns, and
    only the suffix `[m, Tp)` is prefilled through `suffix_logits`
    below (a `decode_verify` forward: the existing single-row jitted
    prefill primitive at suffix granularity).
  * Under memory pressure `plan()` evicts least-recently-used
    refcount-0 subtrees (leaves whose pages are referenced by the tree
    alone — never a page a live request still holds) until the
    admission fits.

Parity: the suffix forward reproduces full prefill bit-for-bit on the
CPU mesh because every per-position computation (attention row, MLP,
norms) is row-independent and the effective masks/positions/embeddings
coincide — the same argument `decode_verify` vs `decode_step` rests
on, pinned by tests/test_serving.py. Matches that end inside a row's
pad region are deliberately treated as cold (`m = 0`): a suffix
containing pad slots would attend them as real candidates and break
that equivalence.

Staleness: cached KV is only valid for the params that produced it.
The rollout scheduler therefore `reset()`s the cache at the start of
every `generate` call (prefix reuse across the repeated prompts of one
rollout queue — the n>1 queued path and dataset-level prompt repeats),
while the serving engine, whose params are fixed, keeps one tree alive
across its whole lifetime.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from nanorlhf_tpu.analysis.lockorder import make_lock
from nanorlhf_tpu.core.model import decode_verify


class RefPagePool:
    """Host-side ref-counted page allocator. `alloc()` pops a free page
    at refcount 1; `ref()` adds a holder; `unref()` drops one and frees
    the page at zero. Double-unref of a free page is a hard error — the
    holders (request block tables, tree nodes) each own exactly one
    reference and must release it exactly once (see the
    `pages.release_row` docstring for the jitted allocator's analogous
    idempotence contract)."""

    def __init__(self, num_pages: int):
        self.num_pages = int(num_pages)
        self.ref = np.zeros(self.num_pages, np.int32)
        self._free = list(range(self.num_pages - 1, -1, -1))

    @property
    def free_count(self) -> int:
        return len(self._free)

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        p = self._free.pop()
        assert self.ref[p] == 0
        self.ref[p] = 1
        return p

    def inc(self, page: int) -> None:
        assert self.ref[page] > 0, f"ref of free page {page}"
        self.ref[page] += 1

    def unref(self, page: int) -> bool:
        """Drop one reference; returns True when the page was freed."""
        assert self.ref[page] > 0, f"unref of free page {page}"
        self.ref[page] -= 1
        if self.ref[page] == 0:
            self._free.append(page)
            return True
        return False

    def shared_count(self) -> int:
        """Pages currently held by more than one owner."""
        return int(np.sum(self.ref > 1))


class _Node:
    __slots__ = ("edge", "end", "children", "page_map", "parent",
                 "last_use")

    def __init__(self, edge: tuple, end: int, parent: "_Node | None"):
        self.edge = edge          # token-key span labelling the inbound edge
        self.end = end            # cumulative key length at this node's end
        self.children: dict = {}  # first key element -> _Node
        self.page_map: dict = {}  # page index -> page id (ends in this span)
        self.parent = parent
        self.last_use = 0


@dataclass
class AdmissionPlan:
    """One admission's page layout, refs already taken: `row_pages` is
    the full block-table row (every entry allocated or shared),
    `m` the matched key length (0 = cold), `cow_src/cow_dst` the
    device copy the caller must issue before the suffix prefill."""
    m: int
    hit_tokens: int               # matched REAL tokens (pads excluded)
    row_pages: np.ndarray         # [n_blocks] int32
    cow_src: Optional[int] = None
    cow_dst: Optional[int] = None
    evicted: int = 0
    shared: int = 0               # pages installed by refcount inc alone


class RadixCache:
    """The tree + pool + stats, all under `make_lock("serving.radix")`.

    `headroom` scales the extra pages the consumers add past the
    resident rows' full budget (`extra = ceil(R * nb * headroom)`) —
    the slack that lets released rows' prefixes stay cached instead of
    being evicted the moment their row is recycled."""

    def __init__(self, enabled: bool = True, headroom: float = 1.0):
        self.enabled = enabled
        self.headroom = float(headroom)
        self._lock = make_lock("serving.radix")
        self.page_size = 0
        self.pool: Optional[RefPagePool] = None
        self._root = _Node((), 0, None)
        self._clock = 0
        # cumulative across resets — the serving/* and pages/shared
        # metric surfaces read these
        self.stats = {
            "lookups": 0, "lookup_tokens": 0, "hit_tokens": 0,
            "cow_splits": 0, "evicted_pages": 0, "inserted_nodes": 0,
            "shared_pages_acquired": 0,
        }

    # ------------------------------------------------------------- #
    # lifecycle
    # ------------------------------------------------------------- #

    def extra_pages(self, rows: int, n_blocks: int) -> int:
        return max(n_blocks, int(np.ceil(rows * n_blocks * self.headroom)))

    def reset(self, num_pages: int, page_size: int) -> None:
        """Fresh pool + empty tree. Cached KV is tied to the params that
        wrote it, so the rollout path resets per generate call; stats
        accumulate across resets."""
        with self._lock:
            self.page_size = int(page_size)
            self.pool = RefPagePool(num_pages)
            self._root = _Node((), 0, None)

    # ------------------------------------------------------------- #
    # match / admit
    # ------------------------------------------------------------- #

    def _match(self, key: tuple):
        """(m, node, pages): longest tree prefix of `key`, the node the
        match ends in (or at), and {page index: (page id, coverage
        end)} along the matched path — deeper occurrences override."""
        node, pos, pages = self._root, 0, {}
        self._clock += 1
        while True:
            node.last_use = self._clock
            for idx, pid in node.page_map.items():
                pages[idx] = (pid, min((idx + 1) * self.page_size, node.end))
            if pos >= len(key):
                return pos, node, pages
            child = node.children.get(key[pos])
            if child is None:
                return pos, node, pages
            common = 0
            limit = min(len(child.edge), len(key) - pos)
            while common < limit and child.edge[common] == key[pos + common]:
                common += 1
            if common < len(child.edge):
                # match dies inside this edge: the child's pages with
                # coverage start below the match point are still valid
                # donors/shares up to pos+common
                child.last_use = self._clock
                for idx, pid in child.page_map.items():
                    pages[idx] = (pid,
                                  min((idx + 1) * self.page_size, child.end))
                return pos + common, child, pages
            node, pos = child, pos + common

    def _find_donor(self, node: _Node, idx: int):
        """DFS below/at `node` for any page with index `idx` — every
        branch agrees on the matched slots, so the first found works."""
        stack = [node]
        while stack:
            n = stack.pop()
            if idx in n.page_map:
                return n.page_map[idx]
            stack.extend(n.children.values())
        return None

    def _evictable(self):
        """Leaves whose pages are tree-only (refcount 1), LRU first."""
        assert self.pool is not None
        out = []
        stack = [self._root]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n is self._root or n.children:
                continue
            if all(self.pool.ref[p] == 1 for p in n.page_map.values()):
                out.append(n)
        out.sort(key=lambda n: n.last_use)
        return out

    def _evict_one(self) -> int:
        """Drop LRU evictable leaves until at least one page is freed;
        returns pages freed (0 = none evictable). Never touches a page
        some request still references — shared pages keep their node
        pinned (refcount > 1). Leaves with an empty page_map (their
        whole coverage lives in an ancestor, e.g. after a split) free
        nothing, so they are collapsed and the scan continues rather
        than being reported as pool exhaustion."""
        while True:
            cands = self._evictable()
            if not cands:
                return 0
            victim = cands[0]
            freed = 0
            for pid in victim.page_map.values():
                freed += 1 if self.pool.unref(pid) else 0
            assert freed == len(victim.page_map), \
                "evicted a page another holder still references"
            parent = victim.parent
            del parent.children[victim.edge[0]]
            self.stats["evicted_pages"] += freed
            if freed:
                return freed

    def plan(self, key: tuple, *, pad_count: int, n_blocks: int,
             prompt_len: int) -> AdmissionPlan:
        """Match `key`, take refs on the shared full pages, allocate the
        rest of the row's full page budget (evicting LRU refcount-0
        subtrees when the free stack runs short), and return the
        admission layout. Raises RuntimeError when the pool cannot fit
        the row even after eviction — callers size rollout pools so this
        never fires there; the serving engine sheds instead."""
        assert self.pool is not None, "RadixCache.reset() before plan()"
        P = self.page_size
        with self._lock:
            m, node, pages = self._match(key)
            m = min(m, prompt_len - 1)       # >= 1 suffix token for logits
            if m < pad_count:
                m = 0                        # suffix must be pad-free
            m_full = (m // P) * P
            self.stats["lookups"] += 1
            self.stats["lookup_tokens"] += prompt_len - pad_count
            shared = {}
            for idx in range(m // P):
                ent = pages.get(idx)
                if ent is None or ent[1] < (idx + 1) * P:
                    # coverage gap (shouldn't happen on contiguous
                    # inserts) — degrade to the covered prefix
                    m, m_full = idx * P, idx * P
                    break
                shared[idx] = ent[0]
            if m < pad_count:                # degrade re-entered the pads
                m = 0
            if m == 0:
                shared = {}
                m_full = 0
            donor = None
            # a straddler is only worth a COW copy when its valid slots
            # [m_full, m) contain REAL tokens; a pads-only straddler
            # (m == pad_count) is never read, so skip the device copy
            # and let the suffix prefill own the page outright
            if m > m_full and m > pad_count:
                ent = pages.get(m // P)
                if ent is not None and ent[1] >= m:
                    donor = ent[0]
                else:
                    donor = self._find_donor(node, m // P)
                if donor is None:
                    # no straddler cached: degrade to the page-aligned
                    # prefix — cold if that boundary sits inside the pads
                    m = m_full if m_full >= pad_count else 0
            if m == 0:
                shared, m_full, donor = {}, 0, None

            need = n_blocks - len(shared)
            evicted = self.stats["evicted_pages"]
            while self.pool.free_count < need:
                if self._evict_one() == 0:
                    raise RuntimeError(
                        f"radix pool exhausted: need {need} pages, "
                        f"{self.pool.free_count} free, nothing evictable")
            row = np.full(n_blocks, self.pool.num_pages, np.int32)
            for idx, pid in shared.items():
                self.pool.inc(pid)
                row[idx] = pid
            for idx in range(len(shared), n_blocks):
                row[idx] = self.pool.alloc()
            cow_src = cow_dst = None
            if donor is not None and m > m_full:
                cow_src, cow_dst = donor, int(row[m // P])
                self.stats["cow_splits"] += 1
            hit = max(0, m - pad_count)
            self.stats["hit_tokens"] += hit
            self.stats["shared_pages_acquired"] += len(shared)
            return AdmissionPlan(
                m=m, hit_tokens=hit, row_pages=row, cow_src=cow_src,
                cow_dst=cow_dst, shared=len(shared),
                evicted=self.stats["evicted_pages"] - evicted)

    # ------------------------------------------------------------- #
    # insert / release
    # ------------------------------------------------------------- #

    def insert(self, key: tuple, row_pages: np.ndarray,
               cached_len: int) -> None:
        """Install the freshly prefilled row's prefix `key[:cached_len]`
        into the tree; the tree takes one extra reference per page it
        adopts (pages already covered by an existing branch stay
        private to the row)."""
        assert self.pool is not None
        key = tuple(key[:cached_len])
        P = self.page_size
        with self._lock:
            self._clock += 1
            node, pos = self._root, 0
            while pos < len(key):
                node.last_use = self._clock
                child = node.children.get(key[pos])
                if child is None:
                    break
                common = 0
                limit = min(len(child.edge), len(key) - pos)
                while common < limit and \
                        child.edge[common] == key[pos + common]:
                    common += 1
                if common < len(child.edge):
                    self._split(child, common)
                    child = node.children[key[pos]]
                node, pos = child, pos + common
            if pos >= len(key):
                node.last_use = self._clock
                return                       # full key already cached
            leaf = _Node(key[pos:], len(key), node)
            node.children[key[pos]] = leaf
            leaf.last_use = self._clock
            for idx in range(pos // P, -(-len(key) // P)):
                pid = int(row_pages[idx])
                self.pool.inc(pid)
                leaf.page_map[idx] = pid
            self.stats["inserted_nodes"] += 1

    def extend_text(self, key: tuple) -> None:
        """Insert `key` into the tree as TEXT ONLY — no pages adopted
        (empty `page_map`). The decode session calls this at row release
        with the row's prompt key extended by its GENERATED tokens
        (mask bit 1), so the tree remembers what followed each cached
        prefix even though the generated tokens' KV pages were recycled.
        `matched_continuation` reads these nodes to seed the n-gram
        drafter (sampler/speculative.py). Text-only nodes are safe by
        construction elsewhere: `plan()` degrades a match to the covered
        page prefix when it walks past the paged region (the existing
        coverage-gap rule), and `_evict_one` collapses empty-page_map
        leaves instead of counting them as pool exhaustion."""
        assert self.pool is not None
        with self._lock:
            self._clock += 1
            node, pos = self._root, 0
            while pos < len(key):
                node.last_use = self._clock
                child = node.children.get(key[pos])
                if child is None:
                    break
                common = 0
                limit = min(len(child.edge), len(key) - pos)
                while common < limit and \
                        child.edge[common] == key[pos + common]:
                    common += 1
                if common < len(child.edge):
                    self._split(child, common)
                    child = node.children[key[pos]]
                node, pos = child, pos + common
            if pos >= len(key):
                node.last_use = self._clock
                return                       # full key already cached
            leaf = _Node(key[pos:], len(key), node)
            node.children[key[pos]] = leaf
            leaf.last_use = self._clock
            self.stats["inserted_nodes"] += 1

    def matched_continuation(self, key: tuple, window: int) -> np.ndarray:
        """Up to `window` DECODE-TOKEN ids cached past `key`'s longest
        tree match — what some earlier request's text continued with
        after this prompt's matched prefix (descending the most recently
        used child at each branch). Elements with the mask bit unset
        (pad-layout keys) are dropped, so the result is plain token ids
        ready for the drafter's seed buffer. Empty when the key is cold."""
        with self._lock:
            m, node, _pages = self._match(key)
            if m == 0:
                return np.zeros((0,), np.int32)
            cont: list = []
            # tail of the edge the match ended inside (m == node.end
            # means the edge is fully consumed and we descend directly)
            edge_off = m - (node.end - len(node.edge))
            cur = node
            while len(cont) < window:
                cont.extend(cur.edge[edge_off:])
                edge_off = 0
                if not cur.children:
                    break
                cur = max(cur.children.values(), key=lambda n: n.last_use)
            toks = [k // 2 for k in cont if k & 1]
            return np.asarray(toks[:window], np.int32)

    def _split(self, child: _Node, at: int) -> None:
        """Split `child`'s edge `at` elements in: a new mid node takes
        the pages whose coverage ends at or before the split point."""
        parent = child.parent
        split_end = child.end - len(child.edge) + at
        mid = _Node(child.edge[:at], split_end, parent)
        mid.last_use = child.last_use
        parent.children[child.edge[0]] = mid
        child.edge = child.edge[at:]
        child.parent = mid
        mid.children[child.edge[0]] = child
        P = self.page_size
        for idx in [i for i in child.page_map
                    if min((i + 1) * P, child.end) <= split_end]:
            mid.page_map[idx] = child.page_map.pop(idx)

    def release(self, row_pages: np.ndarray) -> int:
        """Drop the ROW's reference on each allocated table entry (tree
        references survive — that is the cache). Returns pages actually
        freed. Sentinel entries (== num_pages) are skipped, so a
        released row's sentinel-reset table is safe to pass again —
        idempotence lives at the row-hold level, mirroring
        `pages.release_row`."""
        assert self.pool is not None
        freed = 0
        with self._lock:
            for pid in np.asarray(row_pages).ravel():
                pid = int(pid)
                if pid >= self.pool.num_pages:
                    continue
                freed += 1 if self.pool.unref(pid) else 0
        return freed

    # ------------------------------------------------------------- #
    # introspection
    # ------------------------------------------------------------- #

    def snapshot(self) -> dict:
        """JSON-able state for /statusz and tools/inspect_run.py."""
        with self._lock:
            nodes = cached = 0
            stack = [self._root]
            while stack:
                n = stack.pop()
                stack.extend(n.children.values())
                nodes += 1
                cached += len(n.page_map)
            hit, total = self.stats["hit_tokens"], self.stats["lookup_tokens"]
            return {
                "nodes": nodes - 1,          # root is structural
                "cached_pages": cached,
                "free_pages": self.pool.free_count if self.pool else 0,
                "num_pages": self.pool.num_pages if self.pool else 0,
                "shared_pages": self.pool.shared_count() if self.pool else 0,
                "page_size": self.page_size,
                "hit_frac": hit / max(total, 1),
                **dict(self.stats),
            }


# ----------------------------------------------------------------- #
# device helpers (shared by the rollout scheduler and the engine)
# ----------------------------------------------------------------- #

@jax.jit
def copy_page(caches, src, dst):
    """COW split: duplicate physical page `src` into `dst` across every
    layer of the pool pytree ([L, num_pages, ...] leaves)."""
    return jax.tree.map(lambda c: c.at[:, dst].set(c[:, src]), caches)


@partial(jax.jit, static_argnames=("config", "page_size", "lora_scale"))
def suffix_logits(params, config, suffix_ids, positions, fill, last,
                  key_mask, caches, row_table, *, page_size, lora_scale):
    """Single-row suffix prefill: a `decode_verify` forward over the
    unmatched prompt tail writes its KV at slots [fill, fill+Sb) through
    the row's block table and returns the last REAL token's next-token
    logits ([V]) — `last` indexes past the bucket-padding tail, whose
    garbage KV lands in decode-region slots that the decode loop
    overwrites before ever marking them attendable. The caller buckets
    suffix lengths (`bucket_len`) so retraces stay logarithmic."""
    logits, caches = decode_verify(
        params, config, suffix_ids, positions, fill, key_mask, caches,
        lora_scale=lora_scale, page_table=row_table[None, :],
        page_size=page_size,
    )
    return jnp.take(logits[0], last, axis=0), caches


def bucket_len(n: int, cap: int) -> int:
    """Round a suffix length up to a power of two, clamped to `cap`
    (the slots left in the row's page budget) — one retrace per bucket
    instead of one per distinct suffix length."""
    b = 1
    while b < n:
        b *= 2
    return max(n, min(b, cap))


def prompt_key(tokens: np.ndarray, mask: np.ndarray) -> tuple:
    """Radix key for one left-padded prompt row: the mask bit folds into
    each element so prefixes only match when their pad layout does —
    the condition for slot-identical KV."""
    return tuple(int(t) * 2 + int(b) for t, b in
                 zip(np.asarray(tokens), np.asarray(mask).astype(bool)))
