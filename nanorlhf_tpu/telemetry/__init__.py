"""Telemetry subsystem (docs/OBSERVABILITY.md): cross-thread span tracing
with a flight-recorder ring (tracer.py — Chrome trace-event JSON, Perfetto-
loadable), analytic MFU/throughput accounting with a jax.monitoring
recompile counter (mfu.py), and the run-health plane — streaming anomaly
detection over the metric stream (health.py) plus a live /metrics ·
/healthz · /statusz HTTP exporter (exporter.py), and the per-sample
lineage ledger — end-to-end rollout provenance with drop attribution
(lineage.py, queried by tools/inspect_run.py). tracer/health/exporter/
lineage are jax-free; mfu.py imports jax lazily — bench's jax-averse
parent can load any of them by file path."""

from nanorlhf_tpu.telemetry.exporter import (
    StatusExporter,
    render_prometheus,
    render_prometheus_histograms,
    validate_prometheus_text,
)
from nanorlhf_tpu.telemetry.health import (
    DEFAULT_RULES,
    SLO_RULES,
    HealthConfig,
    HealthMonitor,
    HealthRule,
)
from nanorlhf_tpu.telemetry.hist import (
    LatencyHub,
    StreamingHistogram,
    percentiles_from_samples,
)
from nanorlhf_tpu.telemetry.lineage import (
    LineageLedger,
    chains,
    drop_histogram,
    read_ledger,
)
from nanorlhf_tpu.telemetry.mfu import (
    BACKEND_COMPILE_EVENT,
    CPU_PEAK_FLOPS,
    PEAK_FLOPS_PER_CHIP,
    RecompileCounter,
    flops_param_count,
    peak_flops_per_chip,
    recompile_counter,
    update_flops,
)
from nanorlhf_tpu.telemetry.tracer import (
    SpanTracer,
    validate_trace_events,
    validate_trace_file,
)

__all__ = [
    "BACKEND_COMPILE_EVENT",
    "CPU_PEAK_FLOPS",
    "DEFAULT_RULES",
    "HealthConfig",
    "HealthMonitor",
    "HealthRule",
    "LatencyHub",
    "LineageLedger",
    "PEAK_FLOPS_PER_CHIP",
    "RecompileCounter",
    "SLO_RULES",
    "SpanTracer",
    "StatusExporter",
    "StreamingHistogram",
    "chains",
    "drop_histogram",
    "flops_param_count",
    "peak_flops_per_chip",
    "percentiles_from_samples",
    "read_ledger",
    "recompile_counter",
    "render_prometheus",
    "render_prometheus_histograms",
    "update_flops",
    "validate_prometheus_text",
    "validate_trace_events",
    "validate_trace_file",
]
