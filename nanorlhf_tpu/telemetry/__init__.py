"""Telemetry subsystem (docs/OBSERVABILITY.md): cross-thread span tracing
with a flight-recorder ring (tracer.py — Chrome trace-event JSON, Perfetto-
loadable), and analytic MFU/throughput accounting with a jax.monitoring
recompile counter (mfu.py). tracer.py is jax-free; mfu.py imports jax
lazily — bench's jax-averse parent can load either by file path."""

from nanorlhf_tpu.telemetry.mfu import (
    BACKEND_COMPILE_EVENT,
    CPU_PEAK_FLOPS,
    PEAK_FLOPS_PER_CHIP,
    RecompileCounter,
    flops_param_count,
    peak_flops_per_chip,
    recompile_counter,
    update_flops,
)
from nanorlhf_tpu.telemetry.tracer import (
    SpanTracer,
    validate_trace_events,
    validate_trace_file,
)

__all__ = [
    "BACKEND_COMPILE_EVENT",
    "CPU_PEAK_FLOPS",
    "PEAK_FLOPS_PER_CHIP",
    "RecompileCounter",
    "SpanTracer",
    "flops_param_count",
    "peak_flops_per_chip",
    "recompile_counter",
    "update_flops",
    "validate_trace_events",
    "validate_trace_file",
]
