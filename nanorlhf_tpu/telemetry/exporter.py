"""Live status exporter: /metrics · /healthz · /statusz over stdlib HTTP.

The write side of observability (tracer, blackbox, metrics.jsonl) is
post-mortem; this is the read side — a `ThreadingHTTPServer` on a daemon
thread that lets a human `curl` a running trainer or a monitor scrape it:

- `/metrics`  Prometheus text exposition of the latest scalar metrics row
              (MetricsLogger.latest()) merged with the live health gauges,
              plus the latency histogram families (`_bucket`/`_sum`/
              `_count`) when a LatencyHub is attached
- `/healthz`  200/503 straight from the HealthMonitor verdict — the shape
              k8s-style liveness probes expect
- `/statusz`  one JSON blob of run state: step, policy version, staleness,
              queue depth, fleet membership + lease table, MFU (flagged
              when the peak-FLOPs table doesn't know the chip), and the
              last N health events

Off by default (`cfg.status_port=0` constructs a no-op). `status_port=-1`
binds an ephemeral port (tests, CI); the bound port is in `self.port`.
Responses are built fully, then written once with a Content-Length — a
scrape racing a trainer update sees a complete payload or none, never a
torn one. stdlib-only and jax-free, like the rest of the telemetry plane.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
# exposition line: name{labels} value [timestamp]
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (\S+)( [0-9]+)?$"
)
_VALUE_RE = re.compile(r"^[+-]?(\d+\.?\d*([eE][+-]?\d+)?|\.\d+([eE][+-]?\d+)?"
                       r"|Inf|NaN)$", re.IGNORECASE)


def render_prometheus(metrics: dict, prefix: str = "nanorlhf_") -> str:
    """Render a flat {name: scalar} dict as Prometheus text exposition
    (version 0.0.4). Metric names like `perf/mfu` sanitize to
    `nanorlhf_perf_mfu`; non-numeric values are skipped; NaN/±Inf are
    legal exposition values and pass through. A key carrying a label set
    (`lineage/dropped_total{reason="stale_drop"}`) keeps its labels
    verbatim — only the name part is sanitized — and shares one # TYPE
    line with its sibling series."""
    lines: list[str] = []
    seen: set = set()
    typed: set = set()
    for key in sorted(metrics):
        try:
            v = float(metrics[key])
        except (TypeError, ValueError):
            continue
        raw, labels = str(key), ""
        if raw.endswith("}") and "{" in raw:
            raw, _, tail = raw.partition("{")
            labels = "{" + tail
        name = prefix + _NAME_RE.sub("_", raw)
        if (name, labels) in seen:  # two raw keys can sanitize the same
            continue
        seen.add((name, labels))
        if v != v:
            val = "NaN"
        elif v == float("inf"):
            val = "+Inf"
        elif v == float("-inf"):
            val = "-Inf"
        else:
            val = repr(v)
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{labels} {val}")
    return "\n".join(lines) + "\n"


def render_prometheus_histograms(states: dict, prefix: str = "nanorlhf_") -> str:
    """Render {metric key: StreamingHistogram.state()} as Prometheus
    histogram exposition (version 0.0.4): per family one `# TYPE name
    histogram` line, cumulative `name_bucket{le="..."}` series at the
    sketch's coarse export edges (exact — the edges align with internal
    bucket boundaries), the mandatory `le="+Inf"` bucket equal to
    `name_count`, then `name_sum` and `name_count`. Keys sanitize exactly
    like `render_prometheus` (`latency/ttft_s` → `nanorlhf_latency_ttft_s`)
    so the gauge and histogram surfaces share one naming rule."""
    from nanorlhf_tpu.telemetry.hist import StreamingHistogram

    lines: list[str] = []
    for key in sorted(states):
        try:
            h = StreamingHistogram.load(states[key])
        except Exception:
            continue  # a torn/foreign state must not kill the scrape
        name = prefix + _NAME_RE.sub("_", str(key))
        lines.append(f"# TYPE {name} histogram")
        for edge, cum in h.cumulative_buckets():
            lines.append(f'{name}_bucket{{le="{edge:.6g}"}} {cum}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {h.count}')
        lines.append(f"{name}_sum {repr(h.sum)}")
        lines.append(f"{name}_count {h.count}")
    return "\n".join(lines) + "\n" if lines else ""


def validate_prometheus_text(text: str) -> list[str]:
    """Validate Prometheus text exposition; return a list of problems
    (empty == valid). Shared by the test suite and the CI health-smoke
    step so 'parseable' means the same thing in both."""
    problems: list[str] = []
    samples = 0
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            if not (line.startswith("# TYPE ") or line.startswith("# HELP ")
                    or line.startswith("# EOF")):
                problems.append(f"line {i}: bad comment {line!r}")
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            problems.append(f"line {i}: unparseable sample {line!r}")
            continue
        if not _VALUE_RE.match(m.group(3)):
            problems.append(f"line {i}: bad value {m.group(3)!r}")
            continue
        samples += 1
    if samples == 0:
        problems.append("no samples")
    return problems


class StatusExporter:
    """Serve /metrics, /healthz, /statusz for a running trainer.

    port semantics: 0 → disabled no-op (enabled=False, close() is safe);
    -1 → bind an ephemeral port (self.port holds the real one); >0 → bind
    that port. `metrics_fn` returns the latest flat scalar row,
    `statusz_fn` a JSON-able dict, `health` a HealthMonitor (or None)."""

    def __init__(self, port: int, *,
                 metrics_fn: Optional[Callable[[], dict]] = None,
                 statusz_fn: Optional[Callable[[], dict]] = None,
                 health=None, latency=None, host: str = "127.0.0.1"):
        self.enabled = bool(port)
        self.host = host
        self.port = 0
        self._metrics_fn = metrics_fn
        self._statusz_fn = statusz_fn
        self._health = health
        self._latency = latency  # LatencyHub: /metrics histogram families
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        if not self.enabled:
            return
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_GET(self):  # noqa: N802 (stdlib handler API)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        status, ctype, body = exporter._metrics()
                    elif path == "/healthz":
                        status, ctype, body = exporter._healthz()
                    elif path in ("/statusz", "/"):
                        status, ctype, body = exporter._statusz()
                    else:
                        status, ctype, body = 404, "text/plain", b"not found\n"
                except Exception as e:  # a scrape must never kill itself
                    status, ctype = 500, "text/plain"
                    body = f"{type(e).__name__}: {e}\n".encode()
                self.send_response(status)
                self.send_header("Content-Type", f"{ctype}; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                # one write of the full body: no torn payloads under
                # concurrent scrape
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # silence per-request stderr
                pass

        bind_port = port if port > 0 else 0  # -1 → ephemeral
        self._server = ThreadingHTTPServer((host, bind_port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="status-exporter",
            daemon=True,
        )
        self._thread.start()
        print(f"[status] serving /metrics /healthz /statusz on "
              f"http://{self.host}:{self.port}")

    # ----------------------------------------------------------------- #
    # endpoint bodies (run on HTTP threads; providers are thread-safe)
    # ----------------------------------------------------------------- #

    def _metrics(self) -> tuple:
        merged: dict = {}
        if self._metrics_fn is not None:
            merged.update(self._metrics_fn() or {})
        if self._health is not None:
            merged.update(self._health.gauges())
        text = render_prometheus(merged)
        if self._latency is not None and self._latency.enabled:
            text += render_prometheus_histograms(self._latency.states())
        return 200, "text/plain", text.encode()

    def _healthz(self) -> tuple:
        verdict = self._health.verdict if self._health is not None else "ok"
        status = 503 if verdict == "crit" else 200
        return status, "text/plain", f"{verdict}\n".encode()

    def _statusz(self) -> tuple:
        payload = self._statusz_fn() if self._statusz_fn is not None else {}
        body = json.dumps(payload, default=str).encode()
        return 200, "application/json", body

    # ----------------------------------------------------------------- #

    def close(self) -> None:
        """Stop serving and release the port. Idempotent; safe on the
        disabled no-op."""
        if self._closed or self._server is None:
            self._closed = True
            return
        self._closed = True
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
