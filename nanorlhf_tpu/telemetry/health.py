"""Run-health plane: O(1)-memory streaming aggregators + anomaly rules.

The telemetry subsystem made runs explainable AFTER the fact (trace.json,
blackbox dumps); this module makes them diagnosable WHILE running. Async
RL pipelines fail quietly — reward collapse, entropy collapse, KL blowup,
queue starvation, recompile storms — long before anything crashes, so the
trainer routes every metric row it emits through a `HealthMonitor`:

- per-metric **streaming aggregates** with O(1) memory: a fast and a slow
  EWMA (mean + West variance, the sentinel's recurrence) plus P² quantile
  sketches (Jain & Chlamtac 1985 — five markers track a quantile without
  storing observations) for the p50/p95 of the series;
- **windowed rates** for cumulative counters (consumer_wait_s, fleet
  quarantines, perf/recompiles), measured on the monotonic clock — the
  same clock discipline as PhaseTimer, so an NTP step cannot fake a storm;
- a **declarative rule set** (`HealthRule`) evaluated against those
  aggregates into per-rule OK/WARN/CRIT levels and an overall verdict.

Each `observe()` call returns `health/*` gauge rows that ride the same
metrics row (docs/METRICS.md), emits trace instants on a "health" track at
every rule transition, and — on an OK/WARN→CRIT transition — dumps a
flight-recorder blackbox (`reason="health"`, via the callable the trainer
wires to `SpanTracer.dump_blackbox`) and invokes the optional `on_crit`
hook (cfg.health_arm_sentinel). Monitor state journals into
`trainer_state.json` under `"health"` — same restart/resume continuity
contract as the fleet counters (windowed rates deliberately excluded: the
monotonic clock does not survive the process; windows re-warm).

Thread-safe: the exporter's HTTP threads read (`gauges`, `snapshot`,
`verdict`) while the trainer thread writes (`observe`). jax-free on
purpose, like tracer.py — unit-testable with plain dict rows.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import threading

from nanorlhf_tpu.analysis.lockorder import make_lock
import time
from typing import Callable, Optional

OK, WARN, CRIT = "ok", "warn", "crit"
_LEVELS = {OK: 0, WARN: 1, CRIT: 2}


# --------------------------------------------------------------------- #
# streaming aggregators
# --------------------------------------------------------------------- #


class Ewma:
    """EWMA mean + West's EWMA variance (the sentinel's recurrence)."""

    __slots__ = ("alpha", "n", "mean", "var")

    def __init__(self, alpha: float):
        self.alpha = float(alpha)
        self.n = 0
        self.mean = 0.0
        self.var = 0.0

    def update(self, x: float, winsor_floor: Optional[float] = None) -> None:
        """`winsor_floor` turns on a winsorized VARIANCE update: the mean
        still adapts with the full deviation (the baseline must converge to
        a genuine regime change), but the variance contribution is clipped
        at 4 effective sigma — otherwise the first anomalous observations
        inflate the baseline's own sigma and cap every later z-score at ~2,
        hiding the very collapse the z-rules exist to catch."""
        if self.n == 0:
            self.mean = x
        else:
            d = x - self.mean
            if winsor_floor is not None:
                lim = 4.0 * max(self.sigma, winsor_floor)
                dv = max(-lim, min(lim, d))
            else:
                dv = d
            self.mean += self.alpha * d
            self.var = (1.0 - self.alpha) * (self.var + self.alpha * dv * dv)
        self.n += 1

    @property
    def sigma(self) -> float:
        return math.sqrt(max(self.var, 0.0))

    def state(self) -> dict:
        return {"n": self.n, "mean": self.mean, "var": self.var}

    def load(self, s: dict) -> None:
        self.n = int(s.get("n", 0))
        self.mean = float(s.get("mean", 0.0))
        self.var = float(s.get("var", 0.0))


class P2Quantile:
    """P² single-quantile sketch (Jain & Chlamtac 1985): five markers whose
    heights converge to the q-quantile, adjusted with a piecewise-parabolic
    interpolation per observation. O(1) memory, no stored samples — the
    running p50/p95 of a metric series at the cost of ~20 float ops."""

    __slots__ = ("q", "n", "heights", "npos", "desired", "dn")

    def __init__(self, q: float = 0.5):
        self.q = float(q)
        self.n = 0
        self.heights: list[float] = []
        self.npos = [1.0, 2.0, 3.0, 4.0, 5.0]
        self.desired = [1.0, 1 + 2 * q, 1 + 4 * q, 3 + 2 * q, 5.0]
        self.dn = [0.0, q / 2.0, q, (1.0 + q) / 2.0, 1.0]

    def update(self, x: float) -> None:
        x = float(x)
        if not math.isfinite(x):
            return
        self.n += 1
        h = self.heights
        if len(h) < 5:  # warmup: the first five observations seed the markers
            h.append(x)
            h.sort()
            return
        if x < h[0]:
            h[0] = x
            k = 0
        elif x >= h[4]:
            h[4] = x
            k = 3
        else:
            k = next(i for i in range(4) if h[i] <= x < h[i + 1])
        for i in range(k + 1, 5):
            self.npos[i] += 1.0
        for i in range(5):
            self.desired[i] += self.dn[i]
        for i in (1, 2, 3):
            d = self.desired[i] - self.npos[i]
            if (d >= 1.0 and self.npos[i + 1] - self.npos[i] > 1.0) or (
                d <= -1.0 and self.npos[i - 1] - self.npos[i] < -1.0
            ):
                d = 1.0 if d > 0 else -1.0
                hp = self._parabolic(i, d)
                if h[i - 1] < hp < h[i + 1]:
                    h[i] = hp
                else:  # parabolic estimate escaped its cell: linear fallback
                    j = i + int(d)
                    h[i] = h[i] + d * (h[j] - h[i]) / (self.npos[j] - self.npos[i])
                self.npos[i] += d

    def _parabolic(self, i: int, d: float) -> float:
        h, n = self.heights, self.npos
        return h[i] + d / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + d) * (h[i + 1] - h[i]) / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - d) * (h[i] - h[i - 1]) / (n[i] - n[i - 1])
        )

    def value(self) -> float:
        if not self.heights:
            return float("nan")
        if len(self.heights) < 5:  # warmup: order statistic of what we have
            s = sorted(self.heights)
            return s[min(len(s) - 1, max(0, math.ceil(self.q * len(s)) - 1))]
        return self.heights[2]

    def state(self) -> dict:
        return {"q": self.q, "n": self.n, "heights": list(self.heights),
                "npos": list(self.npos), "desired": list(self.desired)}

    def load(self, s: dict) -> None:
        self.n = int(s.get("n", 0))
        self.heights = [float(v) for v in s.get("heights", [])]
        if s.get("npos"):
            self.npos = [float(v) for v in s["npos"]]
        if s.get("desired"):
            self.desired = [float(v) for v in s["desired"]]


class WindowedRate:
    """Per-second rate of a CUMULATIVE counter over a sliding time window.
    Timestamps come from the monotonic clock (perf_counter — PhaseTimer's
    clock discipline), so NTP steps can neither fake nor hide a storm. The
    point buffer is bounded: O(1) memory like everything else here."""

    __slots__ = ("window_s", "max_points", "_pts")

    def __init__(self, window_s: float = 60.0, max_points: int = 256):
        self.window_s = float(window_s)
        self.max_points = int(max_points)
        self._pts: collections.deque = collections.deque()

    def update(self, t: float, v: float) -> None:
        self._pts.append((float(t), float(v)))
        cut = t - self.window_s
        while len(self._pts) > 2 and (
            self._pts[0][0] < cut or len(self._pts) > self.max_points
        ):
            self._pts.popleft()

    def rate(self) -> float:
        if len(self._pts) < 2:
            return 0.0
        t0, v0 = self._pts[0]
        t1, v1 = self._pts[-1]
        if t1 <= t0:
            return 0.0
        # counters are cumulative and monotone; a reset (restart) would show
        # as a negative delta — clamp rather than report a negative storm
        return max(0.0, (v1 - v0) / (t1 - t0))


class MetricAggregate:
    """The per-metric O(1) state: fast + slow EWMA, p50/p95 sketches, last
    value and count. ~40 floats per metric, updated in ~O(1) per row."""

    __slots__ = ("count", "last", "fast", "slow", "p50", "p95",
                 "var_floor_frac")

    def __init__(self, fast_alpha: float, slow_alpha: float,
                 var_floor_frac: float = 0.05):
        self.count = 0
        self.last = float("nan")
        self.var_floor_frac = float(var_floor_frac)
        self.fast = Ewma(fast_alpha)
        self.slow = Ewma(slow_alpha)
        self.p50 = P2Quantile(0.5)
        self.p95 = P2Quantile(0.95)

    def update(self, x: float) -> None:
        self.count += 1
        self.last = x
        self.fast.update(x)
        # the slow tracker IS the anomaly baseline: winsorize its variance
        # update so an anomaly cannot widen its own detection band
        self.slow.update(x, winsor_floor=self.var_floor_frac
                         * abs(self.slow.mean))
        self.p50.update(x)
        self.p95.update(x)

    def state(self) -> dict:
        return {"count": self.count, "last": self.last,
                "fast": self.fast.state(), "slow": self.slow.state(),
                "p50": self.p50.state(), "p95": self.p95.state()}

    @classmethod
    def from_state(cls, s: dict, fast_alpha: float, slow_alpha: float,
                   var_floor_frac: float = 0.05) -> "MetricAggregate":
        agg = cls(fast_alpha, slow_alpha, var_floor_frac)
        agg.count = int(s.get("count", 0))
        agg.last = float(s.get("last", float("nan")))
        agg.fast.load(s.get("fast", {}))
        agg.slow.load(s.get("slow", {}))
        agg.p50.load(s.get("p50", {}))
        agg.p95.load(s.get("p95", {}))
        return agg


# --------------------------------------------------------------------- #
# declarative rules
# --------------------------------------------------------------------- #


@dataclasses.dataclass(frozen=True)
class HealthRule:
    """One anomaly rule over one metric's aggregate.

    kinds (warn/crit thresholds, warn fires first):
    - "drop_z":   fast EWMA fell below the slow baseline by >= k sigma
                  (sigma floored at var_floor_frac·|baseline|, like the
                  sentinel — a near-constant series must still trip)
    - "rise_z":   symmetric blowup above the baseline
    - "below_frac": fast EWMA <= frac × running p50 (thresholds are
                  fractions; crit < warn)
    - "above_abs": last value >= threshold
    - "rate_above": windowed per-second rate of a cumulative counter
                  >= threshold
    - "quantile_above": the `quantile` of the attached LatencyHub's
                  histogram named by `metric` >= threshold seconds (the
                  SLO rule shape — p95 TTFT, p99 queue wait, RPC RTT).
                  `warmup` counts histogram SAMPLES, not metric rows;
                  evaluates OK when no hub is attached, so the rule set
                  is safe on monitors without a latency surface.
    """

    name: str
    metric: str
    kind: str
    warn: float
    crit: float
    warmup: int = 8          # min observations of the metric before firing
    description: str = ""
    quantile: float = 0.95   # quantile_above only: which quantile to score


DEFAULT_RULES: tuple = (
    HealthRule("reward_collapse", "eval_objective/rlhf_reward_old",
               "drop_z", warn=3.0, crit=6.0,
               description="reward fast-EWMA fell k·sigma below the slow "
                           "baseline"),
    HealthRule("reward_drift", "eval_objective/rlhf_reward_old",
               "rise_z", warn=4.0, crit=10.0,
               description="reward runaway above the slow baseline (grader "
                           "drift / reward hacking)"),
    HealthRule("entropy_collapse", "policy/entropy_avg_new",
               "below_frac", warn=0.5, crit=0.2,
               description="policy entropy fell below a fraction of its "
                           "running median"),
    HealthRule("kl_blowup", "objective/kl_rollout_old",
               "rise_z", warn=4.0, crit=8.0,
               description="rollout KL-to-reference blowing up vs its slow "
                           "baseline"),
    HealthRule("draft_acceptance_degradation", "rollout/draft_acceptance",
               "below_frac", warn=0.7, crit=0.4,
               description="speculative-decode acceptance degraded vs its "
                           "running median"),
    HealthRule("queue_starvation", "orchestrator/consumer_wait_s",
               "rate_above", warn=0.5, crit=0.9,
               description="trainer starved: consumer wait accruing at >= "
                           "threshold seconds per wall second"),
    HealthRule("fleet_reassignment_rate", "fleet/reassigned_leases",
               "rate_above", warn=0.05, crit=0.2,
               description="lease reassignment churn (workers failing or "
                           "straggling)"),
    HealthRule("fleet_quarantine_rate", "fleet/quarantines",
               "rate_above", warn=0.05, crit=0.2,
               description="workers entering quarantine"),
    HealthRule("recompile_storm", "perf/recompiles",
               "rate_above", warn=0.05, crit=0.5,
               description="XLA backend recompiles accruing mid-run (silent "
                           "retraces)"),
    HealthRule("rpc_error_rate", "fleet/rpc_errors",
               "rate_above", warn=0.5, crit=2.0,
               description="fleet RPC transport errors (torn frames, resets, "
                           "timeouts) accruing per wall second"),
    HealthRule("heartbeat_miss_rate", "fleet/heartbeat_misses",
               "rate_above", warn=0.2, crit=1.0,
               description="worker heartbeats failing to reach the "
                           "coordinator (link degradation before lease "
                           "expiry fires)"),
)

# SLO rules over latency-histogram quantiles (docs/OBSERVABILITY.md §7) —
# the verdicts ROADMAP item 5's autoscaler consumes. Kept OUT of
# DEFAULT_RULES: they only evaluate against an attached LatencyHub, and
# the trainers append them when cfg.latency is on. Thresholds are
# deliberately generous for the CPU CI rig (a cold-compile generation
# wall lands in the TTFT sketch); production overrides pass a custom
# rule tuple through HealthConfig.
SLO_RULES: tuple = (
    HealthRule("slo_ttft_p95", "latency/ttft_s",
               "quantile_above", warn=60.0, crit=300.0,
               warmup=16, quantile=0.95,
               description="p95 admission-to-first-token over the SLO"),
    HealthRule("slo_queue_wait_p99", "latency/queue_wait_s",
               "quantile_above", warn=10.0, crit=60.0,
               warmup=16, quantile=0.99,
               description="p99 sample queue wait over the SLO (trainer "
                           "about to starve or producer racing ahead)"),
    HealthRule("slo_rpc_rtt_p95", "latency/rpc_heartbeat_s",
               "quantile_above", warn=1.0, crit=5.0,
               warmup=16, quantile=0.95,
               description="p95 heartbeat RTT over the SLO (control-plane "
                           "link degradation — heartbeats are small and "
                           "frequent, so their RTT isolates the wire)"),
)


@dataclasses.dataclass
class HealthConfig:
    enabled: bool = True
    fast_alpha: float = 0.5      # tracks the last ~2 rows
    slow_alpha: float = 0.05     # the baseline the fast tracker is judged by
    var_floor_frac: float = 0.05  # sigma floor as a fraction of |baseline|
    warmup: int = 8              # default per-rule min observations
    window_s: float = 60.0       # rate-rule sliding window
    max_events: int = 64         # transition ring kept for /statusz
    # hysteresis: a rule's level steps DOWN only after this many consecutive
    # calmer evaluations — the adapting baseline absorbs a collapse within
    # ~1/slow_alpha rows, and without a hold a 30s-interval scraper would
    # miss the CRIT window entirely (alert resolve-delay semantics)
    recovery_rows: int = 8
    blackbox_on_crit: bool = True
    rules: tuple = DEFAULT_RULES


# --------------------------------------------------------------------- #
# the monitor
# --------------------------------------------------------------------- #


class HealthMonitor:
    """Consumes every metric row the trainers emit; see module docstring.

    `blackbox_fn(step, extra)` is the flight-recorder dump seam (the
    trainer wires `SpanTracer.dump_blackbox(dir, step, "health", extra)`;
    a disabled tracer makes it a no-op). `on_crit(step, rules)` is the
    optional escalation hook (cfg.health_arm_sentinel)."""

    def __init__(self, config: Optional[HealthConfig] = None, tracer=None,
                 blackbox_fn: Optional[Callable] = None,
                 on_crit: Optional[Callable] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 latency=None):
        self.cfg = config or HealthConfig()
        self.enabled = bool(self.cfg.enabled)
        self._tracer = tracer
        self._blackbox_fn = blackbox_fn
        self._on_crit = on_crit
        self._clock = clock
        # LatencyHub the quantile_above (SLO) rules read; hub quantile
        # reads acquire telemetry.hist, ranked ABOVE telemetry.health in
        # LOCK_ORDER, so reading it during rule eval is order-legal
        self._latency = latency
        self._lock = make_lock("telemetry.health")
        self._aggs: dict[str, MetricAggregate] = {}
        self._rates: dict[str, WindowedRate] = {
            r.metric: WindowedRate(self.cfg.window_s)
            for r in self.cfg.rules if r.kind == "rate_above"
        }
        self._rule_levels: dict[str, str] = {r.name: OK for r in self.cfg.rules}
        self._improve_streak: dict[str, int] = {}
        self._events: collections.deque = collections.deque(
            maxlen=int(self.cfg.max_events)
        )
        self._verdict = OK
        self.rows = 0        # metric rows observed
        self.trips = 0       # OK/WARN -> CRIT transitions

    def attach_latency(self, hub) -> None:
        """Wire a LatencyHub after construction (the trainer builds the
        hub and the monitor in either order)."""
        self._latency = hub

    # ---------------------------------------------------------------- #
    # observation
    # ---------------------------------------------------------------- #

    def observe(self, step: int, row: dict) -> dict:
        """Fold one metric row into the aggregates, evaluate the rules, and
        return the `health/*` gauge rows to ride the same metrics record.
        {} when disabled (the observation itself is the only cost)."""
        if not self.enabled:
            return {}
        with self._lock:
            t = self._clock()
            for k, v in row.items():
                if k.startswith("health/"):
                    continue  # never aggregate our own output
                try:
                    x = float(v)
                except (TypeError, ValueError):
                    continue
                if not math.isfinite(x):
                    continue
                agg = self._aggs.get(k)
                if agg is None:
                    agg = self._aggs[k] = MetricAggregate(
                        self.cfg.fast_alpha, self.cfg.slow_alpha,
                        self.cfg.var_floor_frac,
                    )
                agg.update(x)
                rate = self._rates.get(k)
                if rate is not None:
                    rate.update(t, x)
            self.rows += 1
            transitions = []
            for rule in self.cfg.rules:
                level, signal, detail = self._eval_rule_locked(rule)
                prev = self._rule_levels[rule.name]
                if _LEVELS[level] < _LEVELS[prev]:
                    # hysteresis hold: step down only after recovery_rows
                    # consecutive calmer evaluations
                    streak = self._improve_streak.get(rule.name, 0) + 1
                    if streak < int(self.cfg.recovery_rows):
                        self._improve_streak[rule.name] = streak
                        level = prev
                    else:
                        self._improve_streak[rule.name] = 0
                else:
                    self._improve_streak[rule.name] = 0
                if level != prev:
                    ev = {"unix_time": time.time(), "step": int(step),
                          "rule": rule.name, "level": level, "prev": prev,
                          "signal": round(float(signal), 4),
                          "detail": detail or rule.description}
                    self._events.append(ev)
                    transitions.append(ev)
                    self._rule_levels[rule.name] = level
            prev_verdict = self._verdict
            verdict = self._verdict_locked()
            self._verdict = verdict
            crit_extra = None
            if verdict == CRIT and prev_verdict != CRIT:
                self.trips += 1
                crit_extra = {
                    "rules": sorted(n for n, l in self._rule_levels.items()
                                    if l == CRIT),
                    "step": int(step),
                }
            rows_out = self._gauges_locked()
        # tracer/blackbox/escalation OUTSIDE the monitor lock: the tracer has
        # its own lock and blackbox_fn reaches back into the trainer
        if self._tracer is not None:
            for ev in transitions:
                self._tracer.instant(
                    f"health.{ev['rule']}", track="health", level=ev["level"],
                    prev=ev["prev"], step=ev["step"], signal=ev["signal"],
                )
            if verdict != prev_verdict:
                self._tracer.instant("health.verdict", track="health",
                                     level=verdict, prev=prev_verdict,
                                     step=int(step))
        if crit_extra is not None:
            if self.cfg.blackbox_on_crit and self._blackbox_fn is not None:
                try:
                    self._blackbox_fn(int(step), dict(crit_extra))
                except Exception as e:  # post-mortem aid must not kill the run
                    print(f"[health] blackbox dump failed: "
                          f"{type(e).__name__}: {e}")
            if self._on_crit is not None:
                self._on_crit(int(step), list(crit_extra["rules"]))
        return rows_out

    def _eval_rule_locked(self, rule: HealthRule) -> tuple:
        """-> (level, signal, detail). The signal is the breach magnitude in
        the rule's own units (z-score, fraction-of-median, rate/s)."""
        warmup = rule.warmup if rule.warmup else self.cfg.warmup
        if rule.kind == "quantile_above":
            # histogram-backed: no MetricAggregate exists for the metric
            # (it names a latency sketch, not a row) — gate on the sketch
            return self._eval_quantile_rule(rule, warmup)
        agg = self._aggs.get(rule.metric)
        if agg is None or agg.count < max(int(warmup), 1):
            return OK, 0.0, ""
        if rule.kind in ("drop_z", "rise_z"):
            floor = self.cfg.var_floor_frac * abs(agg.slow.mean)
            sigma = max(agg.slow.sigma, floor)
            if sigma <= 0.0:
                return OK, 0.0, ""
            z = (agg.fast.mean - agg.slow.mean) / sigma
            signal = -z if rule.kind == "drop_z" else z
            level = (CRIT if signal >= rule.crit
                     else WARN if signal >= rule.warn else OK)
            return level, signal, (
                f"fast={agg.fast.mean:.4g} baseline={agg.slow.mean:.4g} "
                f"z={z:+.2f}" if level != OK else ""
            )
        if rule.kind == "below_frac":
            base = agg.p50.value()
            if not math.isfinite(base) or base <= 0.0:
                return OK, 0.0, ""
            frac = agg.fast.mean / base
            level = (CRIT if frac <= rule.crit
                     else WARN if frac <= rule.warn else OK)
            return level, frac, (
                f"fast={agg.fast.mean:.4g} is {frac:.2f}× the running "
                f"median {base:.4g}" if level != OK else ""
            )
        if rule.kind == "above_abs":
            v = agg.last
            level = (CRIT if v >= rule.crit
                     else WARN if v >= rule.warn else OK)
            return level, v, (f"last={v:.4g}" if level != OK else "")
        if rule.kind == "rate_above":
            r = self._rates[rule.metric].rate()
            level = (CRIT if r >= rule.crit
                     else WARN if r >= rule.warn else OK)
            return level, r, (
                f"{r:.4g}/s over the last {self.cfg.window_s:.0f}s"
                if level != OK else ""
            )
        raise ValueError(f"unknown rule kind {rule.kind!r}")

    def _eval_quantile_rule(self, rule: HealthRule, warmup) -> tuple:
        """SLO rule: score one quantile of an attached latency histogram
        against absolute-seconds thresholds. The warmup gate counts the
        SKETCH's samples (rule.metric names a histogram, not a metric
        row), so a rule cannot fire off two noisy observations. The agg
        warmup gate in _eval_rule_locked does not apply — histograms fill
        many samples per metric row."""
        hub = self._latency
        if hub is None or not getattr(hub, "enabled", False):
            return OK, 0.0, ""
        if hub.count(rule.metric) < max(int(warmup), 1):
            return OK, 0.0, ""
        v = hub.quantile(rule.metric, rule.quantile)
        if not math.isfinite(v):
            return OK, 0.0, ""
        level = (CRIT if v >= rule.crit
                 else WARN if v >= rule.warn else OK)
        return level, v, (
            f"p{rule.quantile * 100:g}={v:.4g}s "
            f"(warn>={rule.warn:g}s crit>={rule.crit:g}s)"
            if level != OK else ""
        )

    def _verdict_locked(self) -> str:
        worst = max(_LEVELS[l] for l in self._rule_levels.values()) \
            if self._rule_levels else 0
        return {0: OK, 1: WARN, 2: CRIT}[worst]

    def _gauges_locked(self) -> dict:
        n_warn = sum(1 for l in self._rule_levels.values() if l == WARN)
        n_crit = sum(1 for l in self._rule_levels.values() if l == CRIT)
        out = {
            "health/verdict": float(_LEVELS[self._verdict]),
            "health/rules_warn": float(n_warn),
            "health/rules_crit": float(n_crit),
            "health/trips": float(self.trips),
        }
        out.update({
            f"health/rule_{name}": float(_LEVELS[level])
            for name, level in self._rule_levels.items()
        })
        return out

    # ---------------------------------------------------------------- #
    # read side (exporter HTTP threads)
    # ---------------------------------------------------------------- #

    @property
    def verdict(self) -> str:
        with self._lock:
            return self._verdict

    def gauges(self) -> dict:
        """Current `health/*` gauge values (the /metrics merge — live even
        between logging_steps rows). {} when disabled."""
        if not self.enabled:
            return {}
        with self._lock:
            return self._gauges_locked()

    def events(self, n: Optional[int] = None) -> list:
        """The most recent rule-transition events (newest last)."""
        with self._lock:
            evs = list(self._events)
        return evs if n is None else evs[-int(n):]

    def snapshot(self) -> dict:
        """JSON-able state for /statusz."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "verdict": self._verdict,
                "trips": self.trips,
                "rows": self.rows,
                "rules": dict(self._rule_levels),
                "events": list(self._events),
            }

    # ---------------------------------------------------------------- #
    # checkpoint journal (trainer_state.json under "health")
    # ---------------------------------------------------------------- #

    def journal(self) -> dict:
        with self._lock:
            return {
                "rows": self.rows,
                "trips": self.trips,
                "verdict": self._verdict,
                "rule_levels": dict(self._rule_levels),
                "improve_streaks": dict(self._improve_streak),
                "events": list(self._events),
                "aggregates": {k: a.state() for k, a in self._aggs.items()},
            }

    def restore(self, journal: dict) -> None:
        """Resume the aggregates/verdict/trip accounting from a checkpoint.
        Windowed rates are NOT restored — their monotonic timestamps died
        with the old process; the windows re-warm, which only delays a
        rate rule, never double-counts."""
        with self._lock:
            self.rows = int(journal.get("rows", 0))
            self.trips = int(journal.get("trips", 0))
            self._verdict = str(journal.get("verdict", OK))
            levels = journal.get("rule_levels") or {}
            self._rule_levels = {
                r.name: str(levels.get(r.name, OK)) for r in self.cfg.rules
            }
            self._improve_streak = {
                k: int(v)
                for k, v in (journal.get("improve_streaks") or {}).items()
            }
            self._events = collections.deque(
                list(journal.get("events") or []),
                maxlen=int(self.cfg.max_events),
            )
            self._aggs = {
                k: MetricAggregate.from_state(
                    s, self.cfg.fast_alpha, self.cfg.slow_alpha,
                    self.cfg.var_floor_frac,
                )
                for k, s in (journal.get("aggregates") or {}).items()
            }
