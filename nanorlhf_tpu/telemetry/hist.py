"""Mergeable streaming latency histograms (docs/OBSERVABILITY.md §7).

The health plane's P² sketches (health.py) answer "what is the running
p95 of THIS scalar series" in O(1) memory, but they are approximate in a
way that cannot be combined: two workers' P² states have no exact merge,
and a sketch tracks exactly one quantile. The latency surface needs the
opposite trade: *fixed* log-spaced bucket boundaries (HDR-histogram
style) shared by every sketch in the fleet, so

- merge is exact bucket-wise addition (associative and commutative — a
  coordinator can fold worker sketches in any order and the result is
  bit-identical to recording every sample centrally);
- any quantile is answerable after the fact, with relative error bounded
  by one bucket's width (``BUCKETS_PER_DECADE = 32`` → bucket edges grow
  by 10^(1/32) ≈ 7.5%, so interpolated quantiles land within ~4% of the
  exact order statistic);
- memory stays O(occupied buckets) regardless of sample count: counts
  live in a sparse dict, and a latency series that spans 3 decades
  touches ≤ 96 buckets.

The scheme covers 100 ns .. 10 000 s (11 decades). Samples below the
floor land in a single underflow bucket, samples above the ceiling in an
overflow bucket; both participate in quantiles (clamped to the observed
min/max) so a pathological value cannot silently vanish.

`LatencyHub` is the process-wide recording surface: named histograms
behind one lock (``telemetry.hist`` in the declared LOCK_ORDER — ranked
above every lock held at a recording site: the sample queue's condition,
the RPC client lock, and the health monitor's lock, which reads quantiles
during SLO rule evaluation). Recording never calls out while holding the
hub lock. jax-free.
"""

from __future__ import annotations

import math
from typing import Optional

from nanorlhf_tpu.analysis.lockorder import make_lock

# Fixed bucket scheme — every sketch in a fleet shares these constants,
# which is what makes merge exact. Changing them is a journal/wire format
# change: state() embeds the scheme and load()/merge() reject mismatches.
HIST_LO = 1e-7            # smallest bucketed value (100 ns)
HIST_DECADES = 11         # 1e-7 s .. 1e4 s
BUCKETS_PER_DECADE = 32   # edge growth 10^(1/32) ≈ 1.0746
HIST_BUCKETS = HIST_DECADES * BUCKETS_PER_DECADE

_LOG_LO = math.log10(HIST_LO)
_UNDER = -1               # value <= HIST_LO
_OVER = HIST_BUCKETS      # value >  10^(log10(lo) + decades)

# metric keys with this prefix are histogram families: the exporter
# renders them as Prometheus histogram exposition and nanolint's registry
# rule cross-checks their _bucket/_sum/_count suffixed forms (both
# directions) against the base METRICS.md row
HISTOGRAM_KEY_PREFIX = "latency/"


def bucket_index(value: float) -> int:
    """Bucket holding `value`; _UNDER/_OVER outside the covered range."""
    if value <= HIST_LO:
        return _UNDER
    i = int(math.floor((math.log10(value) - _LOG_LO) * BUCKETS_PER_DECADE))
    # log10 rounding can land an exact edge value one bucket high/low;
    # clamp — determinism within a process is what merge exactness needs
    if i >= HIST_BUCKETS:
        return _OVER
    return max(i, 0)


def bucket_lower(i: int) -> float:
    return 10.0 ** (_LOG_LO + i / BUCKETS_PER_DECADE)


def bucket_upper(i: int) -> float:
    return 10.0 ** (_LOG_LO + (i + 1) / BUCKETS_PER_DECADE)


# coarse cumulative-export edges for Prometheus `_bucket{le=...}` lines:
# every half decade from 10 µs to 1000 s. These align with internal
# bucket edges (multiples of BUCKETS_PER_DECADE/2), so the cumulative
# counts at each edge are exact, not resampled.
_EXPORT_STEP = BUCKETS_PER_DECADE // 2
EXPORT_EDGE_INDICES = tuple(
    range(2 * BUCKETS_PER_DECADE, HIST_BUCKETS - BUCKETS_PER_DECADE + 1,
          _EXPORT_STEP)
)


class SchemeMismatch(ValueError):
    """Two sketches with different bucket schemes cannot merge exactly."""


class StreamingHistogram:
    """One log-bucketed sketch: record / quantile / merge / state / load.

    NOT thread-safe on its own — `LatencyHub` provides the locking. Kept
    lock-free so tests and the offline inspector can use it directly.
    """

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self):
        self.counts: dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def record(self, value: float) -> None:
        v = float(value)
        if math.isnan(v):
            return  # a NaN duration is a caller bug, not a latency sample
        if v < 0.0:
            v = 0.0  # monotonic-clock differences cannot be negative
        i = bucket_index(v)
        self.counts[i] = self.counts.get(i, 0) + 1
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def quantile(self, q: float) -> float:
        """Interpolated quantile in [0, 1]; NaN on an empty sketch."""
        if self.count == 0:
            return float("nan")
        q = min(max(q, 0.0), 1.0)
        target = q * self.count
        seen = 0
        for i in sorted(self.counts):
            c = self.counts[i]
            if seen + c >= target:
                if i == _UNDER:
                    val = HIST_LO
                elif i == _OVER:
                    val = self.max if self.max is not None else bucket_lower(_OVER)
                else:
                    lo, hi = bucket_lower(i), bucket_upper(i)
                    frac = (target - seen) / c if c else 0.0
                    val = lo + (hi - lo) * frac
                # the sketch knows the exact extremes: never report a
                # quantile outside the observed range
                if self.min is not None:
                    val = max(val, self.min)
                if self.max is not None:
                    val = min(val, self.max)
                return val
            seen += c
        return self.max if self.max is not None else float("nan")

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else float("nan")

    def merge(self, other: "StreamingHistogram") -> "StreamingHistogram":
        """Exact bucket-wise merge; returns self for chaining."""
        for i, c in other.counts.items():
            self.counts[i] = self.counts.get(i, 0) + c
        self.count += other.count
        self.sum += other.sum
        if other.min is not None:
            self.min = other.min if self.min is None else min(self.min, other.min)
        if other.max is not None:
            self.max = other.max if self.max is None else max(self.max, other.max)
        return self

    def cumulative_buckets(self) -> list[tuple[float, int]]:
        """(upper_edge_seconds, cumulative_count) at the coarse export
        edges, exact by construction — the Prometheus `_bucket` series
        (the final +Inf bucket is `count` and rendered by the exporter)."""
        items = sorted(self.counts.items())
        out: list[tuple[float, int]] = []
        pos = 0
        cum = 0
        for edge_i in EXPORT_EDGE_INDICES:
            while pos < len(items) and items[pos][0] < edge_i:
                cum += items[pos][1]
                pos += 1
            out.append((bucket_lower(edge_i), cum))
        return out

    def summary(self) -> dict:
        """Flat JSON-able digest for /statusz and the run inspector."""
        return {
            "count": self.count,
            "mean_s": self.mean if self.count else None,
            "p50_s": self.quantile(0.50) if self.count else None,
            "p95_s": self.quantile(0.95) if self.count else None,
            "p99_s": self.quantile(0.99) if self.count else None,
            "min_s": self.min,
            "max_s": self.max,
        }

    # -- journal (trainer_state.json) ---------------------------------- #

    def state(self) -> dict:
        return {
            "scheme": [HIST_LO, HIST_DECADES, BUCKETS_PER_DECADE],
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "counts": {str(i): c for i, c in self.counts.items()},
        }

    @classmethod
    def load(cls, state: dict) -> "StreamingHistogram":
        scheme = list(state.get("scheme", []))
        if scheme != [HIST_LO, HIST_DECADES, BUCKETS_PER_DECADE]:
            raise SchemeMismatch(
                f"histogram scheme {scheme} != "
                f"{[HIST_LO, HIST_DECADES, BUCKETS_PER_DECADE]}; sketches "
                f"only merge/restore across identical bucket boundaries"
            )
        h = cls()
        h.count = int(state.get("count", 0))
        h.sum = float(state.get("sum", 0.0))
        h.min = state.get("min")
        h.max = state.get("max")
        h.counts = {int(i): int(c) for i, c in state.get("counts", {}).items()}
        return h


class LatencyHub:
    """Named streaming histograms behind one declared lock.

    The recording surface every latency-bearing path shares: the paged
    scheduler's TTFT/inter-token stamps, the sample queue's dequeue wait,
    the RPC client's per-op RTT, the reward-grader wall, and the
    trainer's phase splits. Disabled (`enabled=False`), `record` is a
    guarded no-op so the off path costs one attribute check.
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = make_lock("telemetry.hist")
        self._hists: dict[str, StreamingHistogram] = {}

    def record(self, name: str, value_s: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = StreamingHistogram()
            h.record(value_s)

    # -- read side (exporter, SLO rules, tests) ------------------------ #

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._hists)

    def count(self, name: str) -> int:
        with self._lock:
            h = self._hists.get(name)
            return h.count if h is not None else 0

    def quantile(self, name: str, q: float) -> float:
        with self._lock:
            h = self._hists.get(name)
            return h.quantile(q) if h is not None else float("nan")

    def snapshot(self) -> dict:
        """{name: summary digest} — the /statusz `latency` section."""
        with self._lock:
            return {name: h.summary() for name, h in sorted(self._hists.items())}

    def states(self) -> dict:
        """{name: full sketch state} — exporter + journal input. The
        states are deep copies: safe to render outside the lock."""
        with self._lock:
            return {name: h.state() for name, h in self._hists.items()}

    def merge_states(self, states: dict) -> None:
        """Fold another hub's `states()` in — the fleet-merge seam: a
        coordinator collecting per-worker sketches adds them bucket-wise
        into its own, exactly."""
        if not self.enabled:
            return
        loaded = {name: StreamingHistogram.load(s) for name, s in states.items()}
        with self._lock:
            for name, other in loaded.items():
                h = self._hists.get(name)
                if h is None:
                    self._hists[name] = other
                else:
                    h.merge(other)

    # -- journal (trainer_state.json "latency") ------------------------ #

    def journal(self) -> dict:
        return {"hists": self.states()}

    def restore(self, state: dict) -> None:
        hists = (state or {}).get("hists", {})
        loaded = {name: StreamingHistogram.load(s) for name, s in hists.items()}
        with self._lock:
            self._hists.update(loaded)


def percentiles_from_samples(samples: list[float]) -> dict:
    """Exact order-statistic digest of a raw sample list, shaped like
    `StreamingHistogram.summary()` — the jax-free offline reconstruction
    path (tools/inspect_run.py --latency) and its cross-check tests share
    this so 'reconstructed from the ledger' and 'recorded live' disagree
    only by bucket width."""
    if not samples:
        return {"count": 0, "mean_s": None, "p50_s": None, "p95_s": None,
                "p99_s": None, "min_s": None, "max_s": None}
    xs = sorted(float(x) for x in samples)
    n = len(xs)

    def pct(q: float) -> float:
        # linear interpolation between closest ranks (numpy default)
        pos = q * (n - 1)
        lo = int(math.floor(pos))
        hi = min(lo + 1, n - 1)
        return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)

    return {
        "count": n,
        "mean_s": sum(xs) / n,
        "p50_s": pct(0.50),
        "p95_s": pct(0.95),
        "p99_s": pct(0.99),
        "min_s": xs[0],
        "max_s": xs[-1],
    }
