"""Sample lineage ledger: end-to-end rollout provenance (docs/OBSERVABILITY.md §6).

The paper's two defining data-path tricks — sparse GRPO silently dropping
zero-advantage samples, and an index-keyed rollout seed — make *per-sample*
provenance the real debugging surface. Aggregates (health EWMAs, fleet
counters) tell you THAT samples vanished; this ledger tells you WHICH, WHERE
and WHY. It is the same shape RLAX (arxiv 2512.06392) ships as a first-class
"trajectory store" in its TPU RL stack.

One joinable event stream per rollout index, across every layer a sample
passes through:

    lease      prompt draw + dataset cursor: lease id, worker id (and
               `reassigned_from` when a revoked lease is re-granted — the
               two events for one index carry both worker ids), PRNG
               fold-in key path
    generation policy version, wall time, spec-decode per-row accepted
               tokens / draft acceptance, and the `segments` list
               ([{row, policy_version, tok_range}]) that in-flight
               mid-sequence weight swaps populate with one entry per
               policy-version span (docs/ORCHESTRATOR.md §in-flight swaps;
               `tools/inspect_run.py --segments` is the query side)
    queue      enqueue/dequeue monotonic times, staleness at consumption
    reward     per-sample score, retry attempt, grader wall time
    outcome    advantage, kept rows; excluded rows land as `drop` events
    drop       machine-readable `drop_reason` + affected sample count
               (sparse_zero_advantage, sentinel_quarantine,
               fleet_late_duplicate, stale_drop, keep_filter,
               is_truncated_weight, ...)

Every event carries the `rollout_index` / `step` / `policy_version`
correlation keys the tracer stamps on spans, so a ledger row joins against
trace.json and metrics.jsonl; `tools/inspect_run.py` is the query side.

Mechanics: thread-safe, append-only JSONL under `<dir>/lineage/`, size-
rotated (`ledger_00000.jsonl`, `ledger_00001.jsonl`, ...). Off by default
(`cfg.lineage`); when disabled every method is a cheap no-op, the same
contract as SpanTracer. `cfg.lineage_sample_rate` gates whole rollout
indices (deterministic hash, never individual events) so a sampled index
always has its complete lease→generation→queue→reward→outcome chain.
Drop-reason counters and the last-N sample ring are kept regardless of
sampling — they feed /statusz and the `lineage/*` metric rows. The event
index is monotonic and journaled in trainer_state.json ("lineage", beside
"health") so a resumed run appends, never restarts, the stream. jax-free.
"""

from __future__ import annotations

import glob
import json
import os
import threading

from nanorlhf_tpu.analysis.lockorder import make_lock
import time
from collections import deque
from typing import Iterator, Optional

# Knuth multiplicative hash: cheap, deterministic, index-keyed — the same
# rollout index samples in or out on every worker and every resume
_HASH_MULT = 2654435761
_HASH_MOD = 1 << 32


def _jsonable(v):
    """Best-effort JSON coercion (the tracer's idiom): numpy / device
    scalars and arrays become plain Python; everything else falls back to
    str so a ledger write can never raise on an exotic payload."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    item = getattr(v, "item", None)
    if item is not None and getattr(v, "ndim", None) == 0:
        try:
            return _jsonable(item())
        except Exception:
            pass
    tolist = getattr(v, "tolist", None)
    if tolist is not None:
        try:
            return _jsonable(tolist())
        except Exception:
            pass
    return str(v)


class LineageLedger:
    """Append-only provenance ledger. Construct once per trainer; share the
    instance across the orchestrator/fleet/queue threads — every write takes
    the internal lock, and rotation happens under it."""

    def __init__(
        self,
        output_dir: str,
        enabled: bool = True,
        sample_rate: float = 1.0,
        max_bytes: int = 8 * 1024 * 1024,
        ring_len: int = 32,
        rows_hint: int = 1,
        key_path: Optional[str] = None,
    ):
        self.enabled = bool(enabled)
        self.sample_rate = float(sample_rate)
        self.max_bytes = int(max_bytes)
        # batch rows per rollout index: the unit drop counters are kept in
        # when the dropping layer (queue, fleet dedup) can't see rows
        self.rows_hint = int(rows_hint)
        # human-readable PRNG derivation stamped on lease events, e.g.
        # "fold_in(fold_in(seed_key, 0x5E11), rollout_index)"
        self.key_path = key_path
        self._lock = make_lock("telemetry.lineage")
        self._fh = None
        self._seq = 0            # rotation file sequence
        self._event_index = 0    # monotonic across rotation AND resume
        self.dropped_writes = 0  # events lost to I/O errors (never raise)
        self.drop_counts: dict[str, int] = {}
        self._ring: deque = deque(maxlen=max(1, int(ring_len)))
        self.dir = os.path.join(output_dir, "lineage") if enabled else ""
        if not self.enabled:
            return
        os.makedirs(self.dir, exist_ok=True)
        # resume appends to the newest rotation file rather than clobbering
        existing = sorted(glob.glob(os.path.join(self.dir, "ledger_*.jsonl")))
        if existing:
            try:
                self._seq = int(os.path.basename(existing[-1])[7:-6])
            except ValueError:
                self._seq = len(existing)
        self._open()

    # ----------------------------------------------------------------- #
    # write path
    # ----------------------------------------------------------------- #

    def _path(self) -> str:
        return os.path.join(self.dir, f"ledger_{self._seq:05d}.jsonl")

    def _open(self):
        self._fh = open(self._path(), "a")

    def sampled(self, rollout_index: Optional[int]) -> bool:
        """Deterministic per-index sampling gate. Index-less events (and
        rate >= 1) always pass; a gated-out index is gated out at EVERY
        layer, so no partial chains."""
        if not self.enabled:
            return False
        if rollout_index is None or self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        h = (int(rollout_index) * _HASH_MULT) % _HASH_MOD
        return (h / _HASH_MOD) < self.sample_rate

    def event(self, etype: str, rollout_index: Optional[int] = None,
              **fields) -> int:
        """Append one event; returns its monotonic event index (-1 when
        disabled / sampled out / lost to an I/O error). Never raises."""
        if not self.sampled(rollout_index):
            return -1
        rec = {"type": etype, "time": time.time(),
               "t_mono": time.perf_counter()}
        if rollout_index is not None:
            rec["rollout_index"] = int(rollout_index)
        for k, v in fields.items():
            if v is not None:
                rec[k] = _jsonable(v)
        with self._lock:
            rec["i"] = self._event_index
            try:
                if self._fh.tell() > self.max_bytes:
                    self._fh.close()
                    self._seq += 1
                    self._open()
                self._fh.write(json.dumps(rec) + "\n")
                self._fh.flush()
            except (OSError, ValueError):
                self.dropped_writes += 1
                return -1
            self._event_index += 1
            return rec["i"]

    # ----------------------------------------------------------------- #
    # typed emitters (thin sugar over event(); layers call these so the
    # schema lives in one file)
    # ----------------------------------------------------------------- #

    def lease(self, rollout_index: int, *, lease_id=None, worker_id=None,
              reassigned_from=None, cursor=None, length=None, **fields) -> int:
        return self.event(
            "lease", rollout_index, lease_id=lease_id, worker_id=worker_id,
            reassigned_from=reassigned_from, cursor=cursor, length=length,
            key_path=self.key_path, **fields,
        )

    def generation(self, rollout_index: int, *, policy_version=None,
                   worker_id=None, lease_id=None, gen_s=None, spec=None,
                   segments=None, **fields) -> int:
        # `segments` defaults to the single-policy whole-range entry; the
        # in-flight weight-swap path passes `segments_summary(payload)` —
        # one entry per {policy_version, tok_range} span per row
        if segments is None and policy_version is not None:
            segments = [{"policy_version": policy_version,
                         "tok_range": [0, None]}]
        return self.event(
            "generation", rollout_index, policy_version=policy_version,
            worker_id=worker_id, lease_id=lease_id, gen_s=gen_s, spec=spec,
            segments=segments, **fields,
        )

    def turn(self, rollout_index: int, *, step=None, row=None, turn=None,
             tool_wall_s=None, obs_range=None, obs_tokens=None, reward=None,
             tok_range=None, **fields) -> int:
        # one event per (episode row, turn) from the multi-turn env driver
        # (envs/rollout.py): `tok_range` is the turn's model-token span and
        # `obs_range` the observation span, both in response coordinates —
        # the same coordinate space as generation `segments`, so turn
        # events join generation events on rollout_index
        return self.event(
            "turn", rollout_index, step=step, row=row, turn=turn,
            tool_wall_s=tool_wall_s, obs_range=obs_range,
            obs_tokens=obs_tokens, reward=reward, tok_range=tok_range,
            **fields,
        )

    def queue(self, rollout_index: int, *, enqueue_t=None, dequeue_t=None,
              staleness=None, policy_version=None, **fields) -> int:
        return self.event(
            "queue", rollout_index, enqueue_t=enqueue_t, dequeue_t=dequeue_t,
            staleness=staleness, policy_version=policy_version, **fields,
        )

    def reward(self, rollout_index: int, *, step=None, scores=None,
               attempt=None, wall_s=None, **fields) -> int:
        return self.event(
            "reward", rollout_index, step=step, scores=scores,
            attempt=attempt, wall_s=wall_s, **fields,
        )

    def outcome(self, rollout_index: int, *, step=None, policy_version=None,
                kept=None, advantage=None, **fields) -> int:
        return self.event(
            "outcome", rollout_index, step=step,
            policy_version=policy_version, kept=kept, advantage=advantage,
            **fields,
        )

    def drop(self, rollout_index: Optional[int], reason: str, *,
             count: Optional[int] = None, step=None, row=None,
             **fields) -> int:
        """Attribute excluded samples. `count` defaults to 1 for row-level
        drops (pass `row`) and to `rows_hint` for whole-rollout drops —
        the histogram is denominated in SAMPLES either way. Counters are
        bumped even for sampled-out indices so /statusz and the
        `lineage/dropped_total{reason=...}` rows stay exact."""
        if not self.enabled:
            return -1
        if count is None:
            count = 1 if row is not None else self.rows_hint
        with self._lock:
            self.drop_counts[reason] = (
                self.drop_counts.get(reason, 0) + int(count)
            )
        return self.event(
            "drop", rollout_index, reason=reason, count=int(count),
            step=step, row=row, **fields,
        )

    def fault(self, *, point=None, worker=None, action=None, **fields) -> int:
        """One armed fault site firing (the chaos harness hooks
        FaultInjector.on_fire here): index-less, so the offline
        `inspect_run --chaos` timeline rebuilds from the ledger alone."""
        return self.event(
            "fault", None, point=point, worker=worker, action=action,
            **fields,
        )

    def chaos_run(self, *, seed=None, spec=None, spec_digest=None,
                  path=None, key_path=None, **fields) -> int:
        """Chaos soak header: the composed spec + its derivation, enough
        to replay the identical run (`nanorlhf_tpu/chaos/`)."""
        return self.event(
            "chaos_run", None, seed=seed, spec=spec,
            spec_digest=spec_digest, path=path, key_path=key_path, **fields,
        )

    def chaos_audit(self, *, name=None, ok=None, detail=None,
                    checked=None, **fields) -> int:
        """One run-invariant auditor's verdict (chaos/auditors.py)."""
        return self.event(
            "chaos_audit", None, name=name, ok=ok, detail=detail,
            checked=checked, **fields,
        )

    def note_sample(self, rollout_index: int, *, step=None, score=None,
                    response_chars=None, worker_id=None, kept=None):
        """Feed the last-N ring behind /statusz's `recent` list. Summaries
        only (score, size, provenance) — full text lives in the ledger's
        `sample` events, not in a scrape payload."""
        if not self.enabled:
            return
        with self._lock:
            self._ring.append({
                "rollout_index": int(rollout_index),
                "step": step, "score": _jsonable(score),
                "response_chars": response_chars, "worker_id": worker_id,
                "kept": kept,
            })

    # ----------------------------------------------------------------- #
    # read side: /statusz, /metrics, journal
    # ----------------------------------------------------------------- #

    def statusz(self) -> dict:
        """JSON-able snapshot for the exporter's /statusz `lineage`
        section: drop-reason counts since start + the last-N sample ring."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "events": self._event_index,
                "dropped_writes": self.dropped_writes,
                "drop_reasons": dict(self.drop_counts),
                "recent": list(self._ring),
            }

    def metric_rows(self) -> dict:
        """Labeled gauge rows for /metrics, keyed in the
        `name{label="v"}` form render_prometheus preserves — e.g.
        `lineage/dropped_total{reason="sparse_zero_advantage"}`."""
        if not self.enabled:
            return {}
        with self._lock:
            rows = {"lineage/events_total": float(self._event_index)}
            for reason, n in sorted(self.drop_counts.items()):
                rows[f'lineage/dropped_total{{reason="{reason}"}}'] = float(n)
            return rows

    def journal(self) -> dict:
        """Resume continuity state for trainer_state.json ("lineage",
        beside "health"): the restored ledger continues the monotonic
        event-index stream and the since-start drop counters."""
        with self._lock:
            return {
                "event_index": self._event_index,
                "seq": self._seq,
                "drop_counts": dict(self.drop_counts),
            }

    def restore(self, journal: dict):
        if not self.enabled or not journal:
            return
        with self._lock:
            self._event_index = max(
                self._event_index, int(journal.get("event_index", 0))
            )
            for k, v in (journal.get("drop_counts") or {}).items():
                self.drop_counts[k] = max(
                    self.drop_counts.get(k, 0), int(v)
                )

    def close(self):
        """Flush + close. Idempotent; event() after close counts into
        dropped_writes instead of raising."""
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
        self.enabled = False


def segments_summary(payload) -> Optional[list]:
    """Flatten a rollout payload's per-row `segments` lists (stamped by the
    in-flight weight-swap path, docs/ORCHESTRATOR.md §in-flight swaps) into
    the flat JSON list generation events carry:

        [{"row": r, "policy_version": v, "tok_range": [start, end]}, ...]

    `tok_range` is in response-token coordinates — the same space as `turn`
    events' tok_range, so swap boundaries and turn boundaries join directly.
    None when the payload carries no segments (swaps off, or a non-dict
    payload): `LineageLedger.generation` then falls back to the
    single-policy whole-range default."""
    segs = payload.get("segments") if isinstance(payload, dict) else None
    if not segs:
        return None
    out = []
    for row, row_segs in enumerate(segs):
        for s in row_segs or ():
            out.append({
                "row": row,
                "policy_version": _jsonable(s.get("policy_version")),
                "tok_range": _jsonable(s.get("tok_range")),
            })
    return out or None


def spec_summary(payload) -> Optional[dict]:
    """Pull the spec-decode stats dict out of a (device-ready) rollout
    payload into the JSON shape generation events carry — aggregate draft
    acceptance plus the per-row accepted-token counts. None when the
    payload has no spec stats (spec decode off, or a non-dict payload)."""
    st = payload.get("spec_stats") if isinstance(payload, dict) else None
    if not st:
        return None
    out = {
        k: _jsonable(st[k])
        for k in ("verify_steps", "drafted", "accepted", "emitted",
                  "accepted_rows")
        if k in st
    }
    drafted = out.get("drafted")
    if drafted:
        out["acceptance"] = round(out.get("accepted", 0) / drafted, 4)
    return out or None


# --------------------------------------------------------------------- #
# offline readers (tools/inspect_run.py + tests share these, so "parse
# the ledger" means the same thing in the CLI and in CI)
# --------------------------------------------------------------------- #


def read_ledger(run_dir: str) -> Iterator[dict]:
    """Yield every event from a run's rotated ledger files in write order.
    Accepts the run dir (containing `lineage/`) or the lineage dir itself;
    tolerates a truncated tail line (a crash mid-write)."""
    d = run_dir
    if os.path.isdir(os.path.join(run_dir, "lineage")):
        d = os.path.join(run_dir, "lineage")
    for path in sorted(glob.glob(os.path.join(d, "ledger_*.jsonl"))):
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    continue


def drop_histogram(events) -> dict:
    """Fold `drop` events into {reason: sample_count} — the histogram
    /statusz serves live, reproduced from the ledger alone."""
    hist: dict[str, int] = {}
    for ev in events:
        if ev.get("type") == "drop":
            reason = ev.get("reason", "unknown")
            hist[reason] = hist.get(reason, 0) + int(ev.get("count", 1))
    return hist


def chains(events) -> dict:
    """Group events by rollout index: {index: {type: [events...]}} — the
    join inspect_run.py and the fleet acceptance test walk."""
    by_index: dict[int, dict] = {}
    for ev in events:
        idx = ev.get("rollout_index")
        if idx is None:
            continue
        by_index.setdefault(int(idx), {}).setdefault(
            ev["type"], []
        ).append(ev)
    return by_index
