"""Analytic model-FLOPs / MFU / throughput accounting + recompile counter.

The ROADMAP's "fast as the hardware allows" is unverifiable from
episodes/sec alone — MFU (achieved model FLOPs / peak chip FLOPs) is the
hardware-normalized number. The accounting is ANALYTIC, the standard
transformer napkin model both bench.py and the trainer's per-update
`perf/*` metrics share (one formula, two consumers — they cannot drift):

    fwd FLOPs per token ≈ 2 · n_params        (one MAC per weight)
    bwd ≈ 2 × fwd  →  train tokens cost 3 · fwd

per update:

    flops = (decode + prefill + score_tokens) · 2N  +  train_tokens · 6N
    MFU   = flops / wall_seconds / (peak_flops_per_chip · n_devices)

Deliberate approximations (stable across PRs, so the series is
comparable): attention FLOPs (quadratic term) and the PPO value model are
not counted — at production sequence lengths on the 1.5B policy the 2N
weight term dominates; decode is counted at the full configured
response_length (the toy/real reward loops nearly always run it out).

The recompile counter hangs a `jax.monitoring` duration listener on
XLA's backend-compile event: a silent retrace (a shape that escaped the
bucket menu, a donation change) shows up as a `perf/recompiles` step
instead of an unexplained 40 s stall.

Importable without jax (bench's parent process must never touch the
backend): jax is only imported inside `recompile_counter()` /
`flops_param_count()`.
"""

from __future__ import annotations

import threading

from nanorlhf_tpu.analysis.lockorder import make_lock
from typing import Optional

# peak dense bf16 FLOPs/s per chip by device kind (public figures;
# substring match on jax Device.device_kind). Shared with bench.py.
PEAK_FLOPS_PER_CHIP = {
    "v6": 918e12,       # Trillium / v6e
    "v5p": 459e12,
    "v5": 197e12,       # v5e / "TPU v5 lite"
    "v4": 275e12,
    "v3": 123e12,
    "v2": 46e12,
}
CPU_PEAK_FLOPS = 1e12   # nominal; CPU MFU is not meaningful, only finite


def peak_flops_per_chip(device_kind: str, backend: str) -> tuple[float, bool]:
    """(peak_flops, known): peak dense bf16 FLOPs/s for one chip. Unknown
    TPU kinds fall back to the v5e figure (flagged known=False); non-TPU
    backends get the nominal CPU constant so MFU stays a finite series."""
    if backend != "tpu":
        return CPU_PEAK_FLOPS, False
    kind = (device_kind or "").lower().replace(" ", "")
    for k, v in PEAK_FLOPS_PER_CHIP.items():
        if k in kind:
            return v, True
    return PEAK_FLOPS_PER_CHIP["v5"], False


def flops_param_count(params: dict) -> int:
    """Parameter count for the 2N-per-token FLOPs model: the base policy
    tree without LoRA adapters (adapter FLOPs are a rounding error at
    production ranks, and excluding them keeps fused/LoRA configs on the
    same denominator as full fine-tuning)."""
    import jax
    import numpy as np

    return sum(
        int(np.prod(x.shape))
        for k, v in params.items() if k != "lora"
        for x in jax.tree.leaves(v)
    )


def update_flops(n_params: int, *, decode_tokens: float = 0.0,
                 prefill_tokens: float = 0.0, score_tokens: float = 0.0,
                 train_tokens: float = 0.0) -> float:
    """Model FLOPs for one RL update under the napkin model (module
    docstring): forward-only tokens at 2N, trained tokens at 3·2N."""
    fwd = 2.0 * float(n_params)
    return (decode_tokens + prefill_tokens + score_tokens) * fwd \
        + train_tokens * 3.0 * fwd


# ---------------------------------------------------------------------- #
# recompile counter (jax.monitoring)
# ---------------------------------------------------------------------- #

# XLA emits this duration event once per actual backend compilation —
# cache hits (in-memory jit cache or the persistent compilation cache
# deserialization path) do not fire it, so the count is REAL compiles.
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class RecompileCounter:
    """Cumulative backend-compile count + seconds, fed by jax.monitoring.
    Thread-safe: compiles can happen on the producer thread too."""

    def __init__(self):
        self._lock = make_lock("telemetry.mfu.counter")
        self.count = 0
        self.seconds = 0.0

    def _on_event(self, name: str, secs: float, **kw) -> None:
        if name == BACKEND_COMPILE_EVENT:
            with self._lock:
                self.count += 1
                self.seconds += float(secs)


_COUNTER: Optional[RecompileCounter] = None
_COUNTER_LOCK = make_lock("telemetry.mfu.registry")


def recompile_counter() -> RecompileCounter:
    """The process-global recompile counter, installing its jax.monitoring
    listener on first use. Global because the listener registry is global
    (listeners cannot be unregistered individually) — one listener serves
    every trainer in the process, all reading the same cumulative series."""
    global _COUNTER
    with _COUNTER_LOCK:
        if _COUNTER is None:
            counter = RecompileCounter()
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                counter._on_event
            )
            _COUNTER = counter
    return _COUNTER
