"""Cross-thread span tracer + flight recorder (docs/OBSERVABILITY.md).

The async pipeline (orchestrator producer thread, checkpoint I/O, reward
dispatch, the trainer loop itself) made "where did this update's 40 s go"
unanswerable from the flat `time/*_s` scalars: a phase split cannot show
that the producer's generation for rollout k+1 ran UNDER update k's
backward, or that a sentinel trip landed mid-checkpoint. The tracer records
named spans with correlation args (policy_version, rollout_index,
staleness, step) on per-thread tracks and writes them as Chrome
trace-event JSON (`trace.json`) — load it at https://ui.perfetto.dev or
chrome://tracing and the producer/trainer overlap is a picture, not an
inference.

Three consumers share the one event stream:

- `write_trace(path)` — the full bounded event buffer as a Chrome
  trace-event file (`{"traceEvents": [...]}`; complete "X" events with
  `ts`/`dur` in µs, thread-name "M" metadata, counter "C" events).
- the **flight recorder** — a ring of the most recent completed spans plus
  the latest counter snapshots and the per-thread in-flight span stacks;
  `dump_blackbox()` writes it as `blackbox_<step>.json` when something
  dies (sentinel trip, producer failure, SIGTERM) so the post-mortem has
  "what was every thread doing" even when the run never reached
  `write_trace`.
- live counters (`counter()`) — queue depth, staleness — snapshotted into
  both sinks.

Clock: `time.perf_counter_ns()` relative to tracer construction —
monotonic and process-wide consistent across threads (CLOCK_MONOTONIC), so
cross-thread span overlap is real overlap; an NTP step cannot reorder
tracks (the same reason PhaseTimer uses perf_counter).

Disabled (the default) the tracer is a cheap no-op: `span()` yields an
empty dict without touching the lock, `add_complete`/`instant`/`counter`
return immediately — the enabled/disabled bench A/B is the acceptance
gate for keeping the instrumentation inline unconditionally.

jax-free on purpose: unit-testable (and bench-parent-importable) with
plain Python threads.
"""

from __future__ import annotations

import collections
import contextlib
import json
import math
import os
import threading

from nanorlhf_tpu.analysis.lockorder import make_lock
import time
from typing import Optional

# synthetic tids for logical tracks (work that happens ON some host thread
# but belongs to one conceptual lane — checkpoint I/O, reward dispatch,
# async rollout readiness). Real thread idents are huge (pthread
# addresses); small constants cannot collide with them in practice, and
# each track only ever receives sequential spans from one call site, so
# per-tid nesting stays valid.
_TRACK_TID_BASE = 1


def _jsonable(v):
    """Span args must be JSON scalars — numpy scalars and exotic objects
    are coerced rather than poisoning the trace file at write time."""
    if isinstance(v, (bool, int, str)) or v is None:
        return v
    if isinstance(v, float):
        return v if math.isfinite(v) else str(v)
    try:
        f = float(v)  # numpy scalar
        return f if math.isfinite(f) else str(v)
    except (TypeError, ValueError):
        return str(v)


class SpanTracer:
    """Thread-safe span/counter recorder with a bounded event buffer and a
    flight-recorder ring. One instance per trainer; every subsystem
    (orchestrator, checkpoint manager, reward dispatch) records into it."""

    def __init__(self, enabled: bool = False, max_events: int = 200_000,
                 ring_len: int = 256):
        self.enabled = bool(enabled)
        self.dropped = 0          # events past max_events (telemetry/spans_dropped)
        self._max_events = int(max_events)
        self._lock = make_lock("telemetry.tracer")
        self._events: list[dict] = []
        self._ring: collections.deque = collections.deque(maxlen=int(ring_len))
        self._counters: dict[str, float] = {}
        # per-thread stacks of IN-FLIGHT spans — the flight recorder's
        # "what was every thread doing at the moment of death" view
        self._open: dict[int, list[dict]] = {}
        self._thread_names: dict[int, str] = {}
        self._tracks: dict[str, int] = {}
        self._pid = os.getpid()
        self._t0_ns = time.perf_counter_ns()
        # wall-clock of the trace epoch, so blackbox/trace timestamps can be
        # correlated with metrics.jsonl rows (which carry time.time())
        self.epoch_unix = time.time()

    # ------------------------------------------------------------------ #
    # clock / track plumbing
    # ------------------------------------------------------------------ #

    def now_us(self) -> float:
        """µs since tracer construction (monotonic, cross-thread)."""
        return (time.perf_counter_ns() - self._t0_ns) / 1e3

    def _tid(self, track: Optional[str]) -> int:
        if track is None:
            t = threading.current_thread()
            self._thread_names.setdefault(t.ident, t.name)
            return t.ident
        with self._lock:
            tid = self._tracks.get(track)
            if tid is None:
                tid = self._tracks[track] = _TRACK_TID_BASE + len(self._tracks)
        return tid

    def _record(self, ev: dict) -> None:
        # caller does NOT hold the lock
        with self._lock:
            self._ring.append(ev)
            if len(self._events) < self._max_events:
                self._events.append(ev)
            else:
                self.dropped += 1

    # ------------------------------------------------------------------ #
    # recording API
    # ------------------------------------------------------------------ #

    @contextlib.contextmanager
    def span(self, name: str, track: Optional[str] = None, **args):
        """Record `name` over the with-block on the calling thread's track
        (or the named logical `track`). Yields the mutable args dict so
        correlation ids learned mid-span (rollout_index after the fetch)
        can be attached before the span closes."""
        if not self.enabled:
            yield {}
            return
        args = {k: _jsonable(v) for k, v in args.items()}
        tid = self._tid(track)
        ident = threading.get_ident()
        t0 = self.now_us()
        open_rec = {"name": name, "ts": t0, "tid": tid, "args": args}
        with self._lock:
            self._open.setdefault(ident, []).append(open_rec)
        try:
            yield args
        finally:
            t1 = self.now_us()
            with self._lock:
                stack = self._open.get(ident)
                if stack and stack[-1] is open_rec:
                    stack.pop()
            self._record({
                "name": name, "ph": "X", "ts": t0, "dur": t1 - t0,
                "pid": self._pid, "tid": tid,
                "args": {k: _jsonable(v) for k, v in args.items()},
            })

    def add_complete(self, name: str, ts_us: float, dur_us: float,
                     track: Optional[str] = None, **args) -> None:
        """Record an already-measured span (explicit start/duration in this
        tracer's clock, see now_us()) — for windows whose end is observed on
        a different thread than their start (async rollout readiness) or
        whose body cannot be a with-block (the trainer's per-update span,
        which must survive `continue` on sentinel rollback)."""
        if not self.enabled:
            return
        self._record({
            "name": name, "ph": "X", "ts": float(ts_us),
            "dur": max(0.0, float(dur_us)), "pid": self._pid,
            "tid": self._tid(track),
            "args": {k: _jsonable(v) for k, v in args.items()},
        })

    def add_async(self, name: str, ts_us: float, dur_us: float, aid,
                  track: str = "async", **args) -> None:
        """Record an already-measured window as a Chrome ASYNC event pair
        (ph "b"/"e", keyed by `aid`): unlike complete "X" spans, async
        windows on one track may legitimately OVERLAP — e.g. serial /
        rollout_ahead generation readiness windows, where rollout k+1's
        dispatch precedes rollout k's device-ready. Perfetto draws each id
        on its own sub-row; the schema validator exempts async events from
        the per-track nesting check for the same reason."""
        if not self.enabled:
            return
        tid = self._tid(track)
        base = {
            "name": name, "cat": track, "id": str(aid), "pid": self._pid,
            "tid": tid,
            "args": {k: _jsonable(v) for k, v in args.items()},
        }
        self._record({**base, "ph": "b", "ts": float(ts_us)})
        self._record({**base, "ph": "e", "args": {},
                      "ts": float(ts_us) + max(0.0, float(dur_us))})

    def instant(self, name: str, track: Optional[str] = None, **args) -> None:
        """Zero-duration marker (sentinel trip, quarantine skip)."""
        if not self.enabled:
            return
        self._record({
            "name": name, "ph": "i", "ts": self.now_us(), "s": "t",
            "pid": self._pid, "tid": self._tid(track),
            "args": {k: _jsonable(v) for k, v in args.items()},
        })

    def counter(self, name: str, value) -> None:
        """Latest-value counter (queue depth, staleness): snapshotted into
        the blackbox and emitted as a Chrome "C" event so Perfetto draws
        the series under the tracks."""
        if not self.enabled:
            return
        v = _jsonable(value)
        v = v if isinstance(v, (int, float)) else 0.0
        with self._lock:
            self._counters[name] = float(v)
        self._record({
            "name": name, "ph": "C", "ts": self.now_us(),
            "pid": self._pid, "tid": self._tid("counters"),
            "args": {"value": float(v)},
        })

    # ------------------------------------------------------------------ #
    # sinks
    # ------------------------------------------------------------------ #

    def _metadata_events(self, thread_names: dict, tracks: dict) -> list[dict]:
        evs = [{
            "name": "process_name", "ph": "M", "ts": 0.0, "pid": self._pid,
            "tid": 0, "args": {"name": "nanorlhf_tpu"},
        }]
        for tid, tname in sorted(thread_names.items()):
            evs.append({
                "name": "thread_name", "ph": "M", "ts": 0.0,
                "pid": self._pid, "tid": tid, "args": {"name": tname},
            })
        for track, tid in sorted(tracks.items(), key=lambda kv: kv[1]):
            evs.append({
                "name": "thread_name", "ph": "M", "ts": 0.0,
                "pid": self._pid, "tid": tid, "args": {"name": track},
            })
        return evs

    def trace_events(self) -> list[dict]:
        """Snapshot of metadata + recorded events (Chrome trace order-free).
        The name/track dicts are copied under the lock: the producer thread
        is typically still alive when the end-of-train write runs, and
        iterating a dict another thread is inserting into raises."""
        with self._lock:
            events = list(self._events)
            thread_names = dict(self._thread_names)
            tracks = dict(self._tracks)
        return self._metadata_events(thread_names, tracks) + events

    def write_trace(self, path: str) -> Optional[str]:
        """Write the Chrome trace-event file; returns the path (None when
        disabled). Safe to call repeatedly — each call rewrites the full
        buffered history, so a trace exists after every train() call, not
        only after close()."""
        if not self.enabled:
            return None
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        payload = {
            "traceEvents": self.trace_events(),
            "displayTimeUnit": "ms",
            "otherData": {"trace_epoch_unix": self.epoch_unix,
                          "spans_dropped": self.dropped},
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return path

    def snapshot_blackbox(self, step: int, reason: str,
                          extra: Optional[dict] = None) -> dict:
        """The flight-recorder payload: recent completed spans, per-thread
        in-flight spans, counter snapshots."""
        with self._lock:
            spans = list(self._ring)
            open_spans = [
                {**rec, "args": dict(rec["args"]),
                 "thread": self._thread_names.get(ident, str(ident))}
                for ident, stack in self._open.items() for rec in stack
            ]
            counters = dict(self._counters)
        return {
            "reason": reason,
            "step": int(step),
            "unix_time": time.time(),
            "trace_epoch_unix": self.epoch_unix,
            "now_us": self.now_us(),
            "counters": counters,
            "open_spans": open_spans,
            "spans": spans,
            "spans_dropped": self.dropped,
            "extra": extra or {},
        }

    def dump_blackbox(self, directory: str, step: int, reason: str,
                      extra: Optional[dict] = None) -> Optional[str]:
        """Write `blackbox_<step>.json` (flight-recorder dump) — called by
        the resilience layer on sentinel trip, producer failure, and
        SIGTERM preemption. None when disabled."""
        if not self.enabled:
            return None
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, f"blackbox_{int(step)}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.snapshot_blackbox(step, reason, extra), f)
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------- #
# Chrome trace-event schema validation (shared by tests + the CI smoke)
# ---------------------------------------------------------------------- #

_REQUIRED_KEYS = ("ph", "ts", "pid", "tid")


def _laminar_errors(intervals: list[tuple[float, float, str]]) -> list[str]:
    """Spans on one track must NEST (a laminar interval family): any two
    either disjoint or one inside the other. `intervals` = (ts, dur, name)."""
    errs = []
    eps = 1e-3  # µs: same-µs boundary ties are not violations
    stack: list[tuple[str, float]] = []  # (name, end)
    for ts, dur, name in sorted(intervals, key=lambda x: (x[0], -x[1])):
        end = ts + dur
        while stack and stack[-1][1] <= ts + eps:
            stack.pop()
        if stack and end > stack[-1][1] + eps:
            errs.append(
                f"span {name!r} [{ts:.1f}, {end:.1f}] partially overlaps "
                f"enclosing span {stack[-1][0]!r} (ends {stack[-1][1]:.1f})"
            )
        stack.append((name, end))
    return errs


def validate_trace_events(events) -> list[str]:
    """Return a list of schema violations (empty == valid): every event
    carries ph/ts/pid/tid, ts/dur are finite (no NaN durations), complete
    spans on one (pid, tid) track nest."""
    errors: list[str] = []
    if not isinstance(events, list) or not events:
        return ["traceEvents missing or empty"]
    by_track: dict[tuple, list[tuple[float, float, str]]] = {}
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            errors.append(f"event {i}: not an object")
            continue
        missing = [k for k in _REQUIRED_KEYS if k not in ev]
        if missing:
            errors.append(f"event {i} ({ev.get('name')!r}): missing {missing}")
            continue
        ts = ev["ts"]
        if not isinstance(ts, (int, float)) or not math.isfinite(ts):
            errors.append(f"event {i} ({ev.get('name')!r}): bad ts {ts!r}")
            continue
        if ev["ph"] == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or not math.isfinite(dur) \
                    or dur < 0:
                errors.append(
                    f"event {i} ({ev.get('name')!r}): bad dur {dur!r}"
                )
                continue
            by_track.setdefault((ev["pid"], ev["tid"]), []).append(
                (float(ts), float(dur), str(ev.get("name")))
            )
    for (pid, tid), ivs in sorted(by_track.items()):
        for e in _laminar_errors(ivs):
            errors.append(f"track pid={pid} tid={tid}: {e}")
    return errors


def validate_trace_file(path: str) -> list[str]:
    """Validate a trace.json on disk (the tier-1 CI telemetry gate)."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [f"unreadable trace {path}: {type(e).__name__}: {e}"]
    if not isinstance(payload, dict):
        return ["trace root is not an object"]
    return validate_trace_events(payload.get("traceEvents"))
