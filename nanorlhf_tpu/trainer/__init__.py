from nanorlhf_tpu.trainer.config import RLConfig, AlgoName
from nanorlhf_tpu.trainer.trainer import RLTrainer

__all__ = ["RLConfig", "AlgoName", "RLTrainer"]
