"""Length-bucketed dynamic batching under static XLA shapes.

The reference's r1 trainer packs samples into padding-waste-aware buckets with
a memory-budget model `max_len × (count+1) ≤ budget`
(`/root/reference/examples/r1-v0/grpo_r1_trainer.py:410-435`) and de-pads
between phases (`:571-582`). On GPU every bucket shape is free; under XLA each
new shape is a compile. The TPU twist here: bucket *boundary* lengths and row
counts are rounded up to a small menu (powers of two), so across updates the
compile cache stays warm while padding waste stays bounded (< 2×, typically
~1.3×) — design inversion #3 of SURVEY.md §7.
"""

from __future__ import annotations

import numpy as np


def create_batches(lengths, max_batch_memory_size: int) -> list[list[int]]:
    """Greedy length-sorted packing: fill a bucket while
    max(cur_len, next_len) × (count+1) ≤ budget.

    Exact packing semantics of `_create_batches`
    (`grpo_r1_trainer.py:410-435`); returns index lists into `lengths`.
    Dispatches to the C++ implementation (native/bucketing.cpp) when the
    library is available; tests pin both paths identical.
    """
    from nanorlhf_tpu import native

    out = native.create_batches_native(lengths, max_batch_memory_size)
    if out is not None:
        return out
    lengths = np.asarray(lengths)
    order = np.argsort(lengths, kind="stable")
    batches: list[list[int]] = []
    current: list[int] = []
    cur_len = 0
    for idx in order:
        sample_len = int(lengths[idx])
        future = max(cur_len, sample_len) * (len(current) + 1)
        if future > max_batch_memory_size and current:
            batches.append(current)
            current = []
            cur_len = 0
        current.append(int(idx))
        cur_len = max(cur_len, sample_len)
    if current:
        batches.append(current)
    return batches


def shape_menu(max_value: int, min_value: int = 16) -> list[int]:
    """Powers of two from min_value up, capped at (and including) max_value."""
    menu = []
    v = min_value
    while v < max_value:
        menu.append(v)
        v *= 2
    menu.append(max_value)
    return menu


def round_up_to_menu(value: int, menu: list[int]) -> int:
    """Smallest menu entry ≥ value (menu assumed sorted ascending)."""
    for m in menu:
        if m >= value:
            return m
    return menu[-1]


def depad_queries(queries: np.ndarray, pad_id: int, menu: list[int]) -> np.ndarray:
    """Strip the batch's common left-pad, menu-rounded — the r1 de-padding
    move (`grpo_r1_trainer.py:571-574`) as a shared host-side helper. Pure
    numpy: the batch is already on the host and the result is one slice; no
    device round-trip belongs on the rollout hot path."""
    nz = queries != pad_id
    q_pad = np.where(nz.any(axis=1), nz.argmax(axis=1), queries.shape[1])
    ctx_needed = queries.shape[1] - int(q_pad.min())
    ctx = min(round_up_to_menu(max(ctx_needed, 1), menu), queries.shape[1])
    return queries[:, queries.shape[1] - ctx:]


def pad_rows(arrays: dict, n_rows: int, fill: dict):
    """Pad each [B, ...] array in `arrays` to n_rows with fill values.

    Dummy rows are fully masked downstream, so their content only has to be
    shape-compatible (e.g. all-pad token rows, zero advantages).
    """
    out = {}
    for k, v in arrays.items():
        v = np.asarray(v)
        if v.shape[0] == n_rows:
            out[k] = v
            continue
        pad_shape = (n_rows - v.shape[0],) + v.shape[1:]
        filler = np.full(pad_shape, fill.get(k, 0), dtype=v.dtype)
        out[k] = np.concatenate([v, filler], axis=0)
    return out
