"""Orbax checkpointing with the reference's rotation + best-metric semantics.

Reference behavior to preserve (SURVEY.md §5.4, `/root/reference/GRPO/
grpo_trainer.py:321-404`): checkpoint every `save_steps`; rotate to
`save_total_limit`; `load_best_model_at_end` keyed on a `..._old` metric,
where the `_old` suffix means the metric describes the *previous* checkpoint —
so the best checkpoint is resolved one save back (`:374-382`). The best
checkpoint is never rotated away.

TPU-native mechanics: Orbax writes the sharded param/optimizer trees directly
from HBM (async-capable); PRNG key and step go in a JSON trainer state.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import shutil

import jax
import numpy as np

from nanorlhf_tpu.resilience.faults import InjectedFault
from nanorlhf_tpu.resilience.retry import retry_with_backoff


class CheckpointManager:
    def __init__(self, output_dir: str, save_total_limit: int = 8,
                 greater_is_better: bool = True, async_save: bool = True,
                 io_retries: int = 2, retry_backoff: float = 0.5,
                 faults=None, tracer=None):
        self.output_dir = os.path.abspath(output_dir)
        self.save_total_limit = save_total_limit
        self.greater_is_better = greater_is_better
        # I/O hardening (docs/RESILIENCE.md): io_retries EXTRA attempts with
        # exponential backoff around each save/restore; retry_count feeds
        # the resilience/ckpt_retries metric. `faults` is a
        # resilience.FaultInjector arming ckpt.save / ckpt.restore.
        self.io_retries = io_retries
        self.retry_backoff = retry_backoff
        self.retry_count = 0
        # restore() falls back to older intact checkpoints when the
        # requested one is corrupt/torn (ckpt.corrupt site): fallback_count
        # feeds resilience/ckpt_fallbacks, last_restored_step tells the
        # resume path which step actually loaded.
        self.fallback_count = 0
        self.last_restored_step: int | None = None
        self._faults = faults
        # telemetry.SpanTracer (docs/OBSERVABILITY.md): save/restore get
        # spans on a dedicated "ckpt" track — checkpoint I/O stalls are a
        # classic silent step-time eater
        self._tracer = tracer
        os.makedirs(self.output_dir, exist_ok=True)
        self._ckpt_dirs: list[str] = self._existing()
        # metric history: step -> metric measured ON that step's saved policy
        # (arrives one save later under the `_old` convention). Persisted to
        # disk so best-checkpoint protection and load-best survive a resume.
        self._metric_by_step: dict[int, float] = {}
        self._last_saved_step: int | None = None
        self._load_metric_history()
        import orbax.checkpoint as ocp

        # async: save() blocks only for the device→host copy (so the update
        # step's buffer donation can't race the write), then streams to disk
        # while training continues — the save disappears from the step wall.
        # Every read/rotate path waits for the in-flight write first.
        self._ckptr = (
            ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
            if async_save else ocp.PyTreeCheckpointer()
        )
        # exit barrier: a process that returns from main right after the
        # last step would otherwise abandon the in-flight async write — a
        # corrupt checkpoint the next resume has to clamp away. close()
        # unregisters (idempotent to call wait twice anyway).
        atexit.register(self.wait)

    def _span(self, name: str, **args):
        """Trace span on the "ckpt" track; nullcontext when untraced."""
        if self._tracer is None or not self._tracer.enabled:
            return contextlib.nullcontext({})
        return self._tracer.span(name, track="ckpt", **args)

    def wait(self):
        """Block until any in-flight async save has committed to disk."""
        fn = getattr(self._ckptr, "wait_until_finished", None)
        if fn is not None:
            fn()

    def _absorb_failed_save(self):
        """Flush the in-flight async write, ABSORBING a deferred failure
        from the previous save — the most likely place a real transient
        checkpoint I/O error surfaces. The failed checkpoint never
        committed (atomic tree/ rename), so absorption only has to repair
        the bookkeeping: count the failure, drop the phantom step from
        dirs/metrics/last-saved (else best_step() can protect — or
        metric_old be attributed to — a checkpoint with no tree on disk),
        and remove the partial dir. Every save/recovery path goes through
        this; the END-of-run flush (train()'s final ckpt.wait) stays raw so
        a failed FINAL save still surfaces."""
        try:
            self.wait()
        except Exception as e:
            self.retry_count += 1
            step = self._last_saved_step
            path = os.path.join(self.output_dir, f"checkpoint-{step}")
            if not os.path.isdir(os.path.join(path, "tree")):
                if path in self._ckpt_dirs:
                    self._ckpt_dirs.remove(path)
                shutil.rmtree(path, ignore_errors=True)
                self._metric_by_step.pop(step, None)
                committed = [int(d.rsplit("-", 1)[1]) for d in self._ckpt_dirs]
                self._last_saved_step = max(committed) if committed else None
                self._save_metric_history()
            print(f"[checkpoint] previous async save failed "
                  f"({type(e).__name__}: {e}) — checkpoint {step} not "
                  f"committed; continuing")

    @property
    def _history_path(self) -> str:
        return os.path.join(self.output_dir, "best_metric_history.json")

    def _load_metric_history(self):
        if os.path.exists(self._history_path):
            with open(self._history_path) as f:
                data = json.load(f)
            self._metric_by_step = {int(k): v for k, v in data.get("metrics", {}).items()}
            self._last_saved_step = data.get("last_saved_step")
        # history is written while the async tree write streams, so a crash
        # mid-save can leave it claiming a checkpoint that never committed —
        # clamp to what's actually on disk, else the next save's metric_old
        # gets attributed to the phantom step (and best/rotation follow it)
        committed = {int(d.rsplit("-", 1)[1]) for d in self._ckpt_dirs}
        latest = max(committed) if committed else None
        if self._last_saved_step is not None and \
                self._last_saved_step not in committed:
            self._last_saved_step = latest
        self._metric_by_step = {
            k: v for k, v in self._metric_by_step.items() if k in committed
        }

    def _save_metric_history(self):
        with open(self._history_path, "w") as f:
            json.dump(
                {"metrics": self._metric_by_step,
                 "last_saved_step": self._last_saved_step}, f,
            )

    def _existing(self) -> list[str]:
        if not os.path.isdir(self.output_dir):
            return []
        # only COMMITTED checkpoints count: orbax finalizes the async tree
        # write with an atomic tmp-dir rename, so `tree/` exists iff the
        # write committed — a process that died mid-save leaves a dir this
        # filter (and therefore latest_step()/resume) ignores
        dirs = [
            d for d in os.listdir(self.output_dir)
            if d.startswith("checkpoint-")
            and os.path.isdir(os.path.join(self.output_dir, d, "tree"))
        ]
        return sorted(
            (os.path.join(self.output_dir, d) for d in dirs),
            key=lambda p: int(p.rsplit("-", 1)[1]),
        )

    def save(self, step: int, params, opt_state=None, rng_key=None,
             metric_old: float | None = None, extra_state: dict | None = None,
             value_params=None):
        """Save a checkpoint. `metric_old`, when given, scores the *previous*
        checkpoint (the `_old` semantics) and is recorded against it.
        `value_params` adds the PPO value model (`PPO/ppo_trainer.py:413-416`)."""
        if metric_old is not None and self._last_saved_step is not None:
            self._metric_by_step[self._last_saved_step] = float(metric_old)

        # previous async write must commit before we touch disk (deferred
        # failures absorbed — see _absorb_failed_save)
        self._absorb_failed_save()
        path = os.path.join(self.output_dir, f"checkpoint-{step}")
        tree = {"params": params}
        if opt_state is not None:
            tree["opt_state"] = opt_state
        if value_params is not None:
            tree["value"] = value_params
        state = {"step": step}
        if rng_key is not None:
            import jax.numpy as jnp

            typed = jnp.issubdtype(rng_key.dtype, jax.dtypes.prng_key)
            state["rng_key"] = np.asarray(
                jax.random.key_data(rng_key) if typed else rng_key
            ).tolist()
            state["rng_key_typed"] = bool(typed)
        state.update(extra_state or {})

        def attempt():
            # a failed attempt may have dispatched a partial async write —
            # flush it (best effort) and clear the target before retrying,
            # or the retry races its own predecessor's tmp-dir rename
            if self._faults is not None:
                self._faults.fire("ckpt.save")
            shutil.rmtree(path, ignore_errors=True)
            self._ckptr.save(os.path.join(path, "tree"), tree)
            with open(os.path.join(path, "trainer_state.json"), "w") as f:
                json.dump(state, f)

        def on_retry(_attempt, _exc):
            self.retry_count += 1
            try:
                self.wait()
            except Exception:
                pass  # the failed write's deferred error must not mask retry

        # the span covers the BLOCKING part of an async save (device→host
        # copy + write dispatch); the streaming tail runs off-thread and
        # surfaces in the NEXT save's wait if it stalls
        with self._span("ckpt.save", step=step):
            retry_with_backoff(
                attempt, attempts=self.io_retries + 1,
                backoff_base=self.retry_backoff, on_retry=on_retry,
            )
        if path in self._ckpt_dirs:  # re-saving a step after resume
            self._ckpt_dirs.remove(path)
        self._ckpt_dirs.append(path)
        self._last_saved_step = step
        self._save_metric_history()
        self._rotate()
        return path

    def best_step(self) -> int | None:
        if not self._metric_by_step:
            return None
        pick = max if self.greater_is_better else min
        return pick(self._metric_by_step, key=self._metric_by_step.get)

    def _rotate(self):
        # never delete the best checkpoint, nor the newest one (its metric
        # arrives one save later under the `_old` convention, so it may still
        # become best — and it is the resume point)
        keep_always = set()
        best = self.best_step()
        if best is not None:
            keep_always.add(os.path.join(self.output_dir, f"checkpoint-{best}"))
        if self._ckpt_dirs:
            keep_always.add(self._ckpt_dirs[-1])
        while len(self._ckpt_dirs) > self.save_total_limit:
            for d in self._ckpt_dirs:
                if d not in keep_always:
                    shutil.rmtree(d, ignore_errors=True)
                    self._ckpt_dirs.remove(d)
                    break
            else:
                break  # everything is protected

    def restore(self, step: int, like, fallback: bool = True):
        """Restore the pytree saved at `step`, matching the structure/shardings
        of `like` (pass {"params": params_template, ...}).

        Restored leaves are normalized to match the TEMPLATE's placement —
        orbax hands back arrays that only LOOK like the template's:

        - a template leaf on a single default device (optax scalar state
          like Adam's `count`, produced UNCOMMITTED by `jit(optimizer.init)`
          and therefore auto-replicable by later multi-device jits) comes
          back from orbax COMMITTED to that device — a donating jitted
          update then rejects the mixed-device argument list ("Received
          incompatible devices"). Round-tripping through host restores the
          uncommitted placement; these leaves are scalars, so the copy is
          free;
        - every other leaf is device_put onto the template's sharding (when
          it differs) and then COPIED into a fresh backend-native buffer:
          restored arrays are backed by orbax/tensorstore-owned storage,
          and donating one into the jitted update (which every training
          step after resume does) segfaults the CPU client — observed as a
          hard crash one-to-two updates after resume, serial and
          orchestrated alike.

        Corrupt/torn checkpoints (the `ckpt.corrupt` site, or an organic
        read failure that survives every retry) do not fail the run: with
        `fallback=True` (default) restore walks back to the newest EARLIER
        committed checkpoint, bumping `fallback_count`
        (resilience/ckpt_fallbacks) once per skipped step and recording the
        step that actually loaded in `last_restored_step` — resume callers
        must adopt it (and truncate the corrupt newer trajectory) or their
        trainer_state read diverges from the restored tree."""
        self.wait()
        candidates = [step]
        if fallback:
            candidates += [
                s for s in (
                    int(d.rsplit("-", 1)[1]) for d in reversed(self._existing())
                ) if s < step
            ]
        last_exc: Exception | None = None
        restored = None
        for i, cand in enumerate(candidates):
            path = os.path.join(self.output_dir, f"checkpoint-{cand}", "tree")

            def attempt(path=path):
                if self._faults is not None:
                    self._faults.fire("ckpt.restore")
                return self._ckptr.restore(path, item=like)

            def on_retry(_attempt, _exc):
                self.retry_count += 1

            # ckpt.corrupt models the read returning garbage, not erroring —
            # retrying the same bytes can't help, so it fires once per
            # candidate OUTSIDE the retry loop and sends us straight to the
            # next older checkpoint
            corrupt = None
            if self._faults is not None:
                try:
                    corrupt = self._faults.fire("ckpt.corrupt")
                except InjectedFault as e:
                    corrupt = e
            if corrupt is None:
                try:
                    with self._span("ckpt.restore", step=cand):
                        restored = retry_with_backoff(
                            attempt, attempts=self.io_retries + 1,
                            backoff_base=self.retry_backoff, on_retry=on_retry,
                        )
                except Exception as e:
                    last_exc = e
            if restored is not None:
                self.fallback_count += i
                self.last_restored_step = cand
                if i:
                    print(f"[checkpoint] checkpoint {step} corrupt/unreadable "
                          f"— fell back to checkpoint {cand}")
                break
        if restored is None:
            if last_exc is not None:
                raise last_exc
            raise InjectedFault(
                "ckpt.corrupt", detail=f"no intact checkpoint at or below {step}"
            )
        import jax.numpy as jnp
        from jax.sharding import SingleDeviceSharding

        def replace(r, l):
            ls = getattr(l, "sharding", None)
            if ls is None or not hasattr(r, "sharding"):
                return r
            if isinstance(ls, SingleDeviceSharding):
                return jnp.asarray(np.asarray(r))
            if r.sharding != ls:
                r = jax.device_put(r, ls)
            return jnp.copy(r)  # fresh XLA buffer — safe to donate later

        return jax.tree.map(replace, restored, like)

    def truncate_after(self, step: int):
        """Drop checkpoints and metric history newer than `step` — called on
        resume-from-an-earlier-step so the abandoned trajectory's saves can't
        hijack latest_step()/best_step() or misattribute the next metric_old."""
        self._absorb_failed_save()
        for d in list(self._ckpt_dirs):
            if int(d.rsplit("-", 1)[1]) > step:
                shutil.rmtree(d, ignore_errors=True)
                self._ckpt_dirs.remove(d)
        self._metric_by_step = {
            k: v for k, v in self._metric_by_step.items() if k <= step
        }
        self._last_saved_step = step
        self._save_metric_history()

    def load_trainer_state(self, step: int) -> dict:
        with open(
            os.path.join(self.output_dir, f"checkpoint-{step}", "trainer_state.json")
        ) as f:
            return json.load(f)

    def latest_step(self) -> int | None:
        self._absorb_failed_save()  # sentinel rollback calls this mid-run
        dirs = self._existing()
        return int(dirs[-1].rsplit("-", 1)[1]) if dirs else None

    def close(self):
        """Flush the in-flight save. Call before process exit OR before a
        successor manager opens the same output_dir: an async write
        abandoned at teardown is a corrupt checkpoint, and to a successor
        an unflushed save is indistinguishable from a crash mid-save (its
        step gets clamped out of the metric history). `RLTrainer.train()`
        waits on return and `RLTrainer.close()` calls this. The atexit
        barrier registered at construction covers processes that exit
        without closing; unregister it here so a closed manager can't keep
        the whole tree alive through interpreter shutdown."""
        self.wait()
        atexit.unregister(self.wait)
