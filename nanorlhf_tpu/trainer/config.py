"""Layered dataclass config — "ALL setting is on the file you run".

Mirrors the reference's config surface: a PPOConfig-style dataclass holding
the TRL-inherited fields every launcher sets (`/root/reference/GRPO/
grpo.py:86-155`, SURVEY.md §5.6) plus algorithm-specific fields, extended
with the mesh/sharding knobs the TPU runtime needs. The derived batch-size
hierarchy reproduces `GRPOTrainer.__init__` exactly
(`/root/reference/GRPO/grpo_trainer.py:216-247`):

    local_batch_size = per_device_train_batch_size
                       × gradient_accumulation_steps × num_mini_batches
    batch_size       = local_batch_size × world_size (= mesh data axes)
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import Optional

from nanorlhf_tpu.ops.masking import exact_div
from nanorlhf_tpu.parallel.mesh import MeshConfig


class AlgoName(str, enum.Enum):
    PPO = "ppo"
    GRPO = "grpo"
    RLOO = "rloo"
    REMAX = "remax"
    REINFORCE = "reinforce"
    RAFT = "raft"


@dataclasses.dataclass
class RLConfig:
    # ---- experiment ----
    exp_name: str = "run"
    seed: int = 1
    output_dir: str = "output"
    algo: AlgoName = AlgoName.GRPO

    # ---- models ----
    sft_model_path: str = ""
    reward_model_path: str = ""

    # ---- data ----
    train_dataset_name: str = "Anthropic/hh-rlhf"   # (`GRPO/grpo.py:101`)
    train_dataset_split: str = "train"              # (`GRPO/grpo.py:102`)
    # tokenized-corpus cache dir (data/token_cache.py — the Arrow-cache role
    # `dataset.map` plays for the reference); None disables
    dataset_cache_dir: Optional[str] = None

    # ---- rollout / sampling ----
    response_length: int = 1500          # max new tokens (`GRPO/grpo.py:125`)
    temperature: float = 0.9
    top_p: float = 0.95
    sample_n: int = 4                    # grpo_sample_N / rloo_sample_N / raft_sample_K
    stop_token: str = "eos"
    missing_eos_penalty: Optional[float] = None
    # top-k pre-trim for rollout nucleus sampling (SamplingParams.top_k):
    # 64 keeps the decode step off the full-vocab sort and is exact whenever
    # the 0.95-nucleus fits in 64 tokens — true for instruction-tuned models
    # at production temperatures. 0 = exact full-vocab nucleus, matching the
    # reference's untruncated vLLM top_p (`GRPO/grpo_trainer.py:127`) —
    # the right default for BASE-model policies at high temperature (the
    # r1-zero launcher sets it), where the nucleus can exceed any fixed k
    # early in training and truncation silently narrows exploration
    # (VERDICT r3 #6).
    rollout_top_k: int = 64
    # approx_max_k for the pre-trim (hardware-native O(V); recall 0.99) vs
    # exact lax.top_k (full-vocab sort). Ignored when rollout_top_k=0.
    rollout_approx_top_k: bool = True
    # n>1 rollouts prefill each prompt once and fan the prompt KV out to its
    # N samples (vLLM prefix-sharing analogue; token streams are identical
    # to the repeat path, test-pinned). Off = repeat every prompt ×N before
    # prefill (ablation/debug).
    rollout_shared_prefill: bool = True
    # >0: draft-free speculative rollout decode (sampler/speculative.py,
    # docs/DECODE_ANALYSIS.md): an n-gram/prompt-lookup drafter proposes
    # this many tokens per row from the row's own prompt+output buffer and
    # one batched `decode_verify` forward scores all k+1 candidates —
    # amortizing the HBM-bound per-step weight/cache stream over every
    # accepted token. Greedy rollouts stay bit-exact; sampled rollouts are
    # distribution-exact (rejection sampling). Best on self-repetitive
    # corpora (R1-style math: restated problem text, \boxed{} templates);
    # worst case (acceptance ~0) pays ~one verify forward per token.
    # Per-update acceptance lands in rollout/draft_acceptance /
    # rollout/accepted_per_step (docs/METRICS.md). 0 = off (the monolithic
    # loop, bit-for-bit untouched). Incompatible with
    # rollout_compaction_segments > 0 — `generate` raises (compaction's
    # row gather assumes step-aligned rows).
    rollout_spec_k: int = 0
    # n-gram context the drafter matches on (rollout_spec_k > 0 only)
    rollout_spec_ngram: int = 3

    # ---- batch hierarchy ----
    # total_episodes=None → num_train_epochs × dataset size, resolved by the
    # trainer (`GRPO/grpo_trainer.py:216-217`)
    total_episodes: Optional[int] = 10000
    num_train_epochs: float = 1.0
    per_device_train_batch_size: int = 4
    gradient_accumulation_steps: int = 8
    num_mini_batches: int = 16
    num_ppo_epochs: int = 1
    local_rollout_forward_batch_size: Optional[int] = None  # None → memory formula
    # opt-in: reuse the sampler's per-token logprobs as the rollout-policy
    # logprobs, skipping the policy half of the scoring pass (the ref pass
    # still runs). Decode-vs-scoring numerics make epoch-1 ratios deviate
    # from exactly 1; the drift is logged as sampler_capture/ratio_drift_new.
    sampler_logprob_capture: bool = False
    # opt-in PipelineRL-style overlap: the rollout for update k+1 is
    # DISPATCHED (async) before the host-side decode/reward/assembly of
    # update k, so reward grading (sympy subprocesses, RM scoring) overlaps
    # device generation instead of serializing with it. Each rollout then
    # samples from the params of update k-1 (one update stale); the scoring
    # pass still measures the current policy, so the PPO-clip ratio absorbs
    # the off-policy drift exactly as the reference's off-policy-capable
    # losses do (`REINFORCE/reinforce_trainer.py:637`). Rollout PRNG comes
    # from a dedicated stream, so update 1 is bit-identical either way.
    rollout_ahead: bool = False
    # >0: DISAGGREGATED rollouts — reserve this many devices (a whole slice
    # on multi-slice pods, parallel/mesh.split_rollout_devices) as a
    # dedicated generation mesh; the training mesh spans the rest. Each
    # dispatch syncs the rollout param view onto the generation mesh (the
    # only cross-group transfer; on a pod it rides DCN once per update),
    # and with rollout_ahead=True generation for update k+1 runs on its own
    # devices WHILE update k trains — overlapping the two device-bound
    # phases, not just device-vs-host. 0 = generation shares the training
    # mesh. Requires the trainer to build its own meshes (mesh=None).
    rollout_devices: int = 0
    # mesh layout for the reserved generation devices (rollout_devices>0):
    # default data=-1 → pure data-parallel over the reserved group with
    # params replicated per device — right for models that fit one chip;
    # set tensor/fsdp for bigger policies.
    rollout_mesh: Optional["MeshConfig"] = None
    # ---- async rollout orchestrator (orchestrator/, docs/ORCHESTRATOR.md).
    # Generalizes rollout_ahead's one-step prefetch into a producer-thread
    # pipeline over a version-tagged weight store and a bounded-staleness
    # sample queue: the rollout mesh runs continuously up to max_staleness
    # policy versions ahead of training, with backpressure (or drops) at the
    # bound. Mutually exclusive with rollout_ahead; pairs naturally with
    # rollout_devices>0 (generation silicon never waits on the train step)
    # and with sampler_logprob_capture=True, which supplies the behavior
    # logprobs the truncated-IS off-policy correction needs.
    rollout_orchestrator: bool = False
    # max allowed (policy_version - sample_version) at consumption — how many
    # optimizer updates old a consumed rollout may be. 0 = fully on-policy
    # (reproduces the synchronous trainer exactly); 1 ≈ rollout_ahead's
    # pipelining; 2+ deepens the pipeline against jitter.
    max_staleness: int = 1
    # what happens to a QUEUED sample that goes over-stale anyway — possible
    # only under an abnormal publish-without-consume cadence (external
    # weight syncs; the producer gate itself is identical in both modes and
    # never admits a sample that could exceed the bound under the normal
    # one-publish-per-consume cadence): "wait" still delivers it (the
    # truncated-IS correction absorbs the extra staleness); "drop" discards
    # it and takes the next fresh sample (orchestrator/dropped_total counts
    # the discards).
    staleness_policy: str = "wait"
    # off-policy correction for stale samples: "truncated_is" re-weights
    # each loss term by min(π_old/μ, offpolicy_is_truncation) using the
    # sampler-captured behavior logprobs μ (algos/losses.truncated_is_weights)
    # — active only when the orchestrator runs at max_staleness > 0 WITH
    # sampler_logprob_capture (otherwise μ is unknown and the PPO ratio clip
    # alone absorbs the drift, as under rollout_ahead). "none" disables.
    offpolicy_correction: str = "truncated_is"  # truncated_is | none
    # ρ̄, the IS weight truncation (IMPALA/V-trace c̄): bounds the correction's
    # variance at a small bias toward under-weighting fresh-policy-favored
    # tokens.
    offpolicy_is_truncation: float = 2.0
    # ---- in-flight mid-sequence weight swaps (docs/ORCHESTRATOR.md
    # §in-flight swaps). PipelineRL-style: instead of draining in-flight
    # generations at a publish (idle rollout silicon) or letting them run
    # whole-sequence stale (every token behind the policy), the decode
    # drivers poll the weight store at their host sync points and install a
    # newer snapshot MID-SEQUENCE; the ledger stamps per-generation
    # `segments` ([{policy_version, tok_range}]) and the loss applies
    # PER-SEGMENT truncated-IS weights (algos/losses.segment_is_weights:
    # older segments get a tighter clamp, ρ̄^(1/(1+age))). Requires
    # rollout_orchestrator with a host-sync rollout path — the queued paged
    # scheduler (rollout_page_size>0 and rollout_decode_rows>0) or the
    # multi-turn env driver; the monolithic one-jit sampler has no swap
    # point. Off (or at max_staleness=0, where no publish can land
    # mid-rollout): bit-identical to main, test-pinned.
    rollout_inflight_swaps: bool = False
    # ---- elastic rollout fleet (orchestrator/fleet.py, docs/FLEET.md).
    # >1 generalizes the orchestrator's single producer thread into N
    # independent, preemptible rollout workers behind a FleetCoordinator:
    # leased rollout-index ranges with EWMA-derived deadlines, per-worker
    # heartbeat liveness, lease revocation + reassignment on worker loss
    # (same cached prompt batches + index-keyed PRNG — staleness-0 token
    # streams are bit-identical under reassignment, test-pinned),
    # consecutive-failure quarantine with jittered exponential backoff,
    # straggler speculative re-dispatch, and elastic join/leave; losing the
    # last worker falls through the producer watchdog to the synchronous
    # degraded mode. Requires rollout_orchestrator=True. Useful pipelining
    # needs max_staleness >= rollout_workers (the staleness gate bounds how
    # many indices can be in flight); pairs with rollout_devices>0, whose
    # device group is then split into per-worker meshes
    # (parallel/mesh.split_worker_groups). 1 = the single producer thread.
    rollout_workers: int = 1
    fleet_lease_size: int = 1          # rollout indices per lease
    fleet_failure_budget: int = 2      # consecutive failures → quarantine
    fleet_quarantine_base: float = 0.5  # re-admission backoff base · 2^k s
    fleet_quarantine_max: float = 30.0
    # ±fraction jitter on quarantine backoff — N workers failing on one
    # cause must not stampede the weight store in lockstep retry waves
    fleet_backoff_jitter: float = 0.25
    fleet_straggler_factor: float = 4.0  # lease deadline = factor·ewma·len
    # pre-EWMA lease deadline AND heartbeat-silence timeout (seconds): must
    # comfortably exceed a cold-cache first compile
    fleet_initial_deadline: float = 600.0
    # worker↔coordinator transport seam (orchestrator/rpc.py): "inprocess"
    # keeps direct calls; "rpc" routes leases/completions/heartbeats/weights
    # through the length-prefixed binary loopback RPC — the same wire path a
    # cross-host fleet uses (lease-epoch fencing, retry/backoff, streamed
    # weight fetch), exercisable on CPU CI. Requires rollout_workers > 1
    # (the trainer rejects rpc with a single worker — the seam only exists
    # inside the fleet orchestrator).
    rollout_transport: str = "inprocess"   # inprocess | rpc
    fleet_rpc_host: str = "127.0.0.1"      # bind + dial address
    fleet_rpc_port: int = 0                # 0 = ephemeral (loopback/CI)
    fleet_rpc_timeout: float = 30.0        # per-attempt socket timeout (s)
    fleet_rpc_attempts: int = 4            # retry_with_backoff attempts/call
    fleet_rpc_backoff_base: float = 0.05   # jittered backoff base (s)

    # ---- optimization ----
    learning_rate: float = 6e-6
    value_learning_rate: Optional[float] = None  # PPO separate value LR (`PPO/ppo.py:118-119`)
    warmup_steps: int = 0
    min_lr_rate: float = 0.1             # cosine_with_min_lr (`GRPO/grpo.py:119-121`)
    adam_eps: float = 1e-8
    weight_decay: float = 0.0
    max_grad_norm: Optional[float] = None

    # ---- RL coefficients ----
    kl_coef: float = 0.01
    # With kl_coef == 0 the reference's r1-zero path runs NO reference model
    # at all (`examples/r1-v0/grpo_r1.py:138` — no ref load, no ref pass);
    # matching that skips the ref weight copy (3 GB HBM at 1.5B) and the ref
    # half of every scoring pass — combined with sampler_logprob_capture the
    # scoring forwards disappear entirely. None = auto (ref-free iff
    # kl_coef == 0); True forces ref scoring anyway (e.g. to monitor KL
    # drift at coef 0). KL metrics read 0 in ref-free mode.
    score_ref_logprobs: Optional[bool] = None
    cliprange: float = 0.2
    cliprange_value: float = 0.01
    vf_coef: float = 0.1
    gamma: float = 1.0
    lam: float = 0.95
    whiten_rewards: bool = False
    advantage_whiten: bool = False       # REINFORCE defaults True in its launcher
    # RAFT 1-of-K selection: "best" = argmax (the reference's documented
    # intent, `RAFT/raft_trainer.py:585-586`), "random" = the as-shipped
    # behavior where a torch.randint overwrites the argmax (`:588`) — exposed
    # as config so bit-parity runs need no code change (ADVICE r1)
    raft_selection: str = "best"

    # ---- LoRA ----
    use_lora: bool = True
    lora_r: int = 64
    lora_alpha: int = 16
    # value-model LoRA (`PPO/ppo.py:141-159`): adapters + score head + embed
    # trainable, backbone frozen — without it the 1.5B value tree is full-FT
    # and pays ~3 GB of extra Adam state the reference doesn't
    value_use_lora: bool = True
    value_lora_r: int = 64
    value_lora_alpha: int = 16

    # ---- memory / kernels ----
    # fused hidden→logprob scoring (ops/fused_logprob.py, docs/
    # FUSED_LOGPROB.md): the scoring and update passes compute per-token
    # logprobs (+ the entropy stat) straight from final hidden states in
    # row-chunked blocks — the [B, T, V] logits tensor, the single largest
    # HBM allocation at LLM vocabularies, never materializes, and the
    # custom-VJP backward recomputes chunk logits instead of saving them.
    # False keeps the naive full-logits path (parity tests, triage); the
    # sequence-parallel (sp>1) passes are unaffected either way — they
    # already shard the head over the ring and never build global logits.
    fused_logprob: bool = True
    # rows (flattened microbatch·tokens) per recomputed logits chunk;
    # None → bytes-budget heuristic (ops/fused_logprob.fused_chunk_rows),
    # which shrinks the chunk as vocabulary grows so peak stays ≈ constant
    fused_logprob_chunk: Optional[int] = None
    # "auto" → Pallas online-logsumexp kernel on TPU, lax chunk scan
    # elsewhere; "lax" | "pallas" force one (pallas interprets off-TPU)
    fused_logprob_impl: str = "auto"
    gradient_checkpointing: bool = True
    attention_impl: str = "auto"  # xla | pallas | auto (by seq length, on TPU)
    # remat policy under gradient_checkpointing (core/config.remat_policy):
    # "full" recomputes whole layers in the backward; "dots" saves the MXU
    # projection outputs (more HBM, ~1/3 less recompute). Identical
    # gradients either way — a memory/FLOPs tuning knob.
    remat_policy: str = "full"  # full | dots
    # "int8": generation reads weight-only-quantized base projections (per-
    # output-channel scales, core/quant.py) — halves decode's HBM weight
    # traffic. LoRA/embeddings stay exact bf16 in the sampler; scoring and
    # updates always run exact weights. The quantization mismatch is a small
    # off-policy bias the clip TOLERATES by default; pair with
    # sampler_logprob_capture=True to importance-correct it exactly
    # (captured logprobs are the quantized behavior policy's — see
    # core/quant.py). Quantized once under LoRA (base frozen); re-quantized
    # per update when full fine-tuning.
    rollout_quant: str = "none"   # none | int8
    # "int8": the sampler's KV cache is int8 + per-token bf16 scales (core/
    # config.kv_cache_quant) — 1.78x less cache-read bandwidth at hd=128,
    # the dominant decode HBM stream at long responses. Rollout-only
    # (scoring/training have no cache); same off-policy-tolerance story as
    # rollout_quant.
    kv_cache_quant: str = "none"  # none | int8
    # LEGACY (contiguous layout only) — prefer rollout_page_size. >0:
    # rollouts use compacting decode (sampler/compaction.py) with this many
    # segments — finished rows are flushed at segment boundaries and live
    # rows gathered into a smaller power-of-two batch. A batch-shrink
    # approximation of continuous batching that the paged KV cache
    # supersedes; mutually exclusive with rollout_page_size > 0 and with
    # rollout_spec_k > 0.
    rollout_compaction_segments: int = 0
    # >0: the rollout KV cache switches to the PAGED layout (sampler/paged/,
    # docs/PAGED_CACHE.md) — K/V in a global pool of this-many-token pages
    # addressed through per-row block tables. On its own a pure re-layout
    # (greedy streams bit-identical to contiguous, test-pinned); with
    # rollout_decode_rows > 0 it unlocks true continuous batching. Composes
    # with rollout_spec_k and kv_cache_quant="int8". Use >= 128 on real
    # TPUs (lane-tile alignment for the paged kernels); 0 = contiguous.
    rollout_page_size: int = 0
    # rollout_page_size > 0 only. >0: continuous batching — only this many
    # rows are RESIDENT in the decode loop; when a row emits EOS its pages
    # are released and the next queued prompt is prefilled into the freed
    # pool mid-loop (sampler/paged/scheduler.py). Fixes the long-tail
    # straggler cost compaction approximated, works with spec_k, feeds the
    # rollout/page_* metrics + /statusz "pages" + lineage lease events.
    # 0 (or >= the rollout batch) = monolithic paged loop.
    rollout_decode_rows: int = 0
    # continuous batching only (rollout_page_size > 0 AND
    # rollout_decode_rows > 0). True: admissions route through the
    # cross-request radix prefix cache (serving/radix.py,
    # docs/SERVING.md) — repeated prompt prefixes across the rollout
    # queue (the n>1 fanout, dataset-level repeats) install
    # refcount-shared KV pages with zero prefill FLOPs and only the
    # suffix is prefilled. Greedy streams stay bit-identical to the
    # uncached path (test-pinned); sampled streams are equal in
    # distribution only. COMPOSES with rollout_spec_k > 0 — the n-gram
    # drafter seeds its lookup window from the cached continuation of
    # the matched prefix, so overlapping prompts accept drafts from the
    # first generated token (sampler.compose_check holds the full
    # legality matrix). Default off: the cache resets every generate
    # call (KV is params-tied), so it only pays when rollout prompts
    # overlap.
    rollout_prefix_cache: bool = False
    # continuous batching only. >0: any admission whose real prompt
    # suffix exceeds this many tokens is split into KV-only chunk
    # forwards interleaved with the resident rows' decode chunks
    # (sampler/paged/session.py) — a long cold prompt no longer stalls
    # every live stream for its whole prefill, bounding the p95
    # inter-token gap. Greedy streams are bit-identical to 0 (the final
    # chunk samples from the same admission PRNG fold, test-pinned);
    # sampled streams are equal in distribution only (a delayed row
    # decodes at later global PRNG folds). 0 = whole-suffix admissions.
    rollout_prefill_chunk: int = 0

    # ---- environments (envs/, docs/ENVIRONMENTS.md) ----
    # "" = no environment (the classic reward_func pipeline, unchanged).
    # "single_turn" wraps reward_func into SingleTurnEnv — bit-identical
    # to "" (parity-pinned). "python_tool" runs fenced ```python blocks
    # as mid-episode tools over the pooled executor; multi-turn requires
    # GRPO + rollout_page_size > 0 (the continuation turns ride the paged
    # admission path) and is incompatible with the orchestrator fleet,
    # sampler logprob capture, spec decode, and the prefix cache.
    env_name: str = ""
    # episode turn budget; 1 = single-turn semantics for any env
    env_max_turns: int = 1
    # per-turn generation budget (tokens); 0 = response_length. Multi-turn
    # requires env_turn_tokens*max_turns + env_obs_budget*(max_turns-1)
    # <= response_length so the packed episode fits the scored batch.
    env_turn_tokens: int = 0
    # max observation tokens appended per tool call
    env_obs_budget: int = 64
    # wall-clock seconds per tool call (pooled executor per-job timeout)
    env_tool_timeout: float = 5.0
    # resident rows in the multi-turn continuation loop; 0 = all episodes
    env_decode_rows: int = 0

    # ---- resilience (resilience/, docs/RESILIENCE.md) ----
    # fault-injection spec ("point:at=N,..."); None falls back to the
    # NANORLHF_FAULT env var; empty arms nothing. Injection points:
    # ckpt.save, ckpt.restore, rollout.produce, reward.exec, update.step.
    fault_spec: Optional[str] = None
    # training sentinel: per-update finite checks on loss/grad-norm plus an
    # EWMA spike detector; on trip the trainer restores the last committed
    # checkpoint, quarantines the offending batch, and charges the rollback
    # budget. Observation-only when healthy: a no-fault run with the
    # sentinel on is numerically identical to one without it.
    sentinel: bool = True
    sentinel_spike_zscore: float = 6.0
    sentinel_ewma_alpha: float = 0.1
    sentinel_warmup_steps: int = 20
    rollback_budget: int = 2
    # producer watchdog (orchestrated runs): a dead producer thread is
    # restarted with exponential backoff up to `producer_restart_budget`
    # CONSECUTIVE failures (a consumed sample resets the streak); past the
    # budget the run degrades to synchronous rollouts (staleness 0) instead
    # of dying — unless degrade_to_sync=False, which re-raises.
    producer_restart_budget: int = 2
    producer_backoff_base: float = 0.5
    producer_backoff_max: float = 30.0
    # ±fraction jitter on watchdog restart backoff (resilience/retry.py):
    # several supervised pipelines restarted off one shared cause (a weight
    # store hiccup, a flaky filesystem) must not retry in lockstep
    producer_backoff_jitter: float = 0.1
    producer_heartbeat: float = 30.0    # liveness poll interval in get()
    degrade_to_sync: bool = True
    # checkpoint I/O hardening: save/restore attempts retried with backoff
    # (ckpt_io_retries EXTRA attempts after the first). reward_retries
    # likewise for the host-side reward callable.
    ckpt_io_retries: int = 2
    ckpt_retry_backoff: float = 0.5
    reward_retries: int = 1
    # SIGTERM → flush in-flight async save, write an emergency checkpoint
    # at the current step, raise resilience.Preempted (handler installs
    # only from the main thread; elsewhere this degrades to a no-op guard)
    graceful_preemption: bool = True

    # ---- telemetry (telemetry/, docs/OBSERVABILITY.md) ----
    # span tracer + flight recorder: records named spans with correlation
    # args (step, rollout_index, staleness, policy_version) on per-thread
    # tracks — trainer loop, orchestrator producer, reward dispatch,
    # checkpoint I/O — and writes a Perfetto-loadable Chrome trace
    # (`<telemetry_dir>/trace.json`) at the end of every train() call and
    # on close(). The resilience layer dumps the flight-recorder ring as
    # `blackbox_<step>.json` on sentinel trip / producer failure / SIGTERM.
    # Off by default; the bench A/B (detail.telemetry) holds the enabled
    # overhead under 1% of step wall.
    telemetry: bool = False
    telemetry_dir: Optional[str] = None     # None -> output_dir
    # bounded trace buffer: events past the cap are dropped (counted in the
    # telemetry/spans_dropped metric) so a long run cannot OOM the host
    telemetry_max_events: int = 200_000
    flight_recorder_len: int = 256          # blackbox ring: recent spans kept
    # windowed XLA profiling (utils/profiling.ProfileWindow): wrap
    # jax.profiler around exactly [profile_at_step, +profile_num_steps)
    # updates, writing a TensorBoard-loadable trace to profile_dir
    # (None -> <output_dir>/profile). Independent of `telemetry` — the XLA
    # profile answers "what did the compiler run", the span trace answers
    # "what did the host pipeline do". An on-demand window can be requested
    # on a live run by touching the trigger file (None -> <output_dir>/
    # PROFILE; the file is consumed when the window opens).
    profile_at_step: Optional[int] = None
    profile_num_steps: int = 1
    profile_dir: Optional[str] = None
    profile_trigger_file: Optional[str] = None
    # run-health plane (telemetry/health.py + exporter.py,
    # docs/OBSERVABILITY.md §5): every metric row folds into O(1)-memory
    # streaming aggregates (fast/slow EWMA, P² quantile sketches, windowed
    # counter rates) and a declarative rule set scores the run OK/WARN/CRIT.
    # Health is on by default (bench's detail.health A/B holds its overhead
    # under 1%); the HTTP exporter is off by default. status_port: 0 = off,
    # -1 = ephemeral port (tests/CI), >0 = fixed port serving /metrics
    # (Prometheus text), /healthz (200/503 from the verdict), /statusz
    # (JSON run state incl. fleet membership + lease table).
    health: bool = True
    health_fast_alpha: float = 0.5        # tracks ~the last 2 rows
    health_slow_alpha: float = 0.05       # the baseline fast is judged by
    health_warmup_steps: int = 8          # min rows per metric before firing
    health_window_s: float = 60.0         # rate-rule sliding window
    health_max_events: int = 64           # transition ring for /statusz
    health_blackbox_on_crit: bool = True  # flight-recorder dump, reason="health"
    health_arm_sentinel: bool = False     # CRIT enables TrainingSentinel if off
    status_port: int = 0
    status_host: str = "127.0.0.1"
    # sample lineage ledger (telemetry/lineage.py, docs/OBSERVABILITY.md
    # §6): one joinable provenance stream per rollout index — lease grant
    # (lease/worker ids, PRNG fold-in path), generation (policy version,
    # spec-decode per-row acceptance), queue transit (staleness at
    # consumption), reward (score, retry attempt, grader wall), and
    # training outcome (advantage, kept vs dropped with a machine-readable
    # drop_reason) — as size-rotated append-only JSONL under
    # <output_dir>/lineage/. Query with tools/inspect_run.py; drop-reason
    # counters + a last-N sample ring ride /statusz and /metrics. Off by
    # default; the bench A/B (detail.lineage) holds the enabled overhead
    # under 1% of step wall.
    lineage: bool = False
    # fraction of rollout indices recorded (deterministic per-index hash:
    # a sampled index keeps its COMPLETE lease→...→outcome chain; others
    # are skipped at every layer). Drop counters stay exact regardless.
    lineage_sample_rate: float = 1.0
    # latency surface (telemetry/hist.py, docs/OBSERVABILITY.md §7):
    # log-bucketed mergeable streaming histograms over every
    # latency-bearing path — admission→first-token (TTFT), inter-token
    # gaps, queue wait, per-op RPC RTT, reward-grader wall, per-update
    # phase durations — journaled in trainer_state.json, rendered as
    # Prometheus histogram exposition on /metrics, and scored by the
    # quantile SLO rules (health.SLO_RULES). On by default; the bench
    # A/B (detail.latency) holds the overhead under 1% of step wall.
    latency: bool = True

    # ---- checkpoint / eval / logging ----
    save_steps: int = 1
    save_total_limit: int = 8
    save_optimizer_state: bool = True   # opt state + PRNG for exact resume
    save_value_model: bool = True       # PPO: value model in the checkpoint
                                        # (`PPO/ppo_trainer.py:413-416`)
    metric_for_best_model: str = "eval_objective/rlhf_reward_old"
    greater_is_better: bool = True
    load_best_model_at_end: bool = True
    # after the full run (and load_best), also write an HF-format checkpoint
    # (LoRA merged) here — the reference's `save_model` handoff artifact
    export_hf_dir: Optional[str] = None
    eval_steps: int = 1
    logging_steps: int = 1
    num_printed_samples: int = 5         # rich-table rows (`GRPO/grpo_trainer.py:717`)
    # rows per update routed into the lineage ledger's full-text `sample`
    # events (metrics.jsonl no longer carries sample rows — they polluted
    # the metric-row contract consumers like the health monitor iterate).
    # None -> num_printed_samples, the console table's row count.
    log_samples_limit: Optional[int] = None
    report_to: str = "jsonl"             # "jsonl" | "none" (wandb needs egress)

    # ---- mesh ----
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)

    # ---- derived (filled by finalize) ----
    world_size: int = dataclasses.field(default=1, init=False)
    local_batch_size: int = dataclasses.field(default=0, init=False)
    micro_batch_size: int = dataclasses.field(default=0, init=False)
    batch_size: int = dataclasses.field(default=0, init=False)
    mini_batch_size: int = dataclasses.field(default=0, init=False)
    local_mini_batch_size: int = dataclasses.field(default=0, init=False)
    num_total_batches: int = dataclasses.field(default=0, init=False)

    def finalize(self, n_devices: int) -> "RLConfig":
        """Derive the batch hierarchy from `self.mesh` over n_devices."""
        d, f, t, _sp = self.mesh.resolve(n_devices)
        return self.finalize_world(d * f)

    def finalize_world(self, world_size: int) -> "RLConfig":
        """Derive the batch hierarchy. `world_size` = data-parallel extent of
        the mesh (data × fsdp axes — both shard the batch). Preferred over
        finalize() when an explicit Mesh exists: its axis extents are the
        truth, not self.mesh's (an externally built mesh may differ)."""
        self.world_size = world_size
        self.local_batch_size = (
            self.per_device_train_batch_size
            * self.gradient_accumulation_steps
            * self.num_mini_batches
        )
        self.micro_batch_size = self.per_device_train_batch_size * self.world_size
        self.batch_size = self.local_batch_size * self.world_size
        self.mini_batch_size = exact_div(
            self.batch_size, self.num_mini_batches,
            "`batch_size` must be a multiple of `num_mini_batches`",
        )
        self.local_mini_batch_size = exact_div(
            self.local_batch_size, self.num_mini_batches,
            "`local_batch_size` must be a multiple of `num_mini_batches`",
        )
        if self.whiten_rewards and self.local_mini_batch_size < 8:
            raise ValueError(
                f"Per-rank minibatch size {self.local_mini_batch_size} is "
                "insufficient for whitening"
            )
        self.num_total_batches = math.ceil(self.total_episodes / self.batch_size)
        return self
