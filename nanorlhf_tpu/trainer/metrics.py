"""Metrics with the reference's `_old`/`_new` naming + sample tables.

Naming convention (SURVEY.md §5.5): `_old` = measured on the rollout
(pre-update) policy, `_new` = measured during the update pass — e.g.
`eval_objective/rlhf_reward_old`, `policy/approxkl_avg_new`
(`/root/reference/GRPO/grpo_trainer.py:726-747`). Completion samples print as
a small table each update (`:711-724`). wandb needs egress; the default sink
is a JSONL file any dashboard can tail.
"""

from __future__ import annotations

import atexit
import json
import os
import threading

from nanorlhf_tpu.analysis.lockorder import make_lock
import time


def staleness_histogram_metrics(counts: dict, prefix: str = "orchestrator") -> dict:
    """Flatten the orchestrator's sample-staleness histogram into scalar
    metric keys (`orchestrator/staleness_hist_K` = cumulative count of
    samples consumed at staleness K) — JSONL/TB sinks take scalars only,
    and cumulative counts diff cleanly into per-window rates downstream."""
    return {
        f"{prefix}/staleness_hist_{int(k)}": float(v)
        for k, v in sorted(counts.items(), key=lambda kv: int(kv[0]))
    }


class MetricsLogger:
    """Sinks: "jsonl" (default), "tensorboard" (jsonl + TB event files via
    torch's SummaryWriter — the reference's value-init reports to tensorboard,
    `PPO/ppo.py:100`), "none". wandb (`GRPO/grpo.py:136`) needs egress; point
    any dashboard at the JSONL instead."""

    def __init__(self, output_dir: str, report_to: str = "jsonl"):
        self.output_dir = output_dir
        self.report_to = report_to
        self._fh = None
        self._tb = None
        self._lock = make_lock("trainer.metrics")
        self._latest: dict = {}
        if report_to in ("jsonl", "tensorboard"):
            os.makedirs(output_dir, exist_ok=True)
            self._fh = open(os.path.join(output_dir, "metrics.jsonl"), "a")
        if report_to == "tensorboard":
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(os.path.join(output_dir, "tb"))
            except Exception as e:
                print(f"[metrics] tensorboard unavailable ({type(e).__name__}); "
                      "jsonl only")
        if self._fh is not None or self._tb is not None:
            # abnormal exits (unhandled exception, sys.exit from a harness)
            # bypass trainer.close(): without this barrier the TB writer's
            # buffered events — and any unflushed JSONL tail — are lost with
            # the process. close() unregisters; double-close is a no-op.
            atexit.register(self.close)

    def _emit(self, prefix: str, x: int, extra: dict, metrics: dict):
        # t_mono: perf_counter, PhaseTimer's clock discipline — rate windows
        # built on these rows survive NTP steps (unlike "time")
        # nanolint: allow[determinism.wall-clock] the "time" row IS the wall-clock provenance stamp (METRICS.md); t_mono is the duration clock
        record = {"step": x, **extra, "time": time.time(),
                  "t_mono": time.perf_counter()}
        record.update({k: float(v) for k, v in metrics.items()})
        print(f"[{prefix} {x}] " + " ".join(
            f"{k}={record[k]:.4g}" for k in sorted(metrics)[:8]
        ))
        with self._lock:
            # fresh dict each emit, never mutated after publish: latest()
            # readers on exporter threads see a consistent row
            self._latest = record
        if self._fh:
            self._fh.write(json.dumps(record) + "\n")
            self._fh.flush()
        if self._tb:
            for k, v in metrics.items():
                self._tb.add_scalar(k, float(v), x)

    def latest(self) -> dict:
        """Thread-safe copy of the most recent metrics record ({} before
        the first emit) — the exporter scrapes this instead of re-reading
        the JSONL tail."""
        with self._lock:
            return dict(self._latest)

    def log(self, step: int, episode: int, metrics: dict):
        self._emit("step", step, {"episode": episode}, metrics)

    def log_event(self, index: int, metrics: dict):
        """Out-of-band rows (e.g. sparse-filter skips): stamped with the
        caller's monotonic index + time but NOT 'episode' — consumers
        identify training-step rows by the presence of 'episode'
        (tests/test_resume.py idiom), and TB needs a unique x per record
        (global_step is frozen across consecutive skips)."""
        self._emit("event", index, {}, metrics)

    def log_samples(self, step: int, queries: list[str], responses: list[str],
                    scores, limit: int = 5):
        """Console sample table — the rich-table parity
        (`GRPO/grpo_trainer.py:711-724`). Console only: full-text sample
        records go to the lineage ledger's `sample` events
        (telemetry/lineage.py), NOT into metrics.jsonl — interleaved
        sample rows broke the metric-row contract every JSONL consumer
        (health monitor, inspect_run, resume tests) iterates."""
        print(f"--- samples @ step {step} ---")
        for q, r, s in list(zip(queries, responses, scores))[:limit]:
            q1 = q.replace("\n", " ")[:80]
            r1 = r.replace("\n", " ")[:120]
            print(f"  score={float(s):+.3f} | {q1!r} -> {r1!r}")

    def close(self):
        """Flush + close both sinks. Idempotent (also runs as the atexit
        barrier registered at construction — a second call finds the
        handles already None)."""
        atexit.unregister(self.close)
        if self._fh:
            self._fh.close()
            self._fh = None
        if self._tb:
            self._tb.close()
            self._tb = None
