"""Sparse GRPO — the long-sequence (8k-token) trainer variant of r1-v0.

Re-states `/root/reference/examples/r1-v0/grpo_r1_trainer.py` on the unified
runtime. The four moves that let the reference train 8,000-token responses on
one 40 GB GPU (`examples/r1-v0/README.md:25-28`), here under XLA static
shapes:

1. **sparse filter** — drop samples whose z-scored advantage is 0 (with 0/1
   rewards that's every all-correct/all-wrong group) (`:565-568`);
2. **de-padding** — strip the common left-pad of queries and truncate
   responses to the batch max (`:571-582`), rounded onto a power-of-two menu
   so XLA's compile cache stays warm;
3. **bucket batching** — pack by length under the `max_len × rows ≤ budget`
   memory model, rollout budget 22·2316 / backward budget 4·2316
   (`:589,700,410-435`);
4. **bucket-scaled loss** — each bucket backward is scaled
   `rows / minibatch_rows`, one optimizer step per minibatch (`:786-791`).

Host-side numpy handles all ragged filtering/packing; jit only ever sees the
menu shapes (SURVEY.md §7 hard part #2).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from nanorlhf_tpu.algos import (
    discounted_returns,
    grpo_group_advantage,
    keep_one_of_n_indices,
    sparse_terminal_rewards,
)
from nanorlhf_tpu.algos.losses import grpo_loss
from nanorlhf_tpu.ops.masking import (
    INVALID_LOGPROB,
    first_true_indices,
    logprobs_from_logits,
    response_padding_masks,
    truncate_response,
)
from nanorlhf_tpu.core.model import padded_forward_logits
from nanorlhf_tpu.ops.fused_logprob import chunked_entropy
from nanorlhf_tpu.sampler import SamplingParams, generate
from nanorlhf_tpu.trainer.bucketing import (
    create_batches,
    pad_rows,
    round_up_to_menu,
    shape_menu,
)
from nanorlhf_tpu.trainer.trainer import (
    RLTrainer,
    RolloutStream,
    device_peak_bytes,
    forward_token_budget,
    fused_response_logprobs,
)

# forward budget comes from forward_token_budget (activation ∧ vocab caps);
# backward keeps the reference's dedicated constant (`grpo_r1_trainer.py:700`)
BACKWARD_BUDGET = 4 * 2316


class SparseGRPOTrainer(RLTrainer):
    """GRPO + sparse filtering + bucketed variable-length execution.

    `accuracy_func(trainer) -> float`, when given, runs before training and
    every `cfg.eval_steps` updates (MATH-500 greedy eval in r1,
    `grpo_r1_trainer.py:471-475,824-825`).

    The reward callable may use either protocol:
    `(pmt_and_responses, eos_token)` or the r1 signature
    `(pmt_and_responses, responses_ids, tokenizer)` (`grpo_r1.py:250`).
    """

    def __init__(self, *args, accuracy_func: Optional[Callable] = None, **kwargs):
        super().__init__(*args, **kwargs)
        if self._env_multi_turn:
            # single-turn envs work (RLTrainer unwraps them into a plain
            # reward callable, which _call_reward dispatches unchanged);
            # the multi-turn episode driver is wired into the DENSE
            # runtime's rollout phase only
            raise ValueError(
                "SparseGRPOTrainer does not drive multi-turn environments "
                "(env_max_turns > 1) — use the dense RLTrainer")
        self.accuracy_func = accuracy_func
        self._len_menu = shape_menu(
            self.cfg.response_length + self.dataset.input_ids.shape[1], min_value=32
        )
        self._rows_menu = shape_menu(max(self.cfg.batch_size, 1), min_value=1)

    # ------------------------------------------------------------------ #
    # jitted pieces (bucket-shaped)
    # ------------------------------------------------------------------ #

    def _bucket_score_fn(self):
        if hasattr(self, "_bucket_score_cached"):
            return self._bucket_score_cached
        mcfg, cfg = self.mcfg, self.cfg
        pad_id = self.tokenizer.pad_token_id
        lora_scale = self.lora_scale

        if cfg.fused_logprob:
            # fused hidden→logprob scoring (ops/fused_logprob.py): the
            # parent's non-sp fused chunk scorer is shape-polymorphic over
            # bucket widths already (jit per static context_length) — same
            # closure, one copy, no [rows, T, V] logits block per forward
            score = self._score_chunk_fn()
            self._bucket_score_cached = score
            return score

        @partial(jax.jit, static_argnums=(3,))
        def score(params, ref_params, qr, context_length: int):
            resp = qr[:, context_length:]
            lp = logprobs_from_logits(
                padded_forward_logits(params, mcfg, qr, pad_id,
                                      lora_scale=lora_scale,
                                      response_context_length=context_length),
                resp, cfg.temperature,
            )
            rlp = logprobs_from_logits(
                padded_forward_logits(ref_params, mcfg, qr, pad_id,
                                      response_context_length=context_length),
                resp, cfg.temperature,
            )
            return lp, rlp

        self._bucket_score_cached = score
        return score

    def _bucket_grad_fn(self):
        if hasattr(self, "_bucket_grad_cached"):
            return self._bucket_grad_cached
        mcfg, cfg = self.mcfg, self.cfg
        pad_id = self.tokenizer.pad_token_id
        lora_scale = self.lora_scale
        remat = cfg.gradient_checkpointing
        combine = self._combine

        def loss_fn(trainable, frozen, mb, context_length, loss_scale):
            tree = combine(trainable, frozen)
            if cfg.fused_logprob:
                new_lp, ent_tok = fused_response_logprobs(
                    tree["policy"], mcfg, mb["query_responses"],
                    mb["responses"], pad_id, context_length, cfg,
                    lora_scale=lora_scale, remat=remat, with_entropy=True,
                )
                entropy = jax.lax.stop_gradient(ent_tok.mean())
            else:
                logits = padded_forward_logits(
                    tree["policy"], mcfg, mb["query_responses"], pad_id,
                    lora_scale=lora_scale, remat=remat,
                    response_context_length=context_length,
                )
                # chunked entropy: no stop-gradient f32 full-logits copy
                entropy = jax.lax.stop_gradient(chunked_entropy(
                    logits, cfg.temperature, chunk=cfg.fused_logprob_chunk
                ).mean())
                new_lp = logprobs_from_logits(
                    logits, mb["responses"], cfg.temperature
                )
            new_lp = jnp.where(mb["padding_mask"], INVALID_LOGPROB, new_lp)
            mask = ~mb["padding_mask"]
            if "loss_mask" in mb:
                # env observation tokens: conditioned on, never scored
                # (dense runtime's microbatch_loss composes the same way)
                mask = mask & mb["loss_mask"]
            loss, aux = grpo_loss(
                new_lp, mb["logprobs"], mb["ref_logprobs"], mb["advantages"],
                mask, cfg.cliprange, cfg.kl_coef,
            )
            aux["entropy"] = entropy
            return loss * loss_scale, aux

        @partial(jax.jit, static_argnums=(3,))
        def bucket_grads(trainable, frozen, mb, context_length, loss_scale):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                trainable, frozen, mb, context_length, loss_scale
            )
            return grads, aux

        self._bucket_grad_cached = bucket_grads
        return bucket_grads

    # ------------------------------------------------------------------ #
    # sequence-parallel pieces (mesh sp > 1): bucket-shaped SP scoring and
    # grads — `_sp_on`/`_fsdp_axis` come from RLTrainer, which also runs
    # its own dense chunked passes through SP when the axis is present
    # (VERDICT r1 #3: SP is a trainer capability, not a demo)
    # ------------------------------------------------------------------ #

    def _sp_score_fn(self):
        if hasattr(self, "_sp_score_cached"):
            return self._sp_score_cached
        from nanorlhf_tpu.parallel.sp import sp_score_logprobs

        mcfg, cfg, mesh = self.mcfg, self.cfg, self.mesh
        pad_id = self.tokenizer.pad_token_id
        lora_scale = self.lora_scale
        fsdp_axis = self._fsdp_axis()

        @partial(jax.jit, static_argnums=(3,))
        def score(params, ref_params, qr, context_length: int):
            # same attn_impl as `_sp_grad_fn`'s update forward (ADVICE r3)
            lp = sp_score_logprobs(
                params, mcfg, qr, pad_id, cfg.temperature, mesh,
                fsdp_axis=fsdp_axis, lora_scale=lora_scale,
                attn_impl=mcfg.attention_impl,
            )[:, context_length - 1 : -1]
            rlp = sp_score_logprobs(
                ref_params, mcfg, qr, pad_id, cfg.temperature, mesh,
                fsdp_axis=fsdp_axis, attn_impl=mcfg.attention_impl,
            )[:, context_length - 1 : -1]
            return lp, rlp

        self._sp_score_cached = score
        return score

    def _sp_grad_fn(self):
        if hasattr(self, "_sp_grad_cached"):
            return self._sp_grad_cached
        from nanorlhf_tpu.parallel.sp import sp_score_logprobs

        mcfg, cfg, mesh = self.mcfg, self.cfg, self.mesh
        pad_id = self.tokenizer.pad_token_id
        lora_scale = self.lora_scale
        combine = self._combine
        fsdp_axis = self._fsdp_axis()

        def loss_fn(trainable, frozen, mb, context_length, loss_scale):
            tree = combine(trainable, frozen)
            # attn_impl matches `_sp_score_fn` (the flash ring has a
            # backward): old/ref and new logprobs share kernels, so the
            # exp(new−old) ratio has no kernel-mismatch offset (ADVICE r3)
            new_lp, entropy = sp_score_logprobs(
                tree["policy"], mcfg, mb["query_responses"], pad_id,
                cfg.temperature, mesh, fsdp_axis=fsdp_axis,
                lora_scale=lora_scale, remat=cfg.gradient_checkpointing,
                with_entropy=True, entropy_from_position=context_length - 1,
                attn_impl=mcfg.attention_impl,
            )
            new_lp = new_lp[:, context_length - 1 : -1]
            new_lp = jnp.where(mb["padding_mask"], INVALID_LOGPROB, new_lp)
            mask = ~mb["padding_mask"]
            if "loss_mask" in mb:
                # env observation tokens: conditioned on, never scored
                mask = mask & mb["loss_mask"]
            loss, aux = grpo_loss(
                new_lp, mb["logprobs"], mb["ref_logprobs"], mb["advantages"],
                mask, cfg.cliprange, cfg.kl_coef,
            )
            # the global [B, T, V] logits never materialize under SP (that's
            # the point) — the entropy stat is a per-shard mean pmean'd over
            # the ring inside the scorer
            aux["entropy"] = entropy
            return loss * loss_scale, aux

        @partial(jax.jit, static_argnums=(3,))
        def sp_grads(trainable, frozen, mb, context_length, loss_scale):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                trainable, frozen, mb, context_length, loss_scale
            )
            return grads, aux

        self._sp_grad_cached = sp_grads
        return sp_grads

    def _sp_round_len(self, blen: int, cap: int) -> int:
        """Round a bucket length up to an sp-axis multiple (the sequence dim
        shards evenly over the ring); `cap` is the physical qr width."""
        n_sp = self.mesh.shape.get("sp", 1)
        if n_sp == 1:
            return blen
        blen = -(-blen // n_sp) * n_sp
        if blen > cap:
            if cap % n_sp != 0:
                raise ValueError(
                    f"qr width {cap} not divisible by sp={n_sp}; pick "
                    f"response_length/prompt width as multiples of sp"
                )
            blen = cap
        return blen

    def _apply_grads_fn(self):
        if hasattr(self, "_apply_grads_cached"):
            return self._apply_grads_cached
        optimizer = self.optimizer

        from nanorlhf_tpu.trainer.trainer import donate_argnums_on_accel

        @partial(jax.jit, donate_argnums=donate_argnums_on_accel(0, 1))
        def apply_grads(trainable, opt_state, grads):
            updates, opt_state = optimizer.update(grads, opt_state, trainable)
            return optax.apply_updates(trainable, updates), opt_state

        self._apply_grads_cached = apply_grads
        return apply_grads

    # ------------------------------------------------------------------ #
    # reward protocol bridge
    # ------------------------------------------------------------------ #

    def _call_reward(self, pmt_and_responses, responses_ids):
        try:
            return np.asarray(
                self.reward_func(pmt_and_responses, responses_ids, self.tokenizer),
                np.float32,
            )
        except TypeError:
            return np.asarray(
                self.reward_func(pmt_and_responses, self.tokenizer.eos_token),
                np.float32,
            )

    # ------------------------------------------------------------------ #
    # the sparse training loop
    # ------------------------------------------------------------------ #

    def train(self, num_updates: Optional[int] = None):
        cfg, tok = self.cfg, self.tokenizer
        if cfg.rollout_orchestrator:
            raise ValueError(
                "rollout_orchestrator is not supported by SparseGRPOTrainer "
                "yet: the sparse all-zero-advantage skip consumes a rollout "
                "WITHOUT publishing a policy version, which would wedge the "
                "bounded-staleness gate (orchestrator/sample_queue.py). Use "
                "rollout_ahead for overlap on the sparse path."
            )
        pad_id, eos_id = tok.pad_token_id, tok.eos_token_id
        n = cfg.sample_n
        sp_on = self._sp_on()
        score_fn = self._sp_score_fn() if sp_on else self._bucket_score_fn()
        grad_fn = self._sp_grad_fn() if sp_on else self._bucket_grad_fn()
        apply_fn = self._apply_grads_fn()

        if self.accuracy_func is not None and self.state["global_step"] == 0:
            acc = float(self.accuracy_func(self))
            self.logger.log(0, 0, {"initial_accuracy": acc})

        # the single-model scorer branches to the SP variant when sp is on
        # (see RLTrainer._single_scorer_for for the ref-free/capture matrix)
        capture = cfg.sampler_logprob_capture
        ref_fn = self._single_scorer_for(capture)
        sampling = SamplingParams(
            temperature=cfg.temperature, top_p=cfg.top_p, n=n,
            max_tokens=cfg.response_length, capture_logprobs=capture,
            compaction_segments=cfg.rollout_compaction_segments,
            top_k=cfg.rollout_top_k, approx_top_k=cfg.rollout_approx_top_k,
            shared_prompt_prefill=cfg.rollout_shared_prefill,
            spec_k=cfg.rollout_spec_k, spec_ngram=cfg.rollout_spec_ngram,
            page_size=cfg.rollout_page_size,
            decode_rows=cfg.rollout_decode_rows,
        )
        n_updates = (
            max(0, cfg.num_total_batches - self.state["global_step"])
            if num_updates is None else num_updates
        )

        def rollout_body(queries, gk):
            """DISPATCH one rollout (async — nothing blocks until fetched)."""
            q_j = jnp.asarray(queries)
            if self.rollout_mesh is not None:
                from nanorlhf_tpu.parallel.mesh import batch_sharding

                # disaggregated rollouts: prompts land on the generation
                # mesh; _rollout_params() re-shards the param view there
                q_j = jax.device_put(q_j, batch_sharding(self.rollout_mesh))
            spec_stats: list = []
            paged_stats: list = []
            gen_out = generate(
                self._rollout_params(), self._rollout_mcfg, q_j, q_j != pad_id, gk,
                sampling, eos_token_id=eos_id, pad_token_id=pad_id,
                lora_scale=self.lora_scale,
                spec_stats_out=spec_stats, tracer=self.tracer,
                paged_stats_out=paged_stats, latency=self.latency,
            )
            return {"queries": queries, "gen_out": gen_out,
                    "spec_stats": spec_stats[0] if spec_stats else None,
                    "paged_stats": paged_stats[0] if paged_stats else None}

        stream = RolloutStream(self, rollout_body, meter=self._rollout_meter)
        # lineage (telemetry/lineage.py): whole-rollout drops are counted
        # in samples — one rollout here is batch_size*n completion rows
        self.lineage.rows_hint = cfg.batch_size * n
        for update in range(1, n_updates + 1):
            t_start = time.perf_counter()  # sec_per_episode is a duration
            step_t0 = time.perf_counter()
            # telemetry (docs/OBSERVABILITY.md): profile-window poll + the
            # per-update span, same contract as the dense loop
            self.profile_window.poll(self.state["global_step"] + 1)
            span_t0 = self.tracer.now_us() if self.tracer.enabled else 0.0
            self.state["episode"] += cfg.batch_size

            # ---- rollout + reward -----------------------------------------
            t_roll0 = time.perf_counter()
            ro = stream.fetch_or_dispatch()
            rollout_index = ro["_index"]
            queries = ro["queries"]
            batch_size = queries.shape[0]
            if self.lineage.enabled:
                # serial loop: generation provenance is emitted here (the
                # stream's dispatch already logged the lease event)
                from nanorlhf_tpu.telemetry.lineage import spec_summary

                self.lineage.generation(
                    rollout_index,
                    policy_version=self.state["global_step"], worker_id=0,
                    spec=spec_summary(ro),
                )
            pstats = ro.get("paged_stats")
            if pstats is not None:
                # /statusz "pages" snapshot + one lineage "lease" event per
                # mid-loop admission — same contract as the dense loop
                self._pages_status = {
                    k: (None if pstats[k] is None
                        else float(np.asarray(pstats[k])))
                    for k in ("page_utilization", "pages_recycled",
                              "admitted_midloop", "decode_iterations")
                }
                self._pages_status.update(
                    rows=pstats["rows"], num_pages=pstats["num_pages"],
                    page_size=pstats["page_size"],
                )
                if self.lineage.enabled:
                    for adm in pstats.get("admissions") or []:
                        self.lineage.event(
                            "lease", rollout_index, midloop=True,
                            row=adm["row"], queue_index=adm["queue_index"],
                            iteration=adm["iteration"],
                        )
            if capture:
                responses, captured_lp = ro["gen_out"]
                responses = np.asarray(responses)
                captured_lp = np.asarray(captured_lp)
            else:
                responses = np.asarray(ro["gen_out"])
                captured_lp = None
            rollout_s = time.perf_counter() - t_roll0
            if cfg.rollout_ahead and update < n_updates:
                # overlap the NEXT generation with this update's grading —
                # in the r1 path the sympy/subprocess graders are the
                # dominant host cost, so this is where the overlap pays most
                stream.prefetch()
            question_strings = [
                q.replace(tok.pad_token, "") for q in tok.batch_decode(queries)
            ]
            question_n = [q for q in question_strings for _ in range(n)]
            decoded = tok.batch_decode(responses)
            t_rwd0 = time.perf_counter()
            raw_scores = self._call_reward(
                [q + r for q, r in zip(question_n, decoded)], responses
            )
            if self.latency.enabled:
                # grader wall — same quantity the lineage reward event
                # records as wall_s (the sympy/subprocess graders are the
                # dominant host cost in the r1 path)
                self.latency.record("latency/reward_s",
                                    time.perf_counter() - t_rwd0)
            self.lineage.reward(
                rollout_index, step=self.state["global_step"],
                scores=[round(float(s), 6) for s in raw_scores.tolist()],
                attempt=1,  # _call_reward has no retry loop
                wall_s=round(time.perf_counter() - t_rwd0, 6),
            )
            mean_raw_score = float(raw_scores.mean())
            log_responses_length = float(
                np.asarray(first_true_indices(jnp.asarray(responses) == pad_id)).mean()
            )

            # ---- group z-score + keep-1-of-N ------------------------------
            adv_flat = np.asarray(grpo_group_advantage(jnp.asarray(raw_scores), n))
            self.key, kk = jax.random.split(self.key)
            keep = np.asarray(keep_one_of_n_indices(kk, batch_size, n))
            rows = np.arange(batch_size)
            scores = adv_flat.reshape(batch_size, n)[rows, keep]
            responses = responses.reshape(batch_size, n, -1)[rows, keep]
            if captured_lp is not None:
                captured_lp = captured_lp.reshape(batch_size, n, -1)[rows, keep]
            if n > 1:
                # the other n−1 completions per prompt leave the batch here
                self.lineage.drop(
                    rollout_index, "keep_filter",
                    count=batch_size * (n - 1),
                    step=self.state["global_step"],
                )

            # ---- sparse filter (`grpo_r1_trainer.py:565-568`) -------------
            nz = np.where(scores != 0)[0]
            kept_frac = len(nz) / max(batch_size, 1)
            if self.lineage.enabled:
                # the paper's silent zero-advantage skip, made loud: one
                # drop event PER EXCLUDED ROW — the attribution the sparse
                # filter never had (every dropped row has exactly one
                # machine-readable drop_reason)
                for r in np.where(scores == 0)[0]:
                    self.lineage.drop(
                        rollout_index, "sparse_zero_advantage",
                        row=int(r), step=self.state["global_step"],
                        raw_score=round(
                            float(raw_scores.reshape(batch_size, n)[r, keep[r]]),
                            6,
                        ),
                    )
            if len(nz) == 0:
                print(f"[sparse-grpo] update {update}: all advantages zero, skipping")
                # skip marker in the trace: a starved streak shows up as a
                # row of instants instead of a silent gap
                self.tracer.instant(
                    "sparse.skip", rollout_index=self.state["rollouts"],
                    raw_score_mean=mean_raw_score,
                )
                # a metrics row even for the skip (the reference logs
                # nothing here): with sparse/binary rewards, WHY training
                # starves matters — raw_score_mean 0 = uniformly failed,
                # high = uniformly solved; both give zero group advantage.
                # log_event (no 'episode' stamp, rollout-indexed) keeps
                # step-row consumers and TB x-axes intact across
                # consecutive skips at a frozen global_step.
                self.logger.log_event(self.state["rollouts"], {
                    "sparse_skip/raw_score_mean": mean_raw_score,
                    "sparse_skip/rollout_index": self.state["rollouts"],
                })
                # preemption must be polled on the skip path too: a long
                # uniformly-failed/solved streak would otherwise bypass the
                # bottom-of-loop poll every iteration, swallow SIGTERM, and
                # be SIGKILLed at the end of the grace window
                if self._preemption.triggered:
                    from nanorlhf_tpu.resilience import Preempted

                    self._sparse_save({})
                    self.ckpt.wait()
                    self.tracer.dump_blackbox(
                        self._telemetry_dir, self.state["global_step"],
                        "preemption",
                    )
                    self._write_trace()
                    raise Preempted(
                        f"SIGTERM at step {self.state['global_step']} (sparse "
                        f"skip streak): emergency checkpoint committed to "
                        f"{cfg.output_dir}"
                    )
                continue
            scores, queries_f, responses_f = scores[nz], queries[nz], responses[nz]
            if captured_lp is not None:
                captured_lp = captured_lp[nz]

            # ---- de-pad (`:571-582`), menu-rounded ------------------------
            from nanorlhf_tpu.trainer.bucketing import depad_queries

            queries_f = depad_queries(queries_f, pad_id, self._len_menu)
            context_length = queries_f.shape[1]

            post = np.asarray(truncate_response(eos_id, pad_id, jnp.asarray(responses_f)))
            resp_len = np.asarray(first_true_indices(jnp.asarray(post) == pad_id))
            max_resp = round_up_to_menu(
                max(int(resp_len.max()), 1), self._len_menu
            )
            max_resp = min(max_resp, responses_f.shape[1])
            responses_f = responses_f[:, :max_resp]
            post = post[:, :max_resp]

            qr = np.concatenate([queries_f, responses_f], axis=1)
            qr_len = context_length + resp_len

            # ---- bucketed logprob pass (budget 22·2316, capped so the
            # [tokens, vocab] logits block fits HBM — the cap lifts under
            # fused_logprob, whose chunking bounds that block itself; NOT
            # under sp, whose scorer still materializes per-shard logits) ---
            rollout_budget = forward_token_budget(
                self.mcfg.vocab_size,
                fused_logprob=cfg.fused_logprob and not self._sp_on(),
            )
            backward_budget = min(BACKWARD_BUDGET, rollout_budget // 2)
            buckets = create_batches(qr_len, rollout_budget)
            logprobs = np.full(
                (len(scores), max_resp), INVALID_LOGPROB, np.float32
            )
            ref_logprobs = logprobs.copy()
            if captured_lp is not None:
                # policy logprobs came from the sampler; buckets below only
                # run the ref forward (half the scoring work)
                logprobs = captured_lp[:, :max_resp].astype(np.float32)
            ref_free = self._ref_free
            for idxs in ([] if (ref_free and capture) else buckets):
                # ref-free + capture: zero scoring forwards (sampler-captured
                # policy logprobs, no reference model — the r1 setting)
                blen = round_up_to_menu(int(qr_len[idxs].max()), self._len_menu)
                blen = min(max(blen, context_length + 1), qr.shape[1])
                blen = self._sp_round_len(blen, qr.shape[1])
                rows_b = round_up_to_menu(len(idxs), self._rows_menu)
                padded = pad_rows(
                    {"qr": qr[idxs][:, :blen]}, rows_b, {"qr": pad_id}
                )
                width = blen - context_length
                if ref_free:
                    lp = ref_fn(self.params, jnp.asarray(padded["qr"]),
                                context_length)
                    logprobs[idxs, :width] = np.asarray(lp)[: len(idxs)]
                elif capture:
                    rlp = ref_fn(self.ref_params, jnp.asarray(padded["qr"]),
                                 context_length)
                    ref_logprobs[idxs, :width] = np.asarray(rlp)[: len(idxs)]
                else:
                    lp, rlp = score_fn(
                        self.params, self.ref_params, jnp.asarray(padded["qr"]),
                        context_length,
                    )
                    logprobs[idxs, :width] = np.asarray(lp)[: len(idxs)]
                    ref_logprobs[idxs, :width] = np.asarray(rlp)[: len(idxs)]
            if ref_free:
                # ref == policy-old: every KL term and metric reads exactly 0
                ref_logprobs = logprobs.copy()

            # ---- masks + advantages ---------------------------------------
            seq_len = np.asarray(first_true_indices(jnp.asarray(post) == pad_id) - 1)
            padding_mask, _ = response_padding_masks(post, jnp.asarray(seq_len))
            padding_mask = np.asarray(padding_mask)
            logprobs = np.where(padding_mask, INVALID_LOGPROB, logprobs)
            ref_logprobs = np.where(padding_mask, INVALID_LOGPROB, ref_logprobs)
            rewards = np.asarray(sparse_terminal_rewards(
                jnp.asarray(scores), jnp.asarray(seq_len), max_resp
            ))
            advantages = np.asarray(discounted_returns(jnp.asarray(rewards), 1.0))
            advantages = np.where(padding_mask, 0.0, advantages)

            # ---- bucketed update (budget 4·2316, loss-scaled) -------------
            t_upd0 = time.perf_counter()
            trainable, frozen = self._partition(
                self._train_tree(self.params, self.value_params)
            )
            all_stats = []
            local_bs = len(scores)
            mini = min(cfg.local_mini_batch_size, local_bs)
            lr_step = self.state.get("opt_steps", 0)
            for epoch in range(cfg.num_ppo_epochs):
                self.key, pk = jax.random.split(self.key)
                perm = np.asarray(jax.random.permutation(pk, local_bs))
                for start in range(0, local_bs, mini):
                    mb_inds = perm[start : start + mini]
                    mini_rows = len(mb_inds)
                    grads_acc = None
                    for bidx in create_batches(qr_len[mb_inds], backward_budget):
                        sel = mb_inds[bidx]
                        blen = round_up_to_menu(int(qr_len[sel].max()), self._len_menu)
                        blen = min(max(blen, context_length + 1), qr.shape[1])
                        blen = self._sp_round_len(blen, qr.shape[1])
                        width = blen - context_length
                        rows_b = round_up_to_menu(len(sel), self._rows_menu)
                        mb = pad_rows(
                            {
                                "query_responses": qr[sel][:, :blen],
                                "responses": responses_f[sel][:, :width],
                                "logprobs": logprobs[sel][:, :width],
                                "ref_logprobs": ref_logprobs[sel][:, :width],
                                "advantages": advantages[sel][:, :width],
                                "padding_mask": padding_mask[sel][:, :width],
                            },
                            rows_b,
                            {"query_responses": pad_id, "responses": pad_id,
                             "logprobs": INVALID_LOGPROB,
                             "ref_logprobs": INVALID_LOGPROB,
                             "padding_mask": True},
                        )
                        mb = {k: jnp.asarray(v) for k, v in mb.items()}
                        # scale by REAL rows (`grpo_r1_trainer.py:786-788`)
                        loss_scale = len(sel) / mini_rows
                        grads, aux = grad_fn(
                            trainable, frozen, mb, context_length,
                            jnp.float32(loss_scale),
                        )
                        grads_acc = grads if grads_acc is None else jax.tree.map(
                            jnp.add, grads_acc, grads
                        )
                        all_stats.append(aux)
                    trainable, self.opt_state = apply_fn(
                        trainable, self.opt_state, grads_acc
                    )
                    self.state["opt_steps"] = self.state.get("opt_steps", 0) + 1
            self.params = self._combine(trainable, frozen)["policy"]
            all_stats = jax.device_get(all_stats)
            update_s = time.perf_counter() - t_upd0

            # ---- metrics / eval / checkpoint ------------------------------
            agg = {
                k: float(np.mean([s[k] for s in all_stats]))
                for k in (all_stats[0] if all_stats else {})
            }
            kl_rollout = float(
                np.where(padding_mask, 0.0, logprobs - ref_logprobs).sum(1).mean()
            )
            metrics = {
                # GRPO parity: update-pass refkl (see docs/METRICS.md);
                # 0 in ref-free mode — the stand-in refkl would report
                # KL-to-old-policy, not a reference KL
                "objective/kl_old": (
                    0.0 if self._ref_free
                    else agg.get("refkl_mean", kl_rollout)
                ),
                "objective/kl_rollout_old": kl_rollout,
                "objective/non_score_reward_old": 0.0,  # GRPO: KL is in-loss
                "eval_objective/rlhf_reward_old": mean_raw_score,
                "eval_objective/scores_old": mean_raw_score,
                "policy/approxkl_avg_new": agg.get("approxkl", 0.0),
                "policy/clipfrac_avg_new": agg.get("pg_clipfrac", 0.0),
                "policy/entropy_avg_new": agg.get("entropy", 0.0),
                "loss/policy_avg_new": agg.get("pg_loss", 0.0),
                "val/ratio_new": agg.get("ratio_mean", 1.0),
                "val/ratio_var_new": float(np.var(
                    [s.get("ratio_mean", 1.0) for s in all_stats]
                )) if all_stats else 0.0,
                "lr": float(self._lr_schedules["policy"](lr_step)),
                "eps": cfg.adam_eps,
                "sparse/kept_frac": kept_frac,
                "eval_response_length": log_responses_length,
                **({"sampler_capture/ratio_drift_new": abs(
                    agg.get("ratio_mean", 1.0) - 1.0
                )} if capture else {}),
                "sec_per_episode": (time.perf_counter() - t_start) / cfg.batch_size,
                # memory series (docs/METRICS.md): saved bytes sized from
                # this update's WIDEST backward bucket (rows bounded by the
                # backward token budget at the max bucket width; resp_len /
                # qr_len are per-row arrays here — variable-length buckets)
                # — the buffer the fused path avoids per grad microbatch
                "mem/peak_bytes_in_use": device_peak_bytes(),
                # 0 on an sp mesh too: the sp grad fn runs there, not fused
                "mem/logits_bytes_saved": float(
                    max(1, backward_budget // (context_length + max_resp))
                    * max_resp * self.mcfg.vocab_size
                    * jnp.dtype(self.params["embed_tokens"].dtype).itemsize
                    if cfg.fused_logprob and not self._sp_on() else 0.0
                ),
                "episode": self.state["episode"],
            }
            # speculative-decode acceptance rows: the dense loop's one
            # definition (RLTrainer._spec_decode_metrics, docs/METRICS.md)
            metrics.update(self._spec_decode_metrics(ro.get("spec_stats")))
            metrics.update(self._paged_metrics(ro.get("paged_stats")))
            # perf/MFU accounting (telemetry/, docs/OBSERVABILITY.md): the
            # dense loop's napkin model with sparse-runtime token counts —
            # scoring/update tokens count only the KEPT (post-filter) rows
            score_forwards = (
                0 if (ref_free and capture)
                else 1 if (ref_free or capture) else 2
            )
            metrics.update(self._perf_metrics(
                step_wall_s=time.perf_counter() - step_t0,
                decode_tokens=batch_size * n * cfg.response_length,
                prefill_tokens=batch_size * n * queries.shape[1],
                score_tokens=score_forwards * len(scores)
                * (context_length + max_resp),
                train_tokens=cfg.num_ppo_epochs * local_bs
                * (context_length + max_resp),
                rollout_s=rollout_s,
                update_s=update_s,
            ))
            if self.latency.enabled:
                # per-update phase durations — the sparse loop times its two
                # phases by hand instead of PhaseTimer, same histogram keys
                self.latency.record("latency/phase_rollout_s", rollout_s)
                self.latency.record("latency/phase_update_s", update_s)
            self.state["global_step"] += 1
            if self.accuracy_func is not None and cfg.eval_steps and \
                    self.state["global_step"] % cfg.eval_steps == 0:
                metrics["eval_accuracy_new"] = float(self.accuracy_func(self))
            # run-health plane: same routing as the dense loop — every row
            # folds into the monitor and the health/* gauges ride along
            metrics.update(
                self.health.observe(self.state["global_step"], metrics)
            )
            kept_scores = raw_scores.reshape(batch_size, n)[rows, keep]
            if self.lineage.enabled:
                # outcome closes the chain: kept rows survived BOTH the
                # keep-1-of-N draw and the sparse zero-advantage filter
                self.lineage.outcome(
                    rollout_index, step=self.state["global_step"],
                    policy_version=self.state["global_step"],
                    kept=int(local_bs),
                    advantage=round(float(scores.mean()), 6),
                    scores=[round(float(s), 6) for s in kept_scores.tolist()],
                    kept_frac=round(kept_frac, 4),
                )
                for r in nz[:8]:
                    self.lineage.note_sample(
                        rollout_index, step=self.state["global_step"],
                        score=round(float(kept_scores[r]), 6),
                        response_chars=len(decoded[r * n + keep[r]]),
                        kept=True,
                    )
            if self.state["global_step"] % cfg.logging_steps == 0:
                self.logger.log(self.state["global_step"], self.state["episode"], metrics)
                kept_decoded = [decoded[i * n + j] for i, j in enumerate(keep)]
                sample_limit = (
                    cfg.log_samples_limit
                    if cfg.log_samples_limit is not None
                    else cfg.num_printed_samples
                )
                self.logger.log_samples(
                    self.state["global_step"], question_strings, kept_decoded,
                    kept_scores, sample_limit,
                )
                if self.lineage.enabled:
                    # full-text records belong to the ledger, not
                    # metrics.jsonl (see MetricsLogger.log_samples)
                    for i, (q, r_txt, s) in enumerate(zip(
                            question_strings, kept_decoded,
                            kept_scores.tolist())):
                        if i >= sample_limit:
                            break
                        self.lineage.event(
                            "sample", rollout_index,
                            step=self.state["global_step"], row=i,
                            query=q, response=r_txt,
                            score=round(float(s), 6),
                        )
            saved_this_step = False
            if cfg.save_steps and self.state["global_step"] % cfg.save_steps == 0:
                self._sparse_save(metrics)
                saved_this_step = True
            if self.tracer.enabled:
                # staleness is structurally 0 here (the sparse loop rejects
                # the orchestrator); kept_rows is the sparse-specific
                # correlation arg
                self.tracer.add_complete(
                    "train.update", span_t0, self.tracer.now_us() - span_t0,
                    step=self.state["global_step"],
                    rollout_index=ro["_index"], staleness=0,
                    policy_version=self.state["global_step"],
                    kept_rows=local_bs,
                )
            # graceful preemption (docs/RESILIENCE.md): the guard installed
            # by RLTrainer.__init__ swallows SIGTERM, so this loop MUST poll
            # it — otherwise a preempted sparse run burns the whole grace
            # window and is SIGKILLed with no emergency checkpoint
            if self._preemption.triggered:
                from nanorlhf_tpu.resilience import Preempted

                if not saved_this_step:
                    self._sparse_save(metrics)
                self.ckpt.wait()
                self.tracer.dump_blackbox(
                    self._telemetry_dir, self.state["global_step"],
                    "preemption",
                )
                self._write_trace()
                raise Preempted(
                    f"SIGTERM at step {self.state['global_step']}: emergency "
                    f"checkpoint committed to {cfg.output_dir}"
                )
        # train() returning implies checkpoints are durable (async saver)
        self.ckpt.wait()
        # balance any open XLA profile window + rewrite trace.json (same
        # end-of-train contract as the dense loop)
        self.profile_window.stop()
        self._write_trace()
        if cfg.export_hf_dir and num_updates is None:
            # handoff artifact (same contract as the dense runtime)
            print(f"exporting HF checkpoint to {cfg.export_hf_dir}")
            self.export_model(cfg.export_hf_dir)
        return self.state

    def _sparse_save(self, metrics: dict):
        """Sparse-runtime checkpoint — shared by the periodic path and the
        SIGTERM emergency path. Persists the consumed-rollout cursor (the
        sparse filter skips updates WITHOUT stepping, so global_step alone
        under-counts the data/PRNG streams on resume) and the resilience
        journal, matching the dense runtime's trainer_state contract."""
        cfg = self.cfg
        self.ckpt.save(
            self.state["global_step"], self.params,
            opt_state=self.opt_state if cfg.save_optimizer_state else None,
            rng_key=self.key,
            metric_old=metrics.get(cfg.metric_for_best_model),
            extra_state={"episode": self.state["episode"],
                         "opt_steps": self.state.get("opt_steps", 0),
                         "rollouts": self.state["rollouts"],
                         "resilience": {
                             "sentinel": self.sentinel.journal(),
                             "watchdog": self.watchdog.journal(),
                         },
                         "health": self.health.journal(),
                         "lineage": self.lineage.journal(),
                         "latency": self.latency.journal()},
        )
