"""One RL trainer runtime for all six algorithms.

The reference ships six copy-paste-forked 700-line trainers
(`/root/reference/{GRPO,PPO,RLOO,ReMax,REINFORCE,RAFT}/*_trainer.py`, ~90%
identical — SURVEY.md §1). Here they collapse into a single runtime plus the
per-algorithm branch points SURVEY.md §2.4 tabulates:

  sampling        n per prompt, ReMax extra greedy rollout
  selection       GRPO keep-1-of-N *before* the logprob pass; RLOO/RAFT after
  KL placement    in-reward (PPO/RLOO/ReMax/REINFORCE/RAFT) vs in-loss (GRPO)
  advantage       group z-score / LOO / greedy delta / GAE / γ-discount / none
  loss            token PPO-clip (+k3 KL) / sequence PPO-clip / +value / SFT

TPU execution model (the design inversions of SURVEY.md §7):
- one HBM-resident sharded param tree serves rollout + scoring + update —
  the reference's per-step disk→vLLM handoff and all CPU offload is gone;
- optimizer state is sharded over the mesh (optax + GSPMD), replacing
  `state_to_device(..., 'cpu')`;
- the PPO-epoch × minibatch × microbatch hierarchy
  (`GRPO/grpo_trainer.py:628-707`) becomes one jitted minibatch update with a
  grad-accumulation `lax.scan` inside, stepped per minibatch (the reference's
  `accelerator.accumulate` steps once per minibatch too);
- rollout-phase logprob scoring runs in fixed-size jitted chunks (the
  `22*2316//(ctx+resp)` memory formula, `grpo_trainer.py:534`, becomes a
  static chunk size picked once).
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from nanorlhf_tpu.algos import (
    best_of_k_indices,
    discounted_returns,
    gae,
    grpo_group_advantage,
    grpo_turn_advantage,
    keep_one_of_n_indices,
    per_turn_terminal_rewards,
    remax_advantage,
    rloo_advantage,
    sparse_terminal_rewards,
)
from nanorlhf_tpu.algos.losses import (
    grpo_loss,
    ppo_clip_loss_sequence,
    ppo_clip_loss_token,
    sft_loss,
    value_loss_clipped,
)
from nanorlhf_tpu.core.config import ModelConfig
from nanorlhf_tpu.core.lora import LoraConfig, init_lora_params, trainable_mask
from nanorlhf_tpu.core.model import (
    padded_forward_hidden,
    padded_forward_logits,
    score_forward,
    unembedding,
)
from nanorlhf_tpu.ops.fused_logprob import chunked_entropy, fused_logprob
from nanorlhf_tpu.ops.masking import (
    INVALID_LOGPROB,
    first_true_indices,
    logprobs_from_logits,
    masked_whiten,
    response_padding_masks,
    truncate_response,
)
from nanorlhf_tpu.parallel.mesh import (MeshConfig, batch_sharding, make_mesh,
                                        shard_params)
from nanorlhf_tpu.sampler import SamplingParams, compose_check, generate
from nanorlhf_tpu.telemetry import (DEFAULT_RULES, HealthConfig,
                                    HealthMonitor, LatencyHub,
                                    LineageLedger, SLO_RULES, SpanTracer,
                                    StatusExporter, flops_param_count,
                                    peak_flops_per_chip, recompile_counter,
                                    update_flops)
from nanorlhf_tpu.trainer.checkpoint import CheckpointManager
from nanorlhf_tpu.trainer.config import AlgoName, RLConfig
from nanorlhf_tpu.trainer.metrics import (MetricsLogger,
                                          staleness_histogram_metrics)

# Rollout-phase forward chunking. Two independent memory models bound the
# chunk: (1) the reference's empirical activation budget `22*2316` tokens
# (`GRPO/grpo_trainer.py:534`), (2) the [tokens, vocab] logits block, capped
# at ~2 GB bf16 (dominant at LLM-sized vocabularies — the fixed constant
# alone would OOM a 16 GB chip at 152k vocab). Chunks take the min of both.
# Tunable via cfg.local_rollout_forward_batch_size.
ACTIVATION_TOKEN_BUDGET = 22 * 2316
_LOGITS_BYTES_BUDGET = 2 * 1024**3


def forward_token_budget(
    vocab_size: int, bytes_per_elem: int = 2, fused_logprob: bool = False
) -> int:
    """`fused_logprob=True` drops the vocab cap: the fused scorer
    (ops/fused_logprob.py) never materializes a [tokens, vocab] logits
    block — its internal chunking bounds that term independently — so the
    activation budget alone sizes the chunk, and score-pass chunks at LLM
    vocabularies grow ~8× (the "larger microbatches" half of the fused
    op's win)."""
    if fused_logprob:
        return ACTIVATION_TOKEN_BUDGET
    vocab_cap = max(1024, _LOGITS_BYTES_BUDGET // (vocab_size * bytes_per_elem))
    return min(ACTIVATION_TOKEN_BUDGET, vocab_cap)


def fused_response_logprobs(tree, mcfg, query_responses, responses, pad_id,
                            context_length: int, cfg, lora_scale: float = 1.0,
                            remat: bool = False, with_entropy: bool = False):
    """The ONE fused hidden→logprob scorer call (ops/fused_logprob.py):
    response-position hidden states → per-token logprobs (+ entropy), with
    the cfg's chunk/impl knobs applied. Shared by the chunked scoring fns,
    the update-pass microbatch loss, and SparseGRPOTrainer's bucket fns so
    fused scoring and fused update numerics can never drift apart."""
    hidden = padded_forward_hidden(
        tree, mcfg, query_responses, pad_id, lora_scale=lora_scale,
        remat=remat, response_context_length=context_length,
    )
    # tied embeddings ride vocab-major ([V, D] + transposed=True): feeding
    # the .T view to the op's Pallas kernel would stage a full [D, V]
    # transposed copy for the custom call
    w, w_transposed = unembedding(mcfg, tree)
    return fused_logprob(
        hidden, w, responses, cfg.temperature,
        chunk=cfg.fused_logprob_chunk, impl=cfg.fused_logprob_impl,
        with_entropy=with_entropy, transposed=w_transposed,
    )


def device_peak_bytes() -> float:
    """Max `peak_bytes_in_use` across local devices — the `mem/peak_bytes_
    in_use` metric and bench's `detail.peak_bytes_in_use`. 0.0 where the
    backend reports no memory stats (the CPU test mesh).

    This is the allocator's PROCESS-LIFETIME high-water mark (monotone): it
    answers "what HBM did this run need", not "what did this phase use" —
    a rollout/prefill or compile-time spike higher than the update pass
    dominates the series from then on. The per-phase fused-vs-naive
    attribution lives in `mem/logits_bytes_saved` (analytic) and the
    vocab-scaling memory_analysis assertion in tests/test_fused_logprob.py.
    """
    peak = 0.0
    for d in jax.local_devices():
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        peak = max(peak, float(stats.get("peak_bytes_in_use", 0.0)))
    return peak


def donate_argnums_on_accel(*nums: int) -> tuple:
    """Buffer donation argnums, gated off on the CPU backend.

    On accelerators donation lets XLA reuse the params/opt-state HBM across
    the update — essential at scale. On the CPU backend it buys nothing
    (host RAM, test-sized models) and is LETHAL in combination with the
    persistent compilation cache on current jaxlib: an executable
    deserialized from the cache with donated buffers segfaults/aborts the
    process a few optimizer steps in (deterministically reproduced via
    repeated train/resume cycles — fresh or warm cache alike; with donation
    off, the same sequence passes). Launchers enable the cache for every
    backend, so this protects CPU demo runs as well as the test suite."""
    return nums if jax.default_backend() != "cpu" else ()


def pad_chunk(rows: np.ndarray, chunk: int) -> np.ndarray:
    """Pad a short final chunk up to `chunk` rows by repeating the last row.

    Chunked jitted passes run at ONE fixed shape: a ragged tail (e.g. a prime
    rollout count) is padded instead of shrinking the chunk — the old
    largest-divisor search silently degenerated to chunk=1 on awkward totals.
    Callers slice results back to the real row count.
    """
    n = rows.shape[0]
    if n >= chunk:
        return rows
    reps = np.repeat(rows[-1:], chunk - n, axis=0)
    return np.concatenate([rows, reps], axis=0)


class RolloutStream:
    """Prefetchable rollout dispatcher over a stateless generation PRNG.

    `dispatch()` pulls the next prompt batch and ASYNC-dispatches generation
    through `body(queries, gen_key)` (nothing blocks until the caller reads
    the returned arrays). `fetch_or_dispatch()` consumes the prefetched
    rollout if one is pending and records its index in
    `trainer.state["rollouts"]` — the consumed-rollout counter that
    checkpoint/resume persists to fast-forward the data stream and re-key
    generation exactly (Sparse-GRPO skip-updates consume a rollout without
    advancing global_step, so global_step alone under-counts).

    Generation keys are `fold_in(base, index)` rather than splits of the
    evolving trainer key: rollout_ahead dispatches rollout k+1 before update
    k's host-side draws, and a shared stream would reorder splits between
    modes (and break bit-exact resume).

    `meter` (an orchestrator.OverlapMeter) records every dispatch's true
    [dispatch, device-ready] window via a waiter thread, so serial /
    rollout_ahead runs report the same rollout/train overlap-fraction
    metric the RolloutOrchestrator does (docs/ORCHESTRATOR.md).
    """

    def __init__(self, trainer, body: Callable, meter=None):
        self._t = trainer
        self._body = body
        self._idx = trainer.state["rollouts"]
        self._pending = None
        if meter is None:
            from nanorlhf_tpu.orchestrator import OverlapMeter

            meter = OverlapMeter()
        self.meter = meter

    def dispatch(self) -> dict:
        from nanorlhf_tpu.orchestrator import note_ready_async

        t = self._t
        queries = np.asarray(next(t._iter))
        key = jax.random.fold_in(t._rollout_base, self._idx)
        lin = getattr(t, "lineage", None)
        if lin is not None and lin.enabled:
            # serial/rollout_ahead runs have no coordinator: the dispatch
            # itself is the lease grant (worker 0, cursor == index)
            lin.lease(self._idx, worker_id=0, cursor=self._idx, length=1)
        t0 = time.perf_counter()  # overlap-meter gen window: consumer clock
        ro = self._body(queries, key)
        # hand the watcher a FROZEN view of the async outputs — blocking on
        # `ro` itself would race the "_index" insertion below
        note_ready_async(self.meter, (ro["gen_out"], ro.get("greedy")), t0,
                         tracer=getattr(t, "tracer", None),
                         span_args={"rollout_index": self._idx})
        ro["_index"] = self._idx
        self._idx += 1
        return ro

    def fetch_or_dispatch(self) -> dict:
        ro = self._pending or self.dispatch()
        self._pending = None
        self._t.state["rollouts"] = ro["_index"] + 1
        return ro

    def prefetch(self) -> None:
        self._pending = self.dispatch()

    @property
    def next_index(self) -> int:
        """Index the next fetch_or_dispatch() will deliver."""
        return self._pending["_index"] if self._pending is not None else self._idx

    def skip(self) -> int:
        """Consume the next data batch WITHOUT dispatching generation — the
        sentinel quarantined this index, and replaying it would pay a full
        rollout (the dominant per-step cost) just to discard the result.
        Only legal with no prefetch pending (an already-dispatched rollout
        can't be undone — the caller discards it instead)."""
        assert self._pending is None
        next(self._t._iter)  # burn the data cursor deterministically
        idx = self._idx
        self._idx += 1
        self._t.state["rollouts"] = self._idx
        lin = getattr(self._t, "lineage", None)
        if lin is not None:
            lin.drop(idx, "sentinel_quarantine",
                     step=self._t.state["global_step"], dispatched=False)
        return idx


class RLTrainer:
    """Unified online-RL trainer.

    Args mirror the reference trainer signature (`GRPO/grpo.py:274-285`):
    config, tokenizer, policy params, (optional) ref params, dataset iterator,
    reward_func(list[str], eos_token) -> array of scores.
    """

    def __init__(
        self,
        config: RLConfig,
        model_config: ModelConfig,
        tokenizer,
        params: dict,
        dataset,
        reward_func: Callable,
        value_params: Optional[dict] = None,
        mesh=None,
        rng_key: Optional[jax.Array] = None,
    ):
        self.cfg = config
        self.mcfg = model_config
        self.tokenizer = tokenizer
        self.reward_func = reward_func
        self.algo = config.algo

        # disaggregated rollouts (config.rollout_devices>0): generation gets
        # its own device group + mesh; training spans the rest. The trainer
        # owns both meshes — an externally built mesh can't be split safely.
        self.rollout_mesh = None
        # per-generation-mesh copies of the frozen LoRA base, keyed by mesh
        # identity: the single disaggregated mesh AND each fleet worker's
        # group get their own once-resharded base (see _rollout_params)
        self._disagg_base: dict = {}
        # per-worker generation meshes (rollout fleet × disaggregation):
        # None = every worker generates on the shared rollout/train mesh
        self.worker_meshes = None
        if config.rollout_devices > 0:
            if mesh is not None:
                raise ValueError(
                    "rollout_devices>0 builds its own train+rollout meshes; "
                    "pass mesh=None"
                )
            from nanorlhf_tpu.parallel.mesh import (
                split_rollout_devices,
                split_worker_groups,
            )

            train_dev, roll_dev = split_rollout_devices(
                jax.devices(), config.rollout_devices
            )
            self.mesh = make_mesh(config.mesh, devices=train_dev)
            rm_cfg = (config.rollout_mesh if config.rollout_mesh is not None
                      else MeshConfig())
            # the whole-group mesh stays: the synchronous/degraded fallback
            # generates on all reserved devices even when the fleet split
            # them per worker
            self.rollout_mesh = make_mesh(rm_cfg, devices=roll_dev)
            if config.rollout_workers > 1:
                self.worker_meshes = [
                    make_mesh(rm_cfg, devices=group)
                    for group in split_worker_groups(
                        roll_dev, config.rollout_workers
                    )
                ]
        else:
            self.mesh = mesh if mesh is not None else make_mesh(config.mesh)
        # Pallas-kernel SPMD hints (core/config.py spmd_mesh): on a mesh
        # whose batch/tensor axes span >1 device the kernel call sites must
        # shard_map themselves or GSPMD all-gathers their operands
        if (self.mesh.shape.get("data", 1) * self.mesh.shape.get("fsdp", 1)
                * self.mesh.shape.get("tensor", 1)) > 1:
            import dataclasses as _dc

            self.mcfg = _dc.replace(
                self.mcfg, spmd_mesh=self.mesh,
                spmd_batch_axes=("data", "fsdp"), spmd_head_axis="tensor",
            )
        if (config.remat_policy != "full"
                and config.remat_policy != self.mcfg.remat_policy):
            # RLConfig only OVERRIDES when set off its default — a caller
            # who customized ModelConfig.remat_policy directly must not be
            # silently reverted by an untouched RLConfig
            import dataclasses as _dc

            self.mcfg = _dc.replace(
                self.mcfg, remat_policy=config.remat_policy
            )
        if config.total_episodes is None:
            # episodes-from-epochs parity (`GRPO/grpo_trainer.py:216-217`)
            if not hasattr(dataset, "__len__"):
                raise ValueError(
                    "total_episodes=None needs a sized dataset (e.g. "
                    "PromptDataset) to derive episodes from num_train_epochs"
                )
            config.total_episodes = int(config.num_train_epochs * len(dataset))
        config.finalize_world(
            self.mesh.shape.get("data", 1) * self.mesh.shape.get("fsdp", 1)
        )

        # ---- async rollout orchestrator (orchestrator/) ------------------
        if config.rollout_orchestrator:
            if config.rollout_ahead:
                raise ValueError(
                    "rollout_orchestrator generalizes rollout_ahead — enable "
                    "one, not both"
                )
            if config.max_staleness < 0:
                raise ValueError(f"max_staleness={config.max_staleness}")
            if config.staleness_policy not in ("wait", "drop"):
                raise ValueError(
                    f"staleness_policy={config.staleness_policy!r}: wait|drop"
                )
        if config.rollout_workers < 1:
            raise ValueError(f"rollout_workers={config.rollout_workers}")
        if config.rollout_workers > 1 and not config.rollout_orchestrator:
            raise ValueError(
                "rollout_workers > 1 is the fleet generalization of the "
                "async pipeline — it requires rollout_orchestrator=True "
                "(docs/FLEET.md)"
            )
        if config.rollout_transport not in ("inprocess", "rpc"):
            raise ValueError(
                f"rollout_transport={config.rollout_transport!r}: "
                "inprocess | rpc"
            )
        if (config.rollout_transport == "rpc"
                and config.rollout_workers <= 1):
            raise ValueError(
                "rollout_transport='rpc' is the fleet's network seam — it "
                "requires rollout_workers > 1 (docs/FLEET.md)"
            )
        if config.offpolicy_correction not in ("truncated_is", "none"):
            raise ValueError(
                f"offpolicy_correction={config.offpolicy_correction!r}"
            )
        # truncated-IS correction needs the behavior policy's logprobs —
        # only the sampler capture provides them; without capture the PPO
        # ratio clip alone absorbs the staleness drift (rollout_ahead's
        # documented behavior)
        if config.rollout_inflight_swaps:
            if not config.rollout_orchestrator:
                raise ValueError(
                    "rollout_inflight_swaps reads the orchestrator's weight "
                    "store mid-generation — it requires "
                    "rollout_orchestrator=True (docs/ORCHESTRATOR.md)"
                )
            if config.rollout_page_size <= 0 or config.rollout_decode_rows <= 0:
                raise ValueError(
                    "rollout_inflight_swaps swaps weights at chunk boundaries "
                    "of the queued paged scheduler — it requires "
                    "rollout_page_size > 0 and rollout_decode_rows > 0 "
                    "(docs/PAGED_CACHE.md)"
                )
        self._use_is = (
            config.rollout_orchestrator
            and config.max_staleness > 0
            and config.sampler_logprob_capture
            and config.offpolicy_correction == "truncated_is"
        )
        # per-segment IS (docs/ORCHESTRATOR.md §in-flight swaps): only
        # meaningful when generations can span >1 policy version; without
        # swaps every row is single-segment and whole-sequence IS is exact
        self._use_seg = self._use_is and config.rollout_inflight_swaps
        self._orchestrator = None
        self._orch_restore_state = None  # journal from a resumed checkpoint
        from nanorlhf_tpu.orchestrator import OverlapMeter

        # ONE meter for the whole trainer lifetime (stream objects are
        # recreated per train() call): the rollout/train overlap fraction
        # accumulates across calls — how bench invokes training
        self._rollout_meter = OverlapMeter()

        self.key = rng_key if rng_key is not None else jax.random.PRNGKey(config.seed)
        # generation PRNG is a dedicated STATELESS stream keyed by rollout
        # index: rollout_ahead dispatches rollout k+1 before update k's
        # host-side key draws, and a shared evolving stream would reorder
        # splits between modes (and break bit-exact resume — the index-keyed
        # form needs only global_step to reconstruct)
        self._rollout_base = jax.random.fold_in(self.key, 0x5E11)

        # ---- LoRA + ref policy -------------------------------------------
        self.lora_cfg = (
            LoraConfig(r=config.lora_r, alpha=config.lora_alpha)
            if config.use_lora
            else None
        )
        if self.lora_cfg and "lora" not in params:
            self.key, k = jax.random.split(self.key)
            params = {**params, "lora": init_lora_params(
                self.mcfg, self.lora_cfg, k, dtype=jnp.bfloat16
            )}
        self.lora_scale = self.lora_cfg.scale if self.lora_cfg else 1.0

        # value-model LoRA (`PPO/ppo.py:301-332`): adapters + score + embed
        # train, backbone frozen — the Adam state for the value tree shrinks
        # from full-model to adapter-sized
        self.value_lora_cfg = (
            LoraConfig(r=config.value_lora_r, alpha=config.value_lora_alpha)
            if (config.value_use_lora and value_params is not None)
            else None
        )
        if self.value_lora_cfg and "lora" not in value_params:
            self.key, k = jax.random.split(self.key)
            value_params = {**value_params, "lora": init_lora_params(
                self.mcfg, self.value_lora_cfg, k,
                dtype=value_params["embed_tokens"].dtype,
            )}
        self.value_lora_scale = (
            self.value_lora_cfg.scale if self.value_lora_cfg else 1.0
        )

        # ref policy = frozen copy of the base weights (the reference loads
        # the same SFT model twice, `GRPO/grpo.py:218-224`); sharded alike.
        # Copy-on-intake: device_put with an unchanged sharding ALIASES the
        # caller's buffers, and the jitted update donates its inputs — without
        # the copy, training would invalidate the arrays the caller passed in.
        # Ref-free mode (kl_coef == 0, r1-zero parity): no copy, no ref pass.
        if config.score_ref_logprobs is False and config.kl_coef != 0.0:
            # dropping the ref while its KL coefficient is live would
            # silently swap the configured ref-KL objective for a
            # KL-to-old-policy (GRPO) or a zeroed penalty (KL-in-reward)
            raise ValueError(
                "score_ref_logprobs=False requires kl_coef == 0 — with a "
                "live KL coefficient the reference logprobs are part of "
                "the objective, not just a metric"
            )
        self._ref_free = not (
            config.score_ref_logprobs
            if config.score_ref_logprobs is not None
            else config.kl_coef != 0.0
        )
        if self._ref_free:
            self.ref_params = None
        else:
            ref = {k: v for k, v in params.items() if k != "lora"}
            self.ref_params = shard_params(jax.tree.map(jnp.copy, ref), self.mesh)
        self.params = shard_params(jax.tree.map(jnp.copy, params), self.mesh)
        self.value_params = (
            shard_params(jax.tree.map(jnp.copy, value_params), self.mesh)
            if value_params is not None else None
        )
        if self.algo == AlgoName.PPO and self.value_params is None:
            raise ValueError("PPO requires value_params")

        # single-process SPMD: the dataloader yields the GLOBAL batch, sharded
        # over the mesh's (data, fsdp) axes on device_put
        self.dataset = dataset
        self._iter = dataset.loader(config.batch_size, config.seed) \
            if hasattr(dataset, "loader") else iter(dataset)

        # ---- optimizer ----------------------------------------------------
        # The optimizer only ever sees the *trainable* partition of the tree
        # (LoRA adapters + embed/lm_head + value model): Adam moments and grad
        # accumulators never materialize for frozen base weights, and frozen
        # weights can never drift via weight decay.
        self.optimizer = self._build_optimizer()
        trainable, _ = self._partition(self._train_tree(self.params, self.value_params))
        self.opt_state = jax.jit(self.optimizer.init)(trainable)

        # ---- resilience layer (resilience/, docs/RESILIENCE.md) ----------
        from nanorlhf_tpu.resilience import (
            FaultInjector,
            PreemptionGuard,
            ProducerWatchdog,
            SentinelConfig,
            TrainingSentinel,
            WatchdogConfig,
            null_guard,
        )

        self.faults = FaultInjector.from_spec(config.fault_spec)
        self.sentinel = TrainingSentinel(SentinelConfig(
            enabled=config.sentinel,
            spike_zscore=config.sentinel_spike_zscore,
            ewma_alpha=config.sentinel_ewma_alpha,
            warmup_steps=config.sentinel_warmup_steps,
            rollback_budget=config.rollback_budget,
        ))
        self.watchdog = ProducerWatchdog(WatchdogConfig(
            restart_budget=config.producer_restart_budget,
            backoff_base=config.producer_backoff_base,
            backoff_max=config.producer_backoff_max,
            backoff_jitter=config.producer_backoff_jitter,
            degrade_to_sync=config.degrade_to_sync,
            # the jitter exists to DE-correlate replicas that share a
            # training seed (SPMD determinism forces that), so the draw
            # seed must mix in per-process identity or every replica
            # computes the same "random" backoff and stampedes anyway
        ), seed=(config.seed << 20) ^ (jax.process_index() << 10)
            ^ os.getpid())
        self._preemption = (
            PreemptionGuard() if config.graceful_preemption else null_guard()
        )

        # ---- telemetry (telemetry/, docs/OBSERVABILITY.md) ---------------
        # Span tracer + flight recorder: off by default — disabled, every
        # recording call is a cheap no-op, so the instrumentation stays
        # inline unconditionally (bench's telemetry A/B is the overhead
        # gate). The MFU/throughput accounting below is plain arithmetic
        # and is emitted regardless of the flag.
        self.tracer = SpanTracer(
            enabled=config.telemetry,
            max_events=config.telemetry_max_events,
            ring_len=config.flight_recorder_len,
        )
        self._telemetry_dir = config.telemetry_dir or config.output_dir
        # analytic model-FLOPs inputs (telemetry/mfu.py — the same napkin
        # model bench.py uses, so the two MFU series cannot drift)
        self._flops_params = flops_param_count(self.params)
        self._peak_flops, self._peak_flops_known = peak_flops_per_chip(
            jax.devices()[0].device_kind, jax.default_backend()
        )
        self._n_devices = len(jax.devices())
        # process-global jax.monitoring backend-compile listener: silent
        # retraces surface as a perf/recompiles step, not a mystery stall
        self._recompiles = recompile_counter()

        self.ckpt = CheckpointManager(
            config.output_dir, config.save_total_limit,
            config.greater_is_better,
            io_retries=config.ckpt_io_retries,
            retry_backoff=config.ckpt_retry_backoff,
            faults=self.faults,
            tracer=self.tracer,
        )
        self.logger = MetricsLogger(config.output_dir, config.report_to)
        # sample lineage ledger (telemetry/lineage.py, docs/OBSERVABILITY.md
        # §6): per-rollout-index provenance — lease, generation, queue,
        # reward, outcome, drop — as rotated JSONL under
        # <telemetry_dir>/lineage/. Off by default; disabled, every emit is
        # a cheap no-op so the instrumentation stays inline unconditionally
        # (bench's detail.lineage A/B is the overhead gate). The key_path
        # string documents the generation-PRNG derivation on lease events
        # (RolloutStream.dispatch below holds the actual fold_in).
        self.lineage = LineageLedger(
            self._telemetry_dir,
            enabled=config.lineage,
            sample_rate=config.lineage_sample_rate,
            key_path="fold_in(fold_in(seed_key, 0x5E11), rollout_index)",
        )
        # latency surface (telemetry/hist.py, docs/OBSERVABILITY.md §7):
        # one mergeable log-bucketed histogram per latency/* key — TTFT,
        # inter-token gap, queue wait, RPC RTT, reward wall, phase
        # durations. Disabled, record() is a cheap no-op so every
        # instrumentation site stays inline (bench's detail.latency A/B
        # is the overhead gate).
        self.latency = LatencyHub(enabled=config.latency)
        # cross-request radix prefix cache (rollout_prefix_cache, serving/
        # radix.py, docs/SERVING.md): the queued rollout path admits rows
        # through it — one long-lived object so the cumulative stats feed
        # pages/shared + /statusz "prefix_cache"; the scheduler resets its
        # pool/tree every generate call (cached KV is params-tied).
        # decode-feature legality is validated ONCE here through the same
        # compose_check generate() re-runs per call — the trainer fails at
        # construction, not mid-run, and the matrix lives in one place
        # (sampler/sampler.py). spec×prefix now COMPOSES (the session
        # seeds the drafter from the radix continuation).
        compose_check(
            SamplingParams(
                compaction_segments=config.rollout_compaction_segments,
                page_size=config.rollout_page_size,
                decode_rows=config.rollout_decode_rows,
                spec_k=config.rollout_spec_k,
                prefill_chunk=config.rollout_prefill_chunk),
            prefix_cache=config.rollout_prefix_cache)
        self.prefix_cache = None
        if config.rollout_prefix_cache:
            from nanorlhf_tpu.serving.radix import RadixCache
            self.prefix_cache = RadixCache()
        # environments (envs/, docs/ENVIRONMENTS.md): env_name builds an
        # Environment around reward_func. A SINGLE-TURN env unwraps back
        # into a plain reward callable, so generation, reward dispatch
        # (retries, the reward.exec fault site), and every metric stay on
        # the exact non-env code path — the parity pin holds by
        # construction. MULTI-TURN swaps the rollout phase for the paged
        # episode driver (envs/rollout.py) and threads a per-token
        # loss_mask through the scored batch.
        self.env = None
        self._env_multi_turn = False
        if config.env_name:
            from nanorlhf_tpu.envs import build_env

            self.env = build_env(
                config.env_name, reward_func,
                max_turns=config.env_max_turns,
                tool_timeout=config.env_tool_timeout,
                eos_token=tokenizer.eos_token,
            )
            if self.env.max_turns == 1:
                self.reward_func = self.env.as_reward_func()
            else:
                self._env_multi_turn = True
                if self.algo != AlgoName.GRPO:
                    raise ValueError(
                        "multi-turn environments (env_max_turns > 1) are "
                        "wired for GRPO only: per-turn advantages ride the "
                        "group z-score path")
                if config.rollout_page_size <= 0:
                    raise ValueError(
                        "env_max_turns > 1 requires rollout_page_size > 0: "
                        "continuation turns are admitted through the paged "
                        "single-row bucketed prefill")
                if (config.rollout_orchestrator or config.rollout_workers > 1
                        or config.rollout_spec_k > 0
                        or config.sampler_logprob_capture
                        or config.rollout_prefix_cache):
                    raise ValueError(
                        "env_max_turns > 1 is incompatible with the "
                        "orchestrator fleet, spec decode, sampler logprob "
                        "capture, and the prefix cache — the episode driver "
                        "owns the rollout phase")
                tt = config.env_turn_tokens or config.response_length
                budget = (tt * config.env_max_turns
                          + config.env_obs_budget * (config.env_max_turns - 1))
                if budget > config.response_length:
                    raise ValueError(
                        f"episode budget {budget} (env_turn_tokens={tt} * "
                        f"{config.env_max_turns} turns + env_obs_budget="
                        f"{config.env_obs_budget} * "
                        f"{config.env_max_turns - 1} observations) exceeds "
                        f"response_length={config.response_length} — the "
                        "packed episode must fit the scored batch")
        # run-health plane (telemetry/health.py, docs/OBSERVABILITY.md §5):
        # every metrics row folds through streaming aggregates + anomaly
        # rules; CRIT dumps a reason="health" blackbox through the tracer
        # (a no-op when telemetry is off) and optionally arms the sentinel.
        # With the latency surface on, the quantile SLO rules ride along
        # and read the hub's histograms directly (p95 TTFT, p99 queue
        # wait, p95 RPC RTT — docs/OBSERVABILITY.md §7).
        rules = DEFAULT_RULES + (SLO_RULES if config.latency else ())
        self.health = HealthMonitor(
            HealthConfig(
                enabled=config.health,
                fast_alpha=config.health_fast_alpha,
                slow_alpha=config.health_slow_alpha,
                warmup=config.health_warmup_steps,
                window_s=config.health_window_s,
                max_events=config.health_max_events,
                blackbox_on_crit=config.health_blackbox_on_crit,
                rules=rules,
            ),
            tracer=self.tracer,
            blackbox_fn=self._health_blackbox,
            on_crit=self._on_health_crit,
            latency=self.latency,
        )
        # live status endpoints (telemetry/exporter.py): off unless
        # cfg.status_port is set (-1 = ephemeral — tests/CI)
        self.exporter = StatusExporter(
            config.status_port,
            host=config.status_host,
            metrics_fn=self._export_metrics,
            health=self.health,
            statusz_fn=self._statusz,
            latency=self.latency,
        )
        from nanorlhf_tpu.utils.profiling import PhaseTimer, ProfileWindow

        self.timer = PhaseTimer(tracer=self.tracer)
        # windowed XLA profiling (docs/OBSERVABILITY.md): polled at the top
        # of every update; opens at cfg.profile_at_step or when the trigger
        # file is touched on a live run
        self.profile_window = ProfileWindow(
            config.profile_dir or os.path.join(config.output_dir, "profile"),
            at_step=config.profile_at_step,
            num_steps=config.profile_num_steps,
            trigger_file=config.profile_trigger_file
            or os.path.join(config.output_dir, "PROFILE"),
        )
        self._update_fn = self._make_update_fn()
        # int8 rollout weights (core/quant.py): quantize the frozen base
        # projections once under LoRA; full-FT re-quantizes at each dispatch
        self._quant_layers = None
        if config.rollout_quant == "int8":
            self._refresh_quant_layers()
        elif config.rollout_quant != "none":
            raise ValueError(f"rollout_quant={config.rollout_quant!r}")
        # int8 KV cache: a rollout-only ModelConfig variant — scoring/update
        # paths keep the exact config (they never build a cache)
        if config.kv_cache_quant not in ("none", "int8"):
            raise ValueError(f"kv_cache_quant={config.kv_cache_quant!r}")
        import dataclasses as _dc

        self._rollout_mcfg = (
            _dc.replace(self.mcfg, kv_cache_quant=config.kv_cache_quant)
            if config.kv_cache_quant != self.mcfg.kv_cache_quant else self.mcfg
        )
        if self.rollout_mesh is not None:
            # generation compiles against the ROLLOUT mesh: its kernel SPMD
            # hints must name that mesh (the train-mesh hints inherited from
            # self.mcfg would shard_map kernels over devices generation
            # doesn't run on)
            rsh = self.rollout_mesh.shape
            multi = (rsh.get("data", 1) * rsh.get("fsdp", 1)
                     * rsh.get("tensor", 1)) > 1
            self._rollout_mcfg = _dc.replace(
                self._rollout_mcfg,
                spmd_mesh=self.rollout_mesh if multi else None,
                spmd_batch_axes=("data", "fsdp"),
                spmd_head_axis="tensor",
            )
        # opt_steps counts ACTUAL optimizer.update calls — the schedule index
        # for the `lr` metric (a derived formula drifts when the minibatch
        # loop doesn't divide evenly)
        # "rollouts" counts CONSUMED rollouts (== global_step for the dense
        # runtime; >= for sparse, whose all-zero-advantage skips consume a
        # batch without stepping) — the resume cursor for data + PRNG streams
        self.state = {"episode": 0, "global_step": 0, "opt_steps": 0,
                      "rollouts": 0}

    # ------------------------------------------------------------------ #
    # rollout weight quantization
    # ------------------------------------------------------------------ #

    def _refresh_quant_layers(self, src: Optional[dict] = None):
        from nanorlhf_tpu.core.quant import quantize_layers

        src = self.params if src is None else src
        q = quantize_layers(src["layers"])
        self._quant_layers = shard_params({"layers": q}, self.mesh)["layers"]

    def _rollout_params(self, tree: Optional[dict] = None, mesh=None):
        """The param tree generation samples from: exact everywhere, except
        int8 base projections when rollout_quant is on (LoRA/embed/norm are
        always the live exact arrays — see core/quant.py). With a dedicated
        rollout mesh, the view is re-sharded onto it here — the once-per-
        dispatch param sync (an async device_put tree; the only transfer
        that crosses the train/rollout device groups). `tree` overrides the
        live self.params source — the orchestrator's producer thread passes
        a PUBLISHED snapshot so generation never races the jitted update's
        buffer donation. `mesh` overrides the destination mesh — a fleet
        worker passes its own device group's mesh (docs/FLEET.md)."""
        src = self.params if tree is None else tree
        if self._quant_layers is None:
            tree = src
        else:
            if not self.cfg.use_lora:  # full FT: base changed since last update
                self._refresh_quant_layers(src)
            from nanorlhf_tpu.core.quant import rollout_view

            tree = rollout_view(src, self._quant_layers)
        mesh = mesh if mesh is not None else self.rollout_mesh
        if mesh is not None:
            if self.cfg.use_lora:
                # LoRA freezes the base: re-shard it onto each generation
                # mesh ONCE and reuse; per dispatch only the live adapter
                # subtree (MBs, not the GBs of base projections) crosses
                # the train/rollout device groups
                base = self._disagg_base.get(id(mesh))
                if base is None:
                    base = self._disagg_base[id(mesh)] = shard_params(
                        {k: v for k, v in tree.items() if k != "lora"},
                        mesh,
                    )
                live = shard_params({"lora": tree["lora"]}, mesh)
                tree = {**base, **live}
            else:
                tree = shard_params(tree, mesh)
        return tree

    # ------------------------------------------------------------------ #
    # async rollout orchestrator (orchestrator/, docs/ORCHESTRATOR.md)
    # ------------------------------------------------------------------ #

    def _policy_snapshot(self) -> dict:
        """An immutable view of the current policy for the weight store:
        the TRAINABLE leaves are copied (the jitted update donates exactly
        those buffers — a producer-thread generation reading them live
        would race the donation), frozen leaves alias the live arrays
        (never donated, never mutated). Under LoRA the copy is MBs of
        adapters; full fine-tuning pays a full-tree copy per publish."""
        mask = trainable_mask(self.params, self.lora_cfg)
        return jax.tree.map(
            lambda p, m: jnp.copy(p) if m else p, self.params, mask
        )

    def _ensure_orchestrator(self, body: Callable):
        """Create (once) the rollout pipeline — the single producer thread
        (rollout_workers == 1) or the N-worker fleet (docs/FLEET.md); both
        share the consumer surface, so everything downstream (watchdog,
        sentinel, checkpoints) is mode-blind. The pipeline outlives train()
        calls — it stays warm across repeated train(num_updates=1)
        invocations (how bench measures) — and is torn down by close() or
        resume_from_checkpoint()."""
        if self._orchestrator is None:
            cfg = self.cfg
            if cfg.rollout_workers > 1:
                from nanorlhf_tpu.orchestrator import FleetOrchestrator
                from nanorlhf_tpu.orchestrator.fleet import FleetConfig

                rpc_cfg = None
                if cfg.rollout_transport == "rpc":
                    from nanorlhf_tpu.orchestrator.rpc import RpcConfig

                    rpc_cfg = RpcConfig(
                        host=cfg.fleet_rpc_host,
                        port=cfg.fleet_rpc_port,
                        call_timeout=cfg.fleet_rpc_timeout,
                        attempts=cfg.fleet_rpc_attempts,
                        backoff_base=cfg.fleet_rpc_backoff_base,
                    )

                def batch_fn():
                    # the COORDINATOR is the sole consumer of the data
                    # iterator (under its lock, in strict index order) and
                    # caches each lease's batches — reassignment replays
                    # the same batch without re-burning the cursor
                    return np.asarray(next(self._iter))

                def fleet_dispatch(index: int, queries, tree: dict,
                                   worker_id: int,
                                   weight_refresh=None) -> dict:
                    # the same stateless index-keyed PRNG stream as every
                    # other mode: WHICH worker generates a sample can never
                    # change WHAT is generated (staleness-0 bit parity).
                    # `weight_refresh` arrives only when the transport saw
                    # inflight_swaps=True (4-arg calls stay valid).
                    key = jax.random.fold_in(self._rollout_base, index)
                    gen_mesh = None
                    if self.worker_meshes:
                        gen_mesh = self.worker_meshes[
                            worker_id % len(self.worker_meshes)
                        ]
                    return body(queries, key, tree, gen_mesh, weight_refresh)

                self._orchestrator = FleetOrchestrator(
                    dispatch_fn=fleet_dispatch,
                    batch_fn=batch_fn,
                    initial_params=self._policy_snapshot(),
                    n_workers=cfg.rollout_workers,
                    start_index=self.state["rollouts"],
                    max_staleness=cfg.max_staleness,
                    policy=cfg.staleness_policy,
                    meter=self._rollout_meter,
                    restore=self._orch_restore_state,
                    heartbeat=cfg.producer_heartbeat,
                    faults=self.faults,
                    tracer=self.tracer,
                    lineage=self.lineage,
                    latency=self.latency,
                    fleet=FleetConfig(
                        lease_size=cfg.fleet_lease_size,
                        failure_budget=cfg.fleet_failure_budget,
                        quarantine_base=cfg.fleet_quarantine_base,
                        quarantine_max=cfg.fleet_quarantine_max,
                        backoff_jitter=cfg.fleet_backoff_jitter,
                        straggler_factor=cfg.fleet_straggler_factor,
                        initial_deadline_s=cfg.fleet_initial_deadline,
                        worker_timeout_s=cfg.fleet_initial_deadline,
                        seed=cfg.seed,
                    ),
                    transport=cfg.rollout_transport,
                    rpc=rpc_cfg,
                    inflight_swaps=cfg.rollout_inflight_swaps,
                )
            else:
                from nanorlhf_tpu.orchestrator import RolloutOrchestrator

                def dispatch(index: int, tree: dict) -> dict:
                    # the producer is the SOLE consumer of the data
                    # iterator, and keys come from the stateless
                    # index-keyed stream — the same (data, PRNG) cursors
                    # the synchronous trainer uses, so checkpoint/resume
                    # fast-forwards reproduce the streams
                    queries = np.asarray(next(self._iter))
                    key = jax.random.fold_in(self._rollout_base, index)
                    refresh = None
                    if cfg.rollout_inflight_swaps:
                        # serial/in-process path: poll the orchestrator's
                        # weight store directly (no transport hop), seeded
                        # with the dispatch version the producer pinned
                        from nanorlhf_tpu.orchestrator.weight_store import (
                            make_swap_refresh,
                            store_poll,
                        )

                        refresh = make_swap_refresh(
                            store_poll(self._orchestrator.store),
                            have_version=self._orchestrator.store.version,
                            faults=self.faults, worker=0,
                        )
                    return body(queries, key, tree, None, refresh)

                self._orchestrator = RolloutOrchestrator(
                    dispatch_fn=dispatch,
                    initial_params=self._policy_snapshot(),
                    start_index=self.state["rollouts"],
                    max_staleness=cfg.max_staleness,
                    policy=cfg.staleness_policy,
                    meter=self._rollout_meter,
                    restore=self._orch_restore_state,
                    heartbeat=cfg.producer_heartbeat,
                    faults=self.faults,
                    tracer=self.tracer,
                    lineage=self.lineage,
                    latency=self.latency,
                )
            self._orch_restore_state = None
        return self._orchestrator

    def _reset_data_iterator(self):
        """Rebuild the deterministic loader and fast-forward to the
        consumed-rollout cursor — shared by resume, producer restart, and
        the degraded-mode fallback (all three re-draw anything a dead
        producer may have pulled past the cursor)."""
        self._iter = self.dataset.loader(self.cfg.batch_size, self.cfg.seed) \
            if hasattr(self.dataset, "loader") else iter(self.dataset)
        for _ in range(self.state["rollouts"]):
            next(self._iter)

    def _restart_producer(self, body: Callable):
        """Watchdog restart: tear down the dead pipeline, carry the queue's
        cumulative counters forward, reset the data cursor, and rebuild.
        The index-keyed generation PRNG + deterministic loader make the
        redrawn samples' token streams identical to what the dead producer
        would have delivered (at staleness 0 exactly; at staleness > 0 the
        redraw may sample from fresher weights — the resume semantics)."""
        old = self._orchestrator
        if old is not None:
            self._orch_restore_state = old.journal()
            old.close(join_timeout=5.0)
            self._orchestrator = None
        self._reset_data_iterator()
        return self._ensure_orchestrator(body)

    def rollout_overlap_frac(self) -> float:
        """Cumulative rollout/train overlap fraction (orchestrator metric;
        also measured for serial / rollout_ahead runs) — bench reads this."""
        return self._rollout_meter.overlap_fraction()

    @staticmethod
    def _spec_decode_metrics(spec_stats) -> dict:
        """rollout/draft_acceptance + accepted_per_step + spec_verify_steps
        rows (docs/METRICS.md) from a speculative-decode stats dict — the
        ONE definition of these metrics, shared by the dense and sparse
        loops so the two runtimes can never report differently-defined
        series under the same names. {} when the lever is off."""
        if spec_stats is None:
            return {}
        v_steps = float(np.asarray(spec_stats["verify_steps"]))
        return {
            # fraction of drafted tokens accepted; tokens emitted per live
            # row per verify dispatch (the monolithic loop's is identically
            # 1); and the dispatch count itself
            "rollout/draft_acceptance": (
                float(np.asarray(spec_stats["accepted"]))
                / max(float(np.asarray(spec_stats["drafted"])), 1.0)
            ),
            "rollout/accepted_per_step": (
                float(np.asarray(spec_stats["emitted"]))
                / max(float(np.asarray(spec_stats["row_steps"])), 1.0)
            ),
            "rollout/spec_verify_steps": v_steps,
        }

    @staticmethod
    def _paged_metrics(paged_stats) -> dict:
        """rollout/page_utilization + pages_recycled + admitted_midloop rows
        (docs/METRICS.md) from a paged-cache stats dict — shared by the
        dense and sparse loops like `_spec_decode_metrics`. The monolithic
        paged path reports utilization with zero recycling/admissions; the
        continuous-batching scheduler reports all three. {} when
        rollout_page_size is off."""
        if paged_stats is None:
            return {}
        out = {
            "rollout/page_utilization": float(
                np.asarray(paged_stats["page_utilization"])),
            "rollout/pages_recycled": float(
                np.asarray(paged_stats["pages_recycled"])),
            "rollout/admitted_midloop": float(
                np.asarray(paged_stats["admitted_midloop"])),
        }
        if "prefix_hit_frac" in paged_stats:
            # radix prefix cache active (rollout_prefix_cache): suffix-only
            # admission prefill + refcount-shared pages (docs/SERVING.md)
            out["rollout/prefix_hit_frac"] = float(
                paged_stats["prefix_hit_frac"])
            out["pages/shared"] = float(paged_stats["shared_pages"])
        if "dispatch_events" in paged_stats:
            # decode-session accounting (continuous batching only,
            # sampler/paged/session.py): total device dispatches =
            # admission launches + decode/verify chunk iterations — the
            # number the spec×prefix composition gate drives down — plus
            # the chunked-prefill admission counters
            out["session/dispatch_events"] = float(
                paged_stats["dispatch_events"])
            out["session/chunked_admissions"] = float(
                paged_stats["chunked_admissions"])
            out["session/prefill_backlog"] = float(
                paged_stats["prefill_backlog_peak"])
        return out

    # ------------------------------------------------------------------ #
    # telemetry: perf/MFU accounting (telemetry/, docs/OBSERVABILITY.md)
    # ------------------------------------------------------------------ #

    def _perf_metrics(self, *, step_wall_s: float, decode_tokens: float,
                      prefill_tokens: float, score_tokens: float,
                      train_tokens: float, rollout_s: float,
                      update_s: float) -> dict:
        """Per-update throughput/MFU rows (docs/METRICS.md `perf/*`): the
        analytic napkin FLOPs model from telemetry/mfu.py (shared with
        bench.py — one formula, two consumers). Token counts come from the
        caller's actual per-phase work; the dense and sparse loops both
        feed this, so the two runtimes report comparable series.

        `perf/tokens_per_sec_rollout` divides by the trainer-OBSERVED
        rollout phase seconds: under the orchestrator that window is just
        the fetch wait, so the metric reads as effective pipeline
        throughput (it rises as overlap hides generation), not raw
        generation speed — the producer's own speed is visible in the
        trace spans."""
        flops = update_flops(
            self._flops_params,
            decode_tokens=decode_tokens, prefill_tokens=prefill_tokens,
            score_tokens=score_tokens, train_tokens=train_tokens,
        )
        all_tokens = decode_tokens + prefill_tokens + score_tokens + train_tokens
        return {
            "perf/mfu": flops / max(step_wall_s, 1e-9)
            / (self._peak_flops * self._n_devices),
            # 0.0 = the peak-FLOPs table fell back to a nominal constant
            # (e.g. CPU 1e12) and perf/mfu above is not a trustworthy
            # utilization number — consumers (bench, /statusz) flag it
            "perf/peak_flops_known": 1.0 if self._peak_flops_known else 0.0,
            "perf/tokens_per_sec_step": all_tokens / max(step_wall_s, 1e-9),
            "perf/tokens_per_sec_update": train_tokens / max(update_s, 1e-9),
            "perf/tokens_per_sec_rollout": (decode_tokens + prefill_tokens)
            / max(rollout_s, 1e-9),
            "perf/model_flops_per_step": flops,
            # cumulative real backend compiles (jax.monitoring): a step
            # where this increments mid-run is a silent retrace
            "perf/recompiles": float(self._recompiles.count),
            "perf/recompile_seconds": self._recompiles.seconds,
            "telemetry/spans_dropped": float(self.tracer.dropped),
        }

    # ------------------------------------------------------------------ #
    # run-health plane (telemetry/health.py + exporter.py)
    # ------------------------------------------------------------------ #

    def _health_blackbox(self, step: int, extra: dict):
        """CRIT hook: dump the flight-recorder ring with reason="health"
        (no-op returning None when the tracer is disabled)."""
        return self.tracer.dump_blackbox(
            self._telemetry_dir, step, "health", extra=extra
        )

    def _on_health_crit(self, step: int, rules: list):
        """Optional escalation: a CRIT verdict arms the TrainingSentinel
        when it was configured off (cfg.health_arm_sentinel) — divergence
        detected by the health plane turns on rollback protection for the
        rest of the run."""
        if self.cfg.health_arm_sentinel and not self.sentinel.cfg.enabled:
            self.sentinel.cfg.enabled = True
            print(f"[health] CRIT at step {step} ({', '.join(rules)}): "
                  "arming training sentinel")

    def _statusz(self) -> dict:
        """JSON state for the exporter's /statusz (called on HTTP threads —
        everything read here is either immutable after __init__ or behind
        its own lock)."""
        latest = self.logger.latest()
        orch = self._orchestrator  # local ref: trainer may close it
        out = {
            # nanolint: allow[determinism.wall-clock] statusz provenance stamp for scrapers, never a duration input
            "unix_time": time.time(),
            "algo": self.cfg.algo.value,
            "step": self.state.get("global_step", 0),
            "episode": self.state.get("episode", 0),
            "policy_version": (orch.version if orch is not None
                               else self.state.get("global_step", 0)),
            "devices": self._n_devices,
            "mfu": latest.get("perf/mfu"),
            # the peak-FLOPs table fell back to a nominal constant → the
            # MFU number above is not trustworthy
            "mfu_trusted": bool(self._peak_flops_known),
            "peak_flops_per_chip": self._peak_flops,
            "staleness_avg": latest.get("orchestrator/staleness"),
            "health": self.health.snapshot(),
            # drop-reason counts since start + the last-N sample ring
            # (telemetry/lineage.py) — the live companion to the ledger
            "lineage": self.lineage.statusz(),
            # latency surface (telemetry/hist.py): per-key count/mean/
            # p50/p95/p99/min/max from the streaming histograms; {} when
            # cfg.latency is off
            "latency": self.latency.snapshot(),
            # paged KV cache (rollout_page_size > 0): latest rollout's pool
            # occupancy / recycling / mid-loop admission snapshot; None when
            # the lever is off
            "pages": getattr(self, "_pages_status", None),
            # radix prefix cache (rollout_prefix_cache): tree size, pool
            # occupancy, cumulative hit/COW/eviction counters
            # (serving/radix.py snapshot); None when the lever is off
            "prefix_cache": (self.prefix_cache.snapshot()
                             if self.prefix_cache is not None else None),
            # decode session (continuous batching): end-of-rollout snapshot
            # — resident rows + per-row feature flags, chunked-prefill
            # backlog, dispatch counters (sampler/paged/session.py
            # status()); None until a queued rollout has run
            "session": getattr(self, "_session_status", None),
        }
        if orch is not None and hasattr(orch, "status_snapshot"):
            out.update(orch.status_snapshot())
        return out

    def _export_metrics(self) -> dict:
        """/metrics provider: the latest flat metric row plus the lineage
        ledger's labeled drop-reason gauges
        (`lineage/dropped_total{reason=...}`) — render_prometheus keeps the
        label set verbatim, so these survive validate_prometheus_text."""
        return {**self.logger.latest(), **self.lineage.metric_rows()}

    # ------------------------------------------------------------------ #
    # optimizer
    # ------------------------------------------------------------------ #

    def _train_tree(self, params, value_params):
        return {"policy": params, "value": value_params} if value_params is not None \
            else {"policy": params}

    def _trainable_tree_mask(self, train_tree):
        mask = {"policy": trainable_mask(train_tree["policy"], self.lora_cfg)}
        if train_tree.get("value") is not None:
            vmask = trainable_mask(train_tree["value"], self.value_lora_cfg)
            if self.value_lora_cfg is not None:
                # score head always trains (`value_modules_to_save` parity,
                # `PPO/ppo.py:157-159`); trainable_mask doesn't know it
                vmask["score"] = True
            mask["value"] = vmask
        return mask

    def _partition(self, train_tree):
        """Split into (trainable, frozen) trees with None at excluded leaves
        (equinox-style partition/combine)."""
        mask = self._trainable_tree_mask(train_tree)
        trainable = jax.tree.map(lambda p, m: p if m else None, train_tree, mask)
        frozen = jax.tree.map(lambda p, m: None if m else p, train_tree, mask)
        return trainable, frozen

    @staticmethod
    def _combine(trainable, frozen):
        return jax.tree.map(
            lambda t, f: f if t is None else t,
            trainable, frozen,
            is_leaf=lambda x: x is None,
        )

    def _build_optimizer(self):
        cfg = self.cfg
        total_steps = max(
            1, cfg.num_total_batches * cfg.num_ppo_epochs * cfg.num_mini_batches
        )

        def sched(lr):
            # cosine_with_min_lr parity (`GRPO/grpo.py:119-121`);
            # warmup_steps=0 must not hit optax's 0-step linear ramp (NaN)
            if cfg.warmup_steps > 0:
                return optax.warmup_cosine_decay_schedule(
                    init_value=0.0,
                    peak_value=lr,
                    warmup_steps=cfg.warmup_steps,
                    decay_steps=total_steps,
                    end_value=lr * cfg.min_lr_rate,
                )
            return optax.cosine_decay_schedule(
                lr, decay_steps=total_steps, alpha=cfg.min_lr_rate
            )

        def adamw(lr):
            tx = optax.adamw(
                sched(lr), eps=cfg.adam_eps, weight_decay=cfg.weight_decay
            )
            if cfg.max_grad_norm:
                tx = optax.chain(optax.clip_by_global_norm(cfg.max_grad_norm), tx)
            return tx

        # separate policy/value LR groups (`PPO/ppo_trainer.py:341-402`);
        # operates on the trainable-only partition, so no freeze transform
        value_lr = cfg.value_learning_rate or cfg.learning_rate
        # the schedule fns are kept for the `lr` metric (the reference logs
        # `lr_scheduler.get_last_lr()`, `GRPO/grpo_trainer.py:744`)
        self._lr_schedules = {
            "policy": sched(cfg.learning_rate), "value": sched(value_lr)
        }
        return optax.multi_transform(
            {"policy": adamw(cfg.learning_rate), "value": adamw(value_lr)},
            param_labels=lambda tree: {
                k: jax.tree.map(lambda _: k, v) for k, v in tree.items()
            },
        )

    # ------------------------------------------------------------------ #
    # jitted pieces
    # ------------------------------------------------------------------ #

    def _make_update_fn(self):
        cfg, mcfg = self.cfg, self.mcfg
        algo = self.algo
        lora_scale = self.lora_scale
        value_lora_scale = self.value_lora_scale
        remat = cfg.gradient_checkpointing
        pad_id = self.tokenizer.pad_token_id
        optimizer = self.optimizer
        grad_accum = cfg.gradient_accumulation_steps
        # truncated-IS off-policy correction (orchestrator staleness > 0 with
        # captured behavior logprobs): static for the whole run, so the
        # minibatch dict's key set — and the jitted update — never changes
        use_is = self._use_is
        is_truncation = cfg.offpolicy_is_truncation
        # per-segment IS (rollout_inflight_swaps): same static-key-set
        # contract — segment_ages is in every minibatch or in none, so the
        # jitted update never recompiles mid-run
        use_seg = self._use_seg

        combine = self._combine
        sp_on = self._sp_on()
        sp_mesh, sp_fsdp_axis = self.mesh, self._fsdp_axis()

        def microbatch_loss(trainable, frozen, mb, context_length):
            train_tree = combine(trainable, frozen)
            if sp_on:
                from nanorlhf_tpu.parallel.sp import sp_score_logprobs

                # ring-attention sequence-parallel forward; the global
                # [B, T, V] logits never materialize — the entropy stat
                # comes back as a per-shard mean pmean'd over the ring.
                # attn_impl matches the SCORING pass (the flash ring is
                # differentiable, `_ring_core_bwd`): old/ref logprobs and
                # new logprobs come from the same kernels, so exp(new−old)
                # ratios carry no kernel-mismatch offset (ADVICE r3)
                new_logprobs, entropy = sp_score_logprobs(
                    train_tree["policy"], mcfg, mb["query_responses"], pad_id,
                    cfg.temperature, sp_mesh, fsdp_axis=sp_fsdp_axis,
                    lora_scale=lora_scale, remat=remat, with_entropy=True,
                    entropy_from_position=context_length - 1,
                    attn_impl=mcfg.attention_impl,
                )
                new_logprobs = new_logprobs[:, context_length - 1 : -1]
            elif cfg.fused_logprob:
                # fused hidden→logprob path (ops/fused_logprob.py): the
                # [micro, T_resp, V] logits block never materializes — the
                # chunked linear-cross-entropy op emits per-token logprobs
                # AND the entropy stat in one pass, and its custom-VJP
                # backward recomputes chunk logits instead of saving them
                new_logprobs, ent_tok = fused_response_logprobs(
                    train_tree["policy"], mcfg, mb["query_responses"],
                    mb["responses"], pad_id, context_length, cfg,
                    lora_scale=lora_scale, remat=remat, with_entropy=True,
                )
                # `policy/entropy_avg_new`, unmasked mean like the reference
                # (`GRPO/grpo_trainer.py:679-687`); the op's entropy output
                # already carries stop-gradient semantics
                entropy = jax.lax.stop_gradient(ent_tok.mean())
            else:
                logits = padded_forward_logits(
                    train_tree["policy"], mcfg, mb["query_responses"], pad_id,
                    lora_scale=lora_scale, remat=remat,
                    response_context_length=context_length,
                )
                # true update-pass entropy over the temperature-scaled logits
                # — `policy/entropy_avg_new`, unmasked mean like the
                # reference (`GRPO/grpo_trainer.py:679-687`) — computed
                # CHUNKED (no stop-gradient f32 full-logits copy; the bf16
                # logits buffer itself is this naive path's cost)
                entropy = jax.lax.stop_gradient(chunked_entropy(
                    logits, cfg.temperature, chunk=cfg.fused_logprob_chunk
                ).mean())
                new_logprobs = logprobs_from_logits(
                    logits, mb["responses"], cfg.temperature
                )
            new_logprobs = jnp.where(
                mb["padding_mask"], INVALID_LOGPROB, new_logprobs
            )
            mask = ~mb["padding_mask"]
            # multi-turn environments: observation/tool tokens are
            # conditioned on but never scored — the env driver's per-token
            # loss_mask (False on observation spans) joins the pad mask
            # here, upstream of every algorithm branch. The key is absent
            # outside env multi-turn runs, so the degenerate case compiles
            # the identical program.
            if "loss_mask" in mb:
                mask = mask & mb["loss_mask"]
            # behavior (stale sampling policy) logprobs for truncated IS —
            # None keeps every loss in its exact synchronous form
            behavior = mb["behavior_logprobs"] if use_is else None
            # per-token policy ages (newest version in row − token's
            # segment version): widens the IS weight into its per-segment
            # form; None keeps the whole-sequence weight bit-exact
            seg_ages = mb["segment_ages"] if use_seg else None

            if algo == AlgoName.GRPO:
                loss, aux = grpo_loss(
                    new_logprobs, mb["logprobs"], mb["ref_logprobs"],
                    mb["advantages"], mask, cfg.cliprange, cfg.kl_coef,
                    behavior_logprobs=behavior, is_truncation=is_truncation,
                    segment_ages=seg_ages,
                )
            elif algo == AlgoName.RLOO:
                loss, aux = ppo_clip_loss_sequence(
                    new_logprobs, mb["logprobs"], mb["advantages_seq"], mask,
                    cfg.cliprange,
                    behavior_logprobs=behavior, is_truncation=is_truncation,
                    segment_ages=seg_ages,
                )
            elif algo == AlgoName.RAFT:
                # RAFT's SFT objective has no ratio to correct — best-of-K
                # selection is off-policy by construction
                loss, aux = sft_loss(new_logprobs, mask)
            elif algo == AlgoName.PPO:
                pg_loss, aux = ppo_clip_loss_token(
                    new_logprobs, mb["logprobs"], mb["advantages"], mask,
                    cfg.cliprange,
                    behavior_logprobs=behavior, is_truncation=is_truncation,
                    segment_ages=seg_ages,
                )
                if sp_on:
                    from nanorlhf_tpu.parallel.sp import sp_score_values

                    # same attn_impl as the value SCORING pass (flash ring
                    # is differentiable) — vpred and mb["values"] come from
                    # the same kernels (ADVICE r3)
                    vpred = sp_score_values(
                        train_tree["value"], mcfg, mb["query_responses"],
                        pad_id, sp_mesh, fsdp_axis=sp_fsdp_axis,
                        lora_scale=value_lora_scale, remat=remat,
                        attn_impl=mcfg.attention_impl,
                    )[:, context_length - 1 : -1, 0]
                else:
                    vpred = score_forward(
                        train_tree["value"], mcfg, mb["query_responses"], pad_id,
                        lora_scale=value_lora_scale, remat=remat,
                    )[:, context_length - 1 : -1, 0]
                vpred = jnp.where(mb["padding_mask_p1"], 0.0, vpred)
                vf_loss, vf_aux = value_loss_clipped(
                    vpred, mb["values"], mb["returns"], ~mb["padding_mask_p1"],
                    cfg.cliprange_value,
                )
                loss = pg_loss + cfg.vf_coef * vf_loss
                aux = {**aux, **vf_aux}
            else:  # REINFORCE / ReMax: token-level PPO-clip
                loss, aux = ppo_clip_loss_token(
                    new_logprobs, mb["logprobs"], mb["advantages"], mask,
                    cfg.cliprange,
                    behavior_logprobs=behavior, is_truncation=is_truncation,
                    segment_ages=seg_ages,
                )
            aux["entropy"] = entropy
            return loss, aux

        mesh = self.mesh
        from jax.sharding import NamedSharding, PartitionSpec as P

        def update_minibatch(trainable, frozen, opt_state, minibatch, context_length):
            """One optimizer step over `grad_accum` scanned microbatches.

            Grad accumulation, Adam moments and the optax update all live on
            the trainable-only partition — frozen base weights have no
            optimizer footprint and cannot drift.
            """

            def micro(carry, g_idx):
                # slice microbatch g out of the [micro, grad_accum, ...]
                # stack: indexing the REPLICATED axis 1 keeps the sharded
                # row axis 0 intact — no resharding inside the hot loop
                mb = jax.tree.map(
                    lambda x: jax.lax.dynamic_index_in_dim(
                        x, g_idx, axis=1, keepdims=False
                    ),
                    stacked,
                )
                grads_acc = carry
                (loss, aux), grads = jax.value_and_grad(
                    microbatch_loss, has_aux=True
                )(trainable, frozen, mb, context_length)
                grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
                return grads_acc, aux

            zero = jax.tree.map(
                lambda x: jnp.zeros_like(x, dtype=jnp.float32), trainable
            )
            # [local_mini_batch, ...] -> [micro, grad_accum, ...]: the
            # SHARDED row dim stays major, so GSPMD lowers the reshape
            # comm-free (device-contiguous rows stay device-contiguous);
            # reshaping to [grad_accum, micro] instead puts the tiny scan
            # axis first and forces "involuntary full rematerialization"
            # (replicate-then-repartition) every optimizer step (VERDICT r3
            # #2). Microbatch g is the strided row set {g, G+g, 2G+g, ...} —
            # assignment is arbitrary under grad accumulation: the summed
            # gradient and mean stats are partition-invariant.
            stacked = jax.tree.map(
                lambda x: x.reshape((-1, grad_accum) + x.shape[1:]), minibatch
            )
            stacked = jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    x,
                    NamedSharding(
                        mesh,
                        P(("data", "fsdp"), *([None] * (x.ndim - 1))),
                    ),
                ),
                stacked,
            )
            grads, auxes = jax.lax.scan(
                micro, zero, jnp.arange(grad_accum, dtype=jnp.int32)
            )
            grads = jax.tree.map(lambda g: g / grad_accum, grads)
            updates, opt_state = optimizer.update(grads, opt_state, trainable)
            trainable = optax.apply_updates(trainable, updates)
            stats = jax.tree.map(jnp.mean, auxes)
            # global gradient norm: the training sentinel's finite check
            # reads it, and policy/grad_norm_new is a useful health series
            # regardless — a scalar reduction, negligible next to the update
            stats = {**stats, "grad_norm": optax.global_norm(grads)}
            return trainable, opt_state, stats

        from functools import partial

        return partial(
            jax.jit, static_argnums=(4,),
            donate_argnums=donate_argnums_on_accel(0, 2),
        )(update_minibatch)

    # ------------------------------------------------------------------ #
    # sequence parallelism (mesh sp > 1): the logprob/score pass and the
    # update forward run through ring attention with the sequence dim
    # sharded over the sp axis — for BOTH this dense runtime and the
    # SparseGRPOTrainer subclass (VERDICT r1 #3 / ROADMAP #7)
    # ------------------------------------------------------------------ #

    def _sp_on(self) -> bool:
        on = self.mesh.shape.get("sp", 1) > 1
        if on and self.mesh.shape.get("tensor", 1) > 1:
            raise ValueError("sp > 1 with tensor > 1 is not supported")
        return on

    def _fsdp_axis(self):
        return "fsdp" if self.mesh.shape.get("fsdp", 1) > 1 else None

    def _sp_check_widths(self, context_length: int):
        """The sequence dim shards evenly over the sp ring: every jitted
        width (context, response, and their sum) must divide by sp."""
        n_sp = self.mesh.shape.get("sp", 1)
        for name, width in (("context", context_length),
                            ("response_length", self.cfg.response_length)):
            if width % n_sp != 0:
                raise ValueError(
                    f"{name} width {width} not divisible by sp={n_sp}; pick "
                    f"prompt/response widths as multiples of sp"
                )

    def _score_chunk_fn(self):
        """Jitted policy+ref logprob scorer for one rollout chunk (cached —
        repeated train() calls must reuse the compiled executable). With an
        sp mesh axis the forwards run ring-attention sequence-parallel."""
        if hasattr(self, "_score_fn_cached"):
            return self._score_fn_cached
        mcfg, cfg = self.mcfg, self.cfg
        pad_id = self.tokenizer.pad_token_id
        lora_scale = self.lora_scale

        from functools import partial

        if self._sp_on():
            from nanorlhf_tpu.parallel.sp import sp_score_logprobs

            mesh, fsdp_axis = self.mesh, self._fsdp_axis()

            @partial(jax.jit, static_argnums=(3,))
            def score(params, ref_params, query_responses, context_length: int):
                # same attn_impl as the update pass (ADVICE r3: no
                # scoring/update kernel mismatch)
                lp = sp_score_logprobs(
                    params, mcfg, query_responses, pad_id, cfg.temperature,
                    mesh, fsdp_axis=fsdp_axis, lora_scale=lora_scale,
                    attn_impl=mcfg.attention_impl,
                )[:, context_length - 1 : -1]
                rlp = sp_score_logprobs(
                    ref_params, mcfg, query_responses, pad_id, cfg.temperature,
                    mesh, fsdp_axis=fsdp_axis, attn_impl=mcfg.attention_impl,
                )[:, context_length - 1 : -1]
                return lp, rlp

            self._score_fn_cached = score
            return score

        if cfg.fused_logprob:
            # fused hidden→logprob scoring: no [chunk, T, V] logits block
            # for either forward — the rollout-phase scoring chunk size is
            # no longer bounded by the vocab term of forward_token_budget
            @partial(jax.jit, static_argnums=(3,))
            def score(params, ref_params, query_responses, context_length: int):
                responses = query_responses[:, context_length:]
                logprobs = fused_response_logprobs(
                    params, mcfg, query_responses, responses, pad_id,
                    context_length, cfg, lora_scale=lora_scale,
                )
                ref_logprobs = fused_response_logprobs(
                    ref_params, mcfg, query_responses, responses, pad_id,
                    context_length, cfg,
                )
                return logprobs, ref_logprobs

            self._score_fn_cached = score
            return score

        @partial(jax.jit, static_argnums=(3,))
        def score(params, ref_params, query_responses, context_length: int):
            responses = query_responses[:, context_length:]
            logits = padded_forward_logits(
                params, mcfg, query_responses, pad_id, lora_scale=lora_scale,
                response_context_length=context_length,
            )
            logprobs = logprobs_from_logits(logits, responses, cfg.temperature)
            ref_logits = padded_forward_logits(
                ref_params, mcfg, query_responses, pad_id,
                response_context_length=context_length,
            )
            ref_logprobs = logprobs_from_logits(ref_logits, responses, cfg.temperature)
            return logprobs, ref_logprobs

        self._score_fn_cached = score
        return score

    def _single_score_fn(self, lora_scale: float = 1.0):
        """Single-model logprob scorer (jitted, cached per lora_scale) —
        scores whatever param tree it is handed. lora_scale=1.0 suits the
        (adapter-free) ref tree; pass self.lora_scale to score the POLICY
        tree, whose adapters must be applied (the ref-free path)."""
        cache = getattr(self, "_single_score_cache", None)
        if cache is None:
            cache = self._single_score_cache = {}
        if lora_scale in cache:
            return cache[lora_scale]
        mcfg, cfg = self.mcfg, self.cfg
        pad_id = self.tokenizer.pad_token_id

        from functools import partial

        if self._sp_on():
            from nanorlhf_tpu.parallel.sp import sp_score_logprobs

            mesh, fsdp_axis = self.mesh, self._fsdp_axis()

            @partial(jax.jit, static_argnums=(2,))
            def score_one(tree, query_responses, context_length: int):
                return sp_score_logprobs(
                    tree, mcfg, query_responses, pad_id, cfg.temperature,
                    mesh, fsdp_axis=fsdp_axis, lora_scale=lora_scale,
                    attn_impl=mcfg.attention_impl,
                )[:, context_length - 1 : -1]
        elif cfg.fused_logprob:
            @partial(jax.jit, static_argnums=(2,))
            def score_one(tree, query_responses, context_length: int):
                return fused_response_logprobs(
                    tree, mcfg, query_responses,
                    query_responses[:, context_length:], pad_id,
                    context_length, cfg, lora_scale=lora_scale,
                )
        else:
            @partial(jax.jit, static_argnums=(2,))
            def score_one(tree, query_responses, context_length: int):
                responses = query_responses[:, context_length:]
                logits = padded_forward_logits(
                    tree, mcfg, query_responses, pad_id,
                    lora_scale=lora_scale,
                    response_context_length=context_length,
                )
                return logprobs_from_logits(logits, responses, cfg.temperature)

        cache[lora_scale] = score_one
        return score_one

    def _ref_score_fn(self):
        """Ref-policy-only scorer — the sampler-logprob-capture path skips
        the policy forward entirely."""
        return self._single_score_fn(1.0)

    def _policy_score_fn(self):
        """Policy-only scorer (adapters applied) — the ref-free path's
        replacement for the two-model chunk scorer."""
        return self._single_score_fn(self.lora_scale)

    def _single_scorer_for(self, capture: bool):
        """The single-model scorer the scoring loop needs, or None when no
        single-model pass runs: ref-free scores the POLICY (unless capture
        already supplies it — then nothing is left to score), ref-full +
        capture scores the REF, ref-full without capture uses the two-model
        chunk scorer instead. Shared by the dense and sparse loops."""
        if self._ref_free:
            return None if capture else self._policy_score_fn()
        return self._ref_score_fn() if capture else None

    # ------------------------------------------------------------------ #
    # the training loop
    # ------------------------------------------------------------------ #

    def train(self, num_updates: Optional[int] = None):
        cfg = self.cfg
        tok = self.tokenizer
        pad_id, eos_id = tok.pad_token_id, tok.eos_token_id
        stop_id = eos_id if cfg.stop_token == "eos" else None
        score_fn = self._score_chunk_fn()

        n = cfg.sample_n if self.algo in (AlgoName.GRPO, AlgoName.RLOO, AlgoName.RAFT) else 1
        capture = cfg.sampler_logprob_capture
        # with truncated-IS correction the captured logprobs are the STALE
        # behavior policy's — they feed the IS weights, not the "old"
        # logprobs the clip ratio needs, so the policy scoring pass must
        # still run (score_capture=False) to measure π_old on the current
        # params
        score_capture = capture and not self._use_is
        sampling = SamplingParams(
            temperature=cfg.temperature, top_p=cfg.top_p, n=n,
            max_tokens=cfg.response_length, capture_logprobs=capture,
            compaction_segments=cfg.rollout_compaction_segments,
            top_k=cfg.rollout_top_k, approx_top_k=cfg.rollout_approx_top_k,
            shared_prompt_prefill=cfg.rollout_shared_prefill,
            spec_k=cfg.rollout_spec_k, spec_ngram=cfg.rollout_spec_ngram,
            page_size=cfg.rollout_page_size,
            decode_rows=cfg.rollout_decode_rows,
            prefill_chunk=cfg.rollout_prefill_chunk,
        )
        if self._env_multi_turn:
            # per-TURN generation budget: the episode driver packs model
            # turns + observations into the response_length-wide scored
            # batch, so each generate leg only runs env_turn_tokens
            sampling = SamplingParams(
                temperature=cfg.temperature, top_p=cfg.top_p, n=n,
                max_tokens=cfg.env_turn_tokens or cfg.response_length,
                top_k=cfg.rollout_top_k,
                approx_top_k=cfg.rollout_approx_top_k,
                shared_prompt_prefill=cfg.rollout_shared_prefill,
                page_size=cfg.rollout_page_size,
                decode_rows=cfg.rollout_decode_rows,
            )

        # after a resume, the default budget is the REMAINING updates, not a
        # fresh full run
        n_updates = (
            max(0, cfg.num_total_batches - self.state["global_step"])
            if num_updates is None else num_updates
        )
        from nanorlhf_tpu.trainer.bucketing import depad_queries, shape_menu

        ctx_menu = shape_menu(self.dataset.input_ids.shape[1], min_value=16) \
            if hasattr(self.dataset, "input_ids") else None

        def rollout_body(queries, gen_key, gen_tree=None, gen_mesh=None,
                         weight_refresh=None):
            """DISPATCH one rollout (async — nothing blocks until fetched).
            `gen_tree` (orchestrated mode) is a published weight-store
            snapshot; None samples from the live params. `gen_mesh` (fleet
            × disaggregation) is the calling worker's own device group;
            None generates on the shared rollout/train mesh.
            `weight_refresh` (rollout_inflight_swaps) is the store/transport
            poll callback; raw host snapshots it yields are converted to
            rollout-ready params here before the decode driver installs
            them (docs/ORCHESTRATOR.md §in-flight swaps)."""
            if ctx_menu is not None:
                # r1's de-padding applied to every algorithm: batches of short
                # prompts roll out / score at a menu-rounded context (warm jit
                # cache) instead of the dataset-wide pad width
                queries = depad_queries(queries, pad_id, ctx_menu)
            if self._sp_on():
                self._sp_check_widths(queries.shape[1])
            bs = batch_sharding(
                gen_mesh if gen_mesh is not None
                else self.mesh if self.rollout_mesh is None
                else self.rollout_mesh
            )
            queries_j = jax.device_put(jnp.asarray(queries), bs)
            prompt_mask = queries_j != pad_id
            gen_params = self._rollout_params(gen_tree, mesh=gen_mesh)
            gen_refresh = None
            if weight_refresh is not None:
                def gen_refresh():
                    # device-place a fresh snapshot exactly like the
                    # dispatch tree so a swap cannot change sharding; a
                    # (version, None) poll result passes through untouched
                    version, tree = weight_refresh()
                    if tree is None:
                        return version, None
                    return version, self._rollout_params(tree, mesh=gen_mesh)
            # speculative decode (rollout_spec_k > 0) appends its acceptance
            # counters here — device scalars fetched at metrics time, after
            # the tokens already forced a sync. The tracer hands the spec
            # path its instrumented driver (draft/verify spans on the
            # "rollout" track) when telemetry is on; a disabled tracer is
            # ignored.
            spec_stats: list = []
            paged_stats: list = []
            if self._env_multi_turn:
                from nanorlhf_tpu.envs.rollout import run_env_episodes

                payload = run_env_episodes(
                    gen_params, self._rollout_mcfg, queries_j, prompt_mask,
                    gen_key, sampling, self.env,
                    eos_token_id=eos_id, pad_token_id=pad_id, tokenizer=tok,
                    max_turns=cfg.env_max_turns,
                    turn_tokens=sampling.max_tokens,
                    obs_budget=cfg.env_obs_budget,
                    response_length=cfg.response_length,
                    page_size=cfg.rollout_page_size,
                    decode_rows=(cfg.env_decode_rows
                                 or cfg.rollout_decode_rows),
                    lora_scale=self.lora_scale, faults=self.faults,
                )
                return {"queries": queries, "gen_out": payload["tokens"],
                        "greedy": None, "spec_stats": None,
                        "paged_stats": None, "env": payload}
            gen_out = generate(
                gen_params, self._rollout_mcfg, queries_j, prompt_mask, gen_key,
                sampling, eos_token_id=eos_id, pad_token_id=pad_id,
                lora_scale=self.lora_scale, batch_sharding=bs,
                spec_stats_out=spec_stats, tracer=self.tracer,
                paged_stats_out=paged_stats, latency=self.latency,
                prefix_cache=self.prefix_cache,
                weight_refresh=gen_refresh,
            )                                               # [B*n, T]
            greedy = None
            if self.algo == AlgoName.REMAX:
                # extra greedy rollout as baseline (`ReMax/remax_trainer.py:166-185`)
                greedy = generate(
                    gen_params, self._rollout_mcfg, queries_j, prompt_mask, gen_key,
                    SamplingParams(greedy=True, max_tokens=cfg.response_length),
                    eos_token_id=eos_id, pad_token_id=pad_id,
                    lora_scale=self.lora_scale,
                )
            out = {"queries": queries, "gen_out": gen_out, "greedy": greedy,
                   "spec_stats": spec_stats[0] if spec_stats else None,
                   "paged_stats": paged_stats[0] if paged_stats else None}
            if weight_refresh is not None and paged_stats:
                # hoist swap provenance to the payload top level: the
                # lineage ledger (telemetry.segments_summary) and the
                # per-segment IS batch assembly read it from here
                ps = paged_stats[0]
                for k in ("segments", "swap_installs", "swap_wait_s"):
                    if k in ps:
                        out[k] = ps[k]
            return out

        from nanorlhf_tpu.orchestrator import ProducerFailed
        from nanorlhf_tpu.resilience import Preempted, ProducerWatchdog

        use_orch = False
        orch, stream, meter = None, None, None

        def ensure_handles():
            """(Re)build the rollout source after construction, a sentinel
            rollback (which tears the orchestrator down), or a watchdog
            degradation (which turns the orchestrated run synchronous)."""
            nonlocal use_orch, orch, stream, meter
            use_orch = cfg.rollout_orchestrator and not self.watchdog.degraded
            if use_orch:
                orch = self._ensure_orchestrator(rollout_body)
                stream, meter = None, orch.meter
            else:
                orch = None
                if stream is None:
                    stream = RolloutStream(
                        self, rollout_body, meter=self._rollout_meter
                    )
                meter = stream.meter

        def degrade_to_sync():
            """Watchdog budget exhausted: log the mode transition, tear the
            pipeline down, and fall back to synchronous rollouts (staleness
            0) from the consumed cursor instead of killing the run."""
            nonlocal stream
            print(
                "[resilience] producer restart budget "
                f"({cfg.producer_restart_budget}) exhausted — degrading to "
                "synchronous rollouts (staleness 0)"
            )
            if self._orchestrator is not None:
                # keep the queue's cumulative dropped/staleness counters:
                # _save_checkpoint journals them from _orch_restore_state in
                # degraded mode so the metric series stays continuous across
                # a later resume (the same continuity _restart_producer has)
                self._orch_restore_state = self._orchestrator.journal()
                self._orchestrator.close(join_timeout=5.0)
                self._orchestrator = None
            self._reset_data_iterator()
            stream = None  # force a fresh stream at the restored cursor
            ensure_handles()

        def fetch_sample():
            """One device-ready rollout, supervised: a dead producer is
            restarted with backoff up to the watchdog budget (then the run
            degrades to sync), and sentinel-quarantined batches are consumed
            and discarded so a post-rollback replay skips the offending
            data instead of re-deriving the same divergence."""
            nonlocal orch, sample_staleness, queue_depth
            while True:
                if use_orch:
                    try:
                        sample = orch.get()
                    except ProducerFailed as e:
                        # flight recorder first: the blackbox must capture
                        # what every thread was doing when the producer
                        # died, before the restart machinery mutates state
                        extra = {"error": repr(e.__cause__ or e)}
                        if hasattr(orch, "fleet_stats"):
                            # fleet post-mortem: membership/lease/quarantine
                            # counters at the moment of exhaustion
                            extra["fleet"] = orch.fleet_stats()
                        self.tracer.dump_blackbox(
                            self._telemetry_dir, self.state["global_step"],
                            "producer_failure", extra=extra,
                        )
                        decision, delay = self.watchdog.on_failure()
                        if decision == ProducerWatchdog.RESTART:
                            cause = e.__cause__ or e
                            print(
                                "[resilience] rollout producer died "
                                f"({type(cause).__name__}: {cause}) — restart "
                                f"{self.watchdog.restarts_total} in {delay:.1f}s"
                            )
                            time.sleep(delay)
                            orch = self._restart_producer(rollout_body)
                            continue
                        if decision == ProducerWatchdog.DEGRADE:
                            degrade_to_sync()
                            continue
                        raise
                    self.watchdog.on_success()
                    ro = sample.payload
                    ro["_index"] = sample.index
                    self.state["rollouts"] = sample.index + 1
                    sample_staleness = orch.version - sample.version
                    queue_depth = orch.queue.depth()
                else:
                    # quarantined indices are skipped BEFORE dispatch (zero
                    # rollout cost) — unless a prefetch already paid for one,
                    # which the post-fetch discard below handles
                    while (stream._pending is None
                           and stream.next_index in self.sentinel.quarantined):
                        idx = stream.skip()
                        print(
                            f"[resilience] skipping quarantined rollout "
                            f"{idx} (sentinel rollback; not dispatched)"
                        )
                    ro = stream.fetch_or_dispatch()
                if ro["_index"] in self.sentinel.quarantined:
                    # already-generated sample (orchestrated pipeline or a
                    # serial prefetch): discard it; the producer gate gets a
                    # skip credit (no version publish)
                    print(
                        f"[resilience] skipping quarantined rollout "
                        f"{ro['_index']} (sentinel rollback)"
                    )
                    self.lineage.drop(
                        ro["_index"], "sentinel_quarantine",
                        step=self.state["global_step"], dispatched=True,
                    )
                    if use_orch:
                        orch.consumed_without_update()
                    continue
                return ro

        ensure_handles()
        # whole-rollout drops (queue stale_drop, fleet late-duplicate) are
        # denominated in samples via this hint — one rollout = batch_size*n
        # completion rows
        self.lineage.rows_hint = cfg.batch_size * n
        sample_staleness, queue_depth = 0, 0
        target_step = self.state["global_step"] + n_updates
        while self.state["global_step"] < target_step:
            t_start = time.perf_counter()  # sec_per_episode is a duration
            step_t0 = time.perf_counter()
            # windowed XLA profiling: open/close the jax.profiler window
            # for the update about to run (cfg.profile_at_step or the
            # on-demand trigger file)
            self.profile_window.poll(self.state["global_step"] + 1)
            # per-update trace span: recorded via add_complete at the end
            # of the iteration (a with-block could not survive the sentinel
            # rollback's `continue`)
            span_t0 = self.tracer.now_us() if self.tracer.enabled else 0.0

            # ---- ROLLOUT -------------------------------------------------
            with self.timer.phase("rollout"):
                ro = fetch_sample()
                rollout_index = ro["_index"]
                if capture:
                    responses, captured_lp = ro["gen_out"]
                    captured_lp = np.asarray(captured_lp)
                else:
                    responses, captured_lp = ro["gen_out"], None
                jax.block_until_ready(responses)
                greedy_responses = ro["greedy"]
                if greedy_responses is not None:
                    greedy_responses.block_until_ready()
            # overlap meter: consumer busy from here (perf_counter — must
            # share the producers' gen-window clock or intersections die)
            t_busy0 = time.perf_counter()
            if not use_orch and self.lineage.enabled:
                # serial / rollout_ahead path has no producer thread to emit
                # this: generation provenance lands here, once the arrays
                # are device-ready (policy version == global_step — the same
                # convention the trace spans use without an orchestrator)
                from nanorlhf_tpu.telemetry.lineage import (
                    segments_summary,
                    spec_summary,
                )

                self.lineage.generation(
                    rollout_index,
                    policy_version=self.state["global_step"], worker_id=0,
                    spec=spec_summary(ro),
                    segments=segments_summary(ro),
                    swap_wait_s=ro.get("swap_wait_s"),
                )
            pstats = ro.get("paged_stats")
            if pstats is not None:
                # /statusz "pages" panel reads the latest snapshot; lineage
                # gets one "lease" event per mid-loop admission so a queued
                # sample's provenance records WHICH recycled row produced it
                # and at which decode iteration (runs in every rollout mode)
                self._pages_status = {
                    k: (None if pstats[k] is None
                        else float(np.asarray(pstats[k])))
                    for k in ("page_utilization", "pages_recycled",
                              "admitted_midloop", "decode_iterations")
                }
                self._pages_status.update(
                    rows=pstats["rows"], num_pages=pstats["num_pages"],
                    page_size=pstats["page_size"],
                )
                # the continuous-batching scheduler also ships its decode
                # session's end-of-call status for /statusz "session";
                # the monolithic paged paths have no session
                self._session_status = pstats.get("session")
                if self.lineage.enabled:
                    for adm in pstats.get("admissions") or []:
                        self.lineage.event(
                            "lease", rollout_index, midloop=True,
                            row=adm["row"], queue_index=adm["queue_index"],
                            iteration=adm["iteration"],
                        )
            self.state["episode"] += cfg.batch_size
            queries = ro["queries"]
            batch_size, context_length = queries.shape
            if (not use_orch and cfg.rollout_ahead
                    and self.state["global_step"] + 1 < target_step):
                # dispatch rollout k+1 NOW (from the pre-update-k params, one
                # update stale): the device generates while the host below
                # decodes/grades update k's batch
                stream.prefetch()

            # ---- REWARD (host-side, user callable) -------------------------
            question_strings = [
                q.replace(tok.pad_token, "") for q in tok.batch_decode(queries)
            ]
            question_n = [q for q in question_strings for _ in range(n)]
            responses_np = np.asarray(responses)
            seg_ages = None
            if self._use_seg and ro.get("segments") is not None:
                # per-token policy AGE (newest version that produced any
                # token of the row, minus the token's own segment version)
                # in response coordinates — the same [0, total) space the
                # scheduler's segment tok_ranges tile. Rows untouched by a
                # swap are all-zero, and zero ages make segment_is_weights
                # reduce bit-exactly to the whole-sequence weight.
                seg_ages = np.zeros(responses_np.shape, np.int32)
                for r, segs in enumerate(ro["segments"]):
                    newest = max(s["policy_version"] for s in segs)
                    for s in segs:
                        lo, hi = s["tok_range"]
                        if newest > s["policy_version"]:
                            seg_ages[r, lo:hi] = newest - s["policy_version"]
            responses_decoded = tok.batch_decode(responses_np)
            envp = ro.get("env")
            with self.timer.phase("reward"):
                if envp is not None:
                    # multi-turn env: rewards accrued turn-by-turn inside
                    # the episode driver (the terminal grader already ran
                    # per episode) — no separate dispatch. Lineage gets the
                    # usual reward event plus one `turn` event per
                    # (episode row, turn), joinable to this rollout's
                    # generation event on rollout_index.
                    scores = np.asarray(envp["scores"], np.float32)
                    if self.lineage.enabled:
                        self.lineage.reward(
                            rollout_index, step=self.state["global_step"],
                            scores=[round(float(s), 6) for s in scores],
                            attempt=1,
                            wall_s=envp["stats"]["env/tool_wall_s"],
                        )
                        for rec in envp["turns"]:
                            self.lineage.turn(
                                rollout_index,
                                step=self.state["global_step"], **rec,
                            )
                else:
                    scores = self._dispatch_reward(
                        [q + r for q, r in zip(question_n, responses_decoded)],
                        tok.eos_token,
                        rollout_index=rollout_index,
                        step=self.state["global_step"],
                    )
            log_scores_all = scores.copy()  # raw sampled-rollout scores for logging
            if greedy_responses is not None:
                greedy_decoded = tok.batch_decode(np.asarray(greedy_responses))
                greedy_scores = self._dispatch_reward(
                    [q + r for q, r in zip(question_strings, greedy_decoded)],
                    tok.eos_token,
                )
                # score − score_greedy is the ReMax advantage seed
                # (`ReMax/remax_trainer.py:506-513`); raw scores still logged
                scores = np.asarray(
                    remax_advantage(jnp.asarray(scores), jnp.asarray(greedy_scores))
                )

            # ---- GRPO: group advantage + keep-1-of-N BEFORE scoring --------
            grpo_adv = None
            env_turn_adv = env_turn_ends = env_loss_mask = None
            if self.algo == AlgoName.GRPO:
                adv_flat = np.asarray(grpo_group_advantage(jnp.asarray(scores), n))
                self.key, k = jax.random.split(self.key)
                keep = np.asarray(keep_one_of_n_indices(k, batch_size, n))
                rows = np.arange(batch_size)
                grpo_adv = adv_flat.reshape(batch_size, n)[rows, keep]
                if envp is not None:
                    # per-turn advantages z-score each turn column against
                    # the FULL group (all N siblings) before the keep
                    # filter drops N−1 of them, mirroring the episode-level
                    # baseline above; the turn-end positions and the
                    # observation loss_mask ride the same selection
                    t_adv = np.asarray(grpo_turn_advantage(
                        jnp.asarray(envp["turn_rewards"]), n))
                    env_turn_adv = t_adv.reshape(
                        batch_size, n, -1)[rows, keep]
                    env_turn_ends = np.asarray(envp["turn_ends"]).reshape(
                        batch_size, n, -1)[rows, keep]
                    env_loss_mask = np.asarray(envp["loss_mask"]).reshape(
                        batch_size, n, -1)[rows, keep]
                responses_np = responses_np.reshape(batch_size, n, -1)[rows, keep]
                if captured_lp is not None:
                    captured_lp = captured_lp.reshape(batch_size, n, -1)[rows, keep]
                if seg_ages is not None:
                    seg_ages = seg_ages.reshape(batch_size, n, -1)[rows, keep]
                log_scores = log_scores_all.reshape(batch_size, n)[rows, keep]
                responses_decoded = [
                    responses_decoded[i * n + j] for i, j in enumerate(keep)
                ]
                if n > 1:
                    # the other n−1 completions per prompt leave the batch
                    # here: attribute them like any other exclusion
                    self.lineage.drop(
                        rollout_index, "keep_filter",
                        count=batch_size * (n - 1),
                        step=self.state["global_step"],
                    )
                queries_rep = queries
            else:
                queries_rep = np.repeat(queries, n, axis=0) if n > 1 else queries
                log_scores = log_scores_all

            # ---- LOGPROB PASS (chunked, jitted) ----------------------------
            qr = np.concatenate([queries_rep, responses_np], axis=1)
            total = qr.shape[0]
            # the vocab-cap lift only applies when the fused scorer actually
            # runs — an sp mesh routes scoring through sp_score_logprobs,
            # which still materializes per-shard [chunk, T/sp, V] logits
            chunk = cfg.local_rollout_forward_batch_size or max(
                1,
                forward_token_budget(
                    self.mcfg.vocab_size,
                    fused_logprob=cfg.fused_logprob and not self._sp_on(),
                )
                // (context_length + cfg.response_length),
            )
            chunk = max(1, min(total, chunk))
            logprobs_l, ref_logprobs_l = [], []
            ref_free = self._ref_free
            one_fn = self._single_scorer_for(score_capture)
            with self.timer.phase("logprob"):
                if ref_free and score_capture:
                    # zero scoring forwards: policy logprobs came from the
                    # sampler, and there is no reference model (kl_coef 0 —
                    # the reference's r1 path, `grpo_r1.py:138`)
                    pass
                else:
                    for i in range(0, total, chunk):
                        n_real = min(chunk, total - i)
                        rows_c = jnp.asarray(pad_chunk(qr[i : i + chunk], chunk))
                        if ref_free:
                            # policy-only forward (adapters applied)
                            lp = one_fn(self.params, rows_c, context_length)
                            logprobs_l.append(np.asarray(lp)[:n_real])
                        elif score_capture:
                            # policy logprobs came from the sampler; only the
                            # ref pass runs — half the scoring forwards
                            rlp = one_fn(self.ref_params, rows_c, context_length)
                            ref_logprobs_l.append(np.asarray(rlp)[:n_real])
                        else:
                            lp, rlp = score_fn(
                                self.params, self.ref_params, rows_c,
                                context_length,
                            )
                            logprobs_l.append(np.asarray(lp)[:n_real])
                            ref_logprobs_l.append(np.asarray(rlp)[:n_real])
            logprobs = (
                captured_lp if score_capture else np.concatenate(logprobs_l)
            ).astype(np.float32)
            # ref == policy-old in ref-free mode: every KL term and metric
            # reads exactly 0, matching "no reference model"
            ref_logprobs = (
                logprobs.copy() if ref_free else np.concatenate(ref_logprobs_l)
            )

            # ---- response post-processing ---------------------------------
            responses_j = jnp.asarray(responses_np)
            postprocessed = responses_j
            if stop_id is not None and envp is None:
                # multi-turn episodes carry INTERIOR per-turn EOS tokens the
                # stop-token truncation would cut at; the driver already
                # packed real tokens left-justified with pads only at the
                # tail, so the first-pad seq_lengths below stay correct
                postprocessed = truncate_response(stop_id, pad_id, responses_j)
            seq_lengths = np.asarray(first_true_indices(postprocessed == pad_id) - 1)
            padding_mask, padding_mask_p1 = response_padding_masks(
                np.asarray(postprocessed), jnp.asarray(seq_lengths)
            )
            padding_mask = np.asarray(padding_mask)
            padding_mask_p1 = np.asarray(padding_mask_p1)
            logprobs = np.where(padding_mask, INVALID_LOGPROB, logprobs)
            ref_logprobs = np.where(padding_mask, INVALID_LOGPROB, ref_logprobs)
            behavior_lp = None
            if self._use_is:
                # the STALE sampling policy's logprobs, masked exactly like
                # `logprobs` so the IS weight is 1 at padded positions
                behavior_lp = np.where(
                    padding_mask, INVALID_LOGPROB, captured_lp
                ).astype(np.float32)

            contain_eos = (np.asarray(postprocessed) == eos_id).any(axis=1)
            scores_sel = grpo_adv if self.algo == AlgoName.GRPO else scores
            if cfg.missing_eos_penalty is not None:
                scores_sel = scores_sel.copy()
                scores_sel[~contain_eos] -= cfg.missing_eos_penalty

            # ---- per-algo advantage assembly ------------------------------
            batch, keep_inds, reward_info = self._assemble_batch(
                scores_sel, logprobs, ref_logprobs, padding_mask, padding_mask_p1,
                seq_lengths, qr, responses_np, context_length, batch_size, n,
                behavior_lp=behavior_lp,
                turn_info=((env_turn_adv, env_turn_ends)
                           if env_turn_adv is not None else None),
            )
            if env_loss_mask is not None:
                # observation/tool tokens: conditioned on, never scored.
                # The key is only present in env multi-turn runs, so every
                # other mode compiles the identical jitted update.
                batch["loss_mask"] = env_loss_mask
            if seg_ages is not None:
                # key present only under rollout_inflight_swaps (same
                # conditional-key pattern as loss_mask above): swaps off
                # compiles the identical jitted update
                if keep_inds is not None:
                    # RLOO/RAFT keep-1-of-N happens below, AFTER batch
                    # assembly — realign the ages the same way
                    seg_ages = seg_ages.reshape(batch_size, n, -1)[
                        np.arange(batch_size), keep_inds
                    ]
                batch["segment_ages"] = seg_ages

            if keep_inds is not None:
                # RLOO/RAFT selected 1-of-N *after* the logprob pass; realign
                # the decoded strings/scores used for the sample table
                responses_decoded = [
                    responses_decoded[i * n + j] for i, j in enumerate(keep_inds)
                ]
                log_scores = log_scores.reshape(batch_size, n)[
                    np.arange(batch_size), keep_inds
                ]
                self.lineage.drop(
                    rollout_index, "keep_filter",
                    count=batch_size * (n - 1),
                    step=self.state["global_step"],
                )

            # ---- PPO-epoch / minibatch / microbatch update ----------------
            trainable, frozen = self._partition(
                self._train_tree(self.params, self.value_params)
            )
            all_stats = []
            local_bs = batch["responses"].shape[0]
            mini = max(1, local_bs // cfg.num_mini_batches)
            # lr reported for THIS update = schedule at the step count its
            # first optimizer.update saw (the reference's get_last_lr-before-
            # scheduler.step semantics, `grpo_trainer.py:744-750`)
            lr_step = self.state["opt_steps"]
            with self.timer.phase("update"):
                for epoch in range(cfg.num_ppo_epochs):
                    self.key, pk = jax.random.split(self.key)
                    perm = np.asarray(jax.random.permutation(pk, local_bs))
                    for start in range(0, local_bs - mini + 1, mini):
                        inds = perm[start : start + mini]
                        mb = {
                            k: jax.device_put(
                                jnp.asarray(v[inds]),
                                batch_sharding(self.mesh, np.asarray(v).ndim),
                            )
                            for k, v in batch.items()
                        }
                        trainable, self.opt_state, stats = self._update_fn(
                            trainable, frozen, self.opt_state, mb, context_length
                        )
                        self.state["opt_steps"] += 1
                        # keep stats on device; syncing per minibatch would
                        # serialize update dispatch
                        all_stats.append(stats)
                train_tree = self._combine(trainable, frozen)
                self.params = train_tree["policy"]
                self.value_params = train_tree.get("value")
                all_stats = jax.device_get(all_stats)
            agg = {
                k: float(np.mean([s[k] for s in all_stats]))
                for k in (all_stats[0] if all_stats else {})
            }

            # ---- SENTINEL (resilience/, docs/RESILIENCE.md) ----------------
            # checked BEFORE the weight-store publish so a tripped step never
            # feeds poisoned weights to the producer. The update.step fault
            # poisons the OBSERVED stats (action=nan) — same code path a real
            # NaN loss/grad takes, without hand-corrupting device arrays.
            if self.faults.fire("update.step") == "nan":
                agg["pg_loss"] = float("nan")
                agg["grad_norm"] = float("nan")
            verdict = self.sentinel.observe(
                agg.get("pg_loss", 0.0), agg.get("grad_norm")
            )
            if verdict is not None:
                if self.tracer.enabled:
                    # close the tripped update's span BEFORE the rollback
                    # dumps the flight recorder, so the blackbox ring holds
                    # it — tagged with the quarantined rollout index
                    self.tracer.add_complete(
                        "train.update", span_t0,
                        self.tracer.now_us() - span_t0,
                        step=self.state["global_step"] + 1,
                        rollout_index=rollout_index,
                        staleness=sample_staleness,
                        policy_version=(orch.version if use_orch
                                        else self.state["global_step"]),
                        sentinel_verdict=verdict, quarantined=True,
                    )
                self._sentinel_rollback(verdict, rollout_index)
                # discard the tripped update's phase splits: the continue
                # skips this iteration's summary() reset, and the replayed
                # update's time/*_s rows — and the perf/tokens_per_sec_*
                # divisors that read timer.totals — would otherwise fold in
                # two updates' worth of wall time
                self.timer.summary()
                # the rollback tore the pipeline down and rewound the
                # data/PRNG cursors — rebuild handles and replay
                stream = None
                ensure_handles()
                continue
            if use_orch:
                # one version per optimizer update: snapshot the trainable
                # leaves (donation hazard) and open the producer's gate
                with self.timer.phase("publish"):
                    orch.publish(self._policy_snapshot())

            # ---- METRICS (names + semantics per docs/METRICS.md) -----------
            sec_per_episode = (time.perf_counter() - t_start) / cfg.batch_size
            # entropy proxy: summed response negative logprob (the reference's
            # `(-logprobs).sum(1).mean()`, `GRPO/grpo_trainer.py:710`, with
            # pad positions masked to 0 instead of contributing the INVALID
            # sentinel); the true entropy is policy/entropy_avg_new below
            mean_entropy = float(
                (-np.where(padding_mask, 0.0, logprobs)).sum(1).mean()
            )
            kl_rollout = float(
                np.where(padding_mask, 0.0, logprobs - ref_logprobs).sum(1).mean()
            )
            # GRPO parity: the reference fills kl_old from the UPDATE-pass
            # new-vs-ref KL stats (`GRPO/grpo_trainer.py:668-670,689,728`);
            # every KL-in-reward trainer uses the rollout token-sum KL
            # (`RLOO/rloo_trainer.py:704-706`). kl_rollout_old is always the
            # honest pre-update measurement.
            kl_old = (
                agg.get("refkl_mean", kl_rollout)
                if self.algo == AlgoName.GRPO else kl_rollout
            )
            if self._ref_free:
                # no reference model exists: GRPO's update-pass refkl stat
                # would otherwise report KL-to-OLD-POLICY here (ref stands
                # in as the old logprobs), which is not the metric's meaning
                kl_old = 0.0
            metrics = {
                "objective/kl_old": kl_old,
                "objective/kl_rollout_old": kl_rollout,
                "objective/entropy_old": mean_entropy,
                "objective/non_score_reward_old": reward_info.get(
                    "non_score_reward_old", 0.0
                ),
                "eval_objective/rlhf_reward_old": reward_info.get(
                    "rlhf_reward_old", float(np.mean(log_scores_all))
                ),
                "eval_objective/scores_old": float(np.mean(log_scores_all)),
                "policy/approxkl_avg_new": agg.get("approxkl", 0.0),
                "policy/clipfrac_avg_new": agg.get("pg_clipfrac", 0.0),
                "policy/entropy_avg_new": agg.get("entropy", 0.0),
                "loss/policy_avg_new": agg.get("pg_loss", 0.0),
                "val/ratio_new": agg.get("ratio_mean", 1.0),
                "val/ratio_var_new": float(np.var(
                    [s.get("ratio_mean", 1.0) for s in all_stats]
                )) if all_stats else 0.0,
                "val/num_eos_tokens_old": float(
                    (np.asarray(postprocessed) == eos_id).sum()
                ),
                "lr": float(self._lr_schedules["policy"](lr_step)),
                "eps": cfg.adam_eps,
                "sec_per_episode": sec_per_episode,
                "episode": self.state["episode"],
            }
            if "vf_loss" in agg:
                metrics["loss/value_avg_new"] = agg["vf_loss"]
                metrics["val/clipfrac_avg_new"] = agg.get("vf_clipfrac", 0.0)
            if score_capture:
                # with exact scoring the epoch-1 ratio is identically 1; any
                # deviation here is decode-vs-scoring numerics — the guard
                # for the captured-logprob shortcut
                metrics["sampler_capture/ratio_drift_new"] = abs(
                    agg.get("ratio_mean", 1.0) - 1.0
                )
            # rollout/train overlap fraction: measured for EVERY mode
            # (serial ≈ 0, rollout_ahead partial, orchestrator highest) —
            # the bench payload's pipelining signal
            metrics["time/rollout_overlap_frac"] = meter.overlap_fraction()
            metrics.update(self._spec_decode_metrics(ro.get("spec_stats")))
            metrics.update(self._paged_metrics(ro.get("paged_stats")))
            if envp is not None:
                metrics.update(envp["stats"])
            if use_orch:
                ostats = orch.stats()
                metrics.update({
                    "orchestrator/queue_depth": float(queue_depth),
                    "orchestrator/staleness": float(sample_staleness),
                    "orchestrator/dropped_total": float(ostats["dropped"]),
                    # who-waits-on-whom (cumulative s): trainer starved vs
                    # producer gated — which side is the bottleneck
                    "orchestrator/consumer_wait_s": ostats["consumer_wait_s"],
                    "orchestrator/producer_gate_wait_s": ostats[
                        "producer_gate_wait_s"
                    ],
                })
                metrics.update(staleness_histogram_metrics(
                    ostats["staleness_counts"]
                ))
                if hasattr(orch, "fleet_stats"):
                    # fleet/* series (docs/METRICS.md): membership gauges +
                    # cumulative lease/reassignment/quarantine counters
                    # (counters survive restart/degrade/resume via the
                    # coordinator journal, like the queue's)
                    metrics.update({
                        f"fleet/{k}": v
                        for k, v in orch.fleet_stats().items()
                    })
            if self._use_is:
                metrics["offpolicy/is_weight_mean_new"] = agg.get(
                    "is_weight_mean", 1.0
                )
                metrics["offpolicy/is_trunc_frac_new"] = agg.get(
                    "is_trunc_frac", 0.0
                )
            if cfg.rollout_inflight_swaps:
                # in-flight swap provenance (docs/ORCHESTRATOR.md
                # §in-flight swaps): installs + the mean number of policy
                # segments per completion row THIS update consumed (1.0 =
                # no mid-rollout publish landed), plus the cumulative
                # install stall this rollout paid (device-put of the fresh
                # tree at a chunk boundary — the cost drain-and-wait pays
                # as idle time instead)
                segs = ro.get("segments")
                metrics.update({
                    "rollout/swap_installs": float(
                        ro.get("swap_installs", 0) or 0),
                    "rollout/segments_per_sample": (
                        float(np.mean([len(s) for s in segs]))
                        if segs else 1.0
                    ),
                    "orchestrator/swap_wait_s": float(
                        ro.get("swap_wait_s", 0.0) or 0.0),
                })
            # resilience series (docs/RESILIENCE.md): cumulative counters so
            # dashboards diff them into rates; degraded_mode is the sticky
            # sync-fallback flag (0 in healthy pipelined runs)
            metrics.update({
                "policy/grad_norm_new": agg.get("grad_norm", 0.0),
                "resilience/producer_restarts": float(
                    self.watchdog.restarts_total
                ),
                "resilience/rollbacks": float(self.sentinel.rollbacks),
                "resilience/degraded_mode": float(self.watchdog.degraded),
                "resilience/ckpt_retries": float(self.ckpt.retry_count),
                "resilience/ckpt_fallbacks": float(self.ckpt.fallback_count),
            })
            # memory series (docs/METRICS.md, docs/FUSED_LOGPROB.md):
            # peak_bytes_in_use from the backend (0 on CPU), plus the
            # analytic size of the update-pass full-logits buffer the fused
            # hidden→logprob path avoids per microbatch (param-dtype logits;
            # the naive path's old f32 entropy copy is NOT counted — it is
            # gone in both modes now that the fallback entropy is chunked)
            n_micro_rows = max(1, mini // cfg.gradient_accumulation_steps)
            logits_bytes = (
                n_micro_rows * batch["responses"].shape[1]
                * self.mcfg.vocab_size
                * jnp.dtype(self.params["embed_tokens"].dtype).itemsize
            )
            metrics.update({
                "mem/peak_bytes_in_use": device_peak_bytes(),
                # 0 on an sp mesh too: microbatch_loss takes the sp branch
                # there and the fused op never runs
                "mem/logits_bytes_saved": float(
                    logits_bytes
                    if cfg.fused_logprob and not self._sp_on() else 0.0
                ),
            })
            # ---- perf/MFU accounting (telemetry/, docs/OBSERVABILITY.md):
            # token counts from THIS update's actual work — decode at the
            # configured response_length (the napkin model's convention),
            # scoring forwards as actually run (0 in ref-free+capture, 1
            # with capture or ref-free, 2 otherwise)
            n_rollout_rows = batch_size * n
            t_resp = batch["responses"].shape[1]
            score_forwards = (
                0 if (ref_free and score_capture)
                else 1 if (ref_free or score_capture) else 2
            )
            metrics.update(self._perf_metrics(
                step_wall_s=time.perf_counter() - step_t0,
                decode_tokens=n_rollout_rows * cfg.response_length,
                prefill_tokens=n_rollout_rows * context_length,
                score_tokens=score_forwards * total
                * (context_length + cfg.response_length),
                train_tokens=cfg.num_ppo_epochs * local_bs
                * (context_length + t_resp),
                rollout_s=self.timer.totals.get("rollout", 0.0),
                update_s=self.timer.totals.get("update", 0.0),
            ))
            phase_rows = self.timer.summary()
            metrics.update(phase_rows)
            if self.latency.enabled:
                # per-update phase durations into the latency surface: the
                # time/{phase}_s gauges above are the LAST update's splits,
                # the latency/phase_{phase}_s histograms hold every update's
                for k, v in phase_rows.items():
                    if k.startswith("time/") and k.endswith("_s"):
                        # "time/rollout_s" -> "latency/phase_rollout_s"
                        self.latency.record(
                            f"latency/phase_{k[5:-2]}_s", float(v))
            self.state["global_step"] += 1
            # run-health plane: fold this row into the streaming aggregates,
            # evaluate the anomaly rules, and ride the health/* gauges on
            # the same record (CRIT side effects happen inside observe)
            metrics.update(self.health.observe(self.state["global_step"], metrics))
            if self.lineage.enabled:
                # training-outcome event: closes this index's provenance
                # chain with what the update actually consumed
                adv_arr = np.asarray(
                    batch.get("advantages", scores_sel), dtype=np.float32
                )
                if adv_arr.ndim > 1:
                    # per-token advantages (PPO/GAE): reduce to per-row means
                    adv_arr = adv_arr.mean(axis=tuple(range(1, adv_arr.ndim)))
                self.lineage.outcome(
                    rollout_index, step=self.state["global_step"],
                    policy_version=(orch.version if use_orch
                                    else self.state["global_step"]),
                    kept=int(local_bs),
                    advantage=round(float(adv_arr.mean()), 6),
                    scores=[round(float(s), 6)
                            for s in np.asarray(log_scores).tolist()],
                    eos_frac=round(float(contain_eos.mean()), 4),
                    staleness=sample_staleness,
                )
                if self._use_is and agg.get("is_trunc_frac", 0.0) > 0:
                    # truncated-IS rows stay IN the update with capped
                    # weight — partial influence loss, attributed but not
                    # excluded (`partial` marks it for the histogram reader)
                    n_trunc = int(round(agg["is_trunc_frac"] * local_bs))
                    if n_trunc:
                        self.lineage.drop(
                            rollout_index, "is_truncated_weight",
                            count=n_trunc, step=self.state["global_step"],
                            partial=True,
                        )
                for i, s in enumerate(
                        np.asarray(log_scores).tolist()[:8]):
                    self.lineage.note_sample(
                        rollout_index, step=self.state["global_step"],
                        score=round(float(s), 6),
                        response_chars=len(responses_decoded[i])
                        if i < len(responses_decoded) else None,
                        kept=True,
                    )
            if self.state["global_step"] % cfg.logging_steps == 0:
                self.logger.log(self.state["global_step"], self.state["episode"], metrics)
                sample_limit = (
                    cfg.log_samples_limit
                    if cfg.log_samples_limit is not None
                    else cfg.num_printed_samples
                )
                self.logger.log_samples(
                    self.state["global_step"], question_strings, responses_decoded,
                    log_scores, sample_limit,
                )
                if self.lineage.enabled:
                    # full-text sample records live here now, not in
                    # metrics.jsonl (satellite: metrics stays numeric rows)
                    for i, (q, r, s) in enumerate(zip(
                            question_strings, responses_decoded,
                            np.asarray(log_scores).tolist())):
                        if i >= sample_limit:
                            break
                        self.lineage.event(
                            "sample", rollout_index,
                            step=self.state["global_step"], row=i,
                            query=q, response=r, score=round(float(s), 6),
                        )

            # ---- CHECKPOINT ------------------------------------------------
            saved_this_step = False
            if cfg.save_steps and self.state["global_step"] % cfg.save_steps == 0:
                self._save_checkpoint(orch if use_orch else None, metrics)
                saved_this_step = True
            # overlap meter: consumer busy window = everything since the
            # sample was fetched (reward, scoring, update, logging, save)
            meter.note_busy(t_busy0, time.perf_counter())
            if self.tracer.enabled:
                # the completed update's span on the trainer thread's track,
                # with the correlation args that make trace.json queryable
                self.tracer.add_complete(
                    "train.update", span_t0, self.tracer.now_us() - span_t0,
                    step=self.state["global_step"],
                    rollout_index=rollout_index,
                    staleness=sample_staleness,
                    policy_version=(orch.version if use_orch
                                    else self.state["global_step"]),
                )
                self.tracer.counter("staleness", sample_staleness)

            # ---- PREEMPTION (SIGTERM, docs/RESILIENCE.md) ------------------
            # polled at the update boundary where state is consistent: flush
            # the in-flight async save, commit an emergency checkpoint, and
            # unwind through the launcher's normal close() path
            if self._preemption.triggered:
                if not saved_this_step:
                    self._save_checkpoint(orch if use_orch else None, metrics)
                self.ckpt.wait()
                # blackbox + trace alongside the emergency checkpoint: the
                # post-mortem gets "what was every thread doing at SIGTERM"
                self.tracer.dump_blackbox(
                    self._telemetry_dir, self.state["global_step"],
                    "preemption",
                )
                self._write_trace()
                raise Preempted(
                    f"SIGTERM at step {self.state['global_step']}: emergency "
                    f"checkpoint committed to {self.cfg.output_dir}"
                )

        # train() returning implies every checkpoint is DURABLE: flush the
        # in-flight async save (saves mid-run overlap training; only this
        # final one blocks)
        self.ckpt.wait()
        # balance any still-open XLA profile window, and rewrite trace.json
        # after EVERY train() call (bench's train(num_updates=1) pattern
        # would otherwise only get a trace at close())
        self.profile_window.stop()
        self._write_trace()
        # load_best_model_at_end parity (`GRPO/grpo.py:149`, resolved via the
        # `_old` one-save-back metric semantics, `grpo_trainer.py:374-382`)
        if cfg.load_best_model_at_end and num_updates is None:
            best = self.ckpt.best_step()
            if best is not None and best != self.state["global_step"]:
                self.params = self.ckpt.restore(best, self._restore_template())["params"]
                if self._quant_layers is not None:
                    self._refresh_quant_layers()
                print(f"loaded best checkpoint (step {best})")
        if cfg.export_hf_dir and num_updates is None:
            # handoff artifact AFTER load_best: the exported policy is the
            # one the run would deploy
            print(f"exporting HF checkpoint to {cfg.export_hf_dir}")
            self.export_model(cfg.export_hf_dir)
        return self.state

    def _write_trace(self):
        """Rewrite `<telemetry_dir>/trace.json` from the full buffered span
        history (no-op when telemetry is off). Load it at
        https://ui.perfetto.dev or chrome://tracing."""
        path = self.tracer.write_trace(
            os.path.join(self._telemetry_dir, "trace.json")
        )
        if path is not None:
            print(f"[telemetry] trace written: {path}")
        return path

    def _restore_template(self):
        """Mirror of what checkpoint.save() writes — single source of truth
        for restore structure."""
        like = {"params": self.params}
        if self.cfg.save_optimizer_state:
            like["opt_state"] = self.opt_state
        if self.cfg.save_value_model and self.value_params is not None:
            like["value"] = self.value_params
        return like

    def _save_checkpoint(self, orch, metrics: dict):
        """One checkpoint at the current step — the periodic `save_steps`
        path and the SIGTERM emergency path share it, so an emergency
        checkpoint is exactly as resumable as a scheduled one."""
        extra_state = {"episode": self.state["episode"],
                       "opt_steps": self.state["opt_steps"],
                       "rollouts": self.state["rollouts"],
                       # sentinel/watchdog journals: recovery behavior itself
                       # resumes (rollback spend, quarantined batches,
                       # restart counters, the degraded-mode flag)
                       "resilience": {
                           "sentinel": self.sentinel.journal(),
                           "watchdog": self.watchdog.journal(),
                       },
                       # health-plane journal: aggregate sketches, rule
                       # levels, verdict, trip counts — a resumed run keeps
                       # its learned baselines instead of re-warming and
                       # missing a collapse that started pre-restart
                       "health": self.health.journal(),
                       # lineage journal: monotonic event index + drop
                       # counters, so a resumed ledger appends to the
                       # stream instead of restarting it
                       "lineage": self.lineage.journal(),
                       # latency journal: full histogram states (sparse
                       # bucket counts + scheme), so resumed quantiles
                       # keep the whole run's distribution
                       "latency": self.latency.journal()}
        if orch is not None:
            # journal the queue: pending (dispatched, unconsumed)
            # indices + cumulative drop/staleness counters. Resume
            # re-draws the pending samples from the consumed-rollout
            # cursor — the index-keyed PRNG and deterministic loader
            # reproduce their token streams (docs/ORCHESTRATOR.md)
            extra_state["orchestrator"] = orch.journal()
        elif self._orch_restore_state is not None:
            # degraded mode: the pipeline is gone but its cumulative
            # counters must stay journaled, or a resume zeroes the
            # dropped/staleness series
            extra_state["orchestrator"] = self._orch_restore_state
        cfg = self.cfg
        self.ckpt.save(
            self.state["global_step"], self.params,
            opt_state=self.opt_state if cfg.save_optimizer_state else None,
            rng_key=self.key,
            metric_old=metrics[cfg.metric_for_best_model]
            if cfg.metric_for_best_model in metrics else None,
            extra_state=extra_state,
            value_params=self.value_params if cfg.save_value_model else None,
        )

    def _dispatch_reward(self, prompts_and_responses, eos_token,
                         rollout_index=None, step=None) -> np.ndarray:
        """Reward dispatch with the `reward.exec` injection point and a
        bounded retry: the reward callable is host-side (subprocess graders,
        RM inference) and a transient failure there must not kill a TPU
        run mid-epoch. When `rollout_index` is passed, the lineage ledger
        gets the per-sample scores, the retry attempt that finally landed,
        and the grader wall time (backoff sleeps included — that IS the
        step-time cost)."""
        from nanorlhf_tpu.resilience import retry_with_backoff

        attempts_used = [0]

        def attempt():
            attempts_used[0] += 1
            self.faults.fire("reward.exec")
            return np.asarray(
                self.reward_func(prompts_and_responses, eos_token),
                dtype=np.float32,
            )

        # a dedicated "reward" trace track: the host-side graders
        # (subprocess sympy, RM inference) are a classic hidden step-time
        # eater the device-phase split cannot attribute. span() is a no-op
        # when telemetry is off — one call site either way.
        with self.tracer.span("reward.dispatch", track="reward",
                              rows=len(prompts_and_responses)):
            t0 = time.perf_counter()
            scores = retry_with_backoff(
                attempt, attempts=self.cfg.reward_retries + 1,
                backoff_base=0.1,
            )
        if self.latency.enabled:
            # grader wall incl. retry backoff — the same quantity the
            # lineage reward event records as wall_s
            self.latency.record("latency/reward_s",
                                time.perf_counter() - t0)
        if rollout_index is not None:
            self.lineage.reward(
                rollout_index, step=step,
                scores=[round(float(s), 6) for s in scores.tolist()],
                attempt=attempts_used[0],
                wall_s=round(time.perf_counter() - t0, 6),
            )
        return scores

    def _sentinel_rollback(self, verdict: str, rollout_index: int):
        """Sentinel trip (docs/RESILIENCE.md): charge the rollback budget,
        quarantine the offending rollout index, and restore the last
        committed checkpoint. The in-memory sentinel/watchdog state is
        re-applied after the restore — the checkpoint's (older) journal must
        not erase the trip that triggered this rollback."""
        step_attempted = self.state["global_step"] + 1
        last = self.ckpt.latest_step()
        print(
            f"[resilience] sentinel tripped ({verdict}) at step "
            f"{step_attempted} (rollout {rollout_index}) — rolling back to "
            f"checkpoint {last}"
        )
        # flight recorder FIRST (before note_rollback can raise on budget
        # exhaustion and before the restore rewinds state): the blackbox
        # holds the tripped step's span (tagged with the quarantined
        # rollout index), every thread's in-flight spans, and the latest
        # counter snapshots — alongside the checkpoint it rolls back to
        self.tracer.instant(
            "sentinel.trip", verdict=verdict, rollout_index=rollout_index,
            step=step_attempted,
        )
        self.tracer.dump_blackbox(
            self._telemetry_dir, step_attempted, "sentinel_trip",
            extra={"verdict": verdict, "rollout_index": int(rollout_index),
                   "rollback_to_step": last},
        )
        if last is None:
            raise RuntimeError(
                f"sentinel tripped ({verdict}) at step {step_attempted} with "
                "no committed checkpoint to roll back to — enable save_steps "
                "or disable cfg.sentinel"
            )
        self.sentinel.note_rollback(step_attempted, rollout_index, verdict)
        keep_sentinel = self.sentinel.journal()
        keep_watchdog = self.watchdog.journal()
        # pre-restore statistics rewind with the checkpoint: without this,
        # replayed healthy steps would be folded into the EWMA twice —
        # checkpoints without a resilience journal fall back to zeroed stats
        # (a fresh warmup), which only delays spike detection, never
        # double-counts
        self.sentinel.steps, self.sentinel.ewma, self.sentinel.var = 0, 0.0, 0.0
        self.resume_from_checkpoint(last)
        # the trip's accounting must survive the restore (the checkpoint
        # predates it); EWMA stats stay whatever the checkpoint journaled
        self.sentinel.restore_accounting(keep_sentinel)
        self.watchdog.restore(keep_watchdog)
        self.logger.log_event(rollout_index, {
            "resilience/rollback": 1.0,
            "resilience/rollback_to_step": float(last),
            "resilience/rollbacks": float(self.sentinel.rollbacks),
        })

    def resume_from_checkpoint(self, step: Optional[int] = None):
        """Restore params (+ optimizer state, PRNG key, step/episode counters)
        from a saved checkpoint. `step=None` → latest.

        The reference persists optimizer/scheduler/RNG every save
        (`grpo_trainer.py:345-349`) but ships no resume entry point
        (SURVEY.md §5.3); this is that entry point.
        """
        latest = self.ckpt.latest_step()
        step = step if step is not None else latest
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.cfg.output_dir}")
        if self._orchestrator is not None:
            # queued samples were generated from pre-restore params (and the
            # producer's data cursor ran ahead of the consumed counter) —
            # tear the pipeline down; train() rebuilds it from the restored
            # cursor and the journaled counters
            self._orchestrator.close()
            self._orchestrator = None
        restored = self.ckpt.restore(step, self._restore_template())
        if self.ckpt.last_restored_step is not None and \
                self.ckpt.last_restored_step != step:
            # the requested checkpoint was corrupt/torn and restore fell
            # back to an older intact one (docs/RESILIENCE.md ckpt.corrupt)
            # — adopt the step that actually loaded so trainer_state and
            # truncation below track the restored tree
            step = self.ckpt.last_restored_step
        if latest is not None and step < latest:
            # resuming an earlier step abandons the newer trajectory
            self.ckpt.truncate_after(step)
        self.params = restored["params"]
        if self._quant_layers is not None:
            self._refresh_quant_layers()  # re-quantize the RESTORED base
        if "opt_state" in restored:
            self.opt_state = restored["opt_state"]
        if "value" in restored:
            self.value_params = restored["value"]
        tstate = self.ckpt.load_trainer_state(step)
        self.state["global_step"] = tstate["step"]
        self.state["episode"] = tstate.get("episode", 0)
        self.state["opt_steps"] = tstate.get("opt_steps", 0)
        if "rng_key" in tstate:
            raw = jnp.asarray(np.asarray(tstate["rng_key"], dtype=np.uint32))
            self.key = jax.random.wrap_key_data(raw) if tstate.get("rng_key_typed") else raw
        # data-stream position: the loader is a deterministic function of
        # (seed, batch_size), so skipping the persisted consumed-rollout
        # count reproduces the stream the uninterrupted run would see (a
        # rollout_ahead prefetch in flight at save time was abandoned — its
        # batch is re-drawn; sparse-GRPO skip-updates consumed batches
        # without stepping, hence the dedicated counter). Without this a
        # resumed run silently re-trains on the first batches. Pre-counter
        # checkpoints fall back to global_step (exact for the dense runtime).
        # NOTE: under rollout_ahead what's exact is the DATA and PRNG
        # streams, not the sampled trajectories — the abandoned prefetch had
        # sampled from the params as of one update before the checkpoint,
        # while the re-draw samples from the restored (post-update)
        # params, so the first post-resume rollout is one update fresher
        # than the uninterrupted run's would have been.
        self.state["rollouts"] = tstate.get("rollouts", tstate["step"])
        # orchestrator journal: seeds the rebuilt queue's cumulative
        # drop/staleness counters so the metric series stays continuous
        # (pending samples are re-drawn from the rollouts cursor)
        self._orch_restore_state = tstate.get("orchestrator")
        # resilience journal: rollback spend, quarantined batches, restart
        # counters, degraded-mode flag — recovery behavior itself resumes.
        # (The internal sentinel-rollback path re-applies its own in-memory
        # state after this restore; see _sentinel_rollback.)
        res = tstate.get("resilience")
        if res:
            self.sentinel.restore(res.get("sentinel", {}))
            self.watchdog.restore(res.get("watchdog", {}))
        # health journal: restored baselines (EWMA/P² sketches), rule
        # levels, verdict + trip counts — same continuity contract as the
        # fleet counters. Windowed rates re-warm (monotonic clock).
        h = tstate.get("health")
        if h:
            self.health.restore(h)
        # lineage journal: the resumed ledger continues the monotonic
        # event-index stream and since-start drop counters (the files
        # themselves were already re-opened append-mode at construction)
        lj = tstate.get("lineage")
        if lj:
            self.lineage.restore(lj)
        # latency journal: reload every histogram's bucket counts so
        # post-resume quantiles cover the whole run (SchemeMismatch — a
        # checkpoint from a different bucket scheme — propagates: mixing
        # schemes would silently corrupt every quantile)
        hj = tstate.get("latency")
        if hj:
            self.latency.restore(hj)
        self._reset_data_iterator()
        return self.state

    def export_model(self, out_dir: str, dtype: str = "bfloat16") -> str:
        """Write the CURRENT policy as an HF-format checkpoint (config.json
        + model.safetensors), LoRA folded into the base weights — the
        reference's `save_model` output contract (`grpo_trainer.py:321-341`):
        what comes out of training is a directory transformers/vLLM load."""
        from nanorlhf_tpu.core.params import export_hf_checkpoint

        return export_hf_checkpoint(
            self.mcfg, self.params, out_dir,
            lora_scale=self.lora_scale if self.cfg.use_lora else None,
            dtype=dtype, tokenizer=self.tokenizer,
        )

    def close(self):
        # stop serving status endpoints first: the handlers read trainer
        # state that the teardown below starts dismantling
        self.exporter.close()
        if self._orchestrator is not None:
            self._orchestrator.close()  # stop + join the producer thread
            self._orchestrator = None
        # balance an XLA profile window an exception may have left open
        # (otherwise every later start_trace in the process fails), and
        # write the trace a crashed train() never reached
        self.profile_window.stop()
        self._write_trace()
        self.lineage.close()  # flush the provenance ledger
        self.ckpt.close()  # flush any in-flight async checkpoint write
        self.logger.close()
        self._preemption.uninstall()  # restore the previous SIGTERM handler

    # ------------------------------------------------------------------ #
    # per-algo advantage assembly (host-side numpy, shapes already fixed)
    # ------------------------------------------------------------------ #

    def _assemble_batch(self, scores, logprobs, ref_logprobs, padding_mask,
                        padding_mask_p1, seq_lengths, qr, responses,
                        context_length, batch_size, n, behavior_lp=None,
                        turn_info=None):
        cfg = self.cfg
        T = responses.shape[1]
        kl = logprobs - ref_logprobs
        batch = {
            "query_responses": qr,
            "responses": responses,
            "logprobs": logprobs,
            "padding_mask": padding_mask,
            "padding_mask_p1": padding_mask_p1,
        }
        if behavior_lp is not None:
            # rides through every per-algo selection below (RLOO/RAFT map
            # over batch.items()) and into the jitted update's minibatches
            batch["behavior_logprobs"] = behavior_lp

        if self.algo == AlgoName.GRPO:
            # sparse terminal advantage, reversed cumsum γ=1, KL stays in-loss
            if turn_info is not None:
                # multi-turn env episodes: one spike at EACH turn's final
                # model token (per-turn group z-scored advantages from
                # grpo_turn_advantage) instead of one terminal spike — the
                # γ=1 reversed cumsum below then broadcasts each turn's
                # credit as reward-to-go over the tokens that produced it
                turn_adv, turn_ends = turn_info
                rewards = np.asarray(per_turn_terminal_rewards(
                    jnp.asarray(turn_adv), jnp.asarray(turn_ends), T
                ))
            else:
                rewards = np.asarray(sparse_terminal_rewards(
                    jnp.asarray(scores), jnp.asarray(seq_lengths), T
                ))
            if cfg.whiten_rewards:
                rewards = np.asarray(masked_whiten(
                    jnp.asarray(rewards), jnp.asarray(~padding_mask_p1), shift_mean=True
                ))
                rewards = np.where(padding_mask_p1, 0.0, rewards)
            adv = np.asarray(discounted_returns(jnp.asarray(rewards), 1.0))
            if cfg.advantage_whiten:
                adv = np.asarray(masked_whiten(jnp.asarray(adv), jnp.asarray(~padding_mask)))
            adv = np.where(padding_mask, 0.0, adv)
            batch["advantages"] = adv
            batch["ref_logprobs"] = ref_logprobs
            # GRPO keeps KL in-loss: non_score_reward is identically 0, and
            # the reference hard-codes the metric so (`grpo_trainer.py:730`)
            return batch, None, {"non_score_reward_old": 0.0}

        # KL-in-reward family
        kl_penalty = -cfg.kl_coef * np.where(padding_mask, 0.0, kl)
        rewards = np.asarray(sparse_terminal_rewards(
            jnp.asarray(scores), jnp.asarray(seq_lengths), T,
            kl_penalty=jnp.asarray(kl_penalty),
        ))
        if cfg.whiten_rewards:
            rewards = np.asarray(masked_whiten(
                jnp.asarray(rewards), jnp.asarray(~padding_mask_p1), shift_mean=True
            ))
            rewards = np.where(padding_mask_p1, 0.0, rewards)
        # the scores-vs-rlhf_reward split for KL-in-reward algorithms
        # (`RLOO/rloo_trainer.py:704-710`): non_score = the KL penalty alone,
        # rlhf_reward = the full shaped per-sequence reward, both over ALL
        # B·N rollouts (before any 1-of-N selection)
        reward_info = {
            "non_score_reward_old": float(kl_penalty.sum(1).mean()),
            "rlhf_reward_old": float(rewards.sum(1).mean()),
        }

        if self.algo == AlgoName.RLOO:
            rlhf_reward = rewards.sum(1)
            adv_seq = np.asarray(rloo_advantage(jnp.asarray(rlhf_reward), n))
            self.key, k = jax.random.split(self.key)
            keep = np.asarray(keep_one_of_n_indices(k, batch_size, n))
            rows = np.arange(batch_size)
            sel = lambda x: x.reshape(batch_size, n, *x.shape[1:])[rows, keep]
            adv_seq = adv_seq.reshape(batch_size, n)[rows, keep]
            if cfg.advantage_whiten:
                adv_seq = np.asarray(masked_whiten(
                    jnp.asarray(adv_seq), jnp.ones_like(jnp.asarray(adv_seq), bool)
                ))
            batch = {k_: sel(v) for k_, v in batch.items()}
            batch["advantages_seq"] = adv_seq
            return batch, keep, reward_info

        if self.algo == AlgoName.RAFT:
            rlhf_reward = rewards.sum(1)
            if cfg.raft_selection == "random":
                self.key, rk = jax.random.split(self.key)
                keep = np.asarray(best_of_k_indices(jnp.asarray(rlhf_reward), n, key=rk))
            else:
                keep = np.asarray(best_of_k_indices(jnp.asarray(rlhf_reward), n))
            rows = np.arange(batch_size)
            batch = {
                k_: v.reshape(batch_size, n, *v.shape[1:])[rows, keep]
                for k_, v in batch.items()
            }
            return batch, keep, reward_info

        if self.algo == AlgoName.PPO:
            values = self._value_pass(qr, context_length)
            values = np.where(padding_mask_p1, 0.0, values)
            adv, returns = gae(
                jnp.asarray(rewards), jnp.asarray(values), cfg.gamma, cfg.lam
            )
            adv = np.asarray(adv)
            if cfg.advantage_whiten:
                adv = np.asarray(masked_whiten(jnp.asarray(adv), jnp.asarray(~padding_mask)))
            adv = np.where(padding_mask, 0.0, adv)
            batch["advantages"] = adv
            batch["returns"] = np.asarray(returns)
            batch["values"] = values
            return batch, None, reward_info

        # REINFORCE / ReMax: γ-discounted reversed cumsum
        adv = np.asarray(discounted_returns(jnp.asarray(rewards), cfg.gamma))
        if cfg.advantage_whiten:
            adv = np.asarray(masked_whiten(jnp.asarray(adv), jnp.asarray(~padding_mask)))
        adv = np.where(padding_mask, 0.0, adv)
        batch["advantages"] = adv
        return batch, None, reward_info

    def _value_pass(self, qr, context_length):
        """Chunked value prediction (`PPO/ppo_trainer.py:630-634`).

        Unaffected by `cfg.fused_logprob`: the value head projects hidden
        states to [B, T, 1] scores — there is no vocab-sized logits tensor
        to fuse away, so the naive score_forward IS already the memory-
        minimal form (same reason the in-update vpred forward stays as-is).
        """
        total = qr.shape[0]
        # value forward emits [B, T, 1] scores — no vocab-sized logits block —
        # so only the activation-based token budget applies
        chunk = max(1, min(total, ACTIVATION_TOKEN_BUDGET // qr.shape[1]))
        vals = []
        if not hasattr(self, "_value_fn"):
            from functools import partial

            mcfg, pad_id = self.mcfg, self.tokenizer.pad_token_id
            value_lora_scale = self.value_lora_scale

            if self._sp_on():
                from nanorlhf_tpu.parallel.sp import sp_score_values

                mesh, fsdp_axis = self.mesh, self._fsdp_axis()
                # scoring never differentiates → flash ring is legal
                scorer = partial(
                    sp_score_values, config=mcfg, pad_token_id=pad_id,
                    mesh=mesh, fsdp_axis=fsdp_axis,
                    lora_scale=value_lora_scale, attn_impl=mcfg.attention_impl,
                )
            else:
                scorer = partial(score_forward, config=mcfg,
                                 pad_token_id=pad_id,
                                 lora_scale=value_lora_scale)

            @partial(jax.jit, static_argnums=(2,))
            def value_fn(vparams, qr_chunk, context_length: int):
                v = scorer(vparams, query_responses=qr_chunk)[:, :, 0]
                return v[:, context_length - 1 : -1]

            self._value_fn = value_fn
        for i in range(0, total, chunk):
            n_real = min(chunk, total - i)
            vals.append(np.asarray(
                self._value_fn(self.value_params,
                               jnp.asarray(pad_chunk(qr[i : i + chunk], chunk)),
                               context_length)
            )[:n_real])
        return np.concatenate(vals)
