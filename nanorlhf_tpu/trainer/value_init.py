"""PPO value-model initializer — the pre-training phase before PPO proper.

Re-states `/root/reference/PPO/value_initializer.py:69-388` TPU-style: roll
out one batch of prompts with the frozen policy (n=1), compute KL-shaped
rewards from policy/ref logprobs, build γ-discounted *returns*, then regress
the value model onto those returns with a masked-MSE loss, an 80/20
train/val split and early stopping (patience 3). The reference reports this
costs ~15 minutes on an A100 before PPO starts (`PPO/ppo.py:370`).

Everything runs on the shared mesh: rollout via the jitted sampler, the
regression as a jitted Adam loop — no model migration, no HF Trainer.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from nanorlhf_tpu.algos import discounted_returns, sparse_terminal_rewards
from nanorlhf_tpu.core.config import ModelConfig
from nanorlhf_tpu.core.model import (
    padded_forward_hidden,
    padded_forward_logits,
    score_forward,
    unembedding,
)
from nanorlhf_tpu.ops.fused_logprob import fused_logprob
from nanorlhf_tpu.ops.masking import (
    INVALID_LOGPROB,
    first_true_indices,
    logprobs_from_logits,
    masked_mean,
    response_padding_masks,
    truncate_response,
)
from nanorlhf_tpu.sampler import SamplingParams, generate


@dataclasses.dataclass
class ValueInitConfig:
    """`Value_Finetune_Config` parity (`/root/reference/PPO/ppo.py:78-110`)."""

    train_data_size: int = 500
    num_train_epochs: int = 8
    per_device_train_batch_size: int = 8
    learning_rate: float = 5e-5
    train_split_rate: float = 0.8
    early_stopping_patience: int = 3
    # reduce-on-plateau parity (`PPO/ppo.py:92-98`: factor 0.5, patience 0 —
    # halve on every non-improving eval)
    plateau_factor: float = 0.5
    plateau_patience: int = 0


def finetune_value_model(
    value_params: dict,
    policy_params: dict,
    ref_params: dict | None,
    reward_func,
    prompts: np.ndarray,          # [N, Tp] left-padded prompt ids
    tokenizer,
    model_config: ModelConfig,
    response_length: int,
    temperature: float,
    kl_coef: float,
    gamma: float,
    vcfg: ValueInitConfig = ValueInitConfig(),
    whiten_rewards: bool = False,
    lora_scale: float = 1.0,
    value_lora_cfg=None,
    key: jax.Array | None = None,
    fused_logprob_scoring: bool = True,
) -> dict:
    """Returns value_params regressed onto the rollout returns.

    `value_lora_cfg` (a LoraConfig) restricts the regression to the value
    tree's trainable partition — LoRA adapters + score head + embed — with
    the frozen backbone combined back in for each forward (the reference
    value initializer fine-tunes the PEFT-wrapped value model,
    `PPO/ppo.py:369-380`). The value tree must already carry its "lora"
    subtree (RLTrainer initializes it; standalone callers use
    init_lora_params).
    """
    key = key if key is not None else jax.random.PRNGKey(0)
    pad_id, eos_id = tokenizer.pad_token_id, tokenizer.eos_token_id
    prompts = prompts[: vcfg.train_data_size]
    context_length = prompts.shape[1]

    # ---- rollout (n=1) + reward --------------------------------------------
    key, gk = jax.random.split(key)
    prompts_j = jnp.asarray(prompts)
    responses = generate(
        policy_params, model_config, prompts_j, prompts_j != pad_id, gk,
        SamplingParams(temperature=temperature, top_p=0.95, n=1,
                       max_tokens=response_length),
        eos_token_id=eos_id, pad_token_id=pad_id, lora_scale=lora_scale,
    )
    responses_np = np.asarray(responses)
    question_strings = [
        q.replace(tokenizer.pad_token, "") for q in tokenizer.batch_decode(prompts)
    ]
    decoded = tokenizer.batch_decode(responses_np)
    scores = np.asarray(
        reward_func([q + r for q, r in zip(question_strings, decoded)],
                    tokenizer.eos_token),
        np.float32,
    )

    # ---- logprob pass → KL-shaped rewards → returns ------------------------
    qr = np.concatenate([prompts, responses_np], axis=1)

    # ref_params=None (ref-free mode, kl_coef 0): skip the ref forward
    # entirely — the KL shaping it would feed is multiplied away, and a
    # stand-in policy forward would just double the pass for a zero term
    ref_free = ref_params is None

    # fused hidden→logprob scorer (ops/fused_logprob.py, default chunk/impl
    # — this helper has no RLConfig to read knobs from): without it this
    # one-time startup pass would be the last place still allocating the
    # full [chunk, T, V] logits block at LLM vocabularies.
    # `fused_logprob_scoring=False` mirrors cfg.fused_logprob=False (the
    # PPO entrypoint threads it) so the naive-parity escape hatch covers
    # this pass too.
    def score_one(p, ids, ctx, scale):
        resp = ids[:, ctx:]
        if fused_logprob_scoring:
            w, w_t = unembedding(model_config, p)
            return fused_logprob(
                padded_forward_hidden(p, model_config, ids, pad_id,
                                      lora_scale=scale,
                                      response_context_length=ctx),
                w, resp, temperature, transposed=w_t,
            )
        return logprobs_from_logits(
            padded_forward_logits(p, model_config, ids, pad_id,
                                  lora_scale=scale,
                                  response_context_length=ctx),
            resp, temperature,
        )

    @partial(jax.jit, static_argnums=(3, 4))
    def lp_fn(p, rp, ids, ctx, with_ref: bool):
        lp = score_one(p, ids, ctx, lora_scale)
        if not with_ref:
            return lp, lp
        return lp, score_one(rp, ids, ctx, 1.0)

    chunk = max(1, 28 * 2316 // qr.shape[1])
    lps, rlps = [], []
    for i in range(0, qr.shape[0], chunk):
        lp, rlp = lp_fn(
            policy_params,
            policy_params if ref_free else ref_params,
            jnp.asarray(qr[i : i + chunk]), context_length, not ref_free,
        )
        lps.append(np.asarray(lp))
        rlps.append(np.asarray(rlp))
    logprobs, ref_logprobs = np.concatenate(lps), np.concatenate(rlps)

    post = truncate_response(eos_id, pad_id, jnp.asarray(responses_np))
    seq_len = first_true_indices(post == pad_id) - 1
    padding_mask, padding_mask_p1 = response_padding_masks(np.asarray(post), seq_len)
    padding_mask = np.asarray(padding_mask)
    padding_mask_p1 = np.asarray(padding_mask_p1)
    logprobs = np.where(padding_mask, INVALID_LOGPROB, logprobs)
    ref_logprobs = np.where(padding_mask, INVALID_LOGPROB, ref_logprobs)

    kl_penalty = -kl_coef * np.where(padding_mask, 0.0, logprobs - ref_logprobs)
    rewards = np.asarray(sparse_terminal_rewards(
        jnp.asarray(scores), jnp.asarray(np.asarray(seq_len)),
        responses_np.shape[1], kl_penalty=jnp.asarray(kl_penalty),
    ))
    if whiten_rewards:
        from nanorlhf_tpu.ops.masking import masked_whiten

        rewards = np.asarray(masked_whiten(
            jnp.asarray(rewards), jnp.asarray(~padding_mask_p1), shift_mean=True
        ))
        rewards = np.where(padding_mask_p1, 0.0, rewards)
    returns = np.asarray(discounted_returns(jnp.asarray(rewards), gamma))

    # ---- masked-MSE regression with early stopping -------------------------
    n = qr.shape[0]
    n_train = int(n * vcfg.train_split_rate)
    perm = np.random.default_rng(0).permutation(n)
    tr, va = perm[:n_train], perm[n_train:]

    # trainable/frozen partition: full tree without LoRA, else adapters +
    # score + embed only (Adam state never materializes for the backbone)
    if value_lora_cfg is not None:
        from nanorlhf_tpu.core.lora import trainable_mask

        vmask = trainable_mask(value_params, value_lora_cfg)
        vmask["score"] = True
        value_lora_scale = value_lora_cfg.scale
    else:
        vmask = jax.tree.map(lambda _: True, value_params)
        value_lora_scale = 1.0
    trainable = jax.tree.map(lambda p, m: p if m else None, value_params, vmask)
    frozen = jax.tree.map(lambda p, m: None if m else p, value_params, vmask)

    def combine(t, f):
        return jax.tree.map(
            lambda a, b: b if a is None else a, t, f,
            is_leaf=lambda x: x is None,
        )

    # reduce-on-plateau via an inject_hyperparams LR the host halves when the
    # val loss stalls (the reference's lr_scheduler_type, `PPO/ppo.py:92-93`)
    optimizer = optax.inject_hyperparams(optax.adam)(
        learning_rate=vcfg.learning_rate
    )
    opt_state = optimizer.init(trainable)

    def vloss(t, ids, labels, pm1):
        vp = combine(t, frozen)
        vpred = score_forward(
            vp, model_config, ids, pad_id, lora_scale=value_lora_scale
        )[:, context_length - 1 : -1, 0]
        vpred = jnp.where(pm1, 0.0, vpred)
        return 0.5 * masked_mean(jnp.square(vpred - labels), ~pm1)

    @jax.jit
    def step(t, opt_state, ids, labels, pm1):
        loss, grads = jax.value_and_grad(vloss)(t, ids, labels, pm1)
        updates, opt_state = optimizer.update(grads, opt_state)
        return optax.apply_updates(t, updates), opt_state, loss

    eval_loss_fn = jax.jit(vloss)

    bs = vcfg.per_device_train_batch_size
    best_val, best_trainable, patience = np.inf, trainable, 0
    plateau_wait = 0
    for epoch in range(vcfg.num_train_epochs):
        ep_perm = np.random.default_rng(epoch).permutation(len(tr))
        for i in range(0, len(tr) - bs + 1, bs):
            idx = tr[ep_perm[i : i + bs]]
            trainable, opt_state, _ = step(
                trainable, opt_state, jnp.asarray(qr[idx]),
                jnp.asarray(returns[idx]), jnp.asarray(padding_mask_p1[idx]),
            )
        val_losses = [
            float(eval_loss_fn(trainable, jnp.asarray(qr[va[i : i + bs]]),
                               jnp.asarray(returns[va[i : i + bs]]),
                               jnp.asarray(padding_mask_p1[va[i : i + bs]])))
            for i in range(0, max(1, len(va) - bs + 1), bs)
        ] or [0.0]
        val_loss = float(np.mean(val_losses))
        print(f"[value-init] epoch {epoch}: val_loss={val_loss:.5f}")
        if val_loss < best_val - 1e-6:
            best_val, best_trainable, patience = val_loss, trainable, 0
            plateau_wait = 0
        else:
            patience += 1
            plateau_wait += 1
            if plateau_wait > vcfg.plateau_patience:
                new_lr = float(opt_state.hyperparams["learning_rate"]) * vcfg.plateau_factor
                opt_state.hyperparams["learning_rate"] = jnp.asarray(new_lr)
                print(f"[value-init] plateau: lr -> {new_lr:.2e}")
                plateau_wait = 0
            if patience >= vcfg.early_stopping_patience:
                break
    return combine(best_trainable, frozen)
