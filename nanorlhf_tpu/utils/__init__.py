from nanorlhf_tpu.utils.profiling import PhaseTimer, trace_profile

__all__ = ["PhaseTimer", "trace_profile"]
