"""Persistent XLA compilation cache shared by launchers, bench, and tests.

Compile time is the scarcest resource on a tunneled TPU: the r1 bucket menu
(multiple context × response shapes + sp variants) recompiles every process,
and BENCH_r04 measured 23.6 s of compile for a *tiny* model on CPU. jax's
persistent cache turns the second process's compiles into disk loads — but
only if every entrypoint actually enables it, with a directory that survives
across sessions and is keyed so entries from a different jaxlib or host CPU
never load (XLA:CPU AOT results embed host vector extensions; a carried-over
cache on this host flipped sampled tokens, and mismatched extensions SIGILL).
"""

from __future__ import annotations

import hashlib
import os


def host_fingerprint() -> str:
    """jax/jaxlib version + host CPU feature flags, hashed short.

    The version pair matters because XLA:CPU AOT results embed
    version-dependent target tuning; the cpuinfo flags line matters because
    AOT code for wider vector extensions aborts on narrower hosts.
    """
    try:
        from importlib.metadata import version

        ver = f"{version('jax')}-{version('jaxlib')}"
    except Exception:
        ver = "unknown"
    try:
        with open("/proc/cpuinfo") as f:
            content = f.read()
        for key in ("flags", "Features"):  # x86 / aarch64 spellings
            for line in content.splitlines():
                if line.startswith(key):
                    return hashlib.sha1((ver + line).encode()).hexdigest()[:12]
        # unknown layout: hash the whole thing (may over-rotate on per-boot
        # fields, but never under-distinguishes vector extensions)
        return hashlib.sha1((ver + content).encode()).hexdigest()[:12]
    except OSError:
        import platform

        key = f"{ver}-{platform.machine()}-{platform.processor()}"
        return hashlib.sha1(key.encode()).hexdigest()[:12]


def default_cache_dir() -> str | None:
    """Repo-root `.jax_cache_<fingerprint>` (persists across driver rounds);
    `NANORLHF_CACHE_DIR` overrides; `NANORLHF_CACHE_DIR=0` disables (None)."""
    override = os.environ.get("NANORLHF_CACHE_DIR")
    if override == "0":
        return None
    if override:
        return override
    repo_root = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "..")
    )
    return os.path.join(repo_root, f".jax_cache_{host_fingerprint()}")


# sentinel naming is OWNED here — external cleaners (bench.py's parent
# removing a SIGKILLed child's claim, conftest's session-finish removal)
# must build paths through sentinel_path(), never re-derive the format
SENTINEL_PREFIX = ".suite_in_progress."


def sentinel_path(cache_dir: str, pid: int | None = None) -> str:
    return os.path.join(cache_dir, f"{SENTINEL_PREFIX}{pid or os.getpid()}")


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        # a corrupt/empty sentinel parses to -1; os.kill(-1, 0) signals the
        # whole process group and SUCCEEDS — treat nonpositive pids as dead
        return False
    try:
        os.kill(pid, 0)
        return True
    except PermissionError:
        return True  # alive, owned by another user — must NOT wipe under it
    except (ProcessLookupError, ValueError, OSError):
        return False


def heal_and_claim(path: str) -> str:
    """Crash-heal the cache dir, then plant a pid sentinel for this process.

    A process that dies hard (SIGKILL mid-write, native abort) can leave a
    corrupt cache entry that SIGABRTs every later run at load time
    (observed). Sentinels mark cache users in progress, PID-AWARE: a
    sentinel whose pid is dead marks a crash; the dir is wiped only when a
    crash marker exists AND no live process holds the cache (a naive
    "sentinel exists → wipe" destroyed the cache under a concurrent run).
    EVERY writer must claim — launchers, bench, tools, and pytest all share
    this dir, so an unclaimed writer would be invisible to the healer (its
    crashes never heal) and unprotected from it (a heal could rmtree under
    it). Returns the sentinel path; the atexit hook removes it."""
    import atexit
    import glob
    import signal

    os.makedirs(path, exist_ok=True)
    # the scan→wipe→claim sequence must be serialized: two processes
    # starting together (a pod launch starts N at once) could both read
    # "crash, no live holder", then one's rmtree deletes the other's fresh
    # sentinel and entries. flock releases automatically on process death,
    # so a crashed lock holder can't wedge later claims.
    lock_fd = None
    try:
        import fcntl

        lock_fd = os.open(os.path.join(os.path.dirname(path) or ".",
                                       os.path.basename(path) + ".lock"),
                          os.O_CREAT | os.O_RDWR)
        fcntl.flock(lock_fd, fcntl.LOCK_EX)
    except Exception:
        lock_fd = None  # no fcntl / exotic fs: proceed unlocked (best effort)
    try:
        saw_crash = saw_live = False
        for f in glob.glob(os.path.join(path, SENTINEL_PREFIX + "*")):
            try:
                pid = int(open(f).read().strip() or -1)
            except (OSError, ValueError):
                pid = -1
            if _pid_alive(pid):
                saw_live = True
            else:
                saw_crash = True
                try:
                    os.remove(f)
                except OSError:
                    pass
        if saw_crash and not saw_live:
            import shutil

            shutil.rmtree(path, ignore_errors=True)
            os.makedirs(path, exist_ok=True)
        sentinel = sentinel_path(path)
        with open(sentinel, "w") as f:
            f.write(str(os.getpid()))
    finally:
        if lock_fd is not None:
            try:
                os.close(lock_fd)  # closing releases the flock
            except OSError:
                pass

    def _cleanup():
        try:
            os.remove(sentinel)
        except OSError:
            pass

    atexit.register(_cleanup)
    # Timeout kills are ROUTINE for cache writers here (silicon_session.sh
    # bounds every step with coreutils `timeout` → SIGTERM), and Python's
    # default SIGTERM action skips atexit — the stale sentinel would read
    # as a crash and make the NEXT writer wipe the whole shared cache,
    # i.e. a designed event (step timeout on a flaky tunnel) would cost a
    # full bucket-menu recompile. Remove the sentinel on SIGTERM, then
    # re-raise with the default action. Only installed when no one else
    # claimed the signal; SIGKILLed children are cleaned by their killing
    # parent instead (bench.py) or healed as genuine crashes.
    try:
        if signal.getsignal(signal.SIGTERM) in (signal.SIG_DFL, None):

            def _on_term(signum, frame):
                _cleanup()
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass  # not the main thread — atexit still covers clean exits
    return sentinel


# set by the first successful enable_compilation_cache(): later calls are
# true no-ops returning this dir (ADVICE r5 — conftest, launchers, bench and
# tools all call enable; repeat claims would stack one atexit/SIGTERM
# handler per call and re-run the crash-heal scan under our own live claim)
_enabled_dir: str | None = None


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point jax's persistent compilation cache at a fingerprinted dir,
    with crash-heal + pid-sentinel claim (see `heal_and_claim`).

    Idempotent AND once-only per process: the first successful call claims
    the dir and registers the single sentinel-cleanup handler; every later
    call returns the already-enabled dir without touching disk or handlers
    (even if a different `cache_dir` is passed — re-pointing a live jax
    cache mid-process is not supported). Safe to call before or after
    backend init (the config only has to be set before the first compile).
    Returns the dir, or None when disabled (`NANORLHF_CACHE_DIR=0`) or
    unsupported by this jax.
    """
    global _enabled_dir
    if _enabled_dir is not None:
        return _enabled_dir
    import jax

    path = cache_dir or default_cache_dir()
    if path is None:
        return None
    try:
        heal_and_claim(path)
        jax.config.update("jax_compilation_cache_dir", path)
        # persist even sub-second compiles: a session's worth of small jits
        # (reward shaping, metric reductions) adds up over a tunnel
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        return None  # older jax / read-only fs — run uncached
    _enabled_dir = path
    return path
