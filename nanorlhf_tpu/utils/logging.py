"""Small logging helpers shared across host-side modules."""

from __future__ import annotations

import logging

_seen: set[tuple[str, str]] = set()


def warn_once(logger_name: str, msg: str, *args, level: int = logging.WARNING) -> None:
    """Log a formatted message at most once per unique (logger, rendered
    message) pair — for per-row lookup fallbacks that would otherwise spam
    one identical line per dataset row."""
    rendered = msg % args if args else msg
    key = (logger_name, rendered)
    if key in _seen:
        return
    _seen.add(key)
    logging.getLogger(logger_name).log(level, rendered)
