"""Tracing / profiling utilities (SURVEY.md §5.1).

The reference's observability is ad-hoc: an unused memory_profiler import, a
commented-out CUDA memory recorder, and one wall-clock print per update
(`/root/reference/GRPO/grpo_trainer.py:57,469,726`). The TPU-native
equivalents:

- `PhaseTimer`: per-phase wall-clock split (rollout / reward / logprob /
  update) the reference only has implicitly — `block_until_ready` at phase
  end so device async dispatch doesn't lie about where the time goes;
- `trace_profile`: a `jax.profiler` trace context writing a TensorBoard-
  loadable profile (XLA op breakdown, HBM usage) to a directory.
"""

from __future__ import annotations

import contextlib
import time

import jax


class PhaseTimer:
    """Accumulates wall-clock per named phase; one line per update."""

    def __init__(self):
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        # never reset: whole-run phase split (bench MFU accounting reads this
        # across updates while the per-update summary() resets each step)
        self.cumulative: dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        """Callers must block on the phase's outputs inside the block (e.g.
        `jax.block_until_ready(...)`) or async dispatch shifts time into the
        next phase."""
        t0 = time.time()
        try:
            yield
        finally:
            dt = time.time() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1
            self.cumulative[name] = self.cumulative.get(name, 0.0) + dt

    def summary(self, reset: bool = True) -> dict:
        out = {f"time/{k}_s": v for k, v in self.totals.items()}
        if reset:
            self.totals, self.counts = {}, {}
        return out


@contextlib.contextmanager
def trace_profile(log_dir: str, enabled: bool = True):
    """jax.profiler trace scope: `with trace_profile('/tmp/prof'): step()`."""
    if not enabled:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
