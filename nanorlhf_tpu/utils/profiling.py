"""Tracing / profiling utilities (SURVEY.md §5.1, docs/OBSERVABILITY.md).

The reference's observability is ad-hoc: an unused memory_profiler import, a
commented-out CUDA memory recorder, and one wall-clock print per update
(`/root/reference/GRPO/grpo_trainer.py:57,469,726`). The TPU-native
equivalents:

- `PhaseTimer`: per-phase wall-clock split (rollout / reward / logprob /
  update) the reference only has implicitly — `block_until_ready` at phase
  end so device async dispatch doesn't lie about where the time goes. Timing
  uses `time.perf_counter()` (monotonic): wall-clock `time.time()` jumps
  under NTP steps, which corrupts phase splits and everything downstream of
  them (the cumulative MFU accounting integrates these numbers over a run).
  With a telemetry.SpanTracer attached, every phase is also recorded as a
  trace span on the calling thread's track.
- `trace_profile`: a `jax.profiler` trace context writing a TensorBoard-
  loadable profile (XLA op breakdown, HBM usage) to a directory; start/stop
  stay balanced on exception, so a failed step doesn't wedge the profiler
  for the rest of the process.
- `ProfileWindow`: cfg-driven windowed profiling — the trainer polls it each
  update, and it wraps `trace_profile` around exactly the configured steps
  (`profile_at_step`/`profile_num_steps`) or around a window requested
  on-demand by touching a trigger file. Whole-run always-on profiling is
  useless at scale (GBs of XLA trace per minute); a 1–2 step window at a
  chosen step is what actually gets loaded into TensorBoard.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Optional

import jax


class PhaseTimer:
    """Accumulates monotonic wall-clock per named phase; one line per update."""

    def __init__(self, tracer=None, span_prefix: str = "trainer."):
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        # never reset: whole-run phase split (bench MFU accounting reads this
        # across updates while the per-update summary() resets each step)
        self.cumulative: dict[str, float] = {}
        # optional telemetry.SpanTracer: phases double as trace spans
        self.tracer = tracer
        self.span_prefix = span_prefix

    @contextlib.contextmanager
    def phase(self, name: str):
        """Callers must block on the phase's outputs inside the block (e.g.
        `jax.block_until_ready(...)`) or async dispatch shifts time into the
        next phase."""
        span = (
            self.tracer.span(self.span_prefix + name)
            if self.tracer is not None and self.tracer.enabled
            else contextlib.nullcontext()
        )
        t0 = time.perf_counter()
        try:
            with span:
                yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1
            self.cumulative[name] = self.cumulative.get(name, 0.0) + dt

    def summary(self, reset: bool = True) -> dict:
        out = {f"time/{k}_s": v for k, v in self.totals.items()}
        if reset:
            self.totals, self.counts = {}, {}
        return out


@contextlib.contextmanager
def trace_profile(log_dir: str, enabled: bool = True):
    """jax.profiler trace scope: `with trace_profile('/tmp/prof'): step()`.

    The finally-stop keeps start/stop BALANCED when the profiled body
    raises — without it the process-global profiler stays active and every
    later start_trace in the process fails with "already started"."""
    if not enabled:
        yield
        return
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class ProfileWindow:
    """Windowed XLA profiling around exactly N configured updates.

    `poll(step)` is called at the TOP of each update with the 1-based step
    about to run: the window opens when `step == at_step` (or when the
    trigger file appears — `touch <output_dir>/PROFILE` on a live run) and
    closes after `num_steps` updates. `stop()` is idempotent and must be
    reachable from the trainer's close() path so an exception inside a
    profiled step still balances start/stop."""

    def __init__(self, log_dir: str, at_step: Optional[int] = None,
                 num_steps: int = 1, trigger_file: Optional[str] = None):
        self.log_dir = log_dir
        self.at_step = at_step
        self.num_steps = max(1, int(num_steps))
        self.trigger_file = trigger_file
        self.windows = 0          # completed windows (test/debug introspection)
        self._cm = None
        self._stop_at: Optional[int] = None
        self._armed = at_step is not None

    @property
    def active(self) -> bool:
        return self._cm is not None

    def _trigger_requested(self) -> bool:
        if not self.trigger_file or not os.path.exists(self.trigger_file):
            return False
        try:
            os.remove(self.trigger_file)  # consume the request
        except OSError:
            pass
        return True

    def poll(self, step: int) -> None:
        """Advance the window state machine for the update about to run."""
        if self.active and step >= self._stop_at:
            self.stop()
        if self.active:
            return
        start = self._armed and self.at_step is not None and step >= self.at_step
        if start:
            self._armed = False  # one cfg-driven window per run
        if start or self._trigger_requested():
            self._start(step)

    def _start(self, step: int) -> None:
        self._cm = trace_profile(self.log_dir)
        self._cm.__enter__()
        self._stop_at = step + self.num_steps
        print(f"[profile] XLA trace window open: steps {step}.."
              f"{self._stop_at - 1} -> {self.log_dir}")

    def stop(self) -> None:
        """Close an open window (idempotent; called from poll, the end of
        train(), and trainer.close())."""
        if self._cm is None:
            return
        cm, self._cm = self._cm, None
        self._stop_at = None
        try:
            cm.__exit__(None, None, None)
        finally:
            self.windows += 1
