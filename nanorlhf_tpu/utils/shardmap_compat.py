"""shard_map version compatibility — ONE import site for the API drift
between jax 0.4.x and current jax:

- location: `jax.shard_map` (new top-level export) vs
  `jax.experimental.shard_map.shard_map` (0.4.x);
- replication-check kwarg: `check_vma` (new name) vs `check_rep` (0.4.x).

Lives in utils (imports nothing from core/parallel) so both layers can use
it without cycles.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _shard_map

try:
    _PARAMS = frozenset(inspect.signature(_shard_map).parameters)
except (TypeError, ValueError):  # builtins without signatures
    _PARAMS = None


def shard_map(f=None, /, **kwargs):
    """`jax.shard_map` with `check_vma=` translated to `check_rep=` when the
    installed jax predates the rename. Call with the mapped function
    positionally and everything else by keyword (how this repo calls it)."""
    if _PARAMS is not None and "check_vma" in kwargs \
            and "check_vma" not in _PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, **kwargs) if f is not None else _shard_map(**kwargs)
