"""Test harness: simulate an 8-device TPU mesh on CPU.

Must set XLA flags before jax initializes its backend, hence module-level env
mutation in conftest (pytest imports this before any test module).
"""

import os

# Force CPU even if the environment pins another platform (e.g. a tunneled
# TPU): unit/sharding tests must run on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Belt and suspenders: site plugins (e.g. a tunneled-TPU registrar in
# sitecustomize) may have already overridden jax_platforms via jax.config at
# interpreter startup — config beats env vars, so force it back here too.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

# Persistent XLA compilation cache: jit compiles dominate suite wall time on
# small hosts; repeat runs (CI / driver rounds) reuse executables from disk.
# The dir is keyed by a host CPU fingerprint, and the crash-heal + pid
# sentinel logic lives in utils/compile_cache.py — SHARED with launchers,
# bench, and tools, which write the same dir: every writer claims a
# sentinel, or it would be invisible to the healer (its crashes never
# heal) and unprotected from it (a heal could rmtree under it).
# NOTE: cache-deserialized CPU executables with DONATED buffers abort the
# process on this jaxlib — which is why the trainer gates buffer donation
# off on the CPU backend (trainer.donate_argnums_on_accel); without that
# gate this cache would have to stay off for the whole suite.
from nanorlhf_tpu.utils.compile_cache import (  # noqa: E402
    enable_compilation_cache,
    sentinel_path,
)

_cache_dir = enable_compilation_cache()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_sessionfinish(session, exitstatus):
    # heal_and_claim's atexit hook also removes the sentinel; doing it at
    # session end (before interpreter exit) just shrinks the claim window
    if _cache_dir is not None:
        try:
            os.remove(sentinel_path(_cache_dir))
        except OSError:
            pass


@pytest.fixture
def rng():
    return np.random.default_rng(0)
