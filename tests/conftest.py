"""Test harness: simulate an 8-device TPU mesh on CPU.

Must set XLA flags before jax initializes its backend, hence module-level env
mutation in conftest (pytest imports this before any test module).
"""

import os

# Force CPU even if the environment pins another platform (e.g. a tunneled
# TPU): unit/sharding tests must run on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Belt and suspenders: site plugins (e.g. a tunneled-TPU registrar in
# sitecustomize) may have already overridden jax_platforms via jax.config at
# interpreter startup — config beats env vars, so force it back here too.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

# Persistent XLA compilation cache: jit compiles dominate suite wall time on
# small hosts; repeat runs (CI / driver rounds) reuse executables from disk.
# The dir is keyed by a host CPU fingerprint: XLA:CPU AOT results compiled on
# a machine with different vector extensions ABORT (SIGILL) when loaded — a
# cache carried across driver rounds on heterogeneous hosts did exactly that.
import hashlib


def _host_fingerprint() -> str:
    # the jax/jaxlib version pair belongs in the key: XLA:CPU AOT results
    # embed version-dependent target tuning (+prefer-no-gather/scatter et
    # al.), so entries written by a different jaxlib merely *warn* about a
    # machine-feature mismatch and then execute differently (observed: a
    # carried-over cache flipped sampled tokens on this host)
    try:
        from importlib.metadata import version

        ver = f"{version('jax')}-{version('jaxlib')}"
    except Exception:
        ver = "unknown"
    try:
        with open("/proc/cpuinfo") as f:
            content = f.read()
        for key in ("flags", "Features"):  # x86 / aarch64 spellings
            for line in content.splitlines():
                if line.startswith(key):
                    return hashlib.sha1(
                        (ver + line).encode()
                    ).hexdigest()[:12]
        # unknown layout: hash the whole thing (may over-rotate the cache on
        # per-boot fields, but never under-distinguishes vector extensions)
        return hashlib.sha1((ver + content).encode()).hexdigest()[:12]
    except OSError:
        import platform

        key = f"{ver}-{platform.machine()}-{platform.processor()}"
        return hashlib.sha1(key.encode()).hexdigest()[:12]


_cache_dir = os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", f".jax_cache_{_host_fingerprint()}"
))

# Crash healing: a suite process that dies hard (SIGKILL mid-write, native
# abort) can leave a corrupt cache entry that SIGABRTs every later run at
# load time (observed). Sentinels mark suites in progress — but they must be
# PID-AWARE: the naive "sentinel exists → previous run crashed → wipe"
# logic wiped the cache out from under a CONCURRENT suite when two pytest
# processes overlapped (observed: the live run then died on torn cache
# state, which planted the next crash sentinel — a self-sustaining failure).
# Rules: a sentinel whose pid is dead marks a crash; wipe only when a crash
# marker exists AND no live suite holds the cache.
os.makedirs(_cache_dir, exist_ok=True)


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        # a corrupt/empty sentinel parses to -1; os.kill(-1, 0) signals the
        # whole process group and SUCCEEDS — treat nonpositive pids as dead
        return False
    try:
        os.kill(pid, 0)
        return True
    except PermissionError:
        return True  # alive, owned by another user — must NOT wipe under it
    except (ProcessLookupError, ValueError, OSError):
        return False


import glob

_saw_crash, _saw_live = False, False
for _f in glob.glob(os.path.join(_cache_dir, ".suite_in_progress*")):
    try:
        _pid = int(open(_f).read().strip() or -1)
    except (OSError, ValueError):
        _pid = -1
    if _pid_alive(_pid):
        _saw_live = True
    else:
        _saw_crash = True
        try:
            os.remove(_f)
        except OSError:
            pass
if _saw_crash and not _saw_live:
    import shutil

    shutil.rmtree(_cache_dir, ignore_errors=True)
    os.makedirs(_cache_dir, exist_ok=True)
_sentinel = os.path.join(_cache_dir, f".suite_in_progress.{os.getpid()}")
with open(_sentinel, "w") as _f:
    _f.write(str(os.getpid()))

try:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    # persist even sub-second compiles: tiny-model suites are made of them
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
except Exception:
    pass  # older jax without the persistent cache — suite still runs

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_sessionfinish(session, exitstatus):
    try:
        os.remove(_sentinel)
    except OSError:
        pass


@pytest.fixture
def rng():
    return np.random.default_rng(0)
