"""Advantage estimators vs torch oracles restating the reference formulas."""

import jax
import jax.numpy as jnp
import numpy as np
import torch

from nanorlhf_tpu.algos import (
    grpo_group_advantage,
    rloo_advantage,
    remax_advantage,
    best_of_k_indices,
    keep_one_of_n_indices,
    sparse_terminal_rewards,
    discounted_returns,
    gae,
)


def test_grpo_group_advantage(rng):
    B, N = 5, 4
    scores = rng.normal(size=(B * N,)).astype(np.float32)
    got = np.asarray(grpo_group_advantage(jnp.asarray(scores), N))
    t = torch.from_numpy(scores).view(B, N)
    want = (t - t.mean(dim=1, keepdim=True)) / t.std(dim=1, keepdim=True)
    np.testing.assert_allclose(got, want.reshape(-1).numpy(), rtol=1e-4, atol=1e-5)


def test_grpo_zero_variance_group_maps_nan_to_zero():
    scores = jnp.array([2.0, 2.0, 2.0, 2.0, 1.0, 0.0, 1.0, 0.0])
    got = np.asarray(grpo_group_advantage(scores, 4))
    assert np.all(np.isfinite(got))
    np.testing.assert_array_equal(got[:4], 0.0)


def test_rloo_advantage(rng):
    B, N = 3, 4
    r = rng.normal(size=(B * N,)).astype(np.float32)
    got = np.asarray(rloo_advantage(jnp.asarray(r), N))
    t = torch.from_numpy(r).view(B, N)
    baseline = (t.sum(dim=1, keepdim=True) - t) / (N - 1)
    np.testing.assert_allclose(got, (t - baseline).reshape(-1).numpy(), rtol=1e-4)


def test_remax_advantage():
    s = jnp.array([1.0, 2.0, 3.0])
    b = jnp.array([0.5, 2.5, 3.0])
    np.testing.assert_allclose(np.asarray(remax_advantage(s, b)), [0.5, -0.5, 0.0])


def test_best_of_k():
    r = jnp.array([1.0, 5.0, 2.0, 0.0, 7.0, 3.0, 1.0, 2.0])
    np.testing.assert_array_equal(np.asarray(best_of_k_indices(r, 4)), [1, 0])
    rand = best_of_k_indices(r, 4, key=jax.random.PRNGKey(0))
    assert rand.shape == (2,) and bool(jnp.all(rand >= 0)) and bool(jnp.all(rand < 4))


def test_keep_one_of_n_range():
    idx = keep_one_of_n_indices(jax.random.PRNGKey(1), 100, 4)
    assert idx.shape == (100,)
    assert set(np.unique(np.asarray(idx))) <= {0, 1, 2, 3}


def test_sparse_terminal_rewards_placement():
    scores = jnp.array([10.0, 20.0])
    # row 0: seq ends at 2, position 3 exists -> score at 3
    # row 1: seq ends at 4 (last index of length-5 response) -> score at 4
    seq_len = jnp.array([2, 4])
    got = np.asarray(sparse_terminal_rewards(scores, seq_len, 5))
    want = np.zeros((2, 5), np.float32)
    want[0, 3] = 10.0
    want[1, 4] = 20.0
    np.testing.assert_array_equal(got, want)


def test_sparse_terminal_rewards_with_kl(rng):
    kl_pen = rng.normal(size=(2, 5)).astype(np.float32)
    scores = jnp.array([1.0, -1.0])
    seq_len = jnp.array([0, 3])
    got = np.asarray(sparse_terminal_rewards(scores, seq_len, 5, jnp.asarray(kl_pen)))
    want = kl_pen.copy()
    want[0, 1] += 1.0
    want[1, 4] += -1.0
    np.testing.assert_allclose(got, want, rtol=1e-5)


def _torch_discounted(rewards, gamma):
    lastgaelam = torch.zeros(rewards.shape[0])
    out = []
    for t in reversed(range(rewards.shape[1])):
        lastgaelam = rewards[:, t] + gamma * lastgaelam
        out.append(lastgaelam)
    return torch.stack(out[::-1], axis=1)


def test_discounted_returns(rng):
    r = rng.normal(size=(4, 9)).astype(np.float32)
    for gamma in (1.0, 0.95):
        got = np.asarray(discounted_returns(jnp.asarray(r), gamma))
        want = _torch_discounted(torch.from_numpy(r), gamma)
        np.testing.assert_allclose(got, want.numpy(), rtol=1e-4, atol=1e-5)


def test_gae_matches_reference_loop(rng):
    B, T = 3, 7
    rewards = rng.normal(size=(B, T)).astype(np.float32)
    values = rng.normal(size=(B, T)).astype(np.float32)
    gamma, lam = 1.0, 0.95
    adv, ret = gae(jnp.asarray(rewards), jnp.asarray(values), gamma, lam)

    tr, tv = torch.from_numpy(rewards), torch.from_numpy(values)
    lastgaelam = torch.zeros(B)
    rev = []
    for t in reversed(range(T)):
        nextvalues = tv[:, t + 1] if t < T - 1 else torch.zeros(B)
        delta = tr[:, t] + gamma * nextvalues - tv[:, t]
        lastgaelam = delta + gamma * lam * lastgaelam
        rev.append(lastgaelam)
    want_adv = torch.stack(rev[::-1], axis=1)
    np.testing.assert_allclose(np.asarray(adv), want_adv.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(ret), (want_adv + tv).numpy(), rtol=1e-4, atol=1e-5
    )
