"""Pallas flash attention (interpret mode on CPU) vs the XLA reference."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nanorlhf_tpu.ops.attention import flash_attention, reference_attention


def make_qkv(rng, B=2, H=4, KV=2, T=24, d=16):
    q = rng.normal(size=(B, H, T, d)).astype(np.float32)
    k = rng.normal(size=(B, KV, T, d)).astype(np.float32)
    v = rng.normal(size=(B, KV, T, d)).astype(np.float32)
    valid = np.ones((B, T), bool)
    valid[0, :5] = False   # left-padding pattern
    valid[1, :2] = False
    return map(jnp.asarray, (q, k, v, valid))


def test_flash_matches_reference_causal(rng):
    q, k, v, valid = make_qkv(rng)
    got = flash_attention(q, k, v, valid, causal=True, block_q=8, block_k=8)
    want = reference_attention(q, k, v, valid, causal=True)
    # compare only valid query rows (padding rows are unconstrained)
    mask = np.asarray(valid)[:, None, :, None]
    np.testing.assert_allclose(
        np.asarray(got) * mask, np.asarray(want) * mask, rtol=2e-4, atol=2e-4
    )


def test_flash_matches_reference_non_causal(rng):
    q, k, v, valid = make_qkv(rng, T=16)
    got = flash_attention(q, k, v, valid, causal=False, block_q=8, block_k=8)
    want = reference_attention(q, k, v, valid, causal=False)
    mask = np.asarray(valid)[:, None, :, None]
    np.testing.assert_allclose(
        np.asarray(got) * mask, np.asarray(want) * mask, rtol=2e-4, atol=2e-4
    )


def test_flash_non_multiple_length(rng):
    q, k, v, valid = make_qkv(rng, T=13)
    got = flash_attention(q, k, v, valid, causal=True, block_q=8, block_k=8)
    want = reference_attention(q, k, v, valid, causal=True)
    mask = np.asarray(valid)[:, None, :, None]
    assert got.shape == want.shape
    np.testing.assert_allclose(
        np.asarray(got) * mask, np.asarray(want) * mask, rtol=2e-4, atol=2e-4
    )


def test_flash_gqa_groups(rng):
    # H=8 query heads over KV=2 shared heads exercises the h//G index map
    q, k, v, valid = make_qkv(rng, H=8, KV=2, T=16)
    got = flash_attention(q, k, v, valid, causal=True, block_q=8, block_k=8)
    want = reference_attention(q, k, v, valid, causal=True)
    mask = np.asarray(valid)[:, None, :, None]
    np.testing.assert_allclose(
        np.asarray(got) * mask, np.asarray(want) * mask, rtol=2e-4, atol=2e-4
    )


def test_flash_gradients_match_reference(rng):
    q, k, v, valid = make_qkv(rng, T=16)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, valid, causal=True, block_q=8, block_k=8)
        return jnp.sum(out * jnp.where(valid[:, None, :, None], 1.0, 0.0))

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v, valid, causal=True)
        return jnp.sum(out * jnp.where(valid[:, None, :, None], 1.0, 0.0))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_flash_backward_env_switch_matches(rng, monkeypatch):
    """NANORLHF_FLASH_BWD=xla (recompute) and =pallas (kernel) agree."""
    q, k, v, valid = make_qkv(rng, T=16)

    def loss(q, k, v):
        out = flash_attention(q, k, v, valid, causal=True, block_q=8, block_k=8)
        return jnp.sum(out * jnp.where(valid[:, None, :, None], 1.0, 0.0) ** 2)

    monkeypatch.setenv("NANORLHF_FLASH_BWD", "pallas")
    g_pallas = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv("NANORLHF_FLASH_BWD", "xla")
    g_xla = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_pallas, g_xla):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_flash_backward_ragged_length(rng):
    """Gradients flow correctly through the internal block padding (T=13)."""
    q, k, v, valid = make_qkv(rng, T=13)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, valid, causal=True, block_q=8, block_k=8)
        return jnp.sum(out * jnp.where(valid[:, None, :, None], 1.0, 0.0))

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v, valid, causal=True)
        return jnp.sum(out * jnp.where(valid[:, None, :, None], 1.0, 0.0))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_flash_fully_masked_batch_row_is_finite(rng):
    q, k, v, valid = make_qkv(rng, T=16)
    valid = valid.at[0, :].set(False)  # entire row masked
    out = flash_attention(q, k, v, valid, causal=True, block_q=8, block_k=8)
    assert bool(jnp.all(jnp.isfinite(out)))
