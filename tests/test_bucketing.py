"""Bucket packing semantics + menu rounding."""

import numpy as np

from nanorlhf_tpu.trainer.bucketing import (
    create_batches,
    pad_rows,
    round_up_to_menu,
    shape_menu,
)


def test_create_batches_budget_respected():
    lengths = np.array([10, 3, 7, 2, 9, 4])
    budget = 18
    batches = create_batches(lengths, budget)
    # every index appears exactly once
    flat = sorted(i for b in batches for i in b)
    assert flat == list(range(6))
    # budget model holds per bucket
    for b in batches:
        assert int(lengths[b].max()) * len(b) <= budget
    # sorted ascending within the packing order
    maxes = [int(lengths[b].max()) for b in batches]
    assert maxes == sorted(maxes)


def test_create_batches_single_overbudget_sample():
    # one sample longer than the budget still gets its own bucket
    batches = create_batches(np.array([100]), 18)
    assert batches == [[0]]


def test_create_batches_packs_greedily():
    lengths = np.array([4, 4, 4, 4])
    batches = create_batches(lengths, 16)
    assert len(batches) == 1 and len(batches[0]) == 4


def test_shape_menu_and_rounding():
    menu = shape_menu(100, min_value=16)
    assert menu == [16, 32, 64, 100]
    assert round_up_to_menu(1, menu) == 16
    assert round_up_to_menu(16, menu) == 16
    assert round_up_to_menu(17, menu) == 32
    assert round_up_to_menu(101, menu) == 100  # capped


def test_pad_rows():
    out = pad_rows(
        {"a": np.ones((2, 3), np.int32), "m": np.zeros((2, 3), bool)},
        4, {"a": 9, "m": True},
    )
    assert out["a"].shape == (4, 3)
    np.testing.assert_array_equal(out["a"][2:], 9)
    assert out["m"][2:].all()
