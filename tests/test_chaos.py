"""Chaos soak harness (nanorlhf_tpu/chaos/, docs/RESILIENCE.md §chaos).

Pins the acceptance contract of ISSUE 17: seeded schedule composition
is deterministic and registry-complete (every wired fault site is
pooled or explicitly excluded), ddmin shrinks a failing clause set to a
1-minimal repro, a composed 3-site soak runs green through BOTH
end-to-end paths (loadgen→engine and trainer+fleet) with every
run-invariant auditor passing, `tools/inspect_run.py --chaos` rebuilds
the fault timeline + verdicts jax-free from the ledger alone, and a
deliberately injected invariant violation (KV pages leaked on the
cancel-reap path) is CAUGHT by an auditor and shrunk to a ≤2-clause
minimal repro whose one-liner replays it. CI runs this file as the
`chaos-smoke` tier-1 step.
"""

import json
import subprocess
import sys

import pytest

from nanorlhf_tpu.chaos import (
    ChaosPlan, INVARIANTS, SERVING_SITES, TRAINER_SITES, compose,
    repro_command, shrink, soak_serving, soak_trainer, uncovered_sites,
)
from nanorlhf_tpu.chaos.composer import EXCLUDED, _clause, fold_in
from nanorlhf_tpu.resilience.faults import parse_fault_spec


# --------------------------------------------------------------------- #
# composer: determinism, registry completeness, clause templates
# --------------------------------------------------------------------- #

def test_fault_site_registry_fully_partitioned():
    """Every registered injection point is either in a path pool or in
    EXCLUDED with a reason — adding a fault site without a composer
    decision fails here."""
    assert uncovered_sites() == set()
    pooled = set(TRAINER_SITES) | set(SERVING_SITES)
    assert pooled.isdisjoint(EXCLUDED)
    assert all(reason for reason in EXCLUDED.values())


def test_compose_is_deterministic_and_valid():
    p1 = compose(3, "trainer")
    p2 = compose(3, "trainer")
    assert p1 == p2                       # value-typed replay contract
    assert p1.digest == p2.digest
    assert p1.digest != compose(4, "trainer").digest
    assert set(p1.sites) <= set(TRAINER_SITES)
    # round-trips through the injector's parser clause for clause
    assert len(parse_fault_spec(p1.spec)) == len(p1.clauses) == 3


def test_seed3_plans_are_pinned():
    """The exact seed-3 schedules are part of the replay contract: a
    composer change that reshuffles them must be deliberate (these are
    the specs the soak-green tests below run and the ledger headers
    record)."""
    srv = compose(3, "serving")
    assert srv.spec == ("gw.disconnect:every=2,count=2 "
                        "gw.disconnect:every=5,count=3 "
                        "gw.disconnect:every=4,count=2")
    assert srv.digest == "90648a33dc151c44"
    trn = compose(3, "trainer")
    assert trn.spec == ("worker.crash:at=1,worker=1 "
                        "worker.slow:every=4,delay=0.058,count=3 "
                        "ckpt.save:at=1")
    assert trn.digest == "d2ba59a8651f601f"


def test_compose_serving_pool_wraps():
    """A pool smaller than n_sites wraps with fresh clause keys: three
    distinct disconnect waves, not one clause repeated."""
    plan = compose(3, "serving", n_sites=3)
    assert plan.sites == ("gw.disconnect",) * 3
    assert len(set(plan.clauses)) == 3    # per-slot keys diverge


def test_compose_rejects_bad_args():
    with pytest.raises(ValueError, match="path"):
        compose(0, "nosuch")
    with pytest.raises(ValueError, match="n_sites"):
        compose(0, "serving", n_sites=0)


def test_crash_clause_never_masks_surviving_sites():
    """worker.crash is fatal to its thread, so the composer pins it to
    the LAST worker and leaves worker.slow untargeted — composed clauses
    must stay fireable after the crash lands."""
    assert _clause("worker.crash", fold_in(0, 0), 2) == \
        "worker.crash:at=1,worker=1"
    slow = _clause("worker.slow", fold_in(0, 1), 2)
    assert "worker=" not in slow
    assert _clause("worker.fetch_weights", fold_in(0, 2), 2).endswith(
        ",worker=0")


# --------------------------------------------------------------------- #
# ddmin shrinker (pure, no soak)
# --------------------------------------------------------------------- #

def test_shrink_finds_1_minimal_pair():
    calls = []

    def failing(subset):
        calls.append(list(subset))
        return {"a", "c"} <= set(subset)

    minimal = shrink(["a", "b", "c", "d"], failing)
    assert set(minimal) == {"a", "c"}
    assert len(minimal) == 2
    # 1-minimality: removing either survivor makes the failure vanish
    assert not failing(["a"]) and not failing(["c"])


def test_shrink_single_culprit_and_order_preserved():
    minimal = shrink(["w", "x", "y", "z"], lambda s: "y" in s)
    assert minimal == ["y"]
    minimal = shrink(["a", "b", "c"], lambda s: {"a", "c"} <= set(s))
    assert minimal == ["a", "c"]          # original clause order kept


def test_shrink_rejects_passing_input():
    with pytest.raises(ValueError, match="False on the full clause"):
        shrink(["a", "b"], lambda s: False)


def test_shrink_budget_returns_best_so_far_failing():
    tests = [0]

    def failing(subset):
        tests[0] += 1
        return {"a", "e"} <= set(subset)

    minimal = shrink(list("abcdef"), failing, max_tests=3)
    assert {"a", "e"} <= set(minimal)     # still reproduces
    assert tests[0] <= 4                  # entry check + probe budget


def test_repro_command_is_the_cli_one_liner():
    cmd = repro_command(["gw.disconnect:every=2,count=2"], path="serving",
                        seed=7, run_dir="/tmp/r")
    assert cmd == ('python -m nanorlhf_tpu.chaos --path serving --seed 7 '
                   '--spec "gw.disconnect:every=2,count=2" --run-dir /tmp/r')


# --------------------------------------------------------------------- #
# composed soaks run green on both paths (the acceptance soak)
# --------------------------------------------------------------------- #

def test_serving_soak_green_and_inspectable(tmp_path):
    """Seed-3 three-clause serving soak: faults fire, every auditor
    passes, the ledger carries the full chaos provenance, and the
    offline inspector rebuilds timeline + verdicts from it."""
    from nanorlhf_tpu.telemetry.lineage import read_ledger

    run_dir = str(tmp_path / "run")
    plan = compose(3, "serving")
    rep = soak_serving(run_dir, plan)
    assert rep.ok, rep.failed
    assert {a.name for a in rep.audits} == set(INVARIANTS)
    assert rep.fired_sites() == {"gw.disconnect"}
    assert rep.fault_stats["gw.disconnect"]["fires"] >= 1
    assert rep.summary["offered"] == 24
    # severed streams surface as client errors, honestly accounted
    assert rep.summary["errors"] >= 1
    assert (rep.summary["completed"] + rep.summary["errors"]
            + rep.summary["shed"] == rep.summary["offered"])

    events = list(read_ledger(run_dir))
    kinds = {e.get("type") for e in events}
    assert {"chaos_run", "fault", "chaos_audit"} <= kinds
    fires = sum(s["fires"] for s in rep.fault_stats.values())
    assert sum(1 for e in events if e.get("type") == "fault") == fires

    # offline replay: jax-free, from the ledger alone
    out = subprocess.run(
        [sys.executable, "tools/inspect_run.py", run_dir, "--chaos",
         "--json"],
        capture_output=True, text=True, check=True)
    rebuilt = json.loads(out.stdout)
    assert rebuilt["ok"] is True
    assert rebuilt["runs"][0]["spec"] == plan.spec
    assert rebuilt["runs"][0]["spec_digest"] == plan.digest
    assert len(rebuilt["fires"]) == fires
    assert {a["name"] for a in rebuilt["audits"]} == set(INVARIANTS)
    assert all(a["ok"] for a in rebuilt["audits"])


def test_trainer_soak_green(tmp_path):
    """Seed-3 trainer soak: a fatal worker crash, straggler slowdowns
    and a checkpoint-save fault compose in one run; the fleet recovers
    and every global invariant holds."""
    run_dir = str(tmp_path / "run")
    plan = compose(3, "trainer")
    rep = soak_trainer(run_dir, plan)
    assert rep.ok, rep.failed
    assert {a.name for a in rep.audits} == set(INVARIANTS)
    # all three composed sites actually fired — a soak whose schedule
    # never lands proves nothing
    assert rep.fired_sites() == {"worker.crash", "worker.slow",
                                 "ckpt.save"}
    assert rep.summary["updates"] >= 1
    # the sample-conservation auditor saw real fleet evidence this time
    sample = next(a for a in rep.audits
                  if a.name == "chaos.sample_conservation")
    assert sample.checked > 0
    lease = next(a for a in rep.audits
                 if a.name == "chaos.lease_epoch_monotonic")
    assert lease.checked > 0


# --------------------------------------------------------------------- #
# a real violation is caught and shrunk to a minimal repro
# --------------------------------------------------------------------- #

def test_injected_violation_caught_and_shrunk(tmp_path, monkeypatch):
    """Sabotage the engine's cancel-reap path so abandoned KV pages are
    never released — the exact leak gw.disconnect exists to guard
    against. The kv_page_leak auditor must catch it, ddmin must shrink
    the 3-clause schedule to a ≤2-clause minimal repro, and the printed
    one-liner must replay that minimal spec."""
    from nanorlhf_tpu.serving.engine import ServingEngine

    orig_reap = ServingEngine._reap_cancelled

    def leaky_reap(self, *a, **kw):
        saved = self._radix.release
        self._radix.release = lambda pages: 0      # strand the pages
        try:
            return orig_reap(self, *a, **kw)
        finally:
            self._radix.release = saved

    monkeypatch.setattr(ServingEngine, "_reap_cancelled", leaky_reap)

    plan = compose(3, "serving")
    rep = soak_serving(str(tmp_path / "full"), plan)
    assert not rep.ok
    assert [a.name for a in rep.failed] == ["chaos.kv_page_leak"]
    assert "stranded" in rep.failed[0].detail

    probe = [0]

    def failing(clauses):
        probe[0] += 1
        sub = ChaosPlan(seed=plan.seed, path=plan.path,
                        clauses=tuple(clauses))
        r = soak_serving(str(tmp_path / f"shrink_{probe[0]:02d}"), sub,
                         n_requests=12)
        return any(a.name == "chaos.kv_page_leak" for a in r.failed)

    minimal = shrink(plan.clauses, failing, max_tests=8)
    assert 1 <= len(minimal) <= 2
    assert set(minimal) <= set(plan.clauses)
    assert failing(minimal)               # the minimal spec reproduces
    cmd = repro_command(minimal, path=plan.path, seed=plan.seed)
    assert f'--spec "{" ".join(minimal)}"' in cmd
    assert "--path serving" in cmd


def test_chaos_cli_repro_replay(tmp_path):
    """The printed repro one-liner actually runs: an explicit --spec
    replay through `python -m nanorlhf_tpu.chaos` exits 0 on PASS and
    prints every verdict."""
    out = subprocess.run(
        [sys.executable, "-m", "nanorlhf_tpu.chaos", "--path", "serving",
         "--seed", "3", "--spec", "gw.disconnect:every=3,count=2",
         "--run-dir", str(tmp_path / "replay")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "chaos: PASS" in out.stdout
    for name in INVARIANTS:
        assert name in out.stdout
