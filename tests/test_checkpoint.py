"""CheckpointManager: rotation, best-protection, `_old` one-save-back metric."""

import os

import jax.numpy as jnp
import numpy as np

from nanorlhf_tpu.trainer.checkpoint import CheckpointManager


def params_like(v):
    return {"w": jnp.full((2, 2), float(v))}


def _steps(out):
    return sorted(
        int(d.rsplit("-", 1)[1]) for d in os.listdir(out) if d.startswith("checkpoint-")
    )


def test_rotation_protects_best_and_newest(tmp_path):
    out = str(tmp_path / "ck")
    cm = CheckpointManager(out, save_total_limit=2, greater_is_better=True)
    # metric_old at save N scores checkpoint N-1
    cm.save(1, params_like(1))
    cm.save(2, params_like(2), metric_old=5.0)   # best = step 1 (5.0)
    cm.save(3, params_like(3), metric_old=1.0)   # step 2 scores 1.0
    cm.save(4, params_like(4), metric_old=2.0)   # step 3 scores 2.0
    assert cm.best_step() == 1
    steps = _steps(out)
    assert 1 in steps            # best protected
    assert 4 in steps            # newest protected
    assert len(steps) <= 3       # limit 2 + protected overflow at most


def test_save_total_limit_one_keeps_newest(tmp_path):
    out = str(tmp_path / "ck1")
    cm = CheckpointManager(out, save_total_limit=1, greater_is_better=True)
    cm.save(1, params_like(1))
    cm.save(2, params_like(2), metric_old=5.0)
    cm.save(3, params_like(3), metric_old=1.0)
    steps = _steps(out)
    assert 3 in steps, "the just-saved checkpoint must never be rotated away"
    assert cm.best_step() == 1 and 1 in steps


def test_metric_history_survives_new_manager(tmp_path):
    """Best-checkpoint knowledge must survive a process restart (resume)."""
    out = str(tmp_path / "ckh")
    cm = CheckpointManager(out, save_total_limit=2, greater_is_better=True)
    cm.save(1, params_like(1))
    cm.save(2, params_like(2), metric_old=9.0)    # best = step 1
    # simulated restart — flush the async writer first: a manager handed off
    # without close()/wait() looks like a crash mid-save to the successor
    # (uncommitted steps are clamped out of the metric history)
    cm.close()
    cm2 = CheckpointManager(out, save_total_limit=2, greater_is_better=True)
    assert cm2.best_step() == 1
    cm2.save(3, params_like(3), metric_old=1.0)
    cm2.save(4, params_like(4), metric_old=2.0)
    steps = _steps(out)
    assert 1 in steps, "pre-restart best must stay rotation-protected"
    # re-saving an existing step must not duplicate bookkeeping
    cm2.save(4, params_like(44))
    assert sorted(set(_steps(out))) == _steps(out)


def test_restore_roundtrip(tmp_path):
    out = str(tmp_path / "ck2")
    cm = CheckpointManager(out, save_total_limit=3)
    cm.save(7, params_like(42))
    restored = cm.restore(7, {"params": params_like(0)})
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 42.0)
    assert cm.latest_step() == 7
    assert cm.load_trainer_state(7)["step"] == 7


def test_uncommitted_checkpoint_ignored(tmp_path):
    """A process that dies mid-async-save leaves a checkpoint dir without the
    committed `tree/` subdir (orbax finalizes with an atomic rename). Such a
    dir must be invisible to latest_step()/resume — restoring it would fail."""
    out = str(tmp_path / "ck")
    cm = CheckpointManager(out, save_total_limit=3)
    cm.save(1, {"w": np.ones((2,))})
    cm.close()
    # simulate a crashed save: state json present, tree never committed
    crashed = os.path.join(out, "checkpoint-9")
    os.makedirs(crashed)
    with open(os.path.join(crashed, "trainer_state.json"), "w") as f:
        f.write('{"step": 9}')
    cm2 = CheckpointManager(out, save_total_limit=3)
    assert cm2.latest_step() == 1
