"""Compacting decode (sampler/compaction.py): output contract + equivalence."""

import numpy as np

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.sampler import SamplingParams, generate
from nanorlhf_tpu.trainer import AlgoName

from test_trainer_smoke import make_trainer

PAD, EOS = 0, 3


def _setup(vocab=128, rows=16, Tp=6):
    mcfg = ModelConfig.qwen2_tiny(vocab_size=vocab)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    rng = np.random.default_rng(1)
    ids = rng.integers(4, vocab, (rows, Tp)).astype(np.int32)
    ids[:, 0] = PAD  # a little left-padding
    return mcfg, params, jnp.asarray(ids), jnp.asarray(ids != PAD)


def test_greedy_compaction_matches_monolithic():
    """Greedy decode is sampling-free, so compaction must be EXACTLY
    equivalent to the monolithic loop — rows finish early (random model hits
    EOS fast), get compacted away, and the outputs still line up row-for-row."""
    mcfg, params, ids, mask = _setup()
    sp_mono = SamplingParams(greedy=True, max_tokens=24)
    sp_comp = SamplingParams(greedy=True, max_tokens=24, compaction_segments=6)
    out_m = np.asarray(generate(params, mcfg, ids, mask, jax.random.PRNGKey(2),
                                sp_mono, EOS, PAD))
    out_c = np.asarray(generate(params, mcfg, ids, mask, jax.random.PRNGKey(2),
                                sp_comp, EOS, PAD))
    np.testing.assert_array_equal(out_m, out_c)


def test_sampled_compaction_contract():
    """Sampled path: right-padded contract holds (EOS terminates each row,
    pads after), shapes match, every live token is in-vocab."""
    mcfg, params, ids, mask = _setup()
    sp = SamplingParams(temperature=1.0, top_p=0.95, max_tokens=24,
                        compaction_segments=4)
    out = np.asarray(generate(params, mcfg, ids, mask, jax.random.PRNGKey(5),
                              sp, EOS, PAD))
    assert out.shape == (16, 24)
    for row in out:
        hits = np.where(row == EOS)[0]
        if len(hits):
            assert (row[hits[0] + 1:] == PAD).all()
        assert (row >= 0).all() and (row < 128).all()


def test_capture_logprobs_with_compaction():
    mcfg, params, ids, mask = _setup()
    sp = SamplingParams(temperature=1.0, top_p=0.95, max_tokens=16,
                        compaction_segments=4, capture_logprobs=True)
    out, lp = generate(params, mcfg, ids, mask, jax.random.PRNGKey(7),
                       sp, EOS, PAD)
    out, lp = np.asarray(out), np.asarray(lp)
    assert lp.shape == out.shape
    live = out != PAD
    assert np.isfinite(lp[live]).all() and (lp[live] <= 0.0).all()


def test_trainer_compaction_smoke(tmp_path):
    trainer = make_trainer(
        AlgoName.GRPO, tmp_path, total_episodes=32, save_steps=0,
        rollout_compaction_segments=4,
    )
    state = trainer.train()
    assert state["global_step"] == 2


def _assert_sharded_matches_unsharded(sp: SamplingParams, seed: int):
    """Run `generate(sp)` unsharded and on a (4,2,1) mesh with a sharded
    batch; token streams must be bit-identical — sharding is a layout, not
    a semantics, decision. Shared by the plain and fanout compaction tests."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nanorlhf_tpu.parallel.mesh import MeshConfig, batch_sharding, make_mesh
    from nanorlhf_tpu.sampler.compaction import _batch_axis_size

    mcfg, params, ids, mask = _setup(rows=16)
    out_ref = np.asarray(generate(params, mcfg, ids, mask,
                                  jax.random.PRNGKey(seed), sp, EOS, PAD))

    mesh = make_mesh(MeshConfig(4, 2, 1))          # batch spans data*fsdp = 8
    bs = batch_sharding(mesh)
    assert _batch_axis_size(bs) == 8
    ids_s = jax.device_put(ids, bs)
    mask_s = jax.device_put(mask, bs)
    params_s = jax.device_put(
        params, NamedSharding(mesh, P()))          # replicated params
    out_s = np.asarray(generate(params_s, mcfg, ids_s, mask_s,
                                jax.random.PRNGKey(seed), sp, EOS, PAD,
                                batch_sharding=bs))
    np.testing.assert_array_equal(out_ref, out_s)
    return out_ref


def test_compaction_sharded_matches_unsharded():
    """Mesh-aware compaction (batch_sharding kwarg): gathered carries are
    re-laid-out under the caller's batch sharding and the gather target is
    clamped to a multiple of the batch-axis device count."""
    _assert_sharded_matches_unsharded(
        SamplingParams(temperature=1.0, top_p=0.95, max_tokens=24,
                       compaction_segments=6),
        seed=9,
    )


def test_compaction_sharded_fanout_matches():
    """The trainer's default-on stack composed: shared-prompt-KV fanout
    (n=4) + compacting decode + a sharded batch — layout decisions (GSPMD
    placement, gather re-layout) must never leak into sampling."""
    out = _assert_sharded_matches_unsharded(
        SamplingParams(temperature=1.0, top_p=0.95, max_tokens=24, n=4,
                       compaction_segments=6),  # shared_prompt_prefill default
        seed=5,
    )
    assert out.shape[0] == 64  # 16 prompts × 4 samples


def test_compaction_with_int8_kv_cache():
    """Compaction gathers the int8 cache 4-tuple (values + sublane scale
    planes, batch on axis 1) correctly: greedy compacted decode must equal
    the monolithic int8-cache run token-for-token."""
    import dataclasses

    mcfg, params, ids, mask = _setup()
    mcfg_q = dataclasses.replace(mcfg, kv_cache_quant="int8")
    sp_mono = SamplingParams(greedy=True, max_tokens=24)
    sp_comp = SamplingParams(greedy=True, max_tokens=24, compaction_segments=6)
    out_m = np.asarray(generate(params, mcfg_q, ids, mask,
                                jax.random.PRNGKey(2), sp_mono, EOS, PAD))
    out_c = np.asarray(generate(params, mcfg_q, ids, mask,
                                jax.random.PRNGKey(2), sp_comp, EOS, PAD))
    np.testing.assert_array_equal(out_m, out_c)
