"""enable_compilation_cache is once-only per process (ADVICE r5): the first
successful call latches the dir; every later call — launchers, bench
helpers, tools importing the module — must be a true no-op that neither
re-claims the dir (stacking atexit/SIGTERM handlers, re-running the
crash-heal scan under our own live claim) nor re-points a live jax cache.

Internals are monkeypatched so the test never enables a REAL cache in this
pytest process — conftest deliberately runs the suite uncached (deserialized
XLA:CPU executables abort under the donating update on this jaxlib).
"""

import jax
import pytest

import nanorlhf_tpu.utils.compile_cache as cc


def test_enable_latches_then_noops(monkeypatch, tmp_path):
    claims = []
    monkeypatch.setattr(cc, "_enabled_dir", None)
    monkeypatch.setattr(cc, "heal_and_claim", lambda p: claims.append(p))
    monkeypatch.setattr(jax.config, "update", lambda *a, **k: None)

    d = str(tmp_path / "cache")
    assert cc.enable_compilation_cache(d) == d
    assert claims == [d]

    def boom(path):
        raise AssertionError("repeat call must not re-claim the cache dir")

    monkeypatch.setattr(cc, "heal_and_claim", boom)
    # repeat call: same dir back, no claim, no handler registration
    assert cc.enable_compilation_cache() == d
    # even an explicit different dir is ignored once enabled (re-pointing a
    # live jax cache mid-process is unsupported)
    assert cc.enable_compilation_cache(str(tmp_path / "other")) == d


def test_disabled_env_does_not_latch(monkeypatch):
    monkeypatch.setattr(cc, "_enabled_dir", None)
    monkeypatch.setenv("NANORLHF_CACHE_DIR", "0")
    assert cc.enable_compilation_cache() is None
    assert cc._enabled_dir is None  # a later call may still enable


def test_failure_does_not_latch(monkeypatch, tmp_path):
    monkeypatch.setattr(cc, "_enabled_dir", None)

    def fail(path):
        raise OSError("read-only fs")

    monkeypatch.setattr(cc, "heal_and_claim", fail)
    assert cc.enable_compilation_cache(str(tmp_path / "c")) is None
    assert cc._enabled_dir is None
