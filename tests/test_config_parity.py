"""Config-surface parity additions: dataset fields, episodes-from-epochs."""

import numpy as np

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
from nanorlhf_tpu.parallel import MeshConfig
from nanorlhf_tpu.trainer import RLConfig, AlgoName, RLTrainer


def test_dataset_fields_exist():
    cfg = RLConfig()
    assert cfg.train_dataset_name == "Anthropic/hh-rlhf"
    assert cfg.train_dataset_split == "train"


def test_total_episodes_none_uses_epochs(tmp_path):
    tok = ToyTokenizer(256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    ds = load_prompt_dataset("synthetic:64", tok, max_prompt_len=8)
    cfg = RLConfig(
        algo=AlgoName.REINFORCE, output_dir=str(tmp_path / "ep"),
        total_episodes=None, num_train_epochs=2.0,
        response_length=4, sample_n=1,
        per_device_train_batch_size=1, gradient_accumulation_steps=1,
        num_mini_batches=1, use_lora=True, lora_r=4, lora_alpha=8,
        gradient_checkpointing=False, mesh=MeshConfig(-1, 1, 1), save_steps=0,
    )

    def reward(prs, eos):
        return np.zeros(len(prs), np.float32)

    trainer = RLTrainer(cfg, mcfg, tok, params, ds, reward)
    assert cfg.total_episodes == 128          # 2 epochs × 64 prompts
    assert cfg.num_total_batches == 128 // cfg.batch_size
