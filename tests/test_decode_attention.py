"""Prefix-bounded Pallas decode-attention kernel vs the XLA oracle."""

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

from nanorlhf_tpu.ops.decode_attention import (
    decode_attention,
    reference_decode_attention,
)


def _rand(rng, shape):
    return jnp.asarray(rng.standard_normal(shape).astype(np.float32))


def test_decode_attention_matches_reference(rng):
    B, H, KV, T, hd = 3, 8, 2, 512, 64
    q = _rand(rng, (B, H, hd))
    k = _rand(rng, (B, KV, T, hd))
    v = _rand(rng, (B, KV, T, hd))
    # per-row prefix windows: mixed left-pad offsets + fill levels
    start = jnp.asarray([0, 17, 300], jnp.int32)
    filled = jnp.asarray([512, 200, 400], jnp.int32)
    got = decode_attention(q, k, v, start, filled, block_k=128)
    want = reference_decode_attention(q, k, v, start, filled)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_decode_attention_single_slot(rng):
    """Smallest valid window: one slot (first decode step of a 1-token prompt)."""
    B, H, KV, T, hd = 2, 4, 4, 256, 32
    q = _rand(rng, (B, H, hd))
    k = _rand(rng, (B, KV, T, hd))
    v = _rand(rng, (B, KV, T, hd))
    start = jnp.asarray([0, 5], jnp.int32)
    filled = jnp.asarray([1, 6], jnp.int32)
    got = decode_attention(q, k, v, start, filled, block_k=128)
    want = reference_decode_attention(q, k, v, start, filled)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_decode_attention_unaligned_t(rng):
    """T_max not a block multiple: internal padding must not leak."""
    B, H, KV, T, hd = 2, 6, 2, 200, 64  # G=3 (< sublane), odd T
    q = _rand(rng, (B, H, hd))
    k = _rand(rng, (B, KV, T, hd))
    v = _rand(rng, (B, KV, T, hd))
    start = jnp.asarray([3, 0], jnp.int32)
    filled = jnp.asarray([77, 200], jnp.int32)
    got = decode_attention(q, k, v, start, filled, block_k=128)
    want = reference_decode_attention(q, k, v, start, filled)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_generate_pallas_decode_matches_xla(rng):
    """End-to-end: greedy generate with attention_impl='pallas' (flash prefill
    + prefix-bounded decode) emits the same tokens as the XLA path."""
    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.sampler import SamplingParams, generate

    cfg_xla = ModelConfig.qwen2_tiny(vocab_size=128)
    cfg_pl = dataclasses.replace(cfg_xla, attention_impl="pallas")
    params = init_params(cfg_xla, jax.random.PRNGKey(0), jnp.float32)
    PAD, EOS = 0, 3
    ids = np.full((2, 6), PAD, np.int32)
    ids[0, 2:] = [5, 6, 7, 8]
    ids[1, 4:] = [9, 10]
    mask = jnp.asarray((ids != PAD).astype(np.int32))
    sp = SamplingParams(greedy=True, max_tokens=8, n=1)
    out_xla = generate(params, cfg_xla, jnp.asarray(ids), mask,
                       jax.random.PRNGKey(1), sp, eos_token_id=EOS,
                       pad_token_id=PAD)
    out_pl = generate(params, cfg_pl, jnp.asarray(ids), mask,
                      jax.random.PRNGKey(1), sp, eos_token_id=EOS,
                      pad_token_id=PAD)
    np.testing.assert_array_equal(np.asarray(out_xla), np.asarray(out_pl))
