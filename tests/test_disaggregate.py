"""Disaggregated rollouts: generation on its own device group
(`RLConfig.rollout_devices`), training on the rest, params synced per
dispatch — the actor/learner split that puts rollout_ahead's overlap on
separate silicon (VERDICT r4 #8; multi-slice pods reserve whole slices via
`split_rollout_devices`). All on the forced 8-device CPU mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
from nanorlhf_tpu.parallel import MeshConfig
from nanorlhf_tpu.parallel.mesh import split_rollout_devices
from nanorlhf_tpu.trainer import AlgoName, RLConfig, RLTrainer


def rule_reward(pmt_and_responses, eos_token):
    return np.asarray(
        [1.0 if eos_token in s else -0.1 for s in pmt_and_responses],
        np.float32,
    )


# ---------------------------------------------------------------------------
# split_rollout_devices
# ---------------------------------------------------------------------------


class FakeDev:
    def __init__(self, id, slice_index=None):
        self.id = id
        if slice_index is not None:
            self.slice_index = slice_index


def test_split_tail_fallback():
    devs = [FakeDev(i) for i in range(8)]
    train, roll = split_rollout_devices(devs, 2)
    assert [d.id for d in train] == [0, 1, 2, 3, 4, 5]
    assert [d.id for d in roll] == [6, 7]


def test_split_prefers_whole_slice():
    # two 4-device slices: k=4 must take slice 1 whole
    devs = [FakeDev(i, slice_index=i // 4) for i in range(8)]
    train, roll = split_rollout_devices(devs, 4)
    assert {d.slice_index for d in roll} == {1}
    assert {d.slice_index for d in train} == {0}


def test_split_no_whole_slice_falls_back():
    # k=2 can't be a whole 4-device slice → id-ordered tail, warned: the
    # rollout group fits in slice 1 (ICI-internal) but leaves the TRAIN
    # mesh a partial slice (ADVICE r5)
    devs = [FakeDev(i, slice_index=i // 4) for i in range(8)]
    with pytest.warns(RuntimeWarning, match="partial slice"):
        train, roll = split_rollout_devices(devs, 2)
    assert [d.id for d in roll] == [6, 7]


def test_split_fallback_warns_when_rollout_spans_slices():
    # k=6 over two 4-device slices: tail takes all of slice 1 plus half of
    # slice 0 — rollout-internal collectives would cross DCN
    devs = [FakeDev(i, slice_index=i // 4) for i in range(8)]
    with pytest.warns(RuntimeWarning, match="DCN every decode step"):
        _, roll = split_rollout_devices(devs, 6)
    assert len({d.slice_index for d in roll}) == 2


def test_split_no_warning_without_slice_index(recwarn):
    # CPU test meshes (no slice_index): the tail fallback is the normal
    # path and must stay silent
    devs = [FakeDev(i) for i in range(8)]
    split_rollout_devices(devs, 2)
    assert not [w for w in recwarn if issubclass(w.category, RuntimeWarning)]


def test_split_no_warning_on_single_slice(recwarn):
    # a single-slice host (e.g. v4-8): every link is ICI — the fallback is
    # the only possible path and must not cry DCN
    devs = [FakeDev(i, slice_index=0) for i in range(8)]
    split_rollout_devices(devs, 2)
    assert not [w for w in recwarn if issubclass(w.category, RuntimeWarning)]


def test_split_bounds():
    devs = [FakeDev(i) for i in range(4)]
    for bad in (0, 4, 5, -1):
        with pytest.raises(ValueError):
            split_rollout_devices(devs, bad)


# ---------------------------------------------------------------------------
# trainer integration
# ---------------------------------------------------------------------------


def make_trainer(tmp_path, **overrides):
    tok = ToyTokenizer(vocab_size=256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    cfg = RLConfig(
        algo=AlgoName.GRPO,
        output_dir=str(tmp_path / "disagg"),
        response_length=8,
        temperature=1.0,
        sample_n=2,
        per_device_train_batch_size=1,
        gradient_accumulation_steps=2,
        num_mini_batches=2,
        learning_rate=1e-4,
        kl_coef=0.05,
        use_lora=True,
        lora_r=4,
        lora_alpha=8,
        mesh=MeshConfig(2, 2, 1),       # 4 train devices
        rollout_devices=4,               # 4 generation devices
        save_steps=0,
        report_to="none",
    )
    for k, v in overrides.items():
        setattr(cfg, k, v)
    # batch = 1*2*2 * world(4) = 16 episodes/update
    cfg.total_episodes = 32
    dataset = load_prompt_dataset("synthetic:64", tok, max_prompt_len=12)
    return RLTrainer(cfg, mcfg, tok, params, dataset, rule_reward)


def test_meshes_are_disjoint(tmp_path):
    tr = make_trainer(tmp_path)
    train_ids = {d.id for d in tr.mesh.devices.flat}
    roll_ids = {d.id for d in tr.rollout_mesh.devices.flat}
    assert len(train_ids) == 4 and len(roll_ids) == 4
    assert not (train_ids & roll_ids)
    # rollout model config must NOT carry the train mesh's kernel hints
    assert tr._rollout_mcfg.spmd_mesh is not tr.mesh


def test_disagg_grpo_trains(tmp_path):
    tr = make_trainer(tmp_path)
    state = tr.train()
    assert state["global_step"] == 2
    assert state["episode"] == 32


def test_disagg_with_rollout_ahead(tmp_path):
    tr = make_trainer(tmp_path, rollout_ahead=True)
    state = tr.train()
    assert state["global_step"] == 2


def test_disagg_with_quant_rollout(tmp_path):
    """int8 rollout view must re-shard onto the generation mesh too."""
    tr = make_trainer(tmp_path, rollout_quant="int8")
    state = tr.train(num_updates=1)
    assert state["global_step"] == 1


def test_explicit_mesh_rejected(tmp_path):
    from nanorlhf_tpu.parallel import make_mesh

    tok = ToyTokenizer(vocab_size=256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    cfg = RLConfig(
        algo=AlgoName.GRPO, output_dir=str(tmp_path / "x"),
        response_length=8, rollout_devices=2, report_to="none", save_steps=0,
    )
    dataset = load_prompt_dataset("synthetic:64", tok, max_prompt_len=12)
    with pytest.raises(ValueError, match="rollout_devices"):
        RLTrainer(cfg, mcfg, tok, params, dataset, rule_reward,
                  mesh=make_mesh(MeshConfig(2, 1, 1),
                                 devices=jax.devices()[:2]))


def test_disagg_sparse_grpo(tmp_path):
    from nanorlhf_tpu.trainer.sparse_grpo import SparseGRPOTrainer

    tok = ToyTokenizer(vocab_size=256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    params = init_params(mcfg, jax.random.PRNGKey(1), jnp.float32)
    cfg = RLConfig(
        algo=AlgoName.GRPO,
        output_dir=str(tmp_path / "sparse"),
        response_length=8,
        temperature=1.0,
        sample_n=2,
        per_device_train_batch_size=4,
        gradient_accumulation_steps=1,
        num_mini_batches=1,
        mesh=MeshConfig(4, 1, 1),
        rollout_devices=4,
        save_steps=0,
        report_to="none",
    )
    cfg.total_episodes = 32

    def noisy_reward(pmt_and_responses, eos_token):
        import zlib

        return np.asarray(
            [(zlib.crc32(s.encode()) % 17) / 17.0 for s in pmt_and_responses],
            np.float32,
        )

    dataset = load_prompt_dataset("synthetic:64", tok, max_prompt_len=12)
    tr = SparseGRPOTrainer(cfg, mcfg, tok, params, dataset, noisy_reward)
    state = tr.train(num_updates=1)
    assert state["global_step"] == 1


def test_disagg_with_sequence_parallel_training(tmp_path):
    """The r1 flagship combination: generation on its own devices while the
    TRAINING mesh runs sequence-parallel (sp=2) scoring/updates — the
    rollout mesh must not inherit the sp axis (generation is not
    sequence-sharded) and the sp machinery must see only the train mesh."""
    from nanorlhf_tpu.trainer.sparse_grpo import SparseGRPOTrainer

    tok = ToyTokenizer(vocab_size=256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    params = init_params(mcfg, jax.random.PRNGKey(2), jnp.float32)
    cfg = RLConfig(
        algo=AlgoName.GRPO,
        output_dir=str(tmp_path / "sp"),
        response_length=8,
        temperature=1.0,
        sample_n=2,
        per_device_train_batch_size=4,
        gradient_accumulation_steps=1,
        num_mini_batches=1,
        kl_coef=0.0,                      # ref-free, the r1 setting
        sampler_logprob_capture=True,
        mesh=MeshConfig(2, 1, 1, sp=2),   # 4 train devices, sp=2
        rollout_devices=4,
        save_steps=0,
        report_to="none",
    )
    cfg.total_episodes = 16

    def noisy_reward(pmt_and_responses, eos_token):
        import zlib

        return np.asarray(
            [(zlib.crc32(s.encode()) % 17) / 17.0 for s in pmt_and_responses],
            np.float32,
        )

    dataset = load_prompt_dataset("synthetic:64", tok, max_prompt_len=12)
    tr = SparseGRPOTrainer(cfg, mcfg, tok, params, dataset, noisy_reward)
    assert tr.mesh.shape["sp"] == 2
    assert tr.rollout_mesh.shape.get("sp", 1) == 1
    state = tr.train(num_updates=1)
    assert state["global_step"] == 1
