"""Launcher configs match the reference's per-algorithm settings; one tiny
offline end-to-end launcher run."""

import numpy as np

from nanorlhf_tpu.parallel import MeshConfig
from nanorlhf_tpu.trainer import AlgoName


def test_launcher_config_parity():
    from nanorlhf_tpu.entrypoints.grpo import build_config
    from nanorlhf_tpu.entrypoints.ppo import build_ppo_config
    from nanorlhf_tpu.entrypoints.raft import build_raft_config
    from nanorlhf_tpu.entrypoints.reinforce import build_reinforce_config
    from nanorlhf_tpu.entrypoints.remax import build_remax_config
    from nanorlhf_tpu.entrypoints.rloo import build_rloo_config

    g = build_config()
    assert (g.kl_coef, g.cliprange, g.temperature) == (0.01, 0.2, 0.9)
    assert (g.sample_n, g.response_length, g.learning_rate) == (4, 1500, 6e-6)
    assert g.advantage_whiten is False and g.use_lora and g.lora_r == 64

    assert build_rloo_config().algo == AlgoName.RLOO
    assert build_remax_config().sample_n == 1
    r = build_reinforce_config()
    assert r.advantage_whiten is True and r.sample_n == 1
    assert build_raft_config().sample_n == 4
    p = build_ppo_config()
    assert p.value_learning_rate == 1e-5 and p.lam == 0.95


def test_reinforce_launcher_offline_tiny(tmp_path):
    """Full launcher path (resolve_model/dataset/reward + run) offline."""
    from nanorlhf_tpu.entrypoints.common import run
    from nanorlhf_tpu.entrypoints.reinforce import build_reinforce_config

    cfg = build_reinforce_config()
    cfg.sft_model_path = "tiny-demo"          # triggers offline tiny model
    cfg.reward_model_path = ""                # rule-based stand-in
    cfg.output_dir = str(tmp_path / "ep")
    cfg.response_length = 8
    cfg.total_episodes = 16
    cfg.per_device_train_batch_size = 1
    cfg.gradient_accumulation_steps = 2
    cfg.num_mini_batches = 1
    cfg.learning_rate = 1e-4
    cfg.lora_r, cfg.lora_alpha = 4, 8
    cfg.gradient_checkpointing = False
    cfg.mesh = MeshConfig(-1, 1, 1)   # all 8 test devices on the data axis
    cfg.temperature = 1.0

    state = run(cfg)
    assert state["episode"] == 16
    assert (tmp_path / "ep" / "metrics.jsonl").exists()


def test_grpo_r1_prompt_cache(tmp_path, monkeypatch):
    """build_prompt_dataset consults the token cache: second call with the
    same corpus/tokenizer mmaps instead of re-encoding (encode disabled —
    via monkeypatch, so the tokenizer IDENTITY in the fingerprint is
    unchanged)."""
    import numpy as np

    from nanorlhf_tpu.data import ToyTokenizer
    from nanorlhf_tpu.entrypoints.grpo_r1 import (
        build_prompt_dataset, synthetic_math_corpus)

    tok = ToyTokenizer(512)
    qa = synthetic_math_corpus(24)
    d1 = build_prompt_dataset(qa, tok, cache_dir=str(tmp_path))

    def boom(*a, **k):
        raise AssertionError("re-tokenized on a cache hit")

    monkeypatch.setattr(ToyTokenizer, "encode", boom)
    d2 = build_prompt_dataset(qa, tok, cache_dir=str(tmp_path))
    np.testing.assert_array_equal(d1.input_ids, d2.input_ids)


def test_grpo_r1_main_offline_e2e(tmp_path, monkeypatch):
    """The full R1-Zero launcher path end to end, offline: synthetic math
    corpus, templated+cached prompts, sparse GRPO updates with the r1
    reward protocol, initial+periodic accuracy eval, and the HF handoff
    export at the end of the run. The dataset load is PINNED to the
    synthetic corpus — on a networked machine the fallback would otherwise
    download the full MetaMathQA split before slicing."""
    import os

    from nanorlhf_tpu.entrypoints import grpo_r1
    from nanorlhf_tpu.entrypoints.grpo_r1 import (
        build_config, main, synthetic_math_corpus)

    monkeypatch.setattr(
        grpo_r1, "load_math_datasets",
        lambda *a, limit=None, **k: (synthetic_math_corpus(24),
                                     synthetic_math_corpus(8, seed=1)),
    )

    cfg = build_config()
    cfg.sft_model_path = "tiny-demo"
    cfg.output_dir = str(tmp_path / "r1")
    cfg.dataset_cache_dir = str(tmp_path / "tok")
    cfg.export_hf_dir = str(tmp_path / "hf")
    cfg.response_length = 8
    cfg.total_episodes = 8
    cfg.per_device_train_batch_size = 1
    cfg.gradient_accumulation_steps = 1
    cfg.num_mini_batches = 1
    cfg.sample_n = 2
    cfg.learning_rate = 1e-4
    cfg.lora_r, cfg.lora_alpha = 4, 8
    cfg.gradient_checkpointing = False
    cfg.save_steps = 0
    cfg.eval_steps = 1
    cfg.report_to = "none"
    cfg.mesh = MeshConfig(-1, 1, 1)

    state = main(cfg, limit=24, max_prompt_len=24, eval_response_length=8)
    assert state["episode"] >= 8
    assert os.path.exists(os.path.join(cfg.export_hf_dir, "model.safetensors"))
    assert os.listdir(cfg.dataset_cache_dir)  # token cache was written
