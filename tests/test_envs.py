"""Vectorized multi-turn environments (ISSUE 15, docs/ENVIRONMENTS.md).

The gate by name:
- the episode driver runs 2-turn python-tool episodes over the paged
  scheduler's admission/recycling machinery (pages released BEFORE the
  tool runs, re-admission through the mid-loop prefill path), with
  deterministic per-(episode, turn) admission keys;
- a 2-update GRPO run on that env completes with >= 2 turns/episode in
  metrics.jsonl, every observation token loss_mask=False asserted
  against the ASSEMBLED batch mask, and `turn` lineage events joinable
  to `generation` events;
- SingleTurnEnv pins bit-identical (metrics minus wall-clock keys) to
  the bare-reward-func pipeline;
- the pooled executor reuses one warm worker across calls and survives
  a timeout with terminate→kill→respawn;
- inspect_run --turns rebuilds per-episode timelines from the ledger
  alone; and the env.hang / env.crash fault sites stall / degrade to an
  error observation without killing the rollout.
"""

import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
from nanorlhf_tpu.envs import (
    PythonToolEnv,
    SingleTurnEnv,
    build_env,
    extract_python_block,
    run_env_episodes,
)
from nanorlhf_tpu.parallel import MeshConfig
from nanorlhf_tpu.resilience import FaultInjector, parse_fault_spec
from nanorlhf_tpu.rewards.python_executor import PooledPythonExecutor
from nanorlhf_tpu.sampler import SamplingParams
from nanorlhf_tpu.telemetry import chains, read_ledger
from nanorlhf_tpu.trainer import AlgoName, RLConfig, RLTrainer

TOOLS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools", "inspect_run.py")

# the toy tokenizer collapses whitespace, so fenced ```python blocks don't
# survive a decode round-trip — tests pin the extracted program through the
# public extractor hook; the observation is still a real tool execution
PINNED_PROGRAM = "print(6 * 7)"


def text_reward(pairs, eos_token):
    """Deterministic text-only reward — identical answers on identical
    token streams, so the parity pin can compare metrics exactly."""
    return np.asarray(
        [float(len(s.split()) % 5) + (1.0 if eos_token in s else 0.0)
         for s in pairs],
        np.float32,
    )


# ---------------------------------------------------------------------------
# jax-free units: interface, extraction, advantages, executor, inspector
# ---------------------------------------------------------------------------


def test_extract_python_block_takes_last_fenced_block():
    text = ("thought ```python\nprint(1)\n``` more "
            "```python\nprint(2)\n``` done")
    assert extract_python_block(text).strip() == "print(2)"
    assert extract_python_block("no code here") is None


def test_single_turn_env_round_trip_matches_reward_func():
    env = build_env("single_turn", text_reward, eos_token="</s>")
    fn = env.as_reward_func()
    pairs = ["a b c", "d e </s>", "f"]
    got = np.asarray(fn(pairs, "</s>"))
    want = text_reward(pairs, "</s>")
    assert np.array_equal(got, want)
    with pytest.raises(ValueError):
        build_env("single_turn", text_reward, max_turns=2)
    with pytest.raises(ValueError):
        build_env("no_such_env", text_reward)


def test_python_tool_env_steps_and_terminal_reward():
    env = PythonToolEnv(text_reward, max_turns=2)
    env.eos_token = "</s>"
    try:
        st = env.reset(["q: "])
        obs, rew, done = env.step(
            st, ["```python\nprint(6 * 7)\n```"], indices=[0])
        assert not done[0] and rew[0] == 0.0
        assert "42" in obs[0]              # real subprocess stdout fed back
        obs2, rew2, done2 = env.step(st, ["final answer 42"], indices=[0])
        assert done2[0] and obs2[0] == ""
        assert rew2[0] == text_reward([st.prompts[0] + st.transcripts[0]],
                                      "</s>")[0]
    finally:
        env.close()


def test_grpo_turn_advantage_degenerates_to_group_advantage():
    from nanorlhf_tpu.algos import grpo_group_advantage, grpo_turn_advantage

    rng = np.random.default_rng(0)
    r = rng.normal(size=(8, 1)).astype(np.float32)     # K=1: one turn
    t = np.asarray(grpo_turn_advantage(jnp.asarray(r), 4))
    g = np.asarray(grpo_group_advantage(jnp.asarray(r[:, 0]), 4))
    np.testing.assert_allclose(t[:, 0], g, rtol=1e-6, atol=1e-6)
    # K=2: each turn column is z-scored within its group independently
    r2 = rng.normal(size=(8, 2)).astype(np.float32)
    t2 = np.asarray(grpo_turn_advantage(jnp.asarray(r2), 4))
    for k in range(2):
        np.testing.assert_allclose(
            t2[:, k],
            np.asarray(grpo_group_advantage(jnp.asarray(r2[:, k]), 4)),
            rtol=1e-6, atol=1e-6)


def test_per_turn_terminal_rewards_spikes_and_absent_turns():
    from nanorlhf_tpu.algos import per_turn_terminal_rewards

    adv = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    ends = jnp.asarray([[3, 7], [5, -1]])              # -1 = turn never ran
    dense = np.asarray(per_turn_terminal_rewards(adv, ends, 10))
    want = np.zeros((2, 10), np.float32)
    want[0, 3], want[0, 7], want[1, 5] = 1.0, 2.0, 3.0
    np.testing.assert_allclose(dense, want)            # the -1 column dropped


def test_pooled_executor_warm_reuse_and_timeout_respawn():
    ex = PooledPythonExecutor(timeout=20.0)
    try:
        r1 = ex.run("print('alpha'); answer = 6 * 7")
        assert r1.ok and "alpha" in r1.stdout and r1.answer == "42"
        pid1 = ex.worker_pid
        assert pid1 is not None
        r2 = ex.run("print('beta')")
        assert r2.ok and "beta" in r2.stdout
        assert ex.worker_pid == pid1, "second call must reuse the warm worker"
        r3 = ex.run("raise RuntimeError('boom')")
        assert not r3.ok and "boom" in r3.error
        assert ex.worker_pid == pid1, "a snippet error must not kill the worker"
    finally:
        ex.close()
    assert ex.worker_pid is None


def test_pooled_executor_timeout_reaps_then_respawns():
    ex = PooledPythonExecutor(timeout=1.0)
    try:
        assert ex.run("x = 1").ok
        pid1 = ex.worker_pid
        r = ex.run("import time; time.sleep(60)")
        assert not r.ok and "timeout" in r.error
        assert ex.worker_pid is None, "the wedged worker must be reaped"
        r2 = ex.run("print('back')")
        assert r2.ok and "back" in r2.stdout
        assert ex.worker_pid is not None and ex.worker_pid != pid1
    finally:
        ex.close()


def test_inspect_run_turns_report_from_ledger_alone(tmp_path):
    from nanorlhf_tpu.telemetry import LineageLedger

    led = LineageLedger(str(tmp_path))
    for idx in range(3):
        led.generation(idx, policy_version=0, gen_s=0.1)
        for t in range(1, 3):
            led.turn(idx, step=0, row=idx, turn=t, tool_wall_s=0.25,
                     obs_range=[16, 20] if t == 1 else None,
                     obs_tokens=4 if t == 1 else 0,
                     reward=float(t), tok_range=[0, 16])
    led.close()
    out = subprocess.run(
        [sys.executable, TOOLS, str(tmp_path), "--turns", "--json"],
        capture_output=True, text=True, check=True,
    )
    rep = json.loads(out.stdout)
    assert rep["turns_per_episode"] == 2.0
    assert len(rep["episodes"]) == 3
    for ep in rep["episodes"]:
        assert ep["turns"] == 2
        assert ep["obs_tokens"] == [4, 0]
        assert ep["rewards"] == [1.0, 2.0]
        assert ep["tool_wall_s"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# episode driver over the paged scheduler (tiny model, CPU)
# ---------------------------------------------------------------------------


def _tiny_model():
    tok = ToyTokenizer(vocab_size=256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    return tok, mcfg, params


def _driver_prompts(tok, B, Tp):
    ids = np.full((B, Tp), tok.pad_token_id, np.int32)
    mask = np.zeros((B, Tp), bool)
    for i in range(B):
        e = tok.encode(f"prompt {i} compute the answer now")[:Tp]
        ids[i, Tp - len(e):] = e
        mask[i, Tp - len(e):] = True
    return jnp.asarray(ids), jnp.asarray(mask)


class EchoEnv(PythonToolEnv):
    """PythonToolEnv with the executor swapped for a canned observation —
    driver-mechanics tests don't need a subprocess per turn."""

    def __init__(self, reward_func, max_turns=2, obs_text=" tool says 42 "):
        super().__init__(reward_func, max_turns=max_turns,
                         executor=_NullExecutor(obs_text),
                         extractor=lambda text: PINNED_PROGRAM)


class _NullExecutor:
    def __init__(self, obs_text):
        self.obs_text = obs_text

    def run(self, code):
        from nanorlhf_tpu.rewards.python_executor import ExecutionResult

        return ExecutionResult(ok=True, stdout=self.obs_text)

    def close(self):
        pass


def _run_driver(env, *, faults=None, key=7, B=2, n=2, Tp=8,
                turn_tokens=12, obs_budget=8, resp=40, decode_rows=2,
                greedy=False):
    tok, mcfg, params = _tiny_model()
    ids, mask = _driver_prompts(tok, B, Tp)
    env.eos_token = tok.eos_token
    sampling = SamplingParams(max_tokens=turn_tokens, temperature=1.0, n=n,
                              greedy=greedy)
    try:
        return tok, run_env_episodes(
            params, mcfg, ids, mask, jax.random.PRNGKey(key), sampling, env,
            eos_token_id=tok.eos_token_id, pad_token_id=tok.pad_token_id,
            tokenizer=tok, max_turns=env.max_turns, turn_tokens=turn_tokens,
            obs_budget=obs_budget, response_length=resp, page_size=4,
            decode_rows=decode_rows, faults=faults,
        )
    finally:
        env.close()


def test_driver_two_turns_masked_obs_and_page_recycling():
    tok, out = _run_driver(EchoEnv(text_reward, max_turns=2))
    rows = out["tokens"].shape[0]
    assert rows == 4
    st = out["stats"]
    assert st["env/turns_per_episode"] == 2.0
    assert st["env/obs_tokens"] > 0
    assert st["env/tool_errors"] == 0.0
    # every episode re-admitted exactly once through the mid-loop prefill
    # path, releasing its turn-1 pages first
    assert out["admissions"] == rows
    assert out["pages_recycled"] > 0
    # the loss mask is False EXACTLY on the recorded observation spans
    expected = np.ones_like(out["loss_mask"])
    for rec in out["turns"]:
        if rec["obs_range"]:
            a, b = rec["obs_range"]
            expected[rec["row"], a:b] = False
            assert rec["obs_tokens"] == b - a > 0
    assert np.array_equal(out["loss_mask"], expected)
    assert (~out["loss_mask"]).sum() > 0
    # per-turn bookkeeping: 2 turn records per episode, ends ascending,
    # scores are the summed per-turn rewards
    for ep in range(rows):
        recs = [r for r in out["turns"] if r["row"] == ep]
        assert [r["turn"] for r in recs] == [1, 2]
        e1, e2 = out["turn_ends"][ep]
        assert 0 <= e1 < e2 < out["tokens"].shape[1]
    np.testing.assert_allclose(out["scores"], out["turn_rewards"].sum(1))


def test_driver_greedy_stream_is_schedule_independent():
    """Tool completion order races through thread scheduling; a greedy
    episode stream must not care — each row's logits depend only on its
    own pages, and admission keys are (episode, turn)-derived, so row
    placement and decode-chunk timing never change the tokens."""
    _, a = _run_driver(EchoEnv(text_reward, max_turns=2), greedy=True)
    _, b = _run_driver(EchoEnv(text_reward, max_turns=2), greedy=True)
    assert np.array_equal(a["tokens"], b["tokens"])
    assert np.array_equal(a["loss_mask"], b["loss_mask"])
    assert np.array_equal(a["turn_ends"], b["turn_ends"])


def test_driver_fault_sites_hang_delays_and_crash_degrades():
    faults = FaultInjector(parse_fault_spec(
        "env.hang:at=1,delay=0.3,worker=0 env.crash:at=1,worker=1"))
    tok, out = _run_driver(EchoEnv(text_reward, max_turns=2), faults=faults)
    st = out["stats"]
    # every episode still completes its 2 turns — the crash became an
    # error-text observation, not a dead rollout — and the absorption is
    # counted loudly (the absorbed turn scores 0, so this metric is the
    # only signal distinguishing "tool broke" from "tool scored 0")
    assert st["env/turns_per_episode"] == 2.0
    assert st["env/tool_errors"] == 1.0
    recs = {(r["row"], r["turn"]): r for r in out["turns"]}
    assert recs[(0, 1)]["tool_wall_s"] >= 0.3           # env.hang stalled it
    crash = recs[(1, 1)]
    assert crash["obs_range"] is not None
    a, b = crash["obs_range"]
    assert "InjectedFault" in tok.decode(out["tokens"][1, a:b])


# ---------------------------------------------------------------------------
# trainer end-to-end (2-update GRPO) + the single-turn parity pin
# ---------------------------------------------------------------------------


def _env_trainer(tmp_path, name, **overrides):
    tok = ToyTokenizer(vocab_size=256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    cfg = RLConfig(
        algo=AlgoName.GRPO,
        output_dir=str(tmp_path / name),
        response_length=48,
        temperature=1.0,
        sample_n=2,
        kl_coef=0.0,
        total_episodes=32,                 # batch 1*1*2 × world 8 = 16 → 2 updates
        per_device_train_batch_size=1,
        gradient_accumulation_steps=1,
        num_mini_batches=2,
        num_ppo_epochs=1,
        learning_rate=1e-3,
        logging_steps=1,
        num_printed_samples=0,
        use_lora=False,
        gradient_checkpointing=False,
        mesh=MeshConfig(-1, 1, 1),
        save_steps=0,
        load_best_model_at_end=False,
        report_to="jsonl",
    )
    for k, v in overrides.items():
        setattr(cfg, k, v)
    dataset = load_prompt_dataset("synthetic:64", tok, max_prompt_len=10)
    return RLTrainer(cfg, mcfg, tok, params, dataset, text_reward)


def _read_metrics(run_dir):
    with open(os.path.join(run_dir, "metrics.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_multi_turn_grpo_end_to_end(tmp_path):
    """The ISSUE-15 acceptance run: 2 GRPO updates on the 2-turn
    python-tool env over the paged scheduler."""
    import nanorlhf_tpu.envs.rollout as envroll

    tr = _env_trainer(
        tmp_path, "env_e2e",
        rollout_page_size=4, rollout_decode_rows=2,
        env_name="python_tool", env_max_turns=2,
        env_turn_tokens=16, env_obs_budget=8,
        lineage=True,
    )
    assert isinstance(tr.env, PythonToolEnv) and tr._env_multi_turn
    tr.env.extractor = lambda text: PINNED_PROGRAM

    payloads, batches = [], []
    orig_run = envroll.run_env_episodes
    orig_asm = tr._assemble_batch

    def run_wrap(*a, **k):
        p = orig_run(*a, **k)
        payloads.append(p)
        return p

    def asm_wrap(*a, **k):
        out = orig_asm(*a, **k)
        batches.append(out[0])   # the trainer mutates this dict in place
        return out

    envroll.run_env_episodes = run_wrap
    tr._assemble_batch = asm_wrap
    try:
        state = tr.train()
    finally:
        envroll.run_env_episodes = orig_run
        tr.env.close()
    assert state["global_step"] == 2
    assert len(payloads) == 2 and len(batches) == 2

    # live metric rows: >= 2 turns/episode on every update
    rows = _read_metrics(str(tmp_path / "env_e2e"))
    assert rows
    for row in rows:
        assert row["env/turns_per_episode"] >= 2.0
        assert row["env/obs_tokens"] > 0
        assert 0.0 <= row["env/tool_stall_overlap"] <= 1.0

    for payload, batch in zip(payloads, batches):
        # the driver masked exactly the observation spans...
        expected = np.ones_like(payload["loss_mask"])
        n_obs = 0
        for rec in payload["turns"]:
            if rec["obs_range"]:
                a, b = rec["obs_range"]
                expected[rec["row"], a:b] = False
                n_obs += b - a
        assert n_obs > 0
        assert np.array_equal(payload["loss_mask"], expected)
        # ...and the ASSEMBLED batch carries those same masks through the
        # GRPO keep-1-of-N selection: every batch row is one of the
        # payload's episode masks, so every observation token trains at
        # loss_mask=False
        assert "loss_mask" in batch
        bm = np.asarray(batch["loss_mask"])
        assert (~bm).sum() > 0
        payload_rows = {m.tobytes() for m in payload["loss_mask"]}
        for r in bm:
            assert r.tobytes() in payload_rows

    # turn lineage events join generation events on rollout_index
    events = list(read_ledger(str(tmp_path / "env_e2e")))
    by_index = chains(events)
    turn_evs = [e for e in events if e["type"] == "turn"]
    assert turn_evs
    for ev in turn_evs:
        types = set(by_index[ev["rollout_index"]])   # {type: [events]}
        assert "generation" in types and "reward" in types
    # one turn event per (update, episode row, turn)
    assert len(turn_evs) == sum(len(p["turns"]) for p in payloads)

    # the offline inspector reproduces the live metric from the ledger
    out = subprocess.run(
        [sys.executable, TOOLS, str(tmp_path / "env_e2e"),
         "--turns", "--json"],
        capture_output=True, text=True, check=True,
    )
    rep = json.loads(out.stdout)
    assert rep["turns_per_episode"] >= 2.0
    assert len(rep["episodes"]) == sum(
        p["tokens"].shape[0] for p in payloads)


# wall-clock / throughput keys legitimately differ between two identical
# runs; everything else must match exactly for the parity pin
_TIMEY = re.compile(
    r"(time|_s$|sec|mfu|perf|latency|wall|overlap|^t$|^t_mono$)",
    re.IGNORECASE)


def test_single_turn_env_bit_identical_to_bare_reward_func(tmp_path):
    tr_bare = _env_trainer(tmp_path, "bare", response_length=16)
    s1 = tr_bare.train()
    tr_env = _env_trainer(tmp_path, "env", response_length=16,
                          env_name="single_turn", env_max_turns=1)
    assert isinstance(tr_env.env, SingleTurnEnv)
    assert not tr_env._env_multi_turn
    s2 = tr_env.train()
    assert s1["global_step"] == s2["global_step"] == 2

    rows_bare = _read_metrics(str(tmp_path / "bare"))
    rows_env = _read_metrics(str(tmp_path / "env"))
    assert len(rows_bare) == len(rows_env) > 0
    for a, b in zip(rows_bare, rows_env):
        ka = {k for k in a if not _TIMEY.search(k)}
        kb = {k for k in b if not _TIMEY.search(k)}
        assert ka == kb
        for k in sorted(ka):
            assert a[k] == b[k], f"metric {k!r} diverged: {a[k]} != {b[k]}"


def test_multi_turn_config_validation(tmp_path):
    # multi-turn without the paged scheduler is rejected up front
    with pytest.raises(ValueError, match="rollout_page_size"):
        _env_trainer(tmp_path, "bad_paged",
                     env_name="python_tool", env_max_turns=2,
                     env_turn_tokens=16, env_obs_budget=8)
    # and so is a token budget the episode stream can't hold
    with pytest.raises(ValueError, match="response_length"):
        _env_trainer(tmp_path, "bad_budget",
                     rollout_page_size=4, rollout_decode_rows=2,
                     env_name="python_tool", env_max_turns=2,
                     env_turn_tokens=32, env_obs_budget=8)
