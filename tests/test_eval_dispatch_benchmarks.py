"""Per-benchmark evaluators vs the reference eval script
(`/root/reference/examples/r1-v0/utils/eval/eval_script.py:46-172`)."""

import pytest

from nanorlhf_tpu.rewards.eval_dispatch import (
    eval_agieval_gaokao_math_cloze,
    eval_agieval_gaokao_mathqa,
    eval_last_single_answer,
    eval_math,
    eval_math_sat,
    eval_minif2f_isabelle,
    eval_mmlu_stem,
    eval_ocwcourses,
    get_evaluator,
    is_correct_item,
)


class TestEvalMath:
    def test_dedups_gold_and_truncates_pred(self):
        # gold repeats; model boxed a stray value before the real answers
        assert eval_math(["7", "2", "3"], ["2", "3", "3"])

    def test_order_free_multi_answer(self):
        assert eval_math(["3", "2"], ["2", "3"])

    def test_missing_part_fails(self):
        assert not eval_math(["2"], ["2", "3"])

    def test_scalar_pred_promoted(self):
        assert eval_math("4", ["4"])


class TestGaokaoCloze:
    def test_bracket_aware_split(self):
        # the ',' inside (1,2) must NOT split; the ';' must
        assert eval_agieval_gaokao_math_cloze(["(1,2); 5"], ["(1,2)", "5"])

    def test_order_matters(self):
        assert not eval_agieval_gaokao_math_cloze(["5; (1,2)"], ["(1,2)", "5"])

    def test_keeps_last_n_parts(self):
        assert eval_agieval_gaokao_math_cloze(["9; 1; 2"], ["1", "2"])

    def test_scalar_answer_wraps(self):
        # len() on a raw string would count characters and zero-score it
        assert eval_agieval_gaokao_math_cloze("12", "12")


class TestGaokaoMathQA:
    def test_latest_first_occurrence_wins(self):
        # 'B' first occurs after 'A' first occurs → B is the chosen tag
        assert eval_agieval_gaokao_mathqa(["A is wrong, B is right"], "B")

    def test_single_letter(self):
        assert eval_agieval_gaokao_mathqa(["C"], "C")

    def test_no_letter_fails(self):
        assert not eval_agieval_gaokao_mathqa(["no idea"], "A")


class TestChoiceLetters:
    def test_sat_case_insensitive(self):
        assert eval_math_sat("b", "B")
        assert not eval_math_sat("A", "B")

    def test_mmlu_is_sat(self):
        assert eval_mmlu_stem is eval_math_sat

    def test_sat_coerces_list_to_last_element(self):
        # extractors return lists; a mislabeled row must score, not crash
        assert eval_math_sat(["C", "A"], "a")
        assert not eval_math_sat([], "A")


class TestOCW:
    def test_numeric_with_units(self):
        assert eval_ocwcourses("3.0 m/s", "3")

    def test_numeric_one_percent_threshold(self):
        assert eval_ocwcourses("100.0000001", "100")
        assert not eval_ocwcourses("102", "100")

    def test_exact_zero_and_negative_grade_correct(self):
        # the reference's mean-relative carve-out grades these False
        assert eval_ocwcourses("0", "0")
        assert eval_ocwcourses("-5", "-5")
        assert eval_ocwcourses("-5.00000001", "-5")

    def test_scientific_notation(self):
        assert eval_ocwcourses("3 \\times 10^{4}", "30000")

    def test_equation_equivalence(self):
        assert eval_ocwcourses("y = x + 1", "y = 1 + x")
        assert not eval_ocwcourses("y = x + 2", "y = x + 1")

    def test_expression_equivalence(self):
        assert eval_ocwcourses("\\frac{1}{2}", "0.5")

    def test_empty_pred_fails(self):
        assert not eval_ocwcourses("", "3")


def test_minif2f_always_true():
    assert eval_minif2f_isabelle("anything", "placeholder")


def test_gsm_scalar_and_list_coercion():
    assert eval_last_single_answer("72", "72")
    assert eval_last_single_answer(["8", "72"], "72")  # last element wins


def test_registry_dispatch_and_fallback():
    assert get_evaluator("MATH-COT") is eval_math
    assert get_evaluator("ocw") is eval_ocwcourses
    assert get_evaluator("unknown-benchmark") is is_correct_item
