"""Python executor sandbox + answer extraction dispatcher."""

import pytest

from nanorlhf_tpu.rewards.answer_extraction import (
    extract_after_marker,
    extract_answer,
    extract_last_number,
)
from nanorlhf_tpu.rewards.python_executor import PythonExecutor


class TestExecutor:
    def test_answer_variable(self):
        r = PythonExecutor(timeout=3).run("x = 6\nanswer = x * 7")
        assert r.ok and r.answer == "42"

    def test_stdout_captured(self):
        r = PythonExecutor(timeout=3).run("print('hello')\nanswer = 1")
        assert r.ok and "hello" in r.stdout

    def test_answer_expr(self):
        r = PythonExecutor(timeout=3, answer_expr="y + 1").run("y = 9")
        assert r.ok and r.answer == "10"

    def test_error_reported(self):
        r = PythonExecutor(timeout=3).run("1/0")
        assert not r.ok and "ZeroDivisionError" in r.error

    def test_infinite_loop_times_out(self):
        r = PythonExecutor(timeout=0.5).run("while True: pass")
        assert not r.ok and "timeout" in r.error

    def test_model_code_cannot_kill_parent(self):
        r = PythonExecutor(timeout=2).run("import os; os._exit(3)")
        assert not r.ok  # child died; parent unaffected (we're still here)


class TestExtraction:
    def test_marker(self):
        assert extract_after_marker("blah blah The answer is: 42") == "42"
        assert extract_after_marker("So the final answer is 7.") == "7"
        assert extract_after_marker("no marker here") == ""

    def test_marker_stops_at_sentence(self):
        assert extract_after_marker("The answer is 5. And more text") == "5"

    def test_last_number(self):
        assert extract_last_number("first 3 then 4,000 end") == "4000"
        assert extract_last_number("none") == ""

    @pytest.mark.parametrize(
        "text,want",
        [
            (r"reasoning \boxed{9}", "9"),                   # boxed wins
            ("The answer is: 13", "13"),                     # marker next
            ("it is about 7 or maybe 8", "8"),               # last number
        ],
    )
    def test_auto_dispatch(self, text, want):
        assert extract_answer(text) == want
