"""Elastic rollout fleet (nanorlhf_tpu/orchestrator/fleet.py,
docs/FLEET.md) — the worker-level fault matrix:

- coordinator units (fake dispatch, jax-free): leases grant contiguous
  index ranges under the staleness gate, samples enter the queue in index
  order no matter which worker finishes first, a crashed worker's lease is
  reassigned with the SAME cached prompt batches, consecutive failures
  quarantine with jittered backoff, an expired lease is speculatively
  re-dispatched with late duplicates dropped, membership is elastic, and
  losing every worker surfaces FleetExhausted instead of deadlocking;
- satellite units: jittered exponential backoff bounds/determinism,
  `VersionedWeightStore.wait_for_version`, worker-scoped fault-spec
  grammar, the multi-producer OverlapMeter watermark;
- trainer integration (8-device CPU mesh): killing a worker mid-lease at
  staleness 0 yields rows bit-identical to the synchronous trainer with
  `fleet/reassigned_leases >= 1`; losing ALL workers rides the watchdog
  into the synchronous degraded mode and the run still completes; fleet
  state survives checkpoint/resume and SIGTERM preemption; workers join
  mid-run.
"""

import json
import os
import random
import signal
import threading
import time

import numpy as np
import pytest

from nanorlhf_tpu.orchestrator import (
    BoundedStalenessQueue,
    FleetConfig,
    FleetCoordinator,
    FleetExhausted,
    FleetOrchestrator,
    OverlapMeter,
    ProducerFailed,
    VersionedWeightStore,
)
from nanorlhf_tpu.resilience import FaultInjector, backoff_delay, parse_fault_spec
from nanorlhf_tpu.trainer import AlgoName

from test_trainer_smoke import make_trainer

STREAM_KEYS = ("eval_objective/scores_old", "objective/entropy_old",
               "objective/kl_rollout_old")


def _metric_rows(outdir):
    rows = []
    with open(outdir / "metrics.jsonl") as f:
        for line in f:
            row = json.loads(line)
            if "episode" in row:
                rows.append(row)
    return rows


def _fleet(n_workers=2, max_staleness=2, dispatch=None, faults=None,
           n_batches=1000, transport="inprocess", **fleet_kw):
    """FleetOrchestrator over a fake dispatch (no jax, no model).
    transport="rpc" routes every lease/completion/heartbeat/weight fetch
    through the loopback FleetRpcServer — same coordinator, same worker
    loop, the real wire in between."""
    batches = iter(range(n_batches))
    if dispatch is None:
        def dispatch(index, queries, tree, worker_id):
            time.sleep(0.005)
            return {"index": index, "queries": queries, "worker": worker_id}
    fleet_kw.setdefault("poll_interval", 0.02)
    return FleetOrchestrator(
        dispatch_fn=dispatch, batch_fn=lambda: next(batches),
        initial_params={}, n_workers=n_workers, max_staleness=max_staleness,
        faults=faults, fleet=FleetConfig(**fleet_kw), transport=transport,
    )


# ---------------------------------------------------------------------------
# satellite units
# ---------------------------------------------------------------------------


def test_backoff_jitter_bounds_and_determinism():
    # jitter=0 keeps the exact exponential schedule
    assert backoff_delay(0, 0.5, 30.0) == 0.5
    assert backoff_delay(3, 0.5, 30.0) == 4.0
    assert backoff_delay(10, 0.5, 30.0) == 30.0  # capped
    # jittered draws stay inside ±25% (and under the cap), and a seeded rng
    # makes the schedule reproducible
    rng = random.Random(0)
    draws = [backoff_delay(2, 0.5, 30.0, jitter=0.25, rng=rng)
             for _ in range(100)]
    assert all(2.0 * 0.75 <= d <= 2.0 * 1.25 for d in draws)
    assert len(set(round(d, 9) for d in draws)) > 1  # actually spread
    rng2 = random.Random(0)
    assert draws == [backoff_delay(2, 0.5, 30.0, jitter=0.25, rng=rng2)
                     for _ in range(100)]
    # the cap binds post-jitter too
    assert all(
        backoff_delay(20, 0.5, 30.0, jitter=0.25, rng=rng) <= 30.0
        for _ in range(50)
    )


def test_wait_for_version_blocks_until_publish():
    """A worker that joins before publish-0 blocks instead of crash-looping
    through its failure budget."""
    store = VersionedWeightStore()
    with pytest.raises(RuntimeError, match="no weights published"):
        store.latest()
    with pytest.raises(TimeoutError, match="no weight version"):
        store.wait_for_version(0, timeout=0.05)
    got = {}

    def waiter():
        got["vt"] = store.wait_for_version(1, timeout=5.0)

    t = threading.Thread(target=waiter)
    t.start()
    store.publish({"v": 0})   # version 0: below min_version → keeps waiting
    time.sleep(0.05)
    assert "vt" not in got
    store.publish({"v": 1})   # version 1: releases the waiter
    t.join(timeout=5.0)
    assert got["vt"] == (1, {"v": 1})
    # stop event aborts the wait
    stop = threading.Event()
    stop.set()
    with pytest.raises(TimeoutError, match="stopped"):
        store.wait_for_version(99, timeout=5.0, stop=stop)


def test_fault_spec_worker_selector_and_action_defaults():
    # worker.* points parse, and hang/slow default to their natural actions
    scheds = parse_fault_spec(
        "worker.crash:at=1,worker=0 worker.hang:at=1 "
        "worker.slow:every=2,worker=1,delay=0.25"
    )
    assert [s.point for s in scheds] == ["worker.crash", "worker.hang",
                                         "worker.slow"]
    assert scheds[0].worker == 0 and scheds[0].action == "raise"
    assert scheds[1].action == "hang"
    assert scheds[2].action == "delay" and scheds[2].delay == 0.25
    # the worker selector gates both firing AND the call counter: worker 1's
    # calls never advance a worker=0 schedule
    inj = FaultInjector(parse_fault_spec("worker.crash:at=1,worker=0"))
    assert inj.fire("worker.crash", worker=1) is None
    assert inj.fire("worker.crash", worker=1) is None
    from nanorlhf_tpu.resilience import InjectedFault

    with pytest.raises(InjectedFault, match="worker 0"):
        inj.fire("worker.crash", worker=0)
    # delay actions carry their parameter through fire()
    inj2 = FaultInjector(parse_fault_spec("worker.slow:every=1,delay=0.5"))
    assert inj2.fire("worker.slow", worker=3) == "delay:0.5"


def test_overlap_meter_multiproducer_compaction_exact():
    """N concurrent generation tracks: compaction must fold exactly — the
    old single-track watermark (last APPENDED interval's end) is not a
    lower bound on future starts once producers interleave."""
    compact = OverlapMeter()
    compact._COMPACT_AT = 16
    plain = OverlapMeter()
    rng = np.random.default_rng(0)
    # 3 workers with per-worker chronological windows, interleaved arrivals
    starts = [0.0, 0.33, 0.66]
    events = []
    for w, t in enumerate(starts):
        for _ in range(300):
            g1 = t + 0.5 + rng.random()
            events.append((t, g1, w))
            t = g1 + 0.05 * rng.random()
    rng.shuffle(events)
    # consumer busy windows on their own chronological track
    t, busy = 0.0, []
    for _ in range(300):
        b1 = t + 0.4 + rng.random()
        busy.append((t, b1))
        t = b1 + 0.1
    # interleave arrivals the way racing threads would: sorted by END time
    # (a worker reports when its sample is ready), which still appends
    # overlapping starts out of order across tracks
    for (g0, g1, w), (b0, b1) in zip(sorted(events, key=lambda e: e[1]),
                                     busy * 3):
        for m in (compact, plain):
            m.note_gen(g0, g1, track=w)
            m.note_busy(b0, b1)
    assert compact.overlap_fraction() == pytest.approx(
        plain.overlap_fraction(), rel=1e-9
    )
    assert len(compact._gen) + len(compact._busy) < 600  # actually folded
    # a retired track stops pinning the watermark
    m = OverlapMeter()
    m.note_gen(0.0, 1.0, track=7)
    m.retire_gen_track(7)
    assert 7 not in m._gen_ends


# ---------------------------------------------------------------------------
# coordinator units (fake dispatch — no jax, no model)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("transport", ["inprocess", "rpc"])
def test_fleet_grants_in_order_and_respects_staleness_gate(transport):
    """Workers race, samples may finish out of order, but consumption is
    strictly index-ordered and never beyond the staleness bound — over
    direct calls AND over the loopback RPC wire (ISSUE-11 acceptance: the
    reorder-buffer test generalizes unchanged)."""
    rng = np.random.default_rng(1)

    def dispatch(index, queries, tree, worker_id):
        time.sleep(0.002 + 0.01 * rng.random())  # jittered finish order
        return {"index": index, "worker": worker_id}

    orch = _fleet(n_workers=3, max_staleness=2, dispatch=dispatch,
                  transport=transport)
    try:
        seen, staleness = [], []
        for step in range(10):
            s = orch.get()
            seen.append(s.index)
            staleness.append(orch.version - s.version)
            orch.publish({})
        assert seen == list(range(10))
        assert all(st <= 2 for st in staleness), staleness
        fs = orch.fleet_stats()
        assert fs["leases_granted"] >= 10
        assert fs["workers"] == 3.0
    finally:
        orch.close()


@pytest.mark.parametrize("transport", ["inprocess", "rpc"])
def test_worker_crash_reassigns_lease_with_same_batches(transport):
    """worker 0 dies on its first dispatch: its lease moves to worker 1
    carrying the SAME cached prompt batch (the data cursor is never
    re-burned), the index stream stays gapless, and the fleet counts the
    loss + reassignment — identically over the loopback RPC transport
    (the lease's cached batches round-trip through the wire codec)."""
    dispatched = []  # (index, queries, worker)

    def dispatch(index, queries, tree, worker_id):
        dispatched.append((index, queries, worker_id))
        time.sleep(0.005)
        return {"index": index}

    faults = FaultInjector.from_spec("worker.crash:at=1,worker=0")
    orch = _fleet(n_workers=2, max_staleness=0, dispatch=dispatch,
                  faults=faults, transport=transport)
    try:
        seen = []
        for step in range(4):
            s = orch.get()
            seen.append(s.index)
            orch.publish({})
        assert seen == [0, 1, 2, 3]
        fs = orch.fleet_stats()
        assert fs["reassigned_leases"] >= 1
        assert fs["worker_losses"] == 1 and fs["workers"] == 1.0
        # every index was generated from the batch drawn for it at grant
        # time — index i always carries batch i even across reassignment
        # (the fake batch_fn yields 0,1,2,...)
        for idx, queries, _ in dispatched:
            assert queries == idx
        # worker 0 delivered nothing (it died before its first complete)
        assert all(w == 1 for _, _, w in dispatched)
    finally:
        orch.close()


def test_consecutive_failures_quarantine_with_backoff():
    faults = FaultInjector.from_spec(
        "worker.fetch_weights:every=1,worker=1,count=6"
    )
    orch = _fleet(n_workers=2, max_staleness=1, faults=faults,
                  failure_budget=1, quarantine_base=0.2, quarantine_max=1.0)
    try:
        for step in range(6):
            orch.get()
            orch.publish({})
        fs = orch.fleet_stats()
        assert fs["quarantines"] >= 1
        assert fs["worker_failures"] >= 2
        assert fs["workers"] == 2.0  # quarantined, not lost
    finally:
        orch.close()


def test_straggler_lease_expires_and_is_speculatively_redispatched():
    """worker 0 sleeps far past the EWMA-derived deadline on every
    dispatch: its leases expire, the work is re-dispatched, the stream
    stays complete and in order."""
    faults = FaultInjector.from_spec("worker.slow:every=1,worker=0,delay=1.5")
    orch = _fleet(n_workers=2, max_staleness=2, faults=faults,
                  straggler_factor=3.0, initial_deadline_s=0.4)
    try:
        seen = []
        for step in range(6):
            s = orch.get()
            seen.append(s.index)
            orch.publish({})
        assert seen == list(range(6))
        fs = orch.fleet_stats()
        assert fs["expired_leases"] >= 1
        assert fs["speculative_dispatches"] >= 1
    finally:
        orch.close()


def test_hang_mid_lease_revoked_by_deadline():
    """worker.hang holds the lease without progress; the deadline sweep
    revokes it (waking the hung worker's revocation poll) and the lease is
    completed elsewhere."""
    faults = FaultInjector.from_spec("worker.hang:at=1,worker=0")
    orch = _fleet(n_workers=2, max_staleness=1, faults=faults,
                  straggler_factor=3.0, initial_deadline_s=0.3)
    try:
        seen = []
        for step in range(3):
            seen.append(orch.get().index)
            orch.publish({})
        assert seen == [0, 1, 2]
        assert orch.fleet_stats()["expired_leases"] >= 1
    finally:
        orch.close()


def test_all_workers_lost_raises_fleet_exhausted():
    faults = FaultInjector.from_spec("worker.crash:every=1")
    orch = _fleet(n_workers=2, max_staleness=1, faults=faults)
    try:
        with pytest.raises(ProducerFailed) as ei:
            orch.get()
        # the terminal cause names the fleet exhaustion
        assert isinstance(ei.value, FleetExhausted) or isinstance(
            ei.value.__cause__, FleetExhausted
        )
        assert not orch.producer_alive()
    finally:
        orch.close()


def test_elastic_join_and_leave():
    orch = _fleet(n_workers=1, max_staleness=2)
    try:
        orch.get()
        orch.publish({})
        new_id = orch.add_worker()
        seen_workers = set()
        for step in range(8):
            s = orch.get()
            seen_workers.add(s.payload["worker"])
            orch.publish({})
        assert new_id in seen_workers  # the joiner really took leases
        assert orch.fleet_stats()["worker_joins"] == 2
        orch.remove_worker(new_id)
        for step in range(2):  # survives the scale-down
            orch.get()
            orch.publish({})
        assert orch.fleet_stats()["workers"] == 1.0
    finally:
        orch.close()


def test_coordinator_journal_and_restore_counters():
    q = BoundedStalenessQueue(max_staleness=1)
    coord = FleetCoordinator(queue=q, batch_fn=None)
    coord.counters["reassigned_leases"] = 3
    coord.counters["quarantines"] = 2
    j = json.loads(json.dumps(coord.journal()))  # must be JSON-able
    assert j["counters"]["reassigned_leases"] == 3
    fresh = FleetCoordinator(queue=BoundedStalenessQueue(1), batch_fn=None)
    fresh.restore_counters(j)
    assert fresh.counters["reassigned_leases"] == 3
    assert fresh.counters["quarantines"] == 2
    # a fresh fleet's orchestrator journal nests the queue journal
    orch = _fleet(n_workers=1)
    try:
        orch.get()
        full = orch.journal()
        assert {"pending", "version", "dropped"} <= set(full)
        assert "counters" in full["fleet"]
    finally:
        orch.close()


def test_split_worker_groups():
    from test_disaggregate import FakeDev
    from nanorlhf_tpu.parallel.mesh import split_worker_groups

    devs = [FakeDev(i) for i in range(8)]
    groups = split_worker_groups(devs, 2)
    assert [[d.id for d in g] for g in groups] == [[0, 1, 2, 3], [4, 5, 6, 7]]
    with pytest.raises(ValueError, match="not divisible"):
        split_worker_groups(devs, 3)
    # a per-worker group straddling a slice boundary is warned
    sliced = [FakeDev(i, slice_index=i // 4) for i in range(8)]
    with pytest.warns(RuntimeWarning, match="ride DCN"):
        # 8 devices / 1 worker → the single group spans both slices
        split_worker_groups(sliced, 1)
    # slice-aligned groups don't warn
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        groups = split_worker_groups(sliced, 2)
    assert [{d.slice_index for d in g} for g in groups] == [{0}, {1}]


# ---------------------------------------------------------------------------
# trainer integration (8-device CPU mesh)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def serial_rows(tmp_path_factory):
    """One synchronous 3-update GRPO run — the bit-parity reference shared
    by the fault-matrix tests below."""
    tmp = tmp_path_factory.mktemp("serial")
    tr = make_trainer(AlgoName.GRPO, tmp, total_episodes=48, save_steps=0)
    tr.train()
    tr.close()
    return _metric_rows(tmp / "grpo")


@pytest.mark.parametrize("transport", ["inprocess", "rpc"])
def test_worker_crash_mid_lease_bit_identical_stream(tmp_path, serial_rows,
                                                     transport):
    """ISSUE-6 acceptance: 2 workers at staleness 0, worker 0 crashes on
    its first lease — the token stream and loss trajectory match the
    synchronous trainer (reassignment replays the same cached batch under
    the same index-keyed PRNG), and fleet/reassigned_leases >= 1.
    ISSUE-11 extends the same acceptance over the loopback RPC transport:
    leases, completions, and weights cross the wire codec and the streams
    must still be bit-identical."""
    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=48,
                      save_steps=0, rollout_orchestrator=True,
                      rollout_workers=2, max_staleness=0,
                      rollout_transport=transport,
                      fault_spec="worker.crash:at=1,worker=0")
    tr.train()
    tr.close()
    rows = _metric_rows(tmp_path / "grpo")
    assert len(rows) == len(serial_rows) == 3
    for a, b in zip(serial_rows, rows):
        for key in STREAM_KEYS + ("loss/policy_avg_new",):
            np.testing.assert_allclose(
                a[key], b[key], rtol=1e-5,
                err_msg=f"{key} diverged after worker crash + reassignment",
            )
    last = rows[-1]
    assert last["fleet/reassigned_leases"] >= 1.0
    assert last["fleet/worker_losses"] == 1.0
    assert last["fleet/workers"] == 1.0
    assert last["resilience/degraded_mode"] == 0.0  # fleet stayed up


def test_fleet_staleness0_no_fault_matches_synchronous(tmp_path,
                                                       serial_rows):
    """No-fault parity: the fleet machinery itself (leases, reorder buffer,
    round-robin workers) is invisible at staleness 0."""
    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=48,
                      save_steps=0, rollout_orchestrator=True,
                      rollout_workers=2, max_staleness=0)
    tr.train()
    tr.close()
    rows = _metric_rows(tmp_path / "grpo")
    for a, b in zip(serial_rows, rows):
        for key in STREAM_KEYS + ("loss/policy_avg_new",):
            np.testing.assert_allclose(a[key], b[key], rtol=1e-5,
                                       err_msg=key)
    assert rows[-1]["fleet/worker_failures"] == 0.0


def test_all_workers_lost_degrades_to_sync(tmp_path, serial_rows):
    """ISSUE-6 acceptance: every worker dies on every dispatch — the
    watchdog restarts the fleet, exhausts its budget, and the run completes
    on synchronous rollouts with the serial trainer's streams (no
    deadlock)."""
    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=48,
                      save_steps=0, rollout_orchestrator=True,
                      rollout_workers=2, max_staleness=1,
                      producer_restart_budget=1,
                      producer_backoff_base=0.01,
                      producer_backoff_max=0.05,
                      fault_spec="worker.crash:every=1")
    state = tr.train()
    assert state["global_step"] == 3
    assert tr.watchdog.degraded
    assert tr.watchdog.restarts_total == 1
    tr.close()
    rows = _metric_rows(tmp_path / "grpo")
    assert rows[-1]["resilience/degraded_mode"] == 1.0
    for a, b in zip(serial_rows, rows):
        for key in STREAM_KEYS:
            np.testing.assert_allclose(a[key], b[key], rtol=1e-5,
                                       err_msg=key)


def test_fleet_checkpoint_resume_identical_streams(tmp_path):
    """Fleet cursor + counters survive checkpoint/restore: 2 updates +
    resume + 1 matches a straight 3-update fleet run at staleness 0, and
    the journaled fleet counters ride into the resumed run."""
    kw = dict(total_episodes=48, rollout_orchestrator=True,
              rollout_workers=2, max_staleness=0)
    full = make_trainer(AlgoName.GRPO, tmp_path / "full", **kw)
    full.train()
    full.close()

    half = make_trainer(AlgoName.GRPO, tmp_path / "half", **kw)
    half.train(num_updates=2)
    tstate = half.ckpt.load_trainer_state(2)
    assert "fleet" in tstate["orchestrator"]
    journaled = tstate["orchestrator"]["fleet"]["counters"]["leases_granted"]
    # the journal snapshot was taken mid-step-2; the warm pipeline may have
    # granted another lease since, so compare with <=, not ==
    assert 2 <= journaled <= half._orchestrator.fleet_stats()["leases_granted"]
    half.close()

    res = make_trainer(AlgoName.GRPO, tmp_path / "half", **kw)
    res.resume_from_checkpoint()
    res.train()
    # cumulative counters continued from the journal, not from zero
    assert res._orchestrator.fleet_stats()["leases_granted"] > journaled
    res.close()

    a = _metric_rows(tmp_path / "full" / "grpo")[-1]
    b = _metric_rows(tmp_path / "half" / "grpo")[-1]
    assert a["episode"] == b["episode"]
    for key in STREAM_KEYS + ("loss/policy_avg_new",):
        np.testing.assert_allclose(a[key], b[key], rtol=1e-4, err_msg=key)


def test_fleet_sigterm_emergency_checkpoint_resumes(tmp_path, serial_rows):
    """SIGTERM mid-run with the fleet up: emergency checkpoint commits
    (fleet journal included), the resumed run reproduces the uninterrupted
    streams — the fleet cursor state is exactly restored."""
    import test_trainer_smoke as smoke

    kw = dict(total_episodes=48, save_steps=0, rollout_orchestrator=True,
              rollout_workers=2, max_staleness=0)
    calls = {"n": 0}

    def sigterm_reward(pmt_and_responses, eos_token):
        calls["n"] += 1
        if calls["n"] == 2:
            os.kill(os.getpid(), signal.SIGTERM)
        return smoke.rule_reward(pmt_and_responses, eos_token)

    from nanorlhf_tpu.resilience import Preempted

    half = make_trainer(AlgoName.GRPO, tmp_path, **kw)
    if not half._preemption.installed:  # non-main-thread runner
        half.close()
        pytest.skip("SIGTERM handler needs the main thread")
    half.reward_func = sigterm_reward
    with pytest.raises(Preempted, match="emergency checkpoint"):
        half.train()
    assert half.ckpt.latest_step() == 2
    tstate = half.ckpt.load_trainer_state(2)
    assert "fleet" in tstate["orchestrator"]
    half.close()

    res = make_trainer(AlgoName.GRPO, tmp_path, **kw)
    res.resume_from_checkpoint()
    assert res.state["global_step"] == 2
    res.train()
    res.close()

    rows = _metric_rows(tmp_path / "grpo")
    assert len(rows) == 3
    for key in STREAM_KEYS + ("loss/policy_avg_new",):
        np.testing.assert_allclose(serial_rows[-1][key], rows[-1][key],
                                   rtol=1e-4, err_msg=key)


def test_fleet_worker_joins_mid_run(tmp_path):
    """Elastic membership through the trainer: a worker added between
    train() calls (the pipeline stays warm across them) shows up in the
    fleet/* rows."""
    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=48,
                      save_steps=0, rollout_orchestrator=True,
                      rollout_workers=2, max_staleness=1)
    tr.train(num_updates=1)
    tr._orchestrator.add_worker()
    tr.train(num_updates=2)
    rows = _metric_rows(tmp_path / "grpo")
    assert rows[-1]["fleet/worker_joins"] == 3.0
    assert rows[-1]["fleet/workers"] == 3.0
    tr.close()


def test_fleet_requires_orchestrator(tmp_path):
    with pytest.raises(ValueError, match="rollout_orchestrator"):
        make_trainer(AlgoName.GRPO, tmp_path, rollout_workers=2)


def test_fleet_per_worker_meshes_disaggregated(tmp_path):
    """Fleet × disaggregation: the reserved rollout device group is split
    into disjoint per-worker generation meshes, and the run trains."""
    from test_disaggregate import make_trainer as make_disagg

    tr = make_disagg(tmp_path, rollout_orchestrator=True, rollout_workers=2,
                     max_staleness=2, sampler_logprob_capture=True)
    assert tr.worker_meshes is not None and len(tr.worker_meshes) == 2
    ids = [
        {d.id for d in np.asarray(m.devices).ravel()}
        for m in tr.worker_meshes
    ]
    assert ids[0].isdisjoint(ids[1]) and len(ids[0]) == len(ids[1]) == 2
    # both worker groups sit inside the reserved rollout group
    roll_ids = {d.id for d in np.asarray(tr.rollout_mesh.devices).ravel()}
    assert (ids[0] | ids[1]) == roll_ids
    state = tr.train(num_updates=2)
    assert state["global_step"] == 2
    assert tr._orchestrator.fleet_stats()["workers"] == 2.0
    tr.close()
