"""Network FleetTransport (nanorlhf_tpu/orchestrator/rpc.py,
docs/FLEET.md §multi-host) — the ISSUE-11 fault matrix over loopback:

- wire units (jax-free): codec round-trips scalars/containers/ndarrays
  bit-identically, framing detects torn/corrupt frames by length+checksum,
  the net.* fault-site grammar parses with the worker/at/every selectors;
- fencing: a partitioned worker's late completion after lease expiry +
  re-dispatch is REJECTED by epoch comparison with a
  `fleet_late_duplicate {"fenced": true}` ledger drop, while the
  re-dispatched result is bit-identical to the no-fault run;
- weight streaming: `fetch_weights` round-trips a mixed-dtype param tree
  over the wire with zero disk writes and bit-identical leaves, and the
  client's version cache short-circuits unchanged policies;
- fault matrix: drop / duplicate / tear / delay / partition injected into
  the framing leave the consumed sample stream bit-identical to the
  no-fault run (retry/backoff + reconnect + seq/offset dedup absorb them);
- reconnect: a torn connection re-handshakes (worker id, last epoch, last
  weight version) and the transport counters surface through
  `FleetCoordinator.stats()` / `snapshot()` into /statusz;
- health plane: `rpc_error_rate` + `heartbeat_miss_rate` windowed-rate
  rules are wired over the fleet/rpc_* counter rows.
"""

import builtins
import socket
import threading
import time

import numpy as np
import pytest

from nanorlhf_tpu.orchestrator import (
    BoundedStalenessQueue,
    FleetConfig,
    FleetCoordinator,
    FleetOrchestrator,
    QueuedSample,
    VersionedWeightStore,
)
from nanorlhf_tpu.orchestrator import rpc
from nanorlhf_tpu.resilience import FaultInjector, parse_fault_spec

CFG = rpc.RpcConfig(poll_interval=0.02, call_timeout=5.0,
                    backoff_base=0.02, backoff_max=0.2)


class _Ledger:
    """Minimal lineage double recording lease/drop events."""

    enabled = True

    def __init__(self):
        self.events = []

    def lease(self, index, **kw):
        self.events.append(("lease", index, kw))

    def drop(self, index, reason, **kw):
        self.events.append(("drop", index, reason, kw))


def _coordinator(lineage=None, clock=None, **fleet_kw):
    q = BoundedStalenessQueue(100, "wait", start_index=0)
    batches = iter(range(10000))
    fleet_kw.setdefault("poll_interval", 0.02)
    kw = {"lineage": lineage}
    if clock is not None:
        kw["clock"] = clock
    coord = FleetCoordinator(
        q, lambda: np.asarray([next(batches)]),
        config=FleetConfig(**fleet_kw), **kw,
    )
    return coord, q


# ---------------------------------------------------------------------------
# wire units
# ---------------------------------------------------------------------------


def test_codec_roundtrip_bit_identical():
    obj = {
        "none": None, "t": True, "f": False, "i": -42, "big": 2 ** 100,
        "neg_big": -(2 ** 77), "d": 3.141592653589793, "s": "εποχή",
        "b": b"\x00\xff", "l": [1, [2, 3]], "tup": (4, "x"),
        "f32": np.arange(12, dtype=np.float32).reshape(3, 4),
        "i64": np.asarray([[1, -2], [3, 4]], dtype=np.int64),
        "u8": np.asarray([255, 0], dtype=np.uint8),
        "scalar0d": np.asarray(7.5, dtype=np.float64),
    }
    dec = rpc.loads(rpc.dumps(obj))
    assert dec["none"] is None and dec["t"] is True and dec["f"] is False
    assert dec["i"] == -42 and dec["big"] == 2 ** 100
    assert dec["neg_big"] == -(2 ** 77)
    assert dec["d"] == obj["d"] and dec["s"] == obj["s"]
    assert dec["b"] == obj["b"]
    assert dec["l"] == [1, [2, 3]] and dec["tup"] == (4, "x")
    for k in ("f32", "i64", "u8", "scalar0d"):
        np.testing.assert_array_equal(dec[k], obj[k])
        assert dec[k].dtype == obj[k].dtype
    # numpy scalars degrade to python scalars (never silently mis-typed)
    assert rpc.loads(rpc.dumps(np.float32(1.5))) == 1.5
    with pytest.raises(TypeError, match="cannot encode"):
        rpc.dumps(object())


def test_framing_detects_torn_and_corrupt_frames():
    a, b = socket.socketpair()
    try:
        rpc.send_frame(a, rpc.dumps({"x": 1}))
        kind, payload = rpc.recv_frame(b)
        assert kind == rpc.KIND_OBJ and rpc.loads(payload) == {"x": 1}
        # corrupt payload bytes behind a valid header -> checksum mismatch
        good = rpc.dumps({"x": 2})
        frame = rpc._HEADER.pack(
            rpc._MAGIC, rpc.KIND_OBJ, len(good),
            __import__("zlib").crc32(good) & 0xFFFFFFFF,
        ) + good[:-1] + b"\x00"
        a.sendall(frame)
        with pytest.raises(rpc.TornFrame, match="checksum"):
            rpc.recv_frame(b)
        # header promising more bytes than ever arrive -> torn mid-frame
        a.sendall(rpc._HEADER.pack(rpc._MAGIC, rpc.KIND_OBJ, 100, 0) + b"hi")
        a.close()
        with pytest.raises(rpc.TornFrame, match="mid-frame"):
            rpc.recv_frame(b)
    finally:
        for s in (a, b):
            try:
                s.close()
            except OSError:
                pass
    # clean EOF at a frame boundary is a different, clean signal
    c, d = socket.socketpair()
    c.close()
    with pytest.raises(rpc.ConnectionClosed):
        rpc.recv_frame(d)
    d.close()


def test_net_fault_spec_grammar():
    scheds = parse_fault_spec(
        "net.drop:at=1,worker=0 net.delay:every=2,delay=0.1 "
        "net.partition:at=1,delay=0.5 net.duplicate:every=3 net.tear:at=2"
    )
    assert [s.point for s in scheds] == [
        "net.drop", "net.delay", "net.partition", "net.duplicate", "net.tear"
    ]
    # each net site defaults to its matching action
    assert [s.action for s in scheds] == [
        "drop", "delay", "partition", "duplicate", "tear"
    ]
    assert scheds[0].worker == 0
    # partition carries its duration through fire(), like delay
    inj = FaultInjector(parse_fault_spec("net.partition:every=1,delay=0.5"))
    assert inj.fire("net.partition", worker=1) == "partition:0.5"


# ---------------------------------------------------------------------------
# loopback server/client
# ---------------------------------------------------------------------------


def test_fetch_weights_round_trips_bit_identical_with_zero_disk_writes(
        monkeypatch):
    coord, _q = _coordinator()
    store = VersionedWeightStore()
    tree = {
        "emb": np.arange(64, dtype=np.float32).reshape(8, 8),
        "layers": [
            {"w": np.random.default_rng(0).normal(size=(16, 4)),
             "b": np.zeros(4, dtype=np.float32)},
            {"w": np.asarray([1, 2, 3], dtype=np.int32), "b": None},
        ],
        "meta": ("frozen", 7),
    }
    store.publish(tree)
    server = rpc.FleetRpcServer(coord, store, config=CFG)
    # small chunk size forces the multi-chunk streaming path
    client = rpc.RpcClient(server.address, 0,
                           config=rpc.RpcConfig(chunk_bytes=64,
                                                call_timeout=5.0))
    coord.register_worker(0, alive_fn=lambda: True)
    # any write-mode open during the fetch would be a disk round-trip —
    # the reference's weak point this transport exists to remove
    real_open = builtins.open
    writes = []

    def spy_open(file, mode="r", *a, **kw):
        if any(c in str(mode) for c in "wax+"):
            writes.append((file, mode))
        return real_open(file, mode, *a, **kw)

    monkeypatch.setattr(builtins, "open", spy_open)
    try:
        version, got = client.fetch_weights()
    finally:
        monkeypatch.setattr(builtins, "open", real_open)
    assert version == 0 and writes == []
    np.testing.assert_array_equal(got["emb"], tree["emb"])
    assert got["emb"].dtype == np.float32
    np.testing.assert_array_equal(got["layers"][0]["w"],
                                  tree["layers"][0]["w"])
    assert got["layers"][0]["w"].dtype == tree["layers"][0]["w"].dtype
    np.testing.assert_array_equal(got["layers"][1]["w"],
                                  tree["layers"][1]["w"])
    assert got["layers"][1]["b"] is None
    assert got["meta"] == ("frozen", 7)
    # version cache: an unchanged policy is one tiny round trip, the SAME
    # tree object comes back
    v2, got2 = client.fetch_weights()
    assert v2 == 0 and got2 is got
    # a publish invalidates it
    store.publish({"emb": tree["emb"] * 2})
    v3, got3 = client.fetch_weights()
    assert v3 == 1 and got3 is not got
    np.testing.assert_array_equal(got3["emb"], tree["emb"] * 2)
    client.close()
    server.close()
    coord.close()


def test_partition_fencing_drops_late_completion_with_ledger_event():
    """ISSUE-11 acceptance: worker A is partitioned holding a lease; the
    deadline revokes + re-dispatches to B at a higher epoch. A's late
    completion over the healed link arrives FIRST and must be fenced (epoch
    comparison, not arrival order) with a `fleet_late_duplicate
    {"fenced": true, "epoch": ...}` drop, while B's re-dispatched result —
    bit-identical to the no-fault dispatch — is the one consumed."""
    led = _Ledger()
    clockv = [0.0]
    coord, q = _coordinator(lineage=led, clock=lambda: clockv[0],
                            initial_deadline_s=0.1)
    store = VersionedWeightStore()
    store.publish({"w": np.zeros(2)})
    server = rpc.FleetRpcServer(coord, store, config=CFG)
    coord.register_worker(0, alive_fn=lambda: True)
    coord.register_worker(1, alive_fn=lambda: True)
    ca = rpc.RpcClient(server.address, 0, config=CFG)
    cb = rpc.RpcClient(server.address, 1, config=CFG)
    ra = rpc.RemoteCoordinator(ca, 0.02)
    rb = rpc.RemoteCoordinator(cb, 0.02)
    stop = threading.Event()

    def gen(index, queries):  # deterministic "generation" keyed by index
        return {"tok": np.asarray(queries) * 10 + index}

    la = ra.acquire(0, stop)
    assert la is not None and la.epoch == 1
    payload_a = gen(la.start, la.batches[0])  # A computes, then partitions
    clockv[0] = 1.0                           # lease deadline passes
    coord.poll()                              # revoke -> reassignment pool
    lb = rb.acquire(1, stop)                  # B re-granted, higher epoch
    assert lb is not None and lb.start == la.start and lb.epoch > la.epoch
    # the lease ledger events carry transport + epoch (ISSUE-11 satellite)
    lease_evs = [e for e in led.events if e[0] == "lease"]
    assert [kw["epoch"] for _, _, kw in lease_evs] == [1, 2]
    assert all(kw["transport"] == "rpc" for _, _, kw in lease_evs)
    # A's link heals; its completion arrives BEFORE B's — fenced anyway
    assert ra.complete(0, la, la.start,
                       QueuedSample(la.start, 0, payload_a, 0.0, 0.1)) is False
    payload_b = gen(lb.start, lb.batches[0])
    assert rb.complete(1, lb, lb.start,
                       QueuedSample(lb.start, 0, payload_b, 0.0, 0.1)) is True
    s = q.get(timeout=2)
    # the consumed result is bit-identical to the no-fault dispatch (same
    # cached batch, same index-keyed computation)
    np.testing.assert_array_equal(s.payload["tok"], gen(0, la.batches[0])["tok"])
    drops = [e for e in led.events if e[0] == "drop"]
    assert len(drops) == 1
    _, idx, reason, kw = drops[0]
    assert idx == la.start and reason == "fleet_late_duplicate"
    assert kw["fenced"] is True and kw["epoch"] == la.epoch
    assert kw["worker_id"] == 0 and kw["lease_id"] == la.lease_id
    assert coord.counters["fenced_completions"] == 1
    assert coord.counters["duplicate_samples"] == 1
    ca.close()
    cb.close()
    server.close()
    coord.close()


def test_reconnect_rehandshakes_and_counts():
    """A torn connection is recoverable: the client reconnects, re-sends
    the hello handshake (worker id, last epoch, last weight version), and
    the retry/reconnect counters surface through coordinator stats."""
    coord, q = _coordinator()
    store = VersionedWeightStore()
    store.publish({"w": np.arange(4.0)})
    server = rpc.FleetRpcServer(coord, store, config=CFG)
    coord.register_worker(0, alive_fn=lambda: True)
    faults = FaultInjector.from_spec("net.tear:at=2,worker=0")
    client = rpc.RpcClient(server.address, 0, config=CFG, faults=faults)
    rc = rpc.RemoteCoordinator(client, 0.02)
    # call 1 = hello, call 2 = acquire -> torn mid-frame, retried on a
    # fresh connection after a re-handshake
    lease = rc.acquire(0, threading.Event())
    assert lease is not None
    assert client.retries >= 1 and client.reconnects >= 1
    st = coord.stats()
    assert st["rpc_retries"] >= 1.0 and st["rpc_reconnects"] >= 1.0
    assert st["rpc_bytes_tx"] > 0.0
    # the healed connection still carries a full completion round trip
    assert rc.complete(0, lease, lease.start, QueuedSample(
        lease.start, 0, {"t": np.asarray([1])}, 0.0, 0.1)) is True
    assert q.get(timeout=2).index == lease.start
    client.close()
    server.close()
    coord.close()


def test_heartbeat_miss_counted_not_fatal():
    """Heartbeats over a partitioned link are COUNTED, never raised — real
    worker silence surfaces through lease expiry, not heartbeat failure."""
    coord, _q = _coordinator()
    store = VersionedWeightStore()
    store.publish({})
    server = rpc.FleetRpcServer(coord, store, config=CFG)
    coord.register_worker(0, alive_fn=lambda: True)
    faults = FaultInjector.from_spec("net.partition:at=1,worker=0,delay=0.2")
    client = rpc.RpcClient(server.address, 0, config=CFG, faults=faults)
    transport = rpc.RpcTransport(client, lambda i, q_, t, w: {})
    transport.heartbeat(0)  # partition fires: miss counted, no exception
    assert client.heartbeat_misses == 1
    assert client.stats_payload()["partitioned"] is True
    time.sleep(0.25)        # window passes; the next heartbeat lands and
    transport.heartbeat(0)  # reports the miss count to the coordinator
    assert client.heartbeat_misses == 1
    assert coord.stats()["heartbeat_misses"] == 1.0
    client.close()
    server.close()
    coord.close()


# ---------------------------------------------------------------------------
# orchestrator-level fault matrix (the CI `fleet-rpc-fault-matrix` step)
# ---------------------------------------------------------------------------


def _run_fleet(transport, faults=None, n=16, **fleet_kw):
    batches = iter(range(10000))

    def dispatch(index, queries, tree, worker_id):
        time.sleep(0.002)
        return {"tok": np.asarray(queries) * 10 + index,
                "w0": float(tree["w"][0])}

    fleet_kw.setdefault("poll_interval", 0.02)
    orch = FleetOrchestrator(
        dispatch_fn=dispatch, batch_fn=lambda: np.asarray([next(batches)]),
        initial_params={"w": np.asarray([3.0])}, n_workers=2,
        max_staleness=100, faults=faults, fleet=FleetConfig(**fleet_kw),
        transport=transport,
    )
    out = []
    try:
        for _ in range(n):
            s = orch.get()
            out.append((s.index, int(s.payload["tok"][0]),
                        s.payload["w0"]))
    finally:
        orch.close()
    return out, orch


@pytest.mark.parametrize("spec", [
    None,
    "net.drop:at=3",
    "net.duplicate:every=2",
    "net.tear:at=4",
    "net.delay:every=5,delay=0.05",
    "net.partition:at=2,worker=0,delay=0.3",
])
def test_rpc_fault_matrix_streams_bit_identical(spec):
    """Every injected network failure mode — lost frames, duplicated
    frames, torn frames, latency spikes, a partitioned worker — leaves the
    consumed sample stream bit-identical to the in-process no-fault run:
    retry/backoff, reconnect + re-handshake, seq/offset dedup, lease
    re-dispatch, and epoch fencing absorb all of it."""
    baseline, _ = _run_fleet("inprocess")
    faults = FaultInjector.from_spec(spec) if spec else None
    got, orch = _run_fleet("rpc", faults=faults,
                           initial_deadline_s=0.5 if spec else 600.0)
    assert got == baseline
    if spec:
        fired = sum(v["fires"] for v in faults.stats().values())
        assert fired >= 1, f"{spec} never fired"


def test_statusz_snapshot_carries_transport_state():
    _, orch = _run_fleet("rpc", n=4)
    # the orchestrator is closed but the snapshot machinery still reads —
    # exactly what /statusz does from its HTTP thread
    snap = orch.status_snapshot()
    fleet = snap["fleet"]
    assert fleet["transport"] == "rpc"
    by_id = {w["worker_id"]: w for w in fleet["workers"]}
    assert set(by_id) == {0, 1}
    for w in by_id.values():
        t = w["transport"]
        assert t["state"] in ("connected", "reconnecting", "partitioned")
        assert t["rtt_ewma_s"] >= 0.0
        assert {"retries", "reconnects", "heartbeat_misses",
                "last_epoch", "bytes_tx", "bytes_rx"} <= set(t)
    assert any(w["transport"]["last_epoch"] > 0 for w in by_id.values())
    # flat stats grow the fleet/rpc_* rows for METRICS.md / the exporter
    st = orch.fleet_stats()
    assert {"rpc_retries", "rpc_reconnects", "rpc_rtt_ewma_s",
            "rpc_bytes_tx", "rpc_bytes_rx", "rpc_errors",
            "heartbeat_misses", "fenced_completions"} <= set(st)
    assert st["rpc_bytes_tx"] > 0.0 and st["rpc_rtt_ewma_s"] > 0.0
    # the inprocess fleet reports the same keys, zeroed
    _, orch2 = _run_fleet("inprocess", n=2)
    st2 = orch2.fleet_stats()
    assert st2["rpc_bytes_tx"] == 0.0 and st2["rpc_retries"] == 0.0
    assert orch2.status_snapshot()["fleet"]["transport"] == "inprocess"
    assert orch2.status_snapshot()["fleet"]["workers"][0]["transport"] == {
        "state": "connected"
    }


def test_health_rules_cover_rpc_errors_and_heartbeat_misses():
    from nanorlhf_tpu.telemetry.health import (
        DEFAULT_RULES, HealthMonitor,
    )

    by_name = {r.name: r for r in DEFAULT_RULES}
    assert by_name["rpc_error_rate"].metric == "fleet/rpc_errors"
    assert by_name["rpc_error_rate"].kind == "rate_above"
    assert by_name["heartbeat_miss_rate"].metric == "fleet/heartbeat_misses"
    assert by_name["heartbeat_miss_rate"].kind == "rate_above"
    # the monitor builds windowed rates for both counters and trips CRIT
    # on a sustained error burst
    clock = [0.0]
    mon = HealthMonitor(clock=lambda: clock[0])
    assert {"fleet/rpc_errors", "fleet/heartbeat_misses"} <= set(mon._rates)
    errs = 0.0
    for i in range(12):
        clock[0] += 1.0
        errs += 5.0  # 5 errors/s >> crit=2/s
        mon.observe(i, {"fleet/rpc_errors": errs})
    assert mon.snapshot()["rules"]["rpc_error_rate"] == "crit"
