"""Fused hidden→logprob scoring (ops/fused_logprob.py, docs/FUSED_LOGPROB.md).

Parity gates: the chunked linear-cross-entropy op vs the full-logits oracle —
forward logprobs / entropy / margin, custom-VJP grads (wrt hidden, the
unembedding, a LoRA-composed head, and a tied embedding through the
transpose), the Pallas kernel in interpret mode, padding-mask behavior at the
scorer level, the shared temperature guard, the vocab-scaling memory
assertion (peak temp bytes sublinear in V for fixed B, T), and the
fused-on/off GRPO end-to-end loss identity.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.core.model import (
    padded_forward_hidden,
    padded_forward_logits,
    unembedding,
    unembedding_weight,
)
from nanorlhf_tpu.ops.fused_logprob import (
    chunked_entropy,
    fused_chunk_rows,
    fused_logprob,
    fused_logprob_reference,
)
from nanorlhf_tpu.ops.masking import (
    entropy_from_logits,
    guard_temperature,
    logprobs_from_logits,
)

TEMPS = (0.7, 1.0)


@pytest.fixture(scope="module")
def case():
    # T·B = 26 rows: NOT divisible by the chunk sizes below; V = 517: NOT
    # divisible by the Pallas vocab block — both tail paths exercised
    key = jax.random.PRNGKey(0)
    B, T, D, V = 2, 13, 32, 517
    h = jax.random.normal(key, (B, T, D), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (D, V), jnp.float32) * 0.1
    labels = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, V)
    return h, w, labels


@pytest.mark.parametrize("temp", TEMPS)
@pytest.mark.parametrize("impl,chunk", [("lax", 5), ("lax", 26), ("pallas", 7)])
def test_forward_parity(case, temp, impl, chunk):
    h, w, labels = case
    ref = fused_logprob_reference(h, w, labels, temp, with_entropy=True)
    got = fused_logprob(h, w, labels, temp, chunk=chunk, impl=impl,
                        with_entropy=True)
    assert float(jnp.max(jnp.abs(got[0] - ref[0]))) < 1e-5
    assert float(jnp.max(jnp.abs(got[1] - ref[1]))) < 1e-5
    assert got[0].shape == labels.shape and got[0].dtype == jnp.float32


@pytest.mark.parametrize("temp", TEMPS)
def test_margin_parity(case, temp):
    h, w, labels = case
    ref = fused_logprob_reference(h, w, labels, temp, with_entropy=True,
                                  with_margin=True)
    got = fused_logprob(h, w, labels, temp, chunk=9, impl="lax",
                        with_entropy=True, with_margin=True)
    assert float(jnp.max(jnp.abs(got[2] - ref[2]))) < 1e-5
    # margin is the top-1-vs-top-2 scaled-logit gap — always positive
    assert float(jnp.min(got[2])) >= 0.0
    # with_margin on the pallas impl silently routes to lax (no top-2 in
    # the kernel) rather than erroring
    via_pallas = fused_logprob(h, w, labels, temp, chunk=9, impl="pallas",
                               with_entropy=True, with_margin=True)
    assert float(jnp.max(jnp.abs(via_pallas[2] - ref[2]))) < 1e-5


@pytest.mark.parametrize("temp", TEMPS)
@pytest.mark.parametrize("impl", ["lax", "pallas"])
def test_grad_parity_hidden_and_unembed(case, temp, impl):
    """Backward (chunk-logits recompute) vs naive AD: grads wrt hidden and
    the unembedding, through a masked weighted sum like a real loss."""
    h, w, labels = case
    gmask = jax.random.normal(jax.random.PRNGKey(3), labels.shape)

    def loss_fused(h_, w_):
        return (fused_logprob(h_, w_, labels, temp, chunk=7, impl=impl)
                * gmask).sum()

    def loss_ref(h_, w_):
        return (fused_logprob_reference(h_, w_, labels, temp) * gmask).sum()

    gf = jax.grad(loss_fused, argnums=(0, 1))(h, w)
    gr = jax.grad(loss_ref, argnums=(0, 1))(h, w)
    for a, b in zip(gf, gr):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-5


def test_grad_parity_lora_scaled_head(case):
    """Gradients flow through a LoRA-composed head w = base + scale·(A@B)
    identically to the naive path — the adapter factors see exact grads."""
    h, w, labels = case
    D, V = w.shape
    r, scale = 4, 0.25
    a = jax.random.normal(jax.random.PRNGKey(4), (D, r)) * 0.3
    b = jax.random.normal(jax.random.PRNGKey(5), (r, V)) * 0.3

    def head(a_, b_):
        return w + scale * (a_ @ b_)

    gf = jax.grad(lambda a_, b_: fused_logprob(
        h, head(a_, b_), labels, 0.7, chunk=6, impl="lax").sum(),
        argnums=(0, 1))(a, b)
    gr = jax.grad(lambda a_, b_: fused_logprob_reference(
        h, head(a_, b_), labels, 0.7).sum(), argnums=(0, 1))(a, b)
    for got, want in zip(gf, gr):
        assert float(jnp.max(jnp.abs(got - want))) < 1e-5


def test_grad_parity_tied_embedding(case):
    """Tied head: the transpose sits OUTSIDE the custom_vjp, so dW must
    flow back to the [V, D] embedding exactly as under naive AD."""
    h, _, labels = case
    D = h.shape[-1]
    V = 517
    embed = jax.random.normal(jax.random.PRNGKey(6), (V, D)) * 0.1
    gf = jax.grad(lambda e: fused_logprob(
        h, e.T, labels, 1.0, chunk=8, impl="lax").sum())(embed)
    gr = jax.grad(lambda e: fused_logprob_reference(
        h, e.T, labels, 1.0).sum())(embed)
    assert float(jnp.max(jnp.abs(gf - gr))) < 1e-5


@pytest.mark.parametrize("impl", ["lax", "pallas"])
def test_transposed_weight_parity(case, impl):
    """transposed=True ([V, D] weight, how tied embeddings reach the op —
    core.model.unembedding): forward + entropy match the [D, V] form for
    both impls, and dW comes back [V, D], identical to naive AD through
    the embedding leaf (no transpose copy anywhere in that path)."""
    h, w, labels = case
    embed = w.T  # [V, D], the tied-leaf orientation
    ref = fused_logprob(h, w, labels, 0.7, chunk=7, impl=impl,
                        with_entropy=True)
    got = fused_logprob(h, embed, labels, 0.7, chunk=7, impl=impl,
                        with_entropy=True, transposed=True)
    assert float(jnp.max(jnp.abs(got[0] - ref[0]))) < 1e-5
    assert float(jnp.max(jnp.abs(got[1] - ref[1]))) < 1e-5

    gf = jax.grad(lambda e: fused_logprob(
        h, e, labels, 0.7, chunk=7, impl=impl, transposed=True
    ).sum())(embed)
    gr = jax.grad(lambda e: fused_logprob_reference(
        h, e, labels, 0.7, transposed=True).sum())(embed)
    assert gf.shape == embed.shape
    assert float(jnp.max(jnp.abs(gf - gr))) < 1e-5


def test_entropy_and_margin_are_stop_gradient(case):
    h, w, labels = case
    g_ent = jax.grad(lambda h_: fused_logprob(
        h_, w, labels, 1.0, chunk=8, impl="lax", with_entropy=True
    )[1].sum())(h)
    assert float(jnp.max(jnp.abs(g_ent))) == 0.0
    g_mar = jax.grad(lambda h_: fused_logprob(
        h_, w, labels, 1.0, chunk=8, impl="lax", with_margin=True
    )[1].sum())(h)
    assert float(jnp.max(jnp.abs(g_mar))) == 0.0


def test_chunked_entropy_matches_full_f32_copy(case):
    h, w, labels = case
    z = h @ w
    for temp in TEMPS:
        want = entropy_from_logits(
            z.astype(jnp.float32) / guard_temperature(temp)
        )
        got = chunked_entropy(z, temp, chunk=5)
        assert float(jnp.max(jnp.abs(got - want))) < 1e-5


def test_chunked_entropy_sharded_batch():
    """Regression: chunking must slice the TIME axis, not flattened rows —
    flattening a GSPMD-sharded batch dim into row chunks and concatenating
    a ragged tail produced a miscompiled program whose mean entropy came
    out exactly 2× on a sharded batch (caught by the fused-on/off e2e)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("data", "fsdp", "tensor"))
    B, T, V = 4, 8, 256
    z = jax.random.normal(jax.random.PRNGKey(0), (B, T, V), jnp.float32)
    zs = jax.device_put(z, NamedSharding(mesh, P(("data", "fsdp"))))
    want = float(entropy_from_logits(z / 0.9).mean())
    got = float(jax.jit(lambda x: chunked_entropy(x, 0.9, chunk=5).mean())(zs))
    assert abs(got - want) < 1e-5, (got, want)


def test_fused_chunk_rows_shrinks_with_vocab():
    big = fused_chunk_rows(1024, 10**6, bytes_budget=1 << 20)
    small = fused_chunk_rows(8 * 1024, 10**6, bytes_budget=1 << 20)
    assert small < big
    assert small % 8 == 0 and big % 8 == 0
    assert fused_chunk_rows(151936, 16) == 16  # capped at total rows


# ---------------------------------------------------------------------------
# scorer-level parity: padded batches through the model entrypoints
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    mcfg = ModelConfig.qwen2_tiny(vocab_size=300)
    params = init_params(mcfg, jax.random.PRNGKey(7), jnp.float32)
    return mcfg, params


@pytest.mark.parametrize("temp", TEMPS)
def test_scorer_parity_with_padding(tiny_model, temp):
    """padded_forward_hidden + fused_logprob == padded_forward_logits +
    logprobs_from_logits on a batch with left-padded prompts AND
    right-padded (post-EOS) responses — the trainer's exact scorer swap."""
    mcfg, params = tiny_model
    pad_id, ctx = 0, 6
    qr = np.array(jax.random.randint(
        jax.random.PRNGKey(8), (3, ctx + 11), 1, 300))
    qr[0, :3] = pad_id        # left-padded prompt
    qr[1, ctx + 7:] = pad_id  # truncated response tail
    qr = jnp.asarray(qr)
    resp = qr[:, ctx:]

    naive = logprobs_from_logits(
        padded_forward_logits(params, mcfg, qr, pad_id,
                              response_context_length=ctx),
        resp, temp,
    )
    fused = fused_logprob(
        padded_forward_hidden(params, mcfg, qr, pad_id,
                              response_context_length=ctx),
        unembedding_weight(mcfg, params), resp, temp, chunk=5, impl="lax",
    )
    assert float(jnp.max(jnp.abs(fused - naive))) < 1e-5


def test_padded_forward_hidden_times_unembed_is_logits(tiny_model):
    mcfg, params = tiny_model
    qr = jax.random.randint(jax.random.PRNGKey(9), (2, 10), 1, 300)
    want = padded_forward_logits(params, mcfg, qr, 0,
                                 response_context_length=4)
    got = padded_forward_hidden(params, mcfg, qr, 0,
                                response_context_length=4) \
        @ unembedding_weight(mcfg, params)
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5


def test_temperature_guard_unified():
    """Sampler-captured logprobs and scoring logprobs must agree
    BIT-FOR-BIT at any temperature — one shared guard_temperature floor
    (previously max(t,1e-6) vs raw t vs t+1e-7)."""
    from nanorlhf_tpu.sampler.sampler import _token_logprob

    logits = jax.random.normal(jax.random.PRNGKey(10), (4, 97), jnp.float32)
    tok = jax.random.randint(jax.random.PRNGKey(11), (4,), 0, 97)
    for temp in (1e-9, 1e-6, 0.05, 0.7, 1.0):
        cap = _token_logprob(logits, tok, temp)
        score = logprobs_from_logits(logits, tok, temp)
        np.testing.assert_array_equal(np.asarray(cap), np.asarray(score))
    assert guard_temperature(0.0) == 1e-6
    assert guard_temperature(0.9) == 0.9


# ---------------------------------------------------------------------------
# memory: no live [rows, V] buffer — peak temp bytes sublinear in V
# ---------------------------------------------------------------------------


def _score_temp_bytes(vocab: int, fused: bool) -> int:
    mcfg = ModelConfig.qwen2_tiny(vocab_size=vocab)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    B, ctx, T = 4, 8, 40
    qr = jax.random.randint(
        jax.random.PRNGKey(1), (B, ctx + T), 1, min(vocab, 200))

    def f_fused(params, qr):
        h = padded_forward_hidden(params, mcfg, qr, 0,
                                  response_context_length=ctx)
        # small budget so chunking engages at test-sized vocabs — the
        # production default (256 MB) plays the same role at 152k. The
        # production orientation (qwen2_tiny is tied → [V, D] +
        # transposed=True) so the assertion covers the real wiring.
        w, w_t = unembedding(mcfg, params)
        return fused_logprob(h, w, qr[:, ctx:], 0.9,
                             bytes_budget=64 * 1024, impl="lax",
                             transposed=w_t)

    def f_naive(params, qr):
        z = padded_forward_logits(params, mcfg, qr, 0,
                                  response_context_length=ctx)
        return logprobs_from_logits(z, qr[:, ctx:], 0.9)

    f = f_fused if fused else f_naive
    compiled = jax.jit(f).lower(params, qr).compile()
    return int(compiled.memory_analysis().temp_size_in_bytes)


def test_vocab_scaling_sublinear():
    """Fixed B, T; vocab ×16: the fused scorer's peak temp memory must grow
    SUBLINEARLY (the auto-chunk shrinks with V), while the naive scorer
    tracks the full [B·T, V] logits buffer ≈ linearly."""
    v_lo, v_hi = 512, 8192
    ratio = v_hi / v_lo
    fused_lo, fused_hi = _score_temp_bytes(v_lo, True), _score_temp_bytes(v_hi, True)
    naive_lo, naive_hi = _score_temp_bytes(v_lo, False), _score_temp_bytes(v_hi, False)
    assert fused_hi / fused_lo < 0.5 * ratio, (fused_lo, fused_hi)
    assert naive_hi / naive_lo > 0.75 * ratio, (naive_lo, naive_hi)
    # and at the big vocab, fused peak is decisively under naive
    assert fused_hi < 0.5 * naive_hi, (fused_hi, naive_hi)


# ---------------------------------------------------------------------------
# end-to-end: GRPO update with fused_logprob on/off → identical losses
# ---------------------------------------------------------------------------


def _grpo_losses(tmp_path, tag: str, fused: bool) -> dict:
    import json

    from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
    from nanorlhf_tpu.parallel import MeshConfig
    from nanorlhf_tpu.trainer import AlgoName, RLConfig, RLTrainer

    def reward(pmt_and_responses, eos_token):
        return np.asarray(
            [(1.0 if eos_token in s else 0.0) - 0.01 * len(s.split())
             for s in pmt_and_responses], np.float32)

    tok = ToyTokenizer(vocab_size=256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    cfg = RLConfig(
        algo=AlgoName.GRPO,
        output_dir=str(tmp_path / tag),
        response_length=8,
        temperature=0.9,
        sample_n=2,
        total_episodes=16,
        per_device_train_batch_size=1,
        gradient_accumulation_steps=2,
        num_mini_batches=2,
        num_ppo_epochs=1,
        learning_rate=1e-4,
        kl_coef=0.05,
        use_lora=True, lora_r=4, lora_alpha=8,
        gradient_checkpointing=False,
        fused_logprob=fused,
        fused_logprob_chunk=5,   # does not divide the microbatch rows
        mesh=MeshConfig(2, 2, 2),
        save_steps=0,
        report_to="jsonl",
    )
    dataset = load_prompt_dataset("synthetic:32", tok, max_prompt_len=12)
    tr = RLTrainer(cfg, mcfg, tok, params, dataset, reward)
    try:
        tr.train(num_updates=1)
    finally:
        tr.close()
    rows = [json.loads(l) for l in
            (tmp_path / tag / "metrics.jsonl").read_text().splitlines()]
    return next(r for r in rows if "loss/policy_avg_new" in r)


def test_grpo_update_fused_on_off_identical(tmp_path):
    """Staleness-0 end-to-end: same seed, same data — a GRPO update with
    fused_logprob on vs off produces identical losses/ratios/entropy (the
    fused path is a memory transform, not a numerics change)."""
    on = _grpo_losses(tmp_path, "fused_on", True)
    off = _grpo_losses(tmp_path, "fused_off", False)
    for k in ("loss/policy_avg_new", "policy/entropy_avg_new",
              "val/ratio_new", "objective/kl_old", "policy/approxkl_avg_new"):
        assert abs(on[k] - off[k]) < 1e-5, (k, on[k], off[k])
    # the memory metrics tell the two modes apart
    assert on["mem/logits_bytes_saved"] > 0.0
    assert off["mem/logits_bytes_saved"] == 0.0


def _sparse_grpo_losses(tmp_path, tag: str, fused: bool) -> dict:
    import json

    from nanorlhf_tpu.data import ToyTokenizer
    from nanorlhf_tpu.entrypoints.grpo_r1 import (
        build_prompt_dataset,
        synthetic_math_corpus,
    )
    from nanorlhf_tpu.parallel import MeshConfig
    from nanorlhf_tpu.trainer import AlgoName, RLConfig
    from nanorlhf_tpu.trainer.sparse_grpo import SparseGRPOTrainer

    tok = ToyTokenizer(512)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=512)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    dataset = build_prompt_dataset(synthetic_math_corpus(32), tok,
                                   max_prompt_len=16)
    cfg = RLConfig(
        algo=AlgoName.GRPO,
        output_dir=str(tmp_path / tag),
        response_length=8,
        temperature=0.9,
        sample_n=2,
        total_episodes=16,
        per_device_train_batch_size=1,
        gradient_accumulation_steps=1,
        num_mini_batches=1,
        learning_rate=1e-4,
        use_lora=True, lora_r=4, lora_alpha=8,
        gradient_checkpointing=False,
        fused_logprob=fused,
        fused_logprob_chunk=5,   # does not divide the bucket rows
        mesh=MeshConfig(-1, 1, 1),
        save_steps=0,
        report_to="jsonl",
    )
    # fresh identically-seeded rng per run: both modes see the same rewards
    rng = np.random.default_rng(0)

    def noisy_reward(pmt_and_responses, responses_ids, tokenizer):
        return rng.random(len(pmt_and_responses)).astype(np.float32)

    tr = SparseGRPOTrainer(cfg, mcfg, tok, params, dataset, noisy_reward)
    tr.train(num_updates=1)
    rows = [json.loads(l) for l in
            (tmp_path / tag / "metrics.jsonl").read_text().splitlines()]
    return next(r for r in rows if "sparse/kept_frac" in r)


def test_sparse_grpo_update_fused_on_off_identical(tmp_path):
    """Same identity as test_grpo_update_fused_on_off_identical but through
    SparseGRPOTrainer's bucketed score/update path (its fused branches —
    bucket scorer delegation and the fused bucket loss — are distinct code
    from RLTrainer's and need their own e2e pin)."""
    on = _sparse_grpo_losses(tmp_path, "sparse_fused_on", True)
    off = _sparse_grpo_losses(tmp_path, "sparse_fused_off", False)
    for k in ("loss/policy_avg_new", "policy/entropy_avg_new",
              "val/ratio_new", "policy/approxkl_avg_new",
              "sparse/kept_frac"):
        assert abs(on[k] - off[k]) < 1e-5, (k, on[k], off[k])
    # the memory metrics tell the two modes apart
    assert on["mem/logits_bytes_saved"] > 0.0
    assert off["mem/logits_bytes_saved"] == 0.0
