"""Golden grading pairs — behaviors the reference toolkits grade correctly.

Each row re-states a case the vendored DeepSeek/Qwen toolkits handle
(`/root/reference/examples/r1-v0/utils/toolkit_for_MATH/latex_answer_check.py:52-123`,
`.../eval/eval_utils.py:181-278`, `.../eval/eval_script.py:6-44`,
`.../data_processing/answer_extraction.py:207-338`). Round 1's compact
grader mis-graded several of these shapes (VERDICT r1 missing #4); the suite
is written against the reference semantics FIRST, implementation second.

Run both in-process and through the timeout subprocess guard.
"""

import pytest

from nanorlhf_tpu.rewards.math_grader import is_correct, math_answers_equal

# (prediction, ground truth, expected verdict)
EQUIV_GOLDEN = [
    # --- percentage variants (eval_utils.math_equal include_percentage;
    #     bare x100 variants with NO % marker are eval-path-only leniency,
    #     tested separately in test_grader_strictness.py) ---
    ("50", "50\\%", True),
    ("0.5", "50\\%", True),
    ("50%", "0.5", True),
    ("3", "5\\%", False),
    # --- numeric closeness (abs_tol 1e-3 digits; rel_tol 1e-3 symbolic) ---
    ("0.333", "\\frac{1}{3}", True),
    ("3.1416", "\\pi", True),
    ("3.1429", "\\frac{22}{7}", True),
    ("0.25", "\\frac{1}{3}", False),
    # --- intervals / tuples, elementwise (eval_utils.math_equal:225-231;
    #     bracket TYPES are not compared — reference semantics) ---
    ("(1, 2]", "(1,2]", True),
    ("[1,2)", "(1,2)", True),
    ("(0, 1)", "(0, 2)", False),
    ("(-\\infty, 5)", "(-\\infty,5)", True),
    ("(\\frac{1}{2}, 3)", "(0.5, 3)", True),
    ("(1, 2, 3)", "(1, 2)", False),
    # --- matrices (eval_utils.math_equal:233-253) ---
    ("\\begin{pmatrix}1&2\\\\3&4\\end{pmatrix}",
     "\\begin{bmatrix}1 & 2 \\\\ 3 & 4\\end{bmatrix}", True),
    ("\\begin{pmatrix}\\frac{1}{2}\\\\0\\end{pmatrix}",
     "\\begin{pmatrix}0.5\\\\0\\end{pmatrix}", True),
    ("\\begin{pmatrix}1&2\\\\3&4\\end{pmatrix}",
     "\\begin{pmatrix}1&2\\\\3&5\\end{pmatrix}", False),
    ("\\begin{pmatrix}1&2\\end{pmatrix}",
     "\\begin{pmatrix}1&2\\\\3&4\\end{pmatrix}", False),
    # --- equations / relations (eval_utils.math_equal:255-266) ---
    ("x=5", "5", True),
    ("5", "x = 5", True),
    ("y = 2x + 3", "2x + 3 = y", True),
    ("x + y = 1", "y = 1 - x", True),
    ("y = 2x", "y = 3x", False),
    ("x \\le 5", "x\\leq5", True),
    ("x \\ge 5", "x \\le 5", False),
    ("x < 3", "x<3", True),
    # --- set unions (eval_script.is_correct \cup split) ---
    ("(-\\infty,0)\\cup(1,\\infty)", "(-\\infty, 0) \\cup (1, \\infty)", True),
    ("(-\\infty,0)\\cup(2,\\infty)", "(-\\infty, 0) \\cup (1, \\infty)", False),
    # --- text answers survive \text stripping ---
    ("\\text{east}", "east", True),
    # --- plain regressions the round-1 grader already handled ---
    ("\\frac{1}{2}", "0.5", True),
    ("\\sqrt{8}", "2\\sqrt{2}", True),
    ("1{,}000", "1000", True),
    # --- r3 additions: nested radicals/fractions (fixpoint latex→sympy),
    #     trailing units, finite brace sets, assorted reference shapes ---
    ("\\frac{\\sqrt{3}}{3}", "\\frac{1}{\\sqrt{3}}", True),
    ("\\sqrt{\\frac{1}{4}}", "0.5", True),
    ("\\frac{\\frac{1}{2}}{2}", "0.25", True),
    ("5\\text{ cm}", "5", True),
    ("12 \\text{ cm}^2", "12", True),
    ("\\{1, 2\\}", "\\{2, 1\\}", True),
    ("\\{1, 2\\}", "\\{1, 3\\}", False),
    ("\\{1\\}", "\\{1, 2\\}", False),
    ("\\dfrac{3}{4}", "0.75", True),
    ("2\\frac{1}{2}", "2.5", True),
    ("90^\\circ", "90", True),
    ("1.5\\times10^3", "1500", True),
    ("\\pm\\sqrt{2}", "\\sqrt{2}, -\\sqrt{2}", True),
    ("x^2+2x+1", "(x+1)^2", True),
]


@pytest.mark.parametrize("pred,gt,want", EQUIV_GOLDEN)
def test_equivalence_golden_inprocess(pred, gt, want):
    assert math_answers_equal(pred, gt) is want


def test_equivalence_golden_through_subprocess_guard():
    """The same verdicts must survive the call_with_timeout path the training
    reward uses (`grpo_r1.py:179-192` parity)."""
    for pred, gt, want in EQUIV_GOLDEN[:12]:  # subprocess spin-up is slow; sample
        assert is_correct(pred, gt, timeout=5.0, use_subprocess=True) is want, (
            pred, gt, want
        )


# ---------------------------------------------------------------------------
# multi-answer dispatch (eval_script.is_correct:6-44)
# ---------------------------------------------------------------------------


def test_multi_answer_bipartite_match():
    from nanorlhf_tpu.rewards.eval_dispatch import is_correct_item

    assert is_correct_item(["1", "2"], ["2", "1"]) is True
    assert is_correct_item(["1"], ["1", "2"]) is False      # answer 2 unmatched
    assert is_correct_item(["1", "3"], ["1", "2"]) is False
    assert is_correct_item("0.5", "\\frac{1}{2}") is True
    assert is_correct_item("42", "41") is False


def test_numeric_prec_tolerance():
    from nanorlhf_tpu.rewards.eval_dispatch import is_correct_item

    assert is_correct_item("3.14159", "3.1414", prec=1e-3) is True
    assert is_correct_item("1,000", "1000") is True          # comma stripping


# ---------------------------------------------------------------------------
# per-benchmark extraction (answer_extraction.py:245-338)
# ---------------------------------------------------------------------------


def test_extract_math_answer_boxed_exhaust():
    from nanorlhf_tpu.rewards.answer_extraction import extract_math_answer

    text = "First \\boxed{3} then later \\boxed{\\frac{1}{2}}."
    assert extract_math_answer("q", text, "math") == ["3", "\\frac{1}{2}"]


def test_extract_math_answer_comma_split():
    from nanorlhf_tpu.rewards.answer_extraction import extract_math_answer

    q = "Find all roots, separated by commas."
    text = "The answer is \\boxed{1, 2, 3}"
    assert extract_math_answer(q, text, "math") == ["1", "2", "3"]


def test_extract_math_answer_text_and_split():
    from nanorlhf_tpu.rewards.answer_extraction import extract_math_answer

    text = "\\boxed{3 \\text{ and } 5}"
    assert extract_math_answer("q", text, "math") == ["3", "5"]


def test_extract_gsm_last_number():
    from nanorlhf_tpu.rewards.answer_extraction import (
        extract_gsm_few_shot_cot_answer,
    )

    assert extract_gsm_few_shot_cot_answer(
        "q", "So 4 + 5 = 9 dollars total.", "gsm8k"
    ) == "9"
    # few-shot echo truncation at "Q: "
    assert extract_gsm_few_shot_cot_answer(
        "q", "The total is 12 dollars.\nQ: next question 99", "gsm8k"
    ) == "12"
    assert extract_gsm_few_shot_cot_answer("q", "no digits here", "gsm8k") \
        == "[invalid]"


def test_extract_sat_choice():
    from nanorlhf_tpu.rewards.answer_extraction import extract_sat_few_shot_answer

    assert extract_sat_few_shot_answer(
        "q", "Therefore the final answer is (B).", "sat"
    ) == "B"
    assert extract_sat_few_shot_answer(
        "q", "the final answer is c", "sat"
    ) == "C"
    assert extract_sat_few_shot_answer("q", "no choice given", "sat") \
        == "placeholder"


def test_extract_ocwcourses():
    from nanorlhf_tpu.rewards.answer_extraction import (
        extract_ocwcourses_few_shot_answer,
    )

    assert extract_ocwcourses_few_shot_answer(
        "q", "Thus the final answer is 42. I hope it is correct.", "ocw"
    ) == "42"
    assert extract_ocwcourses_few_shot_answer("q", "nothing", "ocw") == "[invalid]"


def test_extract_cmath_and_gaokao():
    from nanorlhf_tpu.rewards.answer_extraction import (
        extract_agieval_gaokao_mathcloze_few_shot_cot_test,
        extract_cmath_few_shot_test,
    )

    assert extract_cmath_few_shot_test("q", "所以答案是 42。", "cmath") == "42"
    assert extract_agieval_gaokao_mathcloze_few_shot_cot_test(
        "q", "答案是$\\frac{1}{2}$", "gaokao"
    ) == ["\\frac{1}{2}"]


def test_extractor_registry_dispatch():
    """`get_extractor(task)` — the per-benchmark dispatch the reference keys
    its eval scripts on (eval_script.py:6-44 consumes these extractions)."""
    from nanorlhf_tpu.rewards.answer_extraction import get_extractor

    assert get_extractor("math")("q", "\\boxed{7}", "math") == ["7"]
    assert get_extractor("gsm8k")("q", "= 3 apples", "gsm8k") == "3"
    assert get_extractor("sat-math")("q", "the final answer is (a)", "sat") == "A"
    assert get_extractor("unknown-task")("q", "The answer is 5", "t") == "5"


def test_neq_relation():
    """\\neq routes into its own branch — '=' splitting must not turn 'x!'
    into factorial(x)."""
    assert math_answers_equal("5\\neq x", "x \\neq 5") is True
    assert math_answers_equal("x \\neq 5", "x \\neq 6") is False
    assert math_answers_equal("x \\neq 5", "x = 5") is False


def test_extractor_name_normalization():
    from nanorlhf_tpu.rewards.answer_extraction import (
        extract_gsm_few_shot_cot_answer,
        extract_math_answer,
        get_extractor,
    )

    assert get_extractor("MATH500") is extract_math_answer
    assert get_extractor("math-500") is extract_math_answer
    assert get_extractor("gsm8k_test") is extract_gsm_few_shot_cot_answer


def test_brace_set_edge_cases():
    """Review regressions: sets of tuples must not fragment and cross-match,
    and unions of brace sets keep union (not set) semantics."""
    from nanorlhf_tpu.rewards.math_grader import math_answers_equal as eq

    assert not eq("\\{(1,2),(3,4)\\}", "\\{(1,4),(3,2)\\}")
    assert eq("\\{(1,2),(3,4)\\}", "\\{(3,4),(1,2)\\}")
    assert eq("\\{1\\}\\cup\\{2\\}", "\\{2\\}\\cup\\{1\\}")
    assert not eq("\\{[1,2],[3,4]\\}", "\\{[1,4],[3,2]\\}")
