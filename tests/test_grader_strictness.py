"""Training-reward strictness vs offline-eval leniency.

The reference's x100/÷100 percentage leniency lives ONLY in its offline eval
toolkit (`eval_utils.math_equal:195-214`); its training-path grader
(`grpo_r1.py:216-224`) has no such rule. A live reward that accepted '0.5'
for '50' unconditionally would be a reward-hacking surface.
"""

from nanorlhf_tpu.rewards.eval_dispatch import is_correct_item
from nanorlhf_tpu.rewards.math_grader import is_correct, math_answers_equal


class TestTrainingRewardStrict:
    def test_x100_variants_rejected_without_percent_marker(self):
        assert not math_answers_equal("0.5", "50")
        assert not math_answers_equal("50", "0.5")
        assert not math_answers_equal("1234", "12.34")

    def test_percent_marker_enables_variants(self):
        assert math_answers_equal("50%", "0.5")
        assert math_answers_equal("0.5", "50\\%")

    def test_is_correct_training_path_strict(self):
        # is_correct grades the EXTRACTED boxed answer (`grpo_r1.py:216-224`)
        assert not is_correct("0.5", "50", use_subprocess=False)
        assert is_correct("50", "50", use_subprocess=False)

    def test_is_correct_strict_through_subprocess_guard(self):
        assert not is_correct("0.5", "50", timeout=5.0)
        assert not is_correct("0.17", "17", timeout=5.0)


class TestEvalPathLenient:
    def test_eval_dispatch_accepts_x100_variants(self):
        # reference eval parity: math_equal compares vs {gt/100, gt, gt*100}
        assert is_correct_item("0.5", "50")
        assert is_correct_item("50", "0.5")


class TestCupUnionOrderFree:
    def test_union_pieces_match_in_any_order(self):
        a = "(1,2)\\cup(3,4)"
        b = "(3,4)\\cup(1,2)"
        assert math_answers_equal(a, b)
        assert is_correct_item(a, b)

    def test_union_count_mismatch_fails(self):
        assert not math_answers_equal("(1,2)\\cup(3,4)", "(1,2)")
