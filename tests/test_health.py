"""Run-health plane (nanorlhf_tpu/telemetry/health.py + exporter.py,
docs/OBSERVABILITY.md §5) — the tier-1 `health-smoke` CI gate:

- P² quantile sketches track numpy percentiles in O(1) memory and
  journal/restore exactly; windowed counter rates read per-second slopes
  on the monotonic clock;
- an injected reward-collapse stream walks the monitor OK→CRIT, counts
  one trip, lands a `reason="health"` blackbox through the flight
  recorder, and emits instants on the "health" trace track — while a
  noisy-but-healthy stream never leaves OK;
- the StatusExporter serves Prometheus-parseable /metrics (the SHARED
  `validate_prometheus_text` check), a 200/503 /healthz from the verdict,
  and /statusz JSON; port 0 is a disabled no-op; close() releases the
  port;
- a 2-update CPU train with `status_port=-1` survives concurrent scrape
  threads with zero torn/invalid payloads, serves perf/* + health/*
  gauges and queue + fleet state, and stamps rows with monotonic t_mono;
- the health journal rides `trainer_state.json` under "health" and a
  resumed trainer restores the learned baselines (the fleet-counter
  continuity contract).
"""

import json
import math
import random
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from nanorlhf_tpu.telemetry import (
    HealthConfig,
    HealthMonitor,
    SpanTracer,
    StatusExporter,
    render_prometheus,
    validate_prometheus_text,
)
from nanorlhf_tpu.telemetry.health import (
    CRIT,
    OK,
    WARN,
    MetricAggregate,
    P2Quantile,
    WindowedRate,
)
from nanorlhf_tpu.trainer import AlgoName

from test_trainer_smoke import make_trainer

REWARD = "eval_objective/rlhf_reward_old"


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


# ---------------------------------------------------------------------------
# streaming aggregators (jax-free)
# ---------------------------------------------------------------------------


def test_p2_quantile_tracks_numpy():
    rng = random.Random(0)
    xs = [rng.gauss(0.0, 1.0) for _ in range(4000)]
    for q in (0.5, 0.95):
        sk = P2Quantile(q)
        for x in xs:
            sk.update(x)
        true = float(np.percentile(xs, 100 * q))
        # O(1)-memory sketch vs exact percentile of a unit normal
        assert abs(sk.value() - true) < 0.1, (q, sk.value(), true)


def test_p2_quantile_warmup_and_state_roundtrip():
    sk = P2Quantile(0.5)
    assert math.isnan(sk.value())           # no observations yet
    for x in (3.0, 1.0, 2.0):
        sk.update(x)
    assert sk.value() == 2.0                # order statistic under 5 obs
    for x in range(100):
        sk.update(float(x % 10))
    clone = P2Quantile(0.5)
    clone.load(sk.state())
    assert clone.state() == sk.state()
    sk.update(4.2)
    clone.update(4.2)
    assert clone.state() == sk.state()      # identical trajectory after load


def test_windowed_rate_fake_clock():
    r = WindowedRate(window_s=10.0)
    assert r.rate() == 0.0                  # <2 points
    for t, v in [(0.0, 0.0), (1.0, 2.0), (2.0, 4.0)]:
        r.update(t, v)
    assert r.rate() == pytest.approx(2.0)   # 4 units over 2 s
    # old points slide out of the window
    r.update(20.0, 4.0)
    assert r.rate() < 2.0
    # a counter reset (process restart) must not report a negative storm
    r2 = WindowedRate(window_s=10.0)
    r2.update(0.0, 100.0)
    r2.update(1.0, 0.0)
    assert r2.rate() == 0.0


def test_metric_aggregate_state_roundtrip():
    agg = MetricAggregate(0.5, 0.05)
    for i in range(50):
        agg.update(1.0 + 0.1 * math.sin(i))
    back = MetricAggregate.from_state(agg.state(), 0.5, 0.05)
    assert back.state() == agg.state()


# ---------------------------------------------------------------------------
# HealthMonitor rules + verdict (jax-free, synthetic streams)
# ---------------------------------------------------------------------------


def test_reward_collapse_trips_crit_with_blackbox_and_instants(tmp_path):
    tracer = SpanTracer(enabled=True)
    dumps = []

    def blackbox(step, extra):
        dumps.append(tracer.dump_blackbox(str(tmp_path), step, "health",
                                          extra=extra))

    hm = HealthMonitor(HealthConfig(warmup=4), tracer=tracer,
                       blackbox_fn=blackbox)
    rng = random.Random(1)
    for i in range(20):
        rows = hm.observe(i, {REWARD: 1.0 + 0.01 * rng.random()})
    assert hm.verdict == OK
    assert rows["health/verdict"] == 0.0
    assert rows["health/rule_reward_collapse"] == 0.0
    for i in range(20, 28):
        rows = hm.observe(i, {REWARD: 0.0})
    assert hm.verdict == CRIT and hm.trips == 1
    assert rows["health/verdict"] == 2.0
    assert rows["health/rules_crit"] >= 1.0
    assert rows["health/trips"] == 1.0
    # exactly one blackbox, reason="health", tripped rules in extra
    assert len(dumps) == 1
    bb = json.loads(open(dumps[0]).read())
    assert bb["reason"] == "health"
    assert "reward_collapse" in bb["extra"]["rules"]
    # rule transitions + verdict landed as instants on the "health" track
    tracer.write_trace(str(tmp_path / "t.json"))
    ev = json.loads(open(tmp_path / "t.json").read())["traceEvents"]
    names = {e["name"] for e in ev if e.get("ph") == "i"}
    assert "health.reward_collapse" in names
    assert "health.verdict" in names
    # events ring recorded the escalation, newest last
    assert hm.events()[-1]["level"] in (WARN, CRIT)
    # hysteresis: a CRIT level holds for recovery_rows calmer evaluations
    hm.observe(28, {REWARD: 0.0})
    assert hm.verdict == CRIT


def test_noisy_but_healthy_stream_never_fires():
    hm = HealthMonitor(HealthConfig(warmup=4))
    rng = random.Random(2)
    for i in range(300):
        hm.observe(i, {
            REWARD: 1.0 + 0.3 * rng.gauss(0, 1),
            "policy/entropy_avg_new": 2.0 + 0.2 * rng.gauss(0, 1),
            "objective/kl_rollout_old": 0.5 + 0.1 * rng.gauss(0, 1),
        })
        assert hm.verdict == OK, (i, hm.snapshot()["rules"])
    assert hm.trips == 0


def test_warmup_gates_firing():
    # a collapse INSIDE the warmup window must not fire (the 2-update CI
    # smoke never reaches warmup=8 observations per metric)
    hm = HealthMonitor(HealthConfig(warmup=8))
    for i in range(7):
        hm.observe(i, {REWARD: 1.0 if i < 4 else 0.0})
    assert hm.verdict == OK


def test_rate_rule_queue_starvation():
    clock = {"t": 0.0}
    hm = HealthMonitor(HealthConfig(warmup=4, window_s=60.0),
                       clock=lambda: clock["t"])
    wait = 0.0
    for i in range(12):
        clock["t"] += 1.0
        wait += 0.95            # starved: waiting ~0.95 s per wall second
        hm.observe(i, {"orchestrator/consumer_wait_s": wait})
    assert hm.snapshot()["rules"]["queue_starvation"] == CRIT


def test_disabled_monitor_is_noop():
    hm = HealthMonitor(HealthConfig(enabled=False))
    assert hm.observe(1, {REWARD: float("nan")}) == {}
    assert hm.gauges() == {}
    assert hm.verdict == OK


def test_monitor_journal_restore_roundtrip():
    hm = HealthMonitor(HealthConfig(warmup=4))
    rng = random.Random(3)
    for i in range(30):
        hm.observe(i, {REWARD: 1.0 + 0.05 * rng.random(),
                       "policy/entropy_avg_new": 2.0})
    j = hm.journal()
    hm2 = HealthMonitor(HealthConfig(warmup=4))
    hm2.restore(j)
    assert hm2.journal() == j
    # the restored monitor keeps scoring from the learned baselines
    for i in range(30, 38):
        hm.observe(i, {REWARD: 0.0})
        hm2.observe(i, {REWARD: 0.0})
    assert hm2.verdict == hm.verdict == CRIT


# ---------------------------------------------------------------------------
# Prometheus rendering + exporter (jax-free)
# ---------------------------------------------------------------------------


def test_render_prometheus_sanitizes_and_validates():
    text = render_prometheus({
        "perf/mfu": 0.42,
        "health/rule_kl-blowup": 1,
        "weird key!": float("nan"),
        "inf": float("inf"),
        "skipped": "not-a-number",
    })
    assert validate_prometheus_text(text) == []
    assert "nanorlhf_perf_mfu 0.42" in text
    assert "# TYPE nanorlhf_perf_mfu gauge" in text
    assert "nanorlhf_weird_key_ NaN" in text
    assert "nanorlhf_inf +Inf" in text
    assert "skipped" not in text


def test_prometheus_validator_rejects_torn_payloads():
    assert validate_prometheus_text("") == ["no samples"]
    assert validate_prometheus_text("nanorlhf_x 1.0\nnanorlhf_y 2.")[0:0] == []
    assert validate_prometheus_text("9bad_name 1.0") != []
    assert validate_prometheus_text("nanorlhf_x one") != []
    assert validate_prometheus_text("nanorlhf_x 1.0\nnanorlhf_y") != []


def test_exporter_port0_disabled_noop():
    ex = StatusExporter(0, metrics_fn=lambda: {"a": 1.0})
    assert not ex.enabled and ex.port == 0
    ex.close()
    ex.close()  # idempotent


def test_exporter_endpoints_and_healthz_flip():
    hm = HealthMonitor(HealthConfig(warmup=4))
    for i in range(12):
        hm.observe(i, {REWARD: 1.0})
    ex = StatusExporter(-1, metrics_fn=lambda: {"perf/mfu": 0.1, "step": 7},
                        statusz_fn=lambda: {"step": 7}, health=hm)
    try:
        url = f"http://127.0.0.1:{ex.port}"
        body = _get(url + "/metrics")
        assert validate_prometheus_text(body) == []
        assert "nanorlhf_perf_mfu" in body
        assert "nanorlhf_health_verdict 0.0" in body
        assert _get(url + "/healthz").strip() == "ok"
        assert json.loads(_get(url + "/statusz"))["step"] == 7
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(url + "/nope")
        assert e.value.code == 404
        # reward collapse flips /healthz to 503 (the live-verdict seam)
        for i in range(12, 20):
            hm.observe(i, {REWARD: 0.0})
        assert hm.verdict == CRIT
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(url + "/healthz")
        assert e.value.code == 503
        assert e.value.read().decode().strip() == "crit"
        # /metrics keeps serving (503 is /healthz-only semantics)
        assert "nanorlhf_health_verdict 2.0" in _get(url + "/metrics")
    finally:
        ex.close()
    # close() released the port: connections now fail
    with pytest.raises(Exception):
        _get(f"http://127.0.0.1:{ex.port}/healthz", timeout=1)


# ---------------------------------------------------------------------------
# end-to-end: 2-update CPU train under concurrent scrape (the CI smoke)
# ---------------------------------------------------------------------------


# slow: excluded from the tier-1 sweep's wall budget; the named health-smoke
# CI step runs this file without the marker filter, so it still gates CI.
@pytest.mark.slow
def test_train_serves_endpoints_under_concurrent_scrape(tmp_path):
    trainer = make_trainer(
        AlgoName.GRPO, tmp_path, total_episodes=32, telemetry=True,
        rollout_orchestrator=True, rollout_workers=2, max_staleness=2,
        sampler_logprob_capture=True, status_port=-1,
    )
    port = trainer.exporter.port
    assert port > 0
    results, errors = [], []
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                body = _get(f"http://127.0.0.1:{port}/metrics")
                if body.strip():  # pre-first-update scrapes are empty
                    probs = validate_prometheus_text(body)
                    assert probs == [], probs
                sz = json.loads(_get(f"http://127.0.0.1:{port}/statusz"))
                results.append((body, sz))
            except Exception as e:  # noqa: BLE001 - collected for the assert
                errors.append(repr(e))

    threads = [threading.Thread(target=hammer) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        state = trainer.train()
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert state["global_step"] == 2
    # no torn/invalid payloads or handler errors across the whole run
    assert errors == [], errors[:3]
    assert results
    body, sz = results[-1]
    # Prometheus text carries perf/* and health/* gauges
    assert "nanorlhf_perf_mfu" in body
    assert "nanorlhf_perf_peak_flops_known 0.0" in body  # CPU: untrusted
    assert "nanorlhf_health_verdict" in body
    # /statusz carries queue + fleet state from the orchestrator seam
    assert sz["step"] == 2
    assert sz["queue"]["version"] >= 1
    assert "queue_depth" in sz["queue"]
    assert len(sz["fleet"]["workers"]) == 2
    assert "leases" in sz["fleet"]
    assert sz["health"]["verdict"] == OK   # 2 updates < warmup: never fires
    assert sz["mfu_trusted"] is False      # CPU peak-FLOPs is nominal
    # logger satellites: latest() snapshot + monotonic t_mono stamps
    latest = trainer.logger.latest()
    assert latest["step"] == 2 and "t_mono" in latest
    rows = [json.loads(l) for l in
            open(tmp_path / "grpo" / "metrics.jsonl")]
    t_monos = [r["t_mono"] for r in rows if "t_mono" in r]
    assert len(t_monos) >= 2 and t_monos == sorted(t_monos)
    assert all(r["perf/peak_flops_known"] == 0.0
               for r in rows if "perf/peak_flops_known" in r)
    # health journal rode the checkpoint
    tstate = trainer.ckpt.load_trainer_state(2)
    assert tstate["health"]["rows"] == 2
    trainer.close()
    # clean shutdown released the port
    with pytest.raises(Exception):
        _get(f"http://127.0.0.1:{port}/healthz", timeout=1)


@pytest.mark.slow  # see note above: runs in the named health-smoke CI step
def test_health_journal_resumes(tmp_path):
    tr1 = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=32)
    tr1.train()
    j1 = tr1.health.journal()
    assert j1["rows"] == 2 and j1["aggregates"]
    tr1.close()
    tr2 = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=32)
    tr2.resume_from_checkpoint()
    j2 = tr2.health.journal()
    tr2.close()
    # restored baselines match the saved monitor (rates re-warm by design
    # and are not journaled; everything here is)
    assert j2 == tr1.ckpt.load_trainer_state(2)["health"]
    assert j2["rows"] == j1["rows"]
    assert j2["aggregates"].keys() == j1["aggregates"].keys()
    assert j2["aggregates"][REWARD] == j1["aggregates"][REWARD]
