"""HF-format checkpoint export (core/params.export_hf_checkpoint):
`save_model` parity — the trained output is a checkpoint transformers (and
our own loader) accept, with LoRA folded in (`GRPO/grpo_trainer.py:321-341`).
"""

import dataclasses

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params, padded_forward_logits
from nanorlhf_tpu.core.lora import LoraConfig, init_lora_params, merge_lora
from nanorlhf_tpu.core.params import export_hf_checkpoint, load_hf_checkpoint


def _tiny(bias=True):
    cfg = ModelConfig.qwen2_tiny(vocab_size=256)
    return cfg if bias else dataclasses.replace(
        cfg, attention_bias=False, rope_theta=500000.0
    )


def test_roundtrip_with_lora_merge(tmp_path):
    cfg = _tiny()
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    params["lora"] = init_lora_params(
        cfg, LoraConfig(r=4), jax.random.PRNGKey(1), jnp.float32
    )
    # make B nonzero so the merge actually changes weights
    params["lora"] = jax.tree.map(
        lambda x: x + 0.01, params["lora"]
    )
    out = export_hf_checkpoint(cfg, params, str(tmp_path / "ck"),
                               lora_scale=2.0, dtype="float32")
    cfg2, params2 = load_hf_checkpoint(out, dtype=jnp.float32)
    assert cfg2.attention_bias and cfg2.vocab_size == 256

    ids = jnp.asarray(np.random.default_rng(0).integers(2, 256, (2, 8)),
                      jnp.int32)
    want = padded_forward_logits(merge_lora(params, 2.0), cfg, ids, 0)
    got = padded_forward_logits(params2, cfg2, ids, 0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("bias", [True, False])
def test_transformers_loads_export(tmp_path, bias):
    """The exported dir must load through transformers AND score identically
    — the actual handoff contract (HF/vLLM users of the trained model)."""
    from transformers import AutoModelForCausalLM

    cfg = _tiny(bias)
    params = init_params(cfg, jax.random.PRNGKey(2), jnp.float32)
    out = export_hf_checkpoint(cfg, params, str(tmp_path / "ck"),
                               dtype="float32")
    model = AutoModelForCausalLM.from_pretrained(out).eval().to(torch.float32)
    assert model.config.model_type == ("qwen2" if bias else "llama")

    ids = np.random.default_rng(1).integers(2, 256, (2, 10))
    mask = np.ones_like(ids)
    pos = np.cumsum(mask, axis=1) - 1
    with torch.no_grad():
        want = model(
            input_ids=torch.from_numpy(ids),
            attention_mask=torch.from_numpy(mask),
            position_ids=torch.from_numpy(pos),
        ).logits.numpy()
    from nanorlhf_tpu.core import model_forward

    got = np.asarray(model_forward(
        params, cfg, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(pos)
    ))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_bf16_export_loads(tmp_path):
    cfg = _tiny()
    params = init_params(cfg, jax.random.PRNGKey(3), jnp.bfloat16)
    out = export_hf_checkpoint(cfg, params, str(tmp_path / "ck"))
    cfg2, params2 = load_hf_checkpoint(out)
    leaf = params2["layers"]["q_proj"]["kernel"]
    assert leaf.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(leaf, np.float32),
        np.asarray(params["layers"]["q_proj"]["kernel"], np.float32),
    )


def test_trainer_export_model(tmp_path):
    """RLTrainer.export_model: train a step, export, reload, score parity
    with the live (merged) policy."""
    import sys
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from test_trainer_smoke import make_trainer
    from nanorlhf_tpu.trainer import AlgoName

    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=16, save_steps=0)
    tr.train(num_updates=1)
    out = tr.export_model(str(tmp_path / "hf"), dtype="float32")
    cfg2, params2 = load_hf_checkpoint(out, dtype=jnp.float32)

    ids = jnp.asarray(np.random.default_rng(2).integers(
        2, tr.mcfg.vocab_size, (2, 8)), jnp.int32)
    want = padded_forward_logits(
        merge_lora(tr.params, tr.lora_scale), tr.mcfg, ids, 0
    )
    got = padded_forward_logits(params2, cfg2, ids, 0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_export_hf_dir_config(tmp_path):
    """export_hf_dir: the full run leaves an HF checkpoint behind."""
    import os
    import sys
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from test_trainer_smoke import make_trainer
    from nanorlhf_tpu.trainer import AlgoName

    hf_dir = str(tmp_path / "handoff")
    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=16,
                      save_steps=0, export_hf_dir=hf_dir)
    tr.train()
    assert os.path.exists(os.path.join(hf_dir, "model.safetensors"))
    cfg2, _ = load_hf_checkpoint(hf_dir)
    assert cfg2.vocab_size == tr.mcfg.vocab_size


def test_export_writes_generation_config(tmp_path):
    """eos/pad ids from the tokenizer reach config.json +
    generation_config.json — without them, transformers/vLLM generation on
    the exported dir never terminates."""
    import json as _json

    from nanorlhf_tpu.data import ToyTokenizer

    cfg = _tiny()
    tok = ToyTokenizer(vocab_size=256)
    params = init_params(cfg, jax.random.PRNGKey(4), jnp.float32)
    out = export_hf_checkpoint(cfg, params, str(tmp_path / "ck"),
                               dtype="float32", tokenizer=tok)
    gen = _json.load(open(out + "/generation_config.json"))
    hfc = _json.load(open(out + "/config.json"))
    assert gen["eos_token_id"] == tok.eos_token_id == hfc["eos_token_id"]
    assert gen["pad_token_id"] == tok.pad_token_id
