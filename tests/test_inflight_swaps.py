"""In-flight mid-sequence weight swaps (ISSUE 20, docs/ORCHESTRATOR.md
§in-flight swaps).

Pins the acceptance contract from both ends:

- degenerate cases are AIRTIGHT: per-segment IS weights with a single
  segment / all-zero ages equal `truncated_is_weights` bit-exactly
  through the token AND sequence loss paths; a swaps-enabled trainer at
  staleness 0 (where no mid-rollout publish can exist) reproduces the
  swaps-off run over BOTH fleet transports (in-process and loopback RPC)
  with zero installs and exactly one segment per sample;
- the mechanism is REAL: a forced 2-publish generation stamps >= 2
  segments on the rows alive at the swap points, every row's segments
  exactly tile [0, n_generated) with strictly increasing versions, and a
  >= 2-segment batch's per-segment loss DIFFERS from the whole-sequence
  clamp (the correction is not a no-op);
- the plumbing honors its contracts: `_finalize_segments` drops empty
  spans, `make_swap_refresh` counts versions monotonically through the
  `swap.stale` delay fault, and the trainer validation rejects swaps
  without the orchestrator / the queued paged scheduler.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nanorlhf_tpu.algos.losses import (
    grpo_loss,
    ppo_clip_loss_sequence,
    ppo_clip_loss_token,
    segment_is_weights,
    truncated_is_weights,
)
from nanorlhf_tpu.orchestrator.weight_store import (
    VersionedWeightStore,
    make_swap_refresh,
    store_poll,
)
from nanorlhf_tpu.resilience.faults import FaultInjector
from nanorlhf_tpu.sampler import SamplingParams, generate
from nanorlhf_tpu.sampler.paged.scheduler import _finalize_segments
from nanorlhf_tpu.trainer import AlgoName

from test_paged_cache import EOS, PAD, _chain_model, _chain_prompts
from test_trainer_smoke import make_trainer

STREAM_KEYS = ("eval_objective/scores_old", "objective/entropy_old",
               "objective/kl_rollout_old")


def _metric_rows(outdir):
    rows = []
    with open(outdir / "metrics.jsonl") as f:
        for line in f:
            row = json.loads(line)
            if "episode" in row:
                rows.append(row)
    return rows


# --------------------------------------------------------------------- #
# per-segment IS: bit-exact degenerate reduction, real multi-segment diff
# --------------------------------------------------------------------- #

def _logprob_fixture(B=3, T=10, seed=0):
    rng = np.random.default_rng(seed)
    new = jnp.asarray(rng.normal(-1.2, 0.5, (B, T)).astype(np.float32))
    old = jnp.asarray(rng.normal(-1.1, 0.5, (B, T)).astype(np.float32))
    beh = jnp.asarray(rng.normal(-1.3, 0.5, (B, T)).astype(np.float32))
    ref = jnp.asarray(rng.normal(-1.0, 0.5, (B, T)).astype(np.float32))
    adv = jnp.asarray(rng.normal(0.0, 1.0, (B, T)).astype(np.float32))
    mask = jnp.asarray(rng.random((B, T)) < 0.8)
    return new, old, beh, ref, adv, mask


def test_single_segment_weights_bitexact():
    """All-zero ages (no swap landed) must reduce BIT-EXACTLY to the
    whole-sequence truncated-IS weights — not merely allclose."""
    _, old, beh, _, _, _ = _logprob_fixture()
    ages = jnp.zeros(old.shape, jnp.int32)
    w_seg, t_seg = segment_is_weights(old, beh, ages, 2.0)
    w_who, t_who = truncated_is_weights(old, beh, 2.0)
    assert np.array_equal(np.asarray(w_seg), np.asarray(w_who))
    assert np.array_equal(np.asarray(t_seg), np.asarray(t_who))


def test_single_segment_losses_bitexact():
    """segment_ages=zeros vs segment_ages=None through every loss that
    takes the knob: token PPO-clip, GRPO, and the sequence (RLOO) path —
    loss AND aux identical to the bit."""
    new, old, beh, ref, adv, mask = _logprob_fixture()
    ages = jnp.zeros(old.shape, jnp.int32)

    for fn, args in (
        (ppo_clip_loss_token, (new, old, adv, mask, 0.2)),
        (grpo_loss, (new, old, ref, adv, mask, 0.2, 0.05)),
        (ppo_clip_loss_sequence, (new, old, adv[:, 0], mask, 0.2)),
    ):
        base, base_aux = fn(*args, behavior_logprobs=beh, is_truncation=2.0)
        seg, seg_aux = fn(*args, behavior_logprobs=beh, is_truncation=2.0,
                          segment_ages=ages)
        assert np.array_equal(np.asarray(base), np.asarray(seg)), fn.__name__
        for k in base_aux:
            assert np.array_equal(
                np.asarray(base_aux[k]), np.asarray(seg_aux[k])
            ), f"{fn.__name__} aux {k}"


def test_multi_segment_loss_differs_from_whole_sequence():
    """A >= 2-segment row whose raw ratios exceed the tighter per-segment
    cap must produce a DIFFERENT loss than the whole-sequence clamp — the
    correction is real. old − behavior = 1.0 per token → raw ratio
    e ≈ 2.72 > ρ̄ = 2.0 everywhere; the age-1 segment clamps at
    ρ̄^(1/2) ≈ 1.414 instead of 2.0."""
    B, T = 2, 8
    new = jnp.full((B, T), -1.0, jnp.float32)
    old = jnp.full((B, T), -1.0, jnp.float32)
    beh = old - 1.0
    ref = jnp.full((B, T), -1.1, jnp.float32)
    adv = jnp.ones((B, T), jnp.float32)
    mask = jnp.ones((B, T), bool)
    ages = jnp.asarray(
        np.repeat([[0, 0, 0, 0, 1, 1, 1, 1]], B, axis=0), jnp.int32)

    w_seg, _ = segment_is_weights(old, beh, ages, 2.0)
    np.testing.assert_allclose(np.asarray(w_seg[:, :4]), 2.0, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(w_seg[:, 4:]), 2.0 ** 0.5,
                               rtol=1e-6)

    for fn, args in (
        (ppo_clip_loss_token, (new, old, adv, mask, 0.2)),
        (grpo_loss, (new, old, ref, adv, mask, 0.2, 0.05)),
        (ppo_clip_loss_sequence, (new, old, adv[:, 0], mask, 0.2)),
    ):
        whole, _ = fn(*args, behavior_logprobs=beh, is_truncation=2.0)
        seg, _ = fn(*args, behavior_logprobs=beh, is_truncation=2.0,
                    segment_ages=ages)
        assert float(whole) != float(seg), fn.__name__


def test_sequence_segment_weight_factorizes_over_segments():
    """The sequence path's weight must be the PRODUCT of per-segment
    clamped sub-ratios (each segment's summed diff clamped at its own
    ρ̄_a), with pad-tail runs contributing exactly 1."""
    old = jnp.asarray([[-1.0, -1.5, -0.5, -2.0, -1.0, 0.0]], jnp.float32)
    beh = jnp.asarray([[-1.2, -1.1, -0.9, -2.1, -1.4, 0.0]], jnp.float32)
    new = old
    adv = jnp.ones((1,), jnp.float32)
    mask = jnp.asarray([[True, True, True, True, True, False]])
    ages = jnp.asarray([[0, 0, 1, 1, 2, 2]], jnp.int32)
    rho = 1.5

    _, aux = ppo_clip_loss_sequence(
        new, old, adv, mask, 0.2, behavior_logprobs=beh,
        is_truncation=rho, segment_ages=ages)
    d = np.asarray(old - beh)[0]
    expected = 1.0
    for lo, hi, age in ((0, 2, 0), (2, 4, 1), (4, 5, 2)):
        expected *= min(np.exp(d[lo:hi].sum()), rho ** (1.0 / (1.0 + age)))
    np.testing.assert_allclose(
        float(aux["is_weight_mean"]), expected, rtol=1e-5)


# --------------------------------------------------------------------- #
# _finalize_segments: exact tiling, empty spans dropped
# --------------------------------------------------------------------- #

def test_finalize_segments_tiles_and_drops_empty():
    # plain 2-swap row
    assert _finalize_segments([(0, 0), (1, 5), (3, 9)], 12) == [
        {"policy_version": 0, "tok_range": [0, 5]},
        {"policy_version": 1, "tok_range": [5, 9]},
        {"policy_version": 3, "tok_range": [9, 12]},
    ]
    # swaps landing before the row's first token AND after it finished:
    # the empty spans drop, the survivor still tiles [0, total)
    assert _finalize_segments([(0, 0), (1, 0), (2, 4), (3, 4)], 4) == [
        {"policy_version": 1, "tok_range": [0, 4]},
    ]
    # zero-length generation collapses to one stamped span
    assert _finalize_segments([(2, 0)], 0) == [
        {"policy_version": 2, "tok_range": [0, 0]}]


# --------------------------------------------------------------------- #
# make_swap_refresh: base install, monotone versions, swap.stale delay
# --------------------------------------------------------------------- #

def test_swap_refresh_base_install_and_monotone_versions():
    store = VersionedWeightStore()
    refresh = make_swap_refresh(store_poll(store))
    # unpublished store: nothing to install, no crash
    v, tree = refresh()
    assert v < 0 and tree is None
    store.publish({"w": 0})
    # have_version=None: the FIRST hit returns latest outright (base
    # install, uncounted by the caller)
    v, tree = refresh()
    assert (v, tree) == (0, {"w": 0})
    # held version is newest -> None until the next publish
    assert refresh() == (0, None)
    store.publish({"w": 1})
    assert refresh() == (1, {"w": 1})

    # have_version=v seed (fleet workers know their dispatch version):
    # same-version polls install nothing
    r2 = make_swap_refresh(store_poll(store), have_version=1)
    assert r2() == (1, None)
    store.publish({"w": 2})
    assert r2() == (2, {"w": 2})


def test_swap_stale_fault_delays_but_keeps_versions_increasing():
    """The swap.stale delay action sleeps then installs the (possibly
    superseded) tree anyway; the NEXT sync point installs the newer one —
    installed versions stay strictly increasing."""
    import time as _time

    store = VersionedWeightStore()
    store.publish({"w": 0})
    inj = FaultInjector.from_spec("swap.stale:every=1,delay=0.05,count=1")
    refresh = make_swap_refresh(store_poll(store), have_version=0,
                                faults=inj, worker=0)
    store.publish({"w": 1})
    t0 = _time.perf_counter()
    v1, tree1 = refresh()
    stalled = _time.perf_counter() - t0
    assert (v1, tree1) == (1, {"w": 1})
    assert stalled >= 0.05  # the fault really stalled the install
    # a publish that raced the stall lands at the NEXT poll, version up
    store.publish({"w": 2})
    assert refresh() == (2, {"w": 2})
    assert refresh() == (2, None)


# --------------------------------------------------------------------- #
# forced mid-decode swaps: segments tile, versions increase, bits equal
# --------------------------------------------------------------------- #

def test_forced_two_swaps_segments_tile_generation():
    """Two forced publishes mid-decode (same tree, so the greedy stream is
    bit-identical to the refresh-free run): every queue entry's segments
    exactly tile [0, n_generated) with strictly increasing versions, the
    long row alive at both swap points carries 3 segments, and a row
    admitted after the last swap starts at the newest version."""
    cfg, params = _chain_model()
    # greedy lengths 20, 4, 14, 3 (start v -> 31 - v tokens incl. EOS)
    starts = [11, 27, 17, 28]
    ids, mask = _chain_prompts(starts)
    sp = SamplingParams(greedy=True, max_tokens=24, page_size=4,
                        decode_rows=2)

    calls = {"n": 0, "v": 0}

    def refresh():
        # call 1 is the scheduler's pre-loop base poll; calls 2 and 3 are
        # the first two decode-chunk sync points (the host chunk spans
        # several tokens), with the start-11 and start-17 rows resident
        calls["n"] += 1
        if calls["n"] in (2, 3):
            calls["v"] += 1
            return calls["v"], params
        return calls["v"], None

    stats = []
    out = np.asarray(generate(
        params, cfg, ids, mask, jax.random.PRNGKey(0), sp,
        eos_token_id=EOS, pad_token_id=PAD, paged_stats_out=stats,
        weight_refresh=refresh))
    ref = np.asarray(generate(
        params, cfg, ids, mask, jax.random.PRNGKey(0), sp,
        eos_token_id=EOS, pad_token_id=PAD))
    np.testing.assert_array_equal(out, ref)

    st = stats[0]
    assert st["swap_installs"] == 2
    assert st["swap_wait_s"] >= 0.0
    segments = st["segments"]
    assert len(segments) == len(starts)
    for q, segs in enumerate(segments):
        n_gen = int(np.sum(out[q] != PAD))
        # exact tiling of [0, n_generated)
        assert segs[0]["tok_range"][0] == 0
        assert segs[-1]["tok_range"][1] == n_gen
        for a, b in zip(segs, segs[1:]):
            assert a["tok_range"][1] == b["tok_range"][0]
        for s in segs:
            assert s["tok_range"][1] > s["tok_range"][0]
        versions = [s["policy_version"] for s in segs]
        assert versions == sorted(set(versions)), versions  # strictly inc
    # the 20-token row rode through both installs
    assert len(segments[0]) == 3
    assert [s["policy_version"] for s in segments[0]] == [0, 1, 2]
    assert sum(1 for segs in segments if len(segs) >= 2) >= 2
    # the last-admitted short row started life on the newest weights
    assert segments[3] == [{"policy_version": 2, "tok_range": [
        0, int(np.sum(out[3] != PAD))]}]


# --------------------------------------------------------------------- #
# multi-turn env driver: swaps at re-admission, silent poll bit-identical
# --------------------------------------------------------------------- #

def _run_env(refresh, greedy=True):
    from nanorlhf_tpu.envs.rollout import run_env_episodes
    from test_envs import EchoEnv, _driver_prompts, _tiny_model, text_reward

    tok, mcfg, params = _tiny_model()
    ids, mask = _driver_prompts(tok, 2, 8)
    env = EchoEnv(text_reward, max_turns=2)
    env.eos_token = tok.eos_token
    sampling = SamplingParams(max_tokens=12, temperature=1.0, n=2,
                              greedy=greedy)
    try:
        return params, run_env_episodes(
            params, mcfg, ids, mask, jax.random.PRNGKey(7), sampling, env,
            eos_token_id=tok.eos_token_id, pad_token_id=tok.pad_token_id,
            tokenizer=tok, max_turns=2, turn_tokens=12, obs_budget=8,
            response_length=40, page_size=4, decode_rows=2,
            weight_refresh=refresh,
        )
    finally:
        env.close()


def test_env_driver_silent_poll_bit_identical_and_swap_segments():
    """The multi-turn episode driver honors the same contract: a refresh
    that never reports a newer version leaves the packed episode streams
    bit-identical to weight_refresh=None (single segment per episode),
    and one that publishes after turn 1 stamps a second segment at the
    re-admission boundary in packed response-token coordinates — the
    coordinate space the `turns` records share."""
    _, base = _run_env(None)
    _, silent = _run_env(lambda: (0, None))
    np.testing.assert_array_equal(base["tokens"], silent["tokens"])
    assert silent["swap_installs"] == 0
    assert all(len(s) == 1 for s in silent["segments"])

    calls = {"n": 0}

    def hot():
        # call 1 = base install; call 2 lands at the first main-loop sync,
        # after turn 1 but with continuation turns still to decode
        calls["n"] += 1
        if calls["n"] == 2:
            return 1, hot.params
        return min(calls["n"] - 1, 1), None

    # bind after _run_env hands us params (same tree -> same tokens)
    from test_envs import _tiny_model
    hot.params = _tiny_model()[2]
    params, out = _run_env(hot)
    np.testing.assert_array_equal(base["tokens"], out["tokens"])
    assert out["swap_installs"] == 1
    assert len(out["segments"]) == base["tokens"].shape[0]
    multi = [s for s in out["segments"] if len(s) >= 2]
    assert multi, out["segments"]
    for segs in out["segments"]:
        for a, b in zip(segs, segs[1:]):
            assert a["tok_range"][1] == b["tok_range"][0]
            assert b["policy_version"] > a["policy_version"]


# --------------------------------------------------------------------- #
# trainer: swaps-on at staleness 0 is bit-identical to swaps-off, both
# transports; validation rejects unsupported compositions
# --------------------------------------------------------------------- #

@pytest.fixture(scope="module")
def paged_fleet_rows(tmp_path_factory):
    """Baseline: orchestrated 2-worker fleet over the queued paged rollout
    path, swaps OFF."""
    out = tmp_path_factory.mktemp("swapbase")
    tr = make_trainer(AlgoName.GRPO, out, total_episodes=32, save_steps=0,
                      rollout_orchestrator=True, rollout_workers=2,
                      max_staleness=0, rollout_page_size=4,
                      rollout_decode_rows=4)
    tr.train()
    tr.close()
    return _metric_rows(out / "grpo")


@pytest.mark.parametrize("transport", ["inprocess", "rpc"])
def test_swaps_on_staleness0_bit_identical(tmp_path, paged_fleet_rows,
                                           transport):
    """rollout_inflight_swaps=True at max_staleness=0: no publish can land
    mid-rollout (the producer gate serializes publish → dispatch), so the
    poll returns None at every chunk and the run must reproduce the
    swaps-off stream over BOTH transports — with the swap metrics rows
    present, zero installs, and exactly one segment per sample."""
    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=32,
                      save_steps=0, rollout_orchestrator=True,
                      rollout_workers=2, max_staleness=0,
                      rollout_page_size=4, rollout_decode_rows=4,
                      rollout_transport=transport,
                      rollout_inflight_swaps=True)
    tr.train()
    tr.close()
    rows = _metric_rows(tmp_path / "grpo")
    assert len(rows) == len(paged_fleet_rows) == 2
    for a, b in zip(paged_fleet_rows, rows):
        for key in STREAM_KEYS + ("loss/policy_avg_new",):
            np.testing.assert_allclose(
                a[key], b[key], rtol=1e-5,
                err_msg=f"{transport}: swaps-on staleness-0 {key} "
                        f"diverged from swaps-off",
            )
    for row in rows:
        assert row["rollout/swap_installs"] == 0.0
        assert row["rollout/segments_per_sample"] == 1.0
        assert row["orchestrator/swap_wait_s"] == 0.0


def test_swaps_config_validation(tmp_path):
    with pytest.raises(ValueError, match="rollout_orchestrator"):
        make_trainer(AlgoName.GRPO, tmp_path / "a",
                     rollout_inflight_swaps=True)
    with pytest.raises(ValueError, match="rollout_page_size"):
        make_trainer(AlgoName.GRPO, tmp_path / "b",
                     rollout_orchestrator=True, rollout_inflight_swaps=True)
