"""int8 KV cache: quantized-cache decode path + q8 kernel vs oracle."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.core.model import _quantize_kv, init_kv_cache
from nanorlhf_tpu.ops.decode_attention import (
    decode_attention_q8,
    reference_decode_attention,
    reference_decode_attention_q8,
)
from nanorlhf_tpu.sampler import SamplingParams, generate
from nanorlhf_tpu.trainer import AlgoName

from test_trainer_smoke import make_trainer


def test_q8_kernel_matches_dequant_oracle():
    """Same quantized inputs → the Pallas q8 kernel (interpret on CPU) and
    the dequantize-then-exact XLA oracle agree tightly."""
    B, H, KV, T, d = 4, 8, 2, 512, 64
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (B, H, d), jnp.float32)
    k = jax.random.normal(ks[1], (B, KV, T, d), jnp.float32)
    v = jax.random.normal(ks[2], (B, KV, T, d), jnp.float32)
    k_q, k_s = _quantize_kv(k)
    v_q, v_s = _quantize_kv(v)
    start = jnp.asarray([0, 37, 128, 255], jnp.int32)
    filled = jnp.asarray([T, T - 9, T - 64, 300], jnp.int32)
    out = decode_attention_q8(q, k_q, k_s, v_q, v_s, start, filled,
                              block_k=128, interpret=True)
    ref = reference_decode_attention_q8(q, k_q, k_s, v_q, v_s, start, filled)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_q8_oracle_close_to_exact():
    """Dequantized-cache attention approximates exact-cache attention to
    int8-noise level (the end-to-end error the sampler absorbs)."""
    B, H, KV, T, d = 2, 4, 2, 128, 64
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (B, H, d), jnp.float32)
    k = jax.random.normal(ks[1], (B, KV, T, d), jnp.float32)
    v = jax.random.normal(ks[2], (B, KV, T, d), jnp.float32)
    k_q, k_s = _quantize_kv(k)
    v_q, v_s = _quantize_kv(v)
    start = jnp.zeros((B,), jnp.int32)
    filled = jnp.full((B,), T, jnp.int32)
    approx = reference_decode_attention_q8(q, k_q, k_s, v_q, v_s, start, filled)
    exact = reference_decode_attention(q, k, v, start, filled)
    rel = float(jnp.max(jnp.abs(approx - exact))
                / (jnp.max(jnp.abs(exact)) + 1e-6))
    assert rel < 0.05, rel


def test_init_kv_cache_quant_shapes():
    cfg = dataclasses.replace(ModelConfig.qwen2_tiny(), kv_cache_quant="int8")
    caches = init_kv_cache(cfg, batch=3, max_len=16)
    assert len(caches) == 4
    k_q, k_s, v_q, v_s = caches
    assert k_q.dtype == jnp.int8 and k_s.dtype == jnp.bfloat16
    assert k_q.shape == (2, 3, 2, 16, cfg.actual_head_dim)
    assert k_s.shape == (2, 3, 2, 8, 16)


def test_generate_with_quant_cache_close_to_exact():
    """Greedy generate through the quantized cache (CPU dequant fallback)
    mostly matches the exact cache — int8 KV noise may flip near-ties."""
    mcfg = ModelConfig.qwen2_tiny(vocab_size=128)
    qcfg = dataclasses.replace(mcfg, kv_cache_quant="int8")
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    ids = jnp.asarray([[0, 5, 6, 7], [0, 9, 8, 7]])
    mask = ids != 0
    sp = SamplingParams(greedy=True, max_tokens=8)
    out_e = np.asarray(generate(params, mcfg, ids, mask, jax.random.PRNGKey(1),
                                sp, eos_token_id=-1, pad_token_id=0))
    out_q = np.asarray(generate(params, qcfg, ids, mask, jax.random.PRNGKey(1),
                                sp, eos_token_id=-1, pad_token_id=0))
    agree = (out_e == out_q).mean()
    assert agree >= 0.75, (agree, out_e, out_q)


def test_trainer_kv_quant_smoke(tmp_path):
    trainer = make_trainer(
        AlgoName.GRPO, tmp_path, total_episodes=32, save_steps=0,
        kv_cache_quant="int8",
    )
    assert trainer._rollout_mcfg.kv_cache_quant == "int8"
    assert trainer.mcfg.kv_cache_quant == "none"
    state = trainer.train()
    assert state["global_step"] == 2
