"""Latency surface (nanorlhf_tpu/telemetry/hist.py + exporter.py + SLO
rules in health.py, docs/OBSERVABILITY.md §7) — the tier-1
`latency-smoke` CI gate:

- log-bucketed streaming histograms track exact percentiles within one
  bucket width on adversarial distributions (bimodal, heavy-tail,
  constant) and clamp under/overflow to the observed extremes;
- merge is EXACT bucket-wise addition: associative across 3 worker
  sketches, equal to recording every sample centrally, and scheme drift
  raises SchemeMismatch instead of merging garbage;
- the journal (`trainer_state.json` "latency") round-trips through JSON
  exactly and a resumed trainer restores the sketches bit-for-bit;
- `render_prometheus_histograms` emits valid exposition (the SHARED
  validate_prometheus_text check): monotone `_bucket{le=...}` series,
  the mandatory `le="+Inf"` bucket, `_sum`/`_count`;
- a synthetic queue-wait burst walks the p99 SLO rule OK→CRIT through
  the health plane, lands a blackbox dump, and respects the
  sample-count warmup; no attached hub means the rules stay OK;
- `tools/inspect_run.py --latency` reconstructs queue-wait/generation
  percentiles from the ledger ALONE and agrees with a live hub fed the
  same samples;
- a 2-update GRPO run with 2 rollout workers over the rpc transport
  serves Prometheus-valid TTFT/queue-wait histograms on /metrics whose
  `_count` equals the lineage ledger's generation/queue event counts.
"""

import json
import math
import os
import random
import re
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from nanorlhf_tpu.telemetry import (
    DEFAULT_RULES,
    HealthConfig,
    HealthMonitor,
    LatencyHub,
    LineageLedger,
    SLO_RULES,
    StreamingHistogram,
    percentiles_from_samples,
    read_ledger,
    render_prometheus_histograms,
    validate_prometheus_text,
)
from nanorlhf_tpu.telemetry.health import CRIT, OK, WARN
from nanorlhf_tpu.telemetry.hist import (
    EXPORT_EDGE_INDICES,
    HIST_BUCKETS,
    HIST_LO,
    SchemeMismatch,
    bucket_lower,
)
from nanorlhf_tpu.trainer import AlgoName

from test_trainer_smoke import make_trainer

TOOLS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools", "inspect_run.py")

# one log-bucket's relative width: 10^(1/32) - 1 ≈ 7.5% — the histogram's
# quantile error bound on a distribution with ties at the probed ranks
BUCKET_REL = 10 ** (1 / 32) - 1


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def _run_inspect(run_dir, *args):
    out = subprocess.run(
        [sys.executable, TOOLS, str(run_dir), *args, "--json"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    return json.loads(out.stdout.strip().splitlines()[-1])


def _clone(h):
    return StreamingHistogram.load(h.state())


# ---------------------------------------------------------------------------
# sketch mechanics (jax-free)
# ---------------------------------------------------------------------------


def test_export_edges_cover_half_decades():
    # the Prometheus edges run every half decade from 10 µs to 1000 s and
    # align with internal bucket boundaries (what makes cumulative counts
    # exact rather than resampled)
    edges = [bucket_lower(i) for i in EXPORT_EDGE_INDICES]
    assert edges[0] == pytest.approx(1e-5)
    assert edges[-1] == pytest.approx(1e3)
    for a, b in zip(edges, edges[1:]):
        assert b / a == pytest.approx(math.sqrt(10.0))


@pytest.mark.parametrize("dist", ["bimodal", "heavy_tail", "constant"])
def test_quantile_tracks_numpy_on_adversarial_distributions(dist):
    rng = random.Random(0)
    if dist == "bimodal":
        # 40/60 mix: the probed ranks land INSIDE a mode, not in the gap
        # (a rank exactly at the gap has no well-defined percentile to
        # within bucket width — no estimator beats the gap's span)
        xs = [abs(rng.gauss(0.002, 0.0003)) for _ in range(8000)]
        xs += [abs(rng.gauss(5.0, 0.5)) for _ in range(12000)]
    elif dist == "heavy_tail":
        xs = [math.exp(rng.gauss(-3.0, 2.0)) for _ in range(20000)]
    else:
        xs = [0.0123] * 5000
    h = StreamingHistogram()
    for x in xs:
        h.record(x)
    assert h.count == len(xs)
    if dist == "constant":
        # min == max: quantiles clamp to the single observed value exactly
        for q in (0.0, 0.5, 0.95, 0.99, 1.0):
            assert h.quantile(q) == 0.0123
        assert h.mean == pytest.approx(0.0123)
        return
    for q in (0.50, 0.95, 0.99):
        true = float(np.percentile(xs, 100 * q))
        got = h.quantile(q)
        assert abs(got - true) / true < 0.08, (dist, q, got, true)
    assert h.mean == pytest.approx(sum(xs) / len(xs))


def test_underflow_overflow_participate_and_clamp():
    h = StreamingHistogram()
    for v in (1e-9, 1e-8, 0.5, 2e4):
        h.record(v)
    assert h.count == 4
    # the out-of-range samples landed in the under/overflow buckets
    assert -1 in h.counts and HIST_BUCKETS in h.counts
    # extremes are tracked exactly and bound every quantile
    assert h.min == 1e-9 and h.max == 2e4
    assert h.quantile(1.0) == 2e4
    assert h.quantile(0.1) == pytest.approx(HIST_LO)  # underflow reports floor
    assert 0.4 < h.quantile(0.6) < 0.6                # the in-range sample
    # a NaN is a caller bug and must not poison the sketch
    h.record(float("nan"))
    assert h.count == 4
    # negative (impossible monotonic difference) clamps to zero, not a crash
    h.record(-1.0)
    assert h.count == 5 and h.min == 0.0


def test_merge_is_exact_and_associative():
    rng = random.Random(1)
    a, b, c = StreamingHistogram(), StreamingHistogram(), StreamingHistogram()
    central = StreamingHistogram()
    for h, mu in ((a, -6.0), (b, -2.0), (c, 1.0)):
        for _ in range(3000):
            v = math.exp(rng.gauss(mu, 1.0))
            h.record(v)
            central.record(v)
    ab_c = _clone(a).merge(_clone(b)).merge(_clone(c))
    a_bc = _clone(a).merge(_clone(b).merge(_clone(c)))
    for m in (ab_c, a_bc):
        # bucket counts and extremes are bit-identical to central recording
        assert m.counts == central.counts
        assert m.count == central.count == 9000
        assert (m.min, m.max) == (central.min, central.max)
        # quantiles depend only on counts + extremes → also bit-identical
        for q in (0.01, 0.5, 0.95, 0.999):
            assert m.quantile(q) == central.quantile(q)
        # float addition order can differ in the last ulp — that's the only
        # non-exactness merge allows
        assert m.sum == pytest.approx(central.sum, rel=1e-12)


def test_hub_merge_states_folds_worker_sketches():
    rng = random.Random(2)
    workers = [LatencyHub() for _ in range(3)]
    central = LatencyHub()
    for w in workers:
        for _ in range(500):
            v = math.exp(rng.gauss(-3.0, 1.0))
            w.record("latency/ttft_s", v)
            central.record("latency/ttft_s", v)
    coord1, coord2 = LatencyHub(), LatencyHub()
    for w in workers:
        coord1.merge_states(w.states())
    for w in reversed(workers):
        coord2.merge_states(w.states())
    assert coord1.count("latency/ttft_s") == \
        coord2.count("latency/ttft_s") == 1500
    for q in (0.5, 0.99):
        assert coord1.quantile("latency/ttft_s", q) == \
            coord2.quantile("latency/ttft_s", q) == \
            central.quantile("latency/ttft_s", q)
    # scheme drift rejects the merge instead of silently mixing boundaries
    bad = workers[0].states()
    bad["latency/ttft_s"]["scheme"] = [1e-6, 11, 32]
    with pytest.raises(SchemeMismatch):
        coord1.merge_states(bad)


def test_journal_roundtrips_through_json_exactly():
    hub = LatencyHub()
    rng = random.Random(3)
    for _ in range(200):
        hub.record("latency/queue_wait_s", math.exp(rng.gauss(-4.0, 1.5)))
        hub.record("latency/reward_s", rng.random())
    # through JSON — the exact trip trainer_state.json takes
    j = json.loads(json.dumps(hub.journal()))
    back = LatencyHub()
    back.restore(j)
    assert back.journal() == hub.journal()
    # the restored hub keeps recording on the same trajectory
    hub.record("latency/reward_s", 0.25)
    back.record("latency/reward_s", 0.25)
    assert back.journal() == hub.journal()
    # a journal from a different bucket scheme must refuse to load
    j["hists"]["latency/reward_s"]["scheme"] = [1e-9, 11, 32]
    with pytest.raises(SchemeMismatch):
        LatencyHub().restore(j)


def test_disabled_hub_is_a_noop():
    hub = LatencyHub(enabled=False)
    hub.record("latency/ttft_s", 1.0)
    hub.merge_states(LatencyHub().states())
    assert hub.names() == []
    assert hub.count("latency/ttft_s") == 0
    assert math.isnan(hub.quantile("latency/ttft_s", 0.5))
    assert hub.snapshot() == {} and hub.journal() == {"hists": {}}


def test_percentiles_from_samples_matches_numpy():
    rng = random.Random(4)
    xs = [rng.lognormvariate(-2.0, 1.0) for _ in range(1000)]
    d = percentiles_from_samples(xs)
    assert d["count"] == 1000
    for key, q in (("p50_s", 50), ("p95_s", 95), ("p99_s", 99)):
        assert d[key] == pytest.approx(float(np.percentile(xs, q)))
    assert d["min_s"] == min(xs) and d["max_s"] == max(xs)
    empty = percentiles_from_samples([])
    assert empty["count"] == 0 and empty["p99_s"] is None


# ---------------------------------------------------------------------------
# Prometheus histogram exposition
# ---------------------------------------------------------------------------


def test_render_prometheus_histograms_is_valid_exposition():
    hub = LatencyHub()
    rng = random.Random(5)
    for _ in range(400):
        hub.record("latency/ttft_s", math.exp(rng.gauss(-1.0, 1.0)))
        hub.record("latency/queue_wait_s", math.exp(rng.gauss(-5.0, 2.0)))
    text = render_prometheus_histograms(hub.states())
    assert validate_prometheus_text(text) == []
    for fam in ("nanorlhf_latency_ttft_s", "nanorlhf_latency_queue_wait_s"):
        assert f"# TYPE {fam} histogram" in text
        buckets = re.findall(
            rf'^{fam}_bucket{{le="([^"]+)"}} (\d+)$', text, re.M)
        assert buckets[-1][0] == "+Inf"
        cums = [int(c) for _, c in buckets]
        assert cums == sorted(cums)          # cumulative → monotone
        assert cums[-1] == 400
        m = re.search(rf"^{fam}_count (\d+)$", text, re.M)
        assert m and int(m.group(1)) == 400  # _count == le="+Inf" bucket
        assert re.search(rf"^{fam}_sum \S+$", text, re.M)
    # a torn/foreign state is skipped, never a scrape crash
    assert render_prometheus_histograms(
        {"latency/x_s": {"scheme": [1, 2, 3]}}) == ""
    assert render_prometheus_histograms({}) == ""


# ---------------------------------------------------------------------------
# SLO rules through the health plane
# ---------------------------------------------------------------------------


def test_queue_wait_burst_flips_p99_slo_ok_to_crit_with_blackbox():
    hub = LatencyHub()
    dumps = []
    mon = HealthMonitor(
        HealthConfig(rules=DEFAULT_RULES + SLO_RULES),
        blackbox_fn=lambda step, extra: dumps.append((step, extra)),
        latency=hub,
    )
    # warmup counts histogram SAMPLES (not metric rows): 15 pathological
    # waits are still below the 16-sample gate
    for _ in range(15):
        hub.record("latency/queue_wait_s", 120.0)
    rows = mon.observe(1, {})
    assert rows["health/rule_slo_queue_wait_p99"] == 0.0
    assert mon.verdict == OK and not dumps
    # the burst clears warmup: p99 ≈ 120 s >> crit 60 s → one trip,
    # one flight-recorder blackbox
    for _ in range(35):
        hub.record("latency/queue_wait_s", 120.0)
    rows = mon.observe(2, {})
    assert rows["health/rule_slo_queue_wait_p99"] == 2.0
    assert mon.verdict == CRIT and mon.trips == 1
    assert len(dumps) == 1
    step, extra = dumps[0]
    assert step == 2 and "slo_queue_wait_p99" in extra["rules"]


def test_slo_warn_band_and_no_hub_stays_ok():
    # 90 s p95 TTFT sits between warn (60) and crit (300)
    hub = LatencyHub()
    for _ in range(20):
        hub.record("latency/ttft_s", 90.0)
    mon = HealthMonitor(HealthConfig(rules=SLO_RULES), latency=hub)
    rows = mon.observe(1, {})
    assert rows["health/rule_slo_ttft_p95"] == 1.0
    assert mon.verdict == WARN
    # without an attached hub the SLO rules evaluate OK — the rule tuple
    # is safe on monitors that have no latency surface
    bare = HealthMonitor(HealthConfig(rules=SLO_RULES))
    rows = bare.observe(1, {})
    assert all(v == 0.0 for k, v in rows.items()
               if k.startswith("health/rule_slo_"))
    assert bare.verdict == OK


# ---------------------------------------------------------------------------
# registry: histogram metric shape
# ---------------------------------------------------------------------------


def test_registry_folds_histogram_suffixes_to_base_family():
    from nanorlhf_tpu.analysis.registry import hist_family

    base = "latency/ttft_s"
    for suffixed in (f'{base}_bucket{{le="0.01"}}',
                     f'{base}_bucket{{le="+Inf"}}',
                     f"{base}_bucket", f"{base}_sum", f"{base}_count"):
        assert hist_family(suffixed) == base
    # the base family maps to itself; non-latency keys are untouched even
    # with histogram-looking suffixes
    assert hist_family(base) == base
    assert hist_family("perf/mfu_count") == "perf/mfu_count"


# ---------------------------------------------------------------------------
# offline reconstruction (tools/inspect_run.py --latency)
# ---------------------------------------------------------------------------


def test_inspect_run_latency_matches_live_hub(tmp_path):
    # one synthetic run, two recording paths: the ledger's queue/generation
    # events and a live hub fed the SAME samples. The inspector's exact
    # percentiles and the hub's bucketed quantiles must agree to within
    # one bucket width. Values come from a small grid (ties at every
    # probed rank) so the exact percentile is well-defined.
    grid = [2e-4, 1e-3, 5e-3, 0.02, 0.1, 0.5, 2.0, 8.0]
    led = LineageLedger(str(tmp_path))
    hub = LatencyHub()
    rng = random.Random(7)
    base = 100.0
    for i in range(64):
        w = grid[rng.randrange(len(grid))]
        g = grid[rng.randrange(len(grid))]
        led.queue(i, enqueue_t=base, dequeue_t=base + w, staleness=0)
        led.generation(i, policy_version=1, worker_id=0, gen_s=round(g, 6))
        hub.record("latency/queue_wait_s", w)
        hub.record("latency/generation_s", g)
    led.close()
    rep = _run_inspect(tmp_path, "--latency")["latency"]
    for fam, key in (("queue_wait_s", "latency/queue_wait_s"),
                     ("generation_s", "latency/generation_s")):
        offline = rep[fam]
        assert offline["count"] == hub.count(key) == 64
        assert offline["min_s"] == pytest.approx(
            hub.snapshot()[key]["min_s"], abs=1e-6)
        assert offline["max_s"] == pytest.approx(
            hub.snapshot()[key]["max_s"], abs=1e-6)
        for pkey, q in (("p50_s", 0.50), ("p95_s", 0.95)):
            live = hub.quantile(key, q)
            assert abs(live - offline[pkey]) / offline[pkey] \
                <= BUCKET_REL + 1e-6, (fam, pkey, live, offline[pkey])


# ---------------------------------------------------------------------------
# trainer integration (the latency-smoke acceptance runs)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # runs in the named latency-smoke CI step
def test_fleet_rpc_histograms_join_ledger_and_serve_metrics(tmp_path):
    """ISSUE-13 acceptance: 2 rollout workers over the rpc transport, 2
    GRPO updates — /metrics serves Prometheus-valid TTFT and queue-wait
    histograms whose `_count` equals the lineage ledger's generation- and
    queue-event counts, and the inspector's offline view agrees with the
    live hub."""
    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=32,
                      save_steps=0, rollout_orchestrator=True,
                      rollout_workers=2, max_staleness=2,
                      rollout_transport="rpc", lineage=True,
                      status_port=-1)
    tr.train()
    # train() returned but the fleet keeps prefetching until the staleness
    # gate blocks it; wait for quiescence (counts stable across 3 reads)
    # so the scrape, the live hub, and the ledger all see the same events
    stable, prev = 0, (-1, -1)
    for _ in range(30):
        cur = (tr.latency.count("latency/ttft_s"),
               tr.latency.count("latency/queue_wait_s"))
        stable = stable + 1 if cur == prev else 0
        if stable >= 3:
            break
        prev = cur
        time.sleep(1.0)
    port = tr.exporter.port
    body = _get(f"http://127.0.0.1:{port}/metrics")
    statusz = json.loads(_get(f"http://127.0.0.1:{port}/statusz"))
    live_ttft = tr.latency.count("latency/ttft_s")
    live_qw = tr.latency.count("latency/queue_wait_s")
    snap = tr.latency.snapshot()
    tr.close()

    assert validate_prometheus_text(body) == []
    counts = {fam: int(n) for fam, n in re.findall(
        r"^nanorlhf_(latency_\w+)_count (\d+)$", body, re.M)}
    assert counts["latency_ttft_s"] == live_ttft > 0
    assert counts["latency_queue_wait_s"] == live_qw > 0
    assert 'nanorlhf_latency_ttft_s_bucket{le="+Inf"}' in body
    # cfg.latency (on by default) appended the SLO rules to the monitor
    assert "nanorlhf_health_rule_slo_ttft_p95" in body
    # /statusz carries the digest view of the same sketches
    assert statusz["latency"]["latency/ttft_s"]["count"] == live_ttft

    # the join: one TTFT observation per ledger generation event, one
    # queue-wait observation per ledger queue event
    events = list(read_ledger(str(tmp_path / "grpo")))
    gen_events = [ev for ev in events if ev["type"] == "generation"]
    queue_events = [ev for ev in events if ev["type"] == "queue"]
    assert live_ttft == len(gen_events)
    assert live_qw == len(queue_events)
    # the rpc transport's per-op RTT sketches recorded too
    assert any(n.startswith("latency/rpc_") for n in snap)
    # per-update phase splits landed as histograms
    assert snap["latency/phase_rollout_s"]["count"] >= 2

    # offline reconstruction from the ledger alone agrees with the live
    # hub: same event counts, same exact extremes (gen_s is journaled
    # rounded to 1 µs)
    rep = _run_inspect(tmp_path / "grpo", "--latency")["latency"]
    assert rep["generation_s"]["count"] == len(gen_events)
    assert rep["queue_wait_s"]["count"] == len(queue_events)
    assert rep["generation_s"]["max_s"] == pytest.approx(
        snap["latency/generation_s"]["max_s"], abs=1e-4)
    qw_live_p95 = tr.latency.quantile("latency/queue_wait_s", 0.95)
    assert rep["queue_wait_s"]["min_s"] - 1e-6 <= qw_live_p95 \
        <= rep["queue_wait_s"]["max_s"] + 1e-6


@pytest.mark.slow  # runs in the named latency-smoke CI step
def test_latency_journal_resumes_across_checkpoint(tmp_path):
    tr1 = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=32)
    tr1.train()
    tr1.close()
    # journaled beside "health"/"lineage" in trainer_state.json
    tstate = tr1.ckpt.load_trainer_state(2)
    j_ckpt = tstate["latency"]
    assert j_ckpt["hists"], "2 updates must journal latency sketches"
    assert "latency/phase_update_s" in j_ckpt["hists"]
    tr2 = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=64)
    tr2.resume_from_checkpoint()
    # bit-for-bit restore through the JSON journal
    assert tr2.latency.journal() == j_ckpt
    before = {n: tr2.latency.count(n) for n in tr2.latency.names()}
    tr2.train(num_updates=1)
    tr2.close()
    # the resumed run keeps accumulating into the restored sketches
    assert tr2.latency.count("latency/phase_update_s") > \
        before["latency/phase_update_s"]
    assert all(tr2.latency.count(n) >= c for n, c in before.items())


@pytest.mark.slow  # runs in the named latency-smoke CI step
def test_latency_off_disables_surface_and_slo_rules(tmp_path):
    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=32,
                      latency=False)
    tr.train()
    tr.close()
    assert not tr.latency.enabled
    assert tr.latency.names() == []
    # no SLO rules on the monitor when the surface is off
    assert all(not name.startswith("slo_")
               for name in tr.health.snapshot()["rules"])
    tstate = tr.ckpt.load_trainer_state(2)
    assert tstate.get("latency", {"hists": {}})["hists"] == {}
