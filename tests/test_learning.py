"""The RL loop actually optimizes: reward must improve on a learnable task.

The reference's de-facto validation is a rising reward curve (SURVEY.md §4);
this is the miniature, deterministic version: a tiny model + a reward that
prefers emitting EOS early is learnable within a few updates, so mean reward
over the last updates must beat the first update's.
"""

import json

import numpy as np

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
from nanorlhf_tpu.parallel import MeshConfig
from nanorlhf_tpu.trainer import RLConfig, AlgoName, RLTrainer


def test_grpo_reward_improves(tmp_path):
    tok = ToyTokenizer(128)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    cfg = RLConfig(
        algo=AlgoName.GRPO,
        output_dir=str(tmp_path / "learn"),
        response_length=8,
        temperature=1.0,
        sample_n=4,
        kl_coef=0.0,                 # pure reward maximization
        total_episodes=12 * 16,      # 12 updates × batch 16
        per_device_train_batch_size=1,
        gradient_accumulation_steps=1,
        num_mini_batches=2,
        num_ppo_epochs=1,
        learning_rate=5e-2,          # aggressive: tiny fp32 model, 24 steps
        logging_steps=1,
        num_printed_samples=0,
        use_lora=False,              # full fine-tune for fastest movement
        gradient_checkpointing=False,
        mesh=MeshConfig(-1, 1, 1),
        save_steps=0,
        load_best_model_at_end=False,
    )
    dataset = load_prompt_dataset("synthetic:64", tok, max_prompt_len=10)

    def reward(pmt_and_responses, eos_token):
        # dense, trivially learnable: reward repetition — the fraction of the
        # response taken by its most frequent token. Every sample carries
        # signal, so the group baseline gets real variance from update 1.
        out = []
        for s in pmt_and_responses:
            resp = s.split("<assistant>")[-1]
            words = resp.split()
            if not words:
                out.append(0.0)
                continue
            _, top = max(((w, words.count(w)) for w in set(words)), key=lambda kv: kv[1])
            out.append(top / len(words))
        return np.asarray(out, np.float32)

    trainer = RLTrainer(cfg, mcfg, tok, params, dataset, reward)
    trainer.train()

    lines = [
        json.loads(l)
        for l in open(tmp_path / "learn" / "metrics.jsonl")
        if "samples" not in l
    ]
    rewards = [l["eval_objective/rlhf_reward_old"] for l in lines]
    assert len(rewards) == 12
    early = float(np.mean(rewards[:2]))
    late = float(np.mean(rewards[-3:]))
    # observed trajectory: ~0.17 → ~0.75; the bar leaves wide seed margin
    assert late > early + 0.2, f"no learning: first2={early:.3f}, last3={late:.3f}, all={rewards}"
