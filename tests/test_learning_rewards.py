"""Unit tests for the learning-curve harness reward functions
(tools/learning_run.py): the shaped curriculum reward and the r1-contract
binary reward the phase-2 starvation experiment swaps in.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

from learning_run import build_corpus, make_binary_reward, make_reward  # noqa: E402

EOS = "</s>"


def _prompt(q):
    return f"<user> {q} <assistant>"


def test_binary_reward_is_binary():
    q = "What is 2 plus 3? Put the answer in \\boxed{}."
    fn = make_binary_reward({q: "5"})
    cases = [
        (_prompt(q) + " \\boxed{5} " + EOS, 1.0),       # exact → 1
        (_prompt(q) + " \\boxed{ 5 } " + EOS, 1.0),     # whitespace stripped
        (_prompt(q) + " \\boxed{6} " + EOS, 0.0),       # wrong → 0
        (_prompt(q) + " the answer is 5 " + EOS, 0.0),  # unboxed → 0
        (_prompt(q) + " 5 5 5 5 5", 0.0),               # digits alone → 0
    ]
    out = fn([s for s, _ in cases], EOS)
    np.testing.assert_array_equal(out, [e for _, e in cases])


def test_binary_reward_no_partial_credit():
    """Unlike the shaped reward, format alone must score zero."""
    q = "What is 10 plus 1? Put the answer in \\boxed{}."
    shaped = make_reward({q: "11"})
    binary = make_binary_reward({q: "11"})
    boxed_wrong = _prompt(q) + " \\boxed{99} " + EOS
    assert shaped([boxed_wrong], EOS)[0] > 0.0   # format credit exists
    assert binary([boxed_wrong], EOS)[0] == 0.0  # none here


def test_shaped_reward_components():
    q = "What is 4 plus 4? Put the answer in \\boxed{}."
    fn = make_reward({q: "8"})
    # digit-density only
    digits_only = _prompt(q) + " 1 2 3 4"
    r_digits = fn([digits_only], EOS)[0]
    assert 0.9 <= r_digits <= 1.0  # 4/4 digit tokens
    # + boxed + correct + eos stacks toward the max
    full = _prompt(q) + " \\boxed{8} " + EOS
    r_full = fn([full], EOS)[0]
    assert r_full > r_digits
    assert r_full >= 1.5  # 0.5 format + 1.0 correct + 0.25 eos at least


def test_shaped_scores_response_only():
    """Prompt digits must not leak into the density term."""
    q = "What is 40 plus 41? Put the answer in \\boxed{}."
    fn = make_reward({q: "81"})
    no_digit_resp = _prompt(q) + " hello world"
    assert fn([no_digit_resp], EOS)[0] == 0.0


def test_build_corpus_answers_consistent():
    class Tok:  # build_corpus only threads the tokenizer through
        pass

    texts, answers = build_corpus(Tok(), 64, seed=3)
    assert len(texts) == 64
    for t in texts:
        assert t in answers
        a, b = [int(x) for x in t.split("?")[0].split() if x.isdigit()]
        assert answers[t] == str(a + b)
