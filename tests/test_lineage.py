"""Sample lineage ledger (nanorlhf_tpu/telemetry/lineage.py,
docs/OBSERVABILITY.md §6) — the tier-1 `lineage-smoke` CI gate:

- the ledger rotates at max_bytes, keeps one monotonic event-index stream
  across rotation AND resume, and `read_ledger` replays every event in
  write order (tolerating a truncated tail);
- deterministic per-index sampling gates WHOLE chains (never individual
  events) while drop-reason counters stay exact;
- `lineage/dropped_total{reason=...}` rows survive render_prometheus with
  labels intact and pass the shared validate_prometheus_text check;
- a 2-update GRPO run with cfg.lineage on yields a complete
  lease→generation→reward→outcome chain for every consumed rollout index,
  keeps full-text samples OUT of metrics.jsonl, journals "lineage" beside
  "health" in trainer_state.json, and `tools/inspect_run.py --drops`
  reproduces the drop histogram from the ledger alone;
- the fleet path (2 workers, one injected worker.crash) adds queue-transit
  events and stamps BOTH worker ids on the reassigned lease;
- every sparse-GRPO-excluded row carries exactly one machine-readable
  drop_reason.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from nanorlhf_tpu.telemetry import (
    LineageLedger,
    chains,
    drop_histogram,
    read_ledger,
    render_prometheus,
    validate_prometheus_text,
)
from nanorlhf_tpu.trainer import AlgoName

from test_trainer_smoke import make_trainer

TOOLS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools", "inspect_run.py")


# ---------------------------------------------------------------------------
# ledger mechanics (jax-free)
# ---------------------------------------------------------------------------


def test_ledger_rotation_and_monotonic_indices(tmp_path):
    led = LineageLedger(str(tmp_path), max_bytes=2048)
    for i in range(200):
        led.event("generation", i, policy_version=i, blob="x" * 64)
    led.close()
    files = sorted(os.listdir(tmp_path / "lineage"))
    assert len(files) > 1, "2048-byte cap must have rotated"
    assert files[0] == "ledger_00000.jsonl"
    events = list(read_ledger(str(tmp_path)))
    assert len(events) == 200
    idx = [ev["i"] for ev in events]
    assert idx == sorted(idx) == list(range(200))  # monotonic, gapless


def test_ledger_resume_appends_not_restarts(tmp_path):
    led1 = LineageLedger(str(tmp_path), max_bytes=10**9)
    for i in range(5):
        led1.event("lease", i)
    j = led1.journal()
    led1.close()
    # a fresh ledger in the same dir + restored journal continues the stream
    led2 = LineageLedger(str(tmp_path), max_bytes=10**9)
    led2.restore(j)
    led2.event("lease", 5)
    led2.close()
    events = list(read_ledger(str(tmp_path)))
    idx = [ev["i"] for ev in events]
    assert idx == list(range(6))  # no restart at 0, no clobbered file


def test_sampling_gates_whole_chains_but_counts_all_drops(tmp_path):
    led = LineageLedger(str(tmp_path), sample_rate=0.5, rows_hint=4)
    n_in = 0
    for i in range(100):
        a = led.event("lease", i)
        b = led.event("outcome", i)
        led.drop(i, "stale_drop")
        # whole-chain property: both events share one gate decision
        assert (a >= 0) == (b >= 0) == led.sampled(i)
        n_in += a >= 0
    assert 0 < n_in < 100  # the gate actually split the population
    # counters are exact regardless of sampling; rows_hint denominates
    assert led.drop_counts == {"stale_drop": 400}
    # per-row drops count 1 each
    led.drop(None, "sparse_zero_advantage", row=3)
    assert led.drop_counts["sparse_zero_advantage"] == 1
    led.close()
    # disabled ledger: every call a no-op, nothing on disk
    off = LineageLedger(str(tmp_path / "off"), enabled=False)
    assert off.event("lease", 1) == -1
    assert off.drop(1, "stale_drop") == -1
    assert not os.path.exists(tmp_path / "off" / "lineage")


def test_ledger_never_raises_after_close(tmp_path):
    led = LineageLedger(str(tmp_path))
    led.event("lease", 0)
    led.close()
    assert led.event("lease", 1) == -1  # counted, not raised
    led.close()                         # idempotent


def test_metric_rows_render_prometheus_labels(tmp_path):
    led = LineageLedger(str(tmp_path))
    led.drop(0, "sparse_zero_advantage", count=3)
    led.drop(1, "fleet_late_duplicate")
    text = render_prometheus({**led.metric_rows(), "perf/mfu": 0.41})
    led.close()
    validate_prometheus_text(text)
    assert ('lineage_dropped_total{reason="sparse_zero_advantage"} 3'
            in text)
    assert 'lineage_dropped_total{reason="fleet_late_duplicate"} 1' in text
    # one TYPE line for the labeled family, not one per label value
    assert text.count("# TYPE nanorlhf_lineage_dropped_total gauge") == 1


def test_drop_histogram_and_chains_readers(tmp_path):
    led = LineageLedger(str(tmp_path))
    led.lease(7, lease_id=1, worker_id=0, cursor=7, length=1)
    led.generation(7, policy_version=2, worker_id=0)
    led.drop(7, "keep_filter", count=2)
    led.close()
    events = list(read_ledger(str(tmp_path)))
    assert drop_histogram(events) == {"keep_filter": 2}
    by = chains(events)
    assert set(by[7].keys()) == {"lease", "generation", "drop"}
    # the segments schema hook: single-policy whole-range default
    assert by[7]["generation"][0]["segments"] == [
        {"policy_version": 2, "tok_range": [0, None]}
    ]


# ---------------------------------------------------------------------------
# trainer integration (the lineage-smoke acceptance runs)
# ---------------------------------------------------------------------------


def _run_inspect(run_dir, *args):
    out = subprocess.run(
        [sys.executable, TOOLS, str(run_dir), *args, "--json"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.mark.slow  # runs in the named lineage-smoke CI step
def test_grpo_run_complete_chains_and_inspector(tmp_path):
    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=32,
                      lineage=True)
    tr.train()
    statusz_drops = dict(tr.lineage.statusz()["drop_reasons"])
    tr.close()
    run_dir = tmp_path / "grpo"
    events = list(read_ledger(str(run_dir)))
    by_index = chains(events)
    consumed = {ev["rollout_index"] for ev in events
                if ev["type"] == "outcome"}
    assert consumed, "2 updates must consume rollouts"
    for idx in consumed:
        # serial path: lease (stream dispatch) → generation → reward →
        # outcome; no queue events without an orchestrator
        for etype in ("lease", "generation", "reward", "outcome"):
            assert etype in by_index[idx], (idx, sorted(by_index[idx]))
        rwd = by_index[idx]["reward"][0]
        assert rwd["attempt"] >= 1 and rwd["wall_s"] >= 0
        assert rwd["scores"], "per-sample scores on the reward event"
        assert by_index[idx]["lease"][0]["key_path"]  # PRNG fold-in path
    # GRPO sample_n=2: keep-1-of-N drops every other completion
    hist = drop_histogram(events)
    assert hist.get("keep_filter", 0) > 0
    # the inspector reproduces the histogram from the ledger alone, and it
    # matches the live /statusz counters
    assert _run_inspect(run_dir, "--drops")["drops"] == hist == statusz_drops
    # --index renders a chain; --worst reads full-text sample events
    some = sorted(consumed)[0]
    assert "lease" in _run_inspect(run_dir, "--index", str(some))
    worst = _run_inspect(run_dir, "--worst", "2")["worst"]
    assert worst and all("response" in r for r in worst)
    # satellite 1: metrics.jsonl carries ONLY metric rows — no full text
    for line in open(run_dir / "metrics.jsonl"):
        row = json.loads(line)
        assert "query" not in row and "response" not in row
    # full text went to the ledger instead
    assert any(ev["type"] == "sample" and ev.get("response") is not None
               for ev in events)


@pytest.mark.slow  # runs in the named lineage-smoke CI step
def test_lineage_journal_resumes_monotonic(tmp_path):
    tr1 = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=32,
                       lineage=True)
    tr1.train()
    j1 = tr1.lineage.journal()
    tr1.close()
    assert j1["event_index"] > 0
    # journaled beside "health" in trainer_state.json
    tstate = tr1.ckpt.load_trainer_state(2)
    assert "health" in tstate and tstate["lineage"]["event_index"] == \
        j1["event_index"]
    tr2 = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=64,
                       lineage=True)
    tr2.resume_from_checkpoint()
    assert tr2.lineage.journal()["event_index"] == j1["event_index"]
    tr2.train(num_updates=1)
    tr2.close()
    # one gapless monotonic stream across both processes
    idx = [ev["i"] for ev in read_ledger(str(tmp_path / "grpo"))]
    assert idx == sorted(idx) and len(idx) == len(set(idx))
    assert max(idx) >= j1["event_index"]  # the resumed run appended


@pytest.mark.slow  # runs in the named lineage-smoke CI step
def test_fleet_crash_chains_and_reassigned_lease_worker_ids(tmp_path):
    """ISSUE-9 acceptance: 2 rollout workers, one injected worker.crash —
    every consumed index still has a complete lease→generation→queue→
    reward→outcome chain, and the reassigned lease's event pair carries
    both worker ids."""
    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=48,
                      save_steps=0, rollout_orchestrator=True,
                      rollout_workers=2, max_staleness=0,
                      fault_spec="worker.crash:at=1,worker=0",
                      lineage=True)
    tr.train()
    tr.close()
    run_dir = tmp_path / "grpo"
    events = list(read_ledger(str(run_dir)))
    by_index = chains(events)
    consumed = {ev["rollout_index"] for ev in events
                if ev["type"] == "outcome"}
    assert consumed
    for idx in consumed:
        for etype in ("lease", "generation", "queue", "reward", "outcome"):
            assert etype in by_index[idx], (idx, sorted(by_index[idx]))
        q = by_index[idx]["queue"][0]
        assert q["staleness"] == 0  # max_staleness=0 run
    # the crashed lease was re-granted: the index's lease events carry the
    # original worker AND the replacement
    reassigned = [ev for ev in events if ev["type"] == "lease"
                  and ev.get("reassigned_from") is not None]
    assert reassigned, "worker.crash must produce a reassigned lease event"
    ev = reassigned[0]
    assert ev["reassigned_from"] == 0 and ev["worker_id"] != 0
    first_grant = [
        e for e in by_index[ev["rollout_index"]]["lease"]
        if e.get("reassigned_from") is None
    ]
    assert first_grant and first_grant[0]["worker_id"] == 0
    # inspector round-trip on the fleet ledger too
    assert _run_inspect(run_dir, "--drops")["drops"] == \
        drop_histogram(events)


@pytest.mark.slow  # runs in the named lineage-smoke CI step
def test_sparse_grpo_every_dropped_row_has_one_reason(tmp_path):
    """The paper's silent zero-advantage skip, attributed: kept rows +
    sparse-dropped rows partition each consumed batch, and no row carries
    two drop reasons."""
    import jax
    import jax.numpy as jnp

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
    from nanorlhf_tpu.parallel import MeshConfig
    from nanorlhf_tpu.trainer import RLConfig
    from nanorlhf_tpu.trainer.sparse_grpo import SparseGRPOTrainer

    tok = ToyTokenizer(vocab_size=256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    dataset = load_prompt_dataset("synthetic:64", tok, max_prompt_len=12)
    cfg = RLConfig(
        algo=AlgoName.GRPO,
        output_dir=str(tmp_path / "sparse"),
        response_length=8, temperature=1.0, sample_n=2,
        total_episodes=32, per_device_train_batch_size=1,
        gradient_accumulation_steps=2, num_mini_batches=2,
        num_ppo_epochs=1, learning_rate=1e-4, kl_coef=0.0,
        use_lora=True, lora_r=4, lora_alpha=8,
        gradient_checkpointing=False, mesh=MeshConfig(-1, 1, 1),
        save_steps=1, report_to="jsonl", lineage=True,
    )
    rng = np.random.default_rng(0)
    n = cfg.sample_n

    def reward(pmt_and_responses, eos_token):
        # even prompt groups score uniformly (zero group z-advantage →
        # sparse-dropped); odd groups vary (kept)
        out = np.zeros(len(pmt_and_responses), np.float32)
        for i in range(len(out)):
            g = i // n
            out[i] = 0.5 if g % 2 == 0 else float(rng.random())
        return out

    tr = SparseGRPOTrainer(cfg, mcfg, tok, params, dataset, reward)
    tr.train()
    tr.close()
    events = list(read_ledger(str(tmp_path / "sparse")))
    by_index = chains(events)
    outcomes = [ev for ev in events if ev["type"] == "outcome"]
    assert outcomes, "varied odd groups must yield at least one update"
    for out_ev in outcomes:
        idx = out_ev["rollout_index"]
        row_drops = [ev for ev in by_index[idx].get("drop", [])
                     if ev.get("row") is not None]
        rows = [ev["row"] for ev in row_drops]
        # exactly one reason per dropped row
        assert len(rows) == len(set(rows)), rows
        assert all(ev["reason"] == "sparse_zero_advantage"
                   for ev in row_drops)
        # kept + dropped partition the post-keep batch
        batch_rows = out_ev["kept"] + len(rows)
        assert out_ev["kept"] >= 1 and len(rows) >= 1
        # and keep-1-of-N dropped the other (n-1) completions per prompt
        kf = [ev for ev in by_index[idx]["drop"]
              if ev["reason"] == "keep_filter"]
        assert sum(ev["count"] for ev in kf) == batch_rows * (n - 1)
    # the statusz counter agrees with the ledger
    hist = drop_histogram(events)
    assert hist.get("sparse_zero_advantage", 0) >= 1


@pytest.mark.slow  # runs in the named lineage-smoke CI step
def test_statusz_serves_lineage_section(tmp_path):
    import urllib.request

    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=32,
                      lineage=True, status_port=-1)
    tr.train()
    port = tr.exporter.port
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/statusz", timeout=5) as r:
        statusz = json.loads(r.read().decode())
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
        metrics_text = r.read().decode()
    tr.close()
    lin = statusz["lineage"]
    assert lin["enabled"] and lin["events"] > 0
    assert lin["drop_reasons"].get("keep_filter", 0) > 0
    assert lin["recent"], "last-N sample ring must be populated"
    validate_prometheus_text(metrics_text)
    assert "lineage_events_total" in metrics_text
    assert 'lineage_dropped_total{reason="keep_filter"}' in metrics_text
