"""Traffic harness + autoscaling control loop (nanorlhf_tpu/loadgen/,
docs/TRAFFIC.md, ISSUE 16).

Pins the acceptance contract:

- workload replay: same seed + spec is a BIT-identical request sequence
  (requests_digest equality, plus a hard-coded digest pin — the sampler
  is pure 64-bit integer math, so the digest is platform-stable); seed
  and spec sensitivity; Poisson and bursty arrivals monotone from 0;
  prefix groups actually share prefixes;
- autoscaler hysteresis under a fake clock: no flapping on an
  oscillating verdict, cooldown respected, min/max bounds enforced,
  queue-depth leading trigger;
- drain-then-remove on a real (jax-free, fake-dispatch) fleet: a
  drained worker's in-flight lease completes on that worker (nothing
  stranded, nothing reassigned) while abrupt removal still reassigns;
- the open-loop driver against the real in-process ServingEngine:
  request conservation, per-reason shed counters, client-TTFT hub rows,
  `traffic`/`traffic_run` lineage events;
- end-to-end: saturate the engine -> CRIT SLO verdict -> autoscaler
  add_worker on the fleet -> sustained recovery -> drain-remove back to
  the floor, every decision a lineage `autoscale` event;
- `tools/inspect_run.py --traffic` rebuilds offered/goodput/shed + the
  autoscale decision list from the ledger alone (CLI, jax-free).

CI runs this file as the `traffic-smoke` tier-1 step under
NANORLHF_LOCK_CHECK=1 — loadgen.driver/loadgen.autoscaler rank at the
front of the declared LOCK_ORDER, so every actuate-under-lock call is
order-checked live.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.loadgen import (
    Autoscaler,
    AutoscalerConfig,
    TrafficDriver,
    WorkloadSpec,
    requests_digest,
    sample_requests,
    slo_level_from_monitor,
    spec_digest,
)
from nanorlhf_tpu.orchestrator import FleetConfig, FleetOrchestrator
from nanorlhf_tpu.serving.engine import ServingEngine
from nanorlhf_tpu.telemetry.health import (
    CRIT,
    OK,
    HealthConfig,
    HealthMonitor,
    HealthRule,
)
from nanorlhf_tpu.telemetry.hist import LatencyHub
from nanorlhf_tpu.telemetry.lineage import LineageLedger, read_ledger

EOS, PAD = 3, 0

TOOLS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools", "inspect_run.py")


# --------------------------------------------------------------------- #
# workload replay discipline (jax-free)
# --------------------------------------------------------------------- #

def test_replay_bit_identical_and_digest_pinned():
    spec = WorkloadSpec(seed=7, n_requests=32, rate_rps=20.0,
                        arrival="bursty")
    a, b = sample_requests(spec), sample_requests(spec)
    assert a == b                       # frozen dataclasses: full equality
    assert requests_digest(a) == requests_digest(b)
    # pure splitmix64 integer math end to end — the digest is stable
    # across platforms and sessions, so pin it (a drift here means the
    # sampling stream changed and every recorded spec_digest is invalid)
    assert requests_digest(a) == "94ae405ac382b949"
    assert spec_digest(spec) == "acbbd7d142cfcba1"


def test_replay_sensitivity():
    base = WorkloadSpec(seed=7, n_requests=32, rate_rps=20.0)
    assert (requests_digest(sample_requests(base))
            != requests_digest(sample_requests(
                WorkloadSpec(seed=8, n_requests=32, rate_rps=20.0))))
    # any spec field participates in the digest (rate changes arrivals)
    assert (spec_digest(base)
            != spec_digest(WorkloadSpec(seed=7, n_requests=32,
                                        rate_rps=21.0)))


@pytest.mark.parametrize("arrival", ["poisson", "bursty"])
def test_arrival_offsets_monotone_from_zero(arrival):
    spec = WorkloadSpec(seed=3, n_requests=64, rate_rps=50.0,
                        arrival=arrival)
    reqs = sample_requests(spec)
    assert len(reqs) == 64
    offs = [r.t_offset for r in reqs]
    assert offs[0] >= 0.0
    assert offs == sorted(offs)
    assert all(reqs[i].index == i for i in range(len(reqs)))


def test_prefix_groups_share_prefixes():
    spec = WorkloadSpec(seed=5, n_requests=64, rate_rps=50.0,
                        prefix_groups=3, prefix_frac=0.6, prefix_len=4,
                        prompt_len_min=5, prompt_len_max=10)
    reqs = sample_requests(spec)
    grouped = [r for r in reqs if r.prefix_group >= 0]
    # ~60% of 64 requests join a tenant group
    assert len(grouped) >= 20
    by_group: dict = {}
    for r in grouped:
        by_group.setdefault(r.prefix_group, []).append(r)
    for members in by_group.values():
        prefixes = {m.tokens[:4] for m in members}
        assert len(prefixes) == 1       # group members share the prefix
        for m in members:
            assert len(m.tokens) >= 5   # at least one unique tail token
    # loners don't all collapse onto one group's prefix
    assert len(by_group) >= 2


def test_spec_validation():
    with pytest.raises(ValueError):
        WorkloadSpec(rate_rps=0.0).validate()
    with pytest.raises(ValueError):
        WorkloadSpec(arrival="uniform").validate()
    with pytest.raises(ValueError):
        WorkloadSpec(prompt_len_min=8, prompt_len_max=4).validate()


# --------------------------------------------------------------------- #
# autoscaler hysteresis (fake clock, fake actuators)
# --------------------------------------------------------------------- #

class _FakeFleet:
    """Actuator stub: monotonic ids like FleetOrchestrator's."""

    def __init__(self, n=1):
        self.ids = list(range(n))
        self.next_id = n
        self.removed: list = []

    def add(self):
        wid = self.next_id
        self.next_id += 1
        self.ids.append(wid)
        return wid

    def remove(self, wid):
        self.ids.remove(wid)
        self.removed.append(wid)


def _controller(fleet, level_fn, cfg, clock, depth_fn=None, lineage=None):
    return Autoscaler(
        add_worker=fleet.add, remove_worker=fleet.remove,
        worker_ids=lambda: list(fleet.ids), slo_level=level_fn,
        queue_depth=depth_fn, config=cfg, clock=clock, lineage=lineage)


def test_no_flap_under_oscillating_verdict():
    """A verdict that alternates crit/ok every tick never accumulates
    `breach_evals=2` consecutive breaches NOR `recovery_evals=4`
    consecutive healthy ticks from above the floor — zero actions."""
    fleet = _FakeFleet(n=1)
    t = [0.0]
    tick = [0]

    def level():
        return CRIT if tick[0] % 2 == 0 else OK

    asc = _controller(
        fleet, level,
        AutoscalerConfig(min_workers=1, max_workers=3, breach_evals=2,
                         recovery_evals=4, cooldown_s=0.0),
        clock=lambda: t[0])
    for _ in range(50):
        asc.evaluate()
        tick[0] += 1
        t[0] += 1.0
    m = asc.metrics()
    assert m["loadgen/scale_ups"] == 0
    assert m["loadgen/scale_downs"] == 0
    assert fleet.ids == [0]


def test_cooldown_respected_and_counted():
    fleet = _FakeFleet(n=1)
    t = [0.0]
    asc = _controller(
        fleet, lambda: CRIT,
        AutoscalerConfig(min_workers=1, max_workers=3, breach_evals=1,
                         recovery_evals=1, cooldown_s=10.0),
        clock=lambda: t[0])
    actions = []
    for _ in range(15):
        actions.append(asc.evaluate())
        t[0] += 1.0
    # one up immediately, then held until the cooldown elapses, then the
    # second up, then bounded at max_workers
    assert actions[0] == "scale_up"
    assert actions.count("scale_up") == 2
    first, second = (i for i, a in enumerate(actions) if a == "scale_up")
    assert second - first >= 10
    assert "hold_cooldown" in actions[first + 1:second]
    assert asc.metrics()["loadgen/holds_cooldown"] >= 1


def test_min_max_bounds_enforced():
    fleet = _FakeFleet(n=1)
    t = [0.0]
    level = [CRIT]
    asc = _controller(
        fleet, lambda: level[0],
        AutoscalerConfig(min_workers=1, max_workers=2, breach_evals=1,
                         recovery_evals=1, cooldown_s=0.0),
        clock=lambda: t[0])
    for _ in range(10):
        asc.evaluate()
        t[0] += 1.0
    assert fleet.ids == [0, 1]          # capped at max_workers
    level[0] = OK
    for _ in range(10):
        asc.evaluate()
        t[0] += 1.0
    assert fleet.ids == [0]             # floored at min_workers
    # scale-in removed the NEWEST worker (monotonic ids)
    assert fleet.removed == [1]


def test_queue_depth_leading_trigger():
    """Queue depth over `queue_high` counts as a breach while the SLO
    still reads OK — the leading indicator scales before TTFT degrades."""
    fleet = _FakeFleet(n=1)
    t = [0.0]
    depth = [100]
    asc = _controller(
        fleet, lambda: OK,
        AutoscalerConfig(min_workers=1, max_workers=2, breach_evals=2,
                         recovery_evals=99, cooldown_s=0.0, queue_high=8),
        clock=lambda: t[0], depth_fn=lambda: depth[0])
    a1, a2 = asc.evaluate(), asc.evaluate()
    assert (a1, a2) == ("hold", "scale_up")
    assert fleet.ids == [0, 1]


def test_config_validation():
    with pytest.raises(ValueError):
        AutoscalerConfig(min_workers=0).validate()
    with pytest.raises(ValueError):
        AutoscalerConfig(min_workers=3, max_workers=2).validate()
    with pytest.raises(ValueError):
        AutoscalerConfig(breach_level="fatal").validate()


# --------------------------------------------------------------------- #
# drain-then-remove on a real fake-dispatch fleet (jax-free)
# --------------------------------------------------------------------- #

def _fleet(n_workers=2, dispatch_s=0.05, n_batches=1000):
    batches = iter(range(n_batches))

    def dispatch(index, queries, tree, worker_id):
        time.sleep(dispatch_s)
        return {"index": index, "worker": worker_id}

    return FleetOrchestrator(
        dispatch_fn=dispatch, batch_fn=lambda: next(batches),
        initial_params={}, n_workers=n_workers, max_staleness=8,
        fleet=FleetConfig(poll_interval=0.02, lease_size=2),
    )


def test_drain_remove_never_strands_a_lease():
    orch = _fleet(n_workers=2, dispatch_s=0.05)
    try:
        orch.publish({})
        first = orch.get()              # both workers warmed + leased
        victim = first.payload["worker"]
        t0 = time.monotonic()
        drained = orch.remove_worker(victim, drain=True,
                                     drain_timeout_s=10.0)
        assert drained is True
        assert time.monotonic() - t0 < 10.0
        assert victim not in orch.coordinator.live_worker_ids()
        # the drained worker's in-flight lease COMPLETED on that worker:
        # nothing was revoked into the reassignment pool
        assert orch.coordinator.counters["reassigned_leases"] == 0
        assert orch.coordinator.counters["expired_leases"] == 0
        # the fleet still makes progress on the survivor, in index order
        seen = [orch.get().index for _ in range(4)]
        assert seen == sorted(seen)
    finally:
        orch.close()


def test_abrupt_remove_still_reassigns():
    orch = _fleet(n_workers=2, dispatch_s=0.2)
    try:
        orch.publish({})
        first = orch.get()
        victim = first.payload["worker"]
        orch.remove_worker(victim)      # default: abrupt, revoke + reassign
        deadline = time.monotonic() + 10.0
        while (orch.coordinator.counters["reassigned_leases"] == 0
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert orch.coordinator.counters["reassigned_leases"] >= 1
        seen = [orch.get().index for _ in range(4)]
        assert seen == sorted(seen)
    finally:
        orch.close()


def test_draining_worker_gets_no_new_lease():
    orch = _fleet(n_workers=2, dispatch_s=0.02)
    try:
        orch.publish({})
        orch.get()
        victim = orch.coordinator.live_worker_ids()[0]
        assert orch.coordinator.drain_worker(victim)
        assert orch.coordinator.wait_drained(victim, timeout=10.0)
        # the victim's PRE-drain leases are still queued (delivery is
        # index-ordered) — but draining stopped new grants, so its
        # backlog is bounded by the staleness window; past it, every
        # sample is the survivor's
        survivor = [w for w in orch.coordinator.live_worker_ids()
                    if w != victim]
        assert len(survivor) == 1
        tail = []
        for _ in range(24):
            orch.publish({})    # keep the staleness gate open
            tail.append(orch.get().payload["worker"])
        last_victim = max(
            (i for i, w in enumerate(tail) if w == victim), default=-1)
        assert last_victim < 20
        assert all(w == survivor[0] for w in tail[last_victim + 1:])
    finally:
        orch.close()


# --------------------------------------------------------------------- #
# open-loop driver against the real in-process engine
# --------------------------------------------------------------------- #

@pytest.fixture(scope="module")
def tiny():
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(7), jnp.float32)
    return config, params


def _engine(tiny, rows=2, max_queue=2, hub=None, slo_warn=1e9):
    config, params = tiny
    return ServingEngine(
        params, config, eos_token_id=EOS, pad_token_id=PAD, page_size=4,
        prompt_len=12, max_new_tokens=8, rows=rows, max_queue=max_queue,
        latency=hub, slo_warn_ttft_s=slo_warn, seed=0)


def test_driver_open_loop_inprocess(tiny, tmp_path):
    hub = LatencyHub()
    led = LineageLedger(str(tmp_path))
    spec = WorkloadSpec(seed=1, n_requests=16, rate_rps=500.0,
                        prompt_len_min=4, prompt_len_max=12,
                        token_lo=10, token_hi=50, greedy_frac=1.0,
                        prefix_groups=2, prefix_frac=0.5, prefix_len=4,
                        max_tokens_min=8, max_tokens_max=8)
    eng = _engine(tiny, rows=2, max_queue=2)
    try:
        driver = TrafficDriver(engine=eng, latency=hub, lineage=led,
                               stream_timeout_s=120.0)
        summary = driver.run(spec)
    finally:
        eng.close()
    # open loop conserves requests: offered = completed + shed + errors
    assert summary.offered == 16
    assert summary.completed + summary.shed + summary.errors == 16
    assert summary.errors == 0
    # 16 near-simultaneous arrivals into 2 rows + queue bound 2 MUST shed
    assert summary.shed >= 1
    assert set(summary.shed_reasons) <= {"queue_full", "slo_ttft_p95",
                                         "engine_abort"}
    # client-side hub rows: one TTFT and one total per completion
    assert hub.count("latency/client_ttft_s") == summary.completed
    assert hub.count("latency/client_total_s") == summary.completed
    # the engine's per-reason counters agree with the client's view
    m = eng.metrics()
    assert m["serving/shed"] == summary.shed
    assert sum(v for k, v in m.items()
               if k.startswith("serving/shed_total{")) == summary.shed
    dm = driver.metrics()
    assert dm["loadgen/offered"] == 16
    assert dm["loadgen/completed"] == summary.completed
    assert dm["loadgen/goodput_rps"] > 0
    # lineage: one run header + one event per request
    evs = list(read_ledger(str(tmp_path)))
    runs = [e for e in evs if e["type"] == "traffic_run"]
    fired = [e for e in evs if e["type"] == "traffic"]
    assert len(runs) == 1 and runs[0]["spec_digest"] == spec_digest(spec)
    assert len(fired) == 16
    assert ({e["request_index"] for e in fired} == set(range(16)))


def test_driver_requires_exactly_one_target():
    with pytest.raises(ValueError):
        TrafficDriver()
    with pytest.raises(ValueError):
        TrafficDriver(engine=object(), base_url="http://127.0.0.1:1")


# --------------------------------------------------------------------- #
# end-to-end: saturation -> CRIT -> scale up -> recovery -> drain down
# --------------------------------------------------------------------- #

def test_e2e_saturate_crit_scale_up_recover_drain_down(tiny, tmp_path):
    """The acceptance loop (ISSUE 16): drive the in-process engine past
    saturation, watch the SLO rule go CRIT on CLIENT TTFT, see the
    autoscaler add a fleet worker, then — after sustained recovery —
    drain-remove back to the floor, with every decision a lineage event."""
    led = LineageLedger(str(tmp_path))
    hub = LatencyHub()
    # client-TTFT SLO sized for the CPU rig: saturated queue waits are
    # tens of ms, healthy ones sub-ms synthetic
    rule = HealthRule("slo_ttft_p95", "latency/client_ttft_s",
                      "quantile_above", warn=0.002, crit=0.005,
                      warmup=4, quantile=0.95)
    monitor = HealthMonitor(
        HealthConfig(rules=(rule,), recovery_rows=2), latency=hub)

    orch = _fleet(n_workers=1, dispatch_s=0.01)
    asc = Autoscaler(
        add_worker=orch.add_worker,
        remove_worker=lambda wid: orch.remove_worker(
            wid, drain=True, drain_timeout_s=10.0),
        worker_ids=orch.coordinator.live_worker_ids,
        slo_level=lambda: slo_level_from_monitor(
            monitor, rules=("slo_ttft_p95",)),
        config=AutoscalerConfig(min_workers=1, max_workers=2,
                                breach_evals=2, recovery_evals=3,
                                cooldown_s=0.0),
        lineage=led)

    eng = _engine(tiny, rows=2, max_queue=4)
    try:
        # phase 1: saturate. 24 arrivals at 500 rps into 2 rows: queue
        # waits push client p95 TTFT far over crit=5ms
        spec = WorkloadSpec(seed=2, n_requests=24, rate_rps=500.0,
                            prompt_len_min=4, prompt_len_max=12,
                            token_lo=10, token_hi=50, greedy_frac=1.0,
                            max_tokens_min=8, max_tokens_max=8)
        driver = TrafficDriver(engine=eng, latency=hub, lineage=led,
                               stream_timeout_s=120.0)
        summary = driver.run(spec)
        assert summary.completed >= rule.warmup  # enough SLO samples
        for step in range(4):
            monitor.observe(step, {})
        assert slo_level_from_monitor(
            monitor, rules=("slo_ttft_p95",)) == CRIT

        actions = [asc.evaluate() for _ in range(3)]
        assert "scale_up" in actions
        assert len(orch.coordinator.live_worker_ids()) == 2

        # phase 2: recovery. Histograms are cumulative, so the verdict
        # recovers through the documented hub-swap seam: attach a fresh
        # hub (a new measurement window) carrying healthy client TTFTs.
        fresh = LatencyHub()
        for _ in range(rule.warmup + 2):
            fresh.record("latency/client_ttft_s", 0.0005)
        monitor.attach_latency(fresh)
        for step in range(4, 4 + monitor.cfg.recovery_rows + 2):
            monitor.observe(step, {})
        assert slo_level_from_monitor(
            monitor, rules=("slo_ttft_p95",)) == OK

        for _ in range(5):
            asc.evaluate()
        assert len(orch.coordinator.live_worker_ids()) == 1  # the floor
        assert asc.metrics()["loadgen/scale_downs"] == 1
        # the drained fleet never revoked a lease into reassignment
        assert orch.coordinator.counters["reassigned_leases"] == 0
    finally:
        eng.close()
        orch.close()

    # every scaling decision is a lineage event, in order
    evs = list(read_ledger(str(tmp_path)))
    scale = [e for e in evs if e["type"] == "autoscale"]
    assert [e["action"] for e in scale] == ["scale_up", "scale_down"]
    up, down = scale
    assert (up["workers_before"], up["workers_after"]) == (1, 2)
    assert (down["workers_before"], down["workers_after"]) == (2, 1)
    assert down["worker_id"] == up["worker_id"]  # newest drains out
    assert up["level"] == CRIT and down["level"] == OK


# --------------------------------------------------------------------- #
# offline reconstruction: inspect_run --traffic from the ledger alone
# --------------------------------------------------------------------- #

def test_inspect_run_traffic_from_ledger_alone(tiny, tmp_path):
    led = LineageLedger(str(tmp_path))
    spec = WorkloadSpec(seed=4, n_requests=12, rate_rps=200.0,
                        prompt_len_min=4, prompt_len_max=12,
                        token_lo=10, token_hi=50, greedy_frac=1.0,
                        max_tokens_min=8, max_tokens_max=8)
    eng = _engine(tiny, rows=2, max_queue=4)
    try:
        summary = TrafficDriver(engine=eng, lineage=led,
                                stream_timeout_s=120.0).run(spec)
    finally:
        eng.close()
    led.event("autoscale", action="scale_up", worker_id=1,
              workers_before=1, workers_after=2, level="crit", eval=3)

    out = subprocess.run(
        [sys.executable, TOOLS, str(tmp_path), "--traffic", "--json"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert rep["offered"] == 12
    assert rep["outcomes"].get("completed", 0) == summary.completed
    assert rep["outcomes"].get("shed", 0) == summary.shed
    assert rep["client_ttft_s"]["count"] == summary.completed
    assert sum(b["offered"] for b in rep["timeline"]) == 12
    assert rep["runs"][0]["spec_digest"] == spec_digest(spec)
    assert rep["autoscale"] == [{
        "action": "scale_up", "worker_id": 1, "workers_before": 1,
        "workers_after": 2, "level": "crit", "queue_depth": None,
        "eval": 3}]
    # the human printer renders without error too
    out2 = subprocess.run(
        [sys.executable, TOOLS, str(tmp_path), "--traffic"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out2.returncode == 0, out2.stderr
    assert "autoscale decisions" in out2.stdout
