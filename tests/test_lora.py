"""In-graph LoRA: identity at init, effect when trained, merge equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanorlhf_tpu.core import ModelConfig, init_params, model_forward
from nanorlhf_tpu.core.lora import LoraConfig, init_lora_params, merge_lora, trainable_mask


@pytest.fixture(scope="module")
def setup():
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    lora_cfg = LoraConfig(r=4, alpha=8)
    lora = init_lora_params(config, lora_cfg, jax.random.PRNGKey(1), jnp.float32)
    ids = jnp.asarray(np.random.default_rng(0).integers(2, 128, (2, 6)))
    mask = jnp.ones_like(ids)
    pos = jnp.cumsum(mask, axis=1) - 1
    return config, params, lora_cfg, lora, ids, mask, pos


def test_lora_zero_init_is_identity(setup):
    config, params, lora_cfg, lora, ids, mask, pos = setup
    base = model_forward(params, config, ids, mask, pos)
    with_lora = model_forward(
        {**params, "lora": lora}, config, ids, mask, pos, lora_scale=lora_cfg.scale
    )
    np.testing.assert_allclose(np.asarray(base), np.asarray(with_lora), atol=1e-6)


def _perturbed(lora):
    return jax.tree.map(
        lambda x: x + 0.01 * jax.random.normal(jax.random.PRNGKey(3), x.shape, x.dtype),
        lora,
    )


def test_lora_changes_output_and_merge_matches(setup):
    config, params, lora_cfg, lora, ids, mask, pos = setup
    lora_p = _perturbed(lora)
    base = model_forward(params, config, ids, mask, pos)
    in_graph = model_forward(
        {**params, "lora": lora_p}, config, ids, mask, pos, lora_scale=lora_cfg.scale
    )
    assert not np.allclose(np.asarray(base), np.asarray(in_graph), atol=1e-5)
    merged = merge_lora({**params, "lora": lora_p}, lora_cfg.scale)
    assert "lora" not in merged
    merged_out = model_forward(merged, config, ids, mask, pos)
    np.testing.assert_allclose(
        np.asarray(in_graph), np.asarray(merged_out), rtol=1e-4, atol=1e-5
    )


def test_trainable_mask(setup):
    config, params, lora_cfg, lora, *_ = setup
    full = {**params, "lora": lora}
    mask = trainable_mask(full, lora_cfg)
    assert mask["embed_tokens"] is True
    assert mask["norm"] is False
    assert mask["layers"]["q_proj"]["kernel"] is False
    assert all(jax.tree.leaves(mask["lora"]))
    # full fine-tune: everything trainable
    assert all(jax.tree.leaves(trainable_mask(full, None)))
    # frozen embeddings variant
    m2 = trainable_mask(full, LoraConfig(train_embed=False, train_lm_head=False))
    assert m2["embed_tokens"] is False
