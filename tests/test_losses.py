"""Loss functions vs torch oracles restating the reference loss blocks."""

import jax
import jax.numpy as jnp
import numpy as np
import torch

from nanorlhf_tpu.algos import (
    ppo_clip_loss_token,
    ppo_clip_loss_sequence,
    grpo_loss,
    value_loss_clipped,
    sft_loss,
    k3_kl,
)
from nanorlhf_tpu.ops import INVALID_LOGPROB


def make_batch(rng, B=4, T=6):
    new = -np.abs(rng.normal(size=(B, T))).astype(np.float32)
    old = -np.abs(rng.normal(size=(B, T))).astype(np.float32)
    ref = -np.abs(rng.normal(size=(B, T))).astype(np.float32)
    seq_len = rng.integers(1, T, size=(B,))
    pad = np.arange(T)[None, :] > seq_len[:, None]
    # reference masked_fills pads with INVALID_LOGPROB in new/old/ref alike
    new[pad] = INVALID_LOGPROB
    old[pad] = INVALID_LOGPROB
    ref[pad] = INVALID_LOGPROB
    adv = rng.normal(size=(B, T)).astype(np.float32)
    adv[pad] = 0.0
    return new, old, ref, adv, pad


def torch_masked_mean(v, m):
    return (v * m).sum() / m.sum()


def test_ppo_clip_loss_token(rng):
    new, old, ref, adv, pad = make_batch(rng)
    cliprange = 0.2
    loss, aux = ppo_clip_loss_token(
        jnp.asarray(new), jnp.asarray(old), jnp.asarray(adv), jnp.asarray(~pad), cliprange
    )
    tn, to, ta, tm = map(torch.from_numpy, (new, old, adv, ~pad))
    diff = tn - to
    ratio = torch.exp(diff)
    pg1 = -ta * ratio
    pg2 = -ta * torch.clamp(ratio, 1 - cliprange, 1 + cliprange)
    want = torch_masked_mean(torch.max(pg1, pg2), tm)
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-4)
    np.testing.assert_allclose(
        float(aux["approxkl"]), float(0.5 * (diff**2).mean()), rtol=1e-4
    )
    np.testing.assert_allclose(
        float(aux["pg_clipfrac"]), float(torch_masked_mean((pg2 > pg1).float(), tm)), rtol=1e-4
    )


def test_grpo_loss(rng):
    new, old, ref, adv, pad = make_batch(rng)
    cliprange, kl_coef = 0.2, 0.04
    loss, aux = grpo_loss(
        jnp.asarray(new), jnp.asarray(old), jnp.asarray(ref), jnp.asarray(adv),
        jnp.asarray(~pad), cliprange, kl_coef,
    )
    tn, to, tr, ta, tm = map(torch.from_numpy, (new, old, ref, adv, ~pad))
    ratio = torch.exp(tn - to)
    pg1 = -ta * ratio
    pg2 = -ta * torch.clamp(ratio, 1 - cliprange, 1 + cliprange)
    kl = tn - tr
    kl_term = kl_coef * (torch.exp(-kl) + kl - 1)
    want = torch_masked_mean(torch.max(pg1, pg2) + kl_term, tm)
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-4)


def test_k3_kl_nonnegative(rng):
    a = jnp.asarray(rng.normal(size=(10,)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(10,)).astype(np.float32))
    assert bool(jnp.all(k3_kl(a, b) >= -1e-6))
    np.testing.assert_allclose(np.asarray(k3_kl(a, a)), 0.0, atol=1e-6)


def test_ppo_clip_loss_sequence_matches_invalid_fill_semantics(rng):
    """Masked-sum formulation == reference's sum-over-INVALID-filled tensors."""
    new, old, ref, _, pad = make_batch(rng)
    adv_seq = rng.normal(size=(new.shape[0],)).astype(np.float32)
    cliprange = 0.2
    loss, _ = ppo_clip_loss_sequence(
        jnp.asarray(new), jnp.asarray(old), jnp.asarray(adv_seq), jnp.asarray(~pad), cliprange
    )
    # oracle: reference sums the filled tensors directly (pads cancel in diff)
    tn, to, ta = map(torch.from_numpy, (new, old, adv_seq))
    diff = tn.sum(1) - to.sum(1)
    ratio = torch.exp(diff)
    pg1 = -ta * ratio
    pg2 = -ta * torch.clamp(ratio, 1 - cliprange, 1 + cliprange)
    want = torch.max(pg1, pg2).mean()
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-4)


def test_value_loss_clipped(rng):
    B, T = 4, 6
    vpred = rng.normal(size=(B, T)).astype(np.float32)
    values = rng.normal(size=(B, T)).astype(np.float32)
    returns = rng.normal(size=(B, T)).astype(np.float32)
    seq_len = rng.integers(1, T - 1, size=(B,))
    pad_p1 = np.arange(T)[None, :] > (seq_len[:, None] + 1)
    cv = 0.2
    loss, aux = value_loss_clipped(
        jnp.asarray(vpred), jnp.asarray(values), jnp.asarray(returns),
        jnp.asarray(~pad_p1), cv,
    )
    tv, tva, trr, tm = map(torch.from_numpy, (vpred, values, returns, ~pad_p1))
    vclip = torch.clamp(tv, tva - cv, tva + cv)
    l1 = (tv - trr) ** 2
    l2 = (vclip - trr) ** 2
    want = 0.5 * torch_masked_mean(torch.max(l1, l2), tm)
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-4)


def test_sft_loss_gradient_matches_invalid_fill_version(rng):
    """Gradient of masked SFT loss == gradient of the reference's version."""
    new, _, _, _, pad = make_batch(rng)

    def ours(lp):
        return sft_loss(lp, jnp.asarray(~pad))[0]

    def reference_style(lp):
        # pads already carry constant INVALID_LOGPROB; sum everything
        filled = jnp.where(jnp.asarray(pad), INVALID_LOGPROB, lp)
        return -jnp.mean(jnp.sum(filled, axis=1))

    g_ours = jax.grad(ours)(jnp.asarray(new))
    g_ref = jax.grad(reference_style)(jnp.asarray(new))
    np.testing.assert_allclose(np.asarray(g_ours), np.asarray(g_ref), rtol=1e-5)
