"""Pin ops/masking semantics against independent torch reference implementations.

The torch references below re-state the TRL-helper formulas the reference
trainers depend on (SURVEY.md §2.4 'shared numerics') — written fresh here, and
used only as a numerical oracle.
"""

import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from nanorlhf_tpu.ops import (
    INVALID_LOGPROB,
    exact_div,
    first_true_indices,
    truncate_response,
    masked_mean,
    masked_var,
    masked_whiten,
    response_padding_masks,
    logprobs_from_logits,
    entropy_from_logits,
)


def torch_first_true_indices(bools):
    row_len = bools.size(-1)
    zero_or_index = row_len * (~bools).long() + torch.arange(row_len).long() * bools.long()
    return torch.min(zero_or_index, dim=-1).values


def test_first_true_indices(rng):
    bools = rng.random((7, 13)) < 0.2
    got = first_true_indices(jnp.asarray(bools))
    want = torch_first_true_indices(torch.from_numpy(bools))
    np.testing.assert_array_equal(np.asarray(got), want.numpy())


def test_first_true_indices_no_true():
    bools = jnp.zeros((3, 5), dtype=bool)
    np.testing.assert_array_equal(np.asarray(first_true_indices(bools)), [5, 5, 5])


def test_truncate_response():
    stop, pad = 9, 0
    resp = jnp.array(
        [
            [4, 5, 9, 7, 8],   # stop mid-sequence: keep stop, pad rest
            [9, 1, 2, 3, 4],   # stop first
            [1, 2, 3, 4, 5],   # no stop: unchanged
        ]
    )
    got = np.asarray(truncate_response(stop, pad, resp))
    np.testing.assert_array_equal(
        got, [[4, 5, 9, 0, 0], [9, 0, 0, 0, 0], [1, 2, 3, 4, 5]]
    )


def test_masked_mean_var_whiten(rng):
    vals = rng.normal(size=(6, 10)).astype(np.float32)
    mask = rng.random((6, 10)) < 0.7
    mask[0] = True  # ensure nonempty
    jv, jm = jnp.asarray(vals), jnp.asarray(mask)
    tv, tm = torch.from_numpy(vals), torch.from_numpy(mask)

    t_mean = (tv * tm).sum() / tm.sum()
    np.testing.assert_allclose(float(masked_mean(jv, jm)), float(t_mean), rtol=1e-5)

    t_var = ((tv - t_mean) ** 2 * tm).sum() / tm.sum()
    t_var = t_var * tm.sum() / (tm.sum() - 1)
    np.testing.assert_allclose(float(masked_var(jv, jm)), float(t_var), rtol=1e-5)

    t_whiten = (tv - t_mean) * torch.rsqrt(t_var + 1e-8)
    np.testing.assert_allclose(
        np.asarray(masked_whiten(jv, jm)), t_whiten.numpy(), rtol=1e-4, atol=1e-5
    )
    t_whiten_keep = t_whiten + t_mean
    np.testing.assert_allclose(
        np.asarray(masked_whiten(jv, jm, shift_mean=False)),
        t_whiten_keep.numpy(),
        rtol=1e-4,
        atol=1e-5,
    )


def test_masked_mean_axis(rng):
    vals = rng.normal(size=(4, 8)).astype(np.float32)
    mask = np.ones((4, 8), dtype=bool)
    mask[:, 5:] = False
    got = masked_mean(jnp.asarray(vals), jnp.asarray(mask), axis=1)
    np.testing.assert_allclose(np.asarray(got), vals[:, :5].mean(axis=1), rtol=1e-5)


def test_response_padding_masks():
    responses = jnp.zeros((2, 6), dtype=jnp.int32)
    seq_len = jnp.array([2, 5])  # index of last real token
    pm, pm1 = response_padding_masks(responses, seq_len)
    np.testing.assert_array_equal(
        np.asarray(pm),
        [[False, False, False, True, True, True],
         [False, False, False, False, False, False]],
    )
    np.testing.assert_array_equal(
        np.asarray(pm1),
        [[False, False, False, False, True, True],
         [False, False, False, False, False, False]],
    )


def test_logprobs_from_logits_matches_torch(rng):
    logits = rng.normal(size=(3, 7, 11)).astype(np.float32)
    labels = rng.integers(0, 11, size=(3, 7))
    temp = 0.7
    got = logprobs_from_logits(jnp.asarray(logits), jnp.asarray(labels), temp)
    t = torch.from_numpy(logits) / temp
    want = torch.gather(
        F.log_softmax(t, dim=-1), 2, torch.from_numpy(labels)[..., None]
    )[..., 0]
    np.testing.assert_allclose(np.asarray(got), want.numpy(), rtol=1e-4, atol=1e-5)


def test_entropy_from_logits(rng):
    logits = rng.normal(size=(3, 5, 11)).astype(np.float32)
    got = entropy_from_logits(jnp.asarray(logits))
    t = torch.from_numpy(logits)
    probs = F.softmax(t, dim=-1)
    want = torch.logsumexp(t, dim=-1) - (probs * t).sum(-1)
    np.testing.assert_allclose(np.asarray(got), want.numpy(), rtol=1e-4, atol=1e-5)


def test_exact_div():
    assert exact_div(12, 4) == 3
    with pytest.raises(ValueError):
        exact_div(13, 4)


def test_invalid_logprob_sentinel():
    assert INVALID_LOGPROB == 1.0
