"""Model forward with attention_impl='pallas' matches the XLA path."""

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params, padded_forward_logits


def test_padded_forward_pallas_matches_xla(rng):
    cfg_xla = ModelConfig.qwen2_tiny(vocab_size=128)
    cfg_pallas = dataclasses.replace(cfg_xla, attention_impl="pallas")
    params = init_params(cfg_xla, jax.random.PRNGKey(0), jnp.float32)
    ids = rng.integers(2, 128, size=(2, 12)).astype(np.int32)
    ids[0, :3] = 0  # left padding
    want = padded_forward_logits(params, cfg_xla, jnp.asarray(ids), 0)
    got = padded_forward_logits(params, cfg_pallas, jnp.asarray(ids), 0)
    real = (ids != 0)[:, :, None]
    np.testing.assert_allclose(
        np.asarray(got) * real, np.asarray(want) * real, rtol=3e-3, atol=3e-3
    )


def test_pallas_prefill_matches_xla(rng):
    """The prefill flash path (local K/V instead of the padded cache)."""
    from nanorlhf_tpu.core import init_kv_cache, prefill

    cfg_xla = ModelConfig.qwen2_tiny(vocab_size=128)
    cfg_pallas = dataclasses.replace(cfg_xla, attention_impl="pallas")
    params = init_params(cfg_xla, jax.random.PRNGKey(0), jnp.float32)
    ids = rng.integers(2, 128, size=(2, 10)).astype(np.int32)
    ids[0, :4] = 0
    mask = jnp.asarray((ids != 0).astype(np.int32))
    caches_a = init_kv_cache(cfg_xla, 2, 16, jnp.float32)
    caches_b = init_kv_cache(cfg_pallas, 2, 16, jnp.float32)
    logits_xla, cache_xla = prefill(params, cfg_xla, jnp.asarray(ids), mask, caches_a)
    logits_pl, cache_pl = prefill(params, cfg_pallas, jnp.asarray(ids), mask, caches_b)
    np.testing.assert_allclose(
        np.asarray(logits_pl), np.asarray(logits_xla), rtol=3e-3, atol=3e-3
    )
    # caches must match at VALID slots. Pad-slot K/V at layers >= 1 derives
    # from fully-masked query rows whose attention output is implementation-
    # defined garbage (flash and XLA average different denominators); those
    # slots are masked out of every future attention, so only real-token
    # slots carry meaning.
    valid = np.asarray(mask).astype(bool)            # [B, T]
    kp = np.asarray(cache_pl[0])[:, :, :, : ids.shape[1]]   # [L, B, KV, T, hd]
    kx = np.asarray(cache_xla[0])[:, :, :, : ids.shape[1]]
    sel = np.broadcast_to(
        valid[None, :, None, :, None], kp.shape
    )
    np.testing.assert_allclose(kp[sel], kx[sel], rtol=1e-4, atol=1e-4)


def test_pallas_grad_path_works(rng):
    cfg = dataclasses.replace(ModelConfig.qwen2_tiny(vocab_size=64),
                              attention_impl="pallas")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    ids = jnp.asarray(rng.integers(2, 64, size=(1, 8)).astype(np.int32))

    def loss(p):
        return jnp.sum(padded_forward_logits(p, cfg, ids, 0) ** 2)

    g = jax.grad(loss)(params)
    flat = jax.tree.leaves(g)
    assert all(bool(jnp.all(jnp.isfinite(x))) for x in flat)
    assert any(float(jnp.abs(x).sum()) > 0 for x in flat)


def test_remat_policy_gradients_identical(rng):
    """remat_policy is a memory/FLOPs knob, NOT a numerics one: gradients
    through the checkpointed layer scan must match between "full"
    (recompute everything) and "dots" (save MXU projection outputs), and
    match the unremat'd gradient."""
    import dataclasses

    import pytest

    from nanorlhf_tpu.core import padded_forward_logits

    mcfg = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    ids = jnp.asarray(rng.integers(2, 128, (2, 16)).astype(np.int32))

    def loss(p, cfg, remat):
        lg = padded_forward_logits(p, cfg, ids, 0, remat=remat)
        return (lg.astype(jnp.float32) ** 2).mean()

    g_none = jax.grad(loss)(params, mcfg, False)
    g_full = jax.grad(loss)(params, mcfg, True)
    g_dots = jax.grad(loss)(
        params, dataclasses.replace(mcfg, remat_policy="dots"), True
    )
    for a, b, c in zip(jax.tree.leaves(g_none), jax.tree.leaves(g_full),
                       jax.tree.leaves(g_dots)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   rtol=1e-6, atol=1e-6)

    with pytest.raises(ValueError, match="remat_policy"):
        jax.grad(loss)(
            params, dataclasses.replace(mcfg, remat_policy="bogus"), True
        )
