"""Logit parity: our JAX Qwen2 vs the torch transformers implementation.

This is the weight-fidelity gate SURVEY.md §7 calls for (GQA head layout,
tied embeddings, RoPE, padding semantics) — a tiny random-weight torch
Qwen2ForCausalLM is converted and both models score the same batch.
"""

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from nanorlhf_tpu.core import ModelConfig, model_forward, padded_forward_logits
from nanorlhf_tpu.core.params import params_from_hf_state_dict


@pytest.fixture(scope="module")
def tiny_pair():
    from transformers import Qwen2Config, Qwen2ForCausalLM

    hf_config = Qwen2Config(
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=1024,
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True,
        attention_dropout=0.0,
    )
    torch.manual_seed(0)
    model = Qwen2ForCausalLM(hf_config).eval().to(torch.float32)
    config = ModelConfig.from_hf_config(hf_config)
    params = params_from_hf_state_dict(config, model.state_dict(), dtype=jnp.float32)
    return model, config, params


def test_logit_parity_full_batch(tiny_pair, rng):
    model, config, params = tiny_pair
    B, T = 3, 12
    ids = rng.integers(2, 512, size=(B, T))
    mask = np.ones((B, T), dtype=np.int64)
    pos = np.cumsum(mask, axis=1) - 1
    with torch.no_grad():
        want = model(
            input_ids=torch.from_numpy(ids),
            attention_mask=torch.from_numpy(mask),
            position_ids=torch.from_numpy(pos),
        ).logits.numpy()
    got = np.asarray(
        model_forward(params, config, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(pos))
    )
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_logit_parity_right_padded(tiny_pair, rng):
    """padded_forward_logits must match torch under the reference's
    mask/position recipe (positions from mask cumsum, pad ids zeroed)."""
    model, config, params = tiny_pair
    pad_id = 0
    B, T = 3, 10
    ids = rng.integers(2, 512, size=(B, T))
    lengths = [10, 6, 4]
    for b, l in enumerate(lengths):
        ids[b, l:] = pad_id
    mask = (ids != pad_id).astype(np.int64)
    pos = np.cumsum(mask, axis=1) - mask
    with torch.no_grad():
        want = model(
            input_ids=torch.from_numpy(np.where(mask, ids, 0)),
            attention_mask=torch.from_numpy(mask),
            position_ids=torch.from_numpy(pos),
        ).logits.numpy()
    got = np.asarray(padded_forward_logits(params, config, jnp.asarray(ids), pad_id))
    # compare only real positions; padded rows are free to differ
    for b, l in enumerate(lengths):
        np.testing.assert_allclose(got[b, :l], want[b, :l], rtol=2e-4, atol=2e-4)


def test_response_context_slice_equals_post_hoc_slice(tiny_pair, rng):
    """response_context_length=k must equal full logits sliced [k-1:-1] —
    the shift-by-one next-token convention lives in one place."""
    import jax.numpy as jnp
    from nanorlhf_tpu.core import padded_forward_logits

    _, config, params = tiny_pair
    ids = jnp.asarray(rng.integers(2, 512, size=(2, 14)).astype(np.int32))
    for ctx in (1, 5, 10):
        full = padded_forward_logits(params, config, ids, 0)[:, ctx - 1 : -1]
        sliced = padded_forward_logits(params, config, ids, 0,
                                       response_context_length=ctx)
        np.testing.assert_array_equal(np.asarray(full), np.asarray(sliced))


def test_untied_lm_head(rng):
    from transformers import Qwen2Config, Qwen2ForCausalLM

    hf_config = Qwen2Config(
        vocab_size=256,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=4,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(1)
    model = Qwen2ForCausalLM(hf_config).eval().to(torch.float32)
    config = ModelConfig.from_hf_config(hf_config)
    params = params_from_hf_state_dict(config, model.state_dict(), dtype=jnp.float32)
    ids = rng.integers(2, 256, size=(2, 8))
    mask = np.ones((2, 8), dtype=np.int64)
    pos = np.cumsum(mask, axis=1) - 1
    with torch.no_grad():
        want = model(
            input_ids=torch.from_numpy(ids),
            attention_mask=torch.from_numpy(mask),
            position_ids=torch.from_numpy(pos),
        ).logits.numpy()
    got = np.asarray(
        model_forward(params, config, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(pos))
    )
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.fixture(scope="module")
def tiny_llama_pair():
    """Llama-family variant of the parity gate: no q/k/v biases, llama
    RoPE/theta — same decoder, attention_bias=False (core/config.py)."""
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_config = LlamaConfig(
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=1024,
        rope_theta=500000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        attention_bias=False,
        attention_dropout=0.0,
    )
    torch.manual_seed(1)
    model = LlamaForCausalLM(hf_config).eval().to(torch.float32)
    config = ModelConfig.from_hf_config(hf_config)
    assert not config.attention_bias
    params = params_from_hf_state_dict(config, model.state_dict(), dtype=jnp.float32)
    assert "bias" not in params["layers"]["q_proj"]
    return model, config, params


def test_llama_logit_parity(tiny_llama_pair, rng):
    model, config, params = tiny_llama_pair
    B, T = 3, 12
    ids = rng.integers(2, 512, size=(B, T))
    mask = np.ones((B, T), dtype=np.int64)
    pos = np.cumsum(mask, axis=1) - 1
    with torch.no_grad():
        want = model(
            input_ids=torch.from_numpy(ids),
            attention_mask=torch.from_numpy(mask),
            position_ids=torch.from_numpy(pos),
        ).logits.numpy()
    got = np.asarray(
        model_forward(params, config, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(pos))
    )
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_llama_init_params_no_bias():
    """Random init honors attention_bias=False; forward + greedy decode run."""
    from nanorlhf_tpu.core import init_params
    from nanorlhf_tpu.data import ToyTokenizer
    from nanorlhf_tpu.sampler import SamplingParams, generate
    import dataclasses

    cfg = dataclasses.replace(
        ModelConfig.qwen2_tiny(vocab_size=128), attention_bias=False,
        rope_theta=500000.0,
    )
    params = init_params(cfg, __import__("jax").random.PRNGKey(0), jnp.float32)
    assert "bias" not in params["layers"]["k_proj"]
    tok = ToyTokenizer(vocab_size=128)
    import jax
    ids = jnp.asarray(np.full((2, 4), 7, np.int32))
    out = generate(params, cfg, ids, ids != 0, jax.random.PRNGKey(0),
                   SamplingParams(greedy=True, max_tokens=6),
                   eos_token_id=3, pad_token_id=0)
    assert out.shape == (2, 6)
