"""nanolint: per-rule-family fixture tests + lock-order runtime tests.

Each deliberately-broken fixture must trip exactly its rule (and the
known-good twin must stay clean); the lock-graph test plants a synthetic
inversion and expects a cycle; the OrderedLock test proves the runtime
sanitizer raises on an out-of-order acquisition. Everything here is
jax-free — the analysis package is stdlib-only by design.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
import threading
from pathlib import Path

import pytest

from nanorlhf_tpu.analysis import (determinism, engine, jitpurity, lockgraph,
                                   lockorder, registry)

REPO = Path(__file__).resolve().parent.parent


def _proj(tmp_path: Path, files: dict[str, str]) -> engine.Project:
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return engine.load_project(tmp_path, [tmp_path])


def _rules(findings):
    return sorted({f.rule for f in findings})


# --------------------------------------------------------------------------
# family 1: determinism
# --------------------------------------------------------------------------

def test_wall_clock_fires_in_scope_and_perf_counter_is_clean(tmp_path):
    proj = _proj(tmp_path, {
        "nanorlhf_tpu/orchestrator/bad.py": """
            import time
            def latency():
                t0 = time.time()
                return time.perf_counter() - t0
        """,
        "nanorlhf_tpu/orchestrator/good.py": """
            import time
            def latency():
                t0 = time.perf_counter()
                return time.perf_counter() - t0
        """,
    })
    findings = determinism.run(proj)
    assert _rules(findings) == ["determinism.wall-clock"]
    assert len(findings) == 1
    assert findings[0].path.endswith("bad.py")


def test_wall_clock_out_of_scope_is_ignored(tmp_path):
    proj = _proj(tmp_path, {
        "nanorlhf_tpu/telemetry/stamps.py": """
            import time
            def stamp():
                return time.time()
        """,
    })
    assert determinism.run(proj) == []


def test_allowlist_annotation_suppresses_with_reason_only(tmp_path):
    proj = _proj(tmp_path, {
        "nanorlhf_tpu/orchestrator/prov.py": """
            import time
            def stamp():
                # nanolint: allow[determinism.wall-clock] provenance stamp
                return time.time()
            def bare():
                # nanolint: allow[determinism.wall-clock]
                return time.time()
        """,
    })
    findings = engine.apply_allowlist(proj, determinism.run(proj))
    rules = _rules(findings)
    # the reasoned annotation suppressed; the bare one did not, and it
    # additionally flags the missing reason
    assert "determinism.wall-clock" in rules
    assert "meta.allow-missing-reason" in rules
    assert len([f for f in findings
                if f.rule == "determinism.wall-clock"]) == 1


def test_unseeded_random_fires_seeded_ctor_clean(tmp_path):
    proj = _proj(tmp_path, {
        "nanorlhf_tpu/trainer/rng.py": """
            import random
            import numpy as np
            def bad():
                return random.random() + np.random.rand()
            def good(seed):
                return random.Random(seed).random() \
                    + np.random.default_rng(seed).random()
        """,
    })
    findings = determinism.run(proj)
    assert _rules(findings) == ["determinism.unseeded-random"]
    assert len(findings) == 2
    assert all("bad" in f.detail or f.line <= 5 for f in findings)


def test_key_reuse_fires_and_split_or_branches_are_clean(tmp_path):
    proj = _proj(tmp_path, {
        "nanorlhf_tpu/sampler/keys.py": """
            import jax
            def bad(key):
                a = jax.random.normal(key)
                b = jax.random.uniform(key)
                return a + b
            def good(key):
                a = jax.random.normal(key)
                key, sub = jax.random.split(key)
                b = jax.random.uniform(key)
                return a + b
            def branches(key, flag):
                if flag:
                    return jax.random.normal(key)
                return jax.random.uniform(key)
        """,
    })
    findings = determinism.run(proj)
    assert _rules(findings) == ["determinism.key-reuse"]
    assert len(findings) == 1
    assert "bad" in findings[0].detail


# --------------------------------------------------------------------------
# family 2: jit purity
# --------------------------------------------------------------------------

def test_jit_host_sync_item_fires(tmp_path):
    proj = _proj(tmp_path, {
        "nanorlhf_tpu/trainer/jitted.py": """
            import jax
            @jax.jit
            def step(x):
                return x.sum().item()
            def helper(x):
                return x.item()  # reachable? no jit entry calls it
        """,
    })
    findings = jitpurity.run(proj)
    assert "jit.host-sync" in _rules(findings)
    assert any(f.detail.startswith("item in step") for f in findings)


def test_jit_traced_branch_fires_static_is_clean(tmp_path):
    proj = _proj(tmp_path, {
        "nanorlhf_tpu/trainer/branchy.py": """
            import jax
            from functools import partial
            @partial(jax.jit, static_argnames=("mode",))
            def ok(x, mode):
                if mode:
                    return x + 1
                return x
            @jax.jit
            def bad(x):
                if x > 0:
                    return x + 1
                return x
        """,
    })
    findings = jitpurity.run(proj)
    assert _rules(findings) == ["jit.traced-branch"]
    assert len(findings) == 1
    assert "bad" in findings[0].detail


def test_jit_reachability_through_same_module_call(tmp_path):
    proj = _proj(tmp_path, {
        "nanorlhf_tpu/trainer/reach.py": """
            import jax
            def inner(x):
                return x.item()
            @jax.jit
            def outer(x):
                return inner(x)
        """,
    })
    findings = jitpurity.run(proj)
    assert any(f.rule == "jit.host-sync" and "inner" in f.detail
               for f in findings)


def test_repo_jit_bodies_are_clean():
    proj = engine.load_project(REPO, [REPO / "nanorlhf_tpu"])
    assert jitpurity.run(proj) == []


# --------------------------------------------------------------------------
# family 3: registry cross-checks
# --------------------------------------------------------------------------

def test_fault_site_cross_check_both_directions(tmp_path):
    proj = _proj(tmp_path, {
        "docs/RESILIENCE.md": """
            | point | wired where | effect |
            |---|---|---|
            | `ckpt.save` | somewhere | raises |
            | `ghost.site` | documented only | never fired |
        """,
        "nanorlhf_tpu/resilience/f.py": """
            def go(faults):
                faults.fire("ckpt.save")
                faults.fire("rogue.site")
        """,
    })
    (tmp_path / "docs").mkdir(exist_ok=True)
    findings = registry.run(proj)
    rules = _rules(findings)
    assert "registry.fault-site-undocumented" in rules   # rogue.site
    assert "registry.fault-site-unwired" in rules        # ghost.site
    assert any("rogue.site" in f.detail for f in findings)
    assert any("ghost.site" in f.detail for f in findings)


def test_invariant_cross_check_both_directions(tmp_path):
    proj = _proj(tmp_path, {
        "docs/RESILIENCE.md": """
            | invariant | meaning |
            |---|---|
            | `chaos.kv_page_leak` | checked and documented |
            | `chaos.ghost_rule` | documented, never checked |
        """,
        "nanorlhf_tpu/chaos/a.py": """
            INVARIANTS = ("chaos.kv_page_leak", "chaos.rogue_rule")
        """,
    })
    findings = registry.run(proj)
    rules = _rules(findings)
    assert "registry.invariant-undocumented" in rules    # chaos.rogue_rule
    assert "registry.invariant-unchecked" in rules       # chaos.ghost_rule
    assert any(f.rule == "registry.invariant-undocumented"
               and "chaos.rogue_rule" in f.detail for f in findings)
    assert any(f.rule == "registry.invariant-unchecked"
               and "chaos.ghost_rule" in f.detail for f in findings)


def test_invariant_strings_outside_chaos_scope_ignored(tmp_path):
    # the chaos.* string grammar only counts inside nanorlhf_tpu/chaos/
    # — a log message elsewhere must not become a registry obligation
    proj = _proj(tmp_path, {
        "docs/RESILIENCE.md": "",
        "nanorlhf_tpu/telemetry/b.py": """
            MSG = "chaos.not_an_auditor"
        """,
    })
    findings = registry.run(proj)
    assert not any(f.rule.startswith("registry.invariant")
                   for f in findings)


def test_parse_invariant_tables_grammar():
    # same table grammar as the fault-site registry, selected by the
    # header's first cell; non-matching tokens and fault tables ignored
    text = textwrap.dedent("""
        | point | effect |
        |---|---|
        | `ckpt.save` | a fault site, not an invariant |

        | Invariant | meaning |
        |---|---|
        | `chaos.worker_leak` | counted |
        | not backticked | ignored |
        | `Chaos.Uppercase` | ignored: bad grammar |
    """)
    assert registry.parse_invariant_tables(text) == {"chaos.worker_leak"}


def test_metric_doc_cross_check(tmp_path):
    proj = _proj(tmp_path, {
        "docs/METRICS.md": """
            | Metric | Reference semantics | This framework |
            |---|---|---|
            | `perf/mfu` | — | documented and emitted |
            | `perf/ghost` | — | documented, never emitted |
            | `health/rule_<name>` | — | wildcard row |
        """,
        "docs/RESILIENCE.md": "",
        "nanorlhf_tpu/trainer/em.py": """
            def emit(rules):
                out = {"perf/mfu": 1.0, "perf/rogue": 2.0}
                for r in rules:
                    out[f"health/rule_{r}"] = 0.0
                return out
        """,
    })
    findings = registry.run(proj)
    assert any(f.rule == "registry.metric-undocumented"
               and "perf/rogue" in f.detail for f in findings)
    assert any(f.rule == "registry.metric-unemitted"
               and "perf/ghost" in f.detail for f in findings)
    # the wildcard row is matched by the f-string pattern: no unemitted
    # finding for health/rule_*
    assert not any("health/rule" in f.detail for f in findings)


def test_health_rule_metric_must_be_emitted(tmp_path):
    proj = _proj(tmp_path, {
        "docs/METRICS.md": "| `perf/mfu` | — | x |\n",
        "docs/RESILIENCE.md": "",
        "nanorlhf_tpu/telemetry/h.py": """
            def rules(HealthRule):
                return [HealthRule(name="r", metric="perf/never_emitted")]
        """,
    })
    findings = registry.run(proj)
    assert any(f.rule == "registry.health-rule-metric" for f in findings)


def test_repo_registry_is_green():
    proj = engine.load_project(
        REPO, [REPO / "nanorlhf_tpu", REPO / "tools"])
    proj.files = [f for f in proj.files if not f.relpath.startswith("tests/")]
    assert registry.run(proj) == []


# --------------------------------------------------------------------------
# family 4: lock order
# --------------------------------------------------------------------------

def test_lock_graph_synthetic_inversion_and_cycle(tmp_path):
    proj = _proj(tmp_path, {
        "nanorlhf_tpu/orchestrator/inv.py": """
            from nanorlhf_tpu.analysis.lockorder import make_lock

            class Inverted:
                def __init__(self):
                    self._coord = make_lock("fleet.coordinator")
                    self._ledger = make_lock("telemetry.lineage")
                def forward(self):
                    with self._coord:
                        with self._ledger:
                            pass
                def backward(self):
                    with self._ledger:
                        with self._coord:
                            pass
        """,
    })
    graph = lockgraph.extract(proj)
    findings = lockgraph.check(graph)
    rules = _rules(findings)
    # backward holds lineage (high rank) then takes coordinator (rank 0):
    # an inversion; together with forward's edge it is a cycle
    assert "lockorder.inversion" in rules
    assert "lockorder.cycle" in rules


def test_lock_graph_undeclared_raw_lock(tmp_path):
    proj = _proj(tmp_path, {
        "nanorlhf_tpu/orchestrator/raw.py": """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
        """,
    })
    findings = lockgraph.run(proj)
    assert _rules(findings) == ["lockorder.undeclared"]


def test_repo_lock_graph_is_cycle_free_and_ordered():
    proj = engine.load_project(REPO, [REPO / "nanorlhf_tpu"])
    graph = lockgraph.extract(proj)
    findings = lockgraph.check(graph)
    assert findings == [], [f.render() for f in findings]
    # and the graph is non-trivial: the audited cross-subsystem edges exist
    pairs = graph.edge_pairs()
    assert ("fleet.coordinator", "orchestrator.queue") in pairs
    assert ("orchestrator.queue", "telemetry.lineage") in pairs
    assert ("fleet.coordinator", "rpc.server") in pairs


# --------------------------------------------------------------------------
# OrderedLock runtime sanitizer
# --------------------------------------------------------------------------

def test_ordered_lock_violation_raises(monkeypatch):
    monkeypatch.setenv("NANORLHF_LOCK_CHECK", "1")
    lo = lockorder.make_lock("fleet.coordinator")
    hi = lockorder.make_lock("telemetry.lineage")
    with lo:
        with hi:
            pass  # ascending: fine
    with pytest.raises(lockorder.LockOrderViolation):
        with hi:
            with lo:
                pass


def test_ordered_lock_unknown_name_rejected(monkeypatch):
    monkeypatch.setenv("NANORLHF_LOCK_CHECK", "1")
    with pytest.raises(lockorder.LockOrderViolation):
        lockorder.make_lock("not.in.the.order")


def test_ordered_condition_wait_notify(monkeypatch):
    monkeypatch.setenv("NANORLHF_LOCK_CHECK", "1")
    cond = lockorder.make_condition("orchestrator.queue")
    state = []

    def waiter():
        with cond:
            cond.wait_for(lambda: state, timeout=5)
            state.append("woke")

    t = threading.Thread(target=waiter)
    t.start()
    import time
    time.sleep(0.05)
    with cond:
        state.append(1)
        cond.notify_all()
    t.join(timeout=5)
    assert "woke" in state
    # the wait released the lock: the held stack is empty afterwards
    assert lockorder.held_locks() == []


def test_ordered_rlock_reentrant(monkeypatch):
    monkeypatch.setenv("NANORLHF_LOCK_CHECK", "1")
    r = lockorder.make_rlock("rpc.client")
    with r:
        with r:
            assert lockorder.held_locks() == ["rpc.client"]
    assert lockorder.held_locks() == []


def test_factories_return_plain_primitives_when_disabled(monkeypatch):
    monkeypatch.delenv("NANORLHF_LOCK_CHECK", raising=False)
    assert not isinstance(lockorder.make_lock("fleet.coordinator"),
                          lockorder.OrderedLock)
    cond = lockorder.make_condition("orchestrator.queue")
    assert isinstance(cond, threading.Condition)
    assert not isinstance(cond._lock, lockorder.OrderedLock)


# --------------------------------------------------------------------------
# CLI + baseline workflow
# --------------------------------------------------------------------------

def test_cli_repo_is_clean():
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "nanolint.py")],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr


def test_baseline_requires_reason_and_flags_stale(tmp_path):
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"entries": [
        {"rule": "determinism.wall-clock", "path": "x.py",
         "detail": "time.time in f", "reason": ""},
    ]}))
    entries, errors = engine.load_baseline(baseline)
    assert errors, "empty reason must be rejected"
    new, stale = engine.diff_baseline([], entries)
    assert stale == entries, "entry with no matching finding is stale"
