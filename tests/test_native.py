"""Native C++ data-path kernels == Python reference semantics."""

import numpy as np
import pytest

from nanorlhf_tpu import native


def python_create_batches(lengths, budget):
    lengths = np.asarray(lengths)
    order = np.argsort(lengths, kind="stable")
    batches, current, cur_len = [], [], 0
    for idx in order:
        sample_len = int(lengths[idx])
        if max(cur_len, sample_len) * (len(current) + 1) > budget and current:
            batches.append(current)
            current, cur_len = [], 0
        current.append(int(idx))
        cur_len = max(cur_len, sample_len)
    if current:
        batches.append(current)
    return batches


@pytest.fixture(scope="module")
def lib_available():
    if not native.available():
        pytest.skip("native toolchain unavailable")


def test_native_builds(lib_available):
    assert native.available()


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_create_batches_matches_python(lib_available, seed):
    rng = np.random.default_rng(seed)
    lengths = rng.integers(1, 200, size=64)
    for budget in (64, 512, 4096):
        got = native.create_batches_native(lengths, budget)
        want = python_create_batches(lengths, budget)
        assert got == want


def test_create_batches_single(lib_available):
    assert native.create_batches_native([1000], 10) == [[0]]


def test_pack_left_pad(lib_available, rng):
    rows = [list(rng.integers(1, 100, size=n)) for n in (3, 7, 0, 5)]
    got = native.pack_left_pad_native(rows, 7, 0)
    want = np.zeros((4, 7), np.int32)
    for i, r in enumerate(rows):
        r = r[-7:]
        if r:
            want[i, 7 - len(r):] = r
    np.testing.assert_array_equal(got, want)


def test_pack_left_pad_truncates_head(lib_available):
    # rows longer than max_len keep their TAIL (prompt semantics)
    got = native.pack_left_pad_native([[1, 2, 3, 4, 5]], 3, 0)
    np.testing.assert_array_equal(got, [[3, 4, 5]])


def test_pack_right_pad(lib_available, rng):
    rows = [[1, 2, 3], [4], []]
    got = native.pack_right_pad_native(rows, 4, 9)
    np.testing.assert_array_equal(got, [[1, 2, 3, 9], [4, 9, 9, 9], [9, 9, 9, 9]])


def test_bucketing_module_dispatches_to_native(lib_available):
    from nanorlhf_tpu.trainer.bucketing import create_batches

    lengths = [5, 1, 9, 2, 2, 7]
    assert create_batches(lengths, 12) == python_create_batches(lengths, 12)


class _PicklableWordTokenizer:
    """Module-level (picklable) tokenizer for the multiprocess-encode test."""

    pad_token_id = 0

    def encode(self, text):
        return [3 + (len(w) % 50) for w in text.split()]


def test_encode_texts_parallel_matches_serial():
    """dataset.map(num_proc) parity: the fork-pool path returns byte-identical
    ids to the serial path, in order."""
    from nanorlhf_tpu.data.datasets import encode_texts

    tok = _PicklableWordTokenizer()
    texts = [f"word {'x' * (i % 13)} sample {i}" for i in range(400)]
    serial = [tok.encode(t)[:8] for t in texts]
    parallel = encode_texts(tok, texts, 8, num_proc=4)
    assert parallel == serial


def test_encode_texts_toy_tokenizer_keeps_decode_cache():
    """ToyTokenizer opts out of the pool (parallel_safe=False) so its decode
    cache fills in-process — round-tripping still works."""
    from nanorlhf_tpu.data.datasets import encode_texts
    from nanorlhf_tpu.data.tokenizer import ToyTokenizer

    tok = ToyTokenizer(512)
    texts = [f"alpha beta gamma{i}" for i in range(200)]
    ids = encode_texts(tok, texts, 16, num_proc=4)
    assert tok.decode(ids[0]).startswith("alpha beta")
