"""Async rollout orchestrator (nanorlhf_tpu/orchestrator/):

- bounded-staleness queue semantics under a slow-consumer fake trainer
  (wait policy never exceeds the bound; drop policy counts evictions);
- staleness-0 orchestrated training reproduces the synchronous trainer;
- truncated-IS GRPO at staleness 1 matches on-policy training when the
  policy is unchanged (learning_rate=0 → behavior == current policy);
- queue state survives checkpoint/resume with identical token streams;
- with disaggregated meshes, a pipelined max_staleness=2 run reports a
  strictly higher rollout/train overlap fraction than rollout_ahead under
  the bench's repeated train(num_updates=1) invocation pattern.
"""

import json
import time

import numpy as np
import pytest

import jax

from nanorlhf_tpu.orchestrator import (
    BoundedStalenessQueue,
    OverlapMeter,
    RolloutOrchestrator,
)
from nanorlhf_tpu.trainer import AlgoName

from test_trainer_smoke import make_trainer


def _metric_rows(outdir):
    rows = []
    with open(outdir / "metrics.jsonl") as f:
        for line in f:
            row = json.loads(line)
            if "episode" in row:
                rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# queue / producer semantics (no model — fake dispatch)
# ---------------------------------------------------------------------------


def test_staleness_bound_enforced_slow_consumer():
    """Wait policy: a fast producer against a slow consumer never dispatches
    (nor delivers) a sample beyond the staleness bound."""
    dispatched = []

    def dispatch(index, tree):
        dispatched.append((index, tree["v"]))
        return {"index": index}

    orch = RolloutOrchestrator(
        dispatch_fn=dispatch, initial_params={"v": 0}, max_staleness=2,
        policy="wait",
    )
    try:
        consumed = []
        for step in range(5):
            s = orch.get()
            consumed.append(orch.version - s.version)
            time.sleep(0.05)  # slow consumer: the producer races ahead
            orch.publish({"v": orch.version + 1})
        # consumed staleness within the bound, and dispatch-time lead
        # (index ahead of the published version) never exceeded it either
        assert all(st <= 2 for st in consumed), consumed
        assert all(idx - v <= 2 for idx, v in dispatched), dispatched
        # the producer really pipelined (ran ahead of the consumer)
        assert max(idx for idx, _ in dispatched) >= 2
        assert orch.queue.dropped == 0
        hist = orch.queue.staleness_counts
        assert sum(hist.values()) == len(consumed)
        assert set(hist) <= {0, 1, 2}
    finally:
        orch.close()


def test_drop_policy_counts_drops_and_keeps_bound():
    """Drop policy: production is gated exactly like "wait" (a producer
    allowed to run ahead would burn the data/PRNG cursor on samples
    destined for the floor — a real bug caught by the verify drive);
    queued samples that go over-stale anyway — publishes without consumes
    — are discarded at get(), counted, and never delivered."""

    def dispatch(index, tree):
        return {"index": index}

    orch = RolloutOrchestrator(
        dispatch_fn=dispatch, initial_params={}, max_staleness=1,
        policy="drop",
    )
    try:
        deadline = time.time() + 5.0
        while orch.queue.depth() < 2 and time.time() < deadline:
            time.sleep(0.01)  # consumer stalled: queue fills to capacity 2
        assert orch.queue.depth() == 2
        time.sleep(0.2)
        # capacity gate held: the producer did NOT run away with the data
        # cursor while the consumer stalled (idx 0,1 queued + at most one
        # in flight)
        assert orch._next_index <= 3, orch._next_index
        assert orch.queue.dropped == 0
        # two publishes WITHOUT consuming -> both queued samples (v0) are
        # now over-stale for max_staleness=1 and must be discarded
        orch.publish({})
        orch.publish({})
        s = orch.get()
        assert orch.queue.dropped >= 2
        assert orch.version - s.version <= 1  # delivered within the bound
    finally:
        orch.close()


def test_producer_error_surfaces_in_get():
    def dispatch(index, tree):
        raise RuntimeError("boom in producer")

    orch = RolloutOrchestrator(dispatch_fn=dispatch, initial_params={},
                               max_staleness=1)
    try:
        with pytest.raises(RuntimeError, match="rollout producer failed"):
            orch.get()
    finally:
        orch.close()


def test_queue_journal_and_restore_counters():
    q = BoundedStalenessQueue(max_staleness=2, policy="wait")
    from nanorlhf_tpu.orchestrator import QueuedSample

    q.put(QueuedSample(index=5, version=1, payload=None))
    q.advance_version(2)
    q.get()
    j = q.journal()
    assert j["version"] == 2 and j["staleness_counts"] == {"1": 1}

    q2 = BoundedStalenessQueue(max_staleness=2)
    q2.restore_counters(j)
    assert q2.staleness_counts == {1: 1} and q2.dropped == 0


def test_overlap_meter_interval_math():
    m = OverlapMeter()
    m.note_gen(0.0, 10.0)
    m.note_busy(2.0, 4.0)
    m.note_busy(3.0, 7.0)    # overlaps the previous busy window
    m.note_busy(20.0, 30.0)  # outside every gen window
    assert m.overlap_fraction() == pytest.approx(0.5)  # [2,7] of [0,10]
    assert OverlapMeter().overlap_fraction() == 0.0


def test_overlap_meter_compaction_preserves_fraction():
    """History folding (watermark compaction) must not change the
    cumulative fraction — and must actually bound the stored history."""
    compact = OverlapMeter()
    compact._COMPACT_AT = 8
    plain = OverlapMeter()  # default threshold: never compacts at this size
    rng = np.random.default_rng(0)
    t = 0.0
    for _ in range(500):
        g0 = t + rng.random() * 0.1
        g1 = g0 + 0.5 + rng.random()
        b0 = g0 + rng.random()
        b1 = b0 + 0.5 + rng.random()
        for m in (compact, plain):
            m.note_gen(g0, g1)
            m.note_busy(b0, b1)
        t = max(g1, b1)
    assert compact.overlap_fraction() == pytest.approx(
        plain.overlap_fraction(), rel=1e-9
    )
    assert len(compact._gen) + len(compact._busy) <= 16


# ---------------------------------------------------------------------------
# trainer integration (8-device CPU mesh)
# ---------------------------------------------------------------------------


def test_staleness0_matches_synchronous_trainer(tmp_path):
    """max_staleness=0 gates every rollout on the freshest published
    version — the orchestrated run must reproduce the synchronous loss
    trajectory (same data cursor, same index-keyed generation PRNG, same
    params at every dispatch)."""
    serial = make_trainer(AlgoName.GRPO, tmp_path / "serial",
                          total_episodes=48, save_steps=0)
    serial.train()
    serial.close()
    orch = make_trainer(AlgoName.GRPO, tmp_path / "orch", total_episodes=48,
                        save_steps=0, rollout_orchestrator=True,
                        max_staleness=0)
    orch.train()
    orch.close()

    m_serial = _metric_rows(tmp_path / "serial" / "grpo")
    m_orch = _metric_rows(tmp_path / "orch" / "grpo")
    assert len(m_serial) == len(m_orch) == 3
    for a, b in zip(m_serial, m_orch):
        for key in ("objective/kl_rollout_old", "eval_objective/scores_old",
                    "objective/entropy_old", "loss/policy_avg_new"):
            np.testing.assert_allclose(
                a[key], b[key], rtol=1e-5,
                err_msg=f"staleness-0 {key} diverged from synchronous",
            )
    # on-policy: every consumed sample reports staleness 0, nothing dropped
    for row in m_orch:
        assert row["orchestrator/staleness"] == 0.0
        assert row["orchestrator/dropped_total"] == 0.0


def test_truncated_is_staleness1_matches_onpolicy_when_policy_frozen(tmp_path):
    """learning_rate=0 freezes the policy, so a staleness-1 behavior policy
    IS the current policy: truncated-IS GRPO must reproduce the synchronous
    run's trajectory (IS weights ≈ 1 up to decode-vs-scoring numerics) —
    the unbiasedness anchor for the off-policy correction."""
    kw = dict(total_episodes=48, save_steps=0, learning_rate=0.0,
              sampler_logprob_capture=True)
    serial = make_trainer(AlgoName.GRPO, tmp_path / "serial", **kw)
    serial.train()
    serial.close()
    orch = make_trainer(AlgoName.GRPO, tmp_path / "orch",
                        rollout_orchestrator=True, max_staleness=1, **kw)
    orch.train()
    orch.close()

    m_serial = _metric_rows(tmp_path / "serial" / "grpo")
    m_orch = _metric_rows(tmp_path / "orch" / "grpo")
    assert len(m_serial) == len(m_orch) == 3
    for a, b in zip(m_serial, m_orch):
        # frozen policy → identical token streams → identical rewards
        np.testing.assert_allclose(
            a["eval_objective/scores_old"], b["eval_objective/scores_old"],
            rtol=1e-5,
        )
        # loss matches up to decode-vs-scoring float noise in the IS weight
        np.testing.assert_allclose(
            a["loss/policy_avg_new"], b["loss/policy_avg_new"], atol=2e-2,
        )
    # pipeline actually went one step stale, and the correction was live
    assert m_orch[-1]["orchestrator/staleness"] == 1.0
    assert m_orch[-1]["offpolicy/is_weight_mean_new"] == pytest.approx(
        1.0, abs=0.05
    )
    assert "offpolicy/is_trunc_frac_new" in m_orch[-1]


def test_checkpoint_resume_identical_token_streams(tmp_path):
    """Queue state survives checkpoint/resume: the journaled consumed-rollout
    cursor + index-keyed PRNG reproduce the uninterrupted run's token
    streams — a 2+resume+1 orchestrated run matches a straight 3-update run
    exactly at staleness 0."""
    full = make_trainer(AlgoName.GRPO, tmp_path / "full", total_episodes=48,
                        rollout_orchestrator=True, max_staleness=0)
    full.train()
    full.close()

    half = make_trainer(AlgoName.GRPO, tmp_path / "half", total_episodes=48,
                        rollout_orchestrator=True, max_staleness=0)
    half.train(num_updates=2)
    # the checkpoint journaled the orchestrator's queue state
    tstate = half.ckpt.load_trainer_state(2)
    assert "orchestrator" in tstate
    assert set(tstate["orchestrator"]) >= {"pending", "version", "dropped"}
    half.close()

    res = make_trainer(AlgoName.GRPO, tmp_path / "half", total_episodes=48,
                       rollout_orchestrator=True, max_staleness=0)
    res.resume_from_checkpoint()
    res.train()
    res.close()

    a = _metric_rows(tmp_path / "full" / "grpo")[-1]
    b = _metric_rows(tmp_path / "half" / "grpo")[-1]
    assert a["episode"] == b["episode"]
    for key in ("objective/kl_rollout_old", "eval_objective/scores_old",
                "objective/entropy_old", "loss/policy_avg_new"):
        np.testing.assert_allclose(a[key], b[key], rtol=1e-4, err_msg=key)


def test_resume_restores_orchestrator_counters(tmp_path):
    """Cumulative drop/staleness counters come back from the journal so the
    metric series stays continuous across resume."""
    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=48,
                      rollout_orchestrator=True, max_staleness=1)
    tr.train(num_updates=2)
    hist_before = dict(tr._orchestrator.queue.staleness_counts)
    tr.close()

    tr2 = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=48,
                       rollout_orchestrator=True, max_staleness=1)
    tr2.resume_from_checkpoint()
    tr2.train(num_updates=1)
    hist_after = dict(tr2._orchestrator.queue.staleness_counts)
    tr2.close()
    assert sum(hist_after.values()) == sum(hist_before.values()) + 1


def test_orchestrator_rejected_on_sparse_and_with_rollout_ahead(tmp_path):
    with pytest.raises(ValueError, match="rollout_ahead"):
        make_trainer(AlgoName.GRPO, tmp_path, rollout_orchestrator=True,
                     rollout_ahead=True)

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
    from nanorlhf_tpu.parallel import MeshConfig
    from nanorlhf_tpu.trainer import RLConfig
    from nanorlhf_tpu.trainer.sparse_grpo import SparseGRPOTrainer
    import jax.numpy as jnp

    tok = ToyTokenizer(256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    cfg = RLConfig(
        algo=AlgoName.GRPO, output_dir=str(tmp_path / "sp"),
        response_length=8, sample_n=2, total_episodes=32,
        per_device_train_batch_size=4, gradient_accumulation_steps=1,
        num_mini_batches=1, use_lora=False, gradient_checkpointing=False,
        mesh=MeshConfig(-1, 1, 1), save_steps=0, report_to="none",
        rollout_orchestrator=True,
    )
    st = SparseGRPOTrainer(
        cfg, mcfg, tok, init_params(mcfg, jax.random.PRNGKey(0), jnp.float32),
        load_prompt_dataset("synthetic:64", tok, max_prompt_len=12),
        lambda prs, eos: np.zeros(len(prs), np.float32),
    )
    with pytest.raises(ValueError, match="SparseGRPOTrainer"):
        st.train(num_updates=1)
    st.close()


# ---------------------------------------------------------------------------
# overlap fraction: pipelined orchestrator vs rollout_ahead (acceptance)
# ---------------------------------------------------------------------------


def _make_disagg(tmp_path, **overrides):
    """Disaggregated meshes: 4 train + 4 rollout devices (test_disaggregate
    layout) — generation runs on its own silicon."""
    from test_disaggregate import make_trainer as make_disagg

    return make_disagg(tmp_path, **overrides)


def test_overlap_frac_orchestrator_beats_rollout_ahead(tmp_path):
    """ISSUE-1 acceptance: with disaggregated meshes on the 8-device CPU
    mesh, a pipelined max_staleness=2 run reports strictly higher
    rollout/train overlap than rollout_ahead under the bench's invocation
    pattern (repeated train(num_updates=1) calls — where rollout_ahead's
    in-call prefetch never fires, while the orchestrator's producer thread
    keeps generating across call boundaries)."""
    ahead = _make_disagg(tmp_path / "ahead", rollout_ahead=True)
    ahead.cfg.total_episodes = 48
    for _ in range(3):
        ahead.train(num_updates=1)
    ahead_frac = ahead.rollout_overlap_frac()
    ahead.close()

    orch = _make_disagg(tmp_path / "orch", rollout_orchestrator=True,
                        max_staleness=2, report_to="jsonl")
    orch.cfg.total_episodes = 48
    for _ in range(3):
        orch.train(num_updates=1)
    orch_frac = orch.rollout_overlap_frac()
    # orchestrator metrics reached the payload surface
    rows = _metric_rows(tmp_path / "orch" / "disagg")
    assert "time/rollout_overlap_frac" in rows[-1]
    assert "orchestrator/queue_depth" in rows[-1]
    orch.close()

    assert orch_frac > ahead_frac, (
        f"pipelined overlap {orch_frac:.3f} not above rollout_ahead "
        f"{ahead_frac:.3f}"
    )


def test_orchestrated_all_dense_algos_one_update(tmp_path):
    """Every dense algorithm trains one update through the pipeline (PPO
    exercises the value path under staleness; RAFT skips the IS hook)."""
    for algo in (AlgoName.RLOO, AlgoName.RAFT, AlgoName.PPO):
        tr = make_trainer(algo, tmp_path / algo.value, total_episodes=16,
                          save_steps=0, rollout_orchestrator=True,
                          max_staleness=1, sampler_logprob_capture=True)
        state = tr.train()
        tr.close()
        assert state["global_step"] == 1, algo


# ---------------------------------------------------------------------------
# truncated-IS loss math
# ---------------------------------------------------------------------------


def test_truncated_is_loss_math():
    import jax.numpy as jnp

    from nanorlhf_tpu.algos.losses import (
        grpo_loss,
        ppo_clip_loss_sequence,
        ppo_clip_loss_token,
        truncated_is_weights,
    )

    rng = np.random.default_rng(0)
    B, T = 4, 6
    new = jnp.asarray(rng.normal(-1.0, 0.3, (B, T)).astype(np.float32))
    old = jnp.asarray(rng.normal(-1.0, 0.3, (B, T)).astype(np.float32))
    adv = jnp.asarray(rng.normal(0.0, 1.0, (B, T)).astype(np.float32))
    mask = jnp.ones((B, T), bool)

    # behavior == old → weights exactly 1 → losses identical to uncorrected
    for fn, args in [
        (ppo_clip_loss_token, (new, old, adv, mask, 0.2)),
        (grpo_loss, (new, old, old, adv, mask, 0.2, 0.05)),
        (ppo_clip_loss_sequence, (new, old, adv[:, 0], mask, 0.2)),
    ]:
        base, _ = fn(*args)
        corrected, aux = fn(*args, behavior_logprobs=old, is_truncation=2.0)
        np.testing.assert_allclose(np.asarray(base), np.asarray(corrected),
                                   rtol=1e-6)
        assert float(aux["is_weight_mean"]) == pytest.approx(1.0)
        assert float(aux["is_trunc_frac"]) == 0.0

    # a much-less-likely behavior token → raw weight above ρ̄ → truncated
    behavior = old - 3.0  # π_old/μ = e^3 ≈ 20 ≫ ρ̄
    w, truncated = truncated_is_weights(old, behavior, 2.0)
    assert np.all(np.asarray(w) == 2.0) and np.all(np.asarray(truncated))
    _, aux = ppo_clip_loss_token(new, old, adv, mask, 0.2,
                                 behavior_logprobs=behavior,
                                 is_truncation=2.0)
    assert float(aux["is_trunc_frac"]) == 1.0
    assert float(aux["is_weight_mean"]) == pytest.approx(2.0)
