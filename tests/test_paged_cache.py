"""Paged KV cache + continuous batching (sampler/paged/, ISSUE 10).

Pins the acceptance contract: allocator invariants under jit, the paged
Pallas kernels vs their gather-then-reference XLA oracles (f32 + int8 +
k-query verify), greedy bit-parity of the monolithic paged layout vs the
contiguous cache on the CPU mesh (page size dividing AND not dividing the
logical width), composition with speculative decode / int8 / shared-prefill
fanout, the continuous-batching scheduler finishing a long-tail corpus in
STRICTLY fewer decode iterations than the fixed-batch schedule while
emitting identical greedy rows, and the trainer wiring (rollout/page_*
metric rows, checkpoint/resume over the paged rollout path).

The long-tail oracle reuses test_speculative's "cycle model": a Markov
chain over single tokens, so each row's greedy length is constructed by
hand and the fixed-batch iteration count is analytic (per batch: longest
row minus one).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.sampler import SamplingParams, generate
from nanorlhf_tpu.sampler.paged.pages import (
    PageState, alloc_row, blocks_per_row, full_table, init_page_state,
    release_row,
)

EOS, PAD = 3, 0


@pytest.fixture(scope="module")
def tiny():
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(7), jnp.float32)
    return config, params


def _left_pad(rows, T, pad=PAD):
    ids = np.full((len(rows), T), pad, np.int32)
    for i, r in enumerate(rows):
        ids[i, T - len(r):] = r
    ids = jnp.asarray(ids)
    return ids, ids != pad


def _gen(model, key=0, max_tokens=19, prompts=None, stats=None, **kw):
    cfg, params = model
    ids, mask = prompts if prompts is not None else _left_pad(
        [[5, 6, 7, 8], [PAD, 9, 10], [11, 12, 13, 14]], 5
    )
    sp = SamplingParams(max_tokens=max_tokens, **kw)
    return generate(params, cfg, ids, mask, jax.random.PRNGKey(key), sp,
                    eos_token_id=EOS, pad_token_id=PAD,
                    paged_stats_out=stats)


# --------------------------------------------------------------------- #
# allocator: free-list/block-table invariants, fully jitted
# --------------------------------------------------------------------- #

def test_allocator_invariants_under_jit():
    N, R, nb = 12, 4, 3
    alloc = jax.jit(alloc_row)
    release = jax.jit(release_row)
    st = init_page_state(N, R, nb)
    assert int(st.top) == N and (np.asarray(st.table) == N).all()

    # allocate all four rows: every page handed out exactly once
    for r in range(R):
        st, ok = alloc(st, r, nb)
        assert bool(ok)
    tab = np.asarray(st.table)
    assert int(st.top) == 0
    assert sorted(tab.ravel().tolist()) == list(range(N))

    # exhausted pool: ok=False and the state is UNCHANGED
    st2, ok = alloc(st, 0, 1)
    assert not bool(ok)
    np.testing.assert_array_equal(np.asarray(st2.table), tab)
    assert int(st2.top) == int(st.top)

    # release row 2, realloc into row 1's old slot: the SAME pages come back
    freed = set(tab[2].tolist())
    st, m = release(st, 2)
    assert int(m) == nb and int(st.top) == nb
    assert (np.asarray(st.table)[2] == N).all()
    # idempotent: releasing a sentinel row is a no-op
    st3, m2 = release(st, 2)
    assert int(m2) == 0 and int(st3.top) == nb
    st, ok = alloc(st, 2, nb)
    assert bool(ok) and set(np.asarray(st.table)[2].tolist()) == freed

    # partial allocation (traced n_blocks < nb): sentinel tail on the row
    st = init_page_state(N, R, nb)
    st, ok = alloc(st, 1, jnp.int32(2))
    row = np.asarray(st.table)[1]
    assert bool(ok) and int(st.top) == N - 2
    assert (row[:2] < N).all() and row[2] == N


def test_blocks_per_row_and_full_table():
    assert blocks_per_row(24, 8) == 3 and blocks_per_row(25, 8) == 4
    t = np.asarray(full_table(3, 2))
    np.testing.assert_array_equal(t, [[0, 1], [2, 3], [4, 5]])


# --------------------------------------------------------------------- #
# paged kernels vs XLA oracles (interpret mode off-TPU)
# --------------------------------------------------------------------- #

def _scattered_pool(rng, B, KV, hd, P, nb, extra=2):
    """Pool whose pages are a random permutation (plus one sentinel block),
    with the logical contiguous view returned for cross-checking."""
    N = B * nb + extra
    perm = rng.permutation(N - 1)[: B * nb].reshape(B, nb).astype(np.int32)
    perm[0, -1] = N                       # released block on row 0
    T = nb * P
    k_log = rng.standard_normal((B, KV, T, hd)).astype(np.float32)
    v_log = rng.standard_normal((B, KV, T, hd)).astype(np.float32)
    k_pool = np.zeros((N, KV, P, hd), np.float32)
    v_pool = np.zeros((N, KV, P, hd), np.float32)
    for b in range(B):
        for j in range(nb):
            if perm[b, j] < N:
                k_pool[perm[b, j]] = k_log[b, :, j * P:(j + 1) * P, :]
                v_pool[perm[b, j]] = v_log[b, :, j * P:(j + 1) * P, :]
    return (jnp.asarray(perm), jnp.asarray(k_pool), jnp.asarray(v_pool),
            N, T)


def test_paged_decode_kernel_matches_oracle(rng):
    from nanorlhf_tpu.ops.decode_attention import (
        paged_decode_attention, reference_paged_decode_attention,
    )

    B, KV, G, hd, P, nb = 3, 2, 4, 16, 8, 5
    table, k_pool, v_pool, N, T = _scattered_pool(rng, B, KV, hd, P, nb)
    q = jnp.asarray(rng.standard_normal((B, KV * G, hd)).astype(np.float32))
    start = jnp.asarray([0, 3, 9], jnp.int32)
    filled = jnp.asarray([17, 30, 25], jnp.int32)  # row0 below its sentinel
    want = reference_paged_decode_attention(q, k_pool, v_pool, table, start,
                                            filled)
    got = paged_decode_attention(q, k_pool, v_pool, table, start, filled,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_paged_decode_q8_kernel_matches_oracle(rng):
    from nanorlhf_tpu.ops.decode_attention import (
        paged_decode_attention_q8, reference_paged_decode_attention_q8,
    )

    B, KV, G, hd, P, nb = 3, 2, 4, 16, 8, 4
    table, _, _, N, T = _scattered_pool(rng, B, KV, hd, P, nb)
    q = jnp.asarray(rng.standard_normal((B, KV * G, hd)).astype(np.float32))
    kq = jnp.asarray(rng.integers(-127, 127, (N, KV, P, hd)).astype(np.int8))
    vq = jnp.asarray(rng.integers(-127, 127, (N, KV, P, hd)).astype(np.int8))
    ks = jnp.asarray(rng.uniform(0.005, 0.02, (N, KV, 8, P)).astype(np.float32))
    vs = jnp.asarray(rng.uniform(0.005, 0.02, (N, KV, 8, P)).astype(np.float32))
    start = jnp.asarray([0, 2, 7], jnp.int32)
    filled = jnp.asarray([13, 24, 19], jnp.int32)
    want = reference_paged_decode_attention_q8(q, kq, ks, vq, vs, table,
                                               start, filled)
    got = paged_decode_attention_q8(q, kq, ks, vq, vs, table, start, filled,
                                    interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_paged_verify_kernel_matches_oracle(rng):
    from nanorlhf_tpu.ops.decode_attention import (
        paged_decode_verify_attention,
        reference_paged_decode_verify_attention,
    )

    B, KV, G, hd, P, nb, Tq = 3, 2, 4, 16, 8, 5, 4
    table, k_pool, v_pool, N, T = _scattered_pool(rng, B, KV, hd, P, nb)
    q = jnp.asarray(
        rng.standard_normal((B, KV * G, Tq, hd)).astype(np.float32))
    start = jnp.asarray([0, 3, 9], jnp.int32)
    fill = jnp.asarray([10, 22, 15], jnp.int32)   # row 1 straddles a page
    want = reference_paged_decode_verify_attention(q, k_pool, v_pool, table,
                                                   start, fill)
    got = paged_decode_verify_attention(q, k_pool, v_pool, table, start,
                                        fill, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


# --------------------------------------------------------------------- #
# monolithic paged layout: bit-parity with the contiguous cache
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("page_size", [8, 5])  # 8 | 24 = Tp+max_tokens; 5 ∤
def test_greedy_paged_bit_identical(tiny, page_size):
    mono = _gen(tiny, greedy=True)
    paged = _gen(tiny, greedy=True, page_size=page_size)
    np.testing.assert_array_equal(np.asarray(mono), np.asarray(paged))


def test_paged_capture_logprobs_bit_identical(tiny):
    mt, mlp = _gen(tiny, greedy=True, capture_logprobs=True)
    pt, plp = _gen(tiny, greedy=True, capture_logprobs=True, page_size=8)
    np.testing.assert_array_equal(np.asarray(mt), np.asarray(pt))
    np.testing.assert_array_equal(np.asarray(mlp), np.asarray(plp))


def test_paged_int8_kv_cache_bit_identical(tiny):
    cfg, params = tiny
    q_model = (dataclasses.replace(cfg, kv_cache_quant="int8"), params)
    mono = _gen(q_model, greedy=True)
    for P in (8, 5):
        paged = _gen(q_model, greedy=True, page_size=P)
        np.testing.assert_array_equal(np.asarray(mono), np.asarray(paged))


def test_paged_spec_matches_monolithic(tiny):
    """spec_k composes with page_size: paged verify writes land through the
    block table and the greedy stream still equals the plain monolithic
    loop (greedy spec is bit-exact, paged is a pure re-layout)."""
    mono = _gen(tiny, greedy=True)
    for P in (8, 5):
        paged = _gen(tiny, greedy=True, spec_k=3, page_size=P)
        np.testing.assert_array_equal(np.asarray(mono), np.asarray(paged))


def test_paged_shared_prefill_fanout_bit_identical(tiny):
    prompts = _left_pad([[5, 6, 7], [9, 10, 11]], 4)
    mono = _gen(tiny, greedy=True, n=2, prompts=prompts)
    paged = _gen(tiny, greedy=True, n=2, page_size=8, prompts=prompts)
    assert paged.shape == (4, 19)
    np.testing.assert_array_equal(np.asarray(mono), np.asarray(paged))


def test_paged_sampled_stream_bit_identical(tiny):
    """Sampled (non-greedy) monolithic paged: the logits are bit-identical,
    so the SAME key draws the SAME stream."""
    mono = _gen(tiny, key=11, temperature=0.9)
    paged = _gen(tiny, key=11, temperature=0.9, page_size=8)
    np.testing.assert_array_equal(np.asarray(mono), np.asarray(paged))


def test_paged_with_compaction_raises(tiny):
    with pytest.raises(ValueError, match="page_size"):
        _gen(tiny, page_size=8, compaction_segments=2)


def test_cache_extra_gated_to_contiguous(tiny):
    """The spec path's cache_extra slack must NOT inflate the paged pool:
    pool pages == B * ceil((Tp+max_tokens)/P) exactly, slack or not —
    over-budget verify writes drop at the table-routed scatter instead."""
    from nanorlhf_tpu.sampler.sampler import _prefill_state

    cfg, params = tiny
    ids, mask = _left_pad([[5, 6, 7, 8]], 5)
    kw = dict(max_tokens=7, eos_token_id=EOS, pad_token_id=PAD,
              temperature=1.0, top_p=0.95, greedy=True, lora_scale=1.0,
              top_k=64, capture_logprobs=False, approx_top_k=True)
    P = 4
    nb = blocks_per_row(5 + 7, P)
    state = _prefill_state(params, cfg, ids, mask, jax.random.PRNGKey(0),
                           cache_extra=3, page_size=P, **kw)
    assert state[3][0].shape[1] == 1 * nb       # pool pages, NO slack
    assert state[4].shape[1] == 5 + 7           # key_mask width, NO slack
    contig = _prefill_state(params, cfg, ids, mask, jax.random.PRNGKey(0),
                            cache_extra=3, **kw)
    assert contig[4].shape[1] == 5 + 7 + 3      # contiguous keeps the slack


# --------------------------------------------------------------------- #
# continuous batching: long-tail corpus, strictly fewer iterations
# --------------------------------------------------------------------- #

def _chain_model():
    """Markov chains: v -> v+1 -> ... -> 30 -> EOS, so a prompt ending in
    token v generates exactly (30 - v) + 1 tokens greedily. Long-tail
    lengths are then just a choice of start tokens."""
    from tests.test_speculative import cycle_model

    sigma = list(range(32))
    for t in range(10, 30):
        sigma[t] = t + 1
    sigma[30] = EOS
    return cycle_model(sigma, vocab=32)


def _chain_prompts(starts, Tp=2):
    return _left_pad([[9, v] for v in starts], Tp)


def test_queued_long_tail_fewer_iterations_same_tokens():
    """The acceptance gate: one straggler per R-row wave. The fixed-batch
    schedule pays (longest row - 1) decode iterations PER WAVE; the
    scheduler backfills finished rows mid-loop and must land strictly
    under that — while emitting exactly the monolithic greedy rows."""
    model = _chain_model()
    # lengths 20, 3, 18, 4, 16, 3, 14, 5 (start v -> 31 - v tokens)
    starts = [11, 28, 13, 27, 15, 28, 17, 26]
    lengths = [31 - v for v in starts]
    prompts = _chain_prompts(starts)
    R, max_tokens = 2, 24

    mono = _gen(model, greedy=True, max_tokens=max_tokens, prompts=prompts)
    stats = []
    queued = _gen(model, greedy=True, max_tokens=max_tokens, prompts=prompts,
                  page_size=4, decode_rows=R, stats=stats)
    np.testing.assert_array_equal(np.asarray(mono), np.asarray(queued))

    # analytic fixed-batch schedule at the same resident-batch size R
    fixed_iters = sum(max(lengths[i:i + R]) - 1
                      for i in range(0, len(lengths), R))
    st = stats[0]
    assert st["decode_iterations"] < fixed_iters, (
        f"queued {st['decode_iterations']} >= fixed {fixed_iters}")
    assert st["admitted_midloop"] == len(starts) - R
    assert st["pages_recycled"] > 0
    assert 0.0 < st["page_utilization"] <= 1.0
    # every admission names a valid resident row and queue entry
    for adm in st["admissions"]:
        assert 0 <= adm["row"] < R
        assert R <= adm["queue_index"] < len(starts)


def test_queued_spec_composes_and_matches():
    """spec_k over the recycled pool: same greedy rows, and the verify
    dispatch count lands under the plain queued iteration count on the
    self-repetitive tail (the drafter pays off mid-queue too)."""
    from tests.test_speculative import cycle_model

    sigma = list(range(16))
    sigma[5], sigma[6], sigma[7], sigma[8] = 6, 7, 8, 5   # 4-cycle, no EOS
    model = cycle_model(sigma)
    prompts = _left_pad([[5, 6, 7, 8, 5]] * 6, 6)
    mono = _gen(model, greedy=True, max_tokens=24, prompts=prompts)
    plain_stats, spec_stats = [], []
    q_plain = _gen(model, greedy=True, max_tokens=24, prompts=prompts,
                   page_size=4, decode_rows=2, stats=plain_stats)
    q_spec = _gen(model, greedy=True, max_tokens=24, prompts=prompts,
                  page_size=4, decode_rows=2, spec_k=4, stats=spec_stats)
    np.testing.assert_array_equal(np.asarray(mono), np.asarray(q_plain))
    np.testing.assert_array_equal(np.asarray(mono), np.asarray(q_spec))
    assert (spec_stats[0]["decode_iterations"]
            < plain_stats[0]["decode_iterations"])


def test_queued_sampled_rows_terminate_and_fill_contract():
    """Sampled queued rollouts: not bit-pinned (admission re-keys rows),
    but every row must satisfy the output contract — tokens before the
    first PAD, nothing after an EOS, shapes exact."""
    cfg = ModelConfig.qwen2_tiny(vocab_size=64)
    params = init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    prompts = _left_pad([[5 + i, 6, 7] for i in range(7)], 4)
    out = _gen((cfg, params), key=5, max_tokens=10, prompts=prompts,
               temperature=1.0, page_size=4, decode_rows=3)
    rows = np.asarray(out)
    assert rows.shape == (7, 10)
    for r in rows:
        if EOS in r.tolist():
            e = r.tolist().index(EOS)
            assert (r[e + 1:] == PAD).all()


# --------------------------------------------------------------------- #
# trainer wiring: metrics rows + checkpoint/resume over the paged path
# --------------------------------------------------------------------- #

def _paged_trainer(tmp_path, decode_rows=4):
    from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
    from nanorlhf_tpu.parallel import MeshConfig
    from nanorlhf_tpu.trainer import AlgoName, RLConfig, RLTrainer

    mcfg = ModelConfig.qwen2_tiny(vocab_size=512)
    tok = ToyTokenizer(vocab_size=512)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.bfloat16)
    dataset = load_prompt_dataset("synthetic:32", tok, max_prompt_len=16)

    def reward(pmt_and_responses, eos_token):
        return np.asarray([float(len(s) % 3) for s in pmt_and_responses],
                          np.float32)

    cfg = RLConfig(
        algo=AlgoName.GRPO, output_dir=str(tmp_path), response_length=16,
        sample_n=2, per_device_train_batch_size=1,
        gradient_accumulation_steps=1, num_mini_batches=1,
        total_episodes=64, rollout_page_size=4,
        rollout_decode_rows=decode_rows,
        use_lora=True, save_steps=1, mesh=MeshConfig(data=-1),
        report_to="jsonl", logging_steps=1, sentinel=False,
    )
    return RLTrainer(cfg, mcfg, tok, params, dataset, reward)


def test_trainer_paged_metrics_and_resume(tmp_path):
    """2-update GRPO smoke over the continuous-batching rollout path: the
    metrics rows must carry rollout/page_utilization + pages_recycled +
    admitted_midloop (docs/METRICS.md), /statusz must expose the "pages"
    section, and a checkpoint/resume must continue training over the same
    paged path."""
    import json
    import os

    trainer = _paged_trainer(tmp_path / "ck")
    try:
        trainer.train(num_updates=2)
        status = trainer._statusz()
        assert status["pages"] is not None
        assert status["pages"]["page_size"] == 4
        assert status["pages"]["page_utilization"] is not None
        saved_step = trainer.state["global_step"]
    finally:
        trainer.close()
    rows = [json.loads(l) for l in open(
        os.path.join(str(tmp_path / "ck"), "metrics.jsonl")
    ) if l.strip()]
    step_rows = [r for r in rows if "rollout/page_utilization" in r]
    assert len(step_rows) >= 2
    for r in step_rows:
        assert 0.0 < r["rollout/page_utilization"] <= 1.0
        assert r["rollout/pages_recycled"] >= 0.0
        assert r["rollout/admitted_midloop"] >= 0.0

    tr2 = _paged_trainer(tmp_path / "ck")
    try:
        tr2.resume_from_checkpoint()
        assert tr2.state["global_step"] == saved_step
        tr2.train(num_updates=1)
        assert tr2.state["global_step"] == saved_step + 1
    finally:
        tr2.close()
