"""Dataset item processors vs the reference's data-processing toolkit
(`/root/reference/examples/r1-v0/utils/data_processing/process_utils.py`)."""

import pytest

from nanorlhf_tpu.data.process_utils import (
    PROCESSORS,
    get_processor,
    process_items,
)


def test_gsm8k_strips_calculator_and_boxes_answer():
    (s,) = process_items("gsm8k", [{
        "id": 1,
        "question": "Tom has 3 apples and buys 4 more. How many?",
        "cot": "3 + 4 = <<3+4=7>>7.",
        "answer": "7",
    }])
    assert s["dataset"] == "gsm8k-cot"
    assistant = s["messages"][1]["content"]
    assert "<<" not in assistant and ">>" not in assistant
    assert assistant.endswith("So the answer is $\\boxed{7}$.")
    assert s["answer"] == "7"


def test_gsm8k_decommas_answer():
    (s,) = process_items("gsm8k", [{
        "id": 1, "question": "q", "cot": "c", "answer": "1,234",
    }])
    assert s["answer"] == "1234"


def test_math_extracts_gold_from_solution():
    (s,) = process_items("math", [{
        "id": "m1",
        "problem": "What is 2+2?",
        "solution": "We compute. The final answer is $\\boxed{4}$.",
        "level": "Level 1",
        "type": "Algebra",
        "category": "arith",
    }])
    assert s["answer"] == ["4"]
    assert s["level"] == "Level 1"


def test_math_drops_unextractable_items():
    out = process_items("math", [{
        "id": "m2", "problem": "p", "solution": "no final value stated here",
    }])
    assert out == []


def test_math_solution_reflowed_per_sentence():
    (s,) = process_items("math", [{
        "id": "m3",
        "problem": "p",
        "solution": "First step. Second step. The answer is $\\boxed{1}$.",
    }])
    assistant = s["messages"][1]["content"]
    assert assistant.splitlines()[0] == "First step."
    assert assistant.splitlines()[1] == "Second step."


def test_math_sat_reflows_options():
    (s,) = process_items("math_sat", [{
        "id": 9,
        "question": "Pick one.",
        "options": "A) one B) two C) three",
        "Answer": "B",
    }])
    q = s["messages"][0]["content"]
    assert "(A) one" in q and "(B) two" in q and "(C) three" in q
    assert "right choice" in q
    assert s["answer"] == "B"


def test_mmlu_stem_labels_options():
    (s,) = process_items("mmlu_stem", [{
        "id": 2,
        "question": "Which gas?",
        "options": ["O2", "N2", "CO2", "He"],
        "answer": "A",
    }])
    q = s["messages"][0]["content"]
    assert "(A) O2, (B) N2, (C) CO2, (D) He" in q


def test_mgsm_zh_decommas_in_place():
    (s,) = process_items("mgsm-zh", [{
        "id": 3, "question": "q", "answer": "2,000",
    }])
    assert s["answer"] == "2000"
    assert s["question"] == "q"  # passthrough of other fields


def test_cmath_uses_golden_field():
    (s,) = process_items("cmath", [{
        "id": 4, "question": " q ", "golden": " 1,5 ",
        "grade": 3, "reasoning_step": 2,
    }])
    assert s["answer"] == "15"
    assert s["messages"][0]["content"] == "q"


def test_gaokao_cloze_splits_multi_answer():
    (s,) = process_items("agieval-gaokao-math-cloze", [{
        "id": 5, "question": "fill in", "answer": "1; 2",
    }])
    assert s["answer"] == ["1", "2"]


def test_gaokao_mathqa_reflows_paren_options():
    (s,) = process_items("agieval-gaokao-mathqa", [{
        "id": 6,
        "question": "choose",
        "options": ["(A) 1", "(B) 2"],
        "label": "A",
    }])
    assert s["answer"] == "A"
    assert "A: 1" in s["messages"][0]["content"]


def test_gaokao_mathqa_rejects_malformed_options():
    with pytest.raises(ValueError):
        process_items("agieval-gaokao-mathqa", [{
            "id": 6, "question": "q", "options": ["A) 1"], "label": "A",
        }])


def test_minif2f_wraps_informal_as_comment():
    (s,) = process_items("minif2f-isabelle", [{
        "id": 7,
        "informal_statement": "stmt",
        "informal_proof": "proof",
        "formal_statement": "theorem t: ...",
    }])
    q = s["messages"][0]["content"]
    assert q.startswith("(*### Problem")
    assert q.endswith("Formal:\ntheorem t: ...")


def test_registry_lookup_normalizes_and_raises():
    assert get_processor("GSM8K") is PROCESSORS["gsm8k"]
    with pytest.raises(KeyError):
        get_processor("nope")
