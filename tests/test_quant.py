"""Weight-only int8 rollout quantization (core/quant.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.core.model import padded_forward_logits
from nanorlhf_tpu.core.quant import (
    dequantize_kernel,
    quantize_kernel,
    quantize_layers,
    rollout_view,
)
from nanorlhf_tpu.trainer import AlgoName

from test_trainer_smoke import make_trainer


def test_quantize_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.PRNGKey(0), (4, 64, 96), jnp.float32)
    q, scale = quantize_kernel(w)
    assert q.dtype == jnp.int8 and scale.shape == (4, 1, 96)
    back = dequantize_kernel(q, scale, jnp.float32)
    # symmetric per-channel int8: error <= scale/2 = absmax/254 per element
    absmax = np.abs(np.asarray(w)).max(axis=1, keepdims=True)
    err = np.abs(np.asarray(back) - np.asarray(w))
    assert (err <= absmax / 254.0 + 1e-7).all()


def test_quantized_forward_close_to_exact():
    mcfg = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    qparams = rollout_view(params, quantize_layers(params["layers"]))
    ids = jnp.asarray(np.full((2, 12), 7, np.int32))
    exact = padded_forward_logits(params, mcfg, ids, 0)
    quant = padded_forward_logits(qparams, mcfg, ids, 0)
    # logits agree to int8-noise level; argmax (greedy decode) agrees except
    # possibly at near-ties (platform matmul precision can flip those, so an
    # exact-equality assert would be TPU-fragile)
    rel = float(jnp.max(jnp.abs(exact - quant)) / (jnp.max(jnp.abs(exact)) + 1e-6))
    assert rel < 0.05, rel
    agree = (
        np.asarray(jnp.argmax(exact, -1)) == np.asarray(jnp.argmax(quant, -1))
    ).mean()
    assert agree >= 0.9, agree


@pytest.mark.parametrize("use_lora", [True, False])
def test_trainer_int8_rollout_smoke(tmp_path, use_lora):
    trainer = make_trainer(
        AlgoName.GRPO, tmp_path, total_episodes=32, save_steps=0,
        rollout_quant="int8", use_lora=use_lora,
    )
    assert trainer._quant_layers is not None
    assert trainer._quant_layers["q_proj"]["kernel_q"].dtype == jnp.int8
    state = trainer.train()
    assert state["global_step"] == 2


def test_int8_with_rollout_ahead(tmp_path):
    trainer = make_trainer(
        AlgoName.GRPO, tmp_path, total_episodes=32, save_steps=0,
        rollout_quant="int8", rollout_ahead=True,
    )
    state = trainer.train()
    assert state["global_step"] == 2
