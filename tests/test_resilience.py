"""Resilience layer (nanorlhf_tpu/resilience/, docs/RESILIENCE.md):

- fault-injection schedules are deterministic and spec-parseable;
- a producer crash is restarted by the watchdog with bit-identical
  post-recovery token streams (staleness 0), and a persistently crashing
  producer degrades to synchronous rollouts that reproduce the serial
  trainer exactly instead of killing the run;
- a NaN update trips the sentinel, rolls back to the last committed
  checkpoint, quarantines the offending batch, and replays the stream
  bit-identically (lr=0 anchor against a clean run's rows);
- an injected checkpoint-write failure is retried and succeeds;
- SIGTERM commits a resumable emergency checkpoint and the resumed run
  matches an uninterrupted one;
- a no-fault run with the sentinel enabled is numerically identical to one
  with it disabled.
"""

import json
import os
import signal

import numpy as np
import pytest

from nanorlhf_tpu.resilience import (
    FaultInjector,
    InjectedFault,
    Preempted,
    PreemptionGuard,
    ProducerWatchdog,
    SentinelBudgetExceeded,
    SentinelConfig,
    TrainingSentinel,
    WatchdogConfig,
    parse_fault_spec,
    retry_with_backoff,
)
from nanorlhf_tpu.trainer import AlgoName

from test_trainer_smoke import make_trainer


def _metric_rows(outdir):
    rows = []
    with open(outdir / "metrics.jsonl") as f:
        for line in f:
            row = json.loads(line)
            if "episode" in row:
                rows.append(row)
    return rows


# rollout-level keys: functions of (data batch, generation PRNG, policy
# params) only — the bit-exact stream comparators used throughout
STREAM_KEYS = ("eval_objective/scores_old", "objective/entropy_old",
               "objective/kl_rollout_old")


# ---------------------------------------------------------------------------
# fault injection registry
# ---------------------------------------------------------------------------


def test_fault_spec_parsing_and_validation():
    scheds = parse_fault_spec("ckpt.save:at=3 rollout.produce:every=2;"
                              "update.step:prob=0.5,seed=7,action=nan")
    assert [s.point for s in scheds] == ["ckpt.save", "rollout.produce",
                                         "update.step"]
    assert scheds[0].at == 3 and scheds[0].count == 1  # `at` fires once
    assert scheds[2].action == "nan"
    with pytest.raises(ValueError, match="unknown injection point"):
        parse_fault_spec("no.such.point:at=1")
    with pytest.raises(ValueError, match="exactly one"):
        parse_fault_spec("ckpt.save:at=1,every=2")
    with pytest.raises(ValueError, match="action"):
        parse_fault_spec("ckpt.save:at=1,action=explode")


def test_fault_schedules_fire_deterministically():
    inj = FaultInjector.from_spec("ckpt.save:at=2")
    inj.fire("ckpt.save")                      # call 1: no fire
    with pytest.raises(InjectedFault):
        inj.fire("ckpt.save")                  # call 2: fires (once)
    inj.fire("ckpt.save")                      # call 3: spent
    assert inj.stats()["ckpt.save"] == {"calls": 3, "fires": 1}

    every = FaultInjector.from_spec("reward.exec:every=3")
    fired = []
    for i in range(1, 10):
        try:
            every.fire("reward.exec")
        except InjectedFault:
            fired.append(i)
    assert fired == [3, 6, 9]

    # seeded prob schedules replay the same fire pattern
    def pattern():
        inj = FaultInjector.from_spec("update.step:prob=0.4,seed=11,count=100")
        out = []
        for _ in range(50):
            try:
                inj.fire("update.step")
                out.append(0)
            except InjectedFault:
                out.append(1)
        return out

    assert pattern() == pattern()
    assert sum(pattern()) > 0

    # unarmed points are free and silent
    assert FaultInjector.from_spec(None).fire("ckpt.save") is None

    # nan action returns instead of raising
    nan = FaultInjector.from_spec("update.step:at=1,action=nan")
    assert nan.fire("update.step") == "nan"


def test_retry_with_backoff_counts_and_raises():
    calls = {"n": 0}
    retries = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    out = retry_with_backoff(flaky, attempts=3, backoff_base=0.0,
                             on_retry=lambda i, e: retries.append(i))
    assert out == "ok" and retries == [0, 1]

    def always_fail():
        raise OSError("permanent")

    with pytest.raises(OSError, match="permanent"):
        retry_with_backoff(always_fail, attempts=2, backoff_base=0.0)


# ---------------------------------------------------------------------------
# sentinel / watchdog policy units (no trainer)
# ---------------------------------------------------------------------------


def test_sentinel_nonfinite_and_spike_detection():
    s = TrainingSentinel(SentinelConfig(spike_zscore=4.0, warmup_steps=5))
    assert s.observe(float("nan")) == "nonfinite"
    assert s.observe(1.0, grad_norm=float("inf")) == "nonfinite"
    rng = np.random.default_rng(0)
    for _ in range(30):
        assert s.observe(1.0 + 0.01 * rng.standard_normal()) is None
    assert s.observe(50.0) == "spike"
    # the spike was NOT folded into the EWMA: a repeat still trips
    assert s.observe(50.0) == "spike"
    # budget: two rollbacks allowed, the third raises
    s.cfg.rollback_budget = 2
    s.note_rollback(1, 0, "spike")
    s.note_rollback(2, 1, "spike")
    with pytest.raises(SentinelBudgetExceeded):
        s.note_rollback(3, 2, "spike")
    assert s.quarantined == {0, 1, 2}


def test_sentinel_journal_roundtrip():
    s = TrainingSentinel(SentinelConfig())
    for x in (1.0, 1.1, 0.9):
        s.observe(x)
    s.note_rollback(3, 7, "nonfinite")
    j = json.loads(json.dumps(s.journal()))  # must be JSON-able
    s2 = TrainingSentinel(SentinelConfig())
    s2.restore(j)
    assert s2.steps == s.steps and s2.ewma == pytest.approx(s.ewma)
    assert s2.rollbacks == 1 and s2.quarantined == {7}


def test_sentinel_disabled_observes_nothing():
    s = TrainingSentinel(SentinelConfig(enabled=False))
    assert s.observe(float("nan")) is None


def test_watchdog_budget_backoff_and_degrade():
    w = ProducerWatchdog(WatchdogConfig(restart_budget=2, backoff_base=0.5,
                                        backoff_max=10.0))
    d1, b1 = w.on_failure()
    d2, b2 = w.on_failure()
    assert (d1, d2) == (ProducerWatchdog.RESTART, ProducerWatchdog.RESTART)
    assert b2 == 2 * b1  # exponential
    d3, _ = w.on_failure()
    assert d3 == ProducerWatchdog.DEGRADE and w.degraded
    assert w.restarts_total == 2

    # a consumed sample resets the consecutive streak
    w2 = ProducerWatchdog(WatchdogConfig(restart_budget=1))
    assert w2.on_failure()[0] == ProducerWatchdog.RESTART
    w2.on_success()
    assert w2.on_failure()[0] == ProducerWatchdog.RESTART

    # degrade_to_sync=False re-raises instead
    w3 = ProducerWatchdog(WatchdogConfig(restart_budget=0,
                                         degrade_to_sync=False))
    assert w3.on_failure()[0] == ProducerWatchdog.RAISE


def test_queue_drains_buffered_samples_before_raising_producer_failure():
    """Device-ready samples already in the queue were never lost — a
    watchdog restart must not regenerate them. get() delivers the buffer
    first and only then surfaces the producer's failure."""
    from nanorlhf_tpu.orchestrator import BoundedStalenessQueue, QueuedSample
    from nanorlhf_tpu.orchestrator import ProducerFailed

    q = BoundedStalenessQueue(max_staleness=2)
    q.put(QueuedSample(index=0, version=0, payload="a"))
    q.put(QueuedSample(index=1, version=0, payload="b"))
    q.fail(RuntimeError("producer died"))
    assert q.get(timeout=0.1).payload == "a"
    assert q.get(timeout=0.1).payload == "b"
    with pytest.raises(ProducerFailed, match="rollout producer failed"):
        q.get(timeout=0.1)


def test_null_guard_is_fresh_per_call():
    """graceful_preemption=False trainers must not share trigger state — a
    shared guard would let one trainer's trigger() poison every later one."""
    from nanorlhf_tpu.resilience import null_guard

    a = null_guard()
    a.trigger()
    assert not null_guard().triggered


def test_preemption_guard_manual_and_signal():
    g = PreemptionGuard(install=False)
    assert not g.triggered
    g.trigger()
    assert g.triggered
    g.clear()

    g2 = PreemptionGuard()
    try:
        if g2.installed:  # main thread
            os.kill(os.getpid(), signal.SIGTERM)
            assert g2.triggered
    finally:
        g2.uninstall()


# ---------------------------------------------------------------------------
# fault matrix: producer crash → restart → bit-identical streams
# ---------------------------------------------------------------------------


def _fast_watchdog(**over):
    kw = dict(rollout_orchestrator=True, max_staleness=0, total_episodes=48,
              producer_backoff_base=0.01, producer_backoff_max=0.05)
    kw.update(over)
    return kw


def test_producer_crash_restart_bit_identical(tmp_path):
    """One injected producer crash: the watchdog restarts the pipeline from
    the consumed cursor and the run's rollout-level metric rows are
    BIT-IDENTICAL to an uninjected run's (staleness 0: every sample is
    regenerated from the same published version)."""
    clean = make_trainer(AlgoName.GRPO, tmp_path / "clean", save_steps=0,
                         **_fast_watchdog())
    clean.train()
    clean.close()

    faulted = make_trainer(AlgoName.GRPO, tmp_path / "faulted", save_steps=0,
                           fault_spec="rollout.produce:at=2",
                           **_fast_watchdog())
    faulted.train()
    assert faulted.watchdog.restarts_total == 1
    assert not faulted.watchdog.degraded
    faulted.close()

    a = _metric_rows(tmp_path / "clean" / "grpo")
    b = _metric_rows(tmp_path / "faulted" / "grpo")
    assert len(a) == len(b) == 3
    for ra, rb in zip(a, b):
        for key in STREAM_KEYS + ("loss/policy_avg_new",):
            np.testing.assert_allclose(ra[key], rb[key], rtol=1e-6,
                                       err_msg=key)
    assert b[-1]["resilience/producer_restarts"] == 1.0
    assert b[-1]["resilience/degraded_mode"] == 0.0


def test_producer_crash_degrades_to_sync_matches_serial(tmp_path):
    """A producer that dies on EVERY dispatch exhausts the restart budget
    and degrades to synchronous rollouts — the run completes with rows
    identical to the plain serial trainer (the documented fallback mode)."""
    serial = make_trainer(AlgoName.GRPO, tmp_path / "serial", save_steps=0,
                          total_episodes=48)
    serial.train()
    serial.close()

    deg = make_trainer(AlgoName.GRPO, tmp_path / "deg", save_steps=0,
                       fault_spec="rollout.produce:every=1",
                       **_fast_watchdog(producer_restart_budget=1))
    deg.train()
    assert deg.watchdog.degraded
    assert deg.watchdog.restarts_total == 1
    deg.close()

    a = _metric_rows(tmp_path / "serial" / "grpo")
    b = _metric_rows(tmp_path / "deg" / "grpo")
    assert len(a) == len(b) == 3
    for ra, rb in zip(a, b):
        for key in STREAM_KEYS + ("loss/policy_avg_new",):
            np.testing.assert_allclose(ra[key], rb[key], rtol=1e-6,
                                       err_msg=key)
    assert b[-1]["resilience/degraded_mode"] == 1.0
    # degraded rows must not pretend the pipeline is still up
    assert "orchestrator/queue_depth" not in b[-1]


def test_producer_degrade_disabled_reraises(tmp_path):
    tr = make_trainer(AlgoName.GRPO, tmp_path, save_steps=0,
                      fault_spec="rollout.produce:every=1",
                      **_fast_watchdog(producer_restart_budget=0,
                                       degrade_to_sync=False))
    with pytest.raises(RuntimeError, match="rollout producer"):
        tr.train()
    tr.close()


# ---------------------------------------------------------------------------
# fault matrix: NaN step → sentinel rollback → bit-identical replay
# ---------------------------------------------------------------------------


def test_nan_step_rollback_replays_bit_identical_streams(tmp_path):
    """update 2 observes an injected NaN: the sentinel restores checkpoint 1,
    quarantines update 2's rollout index, and replays. With lr=0 (REINFORCE:
    no selection PRNG) the post-rollback rows must be bit-identical to the
    CLEAN run's rows for the same rollout indices — the replayed data/PRNG
    streams are exactly the journal's."""
    kw = dict(total_episodes=64, learning_rate=0.0, save_steps=1)
    clean = make_trainer(AlgoName.REINFORCE, tmp_path / "clean", **kw)
    clean.train()  # 4 updates of 16 episodes
    clean.close()

    faulted = make_trainer(AlgoName.REINFORCE, tmp_path / "faulted",
                           fault_spec="update.step:at=2,action=nan", **kw)
    state = faulted.train()
    assert state["global_step"] == 4
    assert faulted.sentinel.rollbacks == 1
    assert faulted.sentinel.quarantined == {1}  # update 2's rollout index
    faulted.close()

    a = _metric_rows(tmp_path / "clean" / "reinforce")
    b = _metric_rows(tmp_path / "faulted" / "reinforce")
    assert len(a) == len(b) == 4
    # clean step k consumed rollout k-1; the faulted run quarantined rollout
    # 1, so its steps 2..4 consumed rollouts 2..4 — compare rollout-aligned
    # rows: faulted step s (s >= 2) vs clean step s+1
    for s in (1,):
        for key in STREAM_KEYS:
            np.testing.assert_allclose(a[s - 1][key], b[s - 1][key],
                                       rtol=1e-6, err_msg=key)
    for s in (2, 3):
        for key in STREAM_KEYS:
            np.testing.assert_allclose(a[s][key], b[s - 1][key], rtol=1e-6,
                                       err_msg=f"replayed {key} @ step {s}")
    assert b[-1]["resilience/rollbacks"] == 1.0
    # sentinel journal rode into the checkpoint: a fresh trainer resumes
    # the rollback spend and quarantine set
    res = make_trainer(AlgoName.REINFORCE, tmp_path / "faulted", **kw)
    res.resume_from_checkpoint()
    assert res.sentinel.rollbacks == 1
    assert res.sentinel.quarantined == {1}
    res.close()


def test_nan_step_budget_exhausted_raises(tmp_path):
    tr = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=64,
                      save_steps=1, rollback_budget=0,
                      fault_spec="update.step:at=2,action=nan")
    with pytest.raises(SentinelBudgetExceeded):
        tr.train()
    tr.close()


def test_nan_step_without_checkpoint_raises(tmp_path):
    tr = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=64,
                      save_steps=0,
                      fault_spec="update.step:at=1,action=nan")
    with pytest.raises(RuntimeError, match="no committed checkpoint"):
        tr.train()
    tr.close()


def test_sentinel_enabled_is_numerically_inert(tmp_path):
    """Acceptance: a no-fault run with the sentinel on is numerically
    identical to one with it off — the guard only observes."""
    on = make_trainer(AlgoName.GRPO, tmp_path / "on", total_episodes=48,
                      save_steps=0, sentinel=True)
    on.train()
    on.close()
    off = make_trainer(AlgoName.GRPO, tmp_path / "off", total_episodes=48,
                       save_steps=0, sentinel=False)
    off.train()
    off.close()
    a = _metric_rows(tmp_path / "on" / "grpo")
    b = _metric_rows(tmp_path / "off" / "grpo")
    assert len(a) == len(b) == 3
    for ra, rb in zip(a, b):
        for key in STREAM_KEYS + ("loss/policy_avg_new",
                                  "policy/grad_norm_new"):
            np.testing.assert_allclose(ra[key], rb[key], rtol=0, atol=0,
                                       err_msg=key)


# ---------------------------------------------------------------------------
# fault matrix: checkpoint-write failure → retry succeeds
# ---------------------------------------------------------------------------


def test_ckpt_write_failure_retried_and_committed(tmp_path):
    tr = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=32,
                      save_steps=1, fault_spec="ckpt.save:at=1",
                      ckpt_retry_backoff=0.01)
    tr.train()
    assert tr.ckpt.retry_count == 1
    assert tr.ckpt.latest_step() == 2  # both saves committed
    tr.close()
    rows = _metric_rows(tmp_path / "reinforce")
    assert rows[-1]["resilience/ckpt_retries"] == 1.0
    # the retried checkpoint is genuinely restorable
    res = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=32)
    res.resume_from_checkpoint()
    assert res.state["global_step"] == 2
    res.close()


def test_ckpt_restore_failure_retried(tmp_path):
    tr = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=32,
                      save_steps=1)
    tr.train()
    tr.close()
    res = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=32,
                       fault_spec="ckpt.restore:at=1",
                       ckpt_retry_backoff=0.01)
    res.resume_from_checkpoint()
    assert res.ckpt.retry_count == 1
    assert res.state["global_step"] == 2
    res.close()


def test_ckpt_exhausted_retries_raise(tmp_path):
    tr = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=32,
                      save_steps=1, ckpt_io_retries=1,
                      ckpt_retry_backoff=0.01,
                      fault_spec="ckpt.save:every=1,count=2")
    with pytest.raises(InjectedFault):
        tr.train()
    tr.close()


# ---------------------------------------------------------------------------
# fault matrix: SIGTERM → emergency checkpoint → resumable
# ---------------------------------------------------------------------------


def test_sigterm_emergency_checkpoint_resumes_bit_identical(tmp_path):
    """A SIGTERM delivered mid-run (from the reward phase of update 2 —
    a deterministic delivery point) commits an emergency checkpoint even
    with periodic saves OFF; resuming from it reproduces the uninterrupted
    run's rows exactly."""
    full = make_trainer(AlgoName.GRPO, tmp_path / "full", total_episodes=48,
                        save_steps=0)
    full.train()
    full.close()

    import test_trainer_smoke as smoke

    calls = {"n": 0}

    def sigterm_reward(pmt_and_responses, eos_token):
        calls["n"] += 1
        if calls["n"] == 2:
            os.kill(os.getpid(), signal.SIGTERM)
        return smoke.rule_reward(pmt_and_responses, eos_token)

    half = make_trainer(AlgoName.GRPO, tmp_path / "half", total_episodes=48,
                        save_steps=0)
    if not half._preemption.installed:  # non-main-thread runner: raw SIGTERM
        half.close()                    # would kill the test process
        pytest.skip("SIGTERM handler needs the main thread")
    half.reward_func = sigterm_reward
    with pytest.raises(Preempted, match="emergency checkpoint"):
        half.train()
    assert half.ckpt.latest_step() == 2
    half.close()

    res = make_trainer(AlgoName.GRPO, tmp_path / "half", total_episodes=48,
                       save_steps=0)
    res.resume_from_checkpoint()
    assert res.state["global_step"] == 2
    res.train()
    res.close()

    a = _metric_rows(tmp_path / "full" / "grpo")
    b = _metric_rows(tmp_path / "half" / "grpo")
    assert len(a) == len(b) == 3
    for key in STREAM_KEYS + ("loss/policy_avg_new",):
        np.testing.assert_allclose(a[-1][key], b[-1][key], rtol=1e-4,
                                   err_msg=key)


def test_sparse_trainer_polls_preemption(tmp_path):
    """The sparse runtime installs the same SIGTERM guard as the dense one —
    its loop must poll it too, or a preempted sparse run swallows SIGTERM
    and gets SIGKILLed with no emergency checkpoint."""
    import jax
    import jax.numpy as jnp

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
    from nanorlhf_tpu.parallel import MeshConfig
    from nanorlhf_tpu.trainer import RLConfig
    from nanorlhf_tpu.trainer.sparse_grpo import SparseGRPOTrainer

    tok = ToyTokenizer(256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    cfg = RLConfig(
        algo=AlgoName.GRPO, output_dir=str(tmp_path / "sp"),
        response_length=8, sample_n=2, total_episodes=64, kl_coef=0.0,
        per_device_train_batch_size=4, gradient_accumulation_steps=1,
        num_mini_batches=1, use_lora=False, gradient_checkpointing=False,
        mesh=MeshConfig(-1, 1, 1), save_steps=0, report_to="none",
    )
    rng = np.random.default_rng(0)
    tr = SparseGRPOTrainer(
        cfg, mcfg, tok, init_params(mcfg, jax.random.PRNGKey(0), jnp.float32),
        load_prompt_dataset("synthetic:64", tok, max_prompt_len=12),
        lambda prs, eos: rng.random(len(prs)).astype(np.float32),
    )
    tr._preemption.trigger()  # preempt before the first update completes
    with pytest.raises(Preempted, match="emergency checkpoint"):
        tr.train()
    assert tr.ckpt.latest_step() == tr.state["global_step"]
    tstate = tr.ckpt.load_trainer_state(tr.state["global_step"])
    assert tstate["rollouts"] == tr.state["rollouts"]  # sparse cursor saved
    tr.close()

    # the all-zero-advantage SKIP path must poll too: a skip streak would
    # otherwise bypass the bottom-of-loop poll forever
    cfg.output_dir = str(tmp_path / "sp2")  # tr is closed; reuse its config
    tr2 = SparseGRPOTrainer(
        cfg, mcfg, tok,
        init_params(mcfg, jax.random.PRNGKey(0), jnp.float32),
        load_prompt_dataset("synthetic:64", tok, max_prompt_len=12),
        lambda prs, eos: np.zeros(len(prs), np.float32),  # uniformly failed
    )
    tr2._preemption.trigger()
    with pytest.raises(Preempted, match="sparse skip streak"):
        tr2.train()
    assert tr2.ckpt.latest_step() == tr2.state["global_step"]
    tr2.close()


def test_rollback_rewinds_ewma_statistics(tmp_path):
    """The rollback path must restore checkpoint-era EWMA statistics, not
    the pre-trip ones — re-applying those would fold every replayed loss
    into the mean/variance twice."""
    tr = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=64,
                      learning_rate=0.0, save_steps=2,
                      fault_spec="update.step:at=4,action=nan")
    tr.train()
    assert tr.sentinel.rollbacks == 1
    # checkpoint 2 journaled 2 observations; the trip at step 4 rolled back
    # PAST healthy step 3, whose batch is then replayed — its loss must be
    # folded into checkpoint-era statistics exactly once (pre-fix: the
    # carried pre-trip EWMA counted it twice → steps == global_step + 1)
    assert tr.sentinel.steps == tr.state["global_step"]
    tr.close()


# ---------------------------------------------------------------------------
# reward dispatch retry
# ---------------------------------------------------------------------------


def test_reward_failure_retried(tmp_path):
    tr = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=16,
                      save_steps=0, fault_spec="reward.exec:at=1",
                      reward_retries=1)
    state = tr.train()
    assert state["global_step"] == 1  # the injected failure was absorbed
    tr.close()


def test_reward_retries_exhausted_raise(tmp_path):
    tr = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=16,
                      save_steps=0, fault_spec="reward.exec:every=1,count=5",
                      reward_retries=1)
    with pytest.raises(InjectedFault):
        tr.train()
    tr.close()


# ---------------------------------------------------------------------------
# executor hardening (spawn context + kill escalation)
# ---------------------------------------------------------------------------


def test_executor_spawn_context_and_sigterm_immune_child():
    from nanorlhf_tpu.rewards.python_executor import PythonExecutor

    ex = PythonExecutor(timeout=1.0, term_grace=0.5)
    assert ex.mp_context == "spawn"
    r = ex.run("answer = 6 * 7")
    assert r.ok and r.answer == "42"
    # a child that ignores SIGTERM must still die (kill escalation)
    r = ex.run(
        "import signal, time\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
        "time.sleep(60)\n"
    )
    assert not r.ok and "timeout" in r.error


# ---------------------------------------------------------------------------
# backoff_delay properties (docs/RESILIENCE.md: jittered retry schedule)
# ---------------------------------------------------------------------------


def test_backoff_delay_no_jitter_is_exact_exponential():
    from nanorlhf_tpu.resilience import backoff_delay

    base, cap = 0.1, 5.0
    for attempt in range(12):
        expect = min(cap, base * (2 ** attempt))
        assert backoff_delay(attempt, base, cap) == expect
    # negative attempts clamp to attempt 0, never shrink below base
    assert backoff_delay(-3, base, cap) == base


def test_backoff_delay_jitter_bounds_and_cap():
    import random

    from nanorlhf_tpu.resilience import backoff_delay

    base, cap, jitter = 0.05, 2.0, 0.25
    rng = random.Random(11)
    for attempt in range(64):
        a = attempt % 10
        d = backoff_delay(a, base, cap, jitter=jitter, rng=rng)
        raw = min(cap, base * (2 ** a))
        # spread is uniform over +/- jitter * raw, then re-capped
        assert d <= cap + 1e-12
        assert raw * (1.0 - jitter) - 1e-12 <= d
        assert d <= min(cap, raw * (1.0 + jitter)) + 1e-12


def test_backoff_delay_seeded_rng_is_deterministic():
    import random

    from nanorlhf_tpu.resilience import backoff_delay

    def seq(seed):
        rng = random.Random(seed)
        return [backoff_delay(a, 0.1, 10.0, jitter=0.5, rng=rng)
                for a in range(16)]

    assert seq(7) == seq(7)
    assert seq(7) != seq(8)


def test_backoff_delay_default_stream_not_global_random():
    """The rng=None default draws from a module-level SEEDED stream, so
    unrelated code reseeding the global `random` module cannot change
    the retry schedule (and the schedule actually varies — jitter is
    real, not a constant)."""
    import random

    from nanorlhf_tpu.resilience import retry as retry_mod
    from nanorlhf_tpu.resilience.retry import backoff_delay

    state = retry_mod._JITTER_RNG.getstate()
    try:
        retry_mod._JITTER_RNG.setstate(
            random.Random(0x6A177E12).getstate())
        random.seed(123)
        first = [backoff_delay(a, 0.1, 10.0, jitter=0.5)
                 for a in range(8)]
        retry_mod._JITTER_RNG.setstate(
            random.Random(0x6A177E12).getstate())
        random.seed(999)  # perturbing the global module changes nothing
        second = [backoff_delay(a, 0.1, 10.0, jitter=0.5)
                  for a in range(8)]
    finally:
        retry_mod._JITTER_RNG.setstate(state)
    assert first == second
    assert len(set(first)) > 1  # jitter varies across draws


# ---------------------------------------------------------------------------
# fault matrix: corrupt latest checkpoint -> fallback to earlier intact
# ---------------------------------------------------------------------------


def test_ckpt_corrupt_latest_falls_back_to_earlier_intact(tmp_path):
    tr = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=32,
                      save_steps=1)
    tr.train()
    tr.close()
    assert tr.ckpt.latest_step() == 2
    # the latest checkpoint reads as torn exactly once -> restore walks
    # down to step 1 instead of failing the resume
    res = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=32,
                       fault_spec="ckpt.corrupt:at=1",
                       ckpt_retry_backoff=0.01)
    res.resume_from_checkpoint()
    assert res.ckpt.fallback_count == 1
    assert res.ckpt.last_restored_step == 1
    assert res.state["global_step"] == 1  # adopted the fallback step
    # training onward from the fallback recommits step 2 and journals
    # the fallback on the metric surface
    res.train()
    res.close()
    rows = _metric_rows(tmp_path / "reinforce")
    assert rows[-1]["resilience/ckpt_fallbacks"] == 1.0
    assert res.ckpt.latest_step() == 2


def test_ckpt_really_corrupt_tree_falls_back(tmp_path):
    """Genuine on-disk damage (not just the injected site): gut the
    newest committed tree's payload files; restore must exhaust its
    retries on the damaged candidate and fall back to step 1."""
    import shutil

    tr = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=32,
                      save_steps=1)
    tr.train()
    tr.close()
    tree = tmp_path / "reinforce" / "checkpoint-2" / "tree"
    assert tree.exists()
    for child in tree.iterdir():  # keep the dir: still "committed"
        shutil.rmtree(child) if child.is_dir() else child.unlink()
    res = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=32,
                       ckpt_io_retries=1, ckpt_retry_backoff=0.01)
    res.resume_from_checkpoint()
    assert res.ckpt.fallback_count == 1
    assert res.ckpt.last_restored_step == 1
    assert res.state["global_step"] == 1
    res.close()


def test_ckpt_corrupt_everything_raises(tmp_path):
    tr = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=32,
                      save_steps=1)
    tr.train()
    tr.close()
    # every candidate reads as torn -> nothing intact at or below the
    # requested step -> the failure surfaces instead of a silent skip
    res = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=32,
                       fault_spec="ckpt.corrupt:every=1,count=9",
                       ckpt_retry_backoff=0.01)
    with pytest.raises(InjectedFault):
        res.resume_from_checkpoint()
    res.close()
