"""Exact resume: params, optimizer state, PRNG, counters round-trip."""

import numpy as np

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
from nanorlhf_tpu.parallel import MeshConfig
from nanorlhf_tpu.trainer import RLConfig, AlgoName, RLTrainer


def _make(tmp_path, seed=3):
    tok = ToyTokenizer(256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    cfg = RLConfig(
        algo=AlgoName.REINFORCE, output_dir=str(tmp_path / "ck"),
        response_length=6, temperature=1.0, sample_n=1, total_episodes=64,
        per_device_train_batch_size=1, gradient_accumulation_steps=2,
        num_mini_batches=1, learning_rate=1e-3, use_lora=True, lora_r=4,
        lora_alpha=8, gradient_checkpointing=False, mesh=MeshConfig(-1, 1, 1),
        save_steps=1, seed=seed, load_best_model_at_end=False,
    )
    ds = load_prompt_dataset("synthetic:64", tok, max_prompt_len=10)

    def reward(prs, eos):
        return np.asarray([1.0 if eos in s else -0.1 for s in prs], np.float32)

    return RLTrainer(cfg, mcfg, tok, params, ds, reward)


def test_resume_restores_counters_params_and_key(tmp_path):
    tr = _make(tmp_path)
    tr.train(num_updates=2)
    saved_step = tr.state["global_step"]
    saved_episode = tr.state["episode"]
    saved_key = np.asarray(tr.ckpt.load_trainer_state(saved_step)["rng_key"])
    p_leaf = np.asarray(jax.tree.leaves(tr.params)[0]).copy()

    # fresh trainer, same config/output dir
    tr2 = _make(tmp_path)
    assert tr2.state["global_step"] == 0
    tr2.resume_from_checkpoint()
    assert tr2.state["global_step"] == saved_step
    assert tr2.state["episode"] == saved_episode
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(tr2.params)[0]), p_leaf, rtol=1e-6
    )
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(tr2.key)), saved_key
    )
    # optimizer state restored (mu for some trainable leaf is nonzero)
    mus = [np.asarray(x) for x in jax.tree.leaves(tr2.opt_state)
           if hasattr(x, "shape") and getattr(x, "size", 0) > 1]
    assert any(np.abs(m).sum() > 0 for m in mus)
    # and training continues from there
    tr2.train(num_updates=1)
    assert tr2.state["global_step"] == saved_step + 1


def test_resumed_default_train_finishes_remaining_budget(tmp_path):
    """train() after resume runs only the REMAINING updates of the episode
    budget, not a fresh full run."""
    tr = _make(tmp_path)
    total = tr.cfg.num_total_batches
    assert total >= 2
    tr.train(num_updates=total - 1)
    tr2 = _make(tmp_path)
    tr2.resume_from_checkpoint()
    tr2.train()  # default budget
    assert tr2.state["global_step"] == total


def test_resume_reproduces_uninterrupted_stream(tmp_path):
    """A 2+resume+rest run must see the SAME rollouts as an uninterrupted
    run: data-loader position fast-forwards and the stateless generation
    stream re-keys by global_step (a restarted loader silently re-training
    on the first batches was a real r2 bug)."""
    import json

    def last_row(outdir):
        rows = [r for r in map(json.loads, open(outdir / "ck" / "metrics.jsonl"))
                if "episode" in r]
        return rows[-1]

    full = _make(tmp_path / "full")
    full.train()
    half = _make(tmp_path / "half")
    half.train(num_updates=2)
    res = _make(tmp_path / "half")
    res.resume_from_checkpoint()
    res.train()

    a, b = last_row(tmp_path / "full"), last_row(tmp_path / "half")
    assert a["episode"] == b["episode"]
    for key in ("objective/kl_rollout_old", "eval_objective/scores_old",
                "objective/entropy_old"):
        np.testing.assert_allclose(a[key], b[key], rtol=1e-4, err_msg=key)
