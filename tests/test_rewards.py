"""Math grader + reward builders."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from nanorlhf_tpu.rewards import (
    get_boxed,
    normalize_math_answer,
    math_answers_equal,
    is_correct,
    make_binary_math_reward,
    make_rm_reward,
    make_rule_reward,
)


class TestGetBoxed:
    def test_simple(self):
        assert get_boxed(r"the answer is \boxed{42}") == "42"

    def test_nested_braces(self):
        assert get_boxed(r"\boxed{\frac{1}{2}}") == r"\frac{1}{2}"

    def test_missing(self):
        assert get_boxed("no box here") == ""

    def test_unbalanced(self):
        assert get_boxed(r"\boxed{\frac{1}{2}") == ""

    def test_strips_spaces(self):
        assert get_boxed(r"\boxed{1 + 1}") == "1+1"


class TestNormalize:
    @pytest.mark.parametrize(
        "raw,want",
        [
            (r"\frac12", r"\frac{1}{2}"),
            (r"\tfrac{1}{2}", r"\frac{1}{2}"),
            (r"\left(1,2\right)", "(1,2)"),
            (r"\text{cm}", "cm"),
            ("50\\%", "50"),
            ("$12$", "12"),
            ("1,000,000", "1000000"),
            ("x = 5", "5"),
            ("0.5", ".5"),
            (r"90^\circ", "90"),
        ],
    )
    def test_cases(self, raw, want):
        assert normalize_math_answer(raw) == want


class TestEquivalence:
    @pytest.mark.parametrize(
        "a,b",
        [
            ("42", "42"),
            ("0.5", "1/2"),
            (r"\frac{1}{2}", "0.5"),
            (r"\frac{2}{4}", r"\frac{1}{2}"),
            (r"\sqrt{4}", "2"),
            ("2*pi", r"2\pi"),
            ("(1,2)", r"\left(1, 2\right)"),
            ("1000000", "1,000,000"),
            ("x=3", "3"),
            ("2^3", "8"),
        ],
    )
    def test_equal(self, a, b):
        assert math_answers_equal(a, b)

    @pytest.mark.parametrize(
        "a,b",
        [("42", "43"), (r"\frac{1}{2}", r"\frac{1}{3}"), ("(1,2)", "(2,1)"), ("", "5")],
    )
    def test_not_equal(self, a, b):
        assert not math_answers_equal(a, b)

    @pytest.mark.parametrize(
        "a,b",
        [
            (r"1\frac{1}{2}", "1.5"),                  # mixed number
            (r"2\frac{3}{4}", "11/4"),
            (r"-1\frac{1}{2}", "-1.5"),                # sign covers the whole
            (r"2\pm\sqrt{4}", r"2\pm 2"),              # pm sets match
            (r"2\pm 1", "{1, 3}"),                     # pm vs explicit set
            (r"2\pm\sqrt{4}", "(0, 4)"),
            (r"x \in (0, 1)", "(0,1)"),                # \in prefix stripped
        ],
    )
    def test_extended_equal(self, a, b):
        assert math_answers_equal(a, b)

    @pytest.mark.parametrize(
        "a,b",
        [
            (r"2\pm 1", r"2\pm 5"),
            (r"-1\frac{1}{2}", "-0.5"),                # the sign-scope trap
            (r"2\pm 0", r"3\pm 1"),                    # asymmetric-set trap
        ],
    )
    def test_extended_not_equal(self, a, b):
        assert not math_answers_equal(a, b)

    def test_is_correct_subprocess_survives_bomb(self):
        # adversarial: enormous power tower must time out to False, not hang
        assert is_correct("2**(2**(2**100000))", "5", timeout=0.2) is False

    def test_is_correct_inprocess(self):
        assert is_correct("1/2", "0.5", use_subprocess=False)


def test_binary_math_reward():
    qa = {"What is 2+2?": "4"}

    def extract_q(s):
        return s.split("\n")[0]

    def extract_sol(s, eos):
        return s.split("\n", 1)[1] if "\n" in s else ""

    reward = make_binary_math_reward(qa, extract_q, extract_sol, use_subprocess=False)
    got = reward(
        ["What is 2+2?\nI think \\boxed{4}", "What is 2+2?\n\\boxed{5}",
         "Unknown question\n\\boxed{4}"],
        "</s>",
    )
    np.testing.assert_array_equal(got, [1.0, 0.0, 0.0])


def test_rule_reward():
    reward = make_rule_reward(lambda s, eos: float(len(s)))
    np.testing.assert_array_equal(reward(["ab", "abcd"], "</s>"), [2.0, 4.0])


def test_rm_reward_jax():
    from nanorlhf_tpu.core import ModelConfig, init_params, init_score_head
    from nanorlhf_tpu.data import ToyTokenizer

    tok = ToyTokenizer(256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    rm = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    rm.pop("lm_head", None)
    rm["score"] = init_score_head(mcfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    reward = make_rm_reward(rm, mcfg, tok, batch_size=2)
    got = reward(["hello world", "goodbye cruel world", "a b c"], "</s>")
    assert got.shape == (3,) and np.all(np.isfinite(got))
    # deterministic
    np.testing.assert_allclose(got, reward(["hello world", "goodbye cruel world", "a b c"], "</s>"))
