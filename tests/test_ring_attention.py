"""Ring attention over an 8-device sequence axis vs single-device reference."""

import numpy as np
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from nanorlhf_tpu.ops.attention import reference_attention
from nanorlhf_tpu.parallel.ring_attention import ring_attention


def _run_ring(q, k, v, valid, causal, n_dev=8):
    mesh = Mesh(np.asarray(jax.devices()[:n_dev]), ("sp",))
    fn = shard_map(
        partial(ring_attention, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None), P(None, None, "sp", None),
                  P(None, None, "sp", None), P(None, "sp")),
        out_specs=P(None, None, "sp", None),
    )
    return jax.jit(fn)(q, k, v, valid)


def test_ring_matches_reference_causal(rng):
    B, H, KV, T, d = 2, 4, 2, 32, 8   # T sharded 8-way -> 4 tokens/device
    q = jnp.asarray(rng.normal(size=(B, H, T, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    valid = np.ones((B, T), bool)
    valid[0, :6] = False
    valid = jnp.asarray(valid)

    got = _run_ring(q, k, v, valid, causal=True)
    want = reference_attention(q, k, v, valid, causal=True)
    mask = np.asarray(valid)[:, None, :, None]
    np.testing.assert_allclose(
        np.asarray(got) * mask, np.asarray(want) * mask, rtol=2e-4, atol=2e-4
    )


def test_ring_matches_reference_non_causal(rng):
    B, H, KV, T, d = 1, 2, 2, 16, 8
    q = jnp.asarray(rng.normal(size=(B, H, T, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    valid = jnp.ones((B, T), bool)
    got = _run_ring(q, k, v, valid, causal=False)
    want = reference_attention(q, k, v, valid, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_ring_gqa(rng):
    B, H, KV, T, d = 1, 8, 2, 16, 8
    q = jnp.asarray(rng.normal(size=(B, H, T, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    valid = jnp.ones((B, T), bool)
    got = _run_ring(q, k, v, valid, causal=True)
    want = reference_attention(q, k, v, valid, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)
