"""Ring attention over an 8-device sequence axis vs single-device reference."""

import numpy as np
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from nanorlhf_tpu.utils.shardmap_compat import shard_map

from nanorlhf_tpu.ops.attention import reference_attention
from nanorlhf_tpu.parallel.ring_attention import ring_attention


def _run_ring(q, k, v, valid, causal, n_dev=8):
    mesh = Mesh(np.asarray(jax.devices()[:n_dev]), ("sp",))
    fn = shard_map(
        partial(ring_attention, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None), P(None, None, "sp", None),
                  P(None, None, "sp", None), P(None, "sp")),
        out_specs=P(None, None, "sp", None),
    )
    return jax.jit(fn)(q, k, v, valid)


def test_ring_matches_reference_causal(rng):
    B, H, KV, T, d = 2, 4, 2, 32, 8   # T sharded 8-way -> 4 tokens/device
    q = jnp.asarray(rng.normal(size=(B, H, T, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    valid = np.ones((B, T), bool)
    valid[0, :6] = False
    valid = jnp.asarray(valid)

    got = _run_ring(q, k, v, valid, causal=True)
    want = reference_attention(q, k, v, valid, causal=True)
    mask = np.asarray(valid)[:, None, :, None]
    np.testing.assert_allclose(
        np.asarray(got) * mask, np.asarray(want) * mask, rtol=2e-4, atol=2e-4
    )


def test_ring_matches_reference_non_causal(rng):
    B, H, KV, T, d = 1, 2, 2, 16, 8
    q = jnp.asarray(rng.normal(size=(B, H, T, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    valid = jnp.ones((B, T), bool)
    got = _run_ring(q, k, v, valid, causal=False)
    want = reference_attention(q, k, v, valid, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_ring_gqa(rng):
    B, H, KV, T, d = 1, 8, 2, 16, 8
    q = jnp.asarray(rng.normal(size=(B, H, T, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    valid = jnp.ones((B, T), bool)
    got = _run_ring(q, k, v, valid, causal=True)
    want = reference_attention(q, k, v, valid, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_flash_ring_matches_einsum_ring(rng):
    """Forward-only flash ring (per-chunk Pallas flash + lse merge) vs the
    einsum ring and the single-device reference — causal, partial key mask,
    GQA. Interpret-mode Pallas on the CPU mesh; 2-way ring so each chunk
    spans multiple (clamped) blocks."""
    from nanorlhf_tpu.parallel.ring_attention import ring_attention_flash

    B, H, KV, T, d = 2, 4, 2, 256, 16      # 2-way ring -> 128 tokens/device
    q = jnp.asarray(rng.normal(size=(B, H, T, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    valid = jnp.asarray(np.arange(T)[None, :] < np.asarray([[T], [T - 60]]))

    mesh = Mesh(np.asarray(jax.devices()[:2]), ("sp",))
    specs = dict(
        in_specs=(P(None, None, "sp", None), P(None, None, "sp", None),
                  P(None, None, "sp", None), P(None, "sp")),
        out_specs=P(None, None, "sp", None),
    )
    flash = jax.jit(shard_map(
        partial(ring_attention_flash, axis_name="sp", causal=True,
                block_q=64, block_k=64),
        mesh=mesh, check_vma=False, **specs,
    ))(q, k, v, valid)
    einsum = jax.jit(shard_map(
        partial(ring_attention, axis_name="sp", causal=True),
        mesh=mesh, **specs,
    ))(q, k, v, valid)
    ref = reference_attention(q, k, v, valid, causal=True)

    rows_valid = np.asarray(valid)
    for b in range(B):
        sel = rows_valid[b]
        np.testing.assert_allclose(
            np.asarray(flash)[b][:, sel], np.asarray(einsum)[b][:, sel],
            rtol=2e-5, atol=2e-5,
        )
        np.testing.assert_allclose(
            np.asarray(flash)[b][:, sel], np.asarray(ref)[b][:, sel],
            rtol=2e-5, atol=2e-5,
        )


def test_flash_ring_backward_matches_einsum_and_reference(rng):
    """jax.grad through the flash ring (custom_vjp: per-chunk Pallas flash
    bwd with the GLOBAL lse, dk/dv riding the ring with their chunk) vs the
    einsum ring's autodiff and the single-device reference — causal,
    partial key mask, GQA, 2-way ring. The cotangent is zeroed on padding
    rows (the caller's masking contract)."""
    from nanorlhf_tpu.parallel.ring_attention import ring_attention_flash

    B, H, KV, T, d = 2, 4, 2, 256, 16
    q = jnp.asarray(rng.normal(size=(B, H, T, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    valid_np = np.arange(T)[None, :] < np.asarray([[T], [T - 60]])
    valid = jnp.asarray(valid_np)
    w = jnp.asarray(
        rng.normal(size=(B, H, T, d)).astype(np.float32)
        * valid_np[:, None, :, None]
    )

    mesh = Mesh(np.asarray(jax.devices()[:2]), ("sp",))
    specs = dict(
        in_specs=(P(None, None, "sp", None), P(None, None, "sp", None),
                  P(None, None, "sp", None), P(None, "sp")),
        out_specs=P(None, None, "sp", None),
    )
    flash_fn = shard_map(
        partial(ring_attention_flash, axis_name="sp", causal=True,
                block_q=64, block_k=64),
        mesh=mesh, check_vma=False, **specs,
    )
    einsum_fn = shard_map(
        partial(ring_attention, axis_name="sp", causal=True),
        mesh=mesh, **specs,
    )

    def loss(fn, q_, k_, v_):
        return (fn(q_, k_, v_, valid) * w).sum()

    g_flash = jax.jit(jax.grad(partial(loss, flash_fn), argnums=(0, 1, 2)))(
        q, k, v
    )
    g_einsum = jax.jit(jax.grad(partial(loss, einsum_fn), argnums=(0, 1, 2)))(
        q, k, v
    )
    g_ref = jax.grad(
        lambda q_, k_, v_: (reference_attention(q_, k_, v_, valid, True) * w).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_flash, g_einsum):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )


def test_flash_ring_backward_non_aligned_width(rng):
    """Backward through the pad-up path (T_local=192, not a 128-multiple):
    dq/dk/dv must slice the padding back off and match the reference."""
    from nanorlhf_tpu.parallel.ring_attention import ring_attention_flash

    B, H, KV, T, d = 1, 4, 2, 384, 16
    q = jnp.asarray(rng.normal(size=(B, H, T, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    valid_np = np.arange(T)[None, :] < T - 50
    valid = jnp.asarray(valid_np)
    w = jnp.asarray(
        rng.normal(size=(B, H, T, d)).astype(np.float32)
        * valid_np[:, None, :, None]
    )

    mesh = Mesh(np.asarray(jax.devices()[:2]), ("sp",))
    flash_fn = shard_map(
        partial(ring_attention_flash, axis_name="sp", causal=True),
        mesh=mesh, check_vma=False,
        in_specs=(P(None, None, "sp", None), P(None, None, "sp", None),
                  P(None, None, "sp", None), P(None, "sp")),
        out_specs=P(None, None, "sp", None),
    )
    g_flash = jax.jit(jax.grad(
        lambda q_, k_, v_: (flash_fn(q_, k_, v_, valid) * w).sum(),
        argnums=(0, 1, 2),
    ))(q, k, v)
    g_ref = jax.grad(
        lambda q_, k_, v_: (reference_attention(q_, k_, v_, valid, True) * w).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )


def test_flash_ring_non_aligned_width(rng):
    """T_local not a 128-multiple (384 global / 2-way ring = 192/shard):
    the pad-up recipe must kick in — Mosaic would reject the raw width on
    silicon, and an unpadded partial block would read out-of-bounds keys."""
    from nanorlhf_tpu.parallel.ring_attention import ring_attention_flash

    B, H, KV, T, d = 1, 4, 2, 384, 16
    q = jnp.asarray(rng.normal(size=(B, H, T, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, KV, T, d)).astype(np.float32))
    valid = jnp.asarray(np.arange(T)[None, :] < T - 50)

    mesh = Mesh(np.asarray(jax.devices()[:2]), ("sp",))
    flash = jax.jit(shard_map(
        partial(ring_attention_flash, axis_name="sp", causal=True),
        mesh=mesh, check_vma=False,
        in_specs=(P(None, None, "sp", None), P(None, None, "sp", None),
                  P(None, None, "sp", None), P(None, "sp")),
        out_specs=P(None, None, "sp", None),
    ))(q, k, v, valid)
    ref = reference_attention(q, k, v, valid, causal=True)
    sel = np.asarray(valid)[0]
    np.testing.assert_allclose(
        np.asarray(flash)[0][:, sel], np.asarray(ref)[0][:, sel],
        rtol=2e-5, atol=2e-5,
    )
