"""rollout_ahead: PipelineRL-style overlap of generation with host reward.

The async-dispatch pipeline must (a) leave update 1 bit-identical to the
serial mode — its rollout is fetched before any update ran, and generation
keys come from the stateless index-keyed stream either way; (b) keep
training stable from update 2 on, where each rollout is one update stale
and the PPO-clip ratio absorbs the drift.
"""

import json
import numpy as np

from nanorlhf_tpu.trainer import AlgoName

from test_trainer_smoke import make_trainer


def _read_metrics(outdir):
    rows = []
    with open(outdir / "metrics.jsonl") as f:
        for line in f:
            row = json.loads(line)
            if "episode" in row:  # skip sample-table rows
                rows.append(row)
    return rows


def test_update1_identical_and_stale_updates_stable(tmp_path):
    serial = make_trainer(
        AlgoName.GRPO, tmp_path / "serial", total_episodes=48, save_steps=0
    )
    serial.train()
    ahead = make_trainer(
        AlgoName.GRPO, tmp_path / "ahead", total_episodes=48, save_steps=0,
        rollout_ahead=True,
    )
    ahead.train()

    m_serial = _read_metrics(tmp_path / "serial" / "grpo")
    m_ahead = _read_metrics(tmp_path / "ahead" / "grpo")
    assert len(m_serial) == len(m_ahead) == 3

    # update 1: same prompts, same generation keys, no staleness yet → the
    # measured rollout statistics must agree exactly
    for key in ("objective/kl_rollout_old", "eval_objective/scores_old",
                "objective/entropy_old"):
        np.testing.assert_allclose(
            m_serial[0][key], m_ahead[0][key], rtol=1e-5,
            err_msg=f"update-1 {key} diverged between serial and ahead",
        )

    # updates 2..n: rollouts are one update stale; training must stay finite
    # and the epoch-1 importance ratio must stay in a sane band around 1
    for row in m_ahead[1:]:
        for key, val in row.items():
            if isinstance(val, float):
                assert np.isfinite(val), f"{key} not finite: {val}"
        assert 0.5 < row["val/ratio_new"] < 2.0, row["val/ratio_new"]


def test_remax_ahead_smoke(tmp_path):
    trainer = make_trainer(
        AlgoName.REMAX, tmp_path, total_episodes=32, save_steps=0,
        rollout_ahead=True,
    )
    state = trainer.train()
    assert state["global_step"] == 2


def test_sparse_grpo_ahead_smoke(tmp_path):
    import jax
    import jax.numpy as jnp

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.data import ToyTokenizer
    from nanorlhf_tpu.entrypoints.grpo_r1 import (
        build_prompt_dataset, synthetic_math_corpus)
    from nanorlhf_tpu.parallel import MeshConfig
    from nanorlhf_tpu.trainer import RLConfig
    from nanorlhf_tpu.trainer.sparse_grpo import SparseGRPOTrainer

    tok = ToyTokenizer(512)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=512)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    dataset = build_prompt_dataset(synthetic_math_corpus(64), tok,
                                   max_prompt_len=16)
    cfg = RLConfig(
        algo=AlgoName.GRPO, output_dir=str(tmp_path / "r1"),
        response_length=8, sample_n=2, kl_coef=0.0, total_episodes=64,
        per_device_train_batch_size=1, gradient_accumulation_steps=2,
        num_mini_batches=2, learning_rate=1e-4, use_lora=True, lora_r=4,
        lora_alpha=8, gradient_checkpointing=False, mesh=MeshConfig(-1, 1, 1),
        save_steps=0, rollout_ahead=True,
    )
    rng = np.random.default_rng(0)

    def noisy_reward(pmt_and_responses, responses_ids, tokenizer):
        return rng.random(len(pmt_and_responses)).astype(np.float32)

    trainer = SparseGRPOTrainer(cfg, mcfg, tok, params, dataset, noisy_reward)
    state = trainer.train()
    assert state["global_step"] == 2
