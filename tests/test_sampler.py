"""Sampler contract + KV-cache correctness on the tiny model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanorlhf_tpu.core import (
    ModelConfig,
    init_params,
    model_forward,
    init_kv_cache,
    prefill,
    decode_step,
)
from nanorlhf_tpu.sampler import SamplingParams, generate

EOS, PAD = 3, 0


@pytest.fixture(scope="module")
def tiny():
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(7), jnp.float32)
    return config, params


def _left_pad(rows, T, pad=PAD):
    ids = np.full((len(rows), T), pad, np.int32)
    mask = np.zeros((len(rows), T), np.int32)
    for i, r in enumerate(rows):
        ids[i, T - len(r):] = r
        mask[i, T - len(r):] = 1
    return jnp.asarray(ids), jnp.asarray(mask)


def test_prefill_decode_matches_full_forward(tiny):
    """Greedy decode via KV cache == iterative argmax via full forward."""
    config, params = tiny
    rows = [[5, 6, 7, 8], [9, 10]]
    Tp = 5
    ids, mask = _left_pad(rows, Tp)
    max_tokens = 6
    out = generate(
        params, config, ids, mask, jax.random.PRNGKey(0),
        SamplingParams(greedy=True, max_tokens=max_tokens, n=1),
        eos_token_id=EOS, pad_token_id=PAD,
    )
    # oracle: grow the sequence one token at a time through model_forward
    for b, row in enumerate(rows):
        seq = list(row)
        got_row = []
        done = False
        for _ in range(max_tokens):
            if done:
                got_row.append(PAD)
                continue
            cur = jnp.asarray([seq])
            m = jnp.ones_like(cur)
            pos = jnp.cumsum(m, axis=1) - 1
            logits = model_forward(params, config, cur, m, pos)
            nxt = int(jnp.argmax(logits[0, -1]))
            got_row.append(nxt)
            seq.append(nxt)
            if nxt == EOS:
                done = True
        np.testing.assert_array_equal(np.asarray(out[b]), got_row)


def test_generate_contract_n_samples(tiny):
    config, params = tiny
    ids, mask = _left_pad([[5, 6, 7], [8, 9]], 4)
    N, T = 3, 5
    out = generate(
        params, config, ids, mask, jax.random.PRNGKey(1),
        SamplingParams(temperature=1.0, top_p=0.95, n=N, max_tokens=T),
        eos_token_id=EOS, pad_token_id=PAD,
    )
    assert out.shape == (2 * N, T)
    arr = np.asarray(out)
    # after the first EOS, everything is PAD
    for row in arr:
        seen_eos = False
        for t in row:
            if seen_eos:
                assert t == PAD
            if t == EOS:
                seen_eos = True


def test_generate_is_seed_dependent(tiny):
    config, params = tiny
    ids, mask = _left_pad([[5, 6, 7, 11, 12, 13]], 6)
    sp = SamplingParams(temperature=1.0, top_p=1.0, n=4, max_tokens=8)
    a = generate(params, config, ids, mask, jax.random.PRNGKey(0), sp,
                 eos_token_id=EOS, pad_token_id=PAD)
    b = generate(params, config, ids, mask, jax.random.PRNGKey(1), sp,
                 eos_token_id=EOS, pad_token_id=PAD)
    c = generate(params, config, ids, mask, jax.random.PRNGKey(0), sp,
                 eos_token_id=EOS, pad_token_id=PAD)
    assert np.asarray(a).tolist() == np.asarray(c).tolist()  # same key → same sample
    assert np.asarray(a).tolist() != np.asarray(b).tolist()  # changing seed parity


def test_prefill_logits_match_full_forward(tiny):
    config, params = tiny
    rows = [[5, 6, 7, 8], [9, 10, 11]]
    Tp = 6
    ids, mask = _left_pad(rows, Tp)
    caches = init_kv_cache(config, 2, Tp + 4, jnp.float32)
    last_logits, caches = prefill(params, config, ids, mask, caches)
    pos = jnp.cumsum(mask, axis=1) - mask
    full = model_forward(params, config, jnp.where(mask.astype(bool), ids, 0), mask, pos)
    np.testing.assert_allclose(
        np.asarray(last_logits), np.asarray(full[:, -1, :]), rtol=1e-4, atol=1e-4
    )


def test_topk_nucleus_matches_exact_filter():
    """The fused top-k nucleus path with the EXACT candidate set
    (approx_top_k=False) samples only tokens inside the exact full-vocab
    nucleus (the keep rule is applied over true probabilities via a
    full-vocab logsumexp, so whenever the nucleus fits in top-k the two
    filters agree). The approx path intentionally offers a weaker guarantee
    (see SamplingParams.approx_top_k) and is covered separately below."""
    from nanorlhf_tpu.sampler.sampler import _sample_token, top_p_filter

    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 512)) * 3.0  # peaked
    allowed = np.asarray(top_p_filter(logits, 0.95)) > -np.inf
    keys = jax.random.split(jax.random.PRNGKey(1), 256)
    toks = np.asarray(jax.vmap(
        lambda k: _sample_token(k, logits, 1.0, 0.95, False, 64,
                                approx_top_k=False)
    )(keys))                                            # [256, 4]
    for t_row in toks:
        for b, t in enumerate(t_row):
            assert allowed[b, t], f"sampled token {t} outside exact nucleus"


def test_approx_topk_candidates_high_probability():
    """The approx path samples only top-k candidates whose true probability
    mass is nucleus-grade: every sampled token must be inside the exact
    top-p KEEP SET UNION the exact top-k set (the approx candidate set is a
    subset of plausible-high-prob tokens; on CPU ApproxTopK is exact, so
    this degenerates to the exact-path property — the TPU-side deviation is
    bounded by recall_target=0.99 and validated on silicon by the bench's
    distribution of sampled ids, not unit-testable off-TPU)."""
    from nanorlhf_tpu.sampler.sampler import _sample_token

    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 512)) * 3.0
    exact_topk = np.asarray(
        jax.lax.top_k(logits, 64)[1]
    )                                                   # [4, 64]
    keys = jax.random.split(jax.random.PRNGKey(1), 128)
    toks = np.asarray(jax.vmap(
        lambda k: _sample_token(k, logits, 1.0, 0.95, False, 64,
                                approx_top_k=True)
    )(keys))
    for t_row in toks:
        for b, t in enumerate(t_row):
            assert t in exact_topk[b], f"sampled {t} outside top-64 set"


def test_topk_sampling_distribution_small_vocab():
    """With top_k == vocab the fused path IS exact nucleus sampling: the
    empirical distribution over many draws matches the renormalized nucleus
    probabilities."""
    from nanorlhf_tpu.sampler.sampler import _sample_token, top_p_filter

    logits = jnp.asarray([[2.0, 1.0, 0.0, -1.0, -8.0, -8.0, -8.0, -8.0]])
    masked = np.asarray(top_p_filter(logits, 0.9))[0]
    probs = np.exp(masked - masked.max())
    probs[~np.isfinite(masked)] = 0.0
    probs /= probs.sum()
    keys = jax.random.split(jax.random.PRNGKey(3), 4000)
    toks = np.asarray(jax.vmap(
        lambda k: _sample_token(k, logits, 1.0, 0.9, False, 8)
    )(keys))[:, 0]
    counts = np.bincount(toks, minlength=8) / len(toks)
    np.testing.assert_allclose(counts, probs, atol=0.03)


def test_capture_logprobs_match_scoring_pass(tiny):
    """Sampler-captured logprobs equal the scoring pass's
    `logprobs_from_logits` at the response positions (f32 tiny model — the
    two paths share the same math, so agreement is tight)."""
    from nanorlhf_tpu.core import padded_forward_logits
    from nanorlhf_tpu.ops.masking import logprobs_from_logits

    config, params = tiny
    ids, mask = _left_pad([[5, 6, 7], [8, 9]], 4)
    T = 6
    temp = 0.9
    out, lp = generate(
        params, config, ids, mask, jax.random.PRNGKey(5),
        SamplingParams(temperature=temp, top_p=0.95, n=2, max_tokens=T,
                       capture_logprobs=True),
        eos_token_id=EOS, pad_token_id=PAD,
    )
    out, lp = np.asarray(out), np.asarray(lp)
    assert out.shape == (4, T) and lp.shape == (4, T)

    # de-pad the prompt rows like the trainer does and rescore
    ids_rep = np.asarray(jnp.repeat(ids, 2, axis=0))
    qr = np.concatenate([ids_rep, out], axis=1)
    logits = padded_forward_logits(params, config, jnp.asarray(qr), PAD,
                                   response_context_length=ids.shape[1])
    scored = np.asarray(logprobs_from_logits(logits, jnp.asarray(out), temp))
    # compare on real (pre-EOS) tokens only; positions after EOS hold pads
    for b in range(out.shape[0]):
        for t in range(T):
            if out[b, t] == PAD:
                break
            assert abs(lp[b, t] - scored[b, t]) < 1e-3, (b, t, lp[b, t], scored[b, t])
            if out[b, t] == EOS:
                break


def test_top_p_bisect_matches_sort_oracle(rng):
    """The sort-free bisection nucleus filter must produce the SAME keep
    mask as the sort-based oracle — peaked, flat, bf16-quantized (mass
    ties), and near-one-hot distributions. The decode loop's top_k=0 path
    (the r1-zero launcher default) rides the bisection variant."""
    import jax.numpy as jnp

    from nanorlhf_tpu.sampler.sampler import top_p_filter, top_p_filter_bisect

    cases = [
        rng.normal(size=(4, 512)).astype(np.float32),            # generic
        (rng.normal(size=(2, 512)) * 8).astype(np.float32),      # peaked
        np.zeros((1, 512), np.float32),                          # exact flat
        jnp.asarray(rng.normal(size=(2, 512)), jnp.bfloat16)     # bf16 ties
            .astype(jnp.float32),
    ]
    onehot = np.full((1, 512), -30.0, np.float32); onehot[0, 7] = 10.0
    cases.append(onehot)
    for i, logits in enumerate(cases):
        logits = jnp.asarray(logits)
        for p in (0.5, 0.9, 0.95, 0.99):
            want = np.asarray(top_p_filter(logits, p)) > -np.inf
            got = np.asarray(top_p_filter_bisect(logits, p)) > -np.inf
            # identical masks except possibly inside an exact float tie at
            # the boundary (the sort cannot order ties stably either):
            # every disagreement must sit at exactly the threshold prob
            if not np.array_equal(want, got):
                probs = np.asarray(jax.nn.softmax(logits, axis=-1))
                for b in range(logits.shape[0]):
                    dis = want[b] != got[b]
                    if dis.any():
                        kept = probs[b][want[b]]
                        assert np.allclose(
                            probs[b][dis], kept.min(), rtol=1e-6
                        ), f"case {i} p={p}: non-tie disagreement"
            # the kept mass must reach p either way (nucleus property)
            probs = np.asarray(jax.nn.softmax(logits, axis=-1))
            for b in range(logits.shape[0]):
                assert probs[b][got[b]].sum() >= p - 1e-5
