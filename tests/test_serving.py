"""Serving plane: radix prefix cache + engine + gateway (serving/, ISSUE 14).

Pins the acceptance contract: radix insert/match/split on non-page-
aligned boundaries, COW divergence mid-page, LRU eviction never freeing
a page a live holder references, the double-release invariants of both
allocators (refcounted pool AND the jitted free stack), suffix-prefill
logits matching full prefill bit-for-bit on the CPU mesh, greedy queued
generation bit-identical with `prefix_cache` on vs off while dispatching
STRICTLY fewer prefill tokens, and the gateway end-to-end (streaming +
non-streaming /generate, Prometheus-valid /metrics, shed → 429,
loopback-only bind). CI runs this file as the `serving-smoke` tier-1
step under NANORLHF_LOCK_CHECK=1, so every engine/radix lock acquisition
is order-checked live.
"""

import http.client
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.core.model import init_paged_kv_cache, prefill
from nanorlhf_tpu.sampler import SamplingParams, generate
from nanorlhf_tpu.sampler.paged.pages import (
    init_page_state, release_row,
)
from nanorlhf_tpu.serving.radix import (
    AdmissionPlan, RadixCache, RefPagePool, bucket_len, prompt_key,
    suffix_logits,
)

EOS, PAD = 3, 0


@pytest.fixture(scope="module")
def tiny():
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(7), jnp.float32)
    return config, params


def _left_pad(rows, T, pad=PAD):
    ids = np.full((len(rows), T), pad, np.int32)
    for i, r in enumerate(rows):
        ids[i, T - len(r):] = r
    ids = jnp.asarray(ids)
    return ids, ids != pad


def _key_for(toks, T):
    """Radix key of a left-padded row built from real tokens `toks`."""
    row = np.full(T, PAD, np.int32)
    row[T - len(toks):] = toks
    mask = np.zeros(T, bool)
    mask[T - len(toks):] = True
    return prompt_key(row, mask), T - len(toks)


def _cache(num_pages=32, page_size=4):
    rc = RadixCache()
    rc.reset(num_pages=num_pages, page_size=page_size)
    return rc


# --------------------------------------------------------------------- #
# RefPagePool: refcount + double-release invariants
# --------------------------------------------------------------------- #

def test_pool_refcount_lifecycle():
    pool = RefPagePool(4)
    p = pool.alloc()
    assert pool.ref[p] == 1 and pool.free_count == 3
    pool.inc(p)
    assert pool.ref[p] == 2 and pool.shared_count() == 1
    assert not pool.unref(p)          # still held
    assert pool.unref(p)              # freed at zero
    assert pool.free_count == 4 and pool.shared_count() == 0


def test_pool_double_unref_is_hard_error():
    pool = RefPagePool(2)
    p = pool.alloc()
    pool.unref(p)
    with pytest.raises(AssertionError):
        pool.unref(p)                 # past zero: invariant violation
    with pytest.raises(AssertionError):
        pool.inc(p)                   # ref of a free page likewise


def test_radix_release_idempotent_at_row_level():
    rc = _cache(num_pages=8, page_size=4)
    key, pad = _key_for([5, 6, 7, 8, 9, 10], 8)
    plan = rc.plan(key, pad_count=pad, n_blocks=2, prompt_len=8)
    rc.insert(key, plan.row_pages, 8)
    row = plan.row_pages.copy()
    rc.release(row)
    row[:] = rc.pool.num_pages        # scheduler's sentinel reset
    assert rc.release(row) == 0       # second release: no-op, no assert


def test_jitted_release_row_double_release_noop():
    st = init_page_state(8, 2, 2)
    from nanorlhf_tpu.sampler.paged.pages import alloc_row
    st, ok = jax.jit(alloc_row)(st, 0, 2)
    assert bool(ok)
    rel = jax.jit(release_row)
    st, m1 = rel(st, 0)
    st, m2 = rel(st, 0)               # row is sentinel now
    assert int(m1) == 2 and int(m2) == 0
    assert int(st.top) == 8


# --------------------------------------------------------------------- #
# radix tree: match / split / COW / eviction (host-only, no model)
# --------------------------------------------------------------------- #

def test_radix_match_and_split_non_page_aligned():
    rc = _cache(page_size=4)
    T = 12
    k1, pad1 = _key_for([5, 6, 7, 8, 9, 10], T)     # pad=6: non-aligned
    p1 = rc.plan(k1, pad_count=pad1, n_blocks=3, prompt_len=T)
    assert p1.m == 0 and p1.shared == 0             # cold
    rc.insert(k1, p1.row_pages, T)

    # same first 5 real tokens, diverging at the last — the match ends
    # at key position 11 (pad 6 + 5 real), inside page 2 (slots 8..11):
    # a mid-edge split at a non-page-aligned boundary
    k2, pad2 = _key_for([5, 6, 7, 8, 9, 11], T)
    p2 = rc.plan(k2, pad_count=pad2, n_blocks=3, prompt_len=T)
    assert p2.m == 11 and p2.hit_tokens == 5
    assert p2.shared == 2                           # pages 0,1 full-shared
    assert p2.cow_src is not None and p2.cow_dst == int(p2.row_pages[2])
    assert p2.cow_src != p2.cow_dst                 # fresh private copy
    rc.insert(k2, p2.row_pages, T)

    # identical prompt: full-prefix hit capped at prompt_len - 1 (one
    # suffix token must remain to produce admission logits)
    k3, pad3 = _key_for([5, 6, 7, 8, 9, 10], T)
    p3 = rc.plan(k3, pad_count=pad3, n_blocks=3, prompt_len=T)
    assert p3.m == T - 1 and p3.hit_tokens == 5
    # the tree survived the split: nodes for the shared prefix + two
    # divergent tails
    snap = rc.snapshot()
    assert snap["nodes"] >= 3
    assert snap["shared_pages"] > 0


def test_radix_pad_layout_mismatch_shares_no_real_tokens():
    rc = _cache(page_size=4)
    T = 12
    k1, pad1 = _key_for([5, 6, 7, 8, 9, 10], T)     # pad=6
    p1 = rc.plan(k1, pad_count=pad1, n_blocks=3, prompt_len=T)
    rc.insert(k1, p1.row_pages, T)
    # same real tokens, one fewer pad: the slot layouts differ, so the
    # only common key prefix is the PAD run (5 elements). The plan may
    # share the pads-only page (free, never read) but must count zero
    # hit tokens and skip the pointless COW copy of a pad straddler
    k2, pad2 = _key_for([5, 6, 7, 8, 9, 10, 12], T)  # pad=5
    p2 = rc.plan(k2, pad_count=pad2, n_blocks=3, prompt_len=T)
    assert p2.m == pad2                              # pads only
    assert p2.hit_tokens == 0
    assert p2.cow_src is None                        # no pad-page COW
    # every REAL token still prefills (the suffix spans them all)
    assert T - p2.m == len([5, 6, 7, 8, 9, 10, 12])


def test_radix_match_inside_pad_region_degrades_to_cold():
    rc = _cache(page_size=4)
    T = 12
    k1, pad1 = _key_for([5, 6, 7, 8, 9, 10, 11, 12], T)   # pad=4
    p1 = rc.plan(k1, pad_count=pad1, n_blocks=3, prompt_len=T)
    rc.insert(k1, p1.row_pages, T)
    # a much shorter prompt shares only 4 pad elements of its 10-pad
    # run: the match dies STRICTLY inside the new row's pad region
    # (m_raw = 4 < pad_count = 10) and must degrade to cold — a suffix
    # starting inside the pads would break the decode_verify parity
    k2, pad2 = _key_for([7, 8], T)
    assert pad2 == 10
    p2 = rc.plan(k2, pad_count=pad2, n_blocks=3, prompt_len=T)
    assert p2.m == 0 and p2.hit_tokens == 0 and p2.cow_src is None


def test_lru_eviction_never_frees_referenced_page():
    # pool sized so the third admission must evict; full-length prompts
    # (pad_count = 0) so no pad page is shared across the rows
    rc = _cache(num_pages=4, page_size=4)
    T = 8
    ka, pada = _key_for([21, 22, 23, 24, 25, 26, 27, 28], T)
    pa = rc.plan(ka, pad_count=pada, n_blocks=2, prompt_len=T)
    rc.insert(ka, pa.row_pages, T)                  # row A LIVE + cached
    kb, padb = _key_for([31, 32, 33, 34, 35, 36, 37, 38], T)
    pb = rc.plan(kb, pad_count=padb, n_blocks=2, prompt_len=T)
    rc.insert(kb, pb.row_pages, T)
    rc.release(pb.row_pages)                        # row B released: its
    # subtree is refcount-1 (tree-only) → the eviction candidate
    kc, padc = _key_for([41, 42, 43, 44, 45, 46, 47, 48], T)
    pc = rc.plan(kc, pad_count=padc, n_blocks=2, prompt_len=T)
    assert pc.evicted == 2                          # B's pages, not A's
    # A's pages still ref'd by both the tree and the live row
    for pid in pa.row_pages:
        assert rc.pool.ref[int(pid)] == 2
    # and A's prefix still matches — it was never evicted (C's row must
    # release first so its subtree becomes the next eviction candidate)
    rc.release(pc.row_pages)
    pa2 = rc.plan(ka, pad_count=pada, n_blocks=2, prompt_len=T)
    assert pa2.m == T - 1
    assert pa2.shared == 1                          # A's full page 0


def test_plan_raises_when_nothing_evictable():
    rc = _cache(num_pages=2, page_size=4)
    T = 8
    ka, pada = _key_for([21, 22, 23, 24], T)
    rc.insert(ka, rc.plan(ka, pad_count=pada, n_blocks=2,
                          prompt_len=T).row_pages, T)
    kb, padb = _key_for([31, 32, 33, 34], T)
    with pytest.raises(RuntimeError, match="radix pool exhausted"):
        rc.plan(kb, pad_count=padb, n_blocks=2, prompt_len=T)


def test_bucket_len_powers_of_two_clamped():
    assert bucket_len(1, 16) == 1
    assert bucket_len(3, 16) == 4
    assert bucket_len(5, 6) == 6      # clamp beats the power of two
    assert bucket_len(7, 7) == 7


# --------------------------------------------------------------------- #
# suffix prefill ≡ full prefill (the bit-parity the cache rests on)
# --------------------------------------------------------------------- #

def test_suffix_logits_match_full_prefill(tiny):
    config, params = tiny
    Tp, P, max_new = 8, 4, 4
    T_max = Tp + max_new
    nb = -(-T_max // P)
    toks = [5, 6, 7, 8, 9, 10]
    ids, mask = _left_pad([toks], Tp)
    pad_count = Tp - len(toks)

    # oracle: single-row full prefill through an identity block table
    caches_a = init_paged_kv_cache(config, nb, P, jnp.float32)
    table = jnp.arange(nb, dtype=jnp.int32)
    logits_a, _ = prefill(params, config, ids, mask, caches_a,
                          page_table=table[None, :], page_size=P,
                          logical_len=T_max)

    # suffix path: prefill [pad, m) via the oracle's own forward, then
    # decode_verify over [m, Tp) — non-page-aligned split (m = 5)
    m = 5
    caches_b = init_paged_kv_cache(config, nb, P, jnp.float32)
    ids_pref = jnp.asarray(np.where(np.arange(Tp) < m,
                                    np.asarray(ids)[0], PAD)[None, :])
    mask_pref = jnp.asarray((np.arange(Tp) < m)
                            & np.asarray(mask)[0])[None, :]
    _, caches_b = prefill(params, config, ids_pref, mask_pref, caches_b,
                          page_table=table[None, :], page_size=P,
                          logical_len=T_max)
    s_real = Tp - m
    Sb = bucket_len(s_real, T_max - m)
    suffix = np.zeros((1, Sb), np.int32)
    suffix[0, :s_real] = toks[m - pad_count:]
    pos = (m - pad_count) + np.arange(Sb, dtype=np.int32)[None]
    km = np.zeros((1, T_max), bool)
    km[0, pad_count:m] = True
    logits_b, _ = suffix_logits(
        params, config, jnp.asarray(suffix), jnp.asarray(pos),
        jnp.asarray([m], jnp.int32), jnp.int32(s_real - 1),
        jnp.asarray(km), caches_b, table, page_size=P, lora_scale=1.0)
    np.testing.assert_array_equal(np.asarray(logits_a[0]),
                                  np.asarray(logits_b))


# --------------------------------------------------------------------- #
# queued generation: greedy bit-parity + strictly fewer prefill tokens
# --------------------------------------------------------------------- #

OVERLAP_PROMPTS = [
    [5, 6, 7, 8, 9, 10],        # base
    [5, 6, 7, 8, 9, 11],        # mid-page divergence (COW)
    [5, 6, 7, 8, 9, 10],        # exact repeat (full hit)
    [20, 21],                   # cold, different pad layout
    [5, 6, 7, 8, 9, 10, 12],    # longer: no match (pad layout differs)
    [20, 21],                   # repeat of the cold one
]


def _queued(tiny, prefix_cache, stats, greedy=True, key=0):
    config, params = tiny
    ids, mask = _left_pad(OVERLAP_PROMPTS, 12)
    sp = SamplingParams(max_tokens=8, greedy=greedy, page_size=4,
                        decode_rows=2, temperature=1.0, top_p=0.9)
    return generate(params, config, ids, mask, jax.random.PRNGKey(key),
                    sp, eos_token_id=EOS, pad_token_id=PAD,
                    paged_stats_out=stats, prefix_cache=prefix_cache)


def test_greedy_bit_parity_and_fewer_prefill_dispatch(tiny):
    stats_off, stats_on = [], []
    out_off = _queued(tiny, None, stats_off)
    out_on = _queued(tiny, RadixCache(), stats_on)
    np.testing.assert_array_equal(np.asarray(out_off), np.asarray(out_on))
    off, on = stats_off[0], stats_on[0]
    assert on["prefill_token_dispatch"] < off["prefill_token_dispatch"]
    assert on["prefix_hit_frac"] > 0.3
    assert on["cow_splits"] >= 1
    assert on["shared_pages"] > 0
    assert "prefix_hit_frac" not in off       # radix-only stat keys


def test_prefix_cache_spec_k_composes(tiny):
    """spec decode UNDER the radix prefix cache (the decode-session
    composition that used to raise): greedy output is bit-identical to
    the radix-alone run, and the session stats carry both features'
    counters. The deeper A/B gates (fewer dispatch events than either
    feature alone) live in tests/test_session.py."""
    config, params = tiny
    ids, mask = _left_pad(OVERLAP_PROMPTS[:4], 12)
    base = dict(max_tokens=4, greedy=True, page_size=4, decode_rows=2)
    stats_r, stats_rs, spec_stats = [], [], []
    out_r = generate(params, config, ids, mask, jax.random.PRNGKey(0),
                     SamplingParams(**base), eos_token_id=EOS,
                     pad_token_id=PAD, paged_stats_out=stats_r,
                     prefix_cache=RadixCache())
    out_rs = generate(params, config, ids, mask, jax.random.PRNGKey(0),
                      SamplingParams(**base, spec_k=2),
                      eos_token_id=EOS, pad_token_id=PAD,
                      paged_stats_out=stats_rs,
                      spec_stats_out=spec_stats,
                      prefix_cache=RadixCache())
    np.testing.assert_array_equal(np.asarray(out_r), np.asarray(out_rs))
    entry = stats_rs[0]
    assert entry["prefix_hit_tokens"] > 0          # radix did its job
    assert spec_stats and int(np.asarray(
        spec_stats[0]["drafted"])) >= 0            # spec carry ran
    feats = entry["session"]["features"]
    assert feats["spec_k"] == 2 and feats["prefix_cache"]
    assert feats["drafter_seed_window"] > 0        # satellite (b): seeded


# --------------------------------------------------------------------- #
# engine + gateway end-to-end
# --------------------------------------------------------------------- #

@pytest.fixture(scope="module")
def served(tiny):
    from nanorlhf_tpu.serving.engine import ServingEngine
    from nanorlhf_tpu.serving.gateway import ServingGateway
    from nanorlhf_tpu.telemetry.hist import LatencyHub

    config, params = tiny
    hub = LatencyHub(enabled=True)
    eng = ServingEngine(params, config, eos_token_id=EOS,
                        pad_token_id=PAD, page_size=4, prompt_len=12,
                        max_new_tokens=8, rows=2, latency=hub, seed=0)
    gw = ServingGateway(eng, port=-1)
    yield eng, gw, f"http://127.0.0.1:{gw.port}"
    gw.close()
    eng.close()


def _post(base, payload, timeout=120):
    req = urllib.request.Request(
        base + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def test_gateway_generate_and_prefix_reuse(served):
    eng, _, base = served
    r1 = json.loads(_post(base, {"tokens": [5, 6, 7, 8, 9, 10],
                                 "greedy": True}).read())
    assert len(r1["tokens"]) >= 1
    # identical greedy request: bit-identical stream, now prefix-cached
    r2 = json.loads(_post(base, {"tokens": [5, 6, 7, 8, 9, 10],
                                 "greedy": True}).read())
    assert r2["tokens"] == r1["tokens"]
    assert eng.metrics()["serving/prefix_hit_tokens"] > 0

    # streaming: NDJSON token lines then the done record, same tokens
    resp = _post(base, {"tokens": [5, 6, 7, 8, 9, 10], "greedy": True,
                        "stream": True})
    assert "application/x-ndjson" in resp.headers["Content-Type"]
    lines = [json.loads(ln) for ln in resp.read().decode().splitlines()]
    assert lines[-1]["done"] is True
    assert [ln["token"] for ln in lines[:-1]] == r1["tokens"]


def test_gateway_metrics_prometheus_valid(served):
    from nanorlhf_tpu.telemetry.exporter import validate_prometheus_text
    _, _, base = served
    text = urllib.request.urlopen(base + "/metrics",
                                  timeout=30).read().decode()
    assert validate_prometheus_text(text) == []
    assert "nanorlhf_serving_requests" in text
    assert "nanorlhf_pages_shared" in text
    assert "nanorlhf_latency_ttft_s_bucket" in text   # hub histograms ride

    statusz = json.loads(urllib.request.urlopen(
        base + "/statusz", timeout=30).read())
    assert statusz["prefix_cache"]["nodes"] >= 1      # inspectable tree
    assert statusz["slo"]["rule"] == "slo_ttft_p95"
    assert urllib.request.urlopen(base + "/healthz",
                                  timeout=30).status == 200


def test_gateway_sheds_on_slo_and_answers_429(served):
    eng, _, base = served
    hub = eng._hub
    # push the hub's p95 TTFT far over the warn threshold (past warmup)
    for _ in range(eng._slo_warmup + 4):
        hub.record("latency/ttft_s", eng._slo_warn * 10)
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(base, {"tokens": [1, 2, 3]})
    assert err.value.code == 429
    # open-loop clients and dashboards read the cause: Retry-After header
    # (the SLO-shed backoff hint) + the per-reason shed counter family
    assert err.value.headers.get("Retry-After") == "5"
    assert json.loads(err.value.read())["reason"] == "slo_ttft_p95"
    m = eng.metrics()
    shed_before = m["serving/shed"]
    assert shed_before >= 1
    assert m['serving/shed_total{reason="slo_ttft_p95"}'] >= 1
    assert m['serving/shed_total{reason="queue_full"}'] == 0  # pre-seeded
    assert sum(v for k, v in m.items()
               if k.startswith("serving/shed_total{")) == shed_before
    # restore: overwrite the histogram with fast observations is not
    # possible (streaming), so later tests must not submit — this is the
    # module's final gateway test by ordering; still verify the engine
    # rejects directly too
    req, reason = eng.submit([1, 2, 3])
    assert req is None and reason == "slo_ttft_p95"


def test_gateway_rejects_bad_request_and_nonloopback():
    from nanorlhf_tpu.serving.gateway import ServingGateway
    with pytest.raises(ValueError, match="loopback"):
        ServingGateway(object(), port=-1, host="0.0.0.0")


def test_engine_prompt_length_validation(served):
    eng, _, _ = served
    with pytest.raises(ValueError, match="prompt length"):
        eng.submit(list(range(eng.prompt_len + 1)))


# --------------------------------------------------------------------- #
# trainer wiring: knob validation + GRPO smoke with the cache on
# --------------------------------------------------------------------- #

def test_trainer_knob_validation(tmp_path):
    from nanorlhf_tpu.trainer import AlgoName
    from tests.test_trainer_smoke import make_trainer

    # default off
    from nanorlhf_tpu.trainer.config import RLConfig
    assert RLConfig().rollout_prefix_cache is False
    # requires continuous batching (compose_check, the one legality matrix)
    with pytest.raises(ValueError, match="continuous batching"):
        make_trainer(AlgoName.GRPO, tmp_path, rollout_prefix_cache=True)
    # spec decode now COMPOSES with the prefix cache (decode session):
    # the trainer constructs cleanly where it used to raise
    tr = make_trainer(AlgoName.GRPO, tmp_path / "b",
                      rollout_prefix_cache=True, rollout_page_size=4,
                      rollout_decode_rows=2, rollout_spec_k=2)
    assert tr.prefix_cache is not None
    # chunked prefill also rides continuous batching only
    with pytest.raises(ValueError, match="prefill_chunk"):
        make_trainer(AlgoName.GRPO, tmp_path / "c",
                     rollout_prefill_chunk=4)


def test_grpo_update_with_prefix_cache(tmp_path):
    """One GRPO update with rollout_prefix_cache on: the rollout path
    plans/inserts/releases through the radix cache without disturbing
    training, and the prefix-hit + pages/shared metrics land (sample_n=2
    guarantees cross-request overlap — each prompt admits twice)."""
    import json as _json

    from nanorlhf_tpu.trainer import AlgoName
    from tests.test_trainer_smoke import make_trainer

    tr = make_trainer(AlgoName.GRPO, tmp_path, rollout_prefix_cache=True,
                      rollout_page_size=4, rollout_decode_rows=2,
                      total_episodes=16)
    assert tr.prefix_cache is not None
    tr.train(num_updates=1)
    rows = [_json.loads(ln) for ln in
            (tmp_path / "grpo" / "metrics.jsonl").read_text().splitlines()]
    row = rows[-1]
    assert row["rollout/prefix_hit_frac"] > 0.0       # n=2 fanout repeats
    assert row["pages/shared"] > 0
    assert tr.prefix_cache.stats["lookups"] > 0
    # /statusz carries the inspectable tree snapshot
    sz = tr._statusz()
    assert sz["prefix_cache"]["lookups"] > 0

# --------------------------------------------------------------------- #
# gw.disconnect: clients vanishing mid-stream (docs/RESILIENCE.md §chaos)
# --------------------------------------------------------------------- #

def _chaos_engine(tiny, **kw):
    from nanorlhf_tpu.serving.engine import ServingEngine
    config, params = tiny
    kw.setdefault("eos_token_id", EOS)
    kw.setdefault("pad_token_id", PAD)
    kw.setdefault("page_size", 4)
    kw.setdefault("prompt_len", 12)
    kw.setdefault("max_new_tokens", 8)
    kw.setdefault("rows", 2)
    return ServingEngine(params, config, **kw)


def _quiesce(eng, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        snap = eng.snapshot()
        if snap["pending"] == 0 and snap["active"] == 0:
            return snap
        time.sleep(0.01)
    raise AssertionError("engine never drained")


def _full_budget_prompt(eng):
    """A prompt whose natural greedy stream runs the whole token budget
    without hitting EOS — the engine is deterministic given (params,
    seed), so probing is stable, and cancelling such a stream mid-flight
    really abandons a live decoding row."""
    for cand in ([5, 6, 7, 8, 9, 10], [11, 12, 13], [20, 21, 22, 23],
                 [30, 31], [40, 41, 42, 43, 44], [50, 51, 52]):
        req, reason = eng.submit(cand, greedy=True)
        assert reason is None
        toks = list(eng.stream(req))
        if len(toks) == eng.max_new_tokens and toks[-1] != EOS:
            _quiesce(eng)
            return cand
    raise AssertionError("no probe prompt ran the full budget")


def test_engine_cancel_active_releases_pages(tiny):
    """Cancelling an admitted stream reaps the row: the stream ends at
    the sentinel, the `cancelled` counter balances admission, and every
    abandoned KV page returns to free/radix-cached (no leak, nothing
    left shared). Pins the precondition the chaos kv_page_leak auditor
    relies on."""
    eng = _chaos_engine(tiny)
    try:
        victim = _full_budget_prompt(eng)
        base = eng.snapshot()["counters"]

        req, reason = eng.submit(victim, greedy=True)
        assert reason is None
        it = eng.stream(req)
        next(it)                       # live: the row is decoding
        eng.cancel(req)                # client vanished mid-stream
        rest = list(it)                # sentinel lands, stream terminates
        assert len(rest) < eng.max_new_tokens

        snap = _quiesce(eng)
        c = snap["counters"]
        assert c["cancelled"] == base["cancelled"] + 1
        assert c["completed"] == base["completed"]
        assert c["admitted"] == c["completed"] + c["cancelled"]
        radix = snap["prefix_cache"]
        assert (radix["free_pages"] + radix["cached_pages"]
                == snap["num_pages"])
        assert radix["shared_pages"] == 0
        # the session's block table holds no live rows either
        assert int((np.asarray(eng.session.table_np)
                    < eng.num_pages).sum()) == 0

        eng.cancel(req)                # idempotent: reaped requests no-op
        assert eng.snapshot()["counters"]["cancelled"] == c["cancelled"]

        # the engine still serves: same prompt completes bit-identically
        req2, reason = eng.submit(victim, greedy=True)
        assert reason is None
        assert len(list(eng.stream(req2))) == eng.max_new_tokens
    finally:
        eng.close()


def test_engine_cancel_pending_sheds_disconnect(tiny):
    """Cancelling a still-pending request sheds it immediately (reason
    "disconnect", never admitted) and its stream ends at the sentinel
    without blocking."""
    eng = _chaos_engine(tiny)
    try:
        victim = _full_budget_prompt(eng)
        base = eng.snapshot()["counters"]
        # bury the victim deep in the pending queue: with 2 rows and 6
        # submissions, the LAST one needs two full generation rounds to
        # reach admission, so the immediate cancel is guaranteed to find
        # it still pending (no race against the admission loop)
        reqs = [eng.submit(victim, greedy=True)[0] for _ in range(6)]
        assert all(r is not None for r in reqs)
        eng.cancel(reqs[-1])
        assert list(eng.stream(reqs[-1])) == []   # sentinel, no tokens
        for r in reqs[:-1]:
            list(eng.stream(r))
        snap = _quiesce(eng)
        assert snap["shed_reasons"].get("disconnect", 0) == 1
        assert snap["counters"]["admitted"] == base["admitted"] + 5
        m = eng.metrics()
        assert m['serving/shed_total{reason="disconnect"}'] == 1
        assert m["serving/cancelled"] == 0   # never admitted → not reaped
    finally:
        eng.close()


def test_gateway_disconnect_fault_mid_stream(tiny):
    """End-to-end gw.disconnect through the HTTP gateway: the injected
    fire aborts the chunked NDJSON stream mid-flight (client sees a
    truncated body with no done record), the engine reaps the row, and
    at quiescence the counters balance and the page pool is whole — then
    the next request completes normally."""
    from nanorlhf_tpu.resilience.faults import FaultInjector
    from nanorlhf_tpu.serving.gateway import ServingGateway

    eng = _chaos_engine(tiny)
    inj = FaultInjector.from_spec("gw.disconnect:every=3,count=4")
    gw = ServingGateway(eng, port=-1, faults=inj)
    base = f"http://127.0.0.1:{gw.port}"
    try:
        victim = _full_budget_prompt(eng)
        # the engine decodes independently of the HTTP consumer, so by
        # the time the handler's fire aborts the stream the request may
        # already have completed (cancel is then the idempotent no-op);
        # count cancel() invocations to pin the gateway wiring without
        # racing the decode loop
        cancels = []
        orig_cancel = eng.cancel
        eng.cancel = lambda req: (cancels.append(req.request_id),
                                  orig_cancel(req))[1]
        truncated = 0
        for _ in range(4):
            resp = _post(base, {"tokens": victim, "greedy": True,
                                "stream": True})
            try:
                body = resp.read()
            except http.client.IncompleteRead as e:
                body = e.partial
            except (ConnectionError, OSError):
                body = b""
            lines = []
            for ln in body.decode(errors="replace").splitlines():
                try:
                    lines.append(json.loads(ln))
                except ValueError:
                    pass
            if not (lines and lines[-1].get("done")):
                truncated += 1

        stats = inj.stats()["gw.disconnect"]
        assert stats["fires"] >= 1
        assert truncated >= 1          # at least one stream was severed
        assert len(cancels) == truncated  # every severed stream cancelled

        snap = _quiesce(eng)
        c = snap["counters"]
        assert c["admitted"] == c["completed"] + c["cancelled"]
        radix = snap["prefix_cache"]   # abandoned pages all came back
        assert (radix["free_pages"] + radix["cached_pages"]
                == snap["num_pages"])
        assert radix["shared_pages"] == 0

        # injector exhausted (count=4): service is back to normal
        inj_left = stats["fires"]
        resp = _post(base, {"tokens": victim, "greedy": True,
                            "stream": True})
        lines = [json.loads(ln) for ln in resp.read().decode().splitlines()]
        assert lines[-1]["done"] is True
        assert len(lines) - 1 == eng.max_new_tokens
        assert inj.stats()["gw.disconnect"]["fires"] == inj_left == 4
    finally:
        gw.close()
        eng.close()
